"""d=64 MXU lane question, compute-bound and CSE-proof: each unrolled
dot consumes a distinct slice of a VMEM-resident operand."""
import sys

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

sys.path.insert(0, "/root/repo")
from apex_tpu.profiling.trace_report import device_time_ms  # noqa: E402

m, n, reps, U = 512, 512, 32, 16
DN = (((1,), (1,)), ((), ()))


def dev_ms(fn, *args, steps=8):
    fn = jax.jit(fn)
    out = fn(*args)
    jax.block_until_ready(out)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)))
    return device_time_ms(fn, *args, steps=steps)


def kern_A(qa_ref, qb_ref, k1_ref, k2_ref, o_ref):
    acc = jnp.zeros((m, n), jnp.float32)
    for i in range(U):
        s1 = jax.lax.dot_general(qa_ref[0, :, i * 64:(i + 1) * 64],
                                 k1_ref[0], DN,
                                 preferred_element_type=jnp.float32)
        s2 = jax.lax.dot_general(qb_ref[0, :, i * 64:(i + 1) * 64],
                                 k2_ref[0], DN,
                                 preferred_element_type=jnp.float32)
        acc = acc + s1 + s2
    o_ref[0] = acc


def kern_B(qp_ref, kp_ref, o_ref):
    acc = jnp.zeros((2 * m, n), jnp.float32)
    for i in range(U):
        s = jax.lax.dot_general(qp_ref[0, :, i * 128:(i + 1) * 128],
                                kp_ref[0], DN,
                                preferred_element_type=jnp.float32)
        acc = acc + s
    o_ref[0] = acc


def kern_C(qc_ref, kc_ref, o_ref):
    acc = jnp.zeros((m, n), jnp.float32)
    for i in range(U):
        s = jax.lax.dot_general(qc_ref[0, :, i * 128:(i + 1) * 128],
                                kc_ref[0], DN,
                                preferred_element_type=jnp.float32)
        acc = acc + s
    o_ref[0] = acc


key = jax.random.PRNGKey(1)
qa = jax.random.normal(key, (1, m, 64 * U), jnp.bfloat16)
qb = qa * 0.5
k1 = jax.random.normal(key, (1, n, 64), jnp.bfloat16)
k2 = k1 + 1
# B: block-diagonal packing of the two heads' q slices -> [2m, 128U]
qp = jnp.concatenate([
    jnp.concatenate([qa.reshape(1, m, U, 64),
                     jnp.zeros((1, m, U, 64), jnp.bfloat16)], -1),
    jnp.concatenate([jnp.zeros((1, m, U, 64), jnp.bfloat16),
                     qb.reshape(1, m, U, 64)], -1)],
    1).reshape(1, 2 * m, U * 128)
kp = jnp.concatenate([k1, k2], -1)  # [1, n, 128]
qc = jnp.concatenate([qa, qa], -1).reshape(1, m, 2 * U, 64).reshape(
    1, m, 2 * U * 64)  # [m, 128U]
kc = jnp.concatenate([k1, k1], -1)


def run(kern, outshape, *ops):
    return pl.pallas_call(
        kern, grid=(reps,),
        in_specs=[pl.BlockSpec((1,) + o.shape[1:], lambda b: (0, 0, 0))
                  for o in ops],
        out_specs=pl.BlockSpec((1,) + outshape, lambda b: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((1,) + outshape, jnp.float32),
    )(*ops)


tA = dev_ms(lambda: run(kern_A, (m, n), qa, qb, k1, k2))
tB = dev_ms(lambda: run(kern_B, (2 * m, n), qp, kp))
tC = dev_ms(lambda: run(kern_C, (m, n), qc, kc))
useful = reps * U * 2 * m * n * 64 * 2  # two-head useful flops
print(f"A two d=64 dots : {tA:.3f} ms  {useful / tA / 1e9 / 1e3:.1f} TF useful")
print(f"B packed blkdiag: {tB:.3f} ms  {useful / tB / 1e9 / 1e3:.1f} TF useful")
print(f"C one d=128 dot : {tC:.3f} ms  {useful / tC / 1e9 / 1e3:.1f} TF at "
      "equal time (ceiling if packing were free)")
