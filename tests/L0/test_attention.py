"""Fused attention tests: flash kernel vs naive reference, ring attention
across the 8-device mesh, contrib MHA modules.

Mirrors reference tests: contrib/test/fmha/test_fmha.py (fused vs py
reference), multihead_attn tests, plus the new long-context tier.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn, SelfMultiheadAttn
from apex_tpu.ops.attention import (
    flash_attention,
    flash_attention_qkv,
    ring_attention,
)


def _unpack_qkv(qkv, nh, hn):
    """[b, s, nh*(q|k|v)] interleaved projection layout -> three
    [b, nh, s, hn] tensors (the packed-QKV reference construction)."""
    b, s, _ = qkv.shape
    return tuple(t.transpose(0, 2, 1, 3) for t in jnp.split(
        qkv.reshape(b, s, nh, 3 * hn), 3, axis=-1))


def _naive(q, k, v, causal=False, mask_bias=None, scale=None):
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if mask_bias is not None:
        s = s + mask_bias
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        tri = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(tri, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v)


class TestFlashAttention:
    def test_matches_naive(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (4, 64, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (4, 64, 32))
        np.testing.assert_allclose(
            flash_attention(q, k, v), _naive(q, k, v), rtol=1e-4, atol=1e-5)

    def test_causal_matches_naive(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 16))
        np.testing.assert_allclose(
            flash_attention(q, k, v, causal=True), _naive(q, k, v, True),
            rtol=1e-4, atol=1e-5)

    @pytest.mark.slow  # heaviest interpret/parity tier (ISSUE 6 wall-clock)
    def test_packed_qkv_matches_naive(self):
        # the r5 transpose-free entry point: [b, s, nh*(q|k|v)] in the
        # Megatron interleaved projection layout -> context [b, s, h].
        # On CPU this exercises the fallback route; the packed Pallas
        # kernels are parity-tested against it on hardware.
        b, s, nh, hn = 2, 64, 4, 16
        qkv = jax.random.normal(jax.random.PRNGKey(0), (b, s, nh * 3 * hn))
        ctx = flash_attention_qkv(qkv, nh, causal=True, block=32)
        q, k, v = _unpack_qkv(qkv, nh, hn)
        ref = _naive(q, k, v, causal=True)
        ref = ref.transpose(0, 2, 1, 3).reshape(b, s, nh * hn)
        np.testing.assert_allclose(ctx, ref, rtol=1e-4, atol=1e-5)

        def loss(qkv):
            return jnp.sum(flash_attention_qkv(qkv, nh, causal=True,
                                               block=32) ** 2)

        def loss_ref(qkv):
            q, k, v = _unpack_qkv(qkv, nh, hn)
            return jnp.sum(_naive(q, k, v, causal=True) ** 2)

        g1 = jax.grad(loss)(qkv)
        g2 = jax.grad(loss_ref)(qkv)
        np.testing.assert_allclose(g1, g2, rtol=1e-3, atol=1e-4)

    @pytest.mark.slow  # interpret-mode packed-QKV kernels (ISSUE 2 CI satellite)
    def test_packed_qkv_kernels_interpret_mode(self):
        # CI coverage for the packed Pallas kernels themselves (the
        # public wrapper routes to the fallback off-TPU): drive the
        # fwd + bwd pallas_calls in interpret mode and compare against
        # the fallback math — exercises the per-head lane slicing, the
        # joint dqkv store, and the dense lse arrangement
        from apex_tpu.ops.attention import (
            _flash_qkv_bwd_pallas, _flash_qkv_fwd_pallas)

        b, s, nh, hn = 2, 64, 2, 64  # group=2 at hn=64
        scale = 1.0 / np.sqrt(hn)
        qkv = jax.random.normal(jax.random.PRNGKey(0),
                                (b, s, nh * 3 * hn), jnp.float32)
        dctx = jax.random.normal(jax.random.PRNGKey(1), (b, s, nh * hn),
                                 jnp.float32)
        ctx, lse = _flash_qkv_fwd_pallas(qkv, 0, nh, hn, scale, True,
                                         32, 0.0)
        q, k, v = _unpack_qkv(qkv, nh, hn)
        ref = _naive(q, k, v, causal=True)
        ref = ref.transpose(0, 2, 1, 3).reshape(b, s, nh * hn)
        np.testing.assert_allclose(ctx, ref, rtol=1e-4, atol=1e-5)

        dqkv = _flash_qkv_bwd_pallas(qkv, 0, ctx, lse, dctx, nh, hn,
                                     scale, True, 32, 0.0)

        def loss_ref(qkv):
            q, k, v = _unpack_qkv(qkv, nh, hn)
            out = _naive(q, k, v, causal=True)
            out = out.transpose(0, 2, 1, 3).reshape(b, s, nh * hn)
            return jnp.sum(out * dctx)

        dref = jax.grad(loss_ref)(qkv)
        np.testing.assert_allclose(dqkv, dref, rtol=1e-3, atol=1e-4)

    def test_qkv_packed_gate_uses_caller_dtype(self, monkeypatch):
        # ADVICE r5: the VMEM estimate must price the CALLER's itemsize.
        # At the 350M shape (s=1024, hn=64, block=512) bf16 fits the
        # budget but fp32 does not — with the old hardcoded itemsize of
        # 2, fp32 passed the gate and failed Mosaic allocation on chip
        # instead of routing to the fallback.
        from apex_tpu.ops import attention as attn_mod

        monkeypatch.setattr(attn_mod.jax, "default_backend",
                            lambda: "tpu")
        args = (8, 1024, 16, 64, 512, True, 0.0)
        assert attn_mod._qkv_packed_ok(*args, jnp.bfloat16)
        assert not attn_mod._qkv_packed_ok(*args, jnp.float32)

    def test_qkv_packed_block_autoshrink(self, monkeypatch):
        # the d=128/seq-2048 flagship shape exceeds the budget at the
        # default block of 512 but fits at 256: the selector must shrink
        # rather than silently dropping the flagship to the generic
        # kernels (ISSUE 2 tentpole d).  The 350M shape keeps its
        # measured-best 512, and fp32 at the 350M shape shrinks to 256.
        from apex_tpu.ops import attention as attn_mod

        monkeypatch.setattr(attn_mod.jax, "default_backend",
                            lambda: "tpu")
        pick = attn_mod._qkv_packed_block
        assert pick(4, 2048, 16, 128, 512, True, 0.0, jnp.bfloat16) == 256
        assert pick(8, 1024, 16, 64, 512, True, 0.0, jnp.bfloat16) == 512
        assert pick(8, 1024, 16, 64, 512, True, 0.0, jnp.float32) == 256
        # an unalignable shape yields None (generic path)
        assert pick(8, 1000, 16, 64, 512, True, 0.0, jnp.bfloat16) is None

    @pytest.mark.slow  # interpret-mode packed-QKV kernels, like its sibling
    def test_packed_qkv_lse_residual_is_logical_size(self):
        # ADVICE r5: the attn_res remat policy used to save the raw
        # [b, n_hg, group, n_b, 8, block] lse slab — an 8x residual from
        # the sublane broadcast.  The fwd rule now slices row 0 before
        # checkpoint_name; the residual must be logical-size (sublane
        # dim 1) and the backward must consume it and still match the
        # reference grads.
        from apex_tpu.ops.attention import (
            _flash_qkv_bwd_rule, _flash_qkv_fwd_rule)

        b, s, nh, hn, block = 2, 64, 2, 64, 32  # group=2 at hn=64
        scale = 1.0 / np.sqrt(hn)
        qkv = jax.random.normal(jax.random.PRNGKey(0),
                                (b, s, nh * 3 * hn), jnp.float32)
        ctx, res = _flash_qkv_fwd_rule(qkv, None, None, 0, nh, hn, scale,
                                       True, block, 0.0)
        lse = res[5]
        n_hg, group, n_b = 1, 2, s // block
        assert lse.shape == (b, n_hg, group, n_b, 1, block), lse.shape

        dctx = jax.random.normal(jax.random.PRNGKey(1), (b, s, nh * hn),
                                 jnp.float32)
        dqkv, _, _, _ = _flash_qkv_bwd_rule(nh, hn, scale, True, block,
                                            0.0, res, dctx)

        def loss_ref(qkv):
            q, k, v = _unpack_qkv(qkv, nh, hn)
            out = _naive(q, k, v, causal=True)
            out = out.transpose(0, 2, 1, 3).reshape(b, s, nh * hn)
            return jnp.sum(out * dctx)

        dref = jax.grad(loss_ref)(qkv)
        np.testing.assert_allclose(dqkv, dref, rtol=1e-3, atol=1e-4)

    def test_bwd_tiles_gate_lane_alignment(self, monkeypatch):
        # ADVICE r5: the unrolled-tiles backward slices lse on the LANE
        # dim at offsets qi = qb*block_q — unaligned for sub-128 blocks
        # with more than one q-block; such shapes must route to the grid
        # fallback, while single-q-block and 128-multiple blocks keep
        # the tiles kernel.
        from apex_tpu.ops import attention as attn_mod

        monkeypatch.setattr(attn_mod.jax, "default_backend",
                            lambda: "tpu")
        sd = lambda sq: jax.ShapeDtypeStruct((4, sq, 64), jnp.bfloat16)
        ok = attn_mod._bwd_tiles_ok
        # block_q=16 with sq=64 -> 4 q-blocks at lane-unaligned offsets
        assert not ok(sd(64), sd(64), None, 16, 16)
        # sq == block_q: single q-block, offset 0 — allowed
        assert ok(sd(64), sd(64), None, 64, 64)
        # 128-multiple block with several q-blocks — allowed
        assert ok(sd(512), sd(512), None, 128, 128)

    @pytest.mark.slow  # heaviest interpret/parity tier (ISSUE 6 wall-clock)
    def test_causal_sq_longer_than_sk(self):
        # causal cross-attention with sq > sk: the leading q rows attend
        # to nothing (fully masked) — the unrolled-tiles kernels must
        # emit zeros for statically-invisible q-blocks, not crash
        # (r5 review finding)
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 16))
        out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        ref = _naive(q, k, v, causal=True)
        # rows whose causal window is empty are zero by flash convention
        empty = jnp.arange(64) + (32 - 64) < 0
        ref = jnp.where(empty[None, :, None], 0.0, ref)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
        g = jax.grad(lambda *a: jnp.sum(flash_attention(
            *a, causal=True, block_q=16, block_k=16) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for t in g:
            assert np.isfinite(np.asarray(t)).all()

    def test_4d_and_cross_lengths(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 16, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 32, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 32, 8))
        np.testing.assert_allclose(
            flash_attention(q, k, v), _naive(q, k, v), rtol=1e-4, atol=1e-5)

    def test_additive_mask(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (3, 16, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (3, 16, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (3, 16, 8))
        bias = jnp.where(
            jax.random.bernoulli(jax.random.PRNGKey(3), 0.3, (3, 16, 16)),
            -10000.0, 0.0)
        np.testing.assert_allclose(
            flash_attention(q, k, v, mask_bias=bias),
            _naive(q, k, v, mask_bias=bias), rtol=1e-4, atol=1e-5)

    def test_grads_match_naive(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 16))

        def f_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        def f_naive(q, k, v):
            return jnp.sum(_naive(q, k, v, True) ** 2)

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_naive, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)

    def test_grads_with_blocked_bwd(self):
        # force multi-block bwd (block_k < sk)
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 8))

        def f(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, block_q=16, block_k=16) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(_naive(q, k, v) ** 2)

        g1 = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)

    @pytest.mark.slow  # heaviest interpret/parity tier (ISSUE 6 wall-clock)
    def test_pallas_interpret_path_matches(self):
        # exercise the Pallas kernel in interpret mode explicitly
        from apex_tpu.ops.attention import _flash_fwd_pallas
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 256, 128))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 256, 128))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 256, 128))
        o, lse = _flash_fwd_pallas(q, k, v, None, None, None, 0,
                                   1.0 / np.sqrt(128.0), True, 128, 128,
                                   0.0)
        np.testing.assert_allclose(o, _naive(q, k, v, True), rtol=1e-4,
                                   atol=1e-5)
        assert lse.shape == (2, 256)

    @pytest.mark.parametrize("causal,with_mask,with_seg", [
        (False, False, False),
        (True, False, False),
        (False, True, False),
        (False, False, True),
        (True, True, False),
        # per-head mask [bh,...] + shared segments [1,...] together: the
        # batch selectors of the two BlockSpec families must not cross
        (False, True, True),
    ])
    @pytest.mark.slow  # interpret-mode Pallas backward cells (ISSUE 2 CI satellite)
    def test_pallas_bwd_interpret_matches(self, causal, with_mask, with_seg):
        """The Pallas dq/dkv kernels (interpret mode) against jax.grad of
        the naive reference — every mask/seg/causal combination."""
        from apex_tpu.ops.attention import (
            _flash_bwd_pallas, _flash_fwd_pallas)
        bh, s, d = 2, 64, 16
        q = jax.random.normal(jax.random.PRNGKey(0), (bh, s, d))
        k = jax.random.normal(jax.random.PRNGKey(1), (bh, s, d))
        v = jax.random.normal(jax.random.PRNGKey(2), (bh, s, d))
        do = jax.random.normal(jax.random.PRNGKey(3), (bh, s, d))
        bias = jnp.where(
            jax.random.bernoulli(jax.random.PRNGKey(4), 0.3, (bh, s, s)),
            -10000.0, 0.0) if with_mask else None
        seg = (jnp.concatenate([jnp.zeros((1, 24), jnp.int32),
                                jnp.ones((1, 40), jnp.int32)], axis=1)
               if with_seg else None)
        scale = 1.0 / np.sqrt(d)
        o, lse = _flash_fwd_pallas(q, k, v, bias, seg, seg, 0, scale,
                                   causal, 16, 16, 0.0)
        dq, dk, dv = _flash_bwd_pallas(q, k, v, bias, seg, seg, 0, o, lse,
                                       do, scale, causal, 16, 16, 0.0)

        def ref(q, k, v):
            s_ = jnp.einsum("bqd,bkd->bqk", q, k) * scale
            if bias is not None:
                s_ = s_ + bias
            if seg is not None:
                s_ = jnp.where(seg[:, :, None] == seg[:, None, :], s_, -1e30)
            if causal:
                tri = jnp.tril(jnp.ones((s, s), bool))
                s_ = jnp.where(tri, s_, -1e30)
            return jnp.sum(jax.nn.softmax(s_, -1) @ v * do)

        gq, gk, gv = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(dq, gq, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(dk, gk, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(dv, gv, rtol=1e-3, atol=1e-4)

    def test_segment_ids_public_api(self):
        """segment_ids masks cross-segment attention — equal to running the
        two segments separately."""
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 8))
        seg = jnp.array([0] * 12 + [1] * 20)
        out = flash_attention(q, k, v, segment_ids=seg)
        a = _naive(q[:, :12], k[:, :12], v[:, :12])
        b = _naive(q[:, 12:], k[:, 12:], v[:, 12:])
        np.testing.assert_allclose(out, jnp.concatenate([a, b], axis=1),
                                   rtol=1e-4, atol=1e-5)

    def test_segment_ids_grads(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 24, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 24, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 24, 8))
        seg = jnp.array([0] * 8 + [1] * 16)

        def f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, segment_ids=seg) ** 2)

        def f_ref(q, k, v):
            a = jnp.sum(_naive(q[:, :8], k[:, :8], v[:, :8]) ** 2)
            b = jnp.sum(_naive(q[:, 8:], k[:, 8:], v[:, 8:]) ** 2)
            return a + b

        g1 = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)


class TestVarlenFastPath:
    """The r7 varlen fast path (ISSUE 5 tentpole): block-skip index,
    varlen/stream_skip/grid_skip kernels, packed-QKV segment masking,
    and the routing decisions that select them."""

    def _tpu(self, monkeypatch):
        from apex_tpu.ops import attention as attn_mod

        monkeypatch.setattr(attn_mod.jax, "default_backend",
                            lambda: "tpu")
        return attn_mod

    def test_routing_varlen_selects_fast_kernels(self, monkeypatch):
        # L0 routing satellite: varlen/padding shapes now select the
        # fast kernels; gates failing falls back correctly
        attn_mod = self._tpu(monkeypatch)
        sd = lambda s, d=64: jax.ShapeDtypeStruct((8, s, d), jnp.bfloat16)
        r = attn_mod.flash_attention_route(sd(512), segment_ids=True,
                                           block_q=128, block_k=128)
        assert r == {"fwd": "varlen", "bwd": "grid_skip"}
        # no segments: the r5 routes are unchanged
        r = attn_mod.flash_attention_route(sd(512), block_q=128,
                                           block_k=128)
        assert r == {"fwd": "tiles", "bwd": "tiles"}
        # a working set past the whole-sequence VMEM gate: the varlen
        # forward falls back to the grid kernel WITH the skip index
        r = attn_mod.flash_attention_route(sd(16384, 256),
                                           segment_ids=True,
                                           block_q=512, block_k=512)
        assert r["fwd"] == "stream_skip"
        # unalignable shape: everything falls to the XLA path
        r = attn_mod.flash_attention_route(sd(1000), segment_ids=True,
                                           block_q=128, block_k=128)
        assert r == {"fwd": "xla", "bwd": "xla"}

    def test_routing_qkv_packed_varlen(self, monkeypatch):
        attn_mod = self._tpu(monkeypatch)
        route = attn_mod.flash_attention_qkv_route
        assert route(8, 512, 16, 64, has_segments=True) == "packed_varlen"
        assert route(8, 512, 16, 64) == "packed"
        # gate failure (unaligned seq) falls back to the generic path
        assert route(8, 1000, 16, 64, has_segments=True) == "generic"

    def test_qkv_gate_prices_caller_dtype(self, monkeypatch):
        """ADVICE r5 #1 / ROADMAP maintenance regression pin: the
        packed-QKV VMEM gate must price the CALLER's qkv itemsize, not
        a hardcoded bf16.  At the flagship d=128/s=2048 shape the
        resident set is ~11 MB in bf16 (fits the 12 MB budget at the
        auto-shrunk block 256) and ~2x that in fp32 — a near-budget
        fp32 qkv must route to the generic fallback instead of passing
        the gate and failing Mosaic VMEM allocation."""
        import jax.numpy as jnp

        attn_mod = self._tpu(monkeypatch)
        gate = attn_mod._qkv_packed_ok
        assert gate(8, 2048, 16, 128, 256, True, 0.0, jnp.bfloat16)
        assert not gate(8, 2048, 16, 128, 256, True, 0.0, jnp.float32)
        route = attn_mod.flash_attention_qkv_route
        assert route(8, 2048, 16, 128, block=256,
                     dtype=jnp.bfloat16) == "packed"
        assert route(8, 2048, 16, 128, block=256,
                     dtype=jnp.float32) == "generic"
        # the public wrapper threads the real qkv.dtype into the gate:
        # tracing an fp32 qkv takes the generic (transposed) path, whose
        # jaxpr transposes the heads — the packed kernel's does not
        qkv32 = jnp.zeros((1, 2048, 16 * 3 * 128), jnp.float32)
        jaxpr = str(jax.make_jaxpr(
            lambda x: attn_mod.flash_attention_qkv(x, 16, block=256))(
                qkv32))
        assert "transpose" in jaxpr

    def test_routing_override_forces_generic(self, monkeypatch):
        attn_mod = self._tpu(monkeypatch)
        sd = jax.ShapeDtypeStruct((8, 512, 64), jnp.bfloat16)
        with attn_mod.routing_override(fwd="stream", bwd="grid"):
            r = attn_mod.flash_attention_route(sd, segment_ids=True,
                                               block_q=128, block_k=128)
        assert r == {"fwd": "stream", "bwd": "grid"}
        # override does not leak
        r = attn_mod.flash_attention_route(sd, segment_ids=True,
                                           block_q=128, block_k=128)
        assert r["fwd"] == "varlen"

    def test_segment_block_bounds_conservative(self):
        """The skip index may keep a dead tile but must NEVER skip a
        live one — checked against brute-force equality on random ids,
        plus tightness on the two shapes that matter (ascending packing,
        descending key-padding)."""
        from apex_tpu.ops.attention import _segment_block_bounds

        rng = np.random.RandomState(0)
        for _ in range(5):
            seg_q = jnp.asarray(rng.randint(0, 4, (2, 64)), jnp.int32)
            seg_k = jnp.asarray(rng.randint(0, 4, (2, 64)), jnp.int32)
            lq, lk = _segment_block_bounds(seg_q, seg_k, 16, 8)
            live = (np.asarray(seg_q)[:, :, None]
                    == np.asarray(seg_k)[:, None, :])
            for b in range(2):
                for qb in range(4):
                    rows = slice(qb * 16, qb * 16 + 16)
                    for kb in range(8):
                        cols = slice(kb * 8, kb * 8 + 8)
                        if live[b, rows, cols].any():
                            lo, hi = np.asarray(lq)[b, qb]
                            assert lo <= kb < hi, (b, qb, kb, lo, hi)
        # tightness on a padding tail: all-pad k-blocks are outside
        seg_q = jnp.ones((1, 64), jnp.int32)
        seg_k = jnp.asarray([[1] * 40 + [0] * 24], jnp.int32)
        lq, _ = _segment_block_bounds(seg_q, seg_k, 16, 8)
        assert np.asarray(lq)[0, 0].tolist() == [0, 5]  # 40/8 = 5 blocks

    @pytest.mark.slow  # interpret-mode Pallas varlen kernels (ISSUE 5)
    @pytest.mark.parametrize("route", ["varlen", "stream_skip"])
    def test_varlen_fwd_kernels_interpret_match(self, route):
        from apex_tpu.ops.attention import _flash_fwd_pallas

        bh, s, d = 2, 64, 16
        q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (bh, s, d))
                   for i in range(3))
        seg = jnp.asarray([[0] * 24 + [1] * 24 + [2] * 16,
                           [0] * 40 + [1] * 8 + [2] * 16], jnp.int32)
        scale = 1.0 / np.sqrt(d)
        o, lse = _flash_fwd_pallas(q, k, v, None, seg, seg, 0, scale,
                                   False, 16, 16, 0.0, route=route)
        ref = _naive_seg(q, k, v, seg, scale)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)
        assert lse.shape == (bh, s)

    @pytest.mark.slow  # interpret-mode Pallas varlen kernels (ISSUE 5)
    def test_varlen_grid_skip_bwd_interpret_matches(self):
        from apex_tpu.ops.attention import (_flash_bwd_pallas,
                                            _flash_fwd_pallas)

        bh, s, d = 2, 64, 16
        q, k, v, do = (jax.random.normal(jax.random.PRNGKey(i),
                                         (bh, s, d)) for i in range(4))
        seg = jnp.asarray([[0] * 24 + [1] * 40], jnp.int32)
        scale = 1.0 / np.sqrt(d)
        o, lse = _flash_fwd_pallas(q, k, v, None, seg, seg, 0, scale,
                                   False, 16, 16, 0.0, route="varlen")
        dq, dk, dv = _flash_bwd_pallas(q, k, v, None, seg, seg, 0, o,
                                       lse, do, scale, False, 16, 16,
                                       0.0, route="grid_skip")
        gq, gk, gv = jax.grad(
            lambda q, k, v: jnp.sum(_naive_seg(q, k, v, seg, scale) * do),
            argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(dq, gq, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(dk, gk, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(dv, gv, rtol=1e-3, atol=1e-4)

    @pytest.mark.slow  # interpret-mode packed varlen kernels (ISSUE 5)
    @pytest.mark.parametrize("causal", [False, True])
    def test_packed_qkv_varlen_interpret_matches(self, causal):
        """In-kernel segment masking on the packed-QKV kernels (the
        tentpole's fast tile schedule) vs the generic reference —
        interpret-mode parity, fwd and bwd, incl. the dynamic
        block-skip carry loop."""
        from apex_tpu.ops.attention import (_flash_qkv_bwd_pallas,
                                            _flash_qkv_fwd_pallas)

        b, s, nh, hn = 2, 64, 2, 64  # group=2 at hn=64
        scale = 1.0 / np.sqrt(hn)
        qkv = jax.random.normal(jax.random.PRNGKey(0),
                                (b, s, nh * 3 * hn), jnp.float32)
        seg = jnp.asarray([[0] * 24 + [1] * 40,
                           [0] * 40 + [7] * 24], jnp.int32)

        def ref(qkv):
            q, k, v = _unpack_qkv(qkv, nh, hn)
            s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
            s_ = jnp.where(seg[:, None, :, None] == seg[:, None, None, :],
                           s_, -1e30)
            if causal:
                tri = jnp.tril(jnp.ones((s, s), bool))
                s_ = jnp.where(tri, s_, -1e30)
            out = jax.nn.softmax(s_, -1) @ v
            return out.transpose(0, 2, 1, 3).reshape(b, s, nh * hn)

        ctx, lse = _flash_qkv_fwd_pallas(qkv, 0, nh, hn, scale, causal,
                                         16, 0.0, seg_q=seg, seg_k=seg)
        np.testing.assert_allclose(ctx, ref(qkv), rtol=1e-4, atol=1e-5)
        dctx = jax.random.normal(jax.random.PRNGKey(1), ctx.shape)
        dqkv = _flash_qkv_bwd_pallas(qkv, 0, ctx, lse, dctx, nh, hn,
                                     scale, causal, 16, 0.0,
                                     seg_q=seg, seg_k=seg)
        dref = jax.grad(lambda x: jnp.sum(ref(x) * dctx))(qkv)
        np.testing.assert_allclose(dqkv, dref, rtol=1e-3, atol=1e-4)

    @pytest.mark.slow  # heaviest interpret/parity tier (ISSUE 6 wall-clock)
    def test_qkv_wrapper_segments_fallback_matches(self):
        """Public flash_attention_qkv(segment_ids=...) — off-TPU this
        takes the generic fallback with identical math; grads flow."""
        b, s, nh, hn = 2, 32, 2, 8
        qkv = jax.random.normal(jax.random.PRNGKey(0), (b, s, nh * 3 * hn))
        seg = jnp.asarray([[0] * 12 + [1] * 20, [0] * 20 + [1] * 12],
                          jnp.int32)

        def ref(qkv):
            q, k, v = _unpack_qkv(qkv, nh, hn)
            s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(hn)
            s_ = jnp.where(seg[:, None, :, None] == seg[:, None, None, :],
                           s_, -1e30)
            out = jax.nn.softmax(s_, -1) @ v
            return out.transpose(0, 2, 1, 3).reshape(b, s, nh * hn)

        ctx = flash_attention_qkv(qkv, nh, causal=False, block=16,
                                  segment_ids=seg)
        np.testing.assert_allclose(ctx, ref(qkv), rtol=1e-4, atol=1e-5)
        g = jax.grad(lambda x: jnp.sum(flash_attention_qkv(
            x, nh, causal=False, block=16, segment_ids=seg) ** 2))(qkv)
        gr = jax.grad(lambda x: jnp.sum(ref(x) ** 2))(qkv)
        np.testing.assert_allclose(g, gr, rtol=1e-3, atol=1e-4)

    @pytest.mark.slow  # interpret-mode zero-trip edge (ISSUE 5)
    def test_varlen_fully_masked_block_emits_zeros(self):
        """A q-block whose segment has no matching keys anywhere gets a
        zero-trip skip loop: zeros out, -inf lse, finite (zero) grads —
        the l == 0 convention of every other kernel."""
        from apex_tpu.ops.attention import (_flash_bwd_pallas,
                                            _flash_fwd_pallas)

        bh, s, d = 1, 48, 16
        q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (bh, s, d))
                   for i in range(3))
        seg_q = jnp.asarray([[0] * 16 + [9] * 16 + [1] * 16], jnp.int32)
        seg_k = jnp.asarray([[0] * 16 + [2] * 16 + [1] * 16], jnp.int32)
        scale = 1.0 / np.sqrt(d)
        o, lse = _flash_fwd_pallas(q, k, v, None, seg_q, seg_k, 0,
                                   scale, False, 16, 16, 0.0,
                                   route="varlen")
        assert np.allclose(np.asarray(o)[0, 16:32], 0.0)
        assert np.all(np.asarray(lse)[0, 16:32] < -1e29)
        do = jnp.ones_like(q)
        dq, dk, dv = _flash_bwd_pallas(q, k, v, None, seg_q, seg_k, 0,
                                       o, lse, do, scale, False, 16, 16,
                                       0.0, route="grid_skip")
        for t in (dq, dk, dv):
            assert np.isfinite(np.asarray(t)).all()
        assert np.allclose(np.asarray(dq)[0, 16:32], 0.0)


def _naive_seg(q, k, v, seg, scale):
    s_ = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    s_ = jnp.where(seg[:, :, None] == seg[:, None, :], s_, -1e30)
    p = jax.nn.softmax(s_, -1)
    # rows with no visible key are zero under the flash l==0 convention
    dead = (seg[:, :, None] == seg[:, None, :]).sum(-1) == 0
    return jnp.where(dead[..., None], 0.0, p @ v)


class TestVarlen:
    """flash_attention_varlen — the reference FMHA's BERT-style packed
    interface (contrib/fmha/fmha.py:33-75), mapped to segment-id masking."""

    def test_matches_per_sequence(self):
        h, d = 2, 8
        lens = [5, 11, 8]
        total = 32  # includes 8 padding tokens
        cu = jnp.array([0, 5, 16, 24], jnp.int32)
        q = jax.random.normal(jax.random.PRNGKey(0), (total, h, d))
        k = jax.random.normal(jax.random.PRNGKey(1), (total, h, d))
        v = jax.random.normal(jax.random.PRNGKey(2), (total, h, d))
        from apex_tpu.ops.attention import flash_attention_varlen
        out = flash_attention_varlen(q, k, v, cu)
        assert out.shape == (total, h, d)
        start = 0
        for n in lens:
            sl = slice(start, start + n)
            ref = _naive(q[sl].transpose(1, 0, 2), k[sl].transpose(1, 0, 2),
                         v[sl].transpose(1, 0, 2))
            np.testing.assert_allclose(out[sl].transpose(1, 0, 2), ref,
                                       rtol=1e-4, atol=1e-5)
            start += n

    def test_causal_varlen(self):
        h, d = 1, 8
        cu = jnp.array([0, 6, 16], jnp.int32)
        q = jax.random.normal(jax.random.PRNGKey(0), (16, h, d))
        k = jax.random.normal(jax.random.PRNGKey(1), (16, h, d))
        v = jax.random.normal(jax.random.PRNGKey(2), (16, h, d))
        from apex_tpu.ops.attention import flash_attention_varlen
        out = flash_attention_varlen(q, k, v, cu, causal=True)
        for sl in (slice(0, 6), slice(6, 16)):
            ref = _naive(q[sl].transpose(1, 0, 2), k[sl].transpose(1, 0, 2),
                         v[sl].transpose(1, 0, 2), causal=True)
            np.testing.assert_allclose(out[sl].transpose(1, 0, 2), ref,
                                       rtol=1e-4, atol=1e-5)


class TestRingAttention:
    @pytest.fixture(scope="class")
    def mesh(self):
        return Mesh(np.array(jax.devices()[:8]), ("sp",))

    def test_matches_full_attention(self, mesh):
        # sequence 64 sharded 8 ways
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 16))

        def run(q, k, v):
            return ring_attention(q, k, v, "sp")

        out = shard_map(run, mesh=mesh,
                        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
                        out_specs=P(None, "sp"), check_rep=False)(q, k, v)
        np.testing.assert_allclose(out, _naive(q, k, v), rtol=1e-4, atol=1e-5)

    def test_causal_matches_full(self, mesh):
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 16))

        def run(q, k, v):
            return ring_attention(q, k, v, "sp", causal=True)

        out = shard_map(run, mesh=mesh,
                        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
                        out_specs=P(None, "sp"), check_rep=False)(q, k, v)
        np.testing.assert_allclose(out, _naive(q, k, v, causal=True),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.slow  # heaviest 8-device ring bwd (ISSUE 6 wall-clock)
    def test_grads_flow_through_ring(self, mesh):
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 32, 8))

        def loss(q, k, v):
            def run(q, k, v):
                o = ring_attention(q, k, v, "sp", causal=True)
                return jax.lax.psum(jnp.sum(o ** 2), "sp")
            return shard_map(run, mesh=mesh,
                             in_specs=(P(None, "sp"),) * 3,
                             out_specs=P(), check_rep=False)(q, k, v)

        def loss_ref(q, k, v):
            return jnp.sum(_naive(q, k, v, causal=True) ** 2)

        g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)

    def test_backward_memory_flat_in_world_size(self):
        """The custom-VJP second ring pass must not save rotated K/V blocks:
        per-device temp memory of the compiled grad stays flat as the ring
        grows 2 → 8 devices at constant local shard (VERDICT r1 weak #4)."""

        def temp_bytes(n_dev, s_local):
            m = Mesh(np.array(jax.devices()[:n_dev]), ("sp",))
            qg = jnp.zeros((2, s_local * n_dev, 16))

            def loss(q, k, v):
                def run(q, k, v):
                    o = ring_attention(q, k, v, "sp", causal=True)
                    return jax.lax.psum(jnp.sum(o ** 2), "sp")
                return shard_map(run, mesh=m, in_specs=(P(None, "sp"),) * 3,
                                 out_specs=P(), check_rep=False)(q, k, v)

            c = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(
                qg, qg, qg).compile()
            stats = c.memory_analysis()
            assert stats is not None and stats.temp_size_in_bytes > 0
            return stats.temp_size_in_bytes

        b2 = temp_bytes(2, 32)
        b8 = temp_bytes(8, 32)
        assert b8 < b2 * 2.0, (b2, b8)


class TestMultiheadAttnModules:
    def test_self_attn_matches_naive(self):
        m = SelfMultiheadAttn(32, 4, bias=True)
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (10, 2, 32))
        out = m.apply(p, x, is_training=False)
        # reference: same projections + standard attention
        qkv = x @ p["in_proj_weight"].T + p["in_proj_bias"]
        q, k, v = jnp.split(qkv, 3, -1)

        def heads(t):
            return t.reshape(10, 2 * 4, 8).transpose(1, 0, 2)

        ctx = _naive(heads(q), heads(k), heads(v), scale=8 ** -0.5)
        ref = (ctx.transpose(1, 0, 2).reshape(10, 2, 32)
               @ p["out_proj_weight"].T + p["out_proj_bias"])
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_self_attn_padding_mask(self):
        m = SelfMultiheadAttn(16, 2)
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 16))
        mask = jnp.array([[False] * 4 + [True] * 2,
                          [False] * 6])
        out = m.apply(p, x, key_padding_mask=mask, is_training=False)
        assert out.shape == (6, 2, 16)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_norm_add_variant(self):
        m = SelfMultiheadAttn(16, 2, include_norm_add=True)
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, 16))
        out = m.apply(p, x, is_training=False)
        # residual path present: zero attention weights would return x
        assert out.shape == x.shape

    def test_encdec(self):
        m = EncdecMultiheadAttn(16, 2, bias=True)
        p = m.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (5, 2, 16))
        enc = jax.random.normal(jax.random.PRNGKey(2), (9, 2, 16))
        out = m.apply(p, dec, enc, is_training=False)
        assert out.shape == (5, 2, 16)

    def test_dropout_changes_output(self):
        m = SelfMultiheadAttn(16, 2, dropout=0.5)
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, 16))
        o1 = m.apply(p, x, is_training=True,
                     dropout_rng=jax.random.PRNGKey(10))
        o2 = m.apply(p, x, is_training=False)
        assert not np.allclose(o1, o2)


@pytest.mark.slow  # heaviest interpret/parity tier (ISSUE 6 wall-clock)
def test_trainable_mask_bias_gets_gradient():
    """mask_is_constant=False must produce a real (nonzero) bias gradient
    (ADVICE r2: the default path silently returns zeros for it)."""
    from apex_tpu.ops.attention import flash_attention

    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(k1, (2, 16, 8))
    k = jax.random.normal(k2, (2, 16, 8))
    v = jax.random.normal(k3, (2, 16, 8))
    bias = jax.random.normal(k4, (1, 16, 16)) * 0.1

    def loss(b):
        return jnp.sum(flash_attention(q, k, v, mask_bias=b,
                                       mask_is_constant=False) ** 2)

    g = jax.grad(loss)(bias)
    assert jnp.abs(g).max() > 0
    # and the default (constant-mask) path still returns zeros, documented
    def loss_const(b):
        return jnp.sum(flash_attention(q, k, v, mask_bias=b) ** 2)
    g0 = jax.grad(loss_const)(bias)
    assert jnp.abs(g0).max() == 0


class TestKernelDropout:
    """In-kernel attention dropout (reference FMHA's Philox in-kernel
    dropout): counter-based hash masks, bit-identical across the Pallas
    tilings and the XLA fallback, replayed (not stored) in backward."""

    def _qkv(self, bh=4, s=32, d=8):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        return [jax.random.normal(k, (bh, s, d)) for k in ks]

    def test_keep_rate_statistics(self):
        from apex_tpu.ops.attention import _dropout_keep_full

        keep = _dropout_keep_full(jnp.int32(123), 8, 64, 64, 0.3)
        assert abs(float(keep.mean()) - 0.7) < 0.01

    def test_deterministic_and_seed_sensitivity(self):
        from apex_tpu.ops.attention import flash_attention

        q, k, v = self._qkv()
        a = flash_attention(q, k, v, dropout_rate=0.2, dropout_seed=5)
        b = flash_attention(q, k, v, dropout_rate=0.2, dropout_seed=5)
        c = flash_attention(q, k, v, dropout_rate=0.2, dropout_seed=6)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_matches_dense_reference_with_same_mask(self):
        from apex_tpu.ops.attention import (_dropout_keep_full,
                                            flash_attention)

        q, k, v = self._qkv()
        rate, seed = 0.25, 42
        out = flash_attention(q, k, v, causal=True, dropout_rate=rate,
                              dropout_seed=seed)
        s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(q.shape[-1])
        tri = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(tri, s, -1e30)
        p = jax.nn.softmax(s, -1)
        keep = _dropout_keep_full(jnp.int32(seed), *p.shape, rate)
        pd = jnp.where(keep, p, 0.0) / (1 - rate)
        ref = jnp.einsum("bqk,bkd->bqd", pd, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.slow  # heaviest interpret/parity tier (ISSUE 6 wall-clock)
    def test_grads_match_dense_reference(self):
        from apex_tpu.ops.attention import (_dropout_keep_full,
                                            flash_attention)

        q, k, v = self._qkv(bh=2, s=16, d=8)
        rate, seed = 0.3, 9

        def loss_fused(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, dropout_rate=rate,
                dropout_seed=seed) ** 2)

        def loss_ref(q, k, v):
            s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(q.shape[-1])
            tri = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
            s = jnp.where(tri, s, -1e30)
            p = jax.nn.softmax(s, -1)
            keep = _dropout_keep_full(jnp.int32(seed), *p.shape, rate)
            pd = jnp.where(keep, p, 0.0) / (1 - rate)
            return jnp.sum(jnp.einsum("bqk,bkd->bqd", pd, v) ** 2)

    # the custom-vjp backward replays the mask; AD of the dense
    # reference materialises it — gradients must agree
        gf = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-5)

    def test_rate_without_seed_raises(self):
        from apex_tpu.ops.attention import flash_attention

        q, k, v = self._qkv()
        with pytest.raises(ValueError):
            flash_attention(q, k, v, dropout_rate=0.1)


@pytest.mark.slow  # interpret-mode dropout kernels (ISSUE 2 CI satellite)
def test_pallas_dropout_kernels_interpret_match_dense():
    """The Pallas fwd + dq/dkv kernels WITH in-kernel dropout (interpret
    mode) against the dense masked reference using the same hash mask —
    different tile sizes than the mask helper, proving global-coordinate
    replay."""
    from apex_tpu.ops.attention import (
        _dropout_keep_full, _flash_bwd_pallas, _flash_fwd_pallas)

    bh, s, d = 2, 64, 16
    rate, seed = 0.3, 1234
    q = jax.random.normal(jax.random.PRNGKey(0), (bh, s, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (bh, s, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (bh, s, d))
    do = jax.random.normal(jax.random.PRNGKey(3), (bh, s, d))
    scale = 1.0 / np.sqrt(d)
    o, lse = _flash_fwd_pallas(q, k, v, None, None, None, seed, scale,
                               True, 16, 32, rate)
    dq, dk, dv = _flash_bwd_pallas(q, k, v, None, None, None, seed, o,
                                   lse, do, scale, True, 32, 16, rate)

    def ref(q, k, v):
        s_ = jnp.einsum("bqd,bkd->bqk", q, k) * scale
        tri = jnp.tril(jnp.ones((s, s), bool))
        s_ = jnp.where(tri, s_, -1e30)
        p = jax.nn.softmax(s_, -1)
        keep = _dropout_keep_full(jnp.int32(seed), bh, s, s, rate)
        pd = jnp.where(keep, p, 0.0) / (1 - rate)
        return jnp.einsum("bqk,bkd->bqd", pd, v)

    np.testing.assert_allclose(np.asarray(o), np.asarray(ref(q, k, v)),
                               rtol=1e-4, atol=1e-5)
    rq, rk, rv = jax.grad(
        lambda q, k, v: jnp.sum(ref(q, k, v) * do), argnums=(0, 1, 2))(
        q, k, v)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rq),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rk),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rv),
                               rtol=1e-4, atol=1e-5)


def test_bwd_vmem_guard_falls_back_for_large_shapes():
    """Shapes whose fused-backward resident set exceeds the core VMEM
    budget must route to the XLA blockwise backward (the guard added
    with the one-pass kernel) — and small shapes must not."""
    from apex_tpu.ops.attention import _BWD_VMEM_BUDGET, _pallas_bwd_ok

    class Arr:
        def __init__(self, shape, dtype=jnp.bfloat16):
            self.shape = shape
            self.dtype = jnp.dtype(dtype)

    big = Arr((1, 16384, 256))
    assert not _pallas_bwd_ok(big, big, None, 512, 512)
    # estimate for the big shape really is over budget
    small = Arr((8, 1024, 64))
    # off-TPU _pallas_ok is False; assert only the budget arithmetic by
    # checking the big shape trips even if the backend check passed
    sq, d = big.shape[1], big.shape[2]
    resident_min = 3 * sq * d * 2 + sq * d * 4
    assert resident_min > _BWD_VMEM_BUDGET
    assert small.shape[1] * small.shape[2] * 8 < _BWD_VMEM_BUDGET
