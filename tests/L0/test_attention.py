"""Fused attention tests: flash kernel vs naive reference, ring attention
across the 8-device mesh, contrib MHA modules.

Mirrors reference tests: contrib/test/fmha/test_fmha.py (fused vs py
reference), multihead_attn tests, plus the new long-context tier.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn, SelfMultiheadAttn
from apex_tpu.ops.attention import flash_attention, ring_attention


def _naive(q, k, v, causal=False, mask_bias=None, scale=None):
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if mask_bias is not None:
        s = s + mask_bias
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        tri = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(tri, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v)


class TestFlashAttention:
    def test_matches_naive(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (4, 64, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (4, 64, 32))
        np.testing.assert_allclose(
            flash_attention(q, k, v), _naive(q, k, v), rtol=1e-4, atol=1e-5)

    def test_causal_matches_naive(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 16))
        np.testing.assert_allclose(
            flash_attention(q, k, v, causal=True), _naive(q, k, v, True),
            rtol=1e-4, atol=1e-5)

    def test_4d_and_cross_lengths(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 16, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 32, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 32, 8))
        np.testing.assert_allclose(
            flash_attention(q, k, v), _naive(q, k, v), rtol=1e-4, atol=1e-5)

    def test_additive_mask(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (3, 16, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (3, 16, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (3, 16, 8))
        bias = jnp.where(
            jax.random.bernoulli(jax.random.PRNGKey(3), 0.3, (3, 16, 16)),
            -10000.0, 0.0)
        np.testing.assert_allclose(
            flash_attention(q, k, v, mask_bias=bias),
            _naive(q, k, v, mask_bias=bias), rtol=1e-4, atol=1e-5)

    def test_grads_match_naive(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 16))

        def f_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        def f_naive(q, k, v):
            return jnp.sum(_naive(q, k, v, True) ** 2)

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_naive, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)

    def test_grads_with_blocked_bwd(self):
        # force multi-block bwd (block_k < sk)
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 8))

        def f(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, block_q=16, block_k=16) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(_naive(q, k, v) ** 2)

        g1 = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)

    def test_pallas_interpret_path_matches(self):
        # exercise the Pallas kernel in interpret mode explicitly
        from apex_tpu.ops.attention import _flash_fwd_pallas
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 256, 128))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 256, 128))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 256, 128))
        o, lse = _flash_fwd_pallas(q, k, v, 1.0 / np.sqrt(128.0), True,
                                   128, 128)
        np.testing.assert_allclose(o, _naive(q, k, v, True), rtol=1e-4,
                                   atol=1e-5)
        assert lse.shape == (2, 256)


class TestRingAttention:
    @pytest.fixture(scope="class")
    def mesh(self):
        return Mesh(np.array(jax.devices()[:8]), ("sp",))

    def test_matches_full_attention(self, mesh):
        # sequence 64 sharded 8 ways
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 16))

        def run(q, k, v):
            return ring_attention(q, k, v, "sp")

        out = shard_map(run, mesh=mesh,
                        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
                        out_specs=P(None, "sp"), check_rep=False)(q, k, v)
        np.testing.assert_allclose(out, _naive(q, k, v), rtol=1e-4, atol=1e-5)

    def test_causal_matches_full(self, mesh):
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 16))

        def run(q, k, v):
            return ring_attention(q, k, v, "sp", causal=True)

        out = shard_map(run, mesh=mesh,
                        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
                        out_specs=P(None, "sp"), check_rep=False)(q, k, v)
        np.testing.assert_allclose(out, _naive(q, k, v, causal=True),
                                   rtol=1e-4, atol=1e-5)

    def test_grads_flow_through_ring(self, mesh):
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 8))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 8))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 32, 8))

        def loss(q, k, v):
            def run(q, k, v):
                o = ring_attention(q, k, v, "sp", causal=True)
                return jax.lax.psum(jnp.sum(o ** 2), "sp")
            return shard_map(run, mesh=mesh,
                             in_specs=(P(None, "sp"),) * 3,
                             out_specs=P(), check_rep=False)(q, k, v)

        def loss_ref(q, k, v):
            return jnp.sum(_naive(q, k, v, causal=True) ** 2)

        g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)


class TestMultiheadAttnModules:
    def test_self_attn_matches_naive(self):
        m = SelfMultiheadAttn(32, 4, bias=True)
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (10, 2, 32))
        out = m.apply(p, x, is_training=False)
        # reference: same projections + standard attention
        qkv = x @ p["in_proj_weight"].T + p["in_proj_bias"]
        q, k, v = jnp.split(qkv, 3, -1)

        def heads(t):
            return t.reshape(10, 2 * 4, 8).transpose(1, 0, 2)

        ctx = _naive(heads(q), heads(k), heads(v), scale=8 ** -0.5)
        ref = (ctx.transpose(1, 0, 2).reshape(10, 2, 32)
               @ p["out_proj_weight"].T + p["out_proj_bias"])
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_self_attn_padding_mask(self):
        m = SelfMultiheadAttn(16, 2)
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 16))
        mask = jnp.array([[False] * 4 + [True] * 2,
                          [False] * 6])
        out = m.apply(p, x, key_padding_mask=mask, is_training=False)
        assert out.shape == (6, 2, 16)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_norm_add_variant(self):
        m = SelfMultiheadAttn(16, 2, include_norm_add=True)
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, 16))
        out = m.apply(p, x, is_training=False)
        # residual path present: zero attention weights would return x
        assert out.shape == x.shape

    def test_encdec(self):
        m = EncdecMultiheadAttn(16, 2, bias=True)
        p = m.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (5, 2, 16))
        enc = jax.random.normal(jax.random.PRNGKey(2), (9, 2, 16))
        out = m.apply(p, dec, enc, is_training=False)
        assert out.shape == (5, 2, 16)

    def test_dropout_changes_output(self):
        m = SelfMultiheadAttn(16, 2, dropout=0.5)
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, 16))
        o1 = m.apply(p, x, is_training=True,
                     dropout_rng=jax.random.PRNGKey(10))
        o2 = m.apply(p, x, is_training=False)
        assert not np.allclose(o1, o2)
