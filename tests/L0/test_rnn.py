"""RNN stack tests: cell/stack parity vs torch.nn reference implementations
with copied weights (the role torch's own RNNs play for apex/RNN), plus the
reference's structural conventions (hidden tuple, output_size projection,
independent-stack bidirectionality) and amp rnn_compat."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import rnn as apex_rnn
from apex_tpu.amp import lists as amp_lists
from apex_tpu.amp.rnn_compat import half_cell, whitelist_rnn_cells

T, B, I, H = 7, 3, 5, 8


def _to_jax(t):
    return jnp.asarray(t.detach().numpy())


@pytest.mark.parametrize("num_layers", [1, 2])
def test_lstm_matches_torch(num_layers):
    torch.manual_seed(0)
    tmod = torch.nn.LSTM(I, H, num_layers)
    model = apex_rnn.LSTM(I, H, num_layers)
    params = model.init(jax.random.PRNGKey(0))
    for k in range(num_layers):
        params[k]["w_ih"] = _to_jax(getattr(tmod, f"weight_ih_l{k}"))
        params[k]["w_hh"] = _to_jax(getattr(tmod, f"weight_hh_l{k}"))
        params[k]["b_ih"] = _to_jax(getattr(tmod, f"bias_ih_l{k}"))
        params[k]["b_hh"] = _to_jax(getattr(tmod, f"bias_hh_l{k}"))

    x = torch.randn(T, B, I)
    want, (hn, cn) = tmod(x)
    got, (h_got, c_got) = model.apply(params, _to_jax(x))
    np.testing.assert_allclose(np.asarray(got), want.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_got), hn.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c_got), cn.detach().numpy(),
                               rtol=1e-5, atol=1e-5)


def test_gru_matches_torch():
    torch.manual_seed(1)
    tmod = torch.nn.GRU(I, H, 1)
    model = apex_rnn.GRU(I, H, 1)
    params = model.init(jax.random.PRNGKey(0))
    params[0]["w_ih"] = _to_jax(tmod.weight_ih_l0)
    params[0]["w_hh"] = _to_jax(tmod.weight_hh_l0)
    params[0]["b_ih"] = _to_jax(tmod.bias_ih_l0)
    params[0]["b_hh"] = _to_jax(tmod.bias_hh_l0)
    x = torch.randn(T, B, I)
    want, hn = tmod(x)
    got, (h_got,) = model.apply(params, _to_jax(x))
    np.testing.assert_allclose(np.asarray(got), want.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_got), hn.detach().numpy(),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("nonlinearity,factory", [
    ("tanh", apex_rnn.Tanh), ("relu", apex_rnn.ReLU)])
def test_vanilla_rnn_matches_torch(nonlinearity, factory):
    torch.manual_seed(2)
    tmod = torch.nn.RNN(I, H, 1, nonlinearity=nonlinearity)
    model = factory(I, H, 1)
    params = model.init(jax.random.PRNGKey(0))
    params[0]["w_ih"] = _to_jax(tmod.weight_ih_l0)
    params[0]["w_hh"] = _to_jax(tmod.weight_hh_l0)
    params[0]["b_ih"] = _to_jax(tmod.bias_ih_l0)
    params[0]["b_hh"] = _to_jax(tmod.bias_hh_l0)
    x = torch.randn(T, B, I)
    want, _ = tmod(x)
    got, _ = model.apply(params, _to_jax(x))
    np.testing.assert_allclose(np.asarray(got), want.detach().numpy(),
                               rtol=1e-5, atol=1e-5)


def test_bidirectional_single_layer_matches_torch():
    """1-layer bidirectional agrees with torch (for >1 layers the reference
    runs two independent stacks and concats at the end — RNNBackend.py:25-50
    — which deliberately differs from torch's per-layer concat)."""
    torch.manual_seed(3)
    tmod = torch.nn.LSTM(I, H, 1, bidirectional=True)
    model = apex_rnn.LSTM(I, H, 1, bidirectional=True)
    params = model.init(jax.random.PRNGKey(0))
    params[0]["w_ih"] = _to_jax(tmod.weight_ih_l0)
    params[0]["w_hh"] = _to_jax(tmod.weight_hh_l0)
    params[0]["b_ih"] = _to_jax(tmod.bias_ih_l0)
    params[0]["b_hh"] = _to_jax(tmod.bias_hh_l0)
    params[1]["w_ih"] = _to_jax(tmod.weight_ih_l0_reverse)
    params[1]["w_hh"] = _to_jax(tmod.weight_hh_l0_reverse)
    params[1]["b_ih"] = _to_jax(tmod.bias_ih_l0_reverse)
    params[1]["b_hh"] = _to_jax(tmod.bias_hh_l0_reverse)
    x = torch.randn(T, B, I)
    want, _ = tmod(x)
    got, hidden = model.apply(params, _to_jax(x))
    np.testing.assert_allclose(np.asarray(got), want.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
    assert hidden[0].shape == (2, B, H)


def test_batch_first_and_jit():
    model = apex_rnn.GRU(I, H, 2, batch_first=True)
    params = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, I))
    got, _ = jax.jit(lambda p, x: model.apply(p, x))(params, x)
    assert got.shape == (B, T, H)
    x_tmajor = jnp.swapaxes(x, 0, 1)
    model2 = apex_rnn.GRU(I, H, 2, batch_first=False)
    want, _ = model2.apply(params, x_tmajor)
    np.testing.assert_allclose(np.asarray(got), np.asarray(jnp.swapaxes(want, 0, 1)),
                               rtol=1e-6)


def test_output_size_projection_and_mlstm():
    out_size = 4
    model = apex_rnn.mLSTM(I, H, 1, output_size=out_size)
    params = model.init(jax.random.PRNGKey(0))
    assert "w_ho" in params[0] and "w_mih" in params[0]
    x = jax.random.normal(jax.random.PRNGKey(1), (T, B, I))
    out, (h, c) = model.apply(params, x)
    assert out.shape == (T, B, out_size)
    assert h.shape == (1, B, out_size) and c.shape == (1, B, H)
    # trains: grads flow through the multiplicative path
    g = jax.grad(lambda p: jnp.sum(model.apply(p, x)[0] ** 2))(params)
    assert float(jnp.abs(g[0]["w_mih"]).sum()) > 0


def test_dropout_between_layers_only():
    model = apex_rnn.LSTM(I, H, 2, dropout=0.5)
    params = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (T, B, I))
    y1, _ = model.apply(params, x, key=jax.random.PRNGKey(2))
    y2, _ = model.apply(params, x, key=jax.random.PRNGKey(3))
    assert not np.allclose(np.asarray(y1), np.asarray(y2))
    y_eval, _ = model.apply(params, x, training=False)
    y_eval2, _ = model.apply(params, x, training=False)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(y_eval2))


def test_gru_rejects_output_size():
    with pytest.raises(ValueError):
        apex_rnn.GRU(I, H, 1, output_size=4)


def test_rnn_compat_half_cell():
    whitelist_rnn_cells()
    assert "lstm_cell" in amp_lists.FP16_FUNCS
    from apex_tpu.rnn.cells import lstm_cell
    cell = half_cell(lstm_cell)
    params = {"w_ih": jnp.ones((4 * H, I)), "w_hh": jnp.ones((4 * H, H))}
    x = jnp.ones((B, I))
    h = (jnp.zeros((B, H)), jnp.zeros((B, H)))
    hy, cy = cell(params, x, h)
    assert hy.dtype == jnp.bfloat16
    assert cy.dtype == jnp.float32  # cell state carried fp32


class TestAmpRnnCompat:
    """amp ↔ RNN integration (reference apex/amp RNN compat shims,
    amp/rnn_compat.py + VERDICT r1 row 10): with functional params, the O2
    cast/master-weight path applies to RNNs with no special-casing — prove
    it trains under amp O2 with a dynamic loss scale and skips on inf."""

    def test_lstm_trains_under_amp_o2(self):
        from apex_tpu import amp, optimizers
        from apex_tpu.rnn import LSTM

        model = LSTM(input_size=8, hidden_size=16, num_layers=2)
        params = model.init(jax.random.PRNGKey(0))
        amp_state = amp.initialize("O2")
        scaler = amp_state.scaler
        scale_state = scaler.init()
        opt = optimizers.FusedAdam(lr=1e-2)
        opt_state = opt.init(params)

        x = jax.random.normal(jax.random.PRNGKey(1), (6, 4, 8))
        y = jax.random.normal(jax.random.PRNGKey(2), (6, 4, 16))

        def loss_fn(p, x, y):
            out, _ = model.apply(p, x, training=False)
            return jnp.mean((out - y) ** 2)

        grad_fn = amp.scaled_value_and_grad(loss_fn, scaler)

        @jax.jit
        def step(params, opt_state, scale_state, x, y):
            half = amp_state.cast_model(params)
            loss, grads, finite = grad_fn(scale_state, half, x, y)
            new_p, new_o = opt.step(grads, opt_state, params)
            params, opt_state = amp.skip_or_step(
                finite, (new_p, new_o), (params, opt_state))
            return params, opt_state, scaler.update(scale_state, finite), loss

        losses = []
        for _ in range(20):
            params, opt_state, scale_state, loss = step(
                params, opt_state, scale_state, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.9, losses

        # the compute params really are half precision under O2
        half = amp_state.cast_model(params)
        dtypes = {a.dtype for a in jax.tree_util.tree_leaves(half)}
        assert jnp.dtype(jnp.bfloat16) in dtypes or jnp.dtype(jnp.float16) in dtypes

        # a poisoned batch skips the step and halves the scale
        before = jax.tree_util.tree_leaves(params)[0]
        scale_before = scale_state.loss_scale
        params2, _, scale_state2, _ = step(
            params, opt_state, scale_state, jnp.full_like(x, jnp.inf), y)
        np.testing.assert_array_equal(
            jax.tree_util.tree_leaves(params2)[0], before)
        assert float(scale_state2.loss_scale) < float(scale_before)
