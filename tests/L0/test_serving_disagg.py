"""Disaggregated prefill/decode over the transport seam (ISSUE 18).

THE acceptance pin lives here: a prefill tier ships finished KV pages
to a decode tier over a lossy wire — every message class (ping /
migrate / kv_page / kv_commit) crossed with every injected fault
(drop / delay / duplicate / reorder / corrupt) lands on its documented
outcome (retry, dedupe, CRC re-request, fence, or local-prefill
fallback), token streams stay bitwise identical to a colocated
single-engine control, and zero requests are dropped.  The happy path
is additionally compile-free on every replica (warmup built the
import executable too).
"""

import json
import random

import pytest

import apex_tpu.telemetry as tel
from apex_tpu.analysis import hot_path_guard
from apex_tpu.resilience.chaos import KillReplica
from apex_tpu.serving import (ServingEngine, ServingModelConfig, SimClock,
                              SpecConfig, init_params)
from apex_tpu.serving.engine import AdmissionRefused
from apex_tpu.serving.fleet import (FENCED, ChaosTransport, DisaggRouter,
                                    FleetCapacityError, FleetRouter,
                                    LocalTransport, PageImporter,
                                    ReplicaProxy, TransportCorruption,
                                    register_error)
from apex_tpu.serving.fleet.transport import FAULTS
from apex_tpu.serving.kv_cache import verify_page_payload
from apex_tpu.telemetry.regress import key_direction
from apex_tpu.telemetry.summarize import summarize_events

pytestmark = [pytest.mark.serving, pytest.mark.fleet]

CFG = ServingModelConfig(vocab_size=64, hidden_size=32, num_heads=4,
                         num_layers=2, max_position=96)


@pytest.fixture(scope="module")
def serving_params():
    return init_params(CFG, seed=0)


def _factory(params, clock, **kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_budget", CFG.max_position)
    kw.setdefault("max_queue", 16)

    def build():
        return ServingEngine(CFG, params, clock=clock, **kw)

    return build


def _fleet(params, n=2, *, telemetry=None, clock=None, factory_kw=None,
           **router_kw):
    clock = clock if clock is not None else SimClock()
    reps = [ReplicaProxy(f"r{i}", _factory(params, clock,
                                           **(factory_kw or {})))
            for i in range(n)]
    return FleetRouter(reps, telemetry=telemetry, **router_kw), reps


def _disagg(params, *, n_prefill=1, n_decode=1, telemetry=None,
            clock=None, factory_kw=None, prefill_kw=None, decode_kw=None,
            **router_kw):
    """Role-split fleet: ``p*`` replicas are prefill-only, ``d*``
    replicas warm the page-import executable."""
    clock = clock if clock is not None else SimClock()
    pkw = dict(factory_kw or {})
    pkw.update(prefill_kw or {})
    dkw = dict(factory_kw or {})
    dkw.update(decode_kw or {})
    reps = [ReplicaProxy(f"p{i}",
                         _factory(params, clock, prefill_only=True, **pkw),
                         role="prefill")
            for i in range(n_prefill)]
    reps += [ReplicaProxy(f"d{i}",
                          _factory(params, clock, kv_import=True, **dkw),
                          role="decode")
             for i in range(n_decode)]
    return DisaggRouter(reps, telemetry=telemetry, **router_kw), reps


def _prompts(n, seed=0, lo=4, hi=10):
    rng = random.Random(seed)
    return [[rng.randrange(1, CFG.vocab_size)
             for _ in range(rng.randrange(lo, hi))] for _ in range(n)]


def _control_streams(params, prompts, max_new=5, **kw):
    """Uninterrupted colocated control: same prompts in the same
    submit order on one plain engine."""
    eng = _factory(params, SimClock(), **kw)()
    eng.warmup()
    for p in prompts:
        eng.submit(list(p), max_new_tokens=max_new)
    eng.run()
    return {r.rid: list(r.generated) for r in eng.sched.finished}


def _shipment(params, clock, prompt, max_new=5, **kw):
    """Run one prompt through a prefill-only engine and export it:
    returns ``(record, pages_payload, kv_len)``."""
    eng = _factory(params, clock, prefill_only=True, **kw)()
    eng.warmup()
    req = eng.submit(list(prompt), max_new_tokens=max_new)
    eng.step()
    assert req.prefill_pos is None and req.generated
    return eng.export_request(req.rid)


# ---------------------------------------------------------------------------
# The transport seam itself
# ---------------------------------------------------------------------------


class TestTransportSeam:
    def test_pipeline_roundtrip_mints_fresh_msg_ids(self):
        t = LocalTransport()
        seen = []
        t.register("d", "echo",
                   lambda p: (seen.append(p["x"]) or {"x": p["x"]}))
        assert t.call("d", "echo", {"x": 1}) == {"x": 1}
        assert t.call("d", "echo", {"x": 2}) == {"x": 2}
        assert seen == [1, 2]
        w1 = json.loads(t.serialize("d", "echo", {}))
        w2 = json.loads(t.serialize("d", "echo", {}))
        assert w1["msg_id"] != w2["msg_id"]

    def test_duplicate_wire_message_processes_once(self):
        t = LocalTransport()
        hits = []
        t.register("d", "bump",
                   lambda p: (hits.append(1) or {"hits": len(hits)}))
        wire = t.serialize("d", "bump", {})
        r1 = t.deliver(wire)
        r2 = t.deliver(wire)           # the duplicated copy
        assert r1 == r2 and len(hits) == 1

    def test_envelope_crc_catches_in_flight_tamper(self):
        t = LocalTransport()
        t.register("d", "echo", lambda p: {"ok": True})
        env = json.loads(t.serialize("d", "echo", {"x": 1}))
        env["payload"]["x"] = 2        # mutate without re-stamping
        reply = t.deliver(json.dumps(env))
        with pytest.raises(TransportCorruption, match="CRC"):
            t.deserialize_reply(reply)

    def test_registered_errors_cross_typed(self):
        class ProbeFailed(RuntimeError):
            pass

        register_error(ProbeFailed)
        t = LocalTransport()

        def boom(p):
            raise ProbeFailed("pop")

        t.register("d", "boom", boom)
        with pytest.raises(ProbeFailed, match="pop"):
            t.call("d", "boom", {})

    def test_unregistered_handler_error_propagates_raw(self):
        t = LocalTransport()

        def bug(p):
            raise ValueError("handler bug")

        t.register("d", "bug", bug)
        # a handler BUG must not be laundered into a retryable reply
        with pytest.raises(ValueError, match="handler bug"):
            t.call("d", "bug", {})

    def test_missing_handler_is_loud(self):
        with pytest.raises(KeyError, match="no handler"):
            LocalTransport().call("d", "nope", {})

    def test_reorder_never_fires_on_control_classes(self):
        chaos = ChaosTransport(LocalTransport(),
                               schedule={("ping", "reorder"): {1, 2},
                                         ("migrate", "reorder"): {1}})
        chaos.register("d", "ping", lambda p: {"pong": True})
        chaos.register("d", "migrate", lambda p: {"ok": True})
        for _ in range(2):
            assert chaos.call("d", "ping", {})["pong"]
        assert chaos.call("d", "migrate", {})["ok"]
        # request-reply classes are ordered by construction: the armed
        # cells are documented no-ops and must not inject anything
        assert chaos.injected == {}


# ---------------------------------------------------------------------------
# Chaos matrix — control plane (ping / migrate)
# ---------------------------------------------------------------------------


class TestControlPlaneChaosMatrix:
    @pytest.mark.parametrize("fault,cause", [
        ("drop", "transport_timeout"),
        ("delay", "transport_timeout"),
        ("corrupt", "transport_corruption"),
    ])
    def test_ping_fault_fences_and_work_reroutes(self, serving_params,
                                                 fault, cause):
        """A lost / late / corrupted health probe is indistinguishable
        from a dead replica: fence on the spot, migrate, streams stay
        bitwise."""
        prompts = _prompts(4, seed=1)
        control = _control_streams(serving_params, prompts)
        chaos = ChaosTransport(LocalTransport(),
                               schedule={("ping", fault): {1}})
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id=f"ping-{fault}", sinks=[mem])
        fleet, reps = _fleet(serving_params, n=2, telemetry=bus,
                             transport=chaos)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=5)
        fleet.run()
        assert reps[0].state == FENCED
        fences = [e for e in mem.events if e["type"] == "replica_fence"]
        assert [f["cause"] for f in fences] == [cause]
        assert chaos.injected == {f"ping:{fault}": 1}
        assert len(fleet.handles) == len(prompts)
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"

    def test_ping_duplicate_is_harmless(self, serving_params):
        prompts = _prompts(3, seed=2)
        control = _control_streams(serving_params, prompts, max_new=3)
        chaos = ChaosTransport(LocalTransport(),
                               schedule={("ping", "duplicate"): {1}})
        fleet, reps = _fleet(serving_params, n=2, transport=chaos)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=3)
        fleet.run()
        assert all(r.healthy for r in reps)     # nobody fenced
        assert chaos.injected == {"ping:duplicate": 1}
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks

    @pytest.mark.parametrize("fault", ["drop", "delay", "corrupt",
                                       "duplicate"])
    def test_migrate_fault_retries_dedupe_and_stay_bitwise(
            self, serving_params, fault):
        """Migration snapshots survive every wire fault: loss and
        corruption cost an immediate retry; a delayed-but-processed
        shipment's retry hits the rid-dedupe; a duplicated wire
        message hits the msg-id memo.  Nothing adopts twice, streams
        stay bitwise, zero drops."""
        prompts = _prompts(4, seed=3)
        control = _control_streams(serving_params, prompts)
        chaos = ChaosTransport(LocalTransport(),
                               schedule={("migrate", fault): {1}})
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id=f"mig-{fault}", sinks=[mem])
        fleet, reps = _fleet(serving_params, n=2, telemetry=bus,
                             transport=chaos, fault_retries=1)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=5)
        with KillReplica("r0", at_step=2):
            fleet.run()
        assert reps[0].state == FENCED
        assert chaos.injected == {f"migrate:{fault}": 1}
        moves = [e for e in mem.events if e["type"] == "request_migrate"]
        rids = [e["rid"] for e in moves]
        assert moves and len(rids) == len(set(rids))   # one hop per rid
        assert len(fleet.handles) == len(prompts)
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"

    def test_migrate_budget_exhaustion_is_loud(self, serving_params):
        """Control-plane operations have no fallback tier: a migrate
        that outlives its retry budget raises instead of silently
        dropping the snapshot."""
        chaos = ChaosTransport(LocalTransport(),
                               rates={("migrate", "drop"): 1.0})
        fleet, _ = _fleet(serving_params, n=2, transport=chaos,
                          fault_retries=1)
        fleet.warmup()
        for p in _prompts(4, seed=4):
            fleet.submit(p, max_new_tokens=5)
        with KillReplica("r0", at_step=2):
            with pytest.raises(RuntimeError, match="failed after"):
                fleet.run()


# ---------------------------------------------------------------------------
# Disaggregated serving — the happy path
# ---------------------------------------------------------------------------


class TestDisaggServing:
    def test_streams_bitwise_and_compile_free(self, serving_params):
        prompts = _prompts(6, seed=20)
        control = _control_streams(serving_params, prompts)
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="disagg", sinks=[mem])
        fleet, reps = _disagg(serving_params, n_prefill=2, n_decode=2,
                              telemetry=bus)
        fleet.warmup()
        rids = [fleet.submit(p, max_new_tokens=5) for p in prompts]
        # intake lands on the prefill tier only
        assert all(fleet.placement[r].startswith("p") for r in rids)
        with hot_path_guard("disagg serve", transfers=None) as g:
            fleet.run()
        # decode replicas never compile for adopted work: warmup
        # already built the import executable alongside the decode set
        assert g.recompiles == 0 and g.syncs == []
        ships = [e for e in mem.events if e["type"] == "kv_ship"]
        assert len(ships) == len(prompts)
        assert all(e["attempts"] == 0 and e["payload_bytes"] > 0
                   and e["pages"] >= 1 for e in ships)
        assert {e["from_replica"] for e in ships} <= {"p0", "p1"}
        # transfer-aware placement spreads the burst over BOTH decode
        # replicas instead of serializing behind one batch
        assert {e["to_replica"] for e in ships} == {"d0", "d1"}
        assert not [e for e in mem.events
                    if e["type"] == "kv_ship_fallback"]
        # ownership moved wholesale: requests finish on the decode
        # tier, prefill replicas end empty
        assert all(fleet.placement[r].startswith("d") for r in rids)
        assert all(r.queue_depth() + r.running() == 0 for r in reps[:2])
        assert len(fleet.handles) == len(prompts)
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"

    def test_quantized_pool_ships_scale_planes(self, serving_params):
        """int8 pools ship codes AND scales; the decode replica's
        stream matches the quantized colocated control bitwise."""
        prompts = _prompts(4, seed=21)
        control = _control_streams(serving_params, prompts,
                                   kv_quant="int8")
        fleet, _ = _disagg(serving_params,
                           factory_kw={"kv_quant": "int8"})
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=5)
        fleet.run()
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"

    def test_mixed_only_fleet_matches_base_router(self, serving_params):
        """A DisaggRouter over mixed replicas is the r16 router: no
        role to split on, nothing ships."""
        prompts = _prompts(4, seed=22)
        control = _control_streams(serving_params, prompts)
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="mixed", sinks=[mem])
        clock = SimClock()
        reps = [ReplicaProxy(f"r{i}", _factory(serving_params, clock))
                for i in range(2)]
        fleet = DisaggRouter(reps, telemetry=bus)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=5)
        fleet.run()
        assert not [e for e in mem.events if e["type"] == "kv_ship"]
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks

    def test_role_validation_is_loud(self, serving_params):
        clock = SimClock()
        with pytest.raises(ValueError, match="unknown replica role"):
            ReplicaProxy("x", _factory(serving_params, clock),
                         role="oracle")
        pre = [ReplicaProxy("p0", _factory(serving_params, clock,
                                           prefill_only=True),
                            role="prefill")]
        with pytest.raises(ValueError, match="decode-capable"):
            DisaggRouter(pre)
        dec = [ReplicaProxy("d0", _factory(serving_params, clock,
                                           kv_import=True),
                            role="decode")]
        with pytest.raises(ValueError, match="prefill-capable"):
            DisaggRouter(dec)

    def test_decode_tier_loss_is_loud(self, serving_params):
        fleet, reps = _disagg(serving_params)
        fleet.warmup()
        fleet.submit(_prompts(1, seed=23)[0], max_new_tokens=3)
        reps[1].fence()                 # the only decode replica dies
        with pytest.raises(RuntimeError, match="decode tier"):
            fleet.run()

    def test_migration_never_targets_prefill_replicas(self,
                                                      serving_params):
        """A decode replica dying mid-decode migrates its adopted work
        to the OTHER decode replica — never onto the prefill tier,
        whose engines would queue it forever."""
        prompts = _prompts(4, seed=24, lo=8, hi=12)
        control = _control_streams(serving_params, prompts, max_new=6)
        fleet, reps = _disagg(serving_params, n_prefill=1, n_decode=2,
                              fault_retries=0)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=6)
        with KillReplica("d0", at_step=4):
            fleet.run()
        assert reps[1].state == FENCED          # d0
        assert all(not v.startswith("p")
                   for v in fleet.placement.values())
        assert len(fleet.handles) == len(prompts)
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"


# ---------------------------------------------------------------------------
# The export / adopt seam on the engines
# ---------------------------------------------------------------------------


class TestExportAdoptSeam:
    def test_export_then_adopt_continues_bitwise(self, serving_params):
        clock = SimClock()
        prompt = _prompts(1, seed=30, lo=10, hi=11)[0]
        control = _control_streams(serving_params, [prompt], max_new=6)
        record, pages, kv_len = _shipment(serving_params, clock, prompt,
                                          max_new=6)
        assert len(pages) >= 1 and kv_len >= len(prompt)
        for p in pages:
            assert verify_page_payload(p)
        dec = ReplicaProxy("d0", _factory(serving_params, clock,
                                          kv_import=True), role="decode")
        dec.warmup()
        adopted = dec.engine.adopt_prefilled(record, pages, kv_len)
        assert dec.find_request(adopted.rid) is adopted
        dec.engine.run()
        assert list(adopted.generated) == control[adopted.rid]

    def test_export_releases_the_prefill_footprint(self, serving_params):
        clock = SimClock()
        eng = _factory(serving_params, clock, prefill_only=True)()
        eng.warmup()
        free0 = eng.cache.pages_free
        req = eng.submit(_prompts(1, seed=31)[0], max_new_tokens=4)
        eng.step()
        assert eng.cache.pages_free < free0
        eng.export_request(req.rid)
        assert req.finish_reason == "shipped"
        # shipped is NOT a local completion: it retires for real on
        # the decode replica
        assert req not in eng.sched.finished
        assert eng.cache.pages_free == free0

    def test_corrupted_page_is_never_adopted(self, serving_params):
        clock = SimClock()
        record, pages, kv_len = _shipment(
            serving_params, clock, _prompts(1, seed=32, lo=10, hi=11)[0])
        pages[0] = dict(pages[0], k="BBBB" + pages[0]["k"][4:])
        assert not verify_page_payload(pages[0])
        dec = _factory(serving_params, clock, kv_import=True)()
        dec.warmup()
        free0 = dec.cache.pages_free
        with pytest.raises(ValueError, match="CRC"):
            dec.adopt_prefilled(record, pages, kv_len)
        # atomic refusal: no request admitted, no page allocated
        assert not dec.sched.running and dec.cache.pages_free == free0

    def test_adopt_validation_is_loud(self, serving_params):
        clock = SimClock()
        record, pages, kv_len = _shipment(
            serving_params, clock, _prompts(1, seed=33, lo=12, hi=13)[0])
        assert len(pages) >= 2
        dec = _factory(serving_params, clock, kv_import=True)()
        dec.warmup()
        with pytest.raises(ValueError, match="page"):
            dec.adopt_prefilled(record, pages[:1], kv_len)
        dec.adopt_prefilled(record, pages, kv_len)
        with pytest.raises(ValueError, match="rid"):
            dec.adopt_prefilled(record, pages, kv_len)

    def test_full_batch_refuses_retryably(self, serving_params):
        clock = SimClock()
        dec = _factory(serving_params, clock, kv_import=True,
                       max_batch=1)()
        dec.warmup()
        for seed in (34, 35):
            record, pages, kv_len = _shipment(
                serving_params, clock,
                _prompts(1, seed=seed, lo=10, hi=11)[0])
            record = dict(record, rid=seed)
            if seed == 34:
                dec.adopt_prefilled(record, pages, kv_len)
            else:
                # capacity is retryable (AdmissionRefused), unlike the
                # ValueError validation failures above
                with pytest.raises(AdmissionRefused):
                    dec.adopt_prefilled(record, pages, kv_len)

    def test_quantized_export_carries_scale_planes(self, serving_params):
        clock = SimClock()
        _, pages, _ = _shipment(serving_params, clock,
                                _prompts(1, seed=36, lo=10, hi=11)[0],
                                kv_quant="int8")
        for p in pages:
            assert {"k", "v", "crc_k", "crc_v",
                    "k_scale", "v_scale"} <= set(p)
            assert verify_page_payload(p)
            # a tampered SCALE plane fails the same CRC
            assert not verify_page_payload(
                dict(p, k_scale="BBBB" + p["k_scale"][4:]))


# ---------------------------------------------------------------------------
# The receiver: idempotency + resume
# ---------------------------------------------------------------------------


class TestPageImporter:
    def _rig(self, serving_params, seed=40):
        clock = SimClock()
        record, pages, kv_len = _shipment(
            serving_params, clock,
            _prompts(1, seed=seed, lo=12, hi=13)[0])
        assert len(pages) >= 2
        rep = ReplicaProxy("d0", _factory(serving_params, clock,
                                          kv_import=True), role="decode")
        rep.warmup()
        imp = PageImporter(rep)
        tid = f"t{record['rid']}"

        def page(i, data=None):
            return imp.on_page({"transfer_id": tid, "page_index": i,
                                "n_pages": len(pages),
                                "data": data or pages[i]})

        def commit():
            return imp.on_commit({"transfer_id": tid, "record": record,
                                  "kv_len": kv_len,
                                  "n_pages": len(pages)})

        return rep, imp, pages, page, commit

    def test_missing_pages_resume_not_restart(self, serving_params):
        rep, imp, pages, page, commit = self._rig(serving_params)
        assert page(0) == {"ok": True}
        r = commit()
        assert r["ok"] is False and r["reason"] == "missing_pages"
        assert r["missing"] == list(range(1, len(pages)))
        for i in r["missing"]:          # re-ship exactly the gaps
            assert page(i) == {"ok": True}
        assert commit()["ok"] is True
        assert rep.find_request(int(r.get("rid", 0)) or 0) is not None

    def test_commit_reply_is_memoized(self, serving_params):
        rep, imp, pages, page, commit = self._rig(serving_params, seed=41)
        for i in range(len(pages)):
            page(i)
        r1 = commit()
        assert r1["ok"] is True
        # a retried / duplicated commit returns the memoized success —
        # it cannot double-admit
        assert commit() == r1
        assert len(rep.engine.sched.running) == 1
        # a straggler page after commit is a no-op too
        assert page(0) == {"ok": True}

    def test_duplicate_page_is_a_noop(self, serving_params):
        rep, imp, pages, page, commit = self._rig(serving_params, seed=42)
        assert page(0) == {"ok": True}
        assert page(0) == {"ok": True}
        for i in range(1, len(pages)):
            page(i)
        assert commit()["ok"] is True

    def test_corrupt_page_refused_and_not_buffered(self, serving_params):
        rep, imp, pages, page, commit = self._rig(serving_params, seed=43)
        bad = dict(pages[0], k="BBBB" + pages[0]["k"][4:])
        r = page(0, data=bad)
        assert r == {"ok": False, "reason": "crc_mismatch",
                     "page_index": 0}
        for i in range(1, len(pages)):
            page(i)
        # the refused page never entered the buffer: the commit still
        # reports it missing until a clean copy lands
        assert commit()["missing"] == [0]
        assert page(0) == {"ok": True}
        assert commit()["ok"] is True


# ---------------------------------------------------------------------------
# Chaos matrix — data plane (kv_page / kv_commit)
# ---------------------------------------------------------------------------


#: (message class, fault) -> the retry reasons the shipment layer is
#: allowed to book for it (empty = absorbed with no transfer retry).
DATA_PLANE_CELLS = [
    ("kv_page", "drop", {"timeout"}),
    ("kv_page", "delay", {"timeout"}),
    ("kv_page", "duplicate", set()),
    ("kv_page", "reorder", set()),
    ("kv_page", "corrupt", {"crc_mismatch"}),
    ("kv_commit", "drop", {"timeout"}),
    ("kv_commit", "delay", {"timeout"}),
    ("kv_commit", "duplicate", set()),
    ("kv_commit", "corrupt", {"corrupt"}),
]


class TestDataPlaneChaosMatrix:
    @pytest.mark.parametrize("cls,fault,reasons", DATA_PLANE_CELLS,
                             ids=[f"{c}-{f}"
                                  for c, f, _ in DATA_PLANE_CELLS])
    def test_shipment_survives(self, serving_params, cls, fault, reasons):
        prompts = _prompts(3, seed=25)
        control = _control_streams(serving_params, prompts)
        chaos = ChaosTransport(LocalTransport(),
                               schedule={(cls, fault): {1}})
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id=f"{cls}-{fault}", sinks=[mem])
        fleet, reps = _disagg(serving_params, telemetry=bus,
                              transport=chaos)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=5)
        fleet.run()
        assert chaos.injected == {f"{cls}:{fault}": 1}
        got = {e["reason"] for e in mem.events
               if e["type"] == "kv_ship_retry"}
        assert got == reasons
        assert not [e for e in mem.events
                    if e["type"] == "kv_ship_fallback"]
        ships = [e for e in mem.events if e["type"] == "kv_ship"]
        assert len(ships) == len(prompts)
        # exactly one adoption per request, even under delay/duplicate
        assert len(reps[1].engine.sched.finished) == len(prompts)
        assert len(fleet.handles) == len(prompts)
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"


# ---------------------------------------------------------------------------
# Degradation: retry budget, capacity, destination loss
# ---------------------------------------------------------------------------


class TestShipmentDegradation:
    def test_budget_exhaustion_falls_back_to_local_prefill(
            self, serving_params):
        """Every kv_page lost forever: past the budget the request
        migrates to the decode replica and re-prefills LOCALLY —
        slower, still bitwise, zero drops."""
        prompts = _prompts(3, seed=26)
        control = _control_streams(serving_params, prompts)
        chaos = ChaosTransport(LocalTransport(),
                               rates={("kv_page", "drop"): 1.0})
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="fallback", sinks=[mem])
        fleet, _ = _disagg(serving_params, telemetry=bus,
                           factory_kw={"telemetry": bus},
                           transport=chaos, fault_retries=1)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=5)
        fleet.run()
        falls = [e for e in mem.events if e["type"] == "kv_ship_fallback"]
        assert len(falls) == len(prompts)
        assert all(e["reason"] == "timeout" for e in falls)
        assert not [e for e in mem.events if e["type"] == "kv_ship"]
        assert len(fleet.handles) == len(prompts)
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"
        s = summarize_events(mem.events)
        assert s["serving_ship_fallback_rate"] == 1.0
        assert s["serving_ship_success_rate"] == 0.0

    def test_no_capacity_backs_off_until_a_slot_frees(self,
                                                      serving_params):
        """A full decode batch is a capacity refusal, not a failure:
        the sender backs off into the SAME buffered pages and lands
        once a slot frees."""
        prompts = _prompts(3, seed=27)
        control = _control_streams(serving_params, prompts, max_new=3)
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="capacity", sinks=[mem])
        fleet, _ = _disagg(serving_params, telemetry=bus,
                           decode_kw={"max_batch": 1}, fault_retries=5)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=3)
        fleet.run()
        retries = [e for e in mem.events if e["type"] == "kv_ship_retry"]
        assert retries and {e["reason"] for e in retries} == \
            {"no_capacity"}
        assert not [e for e in mem.events
                    if e["type"] == "kv_ship_fallback"]
        ships = [e for e in mem.events if e["type"] == "kv_ship"]
        assert len(ships) == len(prompts)
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"

    def test_destination_fence_retargets_the_transfer(self,
                                                      serving_params):
        """The decode destination dying mid-transfer retargets the
        shipment to a live decode replica from scratch."""
        prompt = _prompts(1, seed=28)[0]
        control = _control_streams(serving_params, [prompt], max_new=4)
        chaos = ChaosTransport(LocalTransport(),
                               rates={("kv_page", "drop"): 1.0})
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="retarget", sinks=[mem])
        fleet, reps = _disagg(serving_params, n_decode=2, telemetry=bus,
                              transport=chaos, fault_retries=20)
        fleet.warmup()
        rid = fleet.submit(prompt, max_new_tokens=4)
        for _ in range(3):
            fleet.step()
        assert fleet._transfers[rid].dst == "d0"
        reps[1].fence()                 # d0 dies mid-transfer
        chaos.rates.clear()             # the wire heals
        fleet.run()
        ships = [e for e in mem.events if e["type"] == "kv_ship"]
        assert [e["to_replica"] for e in ships] == ["d1"]
        assert fleet.placement[rid] == "d1"
        assert fleet.handles[rid].generated == control[rid]


# ---------------------------------------------------------------------------
# Everything at once
# ---------------------------------------------------------------------------


def _data_plane_rates(p):
    return {(cls, fault): p
            for cls in ("migrate", "kv_page", "kv_commit")
            for fault in FAULTS}


class TestChaosEverything:
    def test_all_faults_armed_streams_stay_bitwise(self, serving_params):
        """The tentpole pin: every fault class armed on every data-
        plane message class at once, plus scheduled control-plane
        faults (a prefill replica fences mid-run) — streams bitwise,
        zero drops, every r18 event schema-valid."""
        prompts = _prompts(8, seed=18, lo=6, hi=14)
        control = _control_streams(serving_params, prompts, max_new=6)
        chaos = ChaosTransport(
            LocalTransport(), seed=7,
            rates=_data_plane_rates(0.15),
            schedule={("ping", "drop"): {9},
                      ("ping", "duplicate"): {3},
                      ("ping", "reorder"): {5}})
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="chaos-disagg", sinks=[mem])
        chaos.telemetry = bus
        fleet, reps = _disagg(serving_params, n_prefill=2, n_decode=2,
                              telemetry=bus, transport=chaos,
                              fault_retries=3)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=6)
        fleet.run()
        assert chaos.injected            # chaos actually happened
        # the 9th ping (round 3, first probe) fenced prefill replica p0
        fences = [e for e in mem.events if e["type"] == "replica_fence"]
        assert [f["replica"] for f in fences] == ["p0"]
        for e in mem.events:
            if e["type"] in ("kv_ship", "kv_ship_retry",
                             "kv_ship_fallback", "fault_injected",
                             "request_migrate", "replica_fence"):
                tel.validate_event(e)
        assert len(fleet.handles) == len(prompts)
        assert all(r.done for r in fleet.handles.values())
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_full_grid_sweep(self, serving_params, seed):
        """The heavy grid: higher data-plane rates across more
        traffic, one scheduled prefill fence, per-seed."""
        prompts = _prompts(10, seed=100 + seed, lo=6, hi=14)
        control = _control_streams(serving_params, prompts, max_new=6)
        # Control-plane faults stay rarer than the data plane: migrate
        # has no fallback tier, so its retry budget must statistically
        # always survive (at 0.15/fault, five consecutive faulted
        # attempts are likely somewhere in a 3-seed grid).
        rates = _data_plane_rates(0.25)
        rates.update({("migrate", f): 0.05 for f in FAULTS})
        chaos = ChaosTransport(LocalTransport(), seed=seed, rates=rates,
                               schedule={("ping", "drop"): {9}})
        fleet, _ = _disagg(serving_params, n_prefill=2, n_decode=2,
                           transport=chaos, fault_retries=4)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=6)
        fleet.run()
        assert len(fleet.handles) == len(prompts)
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, \
                f"seed {seed} rid {rid}"


# ---------------------------------------------------------------------------
# Prefix-affinity placement (r18 satellite)
# ---------------------------------------------------------------------------


class TestPrefixAffinity:
    def test_warm_replica_wins_over_load(self, serving_params):
        fleet, reps = _fleet(
            serving_params, n=2,
            factory_kw={"prefix_sharing": True,
                        "spec": SpecConfig(k=0, chunk_size=8)})
        fleet.warmup()
        stem = [(i % 60) + 1 for i in range(16)]
        rid_a = fleet.submit(list(stem), max_new_tokens=4)
        assert fleet.placement[rid_a] == "r0"
        fleet.run()
        # r0's LOCAL index now holds the 16-token prefix (and retains
        # its pages, so r0 carries a sliver of occupancy); nothing was
        # shipped to r1.  Two cold submissions land one request on
        # each replica, leaving r0 STRICTLY more loaded than r1:
        rid_b1 = fleet.submit(_prompts(1, seed=45)[0], max_new_tokens=8)
        assert fleet.placement[rid_b1] == "r1"   # cold: least-loaded
        rid_b2 = fleet.submit(_prompts(1, seed=46)[0], max_new_tokens=8)
        assert fleet.placement[rid_b2] == "r0"
        assert reps[0].load_score() > reps[1].load_score()
        rid_c = fleet.submit(stem + [7, 8, 9], max_new_tokens=4)
        assert fleet.placement[rid_c] == "r0"    # affinity beats load
        rid_d = fleet.submit(_prompts(1, seed=47)[0], max_new_tokens=4)
        assert fleet.placement[rid_d] == "r1"    # cold: least-loaded
        fleet.run()
        assert fleet.handles[rid_c].prefix_hit

    def test_affinity_off_without_sharing(self, serving_params):
        """No index, no affinity: routing is pure least-loaded, as
        before r18."""
        fleet, _ = _fleet(serving_params, n=2)
        fleet.warmup()
        stem = [(i % 60) + 1 for i in range(16)]
        fleet.submit(list(stem), max_new_tokens=3)
        fleet.run()
        fleet.submit(_prompts(1, seed=47)[0], max_new_tokens=8)
        rid = fleet.submit(list(stem) + [5], max_new_tokens=3)
        assert fleet.placement[rid] == "r1"      # least-loaded only


# ---------------------------------------------------------------------------
# Capacity refusal reporting (r18 satellite)
# ---------------------------------------------------------------------------


class TestCapacityRefusal:
    def test_refusal_reports_the_full_shortfall(self, serving_params):
        """A refused plan names EVERY unplaceable request and the
        required-vs-available page arithmetic — on the exception and
        on the ``migrate_refused`` event.  The shortfall here is queue
        headroom: the survivor's bounded queue (max_queue=1) can adopt
        exactly one of the dead replica's five live requests."""
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="refused", sinks=[mem])
        clock = SimClock()
        reps = [ReplicaProxy("r0", _factory(serving_params, clock)),
                ReplicaProxy("r1", _factory(serving_params, clock,
                                            max_batch=1, max_queue=1))]
        fleet = FleetRouter(reps, telemetry=bus, fault_retries=0)
        fleet.warmup()
        # Headroom-preferring routing fills r1's one queue slot with
        # the second submit, then piles the rest onto r0: 5 vs 1.
        for p in _prompts(6, seed=50, lo=8, hi=10):
            fleet.submit(p, max_new_tokens=5)
        assert sum(1 for n in fleet.placement.values() if n == "r0") == 5
        with KillReplica("r0", at_step=2):
            with pytest.raises(FleetCapacityError) as ei:
                fleet.run()
        err = ei.value
        assert len(err.unplaceable) == 4         # ALL of them, not one
        assert set(err.unplaceable) <= {rid for rid, n in
                                        fleet.placement.items()
                                        if n == "r0"}
        assert err.pages_required > 0 and err.pages_available >= 0
        evs = [e for e in mem.events if e["type"] == "migrate_refused"]
        assert len(evs) == 1
        ev = evs[0]
        tel.validate_event(ev)
        assert ev["replica"] == "r0"
        assert ev["unplaceable"] == list(err.unplaceable)
        assert ev["requests"] == len(err.unplaceable)
        assert ev["pages_required"] == err.pages_required
        assert ev["pages_available"] == err.pages_available


# ---------------------------------------------------------------------------
# Telemetry: schema, summary, regression directions
# ---------------------------------------------------------------------------


class TestShipTelemetry:
    def _stamp(self, type_, **payload):
        ev = {"type": type_, "run_id": "r", "step": 0, "t": 0.0,
              "ts": 0.0, "mesh": {}}
        ev.update(payload)
        return ev

    def test_new_events_validate(self):
        tel.validate_event(self._stamp(
            "kv_ship", rid=3, from_replica="p0", to_replica="d1",
            pages=4, payload_bytes=8192, attempts=1))
        tel.validate_event(self._stamp(
            "kv_ship_retry", rid=3, from_replica="p0", to_replica="d1",
            attempt=1, reason="timeout", backoff_rounds=2))
        tel.validate_event(self._stamp(
            "kv_ship_retry", rid=3, from_replica="p0", to_replica="d1",
            attempt=0, reason="crc_mismatch"))   # immediate re-send
        tel.validate_event(self._stamp(
            "kv_ship_fallback", rid=3, from_replica="p0",
            to_replica="d1", attempts=3, reason="no_capacity"))
        tel.validate_event(self._stamp(
            "migrate_refused", replica="r0", unplaceable=[4, 5],
            requests=2, pages_required=8, pages_available=3))

    def test_retry_reason_enum_is_closed(self):
        with pytest.raises(tel.schema.SchemaError, match="must be one of"):
            tel.validate_event(self._stamp(
                "kv_ship_retry", rid=3, from_replica="p0",
                to_replica="d1", attempt=1, reason="cosmic_rays"))

    def test_summary_reports_ship_rates(self):
        events = ([{"type": "kv_ship"}] * 3
                  + [{"type": "kv_ship_fallback"}]
                  + [{"type": "request_retire"}] * 4)
        s = summarize_events(events)
        assert s["serving_ship_success_rate"] == 0.75
        assert s["serving_ship_fallback_rate"] == 0.25
        quiet = summarize_events([{"type": "request_retire"}])
        assert quiet["serving_ship_success_rate"] is None
        assert quiet["serving_ship_fallback_rate"] is None

    def test_ship_fallback_rate_direction_rule(self):
        # the r18 gate family: fallbacks are degradation — DOWN is
        # better (note _hit_rate$ is HIGHER; a fallback is a miss)
        assert key_direction("fleet_ship_fallback_rate") == "lower"
        assert key_direction("serving_ship_fallback_rate") == "lower"
        # the companion retry rate is deliberately UNGATED: the right
        # retry count depends on the injected fault rate
        assert key_direction("fleet_ship_retry_rate") is None
        assert key_direction("fleet_kv_ships") is None
