"""Fused optimizer parity tests vs torch.optim references.

Mirrors reference tests/L0/run_optimizers/test_fused_optimizer.py (Adam/SGD/
Adagrad vs torch.optim on random params), test_lamb.py (hand-written
reference LAMB), test_fused_novograd.py (hand-written reference NovoGrad).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import optimizers as opts
from apex_tpu.multi_tensor import flatten, unflatten


def make_problem(rng, shapes=((8, 16), (33,), (4, 7, 3))):
    params = {f"p{i}": rng.standard_normal(s).astype(np.float32) for i, s in enumerate(shapes)}
    grad_seq = [
        {k: rng.standard_normal(v.shape).astype(np.float32) for k, v in params.items()}
        for _ in range(5)
    ]
    return params, grad_seq


def run_jax(opt, params, grad_seq):
    p = {k: jnp.asarray(v) for k, v in params.items()}
    st = opt.init(p)
    step = jax.jit(opt.step)
    for g in grad_seq:
        p, st = step({k: jnp.asarray(v) for k, v in g.items()}, st, p)
    return {k: np.asarray(v) for k, v in p.items()}


def run_torch(make_opt, params, grad_seq):
    tp = {k: torch.nn.Parameter(torch.tensor(v)) for k, v in params.items()}
    o = make_opt(list(tp.values()))
    for g in grad_seq:
        for k, param in tp.items():
            param.grad = torch.tensor(g[k])
        o.step()
    return {k: v.detach().numpy() for k, v in tp.items()}


class TestFusedAdam:
    @pytest.mark.parametrize("wd,adam_w", [(0.0, True), (0.1, True), (0.1, False)])
    def test_vs_torch(self, wd, adam_w):
        rng = np.random.default_rng(0)
        params, grads = make_problem(rng)
        j = run_jax(
            opts.FusedAdam(lr=1e-2, weight_decay=wd, adam_w_mode=adam_w), params, grads
        )
        mk = (
            (lambda ps: torch.optim.AdamW(ps, lr=1e-2, weight_decay=wd))
            if adam_w
            else (lambda ps: torch.optim.Adam(ps, lr=1e-2, weight_decay=wd))
        )
        t = run_torch(mk, params, grads)
        for k in params:
            # fp32 on-device math vs torch's float64 scalar hyperparams:
            # agreement to ~1e-4 relative (same slack class as the
            # reference's kernel-vs-torch tests)
            np.testing.assert_allclose(j[k], t[k], rtol=5e-4, atol=1e-5)

    def test_amsgrad_rejected(self):
        with pytest.raises(RuntimeError):
            opts.FusedAdam(amsgrad=True)


class TestFusedSGD:
    @pytest.mark.parametrize(
        "momentum,nesterov,wd", [(0.0, False, 0.0), (0.9, False, 0.0), (0.9, True, 0.0), (0.9, False, 0.05)]
    )
    def test_vs_torch(self, momentum, nesterov, wd):
        rng = np.random.default_rng(1)
        params, grads = make_problem(rng)
        j = run_jax(
            opts.FusedSGD(lr=0.05, momentum=momentum, nesterov=nesterov, weight_decay=wd),
            params,
            grads,
        )
        t = run_torch(
            lambda ps: torch.optim.SGD(ps, lr=0.05, momentum=momentum, nesterov=nesterov, weight_decay=wd),
            params,
            grads,
        )
        for k in params:
            np.testing.assert_allclose(j[k], t[k], rtol=2e-5, atol=2e-6)


class TestFusedAdagrad:
    @pytest.mark.parametrize("wd", [0.0, 0.1])
    def test_vs_torch(self, wd):
        rng = np.random.default_rng(2)
        params, grads = make_problem(rng)
        j = run_jax(opts.FusedAdagrad(lr=0.02, weight_decay=wd), params, grads)
        t = run_torch(
            lambda ps: torch.optim.Adagrad(ps, lr=0.02, weight_decay=wd, eps=1e-10),
            params,
            grads,
        )
        for k in params:
            np.testing.assert_allclose(j[k], t[k], rtol=2e-5, atol=2e-6)


def reference_lamb_step(params, grads, m, v, step, *, lr, b1, b2, eps, wd, max_grad_norm, use_nvlamb):
    """Hand-written LAMB (reference tests/L0/run_optimizers/test_lamb.py
    RefLAMB semantics, with FusedLAMB's global grad clip)."""
    gnorm = np.sqrt(sum(np.sum(g**2) for g in grads.values()))
    clip = max(1.0, gnorm / max_grad_norm)
    out = {}
    for k in params:
        g = grads[k] / clip
        m[k] = b1 * m[k] + (1 - b1) * g
        v[k] = b2 * v[k] + (1 - b2) * g * g
        c1 = 1 - b1**step
        c2 = 1 - b2**step
        upd = (m[k] / c1) / (np.sqrt(v[k] / c2) + eps) + wd * params[k]
        wn = np.linalg.norm(params[k])
        un = np.linalg.norm(upd)
        if (wd != 0 or use_nvlamb) and wn > 0 and un > 0:
            ratio = wn / un
        else:
            ratio = 1.0
        out[k] = params[k] - lr * ratio * upd
    return out


class TestFusedLAMB:
    @pytest.mark.parametrize("wd,use_nvlamb", [(0.01, False), (0.0, False), (0.0, True)])
    def test_vs_reference(self, wd, use_nvlamb):
        rng = np.random.default_rng(3)
        params, grads_seq = make_problem(rng)
        opt = opts.FusedLAMB(lr=1e-2, weight_decay=wd, use_nvlamb=use_nvlamb, max_grad_norm=1.0)
        j = run_jax(opt, params, grads_seq)
        ref = {k: v.copy() for k, v in params.items()}
        m = {k: np.zeros_like(v) for k, v in params.items()}
        v_ = {k: np.zeros_like(v) for k, v in params.items()}
        for i, g in enumerate(grads_seq):
            ref = reference_lamb_step(
                ref, g, m, v_, i + 1, lr=1e-2, b1=0.9, b2=0.999, eps=1e-6,
                wd=wd, max_grad_norm=1.0, use_nvlamb=use_nvlamb,
            )
        for k in params:
            np.testing.assert_allclose(j[k], ref[k], rtol=1e-4, atol=1e-5)


class TestFusedNovoGrad:
    def test_vs_reference(self):
        rng = np.random.default_rng(4)
        params, grads_seq = make_problem(rng)
        lr, b1, b2, eps, wd = 1e-2, 0.95, 0.98, 1e-8, 0.01
        opt = opts.FusedNovoGrad(lr=lr, betas=(b1, b2), eps=eps, weight_decay=wd)
        j = run_jax(opt, params, grads_seq)
        ref = {k: v.copy() for k, v in params.items()}
        m = {k: np.zeros_like(v) for k, v in params.items()}
        vs = {k: 0.0 for k in params}
        for i, g in enumerate(grads_seq):
            step = i + 1
            c1, c2 = 1 - b1**step, 1 - b2**step
            for k in ref:
                gn2 = np.sum(g[k] ** 2)
                vs[k] = gn2 if i == 0 else b2 * vs[k] + (1 - b2) * gn2
                gnorm = g[k] / (np.sqrt(vs[k] / c2) + eps)
                m[k] = b1 * m[k] + (1 - b1) * gnorm
                ref[k] = ref[k] - lr * (m[k] / c1 + wd * ref[k])
        for k in params:
            np.testing.assert_allclose(j[k], ref[k], rtol=1e-4, atol=1e-5)


class TestLARC:
    def test_matches_manual_transform(self):
        rng = np.random.default_rng(5)
        params, grads_seq = make_problem(rng)
        inner = opts.FusedSGD(lr=0.1)
        larc = opts.LARC(inner, trust_coefficient=0.02, clip=True, weight_decay=0.01)
        p = {k: jnp.asarray(v) for k, v in params.items()}
        st = larc.init(p)
        g0 = {k: jnp.asarray(v) for k, v in grads_seq[0].items()}
        p1, _ = larc.step(g0, st, p)
        # manual: transform grads then inner sgd
        tg = larc.transform_grads(g0, p)
        for k in params:
            expect = np.asarray(p[k]) - 0.1 * np.asarray(tg[k])
            np.testing.assert_allclose(p1[k], expect, rtol=1e-5)

    def test_trust_ratio_scales_small_grads(self):
        p = {"w": jnp.full((4,), 10.0)}
        g = {"w": jnp.full((4,), 1e-4)}
        larc = opts.LARC(opts.FusedSGD(lr=1.0), trust_coefficient=0.02, clip=False)
        tg = larc.transform_grads(g, p)
        # adaptive lr = 0.02*|p|/|g| = 0.02*20/2e-4 = 2000 → grads scaled up
        np.testing.assert_allclose(np.asarray(tg["w"]), 0.2, rtol=1e-3)


class TestFlatFusedAdam:
    def test_matches_pytree_path(self):
        rng = np.random.default_rng(6)
        params, grads_seq = make_problem(rng)
        # pytree path
        ref = run_jax(opts.FusedAdam(lr=1e-2, weight_decay=0.05), params, grads_seq)
        # flat path
        p = {k: jnp.asarray(v) for k, v in params.items()}
        flat_p, schema = flatten(p, total_multiple_of=1024)
        opt = opts.FlatFusedAdam(lr=1e-2, weight_decay=0.05)
        st = opt.init(flat_p)
        step = jax.jit(opt.step)
        for g in grads_seq:
            flat_g, _ = flatten({k: jnp.asarray(v) for k, v in g.items()}, schema)
            flat_p, st = step(flat_g, st, flat_p)
        back = unflatten(flat_p, schema)
        for k in params:
            np.testing.assert_allclose(np.asarray(back[k]), ref[k], rtol=2e-5, atol=2e-6)

    def test_step_if_finite_integration(self):
        # amp skip-step protocol on the pytree optimizer
        opt = opts.FusedAdam(lr=0.1)
        p = {"w": jnp.ones((4,))}
        st = opt.init(p)
        g = {"w": jnp.ones((4,))}
        p2, st2 = opt.step_if_finite(g, st, p, jnp.asarray(False))
        np.testing.assert_array_equal(p2["w"], p["w"])
        assert int(st2.step) == 0
        p3, st3 = opt.step_if_finite(g, st, p, jnp.asarray(True))
        assert not np.allclose(p3["w"], p["w"])
        assert int(st3.step) == 1
