"""Fault-tolerant input pipeline tests (ISSUE 7).

Covers the four pillars of apex_tpu.data:

- **determinism / addressing** — seeded window-shuffle epochs cover
  every record exactly once; two iterators replay bitwise;
- **exactly-once resume** — the ``data_state`` record restores the
  consumed sample-id stream with no duplicates and no drops, through
  the SIGTERM grace path, the hard ``DeviceLossError`` elastic path,
  and a dp=4→dp=2 elastic reshard (slot ownership re-slices, the
  stream is invariant);
- **degradation** — corrupt records quarantine (skip + count +
  ``data_quarantine`` event) with a hard-fail ceiling; dead shard
  handles recover via re-assignment; slow reads surface as
  ``data_stall``;
- **prefetching** — bounded-queue backpressure, wait accounting,
  consumed-cursor state snapshots, and LOUD loader-thread death
  (postmortem included).

The flagship-fed golden case replays the committed
``gpt1p3b_toy_data`` fp32-hex baseline (tests/L1 REGEN protocol).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import checkpoint as ckpt
from apex_tpu import resilience as res
from apex_tpu import telemetry as tele
from apex_tpu.data import (
    AsyncPrefetcher,
    DataLoaderError,
    DataShardError,
    QuarantineOverflowError,
    QuarantinePolicy,
    ShardedRecordIterator,
    merge_data_states,
    write_checksummed_records,
)
from apex_tpu.data import records as data_records
from apex_tpu.resilience import chaos
from apex_tpu.transformer.testing import run_resilient_training

N_REC, PAYLOAD, BATCH = 64, 12, 8


@pytest.fixture(autouse=True)
def _clear_read_hook():
    """Chaos injectors install a module-global read hook; no test may
    leak one into the next (mirrors the chaos_ckpt_dir discipline)."""
    yield
    data_records.set_read_hook(None)


@pytest.fixture
def shards(tmp_path):
    """Two checksummed shards of 32 records each; payload row i carries
    the global record id in its first 8 bytes (self-identifying)."""
    paths, rb = [], None
    for f in range(2):
        payloads = np.zeros((N_REC // 2, PAYLOAD), np.uint8)
        for i in range(N_REC // 2):
            payloads[i, :8] = np.frombuffer(
                np.int64(f * (N_REC // 2) + i).tobytes(), np.uint8)
        p = str(tmp_path / f"shard{f}.bin")
        rb = write_checksummed_records(p, payloads)
        paths.append(p)
    return paths, rb


def _make(shards, **kw):
    paths, rb = shards
    kw.setdefault("shuffle_window", 16)
    kw.setdefault("seed", 3)
    return ShardedRecordIterator(paths, rb, BATCH, checksummed=True, **kw)


def _drain_ids(it):
    out = []
    for _ in it:
        out.extend(it.last_ids)
    return out


# ------------------------------------------------- determinism/addressing


class TestDeterministicAddressing:
    def test_epoch_covers_every_record_once(self, shards):
        ids = _drain_ids(_make(shards, num_batches=N_REC // BATCH))
        assert sorted(ids) == list(range(N_REC))

    def test_second_epoch_reshuffles_and_covers(self, shards):
        two = _drain_ids(_make(shards, num_batches=2 * N_REC // BATCH))
        e1, e2 = two[:N_REC], two[N_REC:]
        assert sorted(e1) == sorted(e2) == list(range(N_REC))
        assert e1 != e2  # epoch is folded into the window RNG key

    def test_replay_is_bitwise_and_seed_sensitive(self, shards):
        a = _drain_ids(_make(shards, num_batches=4))
        b = _drain_ids(_make(shards, num_batches=4))
        c = _drain_ids(_make(shards, num_batches=4, seed=4))
        assert a == b and a != c

    def test_payload_is_the_record(self, shards):
        it = _make(shards, num_batches=2)
        for batch in it:
            got = [int(np.asarray(row[:8]).view(np.int64)[0])
                   for row in batch]
            assert got == it.last_ids

    def test_record_at_is_pure(self, shards):
        it = _make(shards, num_batches=1)
        pos = [it.record_at(p) for p in range(2 * N_REC)]
        it2 = _make(shards, num_batches=1)
        assert pos == [it2.record_at(p) for p in range(2 * N_REC)]


# -------------------------------------------------- exactly-once position


class TestExactlyOncePosition:
    def test_state_roundtrip_resumes_identically(self, shards):
        control = _drain_ids(_make(shards, num_batches=8))
        it = _make(shards, num_batches=8)
        pre = []
        for _ in range(3):
            next(it)
            pre.extend(it.last_ids)
        st = it.state_dict()
        it2 = _make(shards, num_batches=8)
        it2.load_state_dict(st)
        assert pre + _drain_ids(it2) == control

    def test_dp4_to_dp2_repartition_preserves_stream(self, shards):
        control = _drain_ids(_make(shards, num_batches=8))
        per_batch = [sorted(control[i * BATCH:(i + 1) * BATCH])
                     for i in range(8)]
        views = [_make(shards, dp_rank=r, dp_size=4, num_batches=3)
                 for r in range(4)]
        got = [[] for _ in range(3)]
        for v in views:
            for i in range(3):
                next(v)
                got[i].extend(v.last_ids)
        merged = merge_data_states([v.state_dict() for v in views])
        views2 = [_make(shards, dp_rank=r, dp_size=2, num_batches=8)
                  for r in range(2)]
        got2 = [[] for _ in range(5)]
        for v in views2:
            v.load_state_dict(merged)
        for v in views2:
            for i in range(5):
                next(v)
                got2[i].extend(v.last_ids)
        assert [sorted(b) for b in got] + [sorted(b) for b in got2] \
            == per_batch

    def test_state_mismatch_raises(self, shards, tmp_path):
        it = _make(shards, num_batches=4)
        next(it)
        st = it.state_dict()
        other = _make(shards, num_batches=4, seed=99)
        with pytest.raises(ValueError, match="fingerprint"):
            other.load_state_dict(st)
        bad = dict(st, batch_size=4)
        with pytest.raises(ValueError, match="batch_size"):
            _make(shards, num_batches=4).load_state_dict(bad)
        rank_state = _make(shards, dp_rank=0, dp_size=2,
                           num_batches=4).state_dict()
        with pytest.raises(ValueError, match="merge"):
            _make(shards, num_batches=4).load_state_dict(rank_state)

    def test_data_state_rides_manifest_async_save(self, shards,
                                                  chaos_ckpt_dir):
        it = _make(shards, num_batches=4)
        next(it)
        ckpt.save_checkpoint(str(chaos_ckpt_dir), {"w": jnp.zeros(4)},
                             step=1, data_state=it.state_dict(),
                             blocking=False)
        res.wait_for_save()
        ds = ckpt.load_data_state(str(chaos_ckpt_dir))
        assert ds == it.state_dict()
        # a save without data_state reads back as None
        ckpt.save_checkpoint(str(chaos_ckpt_dir), {"w": jnp.zeros(4)},
                             step=2)
        assert ckpt.load_data_state(str(chaos_ckpt_dir), step=2) is None

    def test_unserializable_data_state_rejected(self, chaos_ckpt_dir):
        with pytest.raises(ValueError, match="JSON"):
            ckpt.save_checkpoint(str(chaos_ckpt_dir), {"w": jnp.zeros(4)},
                                 step=1, data_state={"x": object()})


# --------------------------------------------------- degradation layer


class TestDegradation:
    def test_quarantine_skips_counts_and_emits(self, shards):
        paths, rb = shards
        chaos.corrupt_record(paths[0], 5, rb)
        mem = tele.MemorySink()
        bus = tele.TelemetryBus("q", sinks=[mem])
        it = _make(shards, num_batches=8, telemetry=bus,
                   quarantine=QuarantinePolicy(max_rate=0.5,
                                               min_count=64))
        ids = _drain_ids(it)
        assert it.quarantined == 1
        assert 5 not in ids and len(ids) == N_REC  # skipped, not dropped
        ev = [e for e in mem.events if e["type"] == "data_quarantine"]
        assert len(ev) == 1 and ev[0]["record_id"] == 5 \
            and ev[0]["reason"] == "crc_mismatch"
        for e in mem.events:
            tele.validate_event(e)

    def test_quarantine_is_deterministic_across_resume(self, shards):
        paths, rb = shards
        chaos.corrupt_record(paths[0], 5, rb)
        quar = QuarantinePolicy(max_rate=0.5, min_count=64)
        control = _drain_ids(_make(shards, num_batches=8, quarantine=quar))
        it = _make(shards, num_batches=8, quarantine=quar)
        pre = []
        for _ in range(3):
            next(it)
            pre.extend(it.last_ids)
        it2 = _make(shards, num_batches=8, quarantine=quar)
        it2.load_state_dict(it.state_dict())
        assert pre + _drain_ids(it2) == control

    def test_quarantine_overflow_hard_fails(self, shards):
        paths, rb = shards
        for i in (1, 5, 9):
            chaos.corrupt_record(paths[0], i, rb)
        it = _make(shards, num_batches=8,
                   quarantine=QuarantinePolicy(max_rate=0.02, min_count=2))
        with pytest.raises(QuarantineOverflowError, match="max_rate"):
            _drain_ids(it)

    def test_validate_record_hook_quarantines(self, shards):
        it = _make(shards, num_batches=8,
                   validate_record=lambda p: p[:8] != np.int64(7).tobytes(),
                   quarantine=QuarantinePolicy(max_rate=0.5, min_count=64))
        ids = _drain_ids(it)
        assert 7 not in ids and it.quarantined == 1

    @pytest.mark.chaos_data
    def test_drop_shard_recovers_via_reassignment(self, shards):
        paths, rb = shards
        mem = tele.MemorySink()
        bus = tele.TelemetryBus("drop", sinks=[mem])
        with chaos.DropShard(paths[1], telemetry=bus) as ds:
            it = _make(shards, num_batches=8, telemetry=bus)
            ids = _drain_ids(it)
        assert sorted(ids) == list(range(N_REC))  # nothing lost
        assert ds.reassigned and it.files.reassigns == 1
        assert it.files.retries >= 1
        assert any(e["type"] == "data_stall"
                   and e["cause"] == "shard_reassign" for e in mem.events)
        for e in mem.events:
            tele.validate_event(e)

    @pytest.mark.chaos_data
    def test_dead_shard_raises_instead_of_hanging(self, shards):
        paths, rb = shards
        with chaos.DropShard(paths[1], fail_after_reassign=True):
            it = _make(shards, num_batches=8)
            with pytest.raises(DataShardError, match="re-assigned"):
                _drain_ids(it)

    @pytest.mark.chaos_data
    def test_slow_read_surfaces_data_stall(self, shards):
        paths, rb = shards
        mem = tele.MemorySink()
        bus = tele.TelemetryBus("slow", sinks=[mem])
        with chaos.SlowShardRead(paths[0], delay=0.05, times=2):
            it = _make(shards, num_batches=2, slow_read_threshold=0.01,
                       telemetry=bus)
            for _ in it:
                pass
        ev = [e for e in mem.events if e["type"] == "data_stall"]
        assert ev and all(e["cause"] == "slow_read" for e in ev)
        assert it.files.slow_reads >= 1

    @pytest.mark.chaos_data
    def test_read_timeout_breaks_straggler_wait(self, shards):
        paths, rb = shards
        with chaos.SlowShardRead(paths[0], delay=0.6, times=1):
            it = _make(shards, num_batches=1, read_timeout=0.1)
            next(it)  # must return well before the 0.6s stall ends
        assert it.files.retries >= 1


# ------------------------------------------------------- prefetcher


class TestAsyncPrefetcher:
    def test_backpressure_bounds_production(self, shards):
        produced = []
        src = _make(shards, num_batches=8,
                    on_ids=lambda i, ids: produced.append(i))
        pf = AsyncPrefetcher(src, depth=2)
        import time

        deadline = time.monotonic() + 2.0
        while len(produced) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.1)  # would balloon here without backpressure
        assert len(produced) <= 4  # depth + in-flight, never the full 8
        pf.close()

    def test_consumed_state_excludes_in_flight(self, shards):
        control = _drain_ids(_make(shards, num_batches=8))
        src = _make(shards, num_batches=8)
        pf = AsyncPrefetcher(src, depth=2)
        for _ in range(3):
            next(pf)
        st = pf.state_dict()  # worker may be 2 batches ahead
        pf.close()
        assert st["batches_consumed"] == 3
        it2 = _make(shards, num_batches=8)
        it2.load_state_dict(st)
        assert _drain_ids(it2) == control[3 * BATCH:]

    def test_wait_accounting_and_stall_event(self, shards):
        mem = tele.MemorySink()
        bus = tele.TelemetryBus("pf", sinks=[mem])

        class Slow:
            def __init__(self):
                self.n = 0

            def __iter__(self):
                return self

            def __next__(self):
                import time

                self.n += 1
                if self.n > 3:
                    raise StopIteration
                time.sleep(0.05)
                return self.n

        pf = AsyncPrefetcher(Slow(), depth=2, stall_threshold_s=0.01,
                             telemetry=bus)
        assert list(pf) == [1, 2, 3]
        assert pf.take_wait() > 0 and pf.take_wait() == 0.0
        assert pf.stalls >= 1
        ev = [e for e in mem.events if e["type"] == "data_stall"]
        assert ev and all(e["cause"] == "queue_dry" for e in ev)
        pf.close()

    def test_loader_death_is_loud(self, shards):
        class Dying:
            def __iter__(self):
                return self

            def __next__(self):
                raise RuntimeError("decode exploded")

        pf = AsyncPrefetcher(Dying())
        with pytest.raises(DataLoaderError, match="decode exploded"):
            next(pf)
        pf.close()

    def test_non_checkpointable_source_refuses_state(self):
        pf = AsyncPrefetcher(iter([1, 2]), start=False)
        with pytest.raises(TypeError, match="not checkpointable"):
            pf.state_dict()
        pf.close()

    def test_wraps_native_loader_as_fast_path(self, tmp_path):
        """The dataloader.cpp decision (docs/data.md): the native loader
        binds behind the prefetcher as the non-checkpointable fast
        path."""
        from apex_tpu.data import NativeRecordLoader, native_available, \
            write_records

        if not native_available():
            pytest.skip("native toolchain unavailable")
        recs = np.arange(32 * 8, dtype=np.uint8).reshape(32, 8)
        p = str(tmp_path / "raw.bin")
        write_records(p, recs)
        with NativeRecordLoader([p], 8, 4, shuffle=False) as ld:
            pf = AsyncPrefetcher(ld, depth=2)
            batch = next(pf)
            assert batch.shape == (4, 8)
            with pytest.raises(TypeError, match="not checkpointable"):
                pf.state_dict()
            pf._halt()


# --------------------------------------- train-loop / elastic integration


def _tiny_step_fn():
    """Deterministic fp32 step whose trajectory encodes the batch
    content: exact-integer sums keep the comparison bitwise."""

    @jax.jit
    def bump(w, b):
        return w + jnp.sum(b.astype(jnp.float32)) / 1024.0

    def step_fn(state, batch):
        return {"w": bump(state["w"], jnp.asarray(batch))}, None

    return step_fn


def _data_elastic_build():
    """Synthetic elastic workload fed by real batches: replicated param
    folded from the batch bytes + per-rank opt partitions (total flat
    size 256 survives any 4->2->1 reshard)."""

    @jax.jit
    def bump(w, b):
        return w + jnp.sum(b.astype(jnp.float32)) / 1024.0

    def build(devices):
        n = len(devices)
        params = {"w": jnp.zeros((16,), jnp.float32)}
        opt = {"m": jnp.zeros((n, 256 // n), jnp.float32)}

        def step_fn(state, batch):
            p, o = state
            return ({"w": bump(p["w"], jnp.asarray(batch))}, o), None

        return step_fn, (params, opt), (P(), P("data"))

    return build


class TestLoopIntegration:
    def test_plain_generator_rejected_with_checkpointing(self, shards,
                                                         tmp_path):
        def gen():
            while True:
                yield np.zeros((BATCH, PAYLOAD), np.uint8)

        with pytest.raises(TypeError, match="not checkpointable"):
            run_resilient_training(_tiny_step_fn(), {"w": jnp.zeros(4)},
                                   data_iter=gen(),
                                   ckpt_dir=str(tmp_path / "ck"),
                                   save_every=1)
        # without checkpointing a plain iterator is fine (old behavior)
        r = run_resilient_training(
            _tiny_step_fn(), {"w": jnp.zeros(4)},
            data_iter=iter([np.ones((BATCH, PAYLOAD), np.uint8)] * 2))
        assert r.step == 2

    def test_batches_and_data_iter_are_exclusive(self, shards):
        it = _make(shards, num_batches=1)
        with pytest.raises(ValueError, match="not both"):
            run_resilient_training(_tiny_step_fn(), {"w": jnp.zeros(4)},
                                   [1, 2], data_iter=it)
        with pytest.raises(ValueError, match="batches or data_iter"):
            run_resilient_training(_tiny_step_fn(), {"w": jnp.zeros(4)})

    @pytest.mark.chaos
    @pytest.mark.chaos_data
    def test_sigterm_grace_exactly_once_resume(self, shards,
                                               chaos_ckpt_dir):
        """Kill (real SIGTERM, grace path) mid-run; resume from the
        checkpoint — consumed sample-id stream and the fp32 trajectory
        are bitwise the uninterrupted run's."""
        control_it = _make(shards, num_batches=6)
        control_ids = _drain_ids(control_it)
        w = {"w": jnp.zeros((4,), jnp.float32)}
        step_fn = _tiny_step_fn()
        for i in range(6):
            w, _ = step_fn(w, np.stack(
                [np.frombuffer(
                    control_it.files.read(r)[:PAYLOAD], np.uint8)
                 for r in control_ids[i * BATCH:(i + 1) * BATCH]]))
        control_w = np.asarray(w["w"])

        seen = []
        it = _make(shards, num_batches=6,
                   on_ids=lambda i, ids: seen.extend(ids))
        with res.GracePeriodHandler() as h:
            pre = chaos.SimulatedPreemption(3, handler=h)
            r1 = run_resilient_training(
                step_fn, {"w": jnp.zeros((4,), jnp.float32)},
                data_iter=it, ckpt_dir=str(chaos_ckpt_dir), save_every=1,
                handler=h, on_step=pre.poll)
        assert r1.preempted and r1.step == 3
        assert seen == control_ids[:3 * BATCH]

        state2, step = res.restore_resilient(
            str(chaos_ckpt_dir), {"w": jnp.zeros((4,), jnp.float32)})
        assert step == 3
        it2 = _make(shards, num_batches=6,
                    on_ids=lambda i, ids: seen.extend(ids))
        it2.load_state_dict(ckpt.load_data_state(str(chaos_ckpt_dir),
                                                 step=step))
        r2 = run_resilient_training(step_fn, state2, data_iter=it2,
                                    ckpt_dir=str(chaos_ckpt_dir),
                                    save_every=1, start_step=step)
        assert r2.step == 6
        # no duplicates, no drops — and the trajectory agrees bitwise
        assert seen == control_ids
        np.testing.assert_array_equal(np.asarray(r2.state["w"]), control_w)

    @pytest.mark.chaos
    @pytest.mark.chaos_data
    @pytest.mark.chaos_mesh
    def test_device_loss_elastic_dp4_to_dp2_exactly_once(self, shards,
                                                         tmp_path):
        """Hard-kill path: DeviceLossError at step 3, elastic rebuild
        dp=4→dp=2, model AND iterator restored from one manifest —
        every produced batch matches the control bitwise and the final
        params equal the uninterrupted run's."""
        control_ids = _drain_ids(_make(shards, num_batches=6))
        per_batch = {i: control_ids[i * BATCH:(i + 1) * BATCH]
                     for i in range(6)}
        build = _data_elastic_build()
        step_fn, state, _ = build(jax.devices()[:4])
        it = _make(shards, num_batches=6)
        for b in it:
            state, _ = step_fn(state, b)
        control_w = np.asarray(state[0]["w"])

        produced = {}
        it2 = _make(shards, num_batches=6,
                    on_ids=lambda i, ids: produced.setdefault(i, [])
                    .append(ids))
        dl = chaos.DeviceLoss(at_step=3, device_ids=jax.devices()[2:4])
        result = res.run_elastic_training(
            _data_elastic_build(), jax.devices()[:4], data_iter=it2,
            ckpt_dir=str(tmp_path / "ck"), save_every=1,
            on_step=dl.poll, max_restarts=2)
        assert result.restarts == 1 and len(result.devices) == 2
        assert result.step == 6
        assert sorted(produced) == list(range(6))
        for i, reps in produced.items():
            for ids in reps:
                assert ids == per_batch[i], (i, ids)
        np.testing.assert_array_equal(
            np.asarray(result.state[0]["w"]), control_w)

    @pytest.mark.chaos_data
    def test_elastic_rejects_plain_generator(self, shards, tmp_path):
        def gen():
            yield np.zeros((BATCH, PAYLOAD), np.uint8)

        with pytest.raises(TypeError, match="not checkpointable"):
            res.run_elastic_training(_data_elastic_build(),
                                     jax.devices()[:2], data_iter=gen(),
                                     ckpt_dir=str(tmp_path / "ck"))

    @pytest.mark.chaos
    @pytest.mark.chaos_data
    def test_loader_death_flushes_postmortem(self, shards, tmp_path):
        """A dying loader thread surfaces as DataLoaderError at the
        loop's next fetch AND leaves a postmortem (the loop's crash
        path)."""
        paths, rb = shards

        class DieAfter:
            def __init__(self, src, n):
                self.src, self.n = src, n

            def __iter__(self):
                return self

            def __next__(self):
                if self.src.batches_consumed >= self.n:
                    raise OSError("shard backend gone")
                return next(self.src)

            def state_dict(self):
                return self.src.state_dict()

            def load_state_dict(self, s):
                self.src.load_state_dict(s)

        mem = tele.MemorySink()
        bus = tele.TelemetryBus(
            "loaderdeath",
            sinks=[tele.JsonlSink(str(tmp_path / "s.jsonl")), mem],
            postmortem_dir=str(tmp_path))
        pf = AsyncPrefetcher(DieAfter(_make(shards, num_batches=6), 2),
                             depth=1, telemetry=bus)
        with pytest.raises(DataLoaderError, match="shard backend gone"):
            run_resilient_training(_tiny_step_fn(),
                                   {"w": jnp.zeros((4,), jnp.float32)},
                                   data_iter=pf,
                                   ckpt_dir=str(tmp_path / "ck"),
                                   save_every=1, telemetry=bus)
        pf.close()
        bus.close()
        pms = [f for f in os.listdir(tmp_path)
               if f.startswith("postmortem_")]
        assert len(pms) == 1
        events = tele.load_jsonl(str(tmp_path / pms[0]))
        assert tele.validate_events(events) == len(events)
        assert events[0]["reason"] == "DataLoaderError"


# ------------------------------------------------ flagship golden replay


@pytest.mark.chaos
@pytest.mark.chaos_data
@pytest.mark.chaos_mesh
@pytest.mark.slow  # heaviest single tier-1 case (~24s: full flagship
# golden replay under device loss); the kill/resume property tests
# above keep the exactly-once contract in tier-1 (ISSUE 12 wall trim)
def test_flagship_device_loss_data_resume_matches_golden(tmp_path):
    """ISSUE 7 acceptance: the toy ZeRO flagship fed by the record
    pipeline loses 4 of 8 devices at step 3, rebuilds on the survivor
    submesh, restores model + iterator position from one manifest, and
    reproduces the committed ``gpt1p3b_toy_data`` fp32-hex golden
    trajectory (8-device prefix bitwise; resumed-on-submesh steps ≤ 1
    bf16 ulp — the same bound as the compute-plane golden arc)."""
    from tests.L1.common.harness import (
        load_baseline,
        write_toy_token_shards,
    )

    golden = load_baseline("gpt1p3b_toy_data")
    assert golden is not None and len(golden) == 6

    from apex_tpu.data import ShardedRecordIterator
    from apex_tpu.transformer.testing import (
        flagship_elastic_build,
        gpt1p3b_config,
    )

    cfg = gpt1p3b_config(num_layers=2, hidden_size=256,
                         num_attention_heads=2, vocab_size=512,
                         max_position_embeddings=32)
    paths, rb, decode = write_toy_token_shards(str(tmp_path))
    it = ShardedRecordIterator(paths, rb, 8, checksummed=True,
                               shuffle_window=16, seed=5, num_batches=6,
                               decode=decode)
    losses = []
    build = flagship_elastic_build(cfg, plan="bf16_fit", lr=1e-3,
                                   on_loss=losses.append)
    dl = chaos.DeviceLoss(at_step=3, device_ids=jax.devices()[4:8])
    result = res.run_elastic_training(
        build, jax.devices()[:8], data_iter=it,
        ckpt_dir=str(tmp_path / "ck"), save_every=1, on_step=dl.poll,
        max_restarts=2)
    assert result.restarts == 1 and len(result.devices) == 4
    assert result.step == 6 and len(losses) == 7

    def ulp(a, b):
        ba = np.asarray(a, jnp.bfloat16.dtype).view(np.uint16)
        bb = np.asarray(b, jnp.bfloat16.dtype).view(np.uint16)
        return int(np.abs(ba.astype(np.int64) - bb.astype(np.int64)).max())

    np.testing.assert_array_equal(losses[:3], golden[:3])
    assert max(ulp(np.float32(got), np.float32(want))
               for got, want in zip(losses[3:], golden[2:])) <= 1, (
        losses, golden)
