"""Fleet-wide distributed request tracing (ISSUE 19).

Three contracts live here:

* **Reconstruction** — :func:`build_traces` rebuilds one causal span
  tree per request from ANY set of per-replica streams: file order
  irrelevant, duplicated wire re-emissions merged (never forked),
  migration/preemption lives resolved, dangling parents loud.
* **TTFT decomposition** — the four components (queue / prefill /
  ship / decode-wait) sum to the engine's measured shipping-aware
  ``ttft_ms`` within :data:`TTFT_SUM_TOLERANCE_MS`, on colocated and
  disaggregated paths alike; the colocated control's ship component
  is identically zero.  The satellite-1 pin: a kv_ship retry storm
  lands in TTFT (deadline accounting FLIPS vs the colocated control
  on the same deadline).
* **Flight recorder** — a bounded ring dumped as a schema-valid
  postmortem bundle on fence / migrate refusal / recovery exhaustion;
  memory-only test buses never litter the cwd.
"""

import json
import os

import pytest

import apex_tpu.telemetry as tel
from apex_tpu.analysis import hot_path_guard
from apex_tpu.resilience.chaos import DeviceLossError
from apex_tpu.serving import (ServingEngine, ServingModelConfig, SimClock,
                              init_params)
from apex_tpu.serving.engine import set_fault_hook
from apex_tpu.serving.fleet import (FENCED, ChaosTransport, DisaggRouter,
                                    FleetRouter, LocalTransport,
                                    ReplicaProxy)
from apex_tpu.telemetry.__main__ import main as tel_main
from apex_tpu.telemetry.recorder import FlightRecorder
from apex_tpu.telemetry.regress import (GATED_LOWER, compare_bench,
                                        key_direction)
from apex_tpu.telemetry.schema import load_jsonl, validate_events
from apex_tpu.telemetry.summarize import (format_diff, format_summary,
                                          summarize_events)
from apex_tpu.telemetry.tracing import (SPAN_KINDS, TTFT_SUM_TOLERANCE_MS,
                                        Span, admission_life, build_traces,
                                        critical_path, format_trace,
                                        load_trace_streams,
                                        maybe_dump_flight_record,
                                        run_trace_cli, ttft_decomposition,
                                        validate_trace)

pytestmark = [pytest.mark.serving, pytest.mark.tracing]

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

CFG = ServingModelConfig(vocab_size=64, hidden_size=32, num_heads=4,
                         num_layers=2, max_position=96)


@pytest.fixture(scope="module")
def serving_params():
    return init_params(CFG, seed=0)


def _factory(params, clock, **kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_budget", CFG.max_position)
    kw.setdefault("max_queue", 16)

    def build():
        return ServingEngine(CFG, params, clock=clock, **kw)

    return build


def _disagg(params, *, telemetry=None, clock=None, factory_kw=None,
            **router_kw):
    clock = clock if clock is not None else SimClock()
    kw = dict(factory_kw or {})
    reps = [ReplicaProxy("p0", _factory(params, clock, prefill_only=True,
                                        **kw), role="prefill"),
            ReplicaProxy("d0", _factory(params, clock, kv_import=True,
                                        **kw), role="decode")]
    return DisaggRouter(reps, telemetry=telemetry, **router_kw), reps


PROMPT = [3, 7, 11, 13, 5, 2]


# ---------------------------------------------------------------------------
# Synthetic reconstruction units (no engine: pure span-event fixtures)
# ---------------------------------------------------------------------------

# The worked disaggregated request: arrival 0.0, admitted at 2.0
# (queue 2000ms), prefill done at 3.0 (prefill 1000ms), KV exported
# 3.0 -> shipped -> imported by 6.0 (ship 3000ms), first token
# streamable at 6.5 (decode-wait residual 500ms) — TTFT 6500ms.
_LIFE = admission_life(0, 2.0)
_RID = 7


def _span_ev(span_id, kind, t0, t1, parent=None, **kw):
    ev = dict(type="span", rid=_RID, span_id=span_id, kind=kind,
              t_start=t0, t_end=t1)
    if parent is not None:
        ev["parent_id"] = parent
    ev.update(kw)
    return ev


def _shipped_request_events():
    q = f"{_RID}:queue_wait:{_LIFE}"
    a = f"{_RID}:admit:{_LIFE}"
    exp = f"{_RID}:kv_export:{_LIFE}"
    ship = f"{_RID}:kv_ship:d0:1"
    return [
        _span_ev(q, "queue_wait", 0.0, 2.0),
        _span_ev(a, "admit", 2.0, 2.0, parent=q),
        _span_ev(f"{_RID}:prefill_chunk:{_LIFE}:0", "prefill_chunk",
                 2.0, 3.0, parent=a),
        _span_ev(exp, "kv_export", 3.0, 3.2, parent=a, replica="p0"),
        _span_ev(ship, "kv_ship", 3.2, 5.8, parent=exp, replica="p0",
                 attempt=1, outcome="ok"),
        _span_ev(f"{_RID}:kv_import:1", "kv_import", 5.0, 6.0,
                 parent=ship, replica="d0", attempt=1),
        _span_ev(f"{_RID}:decode_wait:{_LIFE}", "decode_wait", 3.0, 6.5,
                 parent=a),
        _span_ev(f"{_RID}:decode_steps:{_LIFE}", "decode_steps",
                 6.5, 9.0, parent=f"{_RID}:decode_wait:{_LIFE}"),
        _span_ev(f"{_RID}:stream_emit:{_LIFE}", "stream_emit", 6.5, 6.5,
                 parent=f"{_RID}:decode_wait:{_LIFE}"),
    ]


class TestReconstruction:
    def test_out_of_order_streams_reconstruct_one_tree(self):
        events = _shipped_request_events()
        # two "replica streams" interleaved worst-case: reversed halves
        shuffled = list(reversed(events[::2])) + list(reversed(events[1::2]))
        traces = build_traces(shuffled)
        assert set(traces) == {_RID}
        t = traces[_RID]
        assert len(t.spans) == len(events)
        assert validate_trace(t) == []
        assert [s.kind for s in t.roots()] == ["queue_wait"]
        d = ttft_decomposition(t)
        assert d == {"rid": _RID, "ttft_ms": 6500.0,
                     "ttft_queue_ms": 2000.0, "ttft_prefill_ms": 1000.0,
                     "ttft_ship_ms": 3000.0,
                     "ttft_decode_wait_ms": 500.0}

    def test_critical_path_splices_ship_chain(self):
        t = build_traces(_shipped_request_events())[_RID]
        kinds = [s.kind for s in critical_path(t)]
        assert kinds == ["queue_wait", "admit", "kv_export",
                         "decode_wait", "kv_ship", "kv_import",
                         "stream_emit"]

    def test_duplicate_redelivery_merges_never_forks(self):
        events = _shipped_request_events()
        # a duplicated wire copy re-emits the SAME span id, possibly
        # with a narrower interval and missing attributes
        dup = dict(events[5], t_start=5.5, t_end=5.9)
        dup.pop("parent_id")
        dup.pop("attempt")
        traces = build_traces(events + [dup, dict(events[0])])
        t = traces[_RID]
        assert len(t.spans) == len(events)
        assert t.duplicates == 2
        imp = t.spans[f"{_RID}:kv_import:1"]
        # merge widened nothing here (the original covers the dup) and
        # kept the causal link the duplicate lacked
        assert (imp.t_start, imp.t_end) == (5.0, 6.0)
        assert imp.parent_id == f"{_RID}:kv_ship:d0:1"
        assert ttft_decomposition(t)["ttft_ship_ms"] == 3000.0

    def test_merge_widens_interval_and_fills_gaps(self):
        a = Span(rid=1, span_id="s", kind="admit", t_start=2.0, t_end=3.0)
        b = Span(rid=1, span_id="s", kind="admit", t_start=1.0, t_end=2.5,
                 parent_id="q", replica="r0")
        a.merge(b)
        assert (a.t_start, a.t_end) == (1.0, 3.0)
        assert a.parent_id == "q" and a.replica == "r0"

    def test_orphan_span_is_loud(self):
        events = _shipped_request_events()
        events.append(_span_ev(f"{_RID}:kv_import:9", "kv_import",
                               5.0, 6.0, parent=f"{_RID}:kv_ship:d9:9"))
        t = build_traces(events)[_RID]
        problems = validate_trace(t)
        assert len(problems) == 1 and "dangling parent" in problems[0]
        assert [s.span_id for s in t.orphans()] == [f"{_RID}:kv_import:9"]
        assert "ORPHAN" in format_trace(t)

    def test_unknown_kind_and_inverted_interval_flagged(self):
        t = build_traces([
            _span_ev("x:1", "teleport", 0.0, 1.0),
            _span_ev("x:2", "admit", 3.0, 1.0),
        ])[_RID]
        problems = validate_trace(t)
        assert any("unknown kind" in p for p in problems)
        assert any("ends before it starts" in p for p in problems)

    def test_preempted_request_uses_latest_life_before_first_token(self):
        """A preempted request's FINAL life admits after its first
        token existed; the decomposition must attribute prefill to the
        latest life that started before decode_wait, and queue to that
        life's queue_wait."""
        life2 = admission_life(1, 8.0)
        events = _shipped_request_events()
        q2 = f"{_RID}:queue_wait:{life2}"
        events += [
            _span_ev(q2, "queue_wait", 0.0, 8.0),
            _span_ev(f"{_RID}:admit:{life2}", "admit", 8.0, 8.0,
                     parent=q2),
        ]
        # the final-life stream_emit points at a decode_wait whose
        # parent admit came LATER than the wait began
        t = build_traces(events)[_RID]
        wait = t.spans[f"{_RID}:decode_wait:{_LIFE}"]
        wait.parent_id = f"{_RID}:admit:{life2}"
        d = ttft_decomposition(t)
        assert d["ttft_queue_ms"] == 2000.0
        assert d["ttft_prefill_ms"] == 1000.0

    def test_ship_segment_survives_broken_causal_link(self):
        """A kv_import whose parent ship span never landed in any
        recorded stream still decomposes: fall back to the latest
        preceding kv_export."""
        events = [e for e in _shipped_request_events()
                  if e["kind"] != "kv_ship"]
        t = build_traces(events)[_RID]
        assert ttft_decomposition(t)["ttft_ship_ms"] == 3000.0

    def test_unfinished_trace_is_incomplete_in_time_not_structure(self):
        events = [e for e in _shipped_request_events()
                  if e["kind"] not in ("stream_emit", "decode_steps")]
        t = build_traces(events)[_RID]
        assert validate_trace(t) == []
        assert ttft_decomposition(t) is None
        assert critical_path(t) == []

    def test_span_kinds_derive_from_schema(self):
        assert set(SPAN_KINDS) == {
            "queue_wait", "admit", "prefill_chunk", "kv_export",
            "kv_ship", "kv_import", "decode_wait", "decode_steps",
            "migrate_hop", "stream_emit"}


# ---------------------------------------------------------------------------
# Trace context on the wire
# ---------------------------------------------------------------------------


class TestWireTraceContext:
    def test_ctx_rides_envelope_outside_payload_crc(self):
        t = LocalTransport()
        seen = []
        t.register("d", "echo", lambda p: (seen.append(t.current_trace)
                                           or {"ok": True}))
        ctx = {"rid": 4, "span_id": "4:kv_ship:d:1", "attempt": 1}
        assert t.call("d", "echo", {"x": 1}, trace=ctx)["ok"]
        assert seen == [ctx]
        # the context is scoped to the delivery, not left dangling
        assert t.current_trace is None

    def test_corruption_fault_never_touches_ctx(self):
        chaos = ChaosTransport(LocalTransport(),
                               schedule={("migrate", "corrupt"): {1}})
        ctx = {"rid": 9, "span_id": "9:kv_ship:d0:2", "attempt": 2}
        wire = chaos.inner.serialize("d", "migrate", {"records": [1]},
                                     trace=ctx)
        env = json.loads(chaos._corrupt(wire, "migrate"))
        assert env["trace"] == ctx        # verbatim through the fault

    def test_duplicate_wire_copies_carry_identical_ctx(self):
        t = LocalTransport()
        ctx = {"rid": 2, "span_id": "2:kv_ship:d0:1", "attempt": 1}
        wire = t.serialize("d", "kv_page", {"page_index": 0}, trace=ctx)
        # the duplicate is the SAME bytes — same span id on both ends,
        # which is exactly why build_traces can merge instead of fork
        assert json.loads(wire)["trace"] == ctx
        t.register("d", "kv_page", lambda p: {"ok": True})
        assert t.deliver(wire) == t.deliver(wire)


# ---------------------------------------------------------------------------
# Real engine: colocated decomposition pins
# ---------------------------------------------------------------------------


def _colocated_run(params, tmp_path=None, n=4):
    sinks = [tel.MemorySink()]
    if tmp_path is not None:
        sinks.append(tel.JsonlSink(str(tmp_path / "colo.jsonl")))
    bus = tel.TelemetryBus(run_id="trace-colo", sinks=sinks)
    eng = _factory(params, SimClock(), telemetry=bus)()
    eng.warmup()
    for i in range(n):
        eng.submit([2 + i, 5, 9, 4 + i], max_new_tokens=4)
    eng.run()
    return eng, sinks[0].events


class TestColocatedDecomposition:
    def test_components_sum_to_measured_ttft(self, serving_params):
        eng, events = _colocated_run(serving_params)
        retires = {e["rid"]: e for e in events
                   if e["type"] == "request_retire"}
        traces = build_traces(events)
        assert set(traces) == set(retires)
        for rid, t in traces.items():
            assert validate_trace(t) == []
            d = ttft_decomposition(t)
            assert d is not None
            parts = (d["ttft_queue_ms"] + d["ttft_prefill_ms"]
                     + d["ttft_ship_ms"] + d["ttft_decode_wait_ms"])
            assert abs(parts - retires[rid]["ttft_ms"]) \
                <= TTFT_SUM_TOLERANCE_MS
            # the colocated sanity zero: no ship leg, in the spans OR
            # the shipping-aware retire payload
            assert d["ttft_ship_ms"] == 0.0
            assert "ship_ms" not in retires[rid]
            assert not t.by_kind("kv_ship") and not t.by_kind("kv_import")

    def test_span_events_validate_against_schema(self, serving_params):
        _, events = _colocated_run(serving_params)
        assert any(e["type"] == "span" for e in events)
        validate_events(events)   # raises SchemaError on drift

    def test_decode_loop_span_emission_is_host_sync_free(
            self, serving_params):
        """Satellite 3: tracing must not buy observability with decode
        stalls — spans buffer host-side state only."""
        bus = tel.TelemetryBus(run_id="trace-hot",
                               sinks=[tel.MemorySink()])
        eng = _factory(serving_params, SimClock(), telemetry=bus)()
        eng.warmup()
        for i in range(3):
            eng.submit([2 + i, 5, 9], max_new_tokens=4)
        with hot_path_guard("traced serve", transfers=None) as g:
            eng.run()
        assert g.recompiles == 0 and g.syncs == []
        assert any(e["type"] == "span"
                   for e in bus.sinks[0].events)

    def test_trace_cli_exit_0_on_recorded_stream(self, serving_params,
                                                 tmp_path, capsys):
        _colocated_run(serving_params, tmp_path)
        path = str(tmp_path / "colo.jsonl")
        assert run_trace_cli([path]) == 0
        assert tel_main(["trace", path]) == 0
        out = capsys.readouterr().out
        assert "critical path:" in out and "ttft" in out
        assert tel_main(["trace", path, "--json"]) == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["problems"] == [] and len(rec["traces"]) == 4


# ---------------------------------------------------------------------------
# Disaggregated path: ship storm, shipping-aware TTFT, deadline flip
# ---------------------------------------------------------------------------


def _storm_fleet(params, *, deadline_s=None, tmp_path=None):
    """1 prefill + 1 decode replica; the first two kv_page messages
    drop in flight, so the single shipment retries twice (backoff 2
    then 4 rounds) before landing — a deterministic ship storm.  The
    router ticks the shared clock once per ROUND (the bench_fleet
    idiom): backoff rounds cost wall time even while every engine
    idles, which is exactly the wall the ship decomposition must
    surface."""
    sinks = [tel.MemorySink()]
    if tmp_path is not None:
        sinks.append(tel.JsonlSink(str(tmp_path / "storm.jsonl")))
    bus = tel.TelemetryBus(run_id="trace-storm", sinks=sinks)
    chaos = ChaosTransport(LocalTransport(),
                           schedule={("kv_page", "drop"): {1, 2}},
                           telemetry=bus)
    clock = SimClock()
    fleet, reps = _disagg(params, telemetry=bus, clock=clock,
                          factory_kw={"telemetry": bus}, transport=chaos,
                          on_round=clock.advance)
    fleet.warmup()
    rid = fleet.submit(list(PROMPT), max_new_tokens=4,
                       deadline_s=deadline_s)
    fleet.run()
    return fleet, rid, sinks[0].events


class TestShippingAwareTTFT:
    def test_ship_storm_lands_in_ttft_and_sums(self, serving_params):
        fleet, rid, events = _storm_fleet(serving_params)
        retire = [e for e in events if e["type"] == "request_retire"
                  and e["rid"] == rid][0]
        assert retire["ship_ms"] > 0.0
        assert retire["ttft_ms"] >= retire["ship_ms"]
        t = build_traces(events)[rid]
        assert validate_trace(t) == []
        ships = t.by_kind("kv_ship")
        assert [s.outcome for s in ships] == ["retry", "retry", "ok"]
        assert [s.attempt for s in ships] == [1, 2, 3]
        assert all(s.reason == "timeout" for s in ships[:2])
        # the import parents on the WINNING attempt's span id (carried
        # on the wire), not on either dropped attempt
        imp = t.by_kind("kv_import")[-1]
        assert imp.parent_id == ships[-1].span_id
        d = ttft_decomposition(t)
        assert d["ttft_ship_ms"] > 0.0
        parts = (d["ttft_queue_ms"] + d["ttft_prefill_ms"]
                 + d["ttft_ship_ms"] + d["ttft_decode_wait_ms"])
        assert abs(parts - retire["ttft_ms"]) <= TTFT_SUM_TOLERANCE_MS

    def test_ship_retry_storm_flips_deadline_vs_colocated(
            self, serving_params):
        """Satellite 1 acceptance: with shipping-aware accounting, the
        SAME deadline that a colocated engine comfortably makes is
        MISSED under a kv_ship retry storm — the ship wall is real SLO
        time, not bookkeeping."""
        # calibrate: the storm run's actual finish on the shared clock
        fleet, rid, _ = _storm_fleet(serving_params)
        req = fleet.handles[rid]
        calib_finish, calib_tokens = req.finish_t, list(req.generated)
        deadline_s = (calib_finish - 1e-6) - req.arrival_t
        # identical storm, now with the deadline armed: the request
        # must still COMPLETE (its last token predates the sweep that
        # notices the deadline) — but as a recorded SLO miss
        fleet2, rid2, events2 = _storm_fleet(serving_params,
                                             deadline_s=deadline_s)
        req2 = fleet2.handles[rid2]
        assert req2.finish_reason in ("eos", "length")
        assert list(req2.generated) == calib_tokens
        retire2 = [e for e in events2 if e["type"] == "request_retire"
                   and e["rid"] == rid2][0]
        assert retire2["deadline_hit"] is False
        assert retire2["ship_ms"] > 0.0
        # colocated control: same prompt, same budget, same deadline —
        # without the ship wall the deadline is easy
        bus = tel.TelemetryBus(run_id="trace-colo-dl",
                               sinks=[tel.MemorySink()])
        eng = _factory(serving_params, SimClock(), telemetry=bus)()
        eng.warmup()
        eng.submit(list(PROMPT), max_new_tokens=4, deadline_s=deadline_s)
        eng.run()
        ctrl = [e for e in bus.sinks[0].events
                if e["type"] == "request_retire"][0]
        assert ctrl["deadline_hit"] is True
        assert "ship_ms" not in ctrl

    def test_storm_stream_decomposes_via_cli(self, serving_params,
                                             tmp_path):
        _storm_fleet(serving_params, tmp_path=tmp_path)
        assert run_trace_cli([str(tmp_path / "storm.jsonl")],
                             echo=lambda *_: None) == 0


# ---------------------------------------------------------------------------
# Migration hops join the trace
# ---------------------------------------------------------------------------


class TestMigrationTracing:
    def test_fence_migration_hop_is_a_root_span(self, serving_params):
        chaos = ChaosTransport(LocalTransport(),
                               schedule={("ping", "drop"): {1}})
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="trace-migrate", sinks=[mem])
        clock = SimClock()
        reps = [ReplicaProxy(f"r{i}",
                             _factory(serving_params, clock,
                                      telemetry=bus))
                for i in range(2)]
        fleet = FleetRouter(reps, telemetry=bus, transport=chaos)
        fleet.warmup()
        for i in range(4):
            fleet.submit([2 + i, 5, 9, 4], max_new_tokens=4)
        fleet.run()
        assert reps[0].state == FENCED
        moved = [e["rid"] for e in mem.events
                 if e["type"] == "request_migrate"]
        assert moved
        traces = build_traces(mem.events)
        hops = [s for rid in moved
                for s in traces[rid].by_kind("migrate_hop")]
        assert hops and all(s.parent_id is None for s in hops)
        assert all(f":migrate_hop:r0:r1:" in s.span_id for s in hops)
        # migrated lives still reconstruct complete and sum: the whole
        # point of deriving span ids from application identity
        for t in traces.values():
            assert validate_trace(t) == []
        retires = {e["rid"]: e["ttft_ms"] for e in mem.events
                   if e["type"] == "request_retire"}
        for rid, ttft in retires.items():
            d = ttft_decomposition(traces[rid])
            parts = (d["ttft_queue_ms"] + d["ttft_prefill_ms"]
                     + d["ttft_ship_ms"] + d["ttft_decode_wait_ms"])
            assert abs(parts - ttft) <= TTFT_SUM_TOLERANCE_MS


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded_and_oldest_first(self):
        bus = tel.TelemetryBus(run_id="ring",
                               recorder=FlightRecorder(capacity=8))
        for i in range(20):
            bus.emit("step", step=i, step_ms=1.0)
        snap = bus.recorder.snapshot()
        assert len(bus.recorder) == 8 and len(snap) == 8
        assert [e["step"] for e in snap] == list(range(12, 20))

    def test_memory_only_bus_never_dumps(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)   # any leak would be visible here
        bus = tel.TelemetryBus(run_id="memonly", sinks=[tel.MemorySink()])
        bus.emit("step", step=0, step_ms=1.0)
        assert maybe_dump_flight_record(bus, "replica_fence:test") is None
        assert maybe_dump_flight_record(None, "whatever") is None
        assert not list(tmp_path.glob("postmortem_*.jsonl"))

    def test_file_backed_bus_dumps_schema_valid_bundle(self, tmp_path):
        bus = tel.TelemetryBus(
            run_id="fr", sinks=[tel.JsonlSink(str(tmp_path / "s.jsonl"))],
            recorder=FlightRecorder(capacity=8))
        for i in range(12):
            bus.emit("step", step=i, step_ms=1.0)
        path = maybe_dump_flight_record(bus, "migrate_refused", step=12)
        assert path is not None and os.path.exists(path)
        lines = load_jsonl(path)
        assert lines[0]["type"] == "postmortem"
        assert lines[0]["reason"] == "migrate_refused"
        assert [e["step"] for e in lines[1:]] == list(range(4, 12))
        validate_events(lines)

    def test_replica_fence_dumps_the_fenced_ring(self, serving_params,
                                                 tmp_path):
        chaos = ChaosTransport(LocalTransport(),
                               schedule={("ping", "drop"): {1}})
        bus = tel.TelemetryBus(
            run_id="fence-dump",
            sinks=[tel.JsonlSink(str(tmp_path / "fleet.jsonl"))])
        clock = SimClock()
        reps = [ReplicaProxy(f"r{i}",
                             _factory(serving_params, clock,
                                      telemetry=bus))
                for i in range(2)]
        fleet = FleetRouter(reps, telemetry=bus, transport=chaos)
        fleet.warmup()
        for i in range(3):
            fleet.submit([2 + i, 5, 9], max_new_tokens=3)
        fleet.run()
        assert reps[0].state == FENCED
        bundles = sorted(tmp_path.glob("postmortem_*.jsonl"))
        assert bundles
        header = load_jsonl(str(bundles[0]))[0]
        assert header["reason"].startswith("replica_fence:")

    def test_recovery_exhaustion_dumps_before_reraise(
            self, serving_params, tmp_path):
        bus = tel.TelemetryBus(
            run_id="exhaust",
            sinks=[tel.JsonlSink(str(tmp_path / "e.jsonl"))])
        eng = _factory(serving_params, SimClock(), telemetry=bus,
                       max_recoveries=0)()
        eng.warmup()
        eng.submit(list(PROMPT), max_new_tokens=4)

        def boom(event, info):
            if event == "decode":
                raise DeviceLossError([0], "chaos")

        prev = set_fault_hook(boom)
        try:
            with pytest.raises(DeviceLossError):
                eng.run()
        finally:
            set_fault_hook(prev)
        bundles = sorted(tmp_path.glob("postmortem_*.jsonl"))
        assert bundles
        header = load_jsonl(str(bundles[0]))[0]
        assert header["reason"] == "recovery_exhausted:DeviceLossError"


# ---------------------------------------------------------------------------
# Trace CLI exit codes
# ---------------------------------------------------------------------------


def _write_stream(path, events):
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return str(path)


class TestTraceCli:
    def test_exit_1_on_orphan(self, tmp_path, capsys):
        events = _shipped_request_events()
        events.append(_span_ev(f"{_RID}:kv_import:9", "kv_import",
                               5.0, 6.0, parent="never-emitted"))
        path = _write_stream(tmp_path / "orphan.jsonl", events)
        assert tel_main(["trace", path]) == 1
        assert "PROBLEM" in capsys.readouterr().out

    def test_exit_1_on_decomposition_sum_mismatch(self, tmp_path, capsys):
        events = _shipped_request_events()
        events.append({"type": "request_retire", "rid": _RID,
                       "reason": "length", "new_tokens": 4,
                       "preemptions": 0, "ttft_ms": 9999.0})
        path = _write_stream(tmp_path / "mismatch.jsonl", events)
        assert tel_main(["trace", path]) == 1
        assert "sums to" in capsys.readouterr().out

    def test_exit_0_splits_streams_any_which_way(self, tmp_path):
        """The same events split across per-replica files reconstruct
        identically — including the retire record living in a
        DIFFERENT file than the spans it corroborates."""
        events = _shipped_request_events()
        retire = {"type": "request_retire", "rid": _RID,
                  "reason": "length", "new_tokens": 4,
                  "preemptions": 0, "ttft_ms": 6500.0}
        a = _write_stream(tmp_path / "p0.jsonl", events[::2])
        b = _write_stream(tmp_path / "d0.jsonl",
                          events[1::2] + [retire])
        assert run_trace_cli([a, b], echo=lambda *_: None) == 0
        assert run_trace_cli([b, a], echo=lambda *_: None) == 0

    def test_exit_2_on_unreadable_stream(self, tmp_path):
        assert run_trace_cli([str(tmp_path / "nope.jsonl")],
                             echo=lambda *_: None) == 2

    def test_exit_2_on_unknown_rid(self, tmp_path):
        path = _write_stream(tmp_path / "s.jsonl",
                             _shipped_request_events())
        assert run_trace_cli([path], rid=123,
                             echo=lambda *_: None) == 2
        assert run_trace_cli([path], rid=_RID,
                             echo=lambda *_: None) == 0

    def test_torn_tail_stream_still_joins(self, tmp_path):
        path = _write_stream(tmp_path / "torn.jsonl",
                             _shipped_request_events())
        with open(path, "a") as f:
            f.write('{"type": "span", "rid"')   # the crash mid-line
        assert run_trace_cli([path], echo=lambda *_: None) == 0


# ---------------------------------------------------------------------------
# Regress gate: the decomposition key family
# ---------------------------------------------------------------------------


class TestRegressGate:
    def test_ttft_decomposition_direction_rules(self):
        # pinned by name from the GATED_LOWER comment in regress.py
        for tier in ("fleet", "serving"):
            for comp in ("queue", "prefill", "ship", "decode_wait"):
                assert key_direction(f"{tier}_ttft_{comp}_ms") == "lower"
        assert r"ttft_\w*(queue|prefill|ship|decode_wait)_ms$" \
            in GATED_LOWER

    def test_vanished_decomposition_key_fails_gate(self):
        a = {"fleet_ttft_ship_ms": 12.0, "fleet_ttft_queue_ms": 3.0}
        b = {"fleet_ttft_queue_ms": 3.0}
        rows, failures = compare_bench(a, b, 10.0,
                                       keys=["fleet_ttft_ship_ms"])
        assert len(failures) == 1
        assert failures[0]["error"] == "missing from B"

    def test_ship_wall_moving_off_zero_is_unbounded_regression(self):
        rows, failures = compare_bench({"fleet_ttft_ship_ms": 0.0},
                                       {"fleet_ttft_ship_ms": 50.0},
                                       10.0)
        assert len(failures) == 1
        assert failures[0]["delta_pct"] == float("-inf")

    def test_regress_ttft_keys_mandatory_on_committed_r19_pair(self,
                                                               capsys):
        """r19 satellite 6: the TTFT decomposition family is MANDATORY
        over the committed r19 pair (A = 4 colocated replicas, B = the
        same four split 2 prefill + 2 decode, same offered load as the
        r18 pair, both cpu-toy geometry-stamped).  Three facts on
        committed data: (1) queue/prefill/ship medians gate clean at
        ``--keys`` (ship identically 0.0 on BOTH sides — the colocated
        sanity control, and on the disagg side export→import lands
        inside one 10 ms virtual round); (2) the gate has TEETH — the
        decode-wait component is where the shipping round is priced,
        so including it fails the gate with the moved-off-zero
        unbounded delta, with every other row still present and
        directed lower-is-better; (3) a vanished mandatory key is a
        failure, not a skip."""
        a = os.path.join(REPO, "BENCH_r19_fleet.json")
        b = os.path.join(REPO, "BENCH_r19b_fleet.json")
        gate = ("fleet_ttft_queue_ms,fleet_ttft_prefill_ms,"
                "fleet_ttft_ship_ms")
        assert tel_main(["regress", a, b, "--max-regress", "25",
                         "--keys", gate]) == 0
        capsys.readouterr()
        rc = tel_main(["regress", a, b, "--max-regress", "25", "--json",
                       "--keys", gate + ",fleet_ttft_decode_wait_ms"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 1
        by_key = {r["key"]: r for r in rec["rows"]}
        for comp in ("queue", "prefill", "ship", "decode_wait"):
            assert by_key[f"fleet_ttft_{comp}_ms"]["direction"] == "lower"
        assert rec["failures"] == ["fleet_ttft_decode_wait_ms"]
        wait = by_key["fleet_ttft_decode_wait_ms"]
        assert wait["ok"] is False
        assert wait["delta_pct"] == float("-inf")
        ka, kb = (json.load(open(p)) for p in (a, b))
        assert ka["fleet_config"]["mode"] == "colocated"
        assert kb["fleet_config"]["mode"] == "disagg"
        assert kb["fleet_config"]["prefill_replicas"] == 2
        for rec_ in (ka, kb):
            assert rec_["fleet_config"]["geometry"] == "cpu-toy"
            assert rec_["fleet_traced_requests"] == rec_["fleet_requests"]
            # colocated sanity control: no shipping wall in TTFT —
            # and the disagg round-clock side agrees (see docstring)
            assert rec_["fleet_ttft_ship_ms"] == 0.0
        assert ka["fleet_ttft_decode_wait_ms"] == 0.0
        assert kb["fleet_ttft_decode_wait_ms"] == 10.0
        assert kb["fleet_kv_ships"] == kb["fleet_requests"]
        # ...and a vanished mandatory key is a failure, not a skip
        assert tel_main(["regress", a, b, "--max-regress", "25",
                         "--keys", "fleet_ttft_ship_ms,gone_key"]) == 1


# ---------------------------------------------------------------------------
# Summarize integration
# ---------------------------------------------------------------------------


class TestSummarizeIntegration:
    def test_decomposition_keys_and_diff_rows(self, serving_params):
        _, events = _colocated_run(serving_params)
        s = summarize_events(events)
        assert s["serving_traced_requests"] == 4
        for comp in ("queue", "prefill", "ship", "decode_wait"):
            assert f"serving_ttft_{comp}_ms" in s
        assert s["serving_ttft_ship_ms"] == 0.0
        assert "ttft split" in format_summary(s)
        diff = format_diff(s, s)
        assert "ttft queue" in diff and "ttft ship" in diff


# ---------------------------------------------------------------------------
# Multi-seed chaos grid (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestChaosGrid:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_traces_complete_under_randomized_faults(self, serving_params,
                                                     seed):
        """Whatever a seeded fault mix does to the wire — drops,
        delays, duplicates, corruption — every request finishes and
        its trace reconstructs complete with a summing decomposition."""
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id=f"grid-{seed}", sinks=[mem])
        rates = {("kv_page", f): 0.12 for f in
                 ("drop", "delay", "duplicate", "corrupt")}
        rates.update({("kv_commit", "drop"): 0.1,
                      ("migrate", "drop"): 0.1})
        chaos = ChaosTransport(LocalTransport(), rates=rates, seed=seed,
                               telemetry=bus)
        fleet, _ = _disagg(serving_params, telemetry=bus,
                           factory_kw={"telemetry": bus},
                           transport=chaos, fault_retries=3)
        fleet.warmup()
        rids = [fleet.submit([2 + i, 5, 9, 4 + i, 7], max_new_tokens=4)
                for i in range(6)]
        fleet.run()
        for rid in rids:
            assert fleet.handles[rid].finish_reason in ("eos", "length")
        retires = {e["rid"]: e["ttft_ms"] for e in mem.events
                   if e["type"] == "request_retire"}
        traces = build_traces(mem.events)
        assert set(traces) >= set(rids)
        for rid in rids:
            assert validate_trace(traces[rid]) == []
            d = ttft_decomposition(traces[rid])
            parts = (d["ttft_queue_ms"] + d["ttft_prefill_ms"]
                     + d["ttft_ship_ms"] + d["ttft_decode_wait_ms"])
            assert abs(parts - retires[rid]) <= TTFT_SUM_TOLERANCE_MS
