"""GroupBN tests: group statistics over a mesh sub-axis, fused add+relu,
running-stat updates — the checks the reference's distributed bn-group tests
do on real GPUs (tests/distributed/synced_batchnorm/, bn_group variants)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

C = 6


def _ref_bn(x, w, b, eps=1e-5):
    m = x.astype(np.float64).mean(axis=(0, 1, 2))
    v = x.astype(np.float64).var(axis=(0, 1, 2))
    return ((x - m) / np.sqrt(v + eps) * w + b).astype(np.float32)


def test_matches_reference_bn_single():
    bn = BatchNorm2d_NHWC(C)
    v = bn.init()
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 5, 3, C))
    y, new_v = bn.apply(v, x)
    want = _ref_bn(np.asarray(x), np.ones(C), np.zeros(C))
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)
    # minibatch buffers updated (reference batch_norm.py:110-111)
    assert float(jnp.abs(new_v["state"]["minibatch_mean"]).sum()) > 0


def test_bn_group_stats_match_pooled_batch():
    """bn_group=4 over a mesh sub-axis == one BN over the pooled batch (the
    IPC peer-stat path of the reference, batch_norm.py:120-160)."""
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ("data_outer", "data_bn"))
    bn = BatchNorm2d_NHWC(C, bn_group=4, axis_name="data_bn")
    v = bn.init()
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 4, 3, C))

    def f(xl):
        y, _ = bn.apply(v, xl)
        return y

    got = shard_map(f, mesh=mesh, in_specs=P(("data_outer", "data_bn")),
                    out_specs=P(("data_outer", "data_bn")))(x)
    # each outer group of 4 shards (= 8 rows of the batch) pools its stats
    got = np.asarray(got)
    for half in (slice(0, 8), slice(8, 16)):
        want = _ref_bn(np.asarray(x[half]), np.ones(C), np.zeros(C))
        np.testing.assert_allclose(got[half], want, rtol=1e-4, atol=1e-4)
    # outer groups must NOT share stats: full-batch BN differs
    full = _ref_bn(np.asarray(x), np.ones(C), np.zeros(C))
    assert not np.allclose(got, full, atol=1e-4)


def test_addrelu_and_grads():
    bn = BatchNorm2d_NHWC(C)
    v = bn.init()
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 3, 3, C))
    z = jax.random.normal(jax.random.PRNGKey(3), (4, 3, 3, C))

    y, _ = bn.apply(v, x, z)
    assert float(y.min()) >= 0.0
    # dz flows only through the relu mask (reference bitmask backward,
    # batch_norm.py:78-99 — AD re-derives the mask)
    def s(z):
        out, _ = bn.apply(v, x, z)
        return jnp.sum(out)
    dz = jax.grad(s)(z)
    mask = np.asarray(y) > 0
    np.testing.assert_array_equal(np.asarray(dz) != 0, mask)


def test_eval_uses_running_stats():
    bn = BatchNorm2d_NHWC(C, momentum=1.0)  # running stats := batch stats
    v = bn.init()
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 4, 4, C)) * 3 + 1
    _, v2 = bn.apply(v, x, training=True)
    y_eval, _ = bn.apply(v2, x, training=False)
    # eval with momentum=1 running stats ~ train normalize (up to the
    # unbiased-var correction)
    n = x.size // C
    corr = np.sqrt(n / (n - 1))  # sqrt(var_unbiased / var_biased)
    y_train, _ = bn.apply(v, x, training=True)
    np.testing.assert_allclose(np.asarray(y_eval) * corr, np.asarray(y_train),
                               rtol=1e-3, atol=1e-3)


def test_bn_group_requires_axis():
    with pytest.raises(ValueError):
        BatchNorm2d_NHWC(C, bn_group=2)
