"""Megatron arguments + global_vars tests (reference
apex/transformer/testing/arguments.py:23-280, global_vars.py:34-270)."""

import jax.numpy as jnp
import pytest

from apex_tpu.transformer.testing import arguments, global_vars


def _parse(argv, **kw):
    return arguments.parse_args(args=argv, **kw)


def test_parallel_size_derivation():
    args = _parse(["--world-size", "16", "--micro-batch-size", "2",
                   "--tensor-model-parallel-size", "4",
                   "--pipeline-model-parallel-size", "2",
                   "--num-attention-heads", "4", "--hidden-size", "64"])
    assert args.data_parallel_size == 2
    assert args.global_batch_size == 2 * 2  # micro * dp


def test_world_size_divisibility_enforced():
    with pytest.raises(AssertionError):
        _parse(["--world-size", "6", "--micro-batch-size", "1",
                "--tensor-model-parallel-size", "4",
                "--num-attention-heads", "4", "--hidden-size", "64"])


def test_autoresume_biencoder_vit_groups_parse():
    """The r7 groups (reference arguments.py:725-806): autoresume,
    biencoder/ICT/retriever, and ViT flags must parse so reference
    launch scripts run unchanged (VERDICT r5 Missing #2)."""
    args = _parse([
        "--micro-batch-size", "1", "--num-attention-heads", "4",
        "--hidden-size", "64", "--world-size", "1",
        "--adlr-autoresume", "--adlr-autoresume-interval", "500",
        "--ict-head-size", "128", "--biencoder-projection-dim", "64",
        "--biencoder-shared-query-context-model",
        "--ict-load", "/tmp/ict", "--bert-load", "/tmp/bert",
        "--titles-data-path", "/tmp/titles",
        "--query-in-block-prob", "0.2", "--use-one-sent-docs",
        "--evidence-data-path", "/tmp/ev",
        "--retriever-report-topk-accuracies", "1", "5", "20",
        "--retriever-score-scaling",
        "--block-data-path", "/tmp/blocks",
        "--embedding-path", "/tmp/emb",
        "--indexer-batch-size", "64", "--indexer-log-interval", "100",
        "--num-classes", "10", "--img-dim", "32",
        "--num-channels", "1", "--patch-dim", "4",
    ])
    assert args.adlr_autoresume and args.adlr_autoresume_interval == 500
    assert args.ict_head_size == 128
    assert args.retriever_report_topk_accuracies == [1, 5, 20]
    assert args.biencoder_shared_query_context_model
    assert args.num_classes == 10 and args.patch_dim == 4


def test_default_biencoder_vit_values():
    args = _parse(["--micro-batch-size", "1", "--num-attention-heads",
                   "4", "--hidden-size", "64", "--world-size", "1"])
    assert args.adlr_autoresume is False
    assert args.ict_head_size is None
    assert args.biencoder_projection_dim == 0
    assert args.query_in_block_prob == 0.1
    assert args.indexer_batch_size == 128
    assert args.num_classes == 1000 and args.img_dim == 224


def test_virtual_pipeline_derivation():
    args = _parse(["--world-size", "8", "--micro-batch-size", "1",
                   "--pipeline-model-parallel-size", "4",
                   "--num-layers", "16",
                   "--num-layers-per-virtual-pipeline-stage", "2",
                   "--num-attention-heads", "4", "--hidden-size", "64"])
    # (16 layers / 4 stages) / 2 per chunk = 2 virtual chunks
    assert args.virtual_pipeline_model_parallel_size == 2


def test_bf16_forces_fp32_grad_accum():
    args = _parse(["--world-size", "1", "--micro-batch-size", "1", "--bf16",
                   "--num-attention-heads", "4", "--hidden-size", "64"])
    assert args.params_dtype == jnp.bfloat16
    assert args.accumulate_allreduce_grads_in_fp32
    with pytest.raises(AssertionError):
        _parse(["--world-size", "1", "--micro-batch-size", "1", "--bf16",
                "--fp16", "--num-attention-heads", "4", "--hidden-size", "64"])


def test_defaults_fill_only_unset():
    args = _parse(["--world-size", "1", "--micro-batch-size", "1",
                   "--num-attention-heads", "4", "--hidden-size", "64"],
                  defaults={"seq_length": 512, "hidden_size": 9999})
    assert args.seq_length == 512       # was None -> filled
    assert args.hidden_size == 64       # explicitly set -> kept


def test_derived_network_sizes():
    args = _parse(["--world-size", "1", "--micro-batch-size", "1",
                   "--num-attention-heads", "4", "--hidden-size", "64"])
    assert args.ffn_hidden_size == 256
    assert args.kv_channels == 16


def test_global_vars_lifecycle():
    global_vars.destroy_global_vars()
    with pytest.raises(AssertionError):
        global_vars.get_args()
    global_vars.set_global_variables(args=[
        "--world-size", "4", "--micro-batch-size", "2",
        "--num-attention-heads", "4", "--hidden-size", "64"])
    args = global_vars.get_args()
    assert args.data_parallel_size == 4
    assert global_vars.get_num_microbatches() == 1
    assert global_vars.get_current_global_batch_size() == 8
    timers = global_vars.get_timers()
    timers("step").start()
    timers("step").stop()
    # double init asserts (reference _ensure_var_is_not_initialized)
    with pytest.raises(AssertionError):
        global_vars.set_global_variables(args=["--micro-batch-size", "1"])
    global_vars.destroy_global_vars()
