"""Megatron arguments + global_vars tests (reference
apex/transformer/testing/arguments.py:23-280, global_vars.py:34-270)."""

import jax.numpy as jnp
import pytest

from apex_tpu.transformer.testing import arguments, global_vars


def _parse(argv, **kw):
    return arguments.parse_args(args=argv, **kw)


def test_parallel_size_derivation():
    args = _parse(["--world-size", "16", "--micro-batch-size", "2",
                   "--tensor-model-parallel-size", "4",
                   "--pipeline-model-parallel-size", "2",
                   "--num-attention-heads", "4", "--hidden-size", "64"])
    assert args.data_parallel_size == 2
    assert args.global_batch_size == 2 * 2  # micro * dp


def test_world_size_divisibility_enforced():
    with pytest.raises(AssertionError):
        _parse(["--world-size", "6", "--micro-batch-size", "1",
                "--tensor-model-parallel-size", "4",
                "--num-attention-heads", "4", "--hidden-size", "64"])


def test_virtual_pipeline_derivation():
    args = _parse(["--world-size", "8", "--micro-batch-size", "1",
                   "--pipeline-model-parallel-size", "4",
                   "--num-layers", "16",
                   "--num-layers-per-virtual-pipeline-stage", "2",
                   "--num-attention-heads", "4", "--hidden-size", "64"])
    # (16 layers / 4 stages) / 2 per chunk = 2 virtual chunks
    assert args.virtual_pipeline_model_parallel_size == 2


def test_bf16_forces_fp32_grad_accum():
    args = _parse(["--world-size", "1", "--micro-batch-size", "1", "--bf16",
                   "--num-attention-heads", "4", "--hidden-size", "64"])
    assert args.params_dtype == jnp.bfloat16
    assert args.accumulate_allreduce_grads_in_fp32
    with pytest.raises(AssertionError):
        _parse(["--world-size", "1", "--micro-batch-size", "1", "--bf16",
                "--fp16", "--num-attention-heads", "4", "--hidden-size", "64"])


def test_defaults_fill_only_unset():
    args = _parse(["--world-size", "1", "--micro-batch-size", "1",
                   "--num-attention-heads", "4", "--hidden-size", "64"],
                  defaults={"seq_length": 512, "hidden_size": 9999})
    assert args.seq_length == 512       # was None -> filled
    assert args.hidden_size == 64       # explicitly set -> kept


def test_derived_network_sizes():
    args = _parse(["--world-size", "1", "--micro-batch-size", "1",
                   "--num-attention-heads", "4", "--hidden-size", "64"])
    assert args.ffn_hidden_size == 256
    assert args.kv_channels == 16


def test_global_vars_lifecycle():
    global_vars.destroy_global_vars()
    with pytest.raises(AssertionError):
        global_vars.get_args()
    global_vars.set_global_variables(args=[
        "--world-size", "4", "--micro-batch-size", "2",
        "--num-attention-heads", "4", "--hidden-size", "64"])
    args = global_vars.get_args()
    assert args.data_parallel_size == 4
    assert global_vars.get_num_microbatches() == 1
    assert global_vars.get_current_global_batch_size() == 8
    timers = global_vars.get_timers()
    timers("step").start()
    timers("step").stop()
    # double init asserts (reference _ensure_var_is_not_initialized)
    with pytest.raises(AssertionError):
        global_vars.set_global_variables(args=["--micro-batch-size", "1"])
    global_vars.destroy_global_vars()
