"""Weight-norm reparameterization tests (reference
apex/reparameterization/weight_norm.py; torch.nn.utils.weight_norm is the
numerical reference, as it is for the reference's fused kernel)."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from apex_tpu.reparameterization import (
    apply_weight_norm,
    compute_weights,
    remove_weight_norm,
    weight_norm,
)


def test_matches_torch_weight_norm():
    torch.manual_seed(0)
    lin = torch.nn.Linear(6, 4)
    w0 = lin.weight.detach().numpy().copy()
    lin_wn = torch.nn.utils.weight_norm(lin)  # dim=0
    want = lin_wn.weight.detach().numpy()

    params = apply_weight_norm({"weight": jnp.asarray(w0)}, dim=0)
    got = compute_weights(params, dim=0)["weight"]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6, atol=1e-6)


def test_round_trip_identity():
    w = jax.random.normal(jax.random.PRNGKey(0), (5, 3, 3, 8))
    for dim in (0, 3, None):
        p = apply_weight_norm({"w": w}, dim=dim if dim is not None else 0)
        if dim is None:
            p = {"w": {"g": jnp.sqrt(jnp.sum(w * w)), "v": w}}
            back = weight_norm(p["w"]["v"], p["w"]["g"], None)
        else:
            back = compute_weights(p, dim=dim)["w"]
        np.testing.assert_allclose(np.asarray(back), np.asarray(w),
                                   rtol=1e-5, atol=1e-6)


def test_g_controls_magnitude():
    w = jax.random.normal(jax.random.PRNGKey(1), (4, 7))
    p = apply_weight_norm({"w": w}, dim=0)
    p["w"]["g"] = p["w"]["g"] * 2.0
    out = compute_weights(p)["w"]
    norms = jnp.sqrt(jnp.sum(out.astype(jnp.float32) ** 2, axis=1, keepdims=True))
    np.testing.assert_allclose(np.asarray(norms), np.asarray(p["w"]["g"]),
                               rtol=1e-5)


def test_name_filter_and_remove():
    params = {"dense": {"weight": jnp.ones((3, 3)), "bias": jnp.ones((3,))},
              "embed": {"table": jnp.ones((5, 3))}}
    p = apply_weight_norm(params, name="weight")
    assert set(p["dense"]["weight"].keys()) == {"g", "v"}
    assert isinstance(p["embed"]["table"], jnp.ndarray)  # not matched
    back = remove_weight_norm(p)
    np.testing.assert_allclose(np.asarray(back["dense"]["weight"]),
                               np.ones((3, 3)), rtol=1e-6)


def test_dim_mismatch_raises():
    import pytest
    w = jax.random.normal(jax.random.PRNGKey(4), (4, 7))
    p = apply_weight_norm({"w": w}, dim=1)
    with pytest.raises(ValueError):
        compute_weights(p, dim=0)
    # matching dim works
    back = compute_weights(p, dim=1)["w"]
    np.testing.assert_allclose(np.asarray(back), np.asarray(w), rtol=1e-5)


def test_gradients_decouple():
    """d/dg and d/dv are the decoupled directions weight norm exists for:
    grad wrt v is orthogonal to v (per output row)."""
    w = jax.random.normal(jax.random.PRNGKey(2), (4, 6))
    p = apply_weight_norm({"w": w}, dim=0)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 6))

    def loss(p):
        wmat = compute_weights(p)["w"]
        return jnp.sum((x @ wmat.T) ** 2)

    g = jax.grad(loss)(p)
    dot = jnp.sum(g["w"]["v"] * p["w"]["v"], axis=1)
    np.testing.assert_allclose(np.asarray(dot), np.zeros(4), atol=1e-4)
