"""Speculative decoding + chunked prefill tier (ISSUE 12).

THE acceptance pin lives here: greedy speculative decoding (n-gram
proposer, verify-accept at ``q_len = k + 1``, chunked prefill)
produces token streams BITWISE identical to non-speculative greedy
decoding over the seeded Poisson trace — including preemption
mid-draft and chunked-prefill requests — because exact greedy
acceptance commits only tokens the model's own argmax endorses
(docs/serving.md "Speculative decoding").  Speculation may only
change how many tokens commit per boundary, never which tokens.
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu.ops import routing_override
from apex_tpu.serving import (ServingEngine, ServingModelConfig, SimClock,
                              SpecConfig, init_params, poisson_trace)
from apex_tpu.serving.spec import NgramProposer, Proposer, commit_tokens

pytestmark = pytest.mark.serving

CFG = ServingModelConfig(vocab_size=64, hidden_size=32, num_heads=4,
                         num_layers=2, max_position=96)


@pytest.fixture(scope="module")
def serving_params():
    return init_params(CFG, seed=0)


def _engine(params, spec=None, **kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_budget", CFG.max_position)
    kw.setdefault("clock", SimClock())
    return ServingEngine(CFG, params, spec=spec, **kw)


def _trace(seed=3, n=6, **kw):
    kw.setdefault("rate", 2.0)
    kw.setdefault("prompt_len", (4, 10))
    kw.setdefault("max_new", (3, 12))
    kw.setdefault("vocab_size", CFG.vocab_size)
    return poisson_trace(seed, n, **kw)


def _long_trace(seed=7, n=6, **kw):
    """Prompts long enough that chunk_size=16 splits them."""
    kw.setdefault("prompt_len", (20, 60))
    kw.setdefault("max_new", (3, 10))
    return _trace(seed, n, **kw)


def _streams(trace):
    return {r.rid: list(r.generated) for r in trace}


@pytest.fixture(scope="module")
def control_tokens(serving_params):
    """Non-speculative greedy streams for the shared trace shapes."""
    out = {}
    for name, mk in (("short", _trace), ("long", _long_trace)):
        tr = mk()
        _engine(serving_params).serve(tr)
        out[name] = _streams(tr)
    return out


# ---------------------------------------------------------------------------
# NgramProposer: suffix-cache lookup mechanics (pure host-side)
# ---------------------------------------------------------------------------


class TestNgramProposer:
    def test_proposes_continuation_of_repeated_ngram(self):
        p = NgramProposer(ngram_n=2)
        # history ...[5, 6] 7 8 ... [5, 6] -> draft continues 7, 8
        assert p.propose(0, [1, 5, 6, 7, 8, 2, 5, 6], 2) == [7, 8]

    def test_periodic_history_unrolls_past_its_end(self):
        p = NgramProposer(ngram_n=2)
        # period-2 cycle: the continuation reads from the draft itself
        # once it runs off committed history
        assert p.propose(0, [9, 3, 4, 3, 4], 5) == [3, 4, 3, 4, 3]

    def test_no_match_means_empty_draft(self):
        p = NgramProposer(ngram_n=3)
        assert p.propose(0, [1, 2, 3, 4, 5], 4) == []
        assert p.propose(0, [1, 1], 0) == []          # k = 0
        assert p.propose(0, [1], 4) == []             # too short

    def test_longest_ngram_wins_over_shorter(self):
        p = NgramProposer(ngram_n=2)
        # 1-gram [6] occurred at position 1 (-> 9), but the 2-gram
        # [5, 6] match (-> 7) is the more specific prediction
        assert p.propose(0, [5, 6, 9, 0, 5, 6, 7, 1, 5, 6], 1) == [7]

    def test_incremental_index_matches_fresh_proposer(self):
        # the suffix cache is incremental per rid; feeding the history
        # token-by-token must propose exactly what a fresh proposer
        # sees on the full history (determinism witness)
        rng = np.random.RandomState(0)
        hist = [int(t) for t in rng.randint(0, 8, 40)]
        inc = NgramProposer(ngram_n=3)
        for i in range(4, len(hist) + 1):
            got = inc.propose(0, hist[:i], 4)
            fresh = NgramProposer(ngram_n=3).propose(1, hist[:i], 4)
            assert got == fresh, i

    def test_release_and_shrunk_history_reset_state(self):
        p = NgramProposer(ngram_n=2)
        p.propose(0, [1, 2, 3, 1, 2], 2)
        p.release(0)
        assert p._index.get(0) is None
        # a rid reused with a SHORTER history (fresh engine, shared
        # proposer) must not propose phantom tokens from stale grams
        p.propose(1, [4, 5, 6, 7, 8, 9], 2)
        assert p.propose(1, [4, 5], 2) == []

    def test_rid_reuse_one_token_shorter_resets_not_crashes(self):
        # review regression: history shrunk by EXACTLY one token left
        # the old `done > len` guard asleep, and a stale gram whose
        # continuation start == the new length crashed the unroll with
        # IndexError on an empty draft list
        p = NgramProposer(ngram_n=2)
        p.propose(1, [1, 2, 3, 1, 2], 2)     # indexes up to done=4
        assert p.propose(1, [9, 9, 3, 1], 2) in ([], [2])  # no crash
        # same-length different-content reuse resets via the tail probe
        p2 = NgramProposer(ngram_n=2)
        p2.propose(2, [1, 2, 3, 1, 2], 2)
        got = p2.propose(2, [7, 8, 9, 7, 8], 2)
        assert got == [9, 7]   # fresh index of the NEW history only

    def test_protocol_conformance(self):
        assert isinstance(NgramProposer(), Proposer)


class TestEmptyWindowContract:
    def test_kv_len_shorter_than_window_is_exact_zeros(self):
        """The relaxed flash_decode contract the verify/chunk paths
        rely on: a row whose whole sequence is shorter than the fixed
        q window (kv_len < q_len) must return exact zeros for the
        empty-window rows and correct values for the real tail rows —
        on BOTH routes."""
        from apex_tpu.ops import flash_decode

        rng = np.random.RandomState(0)
        ps, h, d, q_len = 8, 2, 8, 5
        k_pages = jnp.asarray(rng.randn(4, ps, h, d).astype(np.float32))
        v_pages = jnp.asarray(rng.randn(4, ps, h, d).astype(np.float32))
        q = jnp.asarray(rng.randn(1, h, q_len, d).astype(np.float32))
        pt = jnp.asarray(np.array([[1, 2]], np.int32))
        kv = jnp.asarray(np.array([3], np.int32))   # < q_len
        outs = {}
        for route in ("xla", "decode"):
            with routing_override(decode=route):
                outs[route] = np.asarray(
                    flash_decode(q, k_pages, v_pages, pt, kv))
        for route, out in outs.items():
            assert np.all(np.isfinite(out)), route
            # rows 0..1 have empty causal windows (3 - 5 + i < 0)
            assert np.all(out[0, :, :2, :] == 0.0), route
            # rows 2..4 attend over 1..3 real columns — nonzero
            assert np.all(np.any(out[0, :, 2:, :] != 0.0, axis=-1)), route
        np.testing.assert_allclose(outs["decode"], outs["xla"],
                                   rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# commit_tokens: the exact-acceptance rule (pure policy)
# ---------------------------------------------------------------------------


class TestCommitTokens:
    def test_full_accept_commits_draft_plus_bonus(self):
        out, n_kv, a = commit_tokens([7, 8, 9], [7, 8, 9, 4],
                                     eos_id=None, remaining=10)
        assert out == [7, 8, 9, 4] and n_kv == 3 and a == 3

    def test_partial_accept_takes_bonus_from_divergence_row(self):
        # model agreed on d1, diverged at d2: commit d1 + the model's
        # own token at that position
        out, n_kv, a = commit_tokens([7, 8, 9], [7, 5, 9, 4],
                                     eos_id=None, remaining=10)
        assert out == [7, 5] and n_kv == 1 and a == 1

    def test_zero_accept_is_a_plain_decode_step(self):
        out, n_kv, a = commit_tokens([7, 8], [3, 8, 9],
                                     eos_id=None, remaining=10)
        assert out == [3] and n_kv == 0 and a == 0
        # and an empty draft commits exactly the argmax
        out, n_kv, a = commit_tokens([], [6], eos_id=None, remaining=10)
        assert out == [6] and n_kv == 0 and a == 0

    def test_eos_truncates_mid_commit(self):
        # d1 = eos: the stream ends there, accepted tail discarded
        out, n_kv, a = commit_tokens([5, 8, 9], [5, 8, 9, 4],
                                     eos_id=5, remaining=10)
        assert out == [5] and n_kv == 1 and a == 3

    def test_remaining_budget_truncates_mid_commit(self):
        out, n_kv, a = commit_tokens([7, 8, 9], [7, 8, 9, 4],
                                     eos_id=None, remaining=2)
        assert out == [7, 8] and n_kv == 2 and a == 3

    def test_row_count_mismatch_raises(self):
        with pytest.raises(ValueError, match="argmax rows"):
            commit_tokens([7, 8], [7], eos_id=None, remaining=5)
        with pytest.raises(ValueError, match="budget"):
            commit_tokens([7], [7, 8], eos_id=None, remaining=0)


# ---------------------------------------------------------------------------
# THE acceptance pin: bitwise streams, spec vs non-spec
# ---------------------------------------------------------------------------


class TestBitwiseContract:
    def test_speculative_streams_bitwise_match_plain_greedy(
            self, serving_params, control_tokens):
        tr = _trace()
        eng = _engine(serving_params, spec=SpecConfig(k=4))
        eng.serve(tr)
        assert _streams(tr) == control_tokens["short"]
        # the trace must actually have speculated (not vacuous)
        assert eng.proposer.drafted > 0
        assert eng.proposer.accepted > 0

    def test_chunked_prefill_streams_bitwise_match(
            self, serving_params, control_tokens):
        tr = _long_trace()
        eng = _engine(serving_params, spec=SpecConfig(k=0, chunk_size=16))
        eng.serve(tr)
        assert _streams(tr) == control_tokens["long"]

    def test_spec_plus_chunked_streams_bitwise_match(
            self, serving_params, control_tokens):
        tr = _long_trace()
        eng = _engine(serving_params,
                      spec=SpecConfig(k=3, chunk_size=16))
        eng.serve(tr)
        assert _streams(tr) == control_tokens["long"]
        assert eng.proposer.drafted > 0

    def test_preemption_mid_draft_is_output_invisible(
            self, serving_params, control_tokens):
        # a pool tight enough to preempt while speculation is live:
        # evicted drafts are simply dropped (proposer state is derived
        # from committed tokens), streams stay bitwise
        tr = _trace()
        eng = _engine(serving_params, spec=SpecConfig(k=4),
                      num_pages=7, max_pages_per_request=3)
        eng.serve(tr)
        assert sum(r.preemptions for r in eng.sched.finished) >= 1, (
            "tight pool was meant to force preemption")
        assert _streams(tr) == control_tokens["short"]
        assert eng.cache.pages_used == 0

    @pytest.mark.slow  # burst-arrival sweep (ISSUE 12 wall discipline;
    # the mid-draft preemption pin above stays in tier-1)
    def test_preemption_of_mid_chunk_request_restarts_cleanly(
            self, serving_params):
        # a BURST of long arrivals over a pool too small to hold them:
        # chunked prefills get evicted mid-chunk, restart from zero on
        # re-admission, and the streams still match the roomy
        # non-speculative control
        tr = _long_trace(rate=50.0)
        ctrl = _engine(serving_params)
        ctrl.serve(tr)
        control = _streams(tr)
        tr2 = _long_trace(rate=50.0)
        eng = _engine(serving_params,
                      spec=SpecConfig(k=3, chunk_size=16),
                      num_pages=13, max_pages_per_request=9)
        eng.serve(tr2)
        assert sum(r.preemptions for r in eng.sched.finished) >= 1, (
            "burst was meant to force preemption")
        assert _streams(tr2) == control
        assert eng.cache.pages_used == 0

    @pytest.mark.slow  # three full engine runs; the eos-truncation
    # RULE is pinned fast by TestCommitTokens::test_eos_truncates
    def test_eos_mid_commit_matches_plain_greedy(self, serving_params):
        # pick a token the model emits mid-stream and rerun with it as
        # EOS on BOTH engines: the speculative commit must truncate at
        # exactly the same position plain decoding stops at
        prompts = [[int(x) for x in
                    np.random.RandomState(100 + i).randint(
                        0, CFG.vocab_size, 5 + 3 * i)] for i in range(2)]

        def run(spec, eos):
            eng = _engine(serving_params, spec=spec, max_batch=2)
            reqs = [eng.submit(p, 12, eos_id=eos) for p in prompts]
            eng.run()
            return [list(r.generated) for r in reqs]

        free = run(None, None)
        eos = free[0][4]
        assert run(SpecConfig(k=4), eos) == run(None, eos)

    @pytest.mark.slow  # interpret-mode Pallas at q_len=k+1 (the PR 6
    # wall tier; the q_len>1 kernel parity sweep also covers this math)
    def test_decode_route_ab_identical_tokens_with_spec(
            self, serving_params):
        # the verify launch at q_len = k+1 through the Pallas decode
        # kernel (interpret mode) vs the XLA baseline: same tokens
        prompts = [[1, 5, 1, 5, 1], [7, 3, 7, 3, 7, 3]]

        def run():
            eng = _engine(serving_params, spec=SpecConfig(k=3),
                          max_batch=2, max_pages_per_request=2)
            reqs = [eng.submit(p, 6) for p in prompts]
            eng.run()
            return [list(r.generated) for r in reqs], eng

        xla_out, _ = run()
        with routing_override(decode="decode"):
            kern_out, eng = run()
        assert kern_out == xla_out
        assert eng.proposer.drafted > 0


# ---------------------------------------------------------------------------
# Rollback, fallback, and page accounting
# ---------------------------------------------------------------------------


class _FixedProposer:
    """Test double: propose a fixed draft for every request."""

    def __init__(self, draft):
        self.draft = list(draft)
        self.observed = []

    def propose(self, rid, context, k):
        return self.draft[:k]

    def observe(self, drafted, accepted):
        self.observed.append((drafted, accepted))

    def release(self, rid):
        pass


class _EmptyProposer(_FixedProposer):
    def __init__(self):
        super().__init__([])


class TestRollbackAndFallback:
    def test_rejected_draft_rolls_back_kv_len(self, serving_params):
        # a garbage draft is fully rejected: the boundary commits ONE
        # token (the bonus), kv_len advances only over the committed
        # prefix, and the pages grown for the draft return to the pool.
        # (One engine step = admit + prefill + a first decode boundary,
        # so the verify fires inside step #1.)
        bad = _FixedProposer([63, 62, 61, 60])
        eng = _engine(serving_params,
                      spec=SpecConfig(k=4, proposer=bad), page_size=4)
        req = eng.submit([1, 2, 3, 4, 5], 8)
        eng.step()
        # prefill sampled token 1, the verify boundary committed ONLY
        # the bonus (drafted, accepted) == (4, 0)
        assert bad.observed == [(4, 0)]
        assert len(req.generated) == 2
        # THE rollback pin: the verify wrote K/V for positions
        # [5, 9] (last token + 4 draft rows) but only the last
        # committed token's row stays — kv_len is back to the
        # pre-draft seq_len (the bonus's K/V appends next boundary,
        # the plain-decode contract)
        assert req.kv_len == 6
        # ...and the pages grown for the rejected rows went back
        assert len(req.pages) == eng.cache.pages_needed(req.seq_len)
        # the engine still finishes the request identically to a
        # proposer-free control
        eng.run()
        ctrl = _engine(serving_params, page_size=4)
        ctrl_req = ctrl.submit([1, 2, 3, 4, 5], 8)
        ctrl.run()
        assert list(req.generated) == list(ctrl_req.generated)
        assert eng.cache.pages_used == 0

    def test_empty_drafts_fall_back_to_plain_decode(self, serving_params):
        from apex_tpu import telemetry as tel

        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="fallback", sinks=[mem])
        eng = _engine(serving_params,
                      spec=SpecConfig(k=4, proposer=_EmptyProposer()),
                      telemetry=bus)
        tr = _trace()
        eng.serve(tr)
        steps = [e for e in mem.events if e["type"] == "decode_step"]
        assert steps and all("spec_verify" not in e for e in steps), (
            "empty drafts must take the plain q_len=1 decode executable")
        assert all(e["new_tokens"] == e["batch"] for e in steps)

    def test_draft_clamped_by_remaining_budget(self, serving_params):
        # a request one token from its budget must not overshoot
        # max_new_tokens however eagerly the proposer drafts
        greedy = _FixedProposer([1, 1, 1, 1])
        eng = _engine(serving_params,
                      spec=SpecConfig(k=4, proposer=greedy))
        req = eng.submit([2, 2, 2, 2], 2)
        eng.run()
        assert len(req.generated) == 2

    def test_spec_config_validates(self):
        with pytest.raises(ValueError, match="enables nothing"):
            SpecConfig(k=0)
        with pytest.raises(ValueError, match="k must be"):
            SpecConfig(k=-1)
        with pytest.raises(ValueError, match="chunk_size"):
            SpecConfig(k=2, chunk_size=0)

    def test_chunk_wider_than_prefill_budget_rejected(self, serving_params):
        with pytest.raises(ValueError, match="prefill "):
            _engine(serving_params,
                    spec=SpecConfig(k=0, chunk_size=CFG.max_position + 1))


# ---------------------------------------------------------------------------
# Chunked prefill: interleaving + scheduler policy
# ---------------------------------------------------------------------------


class TestChunkedPrefill:
    def test_long_prefill_interleaves_with_decode(self, serving_params):
        from apex_tpu import telemetry as tel

        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="interleave", sinks=[mem])
        eng = _engine(serving_params,
                      spec=SpecConfig(k=0, chunk_size=16),
                      telemetry=bus)
        short = eng.submit([1, 2, 3], 12)
        eng.step()                     # short admitted, decoding
        long_req = eng.submit(list(range(1, 61)), 4)
        eng.run()
        admits = {e["rid"]: e for e in mem.events
                  if e["type"] == "request_admit"}
        assert admits[long_req.rid].get("chunked") is True
        assert "chunked" not in admits[short.rid]
        # decode boundaries ran BETWEEN the long request's admission
        # and its first token — the 60-token prefill (4 chunks of 16)
        # never monopolized a boundary
        admit_step = admits[long_req.rid]["step"]
        first_tok_step = next(
            e["step"] for e in mem.events if e["type"] == "decode_step"
            and e["step"] >= admit_step)
        decode_between = [
            e for e in mem.events if e["type"] == "decode_step"
            and admit_step <= e["step"] < admit_step + 4]
        assert len(decode_between) >= 3, (
            "the short request must keep decoding under the long "
            "request's chunked prefill")
        assert first_tok_step is not None
        assert list(long_req.generated)  # and the long request finished

    def test_whole_row_path_used_at_or_under_chunk_size(
            self, serving_params):
        from apex_tpu import telemetry as tel

        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="wholerow", sinks=[mem])
        eng = _engine(serving_params, spec=SpecConfig(k=0, chunk_size=16),
                      telemetry=bus)
        req = eng.submit([1] * 16, 2)
        eng.step()
        # ctx == chunk_size: whole-row prefill (kv for the FULL context
        # lands in one launch and the admit event carries no chunked
        # flag), never chunked mode
        adm = next(e for e in mem.events if e["type"] == "request_admit")
        assert "chunked" not in adm
        assert req.prefill_pos is None and req.generated

    def test_admit_on_chunked_scheduler_refuses(self, serving_params):
        eng = _engine(serving_params, spec=SpecConfig(k=0, chunk_size=16))
        with pytest.raises(RuntimeError, match="schedule_prefill"):
            eng.sched.admit()

    def test_chunk_budget_caps_per_boundary_work(self, serving_params):
        # prefill_budget 20 / chunk 16: two long arrivals cannot both
        # launch a chunk in one boundary — a's first chunk consumes the
        # budget, so b's ADMISSION (which would launch its first chunk)
        # waits for the next boundary
        eng = _engine(serving_params, spec=SpecConfig(k=0, chunk_size=16),
                      prefill_budget=20, max_pages_per_request=6)
        a = eng.submit(list(range(1, 41)), 2)
        b = eng.submit(list(range(2, 42)), 2)
        eng.step()
        assert a.prefill_pos == 16               # one chunk advanced
        assert b.state == "waiting" and not b.pages
        eng.step()
        # in-flight chunks outrank admissions: a advances again, b
        # keeps waiting until a boundary has chunk_size budget free
        assert a.prefill_pos == 32
        assert b.state == "waiting"
        eng.run()
        assert len(a.generated) == 2 and len(b.generated) == 2

    def test_chunked_default_page_table_width_covers_max_position(
            self, serving_params):
        # review regression: with chunking on, the DEFAULT
        # max_pages_per_request must derive from max_position, not the
        # prefill row — the old default rejected the exact requests
        # chunking exists for, with a misleading pages error
        eng = _engine(serving_params, spec=SpecConfig(k=0, chunk_size=16),
                      prefill_budget=32)   # no explicit mppr
        req = eng.submit(list(range(1, 61)), 4)   # 64 > the 32-row
        eng.run()
        assert len(req.generated) == 4

    def test_restore_into_chunkless_engine_refuses_beyond_row_request(
            self, serving_params):
        # review regression: the restore() twin of recover()'s
        # chunk_size-preserving rebuild — a chunked snapshot holding a
        # beyond-the-row request must fail LOUDLY in a chunk-less
        # engine, not queue a request admission can never take
        src = _engine(serving_params, spec=SpecConfig(k=0, chunk_size=16),
                      prefill_budget=32)
        src.submit([7, 8, 9], 2)                  # servable anywhere
        src.submit(list(range(1, 61)), 4)         # beyond the row
        src.step()
        snap = json.loads(json.dumps(src.snapshot()))
        dst = _engine(serving_params, prefill_budget=32,
                      max_pages_per_request=10)
        with pytest.raises(ValueError, match="prefill budget"):
            dst.restore(snap)
        # ...and the refusal is ATOMIC: nothing was queued or retired,
        # so the engine is still fresh for a correctly-configured retry
        assert not dst.sched.waiting and not dst.sched.finished
        dst2 = _engine(serving_params, spec=SpecConfig(k=0, chunk_size=16),
                       prefill_budget=32)
        dst2.restore(snap)
        dst2.run()

    def test_chunked_request_may_exceed_the_prefill_row(
            self, serving_params):
        # THE point of chunking: with chunk_size set, prompt+max_new
        # may exceed the whole-row prefill budget (the request never
        # touches the row executable) — the same submit is rejected on
        # a row-only engine
        long_prompt = list(range(1, 61))
        row_only = _engine(serving_params, prefill_budget=32,
                           max_pages_per_request=9)
        with pytest.raises(ValueError, match="prefill budget"):
            row_only.submit(long_prompt, 4)
        eng = _engine(serving_params, spec=SpecConfig(k=0, chunk_size=16),
                      prefill_budget=32, max_pages_per_request=9)
        req = eng.submit(long_prompt, 4)
        eng.run()
        # and the stream matches a roomy whole-row control
        ctrl = _engine(serving_params, max_pages_per_request=9)
        ctrl_req = ctrl.submit(long_prompt, 4)
        ctrl.run()
        assert list(req.generated) == list(ctrl_req.generated)


# ---------------------------------------------------------------------------
# Snapshot/restore: in-flight chunk + draft state round trip
# ---------------------------------------------------------------------------


class TestSnapshotRestore:
    @pytest.mark.parametrize("cut", [
        1, 3,
        # the deeper cut points replay most of the trace each — slow
        # tier (nightly), the early boundaries stay in tier-1
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(5, marks=pytest.mark.slow),
        pytest.param(8, marks=pytest.mark.slow),
    ])
    def test_round_trip_mid_chunk_and_mid_draft(
            self, serving_params, control_tokens, cut):
        """Snapshot a spec+chunked engine at boundary ``cut`` — with
        requests mid-chunk and drafts in flight — restore into a
        fresh spec engine with a sentinel-poisoned pool, continue:
        streams bitwise the non-speculative control.  Chunk cursors
        and drafts are deliberately NOT in the snapshot: both rebuild
        deterministically from committed tokens, exactly like KV."""
        spec = SpecConfig(k=3, chunk_size=16)
        src = _engine(serving_params, spec=spec)
        tr = _long_trace()
        for r in tr:
            src.submit_request(r)
        for _ in range(cut):
            if src.sched.idle:
                break
            src.step()
        snap = json.loads(json.dumps(src.snapshot()))  # serializability
        dst = _engine(serving_params, spec=SpecConfig(k=3, chunk_size=16))
        dst.cache.k = jnp.full_like(dst.cache.k, 1e3)
        dst.cache.v = jnp.full_like(dst.cache.v, 1e3)
        restored = dst.restore(snap)
        dst.run()
        assert restored
        for r in restored:
            assert list(r.generated) == control_tokens["long"][r.rid], (
                cut, r.rid)

    def test_recover_keeps_chunking_for_beyond_row_requests(
            self, serving_params):
        """Review regression: recover() must rebuild the scheduler
        WITH chunk_size — a chunk-less rebuild could never re-admit a
        request whose context exceeds the prefill row (legal on a
        chunked engine), and FIFO first-failure-stops admission would
        then starve everything behind it forever."""
        from apex_tpu.resilience import chaos

        eng = _engine(serving_params, spec=SpecConfig(k=2, chunk_size=16),
                      prefill_budget=32, max_pages_per_request=10)
        ctrl = _engine(serving_params, spec=SpecConfig(k=2, chunk_size=16),
                       prefill_budget=32, max_pages_per_request=10)
        long_prompt = list(range(1, 61))       # 60 + 4 > the 32-row
        c = ctrl.submit(long_prompt, 4)
        ctrl.run()
        with chaos.ServingDeviceLoss(at_step=1, device_ids=[0]) as dl:
            req = eng.submit(long_prompt, 4)
            behind = eng.submit([1, 2, 3], 2)
            eng.run()
        assert dl.fired and eng.recoveries == 1
        assert eng.sched.chunk_size == 16      # chunking survived
        assert list(req.generated) == list(c.generated)
        assert len(behind.generated) == 2      # nothing starved

    def test_timeout_retirement_releases_proposer_state(
            self, serving_params):
        # review regression: a deadline death is a retirement too —
        # the expire path must drop the rid's suffix cache like
        # retire_finished does
        eng = _engine(serving_params, spec=SpecConfig(k=2),
                      clock=SimClock(1.0))
        req = eng.submit([5, 6, 5, 6, 5], 30, deadline_s=3.0)
        for _ in range(6):
            eng.step()
        assert req.finish_reason == "timeout"
        assert req.rid not in eng.proposer._index

    def test_context_is_memoized_until_tokens_commit(self):
        from apex_tpu.serving import Request

        r = Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4)
        a = r.context
        assert r.context is a          # frozen history: same list,
        r.generated.append(7)          # no per-access copy
        b = r.context
        assert b is not a and b == [1, 2, 3, 7]

    def test_recovery_path_retirement_releases_proposer_state(
            self, serving_params):
        # review regression: a request finished through the recovery
        # path (_finish_restored) must drop its suffix-cache entry
        # like any other retirement
        eng = _engine(serving_params, spec=SpecConfig(k=2))
        req = eng.submit([5, 6, 5, 6, 5], 3)
        eng.run()
        assert req.rid not in eng.proposer._index
        eng2 = _engine(serving_params, spec=SpecConfig(k=2))
        r2 = eng2.submit([5, 6, 5, 6, 5], 3)
        # run to completion but capture BEFORE retirement, then finish
        # through the restore path
        while not r2.done:
            eng2.step()
        snap = eng2.snapshot()
        dst = _engine(serving_params, spec=SpecConfig(k=2))
        dst.proposer.propose(r2.rid, [1, 2, 1, 2], 2)  # seed rid state
        dst.restore(snap)                     # done request: finished
        assert r2.rid not in dst.proposer._index

    def test_corrupt_page_between_chunks_caught_and_recovered_bitwise(
            self, serving_params):
        """Review regression: the chunk step must run the CRC
        read-back like every other pool-reading step — a page
        corrupted between chunks must raise BEFORE the final chunk
        samples the first token from damaged K/V (which recovery's
        re-prefill-from-kept-tokens would then have preserved
        forever)."""
        from apex_tpu.resilience.chaos import corrupt_page

        ctrl = _engine(serving_params, spec=SpecConfig(k=0, chunk_size=16))
        c = ctrl.submit(list(range(1, 61)), 4)
        ctrl.run()
        eng = _engine(serving_params, spec=SpecConfig(k=0, chunk_size=16),
                      validate_pages=True)
        req = eng.submit(list(range(1, 61)), 4)
        eng.step()                       # chunk 1 filled its pages
        assert req.prefill_pos == 16 and not req.generated
        corrupt_page(eng.cache, req.pages[0])
        eng.run()                        # chunk 2's read-back catches it
        assert eng.recoveries == 1
        assert list(req.generated) == list(c.generated)

    def test_recover_mid_trace_stays_bitwise(self, serving_params,
                                             control_tokens):
        # the in-process twin: a device loss mid-speculative-decode
        # rebuilds the pool and the streams still match the control
        from apex_tpu.resilience import chaos

        tr = _long_trace()
        with chaos.ServingDeviceLoss(at_step=3, device_ids=[0]) as dl:
            eng = _engine(serving_params,
                          spec=SpecConfig(k=3, chunk_size=16))
            eng.serve(tr)
        assert dl.fired and eng.recoveries == 1
        assert _streams(tr) == control_tokens["long"]


# ---------------------------------------------------------------------------
# Telemetry: spec_verify fields, accepted-tokens-per-step, schema
# ---------------------------------------------------------------------------


class TestSpecTelemetry:
    def test_stream_validates_and_carries_spec_fields(
            self, serving_params, tmp_path):
        from apex_tpu import telemetry as tel
        from apex_tpu.telemetry.__main__ import main as tel_cli

        path = str(tmp_path / "spec.jsonl")
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="spec-l0",
                               sinks=[tel.JsonlSink(path), mem])
        eng = _engine(serving_params, spec=SpecConfig(k=4, chunk_size=16),
                      telemetry=bus)
        eng.serve(_long_trace())
        bus.close()
        for ev in mem.events:
            tel.validate_event(ev)
        assert tel_cli(["validate", path]) == 0
        verify_steps = [e for e in mem.events
                        if e["type"] == "decode_step"
                        and e.get("spec_verify")]
        assert verify_steps, "the trace was meant to speculate"
        for e in verify_steps:
            assert e["spec_verify"] is True
            assert e["spec_drafted"] >= 1
            assert 0 <= e["spec_accepted"] <= e["spec_drafted"]
            assert e["new_tokens"] >= e["batch"]  # bonus per row, minimum

    def test_summarize_reports_accepted_tokens_per_step(
            self, serving_params, tmp_path):
        from apex_tpu import telemetry as tel

        path = str(tmp_path / "spec_sum.jsonl")
        bus = tel.TelemetryBus(run_id="spec-sum",
                               sinks=[tel.JsonlSink(path)])
        eng = _engine(serving_params, spec=SpecConfig(k=4), telemetry=bus)
        eng.serve(_trace())
        bus.close()
        s = tel.summarize_file(path)
        acc = s["serving_accepted_tokens_per_step"]
        assert acc is not None and acc > 1.0, acc
        assert 0.0 < s["serving_spec_accept_rate"] <= 1.0
        out = tel.format_summary(s)
        assert "tok/step" in out and "spec accept" in out
        # ...and the diff table grows the acc-tok/step row
        assert "acc tok/step" in tel.format_diff(s, s)

    def test_plain_stream_reports_exactly_one(self, serving_params,
                                              tmp_path):
        from apex_tpu import telemetry as tel

        path = str(tmp_path / "plain.jsonl")
        bus = tel.TelemetryBus(run_id="plain", sinks=[tel.JsonlSink(path)])
        _engine(serving_params, telemetry=bus).serve(_trace())
        bus.close()
        s = tel.summarize_file(path)
        assert s["serving_accepted_tokens_per_step"] == 1.0
        assert "serving_spec_accept_rate" not in s

    def test_spec_fields_schema_discipline(self):
        from apex_tpu.telemetry import validate_event
        from apex_tpu.telemetry.schema import SchemaError

        def stamp(**payload):
            ev = {"type": "decode_step", "run_id": "r", "step": 0,
                  "t": 0.0, "ts": 0.0, "mesh": {},
                  "batch": 2, "new_tokens": 5, "pool_used": 1,
                  "pool_pages": 8}
            ev.update(payload)
            return ev

        validate_event(stamp(spec_verify=True, spec_drafted=4,
                             spec_accepted=3))
        validate_event(stamp())     # optional means absent is fine
        with pytest.raises(SchemaError, match="spec_verify"):
            validate_event(stamp(spec_verify=1))    # bool-not-int
        with pytest.raises(SchemaError, match="spec_drafted"):
            validate_event(stamp(spec_drafted=True))  # int-not-bool
        # request_admit's chunked flag is a real bool too
        adm = {"type": "request_admit", "run_id": "r", "step": 0,
               "t": 0.0, "ts": 0.0, "mesh": {}, "rid": 1,
               "context_tokens": 4, "pages": 1, "preemptions": 0}
        validate_event(dict(adm, chunked=True))
        with pytest.raises(SchemaError, match="chunked"):
            validate_event(dict(adm, chunked=1))
