"""Serving fleet tier (ISSUE 16): SLO-aware routing over N replicas,
fencing + live migration, rolling restarts, fleet chaos, and the
autoscaling signal.

THE acceptance pin lives here: a replica killed mid-decode past every
recovery budget is fenced and its live requests migrate onto healthy
peers with token streams bitwise identical to an unmigrated
single-engine control — zero requests dropped.  The migration path
must also be zero-compile on the receiving replicas (their warmup
already built the executable set).
"""

import json
import random

import pytest

import apex_tpu.telemetry as tel
from apex_tpu.analysis import hot_path_guard
from apex_tpu.resilience.chaos import (BlackholeReplica, KillReplica,
                                       SlowReplica)
from apex_tpu.serving import (ServingEngine, ServingModelConfig, SimClock,
                              SpecConfig, init_params)
from apex_tpu.serving.fleet import (FENCED, FleetCapacityError, FleetRouter,
                                    HealthCheckTimeout, ReplicaProxy,
                                    SLOClass, rolling_restart, scale_hint,
                                    scale_hint_from_events)

pytestmark = [pytest.mark.serving, pytest.mark.fleet]

CFG = ServingModelConfig(vocab_size=64, hidden_size=32, num_heads=4,
                         num_layers=2, max_position=96)


@pytest.fixture(scope="module")
def serving_params():
    return init_params(CFG, seed=0)


def _factory(params, clock, **kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_budget", CFG.max_position)
    kw.setdefault("max_queue", 16)

    def build():
        return ServingEngine(CFG, params, clock=clock, **kw)

    return build


def _fleet(params, n=2, *, telemetry=None, clock=None, factory_kw=None,
           **router_kw):
    clock = clock if clock is not None else SimClock()
    reps = [ReplicaProxy(f"r{i}", _factory(params, clock,
                                           **(factory_kw or {})))
            for i in range(n)]
    return FleetRouter(reps, telemetry=telemetry, **router_kw), reps


def _prompts(n, seed=0, lo=4, hi=10):
    rng = random.Random(seed)
    return [[rng.randrange(1, CFG.vocab_size)
             for _ in range(rng.randrange(lo, hi))] for _ in range(n)]


def _control_streams(params, prompts, max_new=5, **kw):
    """Uninterrupted single-engine control: same prompts in the same
    submit order on one plain engine."""
    eng = _factory(params, SimClock(), **kw)()
    eng.warmup()
    for p in prompts:
        eng.submit(list(p), max_new_tokens=max_new)
    eng.run()
    return {r.rid: list(r.generated) for r in eng.sched.finished}


# ---------------------------------------------------------------------------
# Routing and SLO classes
# ---------------------------------------------------------------------------


class TestRouting:
    def test_least_loaded_placement_spreads(self, serving_params):
        fleet, reps = _fleet(serving_params, n=3)
        fleet.warmup()
        for p in _prompts(6):
            fleet.submit(p, max_new_tokens=3)
        depths = sorted(r.queue_depth() for r in reps)
        assert depths == [2, 2, 2]
        fleet.run()
        assert all(len(fleet.handles[r].generated) == 3 for r in range(6))

    def test_slo_class_assigns_deadline(self, serving_params):
        fleet, _ = _fleet(
            serving_params,
            slo_classes=[SLOClass("gold", deadline_s=30.0),
                         SLOClass("best_effort")])
        fleet.warmup()
        rid_g = fleet.submit([1, 2, 3], max_new_tokens=2, slo="gold")
        rid_b = fleet.submit([1, 2, 3], max_new_tokens=2, slo="best_effort")
        assert fleet.handles[rid_g].deadline_s == 30.0
        assert fleet.handles[rid_b].deadline_s is None

    def test_unknown_slo_class_raises(self, serving_params):
        fleet, _ = _fleet(serving_params)
        with pytest.raises(ValueError, match="unknown SLO class"):
            fleet.submit([1], max_new_tokens=1, slo="platinum")

    def test_all_queues_full_rejects_loudly(self, serving_params):
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="full", sinks=[mem])
        fleet, reps = _fleet(serving_params, n=2, telemetry=bus,
                             factory_kw={"max_queue": 1,
                                         "telemetry": bus})
        # no warmup/stepping: fill both bounded queues, then overflow
        for p in _prompts(3, seed=1):
            fleet.submit(p, max_new_tokens=2)
        rejected = [r for r in fleet.handles.values()
                    if r.finish_reason == "rejected"]
        assert len(rejected) == 1
        evs = [e for e in mem.events if e["type"] == "request_reject"]
        assert len(evs) == 1 and evs[0]["reason"] == "queue_full"

    def test_fenced_replicas_never_take_placement(self, serving_params):
        fleet, reps = _fleet(serving_params, n=2)
        reps[0].fence()
        for p in _prompts(4, seed=2):
            fleet.submit(p, max_new_tokens=2)
        assert reps[0].queue_depth() == 0
        assert reps[1].queue_depth() == 4
        reps[1].fence()
        with pytest.raises(RuntimeError, match="no healthy replicas"):
            fleet.submit([1], max_new_tokens=1)


# ---------------------------------------------------------------------------
# request_reject reasons (ISSUE 16 satellite)
# ---------------------------------------------------------------------------


class TestRejectReasons:
    def test_unservable_rejects_as_data_when_opted_in(self, serving_params):
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="unserv", sinks=[mem])
        eng = _factory(serving_params, SimClock(),
                       telemetry=bus, reject_unservable=True)()
        req = eng.submit([1] * 10, max_new_tokens=CFG.max_position)
        assert req.finish_reason == "rejected"
        assert req in eng.rejected
        evs = [e for e in mem.events if e["type"] == "request_reject"]
        assert len(evs) == 1 and evs[0]["reason"] == "unservable"

    def test_unservable_still_raises_by_default(self, serving_params):
        eng = _factory(serving_params, SimClock())()
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit([1] * 10, max_new_tokens=CFG.max_position)

    def test_reason_enum_is_closed(self):
        ev = {"type": "request_reject", "run_id": "r", "step": 0, "t": 0.0,
              "ts": 0.0, "mesh": {}, "rid": 1, "reason": "felt_like_it",
              "queue_depth": 0}
        with pytest.raises(tel.schema.SchemaError, match="must be one of"):
            tel.validate_event(ev)


# ---------------------------------------------------------------------------
# serving_stall (ISSUE 16 satellite)
# ---------------------------------------------------------------------------


class TestServingStall:
    def test_budget_exhaustion_emits_and_raises(self, serving_params):
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="stall", sinks=[mem])
        eng = _factory(serving_params, SimClock(), telemetry=bus)()
        eng.warmup()
        eng.submit([1, 2, 3, 4], max_new_tokens=8)
        with pytest.raises(RuntimeError, match="did not drain"):
            eng.run(max_steps=1)
        evs = [e for e in mem.events if e["type"] == "serving_stall"]
        assert len(evs) == 1
        assert evs[0]["budget"] == 1
        assert evs[0]["waiting"] + evs[0]["running"] >= 1

    def test_raise_on_stall_false_returns_partial(self, serving_params):
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="stall2", sinks=[mem])
        eng = _factory(serving_params, SimClock(), telemetry=bus)()
        eng.warmup()
        eng.submit([1, 2, 3, 4], max_new_tokens=8)
        finished = eng.run(max_steps=1, raise_on_stall=False)
        assert finished == []                      # partial, not a lie
        assert [e["type"] for e in mem.events].count("serving_stall") == 1
        # the engine is still live: the budget was the only limit
        assert eng.run() and eng.sched.idle


# ---------------------------------------------------------------------------
# Heterogeneous snapshot/restore + adopt atomicity (ISSUE 16 satellite)
# ---------------------------------------------------------------------------


class TestHeterogeneousRestore:
    def _snapshot(self, params, n=5):
        src = _factory(params, SimClock(), max_queue=None)()
        for p in _prompts(n, seed=3):
            src.submit(p, max_new_tokens=4)
        return src.snapshot()

    def test_restore_into_smaller_max_queue_refused_atomically(
            self, serving_params):
        snap = self._snapshot(serving_params, n=5)
        tgt = _factory(serving_params, SimClock(), max_queue=2)()
        with pytest.raises(ValueError, match="max_queue"):
            tgt.restore(snap)
        # atomic: nothing queued, nothing retired, counters untouched
        assert not tgt.sched.waiting and not tgt.sched.running
        assert not tgt.sched.finished and tgt.steps == 0

    def test_restore_into_smaller_page_pool_refused_atomically(
            self, serving_params):
        src = _factory(serving_params, SimClock())()
        src.submit([1] * 40, max_new_tokens=20)    # needs 8 pages worst
        snap = src.snapshot()
        tgt = _factory(serving_params, SimClock(), num_pages=4)()
        with pytest.raises(ValueError, match="pages"):
            tgt.restore(snap)
        assert not tgt.sched.waiting and not tgt.sched.finished

    def test_adopt_merges_into_busy_engine(self, serving_params):
        snap = self._snapshot(serving_params, n=2)
        tgt = _factory(serving_params, SimClock())()
        tgt.warmup()
        own = tgt.submit([9] * 6, max_new_tokens=3)
        # rid 0 is taken by `own` — shift the incoming records into
        # free namespace (the router's global-rid job, done by hand)
        recs = json.loads(json.dumps(snap["requests"]))
        for i, r in enumerate(recs):
            r["rid"] = 100 + i
        adopted = tgt.adopt(recs)
        tgt.run()
        assert own.finish_reason is not None
        assert all(len(a.generated) == 4 for a in adopted)

    def test_adopt_refuses_rid_collision_atomically(self, serving_params):
        snap = self._snapshot(serving_params, n=2)
        tgt = _factory(serving_params, SimClock())()
        tgt.submit([9] * 6, max_new_tokens=3)      # takes rid 0
        recs = snap["requests"]
        assert recs[0]["rid"] == 0
        before = len(tgt.sched.waiting)
        with pytest.raises(ValueError, match="collides"):
            tgt.adopt(recs)
        assert len(tgt.sched.waiting) == before

    def test_adopt_refuses_past_queue_headroom_atomically(
            self, serving_params):
        snap = self._snapshot(serving_params, n=5)
        tgt = _factory(serving_params, SimClock(), max_queue=3)()
        with pytest.raises(ValueError, match="headroom"):
            tgt.adopt(snap["requests"])
        assert not tgt.sched.waiting


# ---------------------------------------------------------------------------
# Fence + migration: THE bitwise pin
# ---------------------------------------------------------------------------


class TestFenceAndMigrate:
    def test_killed_replica_fences_and_streams_stay_bitwise(
            self, serving_params):
        prompts = _prompts(6, seed=4)
        control = _control_streams(serving_params, prompts)
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="kill", sinks=[mem])
        fleet, reps = _fleet(serving_params, n=2, telemetry=bus,
                             fault_retries=2)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=5)
        with KillReplica("r0", at_step=3, telemetry=bus):
            with hot_path_guard("fleet migration", transfers=None) as g:
                fleet.run()
        # no compiles anywhere across fence + migration + drain: the
        # receiving replica's warmup already built every executable
        assert g.recompiles == 0 and g.syncs == []
        assert reps[0].state == FENCED
        # both budgets genuinely burned before the fence
        assert reps[0].engine.recoveries == reps[0].engine.max_recoveries
        assert reps[0].fault_attempts == fleet.fault_retries + 1
        fences = [e for e in mem.events if e["type"] == "replica_fence"]
        assert len(fences) == 1 and fences[0]["replica"] == "r0"
        assert fences[0]["cause"] == "DeviceLossError"
        moves = [e for e in mem.events if e["type"] == "request_migrate"]
        assert moves and all(m["from_replica"] == "r0"
                             and m["to_replica"] == "r1" for m in moves)
        # zero drops, every stream bitwise the control's
        assert len(fleet.handles) == len(prompts)
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"

    def test_last_replica_fence_refuses_loudly(self, serving_params):
        fleet, reps = _fleet(serving_params, n=1, fault_retries=0)
        fleet.warmup()
        fleet.submit([1, 2, 3, 4], max_new_tokens=4)
        with KillReplica("r0"):
            with pytest.raises(FleetCapacityError, match="no healthy"):
                fleet.run()

    @pytest.mark.slow
    def test_kill_at_every_boundary_sweep(self, serving_params):
        """The exhaustive form: kill r0 at every step index the
        healthy run ever reaches; every kill point must migrate to
        bitwise streams with zero drops."""
        prompts = _prompts(5, seed=5)
        control = _control_streams(serving_params, prompts)
        # measure the healthy run's step count once
        probe, _ = _fleet(serving_params, n=2)
        probe.warmup()
        for p in prompts:
            probe.submit(p, max_new_tokens=5)
        probe.run()
        total = max(r.engine.steps for r in probe.replicas)
        for at in range(1, total + 1):
            fleet, _ = _fleet(serving_params, n=2)
            fleet.warmup()
            for p in prompts:
                fleet.submit(p, max_new_tokens=5)
            with KillReplica("r0", at_step=at):
                fleet.run()
            for rid, toks in control.items():
                assert fleet.handles[rid].generated == toks, \
                    f"kill at {at}, rid {rid}"


# ---------------------------------------------------------------------------
# Health-check chaos: slow and blackholed replicas
# ---------------------------------------------------------------------------


class TestHealthChaos:
    def test_slow_replica_below_budget_is_tolerated(self, serving_params):
        fleet, reps = _fleet(serving_params, n=2, health_timeout_s=0.25)
        fleet.warmup()
        for p in _prompts(4, seed=6):
            fleet.submit(p, max_new_tokens=3)
        with SlowReplica("r0", latency_s=0.1):
            fleet.run()
        assert reps[0].state != FENCED
        assert all(h.finish_reason is not None or h.done
                   for h in fleet.handles.values())

    def test_slow_replica_past_budget_is_fenced(self, serving_params):
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="slowrep", sinks=[mem])
        fleet, reps = _fleet(serving_params, n=2, telemetry=bus,
                             health_timeout_s=0.25)
        fleet.warmup()
        prompts = _prompts(4, seed=7)
        control = _control_streams(serving_params, prompts, max_new=3)
        for p in prompts:
            fleet.submit(p, max_new_tokens=3)
        with SlowReplica("r0", latency_s=1.0):
            fleet.run()
        assert reps[0].state == FENCED
        fences = [e for e in mem.events if e["type"] == "replica_fence"]
        assert fences[0]["cause"] == "health_check_timeout"
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks

    def test_blackholed_replica_is_detected_not_waited_on(
            self, serving_params):
        fleet, reps = _fleet(serving_params, n=2)
        fleet.warmup()
        for p in _prompts(4, seed=8):
            fleet.submit(p, max_new_tokens=3)
        with BlackholeReplica("r0"):
            # bounded rounds: detection is virtual-latency, so a hang
            # here would be a router bug, not a slow test
            fleet.run(max_steps=500)
        assert reps[0].state == FENCED
        assert all(len(fleet.handles[r].generated) == 3
                   for r in fleet.handles)

    def test_ping_is_deterministic_and_sleepless(self, serving_params):
        rep = ReplicaProxy("solo", _factory(serving_params, SimClock()))
        assert rep.ping(0.25) == 0.0
        with BlackholeReplica("solo"):
            with pytest.raises(HealthCheckTimeout, match="inf"):
                rep.ping(0.25)


# ---------------------------------------------------------------------------
# Rolling restart
# ---------------------------------------------------------------------------


class TestRollingRestart:
    def test_rolling_restart_mid_serve_is_bitwise(self, serving_params):
        prompts = _prompts(6, seed=9)
        control = _control_streams(serving_params, prompts)
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="roll", sinks=[mem])
        fleet, reps = _fleet(serving_params, n=3, telemetry=bus)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=5)
        for _ in range(3):                          # some work in flight
            fleet.step()
        rolling_restart(fleet)          # restarted engines re-warm here
        with hot_path_guard("post-restart drain", transfers=None) as g:
            fleet.run()
        # every RECEIVING replica serves its adopted work compile- and
        # sync-free: the restart re-warmed the full executable set
        assert g.recompiles == 0 and g.syncs == []
        fences = [e for e in mem.events if e["type"] == "replica_fence"]
        assert [f["cause"] for f in fences] == ["rolling_restart"] * 3
        assert all(r.restarts == 1 and r.healthy for r in reps)
        assert len(fleet.handles) == len(prompts)
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"

    def test_fleet_of_one_readmits_its_own_snapshot(self, serving_params):
        prompts = _prompts(4, seed=10)
        control = _control_streams(serving_params, prompts)
        fleet, reps = _fleet(serving_params, n=1)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=5)
        for _ in range(2):
            fleet.step()
        rolling_restart(fleet)
        fleet.run()
        assert reps[0].restarts == 1
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks

    def test_restart_repairs_a_fenced_replica(self, serving_params):
        fleet, reps = _fleet(serving_params, n=2)
        fleet.warmup()
        for p in _prompts(4, seed=11):
            fleet.submit(p, max_new_tokens=3)
        with KillReplica("r0"):
            fleet.run()
        assert reps[0].state == FENCED
        rolling_restart(fleet)
        assert all(r.healthy for r in reps)
        # the repaired replica takes new work again
        fleet.submit([1, 2, 3], max_new_tokens=2)
        assert reps[0].queue_depth() + reps[0].running() == 1
        fleet.run()


# ---------------------------------------------------------------------------
# Speculative + chunked replicas through the same machinery
# ---------------------------------------------------------------------------


class TestSpecChunkedFleet:
    def test_migration_bitwise_with_spec_and_chunked(self, serving_params):
        """The tentpole cross-check at tier-1 scale (the MULTICHIP
        chaos_fleet leg runs the bigger version): spec+chunked
        replicas, kill one mid-decode, control is a PLAIN engine —
        valid because draft-verify and chunked prefill are
        output-invariant by their own acceptance pins."""
        prompts = _prompts(4, seed=12, lo=12, hi=24)
        control = _control_streams(serving_params, prompts, max_new=6)
        spec_kw = {"spec": SpecConfig(k=2, chunk_size=8)}
        fleet, reps = _fleet(serving_params, n=2, factory_kw=spec_kw)
        fleet.warmup()
        for p in prompts:
            fleet.submit(p, max_new_tokens=6)
        with KillReplica("r0", at_step=2):
            fleet.run()
        assert reps[0].state == FENCED
        for rid, toks in control.items():
            assert fleet.handles[rid].generated == toks, f"rid {rid}"


# ---------------------------------------------------------------------------
# Autoscaling signal
# ---------------------------------------------------------------------------


class TestScaleHint:
    def test_pure_thresholds(self):
        assert scale_hint(shed_rate=0.2, occupancy=0.1) == "scale_up"
        assert scale_hint(shed_rate=0.0, occupancy=0.9) == "scale_up"
        assert scale_hint(shed_rate=0.0, occupancy=0.5,
                          deadline_hit_rate=0.5) == "scale_up"
        assert scale_hint(shed_rate=0.0, occupancy=0.1) == "scale_down"
        assert scale_hint(shed_rate=0.0, occupancy=0.1,
                          deadline_hit_rate=1.0) == "scale_down"
        assert scale_hint(shed_rate=0.01, occupancy=0.5) == "hold"
        assert scale_hint(shed_rate=0.0, occupancy=0.5,
                          deadline_hit_rate=0.95) == "hold"

    def test_from_recorded_trace(self, serving_params):
        """The policy is replayable from a recorded stream alone —
        no live fleet needed."""
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="trace", sinks=[mem])
        eng = _factory(serving_params, SimClock(), telemetry=bus)()
        eng.warmup()
        for p in _prompts(4, seed=13):
            eng.submit(p, max_new_tokens=3)
        eng.run()
        assert scale_hint_from_events(mem.events) in (
            "scale_down", "hold")          # light load never scales up
        # synthetic overload trace: heavy shedding must scale up
        synth = [{"type": "request_reject"}] * 5 + \
                [{"type": "request_retire"}] * 5
        assert scale_hint_from_events(synth) == "scale_up"

    def test_router_emits_schema_valid_hint(self, serving_params):
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="hint", sinks=[mem])
        fleet, _ = _fleet(serving_params, n=2, telemetry=bus)
        fleet.warmup()
        for p in _prompts(3, seed=14):
            fleet.submit(p, max_new_tokens=2)
        fleet.run()
        hint = fleet.emit_scale_hint()
        evs = [e for e in mem.events if e["type"] == "fleet_scale_hint"]
        assert evs and evs[-1]["hint"] == hint
        for e in evs:
            tel.validate_event(e)


# ---------------------------------------------------------------------------
# Event schema pins
# ---------------------------------------------------------------------------


class TestFleetEventSchema:
    def _stamp(self, type_, **payload):
        ev = {"type": type_, "run_id": "r", "step": 0, "t": 0.0,
              "ts": 0.0, "mesh": {}}
        ev.update(payload)
        return ev

    def test_new_events_validate(self):
        tel.validate_event(self._stamp(
            "serving_stall", waiting=2, running=1, budget=100))
        tel.validate_event(self._stamp(
            "replica_fence", replica="r0", cause="DeviceLossError",
            live_requests=3, recoveries=3, fault_retries=2))
        tel.validate_event(self._stamp(
            "request_migrate", rid=7, from_replica="r0", to_replica="r1",
            tokens_done=4, was_running=True))
        tel.validate_event(self._stamp(
            "fleet_scale_hint", hint="hold", shed_rate=0.0, occupancy=0.4,
            replicas=3, healthy=3))

    def test_hint_enum_is_closed(self):
        with pytest.raises(tel.schema.SchemaError, match="must be one of"):
            tel.validate_event(self._stamp(
                "fleet_scale_hint", hint="buy_more_tpus", shed_rate=0.0,
                occupancy=0.4, replicas=3, healthy=3))

    def test_was_running_must_be_a_real_bool(self):
        with pytest.raises(tel.schema.SchemaError, match="bool"):
            tel.validate_event(self._stamp(
                "request_migrate", rid=7, from_replica="r0",
                to_replica="r1", tokens_done=4, was_running=1))
