"""Telemetry subsystem tests (ISSUE 4): bus/sinks/schema, goodput
accounting, flight-recorder postmortems on the SIGTERM grace path and
chaos device loss, guard/watchdog/timers integration, and the ≤1%
overhead bound.

Every event any test emits is run through the schema validator
(:func:`apex_tpu.telemetry.validate_event`) — the stream contract IS
the feature; an event a tool can't parse is a print with extra steps.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu import checkpoint as ckpt
from apex_tpu import resilience as res
from apex_tpu import telemetry as tele
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import chaos
from apex_tpu.transformer.testing import run_resilient_training


# ---------------------------------------------------------------- helpers


def _bus(tmp_path, run_id="t", **kw):
    """A bus with both a JSONL sink (the file contract) and a memory
    sink (easy assertions)."""
    mem = tele.MemorySink()
    bus = tele.TelemetryBus(
        run_id, sinks=[tele.JsonlSink(str(tmp_path / f"{run_id}.jsonl")),
                       mem], **kw)
    return bus, mem, str(tmp_path / f"{run_id}.jsonl")


def _toy_state():
    k = jax.random.PRNGKey(0)
    params = {"dense": {"w": jax.random.normal(k, (4, 4), jnp.float32),
                        "b": jnp.zeros((4,), jnp.float32)}}
    opt = FusedAdam(lr=1e-2)
    scaler = amp.initialize("O2").scaler
    state = ckpt.TrainState.create(params, opt.init(params), scaler.init())
    return state, opt, scaler


def _make_step_fn(opt, scaler):
    @jax.jit
    def train_step(state, xy):
        x, y = xy

        def loss(p):
            pred = x @ p["dense"]["w"] + p["dense"]["b"]
            return scaler.scale(jnp.mean((pred - y) ** 2),
                                state.scaler_state)

        grads = jax.grad(loss)(state.params)
        grads, finite = scaler.unscale(grads, state.scaler_state)
        new_p, new_o = opt.step_if_finite(grads, state.opt_state,
                                          state.params, finite)
        return state.replace(
            step=state.step + 1, params=new_p, opt_state=new_o,
            scaler_state=scaler.update(state.scaler_state, finite)), finite

    return lambda s, b: train_step(s, b)


def _batches(n, key=jax.random.PRNGKey(3)):
    out = []
    for i in range(n):
        k = jax.random.fold_in(key, i)
        out.append((jax.random.normal(k, (8, 4), jnp.float32),
                    jax.random.normal(jax.random.fold_in(k, 1), (8, 4),
                                      jnp.float32)))
    return out


def _postmortems(d):
    return sorted(str(p) for p in os.listdir(d)
                  if str(p).startswith("postmortem_"))


# ------------------------------------------------------------- bus core


def test_bus_stamps_counts_and_validates(tmp_path):
    bus, mem, path = _bus(tmp_path)
    bus.emit("run_start", step=0, config={"x": 1})
    bus.emit("step", step=1, step_ms=12.5)
    bus.emit("ckpt_save", step=1, blocking=False, wall_ms=3.0)
    bus.close()
    assert bus.counts == {"run_start": 1, "step": 1, "ckpt_save": 1}
    for ev in mem.events:
        tele.validate_event(ev)
        assert ev["run_id"] == "t"
        assert isinstance(ev["t"], float) and isinstance(ev["mesh"], dict)
    # the JSONL sink wrote the identical stream
    assert tele.validate_jsonl(path) == 3
    assert [e["type"] for e in tele.load_jsonl(path)] == [
        "run_start", "step", "ckpt_save"]


def test_bus_rejects_unknown_event_type(tmp_path):
    bus, _, _ = _bus(tmp_path)
    with pytest.raises(tele.TelemetryError, match="unknown event type"):
        bus.emit("not_a_type", step=0)
    bus.close()


def test_schema_validator_rejects_malformed_events():
    ok = {"type": "step", "run_id": "r", "step": 1, "t": 0.1, "ts": 1.0,
          "mesh": {}, "step_ms": 2.0}
    tele.validate_event(ok)
    with pytest.raises(tele.SchemaError, match="missing stamp"):
        tele.validate_event({k: v for k, v in ok.items() if k != "run_id"})
    with pytest.raises(tele.SchemaError, match="unknown event type"):
        tele.validate_event(dict(ok, type="mystery"))
    with pytest.raises(tele.SchemaError, match="missing required field"):
        tele.validate_event({k: v for k, v in ok.items()
                             if k != "step_ms"})
    with pytest.raises(tele.SchemaError, match="step_ms"):
        tele.validate_event(dict(ok, step_ms="fast"))
    # bool must not satisfy an int-typed field
    skip = {"type": "skip", "run_id": "r", "step": 1, "t": 0.1, "ts": 1.0,
            "mesh": {}, "consecutive": True, "total_skipped": 0}
    with pytest.raises(tele.SchemaError, match="got bool"):
        tele.validate_event(skip)


def test_r17_prefix_fields_pin_bool_vs_int():
    """r17 satellite: ``request_admit.prefix_hit`` is a REAL bool (an
    int hit-COUNT would silently satisfy a sloppier spec and break the
    summarize denominator), ``decode_step.pool_shared_pages`` is a
    REAL int count (a bool would cap the gauge at 1) — and both are
    optional, so pre-r17 event streams still validate."""
    stamp = {"run_id": "r", "step": None, "t": 0.1, "ts": 1.0, "mesh": {}}
    admit = dict(stamp, type="request_admit", rid=0, context_tokens=9,
                 pages=2, preemptions=0)
    tele.validate_event(admit)                          # absent: sharing off
    tele.validate_event(dict(admit, prefix_hit=True))
    tele.validate_event(dict(admit, prefix_hit=False))  # misses emit too
    with pytest.raises(tele.SchemaError, match="prefix_hit must be bool"):
        tele.validate_event(dict(admit, prefix_hit=1))
    step = dict(stamp, type="decode_step", batch=1, new_tokens=1,
                pool_used=3, pool_pages=63)
    tele.validate_event(step)                           # absent: sharing off
    tele.validate_event(dict(step, pool_shared_pages=0))
    tele.validate_event(dict(step, pool_shared_pages=24))
    with pytest.raises(tele.SchemaError, match="got bool"):
        tele.validate_event(dict(step, pool_shared_pages=True))


def test_emit_survives_sink_failure():
    """Observability must never kill the run it observes: a sink whose
    write raises (ENOSPC, broken pipe) is dropped, the event still
    reaches the other sinks and the recorder, and emit returns."""
    class ExplodingSink:
        def write(self, ev):
            raise OSError("disk full")

        def close(self):
            pass

    mem = tele.MemorySink()
    bus = tele.TelemetryBus("boom", sinks=[ExplodingSink(), mem])
    ev = bus.emit("step", step=1, step_ms=1.0)  # must not raise
    assert ev["type"] == "step"
    assert len(bus.sinks) == 1  # the dead sink was dropped
    bus.emit("step", step=2, step_ms=1.0)
    assert [e["step"] for e in mem.events] == [1, 2]
    assert len(bus.recorder) == 2
    bus.close()


def test_flight_recorder_ring_keeps_last_n():
    rec = tele.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record({"i": i})
    assert len(rec) == 8
    assert [e["i"] for e in rec.snapshot()] == list(range(12, 20))
    with pytest.raises(ValueError):
        tele.FlightRecorder(capacity=0)


# ------------------------------------------------------- accounting


def test_accountant_batches_scalars_one_fetch_per_window(tmp_path,
                                                         monkeypatch):
    """The no-extra-device-syncs contract: scalars ride as references
    and are fetched in ONE device_get per `window` steps."""
    bus, mem, _ = _bus(tmp_path)
    acct = bus.accountant(window=5)
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    loss = jnp.asarray(1.5)
    for i in range(1, 11):
        acct.step_done(i, step_s=0.01,
                       scalars={"loss": loss, "scale": jnp.asarray(2.0)})
    assert calls["n"] == 2  # 10 steps / window 5 — one batched fetch each
    steps = [e for e in mem.events if e["type"] == "step"]
    assert [e["step"] for e in steps if "scalars" in e] == [5, 10]
    assert steps[4]["scalars"] == {"loss": 1.5, "scale": 2.0}
    bus.close()


def test_accountant_goodput_buckets_and_run_end(tmp_path):
    bus, mem, _ = _bus(tmp_path)
    acct = bus.accountant(window=4)
    for i in range(1, 5):
        acct.step_done(i, step_s=0.05, data_wait_s=0.01,
                       skipped=(i == 4))
    acct.pause(0.2, "restore")
    with pytest.raises(ValueError, match="unknown pause kind"):
        acct.pause(0.1, "coffee")
    end = acct.finish(step=4, reason="completed")
    tele.validate_event(end)
    assert end["steps"] == 4 and end["skips"] == 1
    b = end["buckets_s"]
    # 3 productive steps of 50ms; the skipped one booked separately
    assert abs(b["step"] - 0.15) < 1e-6
    assert abs(b["skipped"] - 0.05) < 1e-6
    assert abs(b["restore"] - 0.2) < 1e-6
    # synthetic durations exceed the real wall here -> the clamp holds
    assert 0 < end["goodput"] <= 1
    bus.close()


def test_accountant_books_compile_wall_out_of_goodput(tmp_path):
    """Compile wall measured inside a step (first step, mid-run
    reshape) must land in the `compile` bucket, not inflate productive
    step time — a change that doubles compile cost must show up as
    LOWER goodput, never unchanged."""
    bus, mem, _ = _bus(tmp_path, "comp")
    acct = bus.accountant(window=10)
    acct.step_done(1, step_s=7.0, compile_s=6.5)  # compile-laden step 1
    acct.step_done(2, step_s=0.5)
    end = acct.finish(step=2)
    b = end["buckets_s"]
    assert abs(b["compile"] - 6.5) < 1e-6
    assert abs(b["step"] - 1.0) < 1e-6  # 0.5 + (7.0 - 6.5)
    ev1 = [e for e in mem.events if e["type"] == "step"][0]
    # the event keeps the operator-visible full wall AND the split
    assert ev1["step_ms"] == 7000.0 and ev1["compile_ms"] == 6500.0
    bus.close()


def test_loop_books_real_compile_to_compile_bucket(tmp_path):
    """run_resilient_training wires the recompile listener: the first
    step's actual XLA compile lands in the compile bucket and as
    recompile events, and goodput reflects post-compile productivity."""
    bus, mem, _ = _bus(tmp_path, "jitcomp")

    @jax.jit
    def fresh_step(state, b):
        # constants make this a never-before-compiled program
        return {"w": state["w"] * 0.917364 + 0.111213}, None

    run_resilient_training(fresh_step, {"w": jnp.ones((64,))}, [None] * 4,
                           telemetry=bus)
    bus.close()
    assert any(e["type"] == "recompile" for e in mem.events)
    end = [e for e in mem.events if e["type"] == "run_end"][-1]
    assert end["buckets_s"].get("compile", 0) > 0
    step1 = [e for e in mem.events if e["type"] == "step"][0]
    assert step1.get("compile_ms", 0) > 0


def test_summarize_tolerates_torn_trailing_line(tmp_path):
    """An OOM-killed run can leave a partial last line; `summarize`
    must render the stream anyway (`validate` stays strict)."""
    from apex_tpu.telemetry.__main__ import main

    path = tmp_path / "torn.jsonl"
    _write_stream(path, "torn", n=6)
    with open(path, "a") as f:
        f.write('{"type": "step", "run_id": "torn", "st')  # torn write
    s = tele.summarize_file(str(path))
    assert s["steps"] == 6 and s["run_id"] == "torn"
    assert main(["summarize", str(path)]) == 0
    assert main(["validate", str(path)]) == 1  # strict path still flags
    with pytest.raises(tele.SchemaError):
        tele.load_jsonl(str(path))


def test_accountant_goodput_against_real_wall(tmp_path):
    """With real elapsed time dominating, goodput is productive-step
    seconds over wall — pauses and idle drag it down."""
    bus, _, _ = _bus(tmp_path, "wall")
    acct = bus.accountant(window=10)
    t0 = time.monotonic()
    time.sleep(0.03)  # idle (e.g. input pipeline warmup)
    acct.step_done(1, step_s=0.01)
    time.sleep(0.03)
    acct.pause(0.03, "restore")
    wall = time.monotonic() - t0
    g = acct.goodput()
    assert 0 < g <= 0.01 / wall + 0.05
    end = acct.finish(step=1)
    assert end["goodput"] < 0.5  # mostly idle: goodput must say so
    bus.close()


# ------------------------------------------------ guard / watchdog / timers


def test_step_guard_emits_skip_events_with_diagnostics(tmp_path):
    bus, mem, _ = _bus(tmp_path)
    guard = res.StepGuard(max_consecutive_skips=2, telemetry=bus)
    bad = {"g": jnp.asarray([1.0, jnp.nan, 2.0])}
    guard.update(True, step=1)
    with pytest.raises(res.DivergenceError) as ei:
        guard.update(False, bad, loss_scale=jnp.asarray(4096.0), step=2)
        guard.update(False, bad, loss_scale=jnp.asarray(2048.0), step=3)
    # the raise-path diagnostic names leaf + grad-norm + loss scale
    msg = str(ei.value)
    assert "['g']" in msg and "1 nan" in msg
    assert "global grad-norm" in msg and "loss scale" in msg
    skips = [e for e in mem.events if e["type"] == "skip"]
    assert len(skips) == 2
    for ev in skips:
        tele.validate_event(ev)
    assert skips[0]["step"] == 2 and skips[0]["loss_scale"] == 4096.0
    assert np.isnan(skips[0]["grad_norm"])  # nan grads -> nan norm
    assert skips[1]["consecutive"] == 2
    bus.close()


def test_watchdog_emits_event_and_postmortem_includes_report(tmp_path):
    bus, mem, _ = _bus(tmp_path)
    h = res.GracePeriodHandler()
    wd = res.Watchdog(timeout=0.05, handler=h, poll_interval=0.005,
                      telemetry=bus)
    try:
        with wd.step(7):
            time.sleep(0.3)
    finally:
        wd.close()
    assert h.should_stop and "watchdog_timeout" in h.reason
    events = [e for e in mem.events if e["type"] == "watchdog"]
    assert len(events) == 1 and events[0]["step"] == 7
    tele.validate_event(events[0])
    path = bus.flush_postmortem(h.reason, step=7, watchdog=wd)
    header = tele.load_jsonl(path)[0]
    assert "watchdog" in header  # heartbeat-age report rides the header
    assert "device_heartbeat_age_s" in header["watchdog"]
    bus.close()


def test_timers_log_routes_through_bus(tmp_path, capsys):
    from apex_tpu.transformer.pipeline_parallel._timers import Timers

    bus, mem, _ = _bus(tmp_path)
    timers = Timers(telemetry=bus)
    timers("fwd").start()
    timers("fwd").stop()
    out = timers.log(step=3)
    assert out.startswith("time (ms)") and "fwd" in out  # API preserved
    assert capsys.readouterr().out == ""  # routed, not printed
    ev = [e for e in mem.events if e["type"] == "timers"]
    assert len(ev) == 1 and "fwd" in ev[0]["timers_ms"]
    assert ev[0]["step"] == 3
    tele.validate_event(ev[0])
    # without a bus the reference behavior (print) is unchanged
    bare = Timers()
    bare("x").start()
    bare("x").stop()
    bare.log()
    assert "time (ms)" in capsys.readouterr().out
    bus.close()


def test_recompile_listener_emits_on_fresh_jit(tmp_path):
    bus, mem, _ = _bus(tmp_path)
    uninstall = tele.install_recompile_listener(bus)
    try:
        # a jit the process has never compiled before
        f = jax.jit(lambda x: x * 3.14159 + 2.71828)
        f(jnp.ones((3, 5))).block_until_ready()
    finally:
        uninstall()
    rec = [e for e in mem.events if e["type"] == "recompile"]
    assert rec, "no recompile event for a fresh jit"
    for ev in rec:
        tele.validate_event(ev)
        assert ev["duration_ms"] >= 0
    n = len(mem.events)
    f(jnp.ones((3, 5)) * 2).block_until_ready()  # cache hit after uninstall
    assert len(mem.events) == n
    bus.close()


# ------------------------------------------------- loop integration


@pytest.mark.chaos
def test_sigterm_grace_path_flushes_parseable_postmortem(tmp_path):
    """ISSUE 4 acceptance: killing a run (real SIGTERM through the
    GracePeriodHandler grace path) leaves a parseable postmortem
    covering the final ring-buffer window."""
    state, opt, scaler = _toy_state()
    step_fn = _make_step_fn(opt, scaler)
    bus, mem, stream = _bus(tmp_path, "sigterm")
    guard = res.StepGuard(max_consecutive_skips=4)
    with res.GracePeriodHandler() as h:
        pre = chaos.SimulatedPreemption(9, handler=h, telemetry=bus)
        result = run_resilient_training(
            step_fn, state, _batches(30),
            ckpt_dir=str(tmp_path / "ck"), save_every=4,
            handler=h, guard=guard, log_every=4,
            on_step=pre.poll, telemetry=bus)
    bus.close()
    assert result.preempted and result.stop_reason == "SIGTERM"
    assert result.step == 9

    pms = _postmortems(tmp_path)
    assert len(pms) == 1
    pm = tele.load_jsonl(str(tmp_path / pms[0]))
    assert tele.validate_events(pm) == len(pm)
    header = pm[0]
    assert header["type"] == "postmortem" and header["reason"] == "SIGTERM"
    assert header["ring_events"] == len(pm) - 1
    # the ring covers the run right up to the stop step
    ring_steps = [e["step"] for e in pm[1:] if e["type"] == "step"]
    assert ring_steps[-1] == 9 and ring_steps == sorted(ring_steps)
    # a guarded loop's step events are on the synced clock — the
    # guard's finite check bounds the device step, so step_ms is wall,
    # not host dispatch (and the stream says so)
    assert all(e["timing"] == "synced" for e in pm[1:]
               if e["type"] == "step")
    # the chaos injection itself is on the record
    assert any(e["type"] == "fault_injected" and e["kind"] == "preemption"
               for e in pm[1:])
    # main stream: validates whole, carries the same postmortem pointer
    assert tele.validate_jsonl(stream) > 0
    ptr = [e for e in tele.load_jsonl(stream) if e["type"] == "postmortem"]
    assert len(ptr) == 1 and ptr[0]["path"].endswith(pms[0])
    # run_end carries goodput with the ckpt fences booked
    end = [e for e in mem.events if e["type"] == "run_end"][-1]
    assert end["reason"] == "SIGTERM" and 0 < end["goodput"] <= 1
    assert "ckpt_fence" in end["buckets_s"]


def _toy_elastic_build():
    """Synthetic elastic workload: deterministic param bump per step,
    per-rank opt partitions whose total flat size (256) survives any
    8->4->2 reshard."""

    def build(devices):
        n = len(devices)
        params = {"w": jnp.zeros((16,), jnp.float32)}
        opt = {"exp_avg": jnp.zeros((n, 256 // n), jnp.float32)}

        def step_fn(state, batch):
            p, o = state
            return ({"w": p["w"] + 1.0}, o), None

        return step_fn, (params, opt), (P(), P("data"))

    return build


@pytest.mark.chaos
@pytest.mark.chaos_mesh
def test_device_loss_recovery_flushes_postmortem_and_events(tmp_path):
    """ISSUE 4 acceptance: a chaos DeviceLoss run leaves a postmortem
    naming the faulting step, and the main stream shows the full
    recovery arc — fault_injected -> device_loss -> ckpt_restore -> a
    run_end whose goodput ledger spans both loop attempts."""
    bus, mem, stream = _bus(tmp_path, "dloss")
    dl = chaos.DeviceLoss(at_step=3, device_ids=jax.devices()[4:8],
                          telemetry=bus)
    result = res.run_elastic_training(
        _toy_elastic_build(), jax.devices()[:8], [None] * 6,
        ckpt_dir=str(tmp_path / "ck"), save_every=1, on_step=dl.poll,
        max_restarts=2, log_every=2, telemetry=bus)
    bus.close()
    assert result.restarts == 1 and len(result.devices) == 4
    assert result.step == 6

    pms = _postmortems(tmp_path)
    assert len(pms) == 1
    pm = tele.load_jsonl(str(tmp_path / pms[0]))
    assert tele.validate_events(pm) == len(pm)
    assert pm[0]["reason"] == "DeviceLossError"
    # the postmortem contains the faulting step (loss injected at the
    # step-3 boundary poll)
    assert 3 in [e["step"] for e in pm[1:] if e["type"] == "step"]
    assert any(e["type"] == "fault_injected"
               and e["kind"] == "device_loss"
               and e["device_ids"] == [4, 5, 6, 7] for e in pm[1:])

    assert tele.validate_jsonl(stream) > 0
    evs = tele.load_jsonl(stream)
    dloss = [e for e in evs if e["type"] == "device_loss"]
    assert len(dloss) == 1 and dloss[0]["device_ids"] == [4, 5, 6, 7]
    assert dloss[0]["survivors"] == 4 and dloss[0]["recoverable"]
    restore = [e for e in evs if e["type"] == "ckpt_restore"]
    # step 3's save never happened (the poll raised first): the newest
    # intact checkpoint is step 2
    assert len(restore) == 1 and restore[0]["step"] == 2
    assert restore[0]["n_shards"] == 4
    # post-recovery events are stamped with the survivor submesh
    after = [e for e in evs if e["t"] > restore[0]["t"]
             and e["type"] == "step"]
    assert after and all(e["mesh"]["n_devices"] == 4 for e in after)
    # one cumulative ledger across both attempts: the last run_end's
    # rebuild/restore buckets are non-empty and step count is global
    end = [e for e in evs if e["type"] == "run_end"][-1]
    assert end["reason"] == "completed"
    assert "rebuild" in end["buckets_s"] and "restore" in end["buckets_s"]
    assert end["steps"] == 7  # 3 pre-loss + replayed 3..6 from step 2


@pytest.mark.chaos
def test_log_line_carries_steps_per_sec_and_heartbeat_age(tmp_path):
    state, opt, scaler = _toy_state()
    step_fn = _make_step_fn(opt, scaler)
    lines = []
    wd = res.Watchdog(timeout=30.0, poll_interval=0.01)
    try:
        run_resilient_training(step_fn, state, _batches(6),
                               guard=res.StepGuard(), watchdog=wd,
                               log_every=3, log_fn=lines.append)
    finally:
        wd.close()
    assert lines and all("steps/s" in ln for ln in lines)
    assert all("max_hb_age" in ln for ln in lines)
    assert all("skipped 0/" in ln for ln in lines)


def test_divergence_exit_flushes_postmortem(tmp_path):
    """Any exception leaving the loop — here the guard's own
    DivergenceError — dumps the ring before re-raising."""
    bus, mem, _ = _bus(tmp_path, "div")

    def step_fn(state, batch):
        return state, jnp.asarray(False)

    with pytest.raises(res.DivergenceError):
        run_resilient_training(step_fn, {"w": jnp.zeros(2)}, [None] * 9,
                               guard=res.StepGuard(max_consecutive_skips=3),
                               telemetry=bus)
    bus.close()
    pms = _postmortems(tmp_path)
    assert len(pms) == 1
    pm = tele.load_jsonl(str(tmp_path / pms[0]))
    assert pm[0]["reason"] == "DivergenceError"
    # the guard's skip events made it into the ring
    assert sum(e["type"] == "skip" for e in pm[1:]) == 3


# ------------------------------------------------------ summarize CLI


def _write_stream(path, run_id, n=20, ms=10.0, skip_at=()):
    bus = tele.TelemetryBus(run_id, sinks=[tele.JsonlSink(str(path))])
    acct = bus.accountant(window=5)
    bus.emit("run_start", step=0)
    for i in range(1, n + 1):
        acct.step_done(i, step_s=ms / 1e3, skipped=i in skip_at)
    acct.finish(step=n)
    bus.close()


def test_summarize_renders_percentiles_goodput_and_counts(tmp_path,
                                                          capsys):
    from apex_tpu.telemetry.__main__ import main

    a = tmp_path / "a.jsonl"
    _write_stream(a, "run-a", n=20, skip_at={7})
    assert main(["summarize", str(a)]) == 0
    out = capsys.readouterr().out
    assert "p50" in out and "p95" in out and "p99" in out
    assert "goodput" in out and "%" in out
    assert "step=20" in out and "run_end=1" in out

    s = tele.summarize_file(str(a))
    assert s["steps"] == 20 and s["skipped_steps"] == 1
    assert s["step_ms_p50"] > 0 and s["step_ms_p95"] >= s["step_ms_p50"]
    assert 0 < s["goodput"] <= 1

    assert main(["summarize", str(a), "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["run_id"] == "run-a" and rec["counts"]["step"] == 20


def test_summarize_diff_mode_ab_table(tmp_path, capsys):
    from apex_tpu.telemetry.__main__ import main

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write_stream(a, "run-a", n=10, ms=10.0)
    _write_stream(b, "run-b", n=10, ms=20.0)
    assert main(["summarize", str(a), "--diff", str(b)]) == 0
    out = capsys.readouterr().out
    assert "run-a" in out and "run-b" in out and "delta" in out
    # B's p50 is ~2x A's and the table says so
    assert "2.00x" in out


def test_summarize_estimates_goodput_without_run_end(tmp_path):
    """A crashed stream (no run_end) still summarizes — goodput falls
    back to productive-step seconds over the stream extent."""
    path = tmp_path / "crash.jsonl"
    bus = tele.TelemetryBus("crash", sinks=[tele.JsonlSink(str(path))])
    acct = bus.accountant(window=4)
    bus.emit("run_start", step=0)
    for i in range(1, 5):
        acct.step_done(i, step_s=0.01)
        time.sleep(0.012)
    bus.close()  # no finish(): simulated crash
    s = tele.summarize_file(str(path))
    assert s.get("goodput_estimated") and 0 < s["goodput"] <= 1


def test_validate_cli_flags_bad_stream(tmp_path, capsys):
    from apex_tpu.telemetry.__main__ import main

    good = tmp_path / "good.jsonl"
    _write_stream(good, "g", n=3)
    assert main(["validate", str(good)]) == 0
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"type": "step", "run_id": "x"}) + "\n")
    assert main(["validate", str(bad)]) == 1
    assert "INVALID" in capsys.readouterr().err


# ------------------------------------------------------ overhead bound


def test_bench_bert_telemetry_stream_validates(tmp_path, monkeypatch):
    """The BERT-Large flagship bench (ISSUE 5) writes a
    telemetry/bert_large.jsonl stream; it must pass the strict schema
    validator (`python -m apex_tpu.telemetry validate`) and surface the
    bert_large_goodput / bert_large_step_ms_p95 record keys — exercised
    through the bench's own _BenchTelemetry wrapper, not a lookalike."""
    monkeypatch.setenv("BENCH_TELEMETRY_DIR", str(tmp_path))
    import bench

    bt = bench._BenchTelemetry("bert_large")
    assert bt._dead is None, bt._dead
    bt.compile_pause(0.5)
    bt.trial(4, 0.8, scalars={"loss": 3.25})
    bt.trial(4, 0.7, scalars={"loss": 3.11})
    keys = bt.finish()
    path = os.path.join(str(tmp_path), "bert_large.jsonl")
    # strict schema check — the exact code path of the validate CLI
    assert tele.validate_jsonl(path) > 0
    from apex_tpu.telemetry.__main__ import main as tele_cli
    assert tele_cli(["validate", path]) == 0
    assert keys["bert_large_goodput"] is not None
    assert keys["bert_large_step_ms_p95"] is not None
    assert keys["bert_large_telemetry_file"] == "bert_large.jsonl"


@pytest.mark.chaos
def test_telemetry_overhead_at_most_one_percent_of_step(tmp_path):
    """ISSUE 4 satellite: the per-step telemetry work (one step_done
    emit through a real JSONL sink; scalar fetches amortized over the
    window) must cost ≤1% of a toy train step's wall time."""
    @jax.jit
    def step(s, b):
        return s @ s * 0.999 + b

    s = jnp.ones((768, 768), jnp.float32)
    b = jnp.zeros((768, 768), jnp.float32)
    step(s, b).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(5):
        out = step(s, b)
    out.block_until_ready()
    step_wall = (time.perf_counter() - t0) / 5

    bus, _, _ = _bus(tmp_path, "ovh")
    acct = bus.accountant(window=10)
    loss = jnp.asarray(1.0)
    best = float("inf")
    for _ in range(5):  # best-of-5: reject fs hiccups, like the benches
        t0 = time.perf_counter()
        for i in range(200):
            acct.step_done(i, step_s=step_wall, scalars={"loss": loss})
        best = min(best, (time.perf_counter() - t0) / 200)
    bus.close()
    assert best <= 0.01 * step_wall, (
        f"telemetry {best * 1e6:.1f}us/step vs step {step_wall * 1e3:.2f}ms"
        f" = {100 * best / step_wall:.2f}% > 1%")


# ------------------------------------------- trace-capture-backed (slow)


@pytest.mark.slow
def test_device_clock_step_events_from_trace_capture(tmp_path):
    """Telemetry + the offline profiling layer: step events timed on
    DEVICE clocks via a profiler trace capture (the bench's wall-vs-
    device discipline applied to the stream).  Trace-capture-backed,
    so marked slow per the tier-1 budget rule."""
    from apex_tpu import profiling

    @jax.jit
    def f(x):
        return x @ x

    x = jnp.ones((256, 256), jnp.float32)
    f(x).block_until_ready()
    try:
        device_ms = profiling.device_time_ms(f, x, steps=2)
    except Exception as e:  # pragma: no cover — no profiler backend
        pytest.skip(f"trace capture unavailable: {e}")
    bus, mem, stream = _bus(tmp_path, "trace")
    bus.emit("step", step=1, step_ms=round(device_ms, 3), timing="device")
    bus.close()
    ev = tele.load_jsonl(stream)[0]
    tele.validate_event(ev)
    assert ev["timing"] == "device" and ev["step_ms"] > 0


# ------------------------------------------------ data plane (ISSUE 7)


def test_data_wait_bucket_and_stall_events_validate_end_to_end(tmp_path):
    """ISSUE 7 satellite: the accounting ``data_wait`` path and the
    ``data_stall``/``data_quarantine`` events validate against the
    schema driven through a REAL loop — a stalling prefetched source
    feeding run_resilient_training — not just hand-built dicts."""
    import numpy as np

    from apex_tpu.data import AsyncPrefetcher

    class SlowSource:
        """Checkpointable source whose production stalls every batch."""

        def __init__(self, n):
            self.n, self.i = n, 0

        def __iter__(self):
            return self

        def __next__(self):
            if self.i >= self.n:
                raise StopIteration
            time.sleep(0.03)
            self.i += 1
            return np.ones((4,), np.float32)

        def state_dict(self):
            return {"i": self.i}

        def load_state_dict(self, s):
            self.i = s["i"]

    bus, mem, stream = _bus(tmp_path, "datawait")
    pf = AsyncPrefetcher(SlowSource(5), depth=1, stall_threshold_s=0.005,
                         telemetry=bus)
    bus.emit("data_quarantine", record_id=7, reason="crc_mismatch",
             total=1, rate=0.001)
    result = run_resilient_training(
        lambda s, b: ({"w": s["w"] + float(np.sum(b))}, None),
        {"w": jnp.zeros(())}, data_iter=pf,
        ckpt_dir=str(tmp_path / "ck"), save_every=2, telemetry=bus)
    pf.close()
    bus.close()
    assert result.step == 5

    # the whole stream — stall + quarantine events included — is
    # schema-valid (strict mode, no torn-tail tolerance)
    assert tele.validate_jsonl(stream) == len(mem.events)
    stalls = [e for e in mem.events if e["type"] == "data_stall"]
    assert stalls and all(e["cause"] == "queue_dry" and e["wait_ms"] > 0
                          for e in stalls)
    # the loop measured real wait around next() and booked the bucket
    steps = [e for e in mem.events if e["type"] == "step"]
    assert any(e.get("data_wait_ms", 0) > 0 for e in steps)
    end = [e for e in mem.events if e["type"] == "run_end"][-1]
    assert end["buckets_s"].get("data_wait", 0) > 0

    # summarize surfaces the data plane on the one-screen view
    s = tele.summarize_events(mem.events)
    assert s["data_stalls"] == len(stalls)
    assert s["records_quarantined"] == 1
    txt = tele.format_summary(s)
    assert "data" in txt and "stalls" in txt
