"""ISSUE 11 — apex_tpu.analysis: project-invariant linter + hot-path
sanitizer.

Four layers, mirroring the package:

1. framework mechanics — suppression comments, baseline match/stale
   accounting, path normalization, the CLI exit-code gate;
2. the rule catalog — every rule has POSITIVE (flags the seeded bug)
   and NEGATIVE (stays quiet on the sanctioned form) fixtures: a rule
   with no negative fixture is a rule that flags everything;
3. the schema satellite — EVENT_TYPES is derived from EVENT_FIELDS
   (drift impossible by construction), optional fields are type-checked
   when present, bool-not-int covers them too;
4. the runtime half — ``hot_path_guard`` pins the serving engine's
   zero-compiles-after-warmup contract and the flagship step's
   steady-state no-recompile/no-host-sync property, each with a
   CONTROL showing the guard actually fires on a seeded violation.

Plus the regression pins for the genuine violations the first lint run
surfaced (guards.py / checkpoint.py broad-except narrowing, the
serving warmup's missing third executable).
"""

import json
import os
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.analysis import (Baseline, HotPathViolation,
                               hot_path_guard, lint_paths, lint_source,
                               normalize_path)
from apex_tpu.analysis.framework import suppressed_lines
from apex_tpu.analysis.rules import (RULES, ExceptionSwallowing,
                                     HostSyncInHotPath, LockDiscipline,
                                     MissingDonation,
                                     TelemetrySchemaDrift,
                                     UnseededNondeterminism)

pytestmark = pytest.mark.analysis

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _lint(src, path="apex_tpu/fixture.py", rule_cls=None):
    rules = [rule_cls()] if rule_cls is not None else None
    return lint_source(textwrap.dedent(src), path, rules)


def _ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# framework mechanics
# ---------------------------------------------------------------------------


def test_normalize_path_strips_prefix():
    assert normalize_path("/abs/prefix/apex_tpu/serving/engine.py") == \
        "apex_tpu/serving/engine.py"
    assert normalize_path("apex_tpu/x.py") == "apex_tpu/x.py"
    assert normalize_path("elsewhere/y.py") == "elsewhere/y.py"


def test_suppression_same_line_and_comment_above():
    src = ("x = 1  # lint: disable=HS001\n"
           "# lint: disable=ND001, TL001\n"
           "y = 2\n")
    sup = suppressed_lines(src)
    assert sup[1] == {"HS001"}
    assert sup[2] == {"ND001", "TL001"}
    assert sup[3] == {"ND001", "TL001"}  # comment-only line covers next


def test_inline_suppression_waives_only_named_rule():
    hot = """
    import jax

    @jax.jit
    def f(x):
        return x.item()  # lint: disable=HS001
    """
    assert _lint(hot, rule_cls=HostSyncInHotPath) == []
    wrong = hot.replace("HS001", "ND001")
    assert _ids(_lint(wrong, rule_cls=HostSyncInHotPath)) == ["HS001"]


def test_baseline_matches_and_reports_stale(tmp_path):
    pkg = tmp_path / "apex_tpu" / "serving"
    pkg.mkdir(parents=True)
    f = pkg / "mod.py"
    f.write_text("import time\n\n\ndef now():\n    return time.time()\n")
    baseline = Baseline([
        {"rule": "ND001", "path": "apex_tpu/serving/mod.py",
         "match": "time.time()", "justification": "fixture"},
        {"rule": "ND001", "path": "apex_tpu/serving/mod.py",
         "match": "no_such_line", "justification": "stale fixture"},
    ])
    res = lint_paths([str(f)], baseline=baseline)
    assert res.findings == []
    assert len(res.baselined) == 1
    assert len(res.stale_baseline) == 1
    assert res.stale_baseline[0]["match"] == "no_such_line"


def test_baseline_rejects_missing_justification():
    with pytest.raises(ValueError, match="justification"):
        Baseline([{"rule": "ND001", "path": "a.py", "match": "x"}])


def test_cli_lint_gate_exit_codes(tmp_path, capsys):
    from apex_tpu.analysis.__main__ import main

    pkg = tmp_path / "apex_tpu" / "serving"
    pkg.mkdir(parents=True)
    bad = pkg / "bad.py"
    bad.write_text("import time\nT = time.time()\n")
    rc = main(["lint", str(bad), "--no-baseline", "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [f["rule"] for f in report["findings"]] == ["ND001"]
    bad.write_text("import time\nT = time.monotonic()\n")
    assert main(["lint", str(bad), "--no-baseline"]) == 0
    assert main(["lint", str(tmp_path / "nope.py")]) == 2


# ---------------------------------------------------------------------------
# HS001 — host sync in a hot path
# ---------------------------------------------------------------------------


def test_hs001_flags_item_in_jit_decorated():
    src = """
    import jax

    @jax.jit
    def f(x):
        return x.item()
    """
    assert _ids(_lint(src, rule_cls=HostSyncInHotPath)) == ["HS001"]


def test_hs001_flags_device_get_in_jitted_by_name():
    src = """
    import jax

    def _step(x):
        jax.device_get(x)
        return x

    fn = jax.jit(_step)
    """
    assert _ids(_lint(src, rule_cls=HostSyncInHotPath)) == ["HS001"]


def test_hs001_flags_aliased_device_get():
    # `import jax as _jax` must not dodge the rule (found the hard way
    # in the train loop's log path on the rule's first run)
    src = """
    import jax as _jax
    import jax

    @jax.jit
    def f(x):
        return _jax.device_get(x)
    """
    assert _ids(_lint(src, rule_cls=HostSyncInHotPath)) == ["HS001"]


def test_hs001_hot_table_covers_named_loops_and_nested_defs():
    src = """
    import numpy as np

    def _decode_batch(rows):
        def fetch(t):
            return np.asarray(t)
        return [fetch(r) for r in rows]
    """
    found = _lint(src, path="apex_tpu/serving/engine.py",
                  rule_cls=HostSyncInHotPath)
    assert _ids(found) == ["HS001"]
    # same code under a path NOT in the hot table: quiet
    assert _lint(src, path="apex_tpu/ops/misc.py",
                 rule_cls=HostSyncInHotPath) == []


def test_hs001_negative_plain_function_quiet():
    src = """
    import jax
    import numpy as np

    def offline_report(x):
        jax.block_until_ready(x)
        return np.asarray(x).item()
    """
    assert _lint(src, rule_cls=HostSyncInHotPath) == []


# ---------------------------------------------------------------------------
# ND001 — unseeded nondeterminism in bitwise-contract modules
# ---------------------------------------------------------------------------


def test_nd001_flags_wall_clock_and_global_rng():
    src = """
    import random
    import time
    import numpy as np

    def jitter():
        return time.time() + random.random() + np.random.uniform()
    """
    found = _lint(src, path="apex_tpu/data/mod.py",
                  rule_cls=UnseededNondeterminism)
    assert _ids(found) == ["ND001", "ND001", "ND001"]


def test_nd001_negative_seeded_generators_and_monotonic():
    src = """
    import random
    import time
    import numpy as np

    def draw(seed):
        rng = np.random.RandomState(seed)
        g = np.random.Generator(np.random.Philox(seed))
        r = random.Random(seed)
        t0 = time.monotonic()
        return rng.uniform() + g.random() + r.random() + t0
    """
    assert _lint(src, path="apex_tpu/serving/mod.py",
                 rule_cls=UnseededNondeterminism) == []


def test_nd001_scoped_to_contract_modules():
    src = "import time\nT = time.time()\n"
    assert _lint(src, path="apex_tpu/ops/mod.py",
                 rule_cls=UnseededNondeterminism) == []
    assert _ids(_lint(src, path="apex_tpu/multi_tensor/mod.py",
                      rule_cls=UnseededNondeterminism)) == ["ND001"]


# ---------------------------------------------------------------------------
# DN001 — pool-sized jit without donation
# ---------------------------------------------------------------------------


def test_dn001_flags_pool_params_without_donate():
    src = """
    import jax

    def step(k_pool, v_pool, tokens):
        return k_pool, v_pool, tokens

    fn = jax.jit(step)
    """
    found = _lint(src, rule_cls=MissingDonation)
    assert _ids(found) == ["DN001"]
    assert "k_pool" in found[0].message and "v_pool" in found[0].message


def test_dn001_negative_donate_kwarg_or_no_pool_params():
    src = """
    import jax

    def step(k_pool, v_pool, tokens):
        return k_pool, v_pool, tokens

    def light(tokens, positions):
        return tokens + positions

    a = jax.jit(step, donate_argnums=(0, 1))
    b = jax.jit(step, donate_argnums=())   # explicit no-donate decision
    c = jax.jit(light)
    """
    assert _lint(src, rule_cls=MissingDonation) == []


# ---------------------------------------------------------------------------
# TL001 — telemetry emit sites vs the schema table
# ---------------------------------------------------------------------------


def test_tl001_flags_unknown_type_unknown_field_int_for_bool():
    src = """
    def report(bus):
        bus.emit("not_an_event", x=1)
        bus.emit("serving_recovery", cause="dl", pool_rebuilt=1,
                 running_restored=0, waiting_restored=0)
        bus.emit("step", bogus_field=3)
    """
    found = _lint(src, rule_cls=TelemetrySchemaDrift)
    msgs = " | ".join(f.message for f in found)
    assert _ids(found) == ["TL001", "TL001", "TL001"]
    assert "unknown telemetry event type 'not_an_event'" in msgs
    assert "int literal `1` for bool field `serving_recovery.pool_rebuilt`" \
        in msgs
    assert "`bogus_field` is not in the schema table" in msgs


def test_tl001_negative_conforming_and_dynamic_sites():
    src = """
    def report(bus, etype, payload):
        bus.emit("ckpt_save", step=3, blocking=True, wall_ms=1.5)
        bus.emit("step", step_ms=2.0, timing="synced")
        bus.emit(etype, **payload)          # dynamic: not checkable
        bus.emit("request_retire", rid=1, reason="eos", new_tokens=2,
                 preemptions=0, deadline_hit=True)
    """
    assert _lint(src, rule_cls=TelemetrySchemaDrift) == []


# ---------------------------------------------------------------------------
# TH001 — lock discipline across thread boundaries
# ---------------------------------------------------------------------------

_TH_TEMPLATE = """
import threading


class Worker:
    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def _run(self):
        {worker_store}

    def reset(self):
        {other_store}
"""


def test_th001_flags_unlocked_cross_thread_store():
    src = _TH_TEMPLATE.format(worker_store="self.count = self.count + 1",
                              other_store="self.count = 0")
    found = _lint(src, rule_cls=LockDiscipline)
    assert _ids(found) == ["TH001"]
    assert "self.count" in found[0].message


def test_th001_negative_locked_both_sides():
    src = _TH_TEMPLATE.format(
        worker_store="with self._lock:\n            self.count += 1",
        other_store="with self._lock:\n            self.count = 0")
    assert _lint(src, rule_cls=LockDiscipline) == []


def test_th001_negative_single_side_store():
    # worker-only mutation has no cross-thread writer to race with
    src = _TH_TEMPLATE.format(worker_store="self.count = self.count + 1",
                              other_store="pass")
    assert _lint(src, rule_cls=LockDiscipline) == []


def test_th001_follows_nested_thread_target_and_delegate():
    # Thread(target=<nested def>) + worker delegating to self._fire()
    src = """
    import threading


    class W:
        def __init__(self):
            self.flag = 0

        def submit(self):
            def _job():
                self._fire()
            threading.Thread(target=_job).start()

        def _fire(self):
            self.flag = 1

        def clear(self):
            self.flag = 0
    """
    assert _ids(_lint(src, rule_cls=LockDiscipline)) == ["TH001"]


# ---------------------------------------------------------------------------
# EX001 — exception swallowing in run loops
# ---------------------------------------------------------------------------


def test_ex001_flags_broad_swallow_in_loop():
    src = """
    def run(jobs):
        for job in jobs:
            try:
                job()
            except Exception:
                pass
    """
    assert _ids(_lint(src, rule_cls=ExceptionSwallowing)) == ["EX001"]


def test_ex001_negative_narrow_logged_teardown_or_no_loop():
    src = """
    import logging

    log = logging.getLogger(__name__)


    def run(jobs):
        for job in jobs:
            try:
                job()
            except ValueError:          # narrow: a decision, not a net
                continue
            try:
                job()
            except Exception:
                log.exception("job failed")   # surfaced


    def close(handles):
        for h in handles:
            try:
                h.close()
            except Exception:
                pass                    # teardown: the documented sink


    def once(job):
        try:
            job()
        except Exception:
            pass                        # not in a loop: out of scope
    """
    assert _lint(src, rule_cls=ExceptionSwallowing) == []


# ---------------------------------------------------------------------------
# the schema satellite: one table, no drift
# ---------------------------------------------------------------------------


def test_event_types_derived_from_field_specs():
    from apex_tpu.telemetry import bus, schema

    assert bus.EVENT_TYPES is schema.EVENT_TYPES
    assert schema.EVENT_TYPES == frozenset(schema.EVENT_FIELDS)
    for etype, fields in schema.EVENT_FIELDS.items():
        for name, spec in fields.items():
            assert isinstance(spec.types, tuple) and spec.types, \
                f"{etype}.{name} has no types"
            assert all(isinstance(t, type) for t in spec.types)
            assert isinstance(spec.required, bool)
    # the legacy view stays consistent with the table
    for etype, required in schema.PAYLOAD_REQUIRED.items():
        assert required == {f: s.types
                            for f, s in schema.EVENT_FIELDS[etype].items()
                            if s.required}


def test_emitting_unspecced_type_fails_loudly():
    from apex_tpu.telemetry import (MemorySink, SchemaError, TelemetryBus,
                                    TelemetryError, validate_event)

    bus = TelemetryBus(run_id="drift", sinks=[MemorySink()])
    with pytest.raises(TelemetryError, match="unknown event type"):
        bus.emit("brand_new_event", x=1)
    ev = bus.emit("step", step=1, step_ms=1.0)
    with pytest.raises(SchemaError, match="unknown event type"):
        validate_event(dict(ev, type="brand_new_event"))


def test_optional_fields_typed_when_present():
    from apex_tpu.telemetry import (MemorySink, SchemaError, TelemetryBus,
                                    validate_event)

    bus = TelemetryBus(run_id="opt", sinks=[MemorySink()])
    ev = bus.emit("request_retire", step=1, rid=1, reason="eos",
                  new_tokens=3, preemptions=0, ttft_ms=4.2,
                  deadline_hit=True)
    validate_event(ev)
    with pytest.raises(SchemaError, match="deadline_hit"):
        validate_event(dict(ev, deadline_hit=1))  # int-for-bool
    with pytest.raises(SchemaError, match="ttft_ms"):
        validate_event(dict(ev, ttft_ms="fast"))
    # absent optional stays fine
    ev2 = {k: v for k, v in ev.items()
           if k not in ("ttft_ms", "deadline_hit")}
    validate_event(ev2)


# ---------------------------------------------------------------------------
# the tier-1 gate: the repo lints clean against its committed baseline
# ---------------------------------------------------------------------------


def test_repo_lints_clean_against_committed_baseline():
    # the gate covers every PRODUCT surface: the package, the bench
    # driver, and the example entrypoints.  tests/ stay out of scope —
    # they deliberately contain the rules' negative fixtures (unknown
    # event types, undonated jits) as test data
    baseline = Baseline.load(
        os.path.join(REPO_ROOT, "analysis_baseline.json"))
    res = lint_paths([os.path.join(REPO_ROOT, "apex_tpu"),
                      os.path.join(REPO_ROOT, "bench.py"),
                      os.path.join(REPO_ROOT, "examples")],
                     baseline=baseline)
    assert res.findings == [], "\n".join(f.format() for f in res.findings)
    assert res.stale_baseline == [], (
        "stale baseline entries — the documented exception no longer "
        f"exists, delete them: {res.stale_baseline}")
    assert res.files > 100  # the walk really covered the package


# ---------------------------------------------------------------------------
# regression pins for the violations the first lint run surfaced
# ---------------------------------------------------------------------------


def test_grad_norm_counts_bf16_and_no_longer_swallows(monkeypatch):
    from apex_tpu.resilience.guards import global_grad_norm

    # the narrow except still takes the legitimate skip/convert paths
    tree = {"a": jnp.full((4,), 1.0, jnp.bfloat16),
            "b": np.arange(3)}           # int leaf: skipped, not normed
    assert global_grad_norm(tree) == pytest.approx(2.0)
    # …but an unexpected failure now surfaces instead of silently
    # under-reporting the norm (EX001 fix)
    monkeypatch.setattr(jax.numpy, "issubdtype",
                        lambda *a: (_ for _ in ()).throw(
                            RuntimeError("issubdtype broke")))
    with pytest.raises(RuntimeError, match="issubdtype broke"):
        global_grad_norm({"a": jnp.full((2,), 1.0, jnp.bfloat16)})


def test_checkpoint_topology_probe_narrowed(tmp_path):
    from apex_tpu.checkpoint import restore_checkpoint, save_checkpoint

    # numpy leaves (no .sharding at all) keep saving — the documented
    # best-effort "no topology" case
    state = {"w": np.arange(6, dtype=np.float32)}
    save_checkpoint(str(tmp_path / "ok"), state, step=1)
    restored, step = restore_checkpoint(
        str(tmp_path / "ok"), {"w": np.zeros(6, np.float32)})
    assert step == 1 and np.array_equal(restored["w"], state["w"])

    # …but a genuinely broken sharding probe now surfaces (EX001 fix:
    # the broad except used to swallow ANY failure here)
    class _Weird(np.ndarray):
        @property
        def sharding(self):
            raise RuntimeError("sharding probe broke")

    arr = np.arange(4, dtype=np.float32).view(_Weird)
    with pytest.raises(RuntimeError, match="sharding probe broke"):
        save_checkpoint(str(tmp_path / "bad"), {"w": arr}, step=1)


# ---------------------------------------------------------------------------
# runtime half: hot_path_guard mechanics
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def warm_jit():
    f = jax.jit(lambda a: a * 2 + 1)
    x = jnp.ones((8,))
    y = f(x)
    jax.block_until_ready(y)
    return f, x, y


def test_guard_steady_state_passes(warm_jit):
    f, x, _ = warm_jit
    with hot_path_guard("steady", transfers=None) as g:
        for _ in range(3):
            y = f(x)
    assert g.recompiles == 0 and g.syncs == []
    assert float(y[0]) == 3.0  # fetch OUTSIDE the region is fine


def test_guard_fires_on_recompile(warm_jit):
    f, _, _ = warm_jit
    x9 = jnp.ones((9,))  # new shape, built outside the region
    with pytest.raises(HotPathViolation, match="XLA compile"):
        with hot_path_guard("recompile-control", transfers=None):
            f(x9)


def test_guard_recompile_budget(warm_jit):
    f, _, _ = warm_jit
    x10 = jnp.ones((10,))
    with hot_path_guard("budgeted", transfers=None,
                        max_recompiles=1) as g:
        f(x10)
    assert g.recompiles == 1


@pytest.mark.parametrize("sync", ["device_get", "block_until_ready",
                                  "item"])
def test_guard_tripwire_fires_on_host_sync(warm_jit, sync):
    _, _, y = warm_jit
    calls = {"device_get": lambda: jax.device_get(y),
             "block_until_ready": lambda: jax.block_until_ready(y),
             "item": lambda: y.sum().item()}
    with pytest.raises(HotPathViolation, match="host sync"):
        with hot_path_guard("sync-control", transfers=None):
            calls[sync]()
    # and the tripwire is fully uninstalled afterwards
    calls[sync]()


def test_guard_records_instead_of_raising_when_asked(warm_jit):
    _, _, y = warm_jit
    with hot_path_guard("recording", transfers=None,
                        raise_on_sync=False) as g:
        jax.device_get(y)
        y.sum().item()
    assert g.syncs == ["jax.device_get", "Array.item"]


def test_guard_body_exception_propagates_and_restores(warm_jit):
    _, _, y = warm_jit
    with pytest.raises(RuntimeError, match="boom"):
        with hot_path_guard("err", transfers=None):
            raise RuntimeError("boom")
    jax.device_get(y)  # tripwire gone


# ---------------------------------------------------------------------------
# the two enforced-by-construction contracts
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serving_cfg():
    from apex_tpu.serving.model import ServingModelConfig

    return ServingModelConfig(vocab_size=64, hidden_size=32, num_heads=4,
                              num_layers=2, max_position=96)


def _make_engine(cfg):
    from apex_tpu.serving.engine import ServingEngine, SimClock

    return ServingEngine(cfg, num_pages=32, page_size=8, max_batch=4,
                         clock=SimClock(), seed=0)


@pytest.mark.serving
def test_serving_lifetime_zero_compiles_after_warmup(serving_cfg):
    """The PR 8 compiled-shapes contract, enforced by construction:
    warmup compiles all three executables (prefill row, decode step,
    admission scatter) and the whole serving lifetime after it — spans
    admission, growth, retirement — compiles NOTHING."""
    eng = _make_engine(serving_cfg)
    eng.warmup()
    with hot_path_guard("serving lifetime", transfers=None) as g:
        for i, prompt in enumerate([[1, 2, 3], [4, 5, 6, 7], [8, 9],
                                    [10, 11, 12, 13, 14]]):
            eng.submit(prompt, max_new_tokens=3 + i)
        finished = eng.run()
    assert len(finished) == 4
    assert g.recompiles == 0 and g.syncs == []


@pytest.mark.serving
def test_spec_serving_lifetime_zero_compiles_after_warmup(serving_cfg):
    """ISSUE 12: the compiled-shapes contract over the GROWN executable
    set — warmup also compiles the speculative verify step
    (q_len = k + 1) and the chunked-prefill step, and a trace that
    exercises draft–verify boundaries, chunked prefill, AND pool-
    pressure preemption still compiles NOTHING after warmup."""
    from apex_tpu.serving.engine import ServingEngine, SimClock
    from apex_tpu.serving.spec import SpecConfig

    eng = ServingEngine(serving_cfg, num_pages=13, page_size=8,
                        max_batch=4, clock=SimClock(), seed=0,
                        max_pages_per_request=6,
                        spec=SpecConfig(k=3, chunk_size=16))
    eng.warmup()
    with hot_path_guard("spec serving lifetime", transfers=None) as g:
        # a long prompt (chunked prefill), repetitive prompts (drafts
        # that accept), and enough load on 12 pages to preempt
        reqs = [eng.submit([1, 2] * 12, max_new_tokens=6),
                eng.submit([3, 4, 3, 4, 3], max_new_tokens=8),
                eng.submit(list(range(5, 25)), max_new_tokens=4),
                eng.submit([7, 8] * 5, max_new_tokens=6)]
        finished = eng.run()
    assert len(finished) == 4
    assert g.recompiles == 0 and g.syncs == []
    assert eng.proposer.drafted > 0, "trace was meant to speculate"


@pytest.mark.serving
def test_serving_unwarmed_engine_trips_the_guard(serving_cfg):
    """Control: without warmup the first admission compiles inside the
    guarded region — the guard MUST fire (this is also the pin for the
    warmup gap the guard originally found: the admission scatter was
    the third executable warmup never compiled)."""
    eng = _make_engine(serving_cfg)
    with pytest.raises(HotPathViolation, match="XLA compile"):
        with hot_path_guard("unwarmed serving", transfers=None):
            eng.submit([1, 2, 3], max_new_tokens=2)
            eng.run()


@pytest.fixture(scope="module")
def toy_flagship():
    from apex_tpu.transformer.testing.flagship import (
        build_flagship_train_step, gpt1p3b_config)

    cfg = gpt1p3b_config(num_layers=1, hidden_size=64,
                         num_attention_heads=2, vocab_size=64,
                         max_position_embeddings=16)
    fs = build_flagship_train_step(cfg, plan="bf16_fit", lr=1e-3,
                                   devices=jax.devices()[:2],
                                   donate=False)
    from jax.sharding import NamedSharding, PartitionSpec as P

    k = jax.random.PRNGKey(1)
    tokens = jax.random.randint(k, (2, cfg.max_position_embeddings), 0,
                                cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=-1)
    sharding = NamedSharding(fs.mesh, P("data"))
    tokens = jax.device_put(tokens, sharding)
    labels = jax.device_put(labels, sharding)
    # steady state starts at step 2: step 1 compiles, and its output
    # state lands in the executable's (possibly different) sharding —
    # feeding it back once reaches the sharding fixed point
    p, s, _ = fs.step(fs.params, fs.opt_state, tokens, labels)
    p, s, loss = fs.step(p, s, tokens, labels)
    jax.block_until_ready(loss)
    return fs, p, s, tokens, labels


def test_flagship_steady_state_no_recompile_no_sync(toy_flagship):
    """The flagship train step's steady-state property, enforced by
    construction: with pre-placed inputs and warmed state, N further
    steps do zero compiles, zero host syncs, and zero guarded
    transfers ("disallow" is active inside the region)."""
    fs, p, s, tokens, labels = toy_flagship
    with hot_path_guard("flagship steady state") as g:
        for _ in range(3):
            p, s, loss = fs.step(p, s, tokens, labels)
    assert g.recompiles == 0 and g.syncs == []
    assert np.isfinite(float(loss))  # fetched OUTSIDE the region


def test_flagship_guard_fires_on_seeded_sync(toy_flagship):
    """Control: a mid-loop loss fetch — the exact HS001 anti-pattern —
    trips the guard."""
    fs, p, s, tokens, labels = toy_flagship
    with pytest.raises(HotPathViolation, match="host sync"):
        with hot_path_guard("flagship sync control"):
            _, _, loss = fs.step(p, s, tokens, labels)
            jax.device_get(loss)


def test_flagship_guard_fires_on_unplaced_inputs(toy_flagship):
    """Control: feeding the step an unplaced (differently-sharded)
    batch forces a device-to-device reshard per call — the transfer
    guard half catches it even on CPU (resharding IS guarded there,
    unlike host copies)."""
    fs, p, s, _, _ = toy_flagship
    k = jax.random.PRNGKey(2)
    t2 = jax.random.randint(k, (2, 16), 0, 64)
    l2 = jnp.roll(t2, -1, axis=-1)
    with pytest.raises(Exception, match="[Tt]ransfer"):
        with hot_path_guard("unplaced inputs"):
            fs.step(p, s, t2, l2)
