"""Spatial-parallel bottleneck tests: H-sharded vs unsharded parity,
forward and gradients — the multi-device parity check the reference does
with real GPUs for SpatialBottleneck (bottleneck.py:218-510)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.bottleneck import (
    Bottleneck,
    SpatialBottleneck,
    halo_exchange,
    spatial_conv2d,
)

# whole-module slow tier (ISSUE 2 CI satellite): every case here is
# an 8-device-mesh halo-exchange parity run (~60 s total)
pytestmark = pytest.mark.slow

SPATIAL = 4


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:SPATIAL]), ("spatial",))


def test_halo_exchange_rows(mesh):
    # global (1, 8, 1, 1) tensor with row index as value, 4-way H shard
    x = jnp.arange(8.0).reshape(1, 8, 1, 1)

    def f(xl):
        return halo_exchange(xl, "spatial", 1, 1)

    out = shard_map(f, mesh=mesh, in_specs=P(None, "spatial"),
                    out_specs=P(None, "spatial"))(x)
    out = np.asarray(out).reshape(SPATIAL, 4)  # 4 shards x (1+2+1) rows
    # shard 1 holds rows 2,3 -> halo-extended [1, 2, 3, 4]
    np.testing.assert_array_equal(out[1], [1, 2, 3, 4])
    # edge shards zero-padded
    np.testing.assert_array_equal(out[0], [0, 0, 1, 2])
    np.testing.assert_array_equal(out[3], [5, 6, 7, 0])


@pytest.mark.parametrize("stride,kh", [(1, 3), (2, 3), (1, 5)])
def test_spatial_conv_matches_global(mesh, stride, kh):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (2, 16, 8, 6))
    w = jax.random.normal(jax.random.fold_in(key, 1), (kh, 3, 6, 10))

    want = spatial_conv2d(x, w, stride=stride)

    f = functools.partial(spatial_conv2d, stride=stride, axis_name="spatial")
    got = shard_map(f, mesh=mesh, in_specs=(P(None, "spatial"), P()),
                    out_specs=P(None, "spatial"))(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("stride,stride_1x1", [(1, False), (2, False), (2, True)])
def test_spatial_bottleneck_matches_unsharded(mesh, stride, stride_1x1):
    block = Bottleneck(8, 4, 16, stride=stride, stride_1x1=stride_1x1)
    sblock = SpatialBottleneck(8, 4, 16, stride=stride, stride_1x1=stride_1x1,
                               axis_name="spatial")
    params = block.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 8, 8))

    want = block.apply(params, x)
    got = shard_map(sblock.apply, mesh=mesh, in_specs=(P(), P(None, "spatial")),
                    out_specs=P(None, "spatial"))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_spatial_bottleneck_grad_parity(mesh):
    """AD through ppermute derives the reference's hand-written backward
    halo exchange (dgrad/wgrad halo terms, bottleneck.py:289-510)."""
    block = Bottleneck(6, 4, 6, stride=1)
    sblock = SpatialBottleneck(6, 4, 6, stride=1, axis_name="spatial")
    params = block.init(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 4, 6))

    def loss_global(params, x):
        return jnp.sum(block.apply(params, x) ** 2)

    def loss_sharded(params, x):
        def inner(p, xl):
            partial = jnp.sum(sblock.apply(p, xl) ** 2)
            return jax.lax.psum(partial, "spatial")
        return shard_map(inner, mesh=mesh, in_specs=(P(), P(None, "spatial")),
                         out_specs=P())(params, x)

    gw_want, gx_want = jax.grad(loss_global, argnums=(0, 1))(params, x)
    gw_got, gx_got = jax.grad(loss_sharded, argnums=(0, 1))(params, x)
    np.testing.assert_allclose(np.asarray(gx_got), np.asarray(gx_want),
                               rtol=1e-4, atol=1e-5)
    for k in gw_want:
        np.testing.assert_allclose(np.asarray(gw_got[k]), np.asarray(gw_want[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_identity_residual_no_downsample():
    block = Bottleneck(8, 4, 8, stride=1)
    params = block.init(jax.random.PRNGKey(0))
    assert "conv4" not in params
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 8, 8))
    out = block.apply(params, x)
    assert out.shape == x.shape
    assert float(out.min()) >= 0.0  # final relu
