"""Data-parallel tier tests on the 8-device emulated CPU mesh.

Mirrors the reference's multi-process tests (SURVEY.md §4):
tests/distributed/synced_batchnorm/ (SyncBN vs single-device BN reference,
incl. different per-device batch), tests/distributed/DDP (grad correctness),
amp_master_params (replica consistency).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import parallel

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:N_DEV])
    return Mesh(devs, ("data",))


def _bn_ref(x, w, b, eps=1e-5):
    """Single-device full-batch BN over all axes but the last (NHWC)."""
    x32 = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    mean = x32.mean(axes)
    var = x32.var(axes)
    y = (x32 - mean) / jnp.sqrt(var + eps)
    return (y * w + b).astype(x.dtype)


class TestAllReduceGrads:
    def test_mean_reduction(self, mesh):
        grads = {"w": jax.random.normal(jax.random.PRNGKey(0), (N_DEV, 4, 4)),
                 "b": jax.random.normal(jax.random.PRNGKey(1), (N_DEV, 4))}

        f = shard_map(
            lambda g: parallel.all_reduce_grads(g, "data"),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"))
        out = f(grads)
        for k in grads:
            expect = jnp.broadcast_to(grads[k].mean(0, keepdims=True),
                                      grads[k].shape)
            np.testing.assert_allclose(out[k], expect, rtol=1e-6, atol=1e-6)

    def test_sum_reduction_and_predivide(self, mesh):
        g = jax.random.normal(jax.random.PRNGKey(0), (N_DEV, 8))

        out_sum = shard_map(
            lambda g: parallel.all_reduce_grads(g, "data", gradient_average=False),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"))(g)
        np.testing.assert_allclose(
            out_sum, jnp.broadcast_to(g.sum(0, keepdims=True), g.shape),
            rtol=1e-5, atol=1e-5)

        # predivide: same mean result, different reduction order
        out_pre = shard_map(
            lambda g: parallel.all_reduce_grads(
                g, "data", gradient_predivide_factor=4.0),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"))(g)
        np.testing.assert_allclose(
            out_pre, jnp.broadcast_to(g.mean(0, keepdims=True), g.shape),
            rtol=1e-5, atol=1e-5)

    def test_fp32_allreduce_of_bf16(self, mesh):
        g = (jax.random.normal(jax.random.PRNGKey(0), (N_DEV, 128)) * 1e-3
             ).astype(jnp.bfloat16)
        out = shard_map(
            lambda g: parallel.all_reduce_grads(
                g, "data", allreduce_always_fp32=True),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"))(g)
        assert out.dtype == jnp.bfloat16
        ref = g.astype(jnp.float32).mean(0)
        np.testing.assert_allclose(
            out[0].astype(jnp.float32), ref, rtol=2e-2, atol=1e-5)

    def test_broadcast_params(self, mesh):
        p = jax.random.normal(jax.random.PRNGKey(0), (N_DEV, 16))
        out = shard_map(
            lambda p: parallel.broadcast_params(p, "data"),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"))(p)
        for i in range(N_DEV):
            np.testing.assert_array_equal(out[i], p[0])


class TestSyncBatchNorm:
    def test_matches_full_batch_bn(self, mesh):
        # reference tests/distributed/synced_batchnorm: SyncBN over N devices
        # must equal single-device BN over the full batch.
        full = jax.random.normal(jax.random.PRNGKey(0), (16, 4, 4, 8))
        w = jnp.linspace(0.5, 1.5, 8)
        b = jnp.linspace(-0.2, 0.2, 8)

        bn = parallel.SyncBatchNorm(8, process_group="data")
        variables = bn.init()
        variables["params"] = {"weight": w, "bias": b}

        def step(x):
            y, new_vars = bn.apply(variables, x, training=True)
            return y, new_vars["state"]["running_mean"]

        y, rm = shard_map(step, mesh=mesh, in_specs=P("data"),
                          out_specs=(P("data"), P("data")))(full)
        np.testing.assert_allclose(y, _bn_ref(full, w, b), rtol=1e-4, atol=1e-4)
        # running stats identical on every device and correct
        np.testing.assert_allclose(
            rm.reshape(N_DEV, -1)[0],
            0.1 * full.astype(jnp.float32).mean((0, 1, 2)), rtol=1e-4, atol=1e-5)

    def test_grad_matches_full_batch_bn(self, mesh):
        full = jax.random.normal(jax.random.PRNGKey(2), (16, 8))
        w = jnp.full((8,), 1.2)
        b = jnp.zeros((8,))

        def loss_sync(x):
            def inner(xs):
                y, _, _ = parallel.sync_batch_norm(
                    xs, w, b, axis_name="data", training=True)
                return jax.lax.psum(jnp.sum(jnp.sin(y)), "data")
            return shard_map(inner, mesh=mesh, in_specs=P("data"),
                             out_specs=P())(x)

        def loss_ref(x):
            return jnp.sum(jnp.sin(_bn_ref(x, w, b)))

        g1 = jax.grad(loss_sync)(full)
        g2 = jax.grad(loss_ref)(full)
        np.testing.assert_allclose(g1, g2, rtol=1e-4, atol=1e-4)

    def test_eval_mode_uses_running_stats(self):
        bn = parallel.SyncBatchNorm(4, process_group=None)
        variables = bn.init()
        variables["state"] = {"running_mean": jnp.full((4,), 2.0),
                              "running_var": jnp.full((4,), 4.0)}
        x = jnp.ones((3, 4)) * 2.0
        y, _ = bn.apply(variables, x, training=False)
        np.testing.assert_allclose(y, jnp.zeros((3, 4)), atol=1e-5)

    def test_different_per_device_batch_weighting(self, mesh):
        # reference two_gpu_test_different_batch_size.py: stats must be
        # element-weighted. Here every device has equal shape (SPMD), so we
        # check the count-weighted merge math against a lopsided manual split.
        x = jax.random.normal(jax.random.PRNGKey(3), (8, 2, 4))
        mean, var, n = shard_map(
            lambda xs: parallel.sync_batch_norm_stats(xs, "data"),
            mesh=mesh, in_specs=P("data"), out_specs=(P("data"), P("data"), P()),
        )(x)
        assert float(n) == x.size // x.shape[-1]
        np.testing.assert_allclose(mean.reshape(N_DEV, -1)[0],
                                   x.mean((0, 1)), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(var.reshape(N_DEV, -1)[0],
                                   x.var((0, 1)), rtol=1e-4, atol=1e-5)

    def test_convert_and_group_helpers(self):
        class FakeBN:
            num_features = 32
            eps = 1e-4
            momentum = 0.05
            affine = True
            track_running_stats = True

        tree = {"bn1": FakeBN(), "inner": [FakeBN(), "not-bn"]}
        out = parallel.convert_syncbn_model(tree)
        assert isinstance(out["bn1"], parallel.SyncBatchNorm)
        assert out["bn1"].eps == 1e-4
        assert isinstance(out["inner"][0], parallel.SyncBatchNorm)
        assert out["inner"][1] == "not-bn"

        assert parallel.create_syncbn_process_group(2, 8) == ("data_outer", "data_bn")
        with pytest.raises(ValueError):
            parallel.create_syncbn_process_group(3, 8)
