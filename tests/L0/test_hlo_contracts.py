"""ISSUE 13 — compiled-artifact contract checker (`apex_tpu.analysis
hlo`).

Five layers:

1. parser units on SYNTHETIC HLO fixtures — aliasing header entries,
   the anchored collective-opcode discipline (``all-gather-start.3``
   counts once, a pass-named row like ``all-reduce-promotion`` never
   counts), async start/done pairs counted once, while-body
   collectives counted once (the flops-parser caveat, documented),
   shape→bytes, host-op detection;
2. REAL small executables proving the report reads what the compiler
   delivered — donation present/stripped, a deliberately doubled
   psum, an injected host callback;
3. the acceptance controls against the COMMITTED contracts: a
   donate-stripped decode fails the aliasing contract, a
   callback-wrapped decode fails the host-op contract;
4. the tier-1 GATE: every registered executable compiles, reports,
   and passes the committed ``hlo_contracts.json`` with zero
   violations, zero missing entries, zero stale entries;
5. CLI exit-code discipline (0 clean / 1 violations-or-stale / 2
   missing-or-unparseable — the r4 ``parsed:null`` lesson), the
   ``--update`` workflow, the geometry provenance stamp, and the
   serving doc-drift pin (module docstring == docs table ==
   ``SERVING_EXECUTABLES`` == registry).
"""

import json
import os
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.analysis import hlo as H
from apex_tpu.analysis import registry as R
from apex_tpu.analysis.__main__ import main as analysis_main
from apex_tpu.analysis.hlo import (check_contract, check_reports,
                                   collective_inventory,
                                   contract_from_report,
                                   executable_report,
                                   host_interaction_ops, load_contracts,
                                   parse_aliases)

pytestmark = pytest.mark.analysis

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
CONTRACTS = os.path.join(REPO_ROOT, "hlo_contracts.json")


# ---------------------------------------------------------------------------
# 1. parser units on synthetic HLO
# ---------------------------------------------------------------------------

SYNTH_HLO = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (1, {}, may-alias), {1,0}: (2, {}) }, entry_computation_layout={(f32[8,128]{1,0})->f32[8,128]{1,0}}

%add.clone (x.1: f32[], y.1: f32[]) -> f32[] {
  %x.1 = f32[] parameter(0)
  %y.1 = f32[] parameter(1)
  ROOT %add.2 = f32[] add(f32[] %x.1, f32[] %y.1)
}

%while_body (p.1: (s32[], f32[256])) -> (s32[], f32[256]) {
  %p.1 = (s32[], f32[256]{0}) parameter(0)
  %gte.1 = f32[256]{0} get-tuple-element((s32[], f32[256]{0}) %p.1), index=1
  %all-reduce.7 = f32[256]{0} all-reduce(f32[256]{0} %gte.1), replica_groups={}, to_apply=%add.clone
  ROOT %tuple.9 = (s32[], f32[256]{0}) tuple(%gte.1, %all-reduce.7)
}

ENTRY %main.42 (p0.1: f32[8,128]) -> f32[8,128] {
  %p0.1 = f32[8,128]{1,0} parameter(0)
  %all-gather-start.3 = (f32[8,128]{1,0}, f32[16,128]{1,0}) all-gather-start(f32[8,128]{1,0} %p0.1), dimensions={0}
  %all-gather-done.3 = f32[16,128]{1,0} all-gather-done(%all-gather-start.3)
  %reduce-scatter-decomposer = f32[8,128]{1,0} bitcast(f32[8,128]{1,0} %p0.1)
  %pass.1 = f32[8,128]{1,0} all-reduce-promotion(f32[8,128]{1,0} %p0.1)
  %rs.1 = f32[4,128]{1,0} reduce-scatter(f32[8,128]{1,0} %p0.1), dimensions={0}
  %cb.1 = f32[4]{0} custom-call(f32[8,128]{1,0} %p0.1), custom_call_target="xla_python_cpu_callback"
  %pallas.1 = f32[4]{0} custom-call(f32[8,128]{1,0} %p0.1), custom_call_target="tpu_custom_call"
  %of.1 = token[] outfeed(f32[4]{0} %cb.1)
  %send.5 = (f32[4]{0}, u32[], token[]) send(f32[4]{0} %cb.1), channel_id=1
  %send-done.5 = token[] send-done((f32[4]{0}, u32[], token[]) %send.5), channel_id=1
  %w.1 = (s32[], f32[256]{0}) while((s32[], f32[256]{0}) %w.1), condition=%add.clone, body=%while_body
  ROOT %copy.1 = f32[8,128]{1,0} copy(f32[8,128]{1,0} %p0.1)
}
"""


def test_parse_aliases_from_header():
    pairs = parse_aliases(SYNTH_HLO)
    assert [(a.param_number, a.output_index, a.kind) for a in pairs] == [
        (1, "0", "may-alias"), (2, "1,0", "may-alias")]
    # no header entry -> no aliases (the donation-stripped signature)
    assert parse_aliases("HloModule jit_f, is_scheduled=true\n") == []
    # layout braces / buffer_donor entries never parse as aliases
    assert parse_aliases(
        "HloModule j, buffer_donor={ {2} }, entry_computation_layout="
        "{(f32[8,128]{1,0})->f32[8,128]{1,0}}\n") == []


def test_collective_inventory_anchored_async_and_while_once():
    inv = collective_inventory(SYNTH_HLO)
    # all-gather: the -start row counts ONCE under the base opcode;
    # the -done half is skipped
    assert inv["all-gather"]["count"] == 1
    # the while-body all-reduce appears once in the text, so it counts
    # once regardless of trip count — the same stated undercount as
    # the HLO flops parser (hlo.py module docstring)
    assert inv["all-reduce"]["count"] == 1
    assert inv["reduce-scatter"]["count"] == 1
    # anchoring: the bitcast NAMED reduce-scatter-decomposer and the
    # pass-named all-reduce-promotion row contribute nothing
    assert set(inv) == {"all-gather", "all-reduce", "reduce-scatter"}


def test_collective_bytes_from_shapes():
    inv = collective_inventory(SYNTH_HLO)
    # start-row tuple (f32[8,128], f32[16,128]) -> 4096 + 8192
    assert inv["all-gather"]["bytes"] == 12288
    assert inv["all-reduce"]["bytes"] == 256 * 4
    assert inv["reduce-scatter"]["bytes"] == 4 * 128 * 4


def test_host_interaction_ops_detection():
    ops = host_interaction_ops(SYNTH_HLO)
    kinds = [(h.opcode, h.target) for h in ops]
    # callback custom-call, outfeed, send (send-done pairs with it);
    # the Pallas tpu_custom_call is NOT host interaction
    assert ("custom-call", "xla_python_cpu_callback") in kinds
    assert ("outfeed", "") in kinds
    assert ("send", "") in kinds
    assert len(ops) == 3
    assert not any(h.target == "tpu_custom_call" for h in ops)


def test_opcode_histogram_shared_with_profiling():
    from apex_tpu.profiling import opcode_histogram_from_text

    hist = opcode_histogram_from_text(SYNTH_HLO)
    assert hist["all-reduce"] == 1
    assert hist["copy"] == 1
    assert hist["parameter"] >= 2
    # tuple-shaped rows count too (review-found: the old \S+ shape
    # group could not span the space inside a tuple shape, silently
    # dropping every async -start / send / while row)
    assert hist["all-gather-start"] == 1
    assert hist["send"] == 1
    assert hist["while"] == 1


def test_check_contract_directions():
    rep = H.ExecutableReport(
        name="x",
        aliasing=[H.AliasPair("0", 1)],
        collectives={"all-reduce": {"count": 2, "bytes": 64}},
        host_ops=[H.HostOp("custom-call", "cb.1",
                           "xla_python_cpu_callback")],
        opcode_histogram={}, argument_bytes=0, output_bytes=0,
        temp_bytes=100, flops=0.0)
    clean = {"required_aliases": [{"param": 1, "output": "0"}],
             "max_collectives": {"all-reduce": 2},
             "allow_host_ops": ["callback"],
             "max_temp_bytes": 100}
    assert check_contract(rep, clean) == []
    # one-sided: fewer collectives / more aliases / smaller temp pass
    rep2 = H.ExecutableReport("x", [H.AliasPair("0", 1),
                                    H.AliasPair("1", 2)],
                              {}, [], {}, 0, 0, 0, 0.0)
    assert check_contract(rep2, clean) == []
    # each violation class fires
    assert any("aliasing" in v for v in check_contract(
        rep, {**clean, "required_aliases": [{"param": 9, "output": "0"}]}))
    assert any("collectives" in v for v in check_contract(
        rep, {**clean, "max_collectives": {"all-reduce": 1}}))
    assert any("host interaction" in v for v in check_contract(
        rep, {**clean, "allow_host_ops": []}))
    assert any("temp bytes" in v for v in check_contract(
        rep, {**clean, "max_temp_bytes": 99}))
    # review-found: an allow entry naming a host OPCODE must not
    # substring-match custom-call targets — a blessed `send` op must
    # not whitelist a callback whose target merely contains "send"
    sneaky = H.ExecutableReport(
        "x", [], {}, [H.HostOp("custom-call", "cb.2",
                               "host_send_buffer_to_somewhere")],
        {}, 0, 0, 0, 0.0)
    assert any("host interaction" in v for v in check_contract(
        sneaky, {"allow_host_ops": ["send"]}))
    assert check_contract(
        sneaky, {"allow_host_ops": ["host_send_buffer"]}) == []


# ---------------------------------------------------------------------------
# 4. the tier-1 gate (early: warms the registry's report cache for
#    the controls below)
# ---------------------------------------------------------------------------


def test_hlo_contract_gate_zero_violations():
    """THE gate: every registered executable builds, and the committed
    hlo_contracts.json passes with zero violations / missing / stale."""
    reports, errors = R.build_all_reports()
    assert errors == {}, errors
    assert len(reports) >= 8   # 5 serving + flagship + flat adam + reshard
    doc = load_contracts(CONTRACTS)
    res = check_reports(reports, doc,
                        registry_names=R.registered_executables())
    assert res.missing == []
    assert res.stale == []
    assert {k: v for k, v in res.violations.items() if v} == {}
    assert res.exit_code == 0


def test_serving_tp_builders_ignore_ambient_parallel_state():
    """r17 regression pin: the serving_tp_* builders lower the pinned
    tp=2 cpu-toy geometry even when a surrounding process has the
    global model-parallel state registered with a DIFFERENT tensor
    world (the exact leak a module-scoped training fixture can leave
    behind mid-suite).  Without ``uninitialized_scope`` this raises
    ``tp=2 does not match the initialized tensor-parallel world size
    1`` and the gate above reports builder errors."""
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        1, 1, devices=jax.devices()[:1])
    R._toy_engine_tp.cache_clear()
    R._serving_tp_lowered.cache_clear()
    try:
        lowered = R._serving_tp_lowered()
        # the sweep lowers all five executables; the registry registers
        # the hot-path subset
        assert set(R.SERVING_TP_EXECUTABLES) <= set(lowered)
        # and the ambient state survived the build untouched
        assert parallel_state.get_tensor_model_parallel_world_size() == 1
    finally:
        parallel_state.destroy_model_parallel()
        R._toy_engine_tp.cache_clear()
        R._serving_tp_lowered.cache_clear()


def test_committed_contracts_pin_the_properties_that_matter():
    """The committed entries encode the real invariants: serving is
    communication-lean and host-silent with the pool donation
    verified; the flagship entry is ROADMAP item 3's measured
    collective baseline."""
    doc = load_contracts(CONTRACTS)
    execs = doc["executables"]
    for name in ("serving_decode", "serving_verify", "serving_chunk",
                 "serving_admission_scatter"):
        e = execs[name]
        # both pool buffers' donation machine-verified (768 MB lesson)
        assert len(e["required_aliases"]) >= 2, name
        assert e["max_collectives"] == {}, name
        assert e["allow_host_ops"] == [], name
    fl = execs["flagship_dp_tp_step"]
    assert fl["max_collectives"].get("all-reduce", 0) >= 1
    assert fl["max_collectives"].get("reduce-scatter", 0) >= 1
    assert fl["required_aliases"]   # donated params + opt state
    assert fl["inventory"]["collective_bytes"]  # the item-3 baseline
    za = execs["zero_flat_adam_update"]
    assert len(za["required_aliases"]) >= 3  # params + both moments
    rs = execs["reshard_stack"]
    assert rs["max_collectives"] == {} and rs["allow_host_ops"] == []


def test_contracts_geometry_stamp():
    """Satellite: the committed file self-declares cpu-toy provenance
    (the BENCH_r10/r12 lesson — absolute bytes are gate fixtures, not
    flagship-scale truth), and an unstamped file refuses to load."""
    doc = json.load(open(CONTRACTS))
    assert doc["format"] == 1
    assert doc["geometry"] == "cpu-toy"
    assert "cpu-toy" in doc["comment"]


def test_unstamped_contracts_refuse_to_load(tmp_path):
    p = tmp_path / "c.json"
    p.write_text(json.dumps({"format": 1, "executables": {}}))
    with pytest.raises(H.ContractFileError, match="geometry"):
        load_contracts(str(p))


# ---------------------------------------------------------------------------
# 2. real executables: the report reads what the compiler delivered
# ---------------------------------------------------------------------------


def test_donation_report_on_real_executable():
    def f(pool, tok):
        return pool + tok, tok * 2

    x = jax.ShapeDtypeStruct((8, 128), jnp.float32)
    rep = executable_report(
        "donated", jax.jit(f, donate_argnums=(0,)).lower(x, x).compile())
    assert [(a.param_number, a.output_index) for a in rep.aliasing] \
        == [(0, "0")]
    stripped = executable_report(
        "stripped", jax.jit(f).lower(x, x).compile())
    assert stripped.aliasing == []
    contract = contract_from_report(rep)
    assert check_contract(rep, contract) == []
    v = check_contract(stripped, contract)
    assert any("donation did not survive" in s for s in v)


def test_doubled_collective_fails_inventory_contract():
    """Acceptance control: a deliberately doubled collective fails the
    committed-style inventory contract built from the single form."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:2]), ("x",))

    def once(x):
        return jax.lax.psum(x, "x")

    def twice(x):
        return jax.lax.psum(jax.lax.psum(x, "x"), "x")

    def rep_of(fn, name):
        sm = shard_map(fn, mesh=mesh, in_specs=P("x"), out_specs=P(),
                       check_rep=False)
        arr = jnp.ones((2, 8), jnp.float32)
        return executable_report(name, jax.jit(sm).lower(arr).compile())

    r1 = rep_of(once, "once")
    r2 = rep_of(twice, "twice")
    assert r1.collectives["all-reduce"]["count"] == 1
    assert r2.collectives["all-reduce"]["count"] == 2
    contract = contract_from_report(r1)
    assert check_contract(r1, contract) == []
    v = check_contract(r2, contract)
    assert any("all-reduce x2 exceeds" in s for s in v)


# ---------------------------------------------------------------------------
# 3. acceptance controls against the COMMITTED serving contracts
# ---------------------------------------------------------------------------


def _committed(name):
    return load_contracts(CONTRACTS)["executables"][name]


def test_donate_stripped_decode_fails_aliasing_contract():
    """Acceptance control: strip the decode step's pool donation and
    the committed aliasing contract fails — donation is now a
    machine-checked property, not a trusted kwarg."""
    eng = R._toy_engine()
    low = eng.analysis_executables(donate=False)["decode"]
    rep = executable_report("serving_decode", low.compile())
    v = check_contract(rep, _committed("serving_decode"))
    assert any("donation did not survive" in s for s in v)
    # ... and the shipped (donating) artifact passes the same entry
    ok = R.build_report("serving_decode")
    assert check_contract(ok, _committed("serving_decode")) == []


def test_donate_stripped_scatter_fails_aliasing_contract():
    eng = R._toy_engine()
    low = eng.cache.analysis_executable(eng.prefill_budget, donate=False)
    rep = executable_report("serving_admission_scatter", low.compile())
    v = check_contract(rep, _committed("serving_admission_scatter"))
    assert any("donation did not survive" in s for s in v)


def test_injected_host_callback_fails_host_contract():
    """Acceptance control: wrap the decode step with a host callback
    (the way a stray debug hook would) and the committed host-op
    contract fails — 'zero host interaction' is machine-checked."""
    eng = R._toy_engine()
    fn, _donate = eng._exec_defs["decode"]
    structs = eng._executable_arg_structs()["decode"]

    def with_callback(*args):
        tok, k, v = fn(*args)
        tok = jax.pure_callback(
            lambda t: t, jax.ShapeDtypeStruct(tok.shape, tok.dtype), tok)
        return tok, k, v

    rep = executable_report(
        "decode_cb", jax.jit(with_callback).lower(*structs).compile())
    assert rep.host_ops
    v = check_contract(rep, _committed("serving_decode"))
    assert any("host interaction" in s for s in v)


def test_flat_adam_donation_verified_and_strippable():
    from apex_tpu.optimizers.flat import FlatAdamState, FlatFusedAdam

    opt = FlatFusedAdam()
    buf = jax.ShapeDtypeStruct((R.FLAT_ADAM_N,), jnp.float32)
    st = FlatAdamState(step=jax.ShapeDtypeStruct((), jnp.int32),
                       exp_avg=buf, exp_avg_sq=buf)
    rep = executable_report(
        "zero_flat_adam_update",
        opt.jit_step(donate=False).lower(buf, st, buf).compile())
    v = check_contract(rep, _committed("zero_flat_adam_update"))
    assert any("donation did not survive" in s for s in v)


# ---------------------------------------------------------------------------
# ISSUE 15: the ratcheted bucketed-flagship contract
# ---------------------------------------------------------------------------


def test_serialized_flagship_step_fails_ratcheted_contract():
    """THE ratchet control (ISSUE 15 satellite): the pre-r15 serialized
    construction — per-leaf boundary grad all-reduces feeding one
    monolithic scatter/gather — must FAIL the committed (ratcheted)
    ``flagship_dp_tp_step`` entry on its all-reduce count, while the
    shipped bucketed artifact passes the same entry.  The ratchet is a
    one-way door: the serialized inventory cannot silently come
    back."""
    rep = executable_report(
        "flagship_serialized",
        R.flagship_serialized_lowered().compile())
    contract = _committed("flagship_dp_tp_step")
    # the old inventory really is the committed "before" baseline:
    # 30 all-reduces, one reduce-scatter, one all-gather (PR 13)
    assert rep.collectives["all-reduce"]["count"] == 30
    assert rep.collectives["reduce-scatter"]["count"] == 1
    assert rep.collectives["all-gather"]["count"] == 1
    v = check_contract(rep, contract)
    assert any("all-reduce x30 exceeds" in s for s in v), v
    # ...and the shipped bucketed step passes the entry it ratcheted
    ok = R.build_report("flagship_dp_tp_step")
    assert check_contract(ok, contract) == []


def test_ratcheted_flagship_entry_pins_the_bucketed_inventory():
    """The committed entry proves the tentpole structurally: the
    all-reduce cap dropped WELL below the serialized 30 (only the
    model's tp activation collectives remain), the scatter/gather pair
    became per-bucket (several of each), the all-reduce byte inventory
    collapsed (the replicated-master-grad transfers are gone), and
    end-to-end donation survived (params + opt-state leaves all
    aliased)."""
    fl = _committed("flagship_dp_tp_step")
    caps = fl["max_collectives"]
    assert caps["all-reduce"] < 30, caps
    assert caps["reduce-scatter"] > 1, caps
    assert caps["all-gather"] == caps["reduce-scatter"], caps
    # the grad traffic moved out of all-reduce: remaining AR bytes are
    # activation-sized, an order of magnitude under the old 7.5 MB
    assert fl["inventory"]["collective_bytes"]["all-reduce"] < 2_000_000
    assert len(fl["required_aliases"]) >= 19


def test_bucketed_flat_adam_contract_donates_end_to_end():
    """The new bucketed executable's entry: per-span kernel launches
    still donate params + both moments at the entry boundary (4 alias
    pairs — the concat reassembly did not break XLA's aliasing) with
    zero collectives and zero host interaction."""
    e = _committed("zero_flat_adam_update_bucketed")
    assert len(e["required_aliases"]) >= 4
    assert e["max_collectives"] == {}
    assert e["allow_host_ops"] == []
    ok = R.build_report("zero_flat_adam_update_bucketed")
    assert check_contract(ok, e) == []


# ---------------------------------------------------------------------------
# engine exposure: analysis shapes ARE the served shapes
# ---------------------------------------------------------------------------


def test_analysis_shapes_match_warmup_zero_recompiles():
    """No-drift pin: after warmup(), launching every executable with
    arguments built from _executable_arg_structs compiles NOTHING —
    the analyzed artifacts are the served artifacts, by construction."""
    from apex_tpu.analysis import hot_path_guard

    eng = R._toy_engine()
    eng.warmup()
    structs = eng._executable_arg_structs()
    zeros = {name: tuple(
        jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), a)
        for a in args) for name, args in structs.items()}
    L, S = eng.cfg.num_layers, eng.prefill_budget
    kz = jnp.zeros((L, S, eng.cfg.num_heads, eng.cfg.head_dim),
                   eng.cache.k.dtype)
    iz = np.zeros((S,), np.int32)
    jitted = {"prefill": eng._prefill_fn, "decode": eng._decode_fn,
              "verify": eng._verify_fn, "chunk": eng._chunk_fn}
    with hot_path_guard("analysis-shapes", max_recompiles=0,
                        transfers=None, tripwire=False):
        for name, fn in jitted.items():
            fn(*zeros[name])
        eng.cache.write_tokens(kz, kz, iz, iz)


def test_toy_engine_enables_all_five_executables():
    from apex_tpu.serving.engine import SERVING_EXECUTABLES

    lowered = R._toy_engine().analysis_executables()
    assert tuple(lowered) == SERVING_EXECUTABLES


# ---------------------------------------------------------------------------
# reshard device twin
# ---------------------------------------------------------------------------


def test_reshard_stack_device_matches_host_contract():
    from apex_tpu.multi_tensor.flat import (reshard_stack,
                                            reshard_stack_device)

    val = np.arange(4 * 2 * 8, dtype=np.float32).reshape(4, 2, 8)
    # constant world size: (4, 2, ·) -> (8, ·) C-order merge
    want = (8, 8)
    np.testing.assert_array_equal(
        np.asarray(reshard_stack_device(val, want)),
        reshard_stack(val, 2, want))
    # growth: schema tail zero-fills, same as the host contract
    want2 = (2, 40)
    np.testing.assert_array_equal(
        np.asarray(reshard_stack_device(val, want2)),
        reshard_stack(val, 2, want2))
    # trims are a host-side decision — the device twin refuses
    with pytest.raises(ValueError, match="grows or keeps size"):
        reshard_stack_device(val, (4, 8))


# ---------------------------------------------------------------------------
# 5. CLI exit codes (satellite: 0 / 1 / 2, all self-tested)
# ---------------------------------------------------------------------------


def test_cli_exit_0_clean(capsys):
    rc = analysis_main(["hlo", "--contracts", CONTRACTS,
                        "--only", "reshard_stack"])
    assert rc == 0
    assert "0 violation(s)" in capsys.readouterr().out


def test_cli_exit_1_on_violation(tmp_path, capsys):
    doc = {"format": 1, "geometry": "cpu-toy", "executables": {
        "reshard_stack": {
            "required_aliases": [{"param": 0, "output": "0"}],
            "max_collectives": {}, "allow_host_ops": [],
            "max_temp_bytes": 0}}}
    p = tmp_path / "c.json"
    p.write_text(json.dumps(doc))
    rc = analysis_main(["hlo", "--contracts", str(p),
                        "--only", "reshard_stack"])
    assert rc == 1
    assert "donation did not survive" in capsys.readouterr().out


def test_cli_exit_1_on_stale_entry(tmp_path, capsys):
    """A contract for a deleted executable fails LOUDLY (the PR 11
    stale-baseline discipline) — it cannot ride along green."""
    doc = json.load(open(CONTRACTS))
    doc["executables"]["serving_deleted_step"] = \
        doc["executables"]["reshard_stack"]
    p = tmp_path / "c.json"
    p.write_text(json.dumps(doc))
    rc = analysis_main(["hlo", "--contracts", str(p),
                        "--only", "reshard_stack"])
    assert rc == 1
    assert "stale contract entry" in capsys.readouterr().out


def test_cli_exit_2_missing_file(tmp_path, capsys):
    rc = analysis_main(["hlo", "--contracts",
                        str(tmp_path / "nope.json"),
                        "--only", "reshard_stack"])
    assert rc == 2
    assert "not found" in capsys.readouterr().err


def test_cli_exit_2_unparseable_file(tmp_path, capsys):
    """The r4 parsed:null lesson: an unreadable gate exits 2, never
    green."""
    p = tmp_path / "c.json"
    p.write_text('{"format": 1, "geometry": "cpu-toy", "executab')
    rc = analysis_main(["hlo", "--contracts", str(p),
                        "--only", "reshard_stack"])
    assert rc == 2
    assert "unparseable" in capsys.readouterr().err


def test_cli_exit_2_missing_contract_entry(tmp_path, capsys):
    p = tmp_path / "c.json"
    p.write_text(json.dumps(
        {"format": 1, "geometry": "cpu-toy", "executables": {}}))
    rc = analysis_main(["hlo", "--contracts", str(p),
                        "--only", "reshard_stack"])
    assert rc == 2
    assert "no contract entry" in capsys.readouterr().out


def test_cli_exit_2_unknown_executable(capsys):
    rc = analysis_main(["hlo", "--contracts", CONTRACTS,
                        "--only", "no_such_executable"])
    assert rc == 2
    assert "unknown executable" in capsys.readouterr().err


def test_cli_update_roundtrip(tmp_path, capsys):
    p = tmp_path / "c.json"
    rc = analysis_main(["hlo", "--update", "--contracts", str(p),
                        "--only", "reshard_stack"])
    assert rc == 0
    doc = json.load(open(p))
    assert doc["format"] == 1 and doc["geometry"] == "cpu-toy"
    assert "reshard_stack" in doc["executables"]
    rc = analysis_main(["hlo", "--contracts", str(p),
                        "--only", "reshard_stack"])
    assert rc == 0
    capsys.readouterr()


def test_cli_json_report(capsys):
    rc = analysis_main(["hlo", "--contracts", CONTRACTS,
                        "--only", "reshard_stack", "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["exit_code"] == 0
    assert doc["geometry"] == "cpu-toy"
    assert "reshard_stack" in doc["reports"]


# ---------------------------------------------------------------------------
# doc drift: docstring == docs table == SERVING_EXECUTABLES == registry
# ---------------------------------------------------------------------------

_WORDS = {"one": 1, "two": 2, "three": 3, "four": 4, "five": 5,
          "six": 6, "seven": 7, "eight": 8, "nine": 9, "ten": 10}


def test_serving_docstring_matches_docs_table_and_registry():
    """Satellite: the engine docstring's executable count, the
    docs/serving.md compiled-shapes table, the SERVING_EXECUTABLES
    tuple, and the checker registry's serving entries all agree — the
    ISSUE 12 'two compiled' docstring drift class, made impossible."""
    import apex_tpu.serving.engine as E

    m = re.search(r"fixed set of (\w+) compiled executables",
                  " ".join(E.__doc__.split()))
    assert m, "engine docstring lost its executable-count anchor"
    n = _WORDS[m.group(1)]
    assert n == len(E.SERVING_EXECUTABLES)

    md = open(os.path.join(REPO_ROOT, "docs", "serving.md")).read()
    section = md.split("## The compiled-shapes contract")[1].split("\n## ")[0]
    rows = re.findall(r"^\| \d+ \|", section, re.M)
    assert len(rows) == n

    serving_entries = [x for x in R.registered_executables()
                      if x.startswith("serving_")]
    base = [x for x in serving_entries if not x.startswith("serving_tp_")]
    assert base == [f"serving_{x}" for x in E.SERVING_EXECUTABLES]
    # r17: the tp-sharded serving modes register their own family —
    # every entry names an executable from the SAME compiled set (the
    # tp engine changes sharding and pool dtype, not the shape table)
    tp = [x for x in serving_entries if x.startswith("serving_tp_")]
    from apex_tpu.analysis.registry import SERVING_TP_EXECUTABLES
    assert tp == [f"serving_tp_{x}" for x in SERVING_TP_EXECUTABLES]
    assert set(SERVING_TP_EXECUTABLES) <= set(E.SERVING_EXECUTABLES)
