"""End-to-end Megatron GPT/BERT tests on the 8-device emulated mesh.

Mirrors the reference's canonical integration tests (SURVEY.md §4):
run_megatron_gpt_pipeline.py (GPT fwd+bwd under PP, loss parity vs
single-stage), run_bert_minimal_test.py, with TP sharding checked against a
tp=1 run of the same master weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving,
)
from apex_tpu.transformer.testing import (
    BertConfig,
    BertModel,
    GPTConfig,
    GPTModel,
    make_gpt_stage_fns,
)

VOCAB = 32
SEQ = 8
B = 4


def _tokens(key, b=B):
    return jax.random.randint(key, (b, SEQ), 0, VOCAB)


def _serial_gpt_loss(cfg1, master, tokens, labels):
    """tp=1 reference run on the master weights (single device semantics
    inside a world-spanning shard_map so axis names resolve)."""
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(1, 1)
    model = GPTModel(cfg1)
    sharded = model.shard_master(master, 0)

    def run(p, t, l):
        return jnp.mean(model.apply(p, t, labels=l))

    out = shard_map(run, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
                    check_rep=False)(sharded, tokens, labels)
    parallel_state.destroy_model_parallel()
    return out


class TestGPTTensorParallel:
    @pytest.mark.slow  # 8-device TP4 parity (ISSUE 2 CI satellite)
    def test_tp4_matches_tp1(self):
        # reference run_layers_test/run_megatron_gpt: same master weights,
        # different tp -> identical loss
        cfg1 = GPTConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                         vocab_size=VOCAB, max_position_embeddings=SEQ,
                         tp_size=1)
        master = GPTModel(cfg1).init_master(jax.random.PRNGKey(0))
        tokens = _tokens(jax.random.PRNGKey(1))
        labels = _tokens(jax.random.PRNGKey(2))
        ref = _serial_gpt_loss(cfg1, master, tokens, labels)

        cfg4 = GPTConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                         vocab_size=VOCAB, max_position_embeddings=SEQ,
                         tp_size=4)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(4, 1)
        model = GPTModel(cfg4)
        shards = [model.shard_master(master, r) for r in range(4)]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)

        def run(p, t, l):
            p = jax.tree_util.tree_map(lambda a: a[0], p)
            return jnp.mean(model.apply(p, t, labels=l))

        out = shard_map(run, mesh=mesh, in_specs=(P("tensor"), P(), P()),
                        out_specs=P(), check_rep=False)(stacked, tokens, labels)
        parallel_state.destroy_model_parallel()
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)

    @pytest.mark.slow  # heaviest 8-device parity tier (ISSUE 6 wall-clock)
    def test_gpt_grads_flow(self):
        cfg = GPTConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                        vocab_size=VOCAB, max_position_embeddings=SEQ,
                        tp_size=2)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(2, 1)
        model = GPTModel(cfg)
        master = GPTModel(GPTConfig(**{**cfg.__dict__, "tp_size": 1})
                          ).init_master(jax.random.PRNGKey(0))
        shards = [model.shard_master(master, r) for r in range(2)]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)
        tokens = _tokens(jax.random.PRNGKey(1))
        labels = _tokens(jax.random.PRNGKey(2))

        def loss(p, t, l):
            p = jax.tree_util.tree_map(lambda a: a[0], p)
            return jnp.mean(model.apply(p, t, labels=l))

        def run(p, t, l):
            return jax.value_and_grad(loss)(p, t, l)

        lv, grads = shard_map(run, mesh=mesh,
                              in_specs=(P("tensor"), P(), P()),
                              out_specs=(P(), P("tensor")),
                              check_rep=False)(stacked, tokens, labels)
        parallel_state.destroy_model_parallel()
        assert np.isfinite(float(lv))
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(bool(jnp.all(jnp.isfinite(g))) for g in leaves)
        assert max(float(jnp.abs(g).max()) for g in leaves) > 0


class TestGPTPipeline:
    @pytest.mark.slow  # heaviest 8-device parity tier (ISSUE 6 wall-clock)
    def test_pp4_loss_matches_single_stage(self):
        # the reference's headline assertion (run_megatron_gpt_pipeline.py:78):
        # pipeline-parallel GPT loss == single-stage loss
        PP = 4
        N_MICRO = 4
        cfg = GPTConfig(num_layers=4, hidden_size=32, num_attention_heads=4,
                        vocab_size=VOCAB, max_position_embeddings=SEQ,
                        tp_size=1)
        master = GPTModel(cfg).init_master(jax.random.PRNGKey(0))
        tokens = _tokens(jax.random.PRNGKey(1), b=N_MICRO * 2)
        labels = _tokens(jax.random.PRNGKey(2), b=N_MICRO * 2)
        ref = _serial_gpt_loss(cfg, master, tokens, labels)

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, PP)
        stage_fn, loss_fn = make_gpt_stage_fns(cfg, PP)

        # stage s params: its layer slice + (embedding, head on all stages
        # for SPMD-uniform structure; only first/last use them)
        per_layer = cfg.num_layers // PP

        def stage_params(s):
            p = GPTModel(cfg, num_layers=per_layer).shard_master(
                {**master,
                 "transformer": {"layers": jax.tree_util.tree_map(
                     lambda a: a[s * per_layer:(s + 1) * per_layer],
                     master["transformer"]["layers"])}}, 0)
            return p

        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[stage_params(s) for s in range(PP)])
        microbatches = {
            "tokens": tokens.reshape(N_MICRO, 2, SEQ),
            "labels": labels.reshape(N_MICRO, 2, SEQ),
        }

        def run(p, mb):
            p = jax.tree_util.tree_map(lambda a: a[0], p)
            (loss,) = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, p, mb,
                n_microbatches=N_MICRO,
                tensor_shape=(2, SEQ, cfg.hidden_size),
                forward_only=True)
            return loss

        out = shard_map(run, mesh=mesh, in_specs=(P("pipeline"), P()),
                        out_specs=P(), check_rep=False)(stacked, microbatches)
        parallel_state.destroy_model_parallel()
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)

    @pytest.mark.slow  # heaviest 8-device parity tier (ISSUE 6 wall-clock)
    def test_pp_training_decreases_loss(self):
        PP = 2
        N_MICRO = 4
        cfg = GPTConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                        vocab_size=VOCAB, max_position_embeddings=SEQ,
                        tp_size=1)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, PP)
        stage_fn, loss_fn = make_gpt_stage_fns(cfg, PP)
        per_layer = cfg.num_layers // PP
        master = GPTModel(cfg).init_master(jax.random.PRNGKey(0))

        def stage_params(s):
            return GPTModel(cfg, num_layers=per_layer).shard_master(
                {**master,
                 "transformer": {"layers": jax.tree_util.tree_map(
                     lambda a: a[s * per_layer:(s + 1) * per_layer],
                     master["transformer"]["layers"])}}, 0)

        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[stage_params(s) for s in range(PP)])
        tokens = _tokens(jax.random.PRNGKey(1), b=N_MICRO * 2)
        mb = {"tokens": tokens.reshape(N_MICRO, 2, SEQ),
              "labels": jnp.roll(tokens, -1, axis=-1).reshape(N_MICRO, 2, SEQ)}

        @jax.jit
        def train_step(p, mb):
            def run(p, mb):
                p_local = jax.tree_util.tree_map(lambda a: a[0], p)
                loss, grads = forward_backward_pipelining_without_interleaving(
                    stage_fn, loss_fn, p_local, mb,
                    n_microbatches=N_MICRO,
                    tensor_shape=(2, SEQ, cfg.hidden_size))
                # restore the leading stage axis so out_specs P("pipeline")
                # reassembles grads with the same shape as params
                grads = jax.tree_util.tree_map(lambda g: g[None], grads)
                return loss, grads
            return shard_map(run, mesh=mesh, in_specs=(P("pipeline"), P()),
                             out_specs=(P(), P("pipeline")),
                             check_rep=False)(p, mb)

        losses = []
        p = stacked
        for _ in range(8):
            loss, g = train_step(p, mb)
            p = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)
            losses.append(float(loss))
        parallel_state.destroy_model_parallel()
        assert losses[-1] < losses[0], losses


class TestBert:
    @pytest.mark.slow  # heaviest 8-device parity tier (ISSUE 6 wall-clock)
    def test_bert_packed_matches_padded(self):
        """Varlen packing (r7, ISSUE 5): two sequences packed into one
        row with segment ids + per-segment positions must produce the
        SAME per-token MLM losses as the padded two-row layout, on both
        the flash path (packed-QKV varlen route on chip, XLA fallback
        here) and the fused-softmax reference path (segment mask through
        the boolean-mask softmax)."""
        seq = 16
        kw = dict(num_layers=2, hidden_size=32, num_attention_heads=4,
                  vocab_size=VOCAB, max_position_embeddings=seq,
                  tp_size=1, add_binary_head=False, num_tokentypes=0)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, 1)
        lens = [6, 10]
        toks = [jax.random.randint(jax.random.PRNGKey(i + 1), (n,), 0,
                                   VOCAB) for i, n in enumerate(lens)]
        labs = [jax.random.randint(jax.random.PRNGKey(i + 10), (n,), 0,
                                   VOCAB) for i, n in enumerate(lens)]
        # padded: one row per sequence + key-padding mask
        tok_p = jnp.zeros((2, seq), jnp.int32)
        lab_p = jnp.zeros((2, seq), jnp.int32)
        msk_p = jnp.zeros((2, seq), jnp.int32)
        for i, n in enumerate(lens):
            tok_p = tok_p.at[i, :n].set(toks[i])
            lab_p = lab_p.at[i, :n].set(labs[i])
            msk_p = msk_p.at[i, :n].set(1)
        # packed: both sequences in ONE row, positions restarting
        tok_k = jnp.concatenate(toks)[None]
        lab_k = jnp.concatenate(labs)[None]
        seg_k = jnp.concatenate([jnp.full((n,), i, jnp.int32)
                                 for i, n in enumerate(lens)])[None]
        pos_k = jnp.concatenate([jnp.arange(n) for n in lens])[None]

        def run(model, packed):
            def f(p, *args):
                if packed:
                    losses, _ = model.apply(p, tok_k, lm_labels=lab_k,
                                            segment_ids=seg_k,
                                            position_ids=pos_k)
                else:
                    losses, _ = model.apply(p, tok_p,
                                            attention_mask=msk_p,
                                            lm_labels=lab_p)
                return losses
            return shard_map(f, mesh=mesh, in_specs=(P(),),
                             out_specs=P(), check_rep=False)(params)

        for flash in (True, False):
            model = BertModel(BertConfig(use_flash_attention=flash, **kw))
            master = model.init_master(jax.random.PRNGKey(0))
            params = model.shard_master(master, 0)
            l_pad = run(model, packed=False)
            l_pack = run(model, packed=True)
            # real-token losses line up: packed row = concat of the
            # padded rows' real prefixes
            ref = jnp.concatenate([l_pad[i, :n]
                                   for i, n in enumerate(lens)])
            np.testing.assert_allclose(
                np.asarray(l_pack[0]), np.asarray(ref), rtol=2e-5,
                atol=2e-5, err_msg=f"flash={flash}")
        parallel_state.destroy_model_parallel()

    def test_bert_forward_and_loss(self):
        cfg = BertConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                         vocab_size=VOCAB, max_position_embeddings=SEQ,
                         tp_size=2)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(2, 1)
        model = BertModel(cfg)
        cfg1 = BertConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                          vocab_size=VOCAB, max_position_embeddings=SEQ,
                          tp_size=1)
        master = BertModel(cfg1).init_master(jax.random.PRNGKey(0))
        shards = [model.shard_master(master, r) for r in range(2)]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)
        tokens = _tokens(jax.random.PRNGKey(1))
        mask = jnp.ones((B, SEQ), jnp.int32)
        labels = _tokens(jax.random.PRNGKey(2))

        def run(p, t, m, l):
            p = jax.tree_util.tree_map(lambda a: a[0], p)
            losses, binary = model.apply(p, t, attention_mask=m, lm_labels=l)
            return jnp.mean(losses), binary

        loss, binary = shard_map(
            run, mesh=mesh, in_specs=(P("tensor"), P(), P(), P()),
            out_specs=(P(), P()), check_rep=False)(stacked, tokens, mask, labels)
        parallel_state.destroy_model_parallel()
        assert np.isfinite(float(loss))
        assert binary.shape == (B, 2)

    @pytest.mark.slow  # heaviest 8-device parity tier (ISSUE 6 wall-clock)
    def test_bert_flash_matches_softmax_path(self):
        """BERT's key-padding mask through the flash path (segment ids
        with all-ones query ids — the FMHA varlen role, r5) must match
        the fused-softmax path: key-side-only masking semantics, pad
        query rows included."""
        kw = dict(num_layers=2, hidden_size=32, num_attention_heads=4,
                  vocab_size=VOCAB, max_position_embeddings=SEQ,
                  tp_size=1, add_binary_head=False)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, 1)
        m_soft = BertModel(BertConfig(**kw))
        m_flash = BertModel(BertConfig(use_flash_attention=True, **kw))
        master = m_soft.init_master(jax.random.PRNGKey(0))
        params = m_soft.shard_master(master, 0)
        tokens = _tokens(jax.random.PRNGKey(1))
        # real padding: last third of every sequence masked
        mask = jnp.concatenate(
            [jnp.ones((B, SEQ - SEQ // 3), jnp.int32),
             jnp.zeros((B, SEQ // 3), jnp.int32)], axis=1)
        labels = _tokens(jax.random.PRNGKey(2))

        def run(model):
            def f(p, t, m, l):
                losses, _ = model.apply(p, t, attention_mask=m,
                                        lm_labels=l)
                return losses
            return shard_map(
                f, mesh=mesh, in_specs=(P(), P(), P(), P()),
                out_specs=P(), check_rep=False)(params, tokens, mask,
                                                labels)

        l_soft = run(m_soft)
        l_flash = run(m_flash)
        parallel_state.destroy_model_parallel()
        np.testing.assert_allclose(np.asarray(l_flash),
                                   np.asarray(l_soft),
                                   rtol=2e-3, atol=2e-3)

    @pytest.mark.slow  # heaviest 8-device parity tier (ISSUE 6 wall-clock)
    def test_bert_tp_matches_tp1(self):
        cfg1 = BertConfig(num_layers=1, hidden_size=32, num_attention_heads=4,
                          vocab_size=VOCAB, max_position_embeddings=SEQ,
                          tp_size=1, add_binary_head=False)
        master = BertModel(cfg1).init_master(jax.random.PRNGKey(0))
        tokens = _tokens(jax.random.PRNGKey(1))
        mask = jnp.ones((B, SEQ), jnp.int32)
        labels = _tokens(jax.random.PRNGKey(2))

        def loss_for_tp(tp):
            cfg = BertConfig(num_layers=1, hidden_size=32,
                             num_attention_heads=4, vocab_size=VOCAB,
                             max_position_embeddings=SEQ, tp_size=tp,
                             add_binary_head=False)
            parallel_state.destroy_model_parallel()
            mesh = parallel_state.initialize_model_parallel(tp, 1)
            model = BertModel(cfg)
            shards = [model.shard_master(master, r) for r in range(tp)]
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)

            def run(p, t, m, l):
                p = jax.tree_util.tree_map(lambda a: a[0], p)
                losses, _ = model.apply(p, t, attention_mask=m, lm_labels=l)
                return jnp.mean(losses)

            out = shard_map(run, mesh=mesh,
                            in_specs=(P("tensor"), P(), P(), P()),
                            out_specs=P(), check_rep=False)(
                stacked, tokens, mask, labels)
            parallel_state.destroy_model_parallel()
            return out

        np.testing.assert_allclose(loss_for_tp(4), loss_for_tp(1),
                                   rtol=2e-4, atol=1e-5)


class TestFlashAndRemat:
    """The TPU-first GPTConfig extensions (use_flash_attention, remat) must
    not change the math: same master weights -> same loss as the
    reference-shaped softmax path."""

    def _loss(self, cfg, master, tokens, labels):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, 1)
        model = GPTModel(cfg)
        p = model.shard_master(master, 0)

        def run(p, t, l):
            return jnp.mean(model.apply(p, t, labels=l))

        out = shard_map(run, mesh=mesh, in_specs=(P(), P(), P()),
                        out_specs=P(), check_rep=False)(p, tokens, labels)
        parallel_state.destroy_model_parallel()
        return float(out)

    @pytest.mark.slow  # heaviest 8-device parity tier (ISSUE 6 wall-clock)
    def test_flash_and_remat_match_reference_path(self):
        kw = dict(num_layers=2, hidden_size=32, num_attention_heads=4,
                  vocab_size=VOCAB, max_position_embeddings=SEQ, tp_size=1)
        master = GPTModel(GPTConfig(**kw)).init_master(jax.random.PRNGKey(0))
        tokens = _tokens(jax.random.PRNGKey(1))
        labels = _tokens(jax.random.PRNGKey(2))
        base = self._loss(GPTConfig(**kw), master, tokens, labels)
        flash = self._loss(GPTConfig(**kw, use_flash_attention=True),
                           master, tokens, labels)
        remat = self._loss(GPTConfig(**kw, use_flash_attention=True,
                                     remat=True), master, tokens, labels)
        np.testing.assert_allclose(flash, base, rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(remat, base, rtol=2e-5, atol=2e-6)

    @pytest.mark.slow  # heaviest interpret/parity tier (ISSUE 6 wall-clock)
    def test_causal_model_keeps_causality_with_padding_mask(self):
        """A causal model handed an ADDITIONAL [b,1,1,s] padding mask
        must stay causal on the flash path (r5 review finding: the
        key-padding flash branch once dropped the causal mask)."""
        from apex_tpu.transformer.testing.standalone_gpt import (
            ParallelAttention)

        cfg = GPTConfig(num_layers=1, hidden_size=32,
                        num_attention_heads=4, vocab_size=VOCAB,
                        max_position_embeddings=SEQ, tp_size=1)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, 1)
        attn_soft = ParallelAttention(cfg)
        attn_flash = ParallelAttention(
            GPTConfig(num_layers=1, hidden_size=32,
                      num_attention_heads=4, vocab_size=VOCAB,
                      max_position_embeddings=SEQ, tp_size=1,
                      use_flash_attention=True))
        params = attn_soft.shard_master(
            attn_soft.init_master(jax.random.PRNGKey(0)), 0)
        h = jax.random.normal(jax.random.PRNGKey(1), (B, SEQ, 32))
        pad = jnp.concatenate(
            [jnp.zeros((B, SEQ - 2), bool), jnp.ones((B, 2), bool)],
            axis=1)[:, None, None, :]  # True = masked key

        def run(attn):
            return shard_map(
                lambda p, h: attn.apply(p, h, attention_mask=pad),
                mesh=mesh, in_specs=(P(), P()), out_specs=P(),
                check_rep=False)(params, h)

        o_soft = run(attn_soft)
        o_flash = run(attn_flash)
        parallel_state.destroy_model_parallel()
        np.testing.assert_allclose(np.asarray(o_flash),
                                   np.asarray(o_soft),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.slow  # remat grad parity (interpret-mode kernels) (ISSUE 2 CI satellite)
    def test_remat_grads_match(self):
        kw = dict(num_layers=2, hidden_size=32, num_attention_heads=4,
                  vocab_size=VOCAB, max_position_embeddings=SEQ, tp_size=1)
        tokens = _tokens(jax.random.PRNGKey(1))
        labels = _tokens(jax.random.PRNGKey(2))

        def grads_for(cfg):
            parallel_state.destroy_model_parallel()
            mesh = parallel_state.initialize_model_parallel(1, 1)
            model = GPTModel(cfg)
            master = GPTModel(GPTConfig(**kw)).init_master(
                jax.random.PRNGKey(0))
            p = model.shard_master(master, 0)

            def loss(p):
                def run(p, t, l):
                    return jnp.mean(model.apply(p, t, labels=l))
                return shard_map(run, mesh=mesh, in_specs=(P(), P(), P()),
                                 out_specs=P(), check_rep=False)(
                    p, tokens, labels)

            g = jax.grad(loss)(p)
            parallel_state.destroy_model_parallel()
            return g

        g0 = grads_for(GPTConfig(**kw))
        g1 = grads_for(GPTConfig(**kw, use_flash_attention=True, remat=True))
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestDropout:
    """The reference RNG-tracker property (run_random_test.py +
    random.py:193-221): dropout on TP-*replicated* activations must be
    identical across ranks, dropout on TP-*sharded* activations must
    differ — and the model must stay TP-consistent with both on."""

    def test_mask_streams_tp_property(self):
        from apex_tpu.transformer.tensor_parallel.random import (
            dropout, model_parallel_dropout_key)

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(4, 1)
        base = jax.random.PRNGKey(3)
        x = jnp.ones((64, 16))

        def run(_):
            rep = dropout(x, 0.5, base)                           # replicated
            shd = dropout(x, 0.5, model_parallel_dropout_key(base))  # sharded
            return rep[None], shd[None]

        rep, shd = shard_map(
            run, mesh=mesh, in_specs=(P("tensor"),),
            out_specs=(P("tensor"), P("tensor")), check_rep=False)(
            jnp.zeros((4, 1)))
        parallel_state.destroy_model_parallel()
        for r in range(1, 4):
            np.testing.assert_array_equal(np.asarray(rep[0]),
                                          np.asarray(rep[r]))
        assert any(not np.array_equal(np.asarray(shd[0]), np.asarray(shd[r]))
                   for r in range(1, 4))

    def _dropout_cfg(self, tp):
        return GPTConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                         vocab_size=VOCAB, max_position_embeddings=SEQ,
                         tp_size=tp, attention_dropout=0.3,
                         hidden_dropout=0.25)

    @pytest.mark.slow  # 8-device dropout statistics (ISSUE 2 CI satellite)
    def test_dropout_active_and_deterministic(self):
        cfg = self._dropout_cfg(1)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, 1)
        model = GPTModel(cfg)
        params = model.shard_master(model.init_master(jax.random.PRNGKey(0)), 0)
        tokens, labels = _tokens(jax.random.PRNGKey(1)), _tokens(jax.random.PRNGKey(2))

        def loss(key):
            def run(p, t, l):
                return jnp.mean(model.apply(p, t, labels=l, dropout_key=key))
            return float(shard_map(run, mesh=mesh, in_specs=(P(), P(), P()),
                                   out_specs=P(), check_rep=False)(
                params, tokens, labels))

        def loss_eval():
            def run(p, t, l):
                return jnp.mean(model.apply(p, t, labels=l))
            return float(shard_map(run, mesh=mesh, in_specs=(P(), P(), P()),
                                   out_specs=P(), check_rep=False)(
                params, tokens, labels))

        la = loss(jax.random.PRNGKey(7))
        lb = loss(jax.random.PRNGKey(7))
        lc = loss(jax.random.PRNGKey(8))
        le = loss_eval()
        parallel_state.destroy_model_parallel()
        assert la == lb                  # same key -> bitwise same
        assert la != lc                  # different key -> different masks
        assert la != le                  # dropout actually does something
        assert np.isfinite(la) and np.isfinite(le)

    def test_tp2_stays_consistent_with_dropout(self):
        """With attention (sharded-stream) AND hidden (replicated-stream)
        dropout on, every TP rank must compute the SAME transformer
        output — the property the whole tracker design exists for.  It
        fails if hidden dropout ever uses a per-rank stream."""
        cfg = self._dropout_cfg(2)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(2, 1)
        model = GPTModel(cfg)
        master = GPTModel(self._dropout_cfg(1)).init_master(
            jax.random.PRNGKey(0))
        shards = [model.shard_master(master, r) for r in range(2)]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)
        tokens = _tokens(jax.random.PRNGKey(1))
        key = jax.random.PRNGKey(11)

        def run(p, t):
            p = jax.tree_util.tree_map(lambda a: a[0], p)
            h = model.embed(p, t)
            h, _aux = model.transformer.apply(p["transformer"], h,
                                              dropout_key=key)
            return h[None]

        hs = shard_map(run, mesh=mesh, in_specs=(P("tensor"), P()),
                       out_specs=P("tensor"), check_rep=False)(
            stacked, tokens)
        parallel_state.destroy_model_parallel()
        np.testing.assert_allclose(np.asarray(hs[0]), np.asarray(hs[1]),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.slow  # 8-device BERT dropout statistics (ISSUE 2 CI satellite)
    def test_bert_dropout_active_and_deterministic(self):
        cfg = BertConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                         vocab_size=VOCAB, max_position_embeddings=SEQ,
                         tp_size=1, attention_dropout=0.3,
                         hidden_dropout=0.25)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, 1)
        model = BertModel(cfg)
        params = model.shard_master(
            model.init_master(jax.random.PRNGKey(0)), 0)
        tokens = _tokens(jax.random.PRNGKey(1))
        labels = _tokens(jax.random.PRNGKey(2))
        amask = jnp.ones_like(tokens)

        def loss(key):
            def run(p, t, l):
                losses, _ = model.apply(p, t, attention_mask=amask,
                                        lm_labels=l, dropout_key=key)
                return jnp.mean(losses)
            return float(shard_map(run, mesh=mesh, in_specs=(P(), P(), P()),
                                   out_specs=P(), check_rep=False)(
                params, tokens, labels))

        la = loss(jax.random.PRNGKey(3))
        lb = loss(jax.random.PRNGKey(3))
        lc = loss(jax.random.PRNGKey(4))
        parallel_state.destroy_model_parallel()
        assert la == lb and la != lc and np.isfinite(la)

    @pytest.mark.slow  # 8-device in-kernel dropout (ISSUE 2 CI satellite)
    def test_flash_path_dropout_in_kernel(self):
        """use_flash_attention + attention_dropout uses the in-kernel
        dropout (no S×S probs): deterministic per key, active, and the
        TP2 consistency property still holds."""
        cfg = GPTConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                        vocab_size=VOCAB, max_position_embeddings=SEQ,
                        tp_size=1, attention_dropout=0.3,
                        hidden_dropout=0.0, use_flash_attention=True)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, 1)
        model = GPTModel(cfg)
        params = model.shard_master(
            model.init_master(jax.random.PRNGKey(0)), 0)
        tokens = _tokens(jax.random.PRNGKey(1))
        labels = _tokens(jax.random.PRNGKey(2))

        def loss(key):
            def run(p, t, l):
                return jnp.mean(model.apply(p, t, labels=l,
                                            dropout_key=key))
            return float(shard_map(run, mesh=mesh,
                                   in_specs=(P(), P(), P()),
                                   out_specs=P(), check_rep=False)(
                params, tokens, labels))

        def loss_eval():
            def run(p, t, l):
                return jnp.mean(model.apply(p, t, labels=l))
            return float(shard_map(run, mesh=mesh,
                                   in_specs=(P(), P(), P()),
                                   out_specs=P(), check_rep=False)(
                params, tokens, labels))

        la = loss(jax.random.PRNGKey(7))
        lb = loss(jax.random.PRNGKey(7))
        lc = loss(jax.random.PRNGKey(8))
        le = loss_eval()
        parallel_state.destroy_model_parallel()
        assert la == lb and la != lc and la != le
        assert np.isfinite(la)


class TestMoEGPT:
    """GPTConfig(num_experts>0): every layer's MLP is Switch-routed
    (TPU-first extension; experts replicated across TP)."""

    def _cfg(self, tp):
        return GPTConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                         vocab_size=VOCAB, max_position_embeddings=SEQ,
                         tp_size=tp, num_experts=4,
                         moe_capacity_factor=8.0)

    @pytest.mark.slow  # heaviest interpret/parity tier (ISSUE 6 wall-clock)
    def test_moe_gpt_trains(self):
        from apex_tpu import optimizers

        cfg = self._cfg(1)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, 1)
        model = GPTModel(cfg)
        params = model.shard_master(
            model.init_master(jax.random.PRNGKey(0)), 0)
        opt = optimizers.FusedAdam(lr=3e-3)
        opt_state = opt.init(params)
        tokens = _tokens(jax.random.PRNGKey(1))
        labels = _tokens(jax.random.PRNGKey(2))

        # jax 0.4.37 compat: under check_rep=False, shard_map AD turns
        # forward residuals into extra outputs with inferred specs, and
        # the MoE aux-loss SCALAR residual has no rank to carry them —
        # value_and_grad over the bare shard_map dies with _SpecError.
        # jax.checkpoint over the shard_map keeps residuals internal
        # (the backward re-runs the forward inside), same math.
        inner = shard_map(
            lambda p, t, l: jnp.mean(model.apply(p, t, labels=l)),
            mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            check_rep=False)

        @jax.jit
        def step(p, o):
            def lossf(p):
                return jax.checkpoint(inner)(p, tokens, labels)

            loss, g = jax.value_and_grad(lossf)(p)
            p, o = opt.step(g, o, p)
            return p, o, loss, g

        first = None
        for _ in range(25):
            params, opt_state, loss, g = step(params, opt_state)
            if first is None:
                first = float(loss)
                # gradients flow into gate and experts of every layer
                ml = g["transformer"]["layers"]["mlp"]
                assert float(jnp.abs(ml["gate"]["weight"]).max()) > 0
                assert float(jnp.abs(ml["experts"]["w1"]).max()) > 0
        parallel_state.destroy_model_parallel()
        assert np.isfinite(float(loss)) and float(loss) < first

    @pytest.mark.slow  # 8-device MoE TP parity (ISSUE 2 CI satellite)
    def test_moe_gpt_tp2_matches_tp1(self):
        """Experts replicated across TP: tp=2 must equal tp=1 exactly
        (gate runs on the TP-replicated hidden, routing agrees)."""
        master = GPTModel(self._cfg(1)).init_master(jax.random.PRNGKey(0))
        tokens = _tokens(jax.random.PRNGKey(1))
        labels = _tokens(jax.random.PRNGKey(2))
        ref = _serial_gpt_loss(self._cfg(1), master, tokens, labels)

        cfg2 = self._cfg(2)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(2, 1)
        model = GPTModel(cfg2)
        shards = [model.shard_master(master, r) for r in range(2)]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)

        def run(p, t, l):
            p = jax.tree_util.tree_map(lambda a: a[0], p)
            return jnp.mean(model.apply(p, t, labels=l))

        out = shard_map(run, mesh=mesh, in_specs=(P("tensor"), P(), P()),
                        out_specs=P(), check_rep=False)(
            stacked, tokens, labels)
        parallel_state.destroy_model_parallel()
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)
