"""Checkpoint/resume tests (SURVEY.md §5.4).

Reference coverage being matched: amp state round-trip
(tests/L0/run_amp/test_checkpointing.py), FP16_Optimizer master-weight
state_dicts (fp16_optimizer.py:209-271), plus the TPU-design extensions:
precision-portable fp32 storage and restore onto a different-size mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu import checkpoint as ckpt
from apex_tpu.amp import scaler as scaler_lib
from apex_tpu.optimizers import FusedAdam


def _toy_params(key):
    k1, k2 = jax.random.split(key)
    return {
        "dense": {"w": jax.random.normal(k1, (8, 16), jnp.float32),
                  "b": jnp.zeros((16,), jnp.float32)},
        "out": {"w": jax.random.normal(k2, (16, 4), jnp.float32)},
    }


def _loss(params, x, y):
    h = jnp.tanh(x @ params["dense"]["w"] + params["dense"]["b"])
    logits = h @ params["out"]["w"]
    return jnp.mean((logits - y) ** 2)


def _make_step(opt, amp_state):
    @jax.jit
    def step(state, x, y):
        def scaled_loss(p):
            return amp_state.scaler.scale(_loss(p, x, y), state.scaler_state)

        grads = jax.grad(scaled_loss)(state.params)
        grads, finite = amp_state.scaler.unscale(grads, state.scaler_state)
        new_p, new_o = opt.step_if_finite(grads, state.opt_state, state.params, finite)
        return state.replace(
            step=state.step + 1,
            params=new_p,
            opt_state=new_o,
            scaler_state=amp_state.scaler.update(state.scaler_state, finite),
        )

    return step


def _train(n_steps, state, step_fn, key):
    for i in range(n_steps):
        k = jax.random.fold_in(key, i)
        x = jax.random.normal(k, (32, 8), jnp.float32)
        y = jax.random.normal(jax.random.fold_in(k, 1), (32, 4), jnp.float32)
        state = step_fn(state, x, y)
    return state


def test_round_trip_exact(tmp_path):
    params = _toy_params(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    amp_state = amp.initialize("O2")
    state = ckpt.TrainState.create(params, opt.init(params), amp_state.scaler.init())
    state = _train(3, state, _make_step(opt, amp_state), jax.random.PRNGKey(1))

    ckpt.save_checkpoint(str(tmp_path), state, step=int(state.step))
    restored, step = ckpt.restore_checkpoint(str(tmp_path), target=state)
    assert step == 3
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # NamedTuple / dataclass structure survives
    assert isinstance(restored, ckpt.TrainState)
    assert restored.scaler_state.loss_scale == state.scaler_state.loss_scale


def test_resume_continues_trajectory_bitwise(tmp_path):
    """3 steps + save/restore + 3 steps == 6 straight steps, bitwise.

    The trajectory-parity discipline of the reference L1 tier
    (tests/L1/common/compare.py:40-64) applied to resume.
    """
    params = _toy_params(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2, weight_decay=0.01)
    amp_state = amp.initialize("O2")
    step_fn = _make_step(opt, amp_state)
    key = jax.random.PRNGKey(7)

    s0 = ckpt.TrainState.create(params, opt.init(params), amp_state.scaler.init())
    straight = _train(6, s0, step_fn, key)

    half = _train(3, s0, step_fn, key)
    ckpt.save_checkpoint(str(tmp_path), half, step=3)
    resumed, _ = ckpt.restore_checkpoint(str(tmp_path), target=half)
    # continue with the same per-step data keys (fold_in i=3..5)
    for i in range(3, 6):
        k = jax.random.fold_in(key, i)
        x = jax.random.normal(k, (32, 8), jnp.float32)
        y = jax.random.normal(jax.random.fold_in(k, 1), (32, 4), jnp.float32)
        resumed = step_fn(resumed, x, y)

    for a, b in zip(jax.tree_util.tree_leaves(straight), jax.tree_util.tree_leaves(resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_precision_portable_fp32_on_disk(tmp_path):
    """bf16 leaves are stored fp32 (O2StateDictHook parity,
    _initialize.py:133-142) and restore to the target's dtype."""
    tree = {"w": jnp.arange(6, dtype=jnp.bfloat16).reshape(2, 3) / 3.0,
            "b": jnp.ones((3,), jnp.float32)}
    ckpt.save_checkpoint(str(tmp_path), tree, step=0)

    import numpy as _np
    with _np.load(str(tmp_path) + "/step_0000000000/arrays.npz") as z:
        stored = {k: z[k].dtype for k in z.files}
    assert all(dt == _np.float32 for dt in stored.values())

    # restore into a bf16 target -> bf16; into an fp32 target -> fp32
    back, _ = ckpt.restore_checkpoint(str(tmp_path), target=tree)
    assert back["w"].dtype == jnp.bfloat16
    fp32_target = jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), tree)
    back32, _ = ckpt.restore_checkpoint(str(tmp_path), target=fp32_target)
    assert back32["w"].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(back32["w"]), np.asarray(tree["w"], dtype=np.float32))


def test_restore_on_different_mesh_size(tmp_path):
    """Save under an 8-way dp mesh, restore onto a 4-way mesh — the
    restart-on-different-topology design of SURVEY §5.4 (impossible with the
    reference's per-rank torch.save)."""
    devs = jax.devices()
    assert len(devs) >= 8
    mesh8 = Mesh(np.array(devs[:8]), ("data",))
    specs = {"w": P("data", None), "b": P()}
    w = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    b = jnp.ones((8,), jnp.float32)
    tree = {
        "w": jax.device_put(w, NamedSharding(mesh8, specs["w"])),
        "b": jax.device_put(b, NamedSharding(mesh8, specs["b"])),
    }
    ckpt.save_checkpoint(str(tmp_path), tree, step=10, shardings=specs)

    mesh4 = Mesh(np.array(devs[:4]), ("data",))
    restored, step = ckpt.restore_checkpoint(
        str(tmp_path), target=tree, mesh=mesh4, shardings=specs)
    assert step == 10
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(w))
    assert restored["w"].sharding.mesh.shape["data"] == 4

    # manifest specs alone (no shardings arg) also work
    restored2, _ = ckpt.restore_checkpoint(str(tmp_path), target=tree, mesh=mesh4)
    assert restored2["w"].sharding.spec == P("data", None)


def test_latest_step_and_keep(tmp_path):
    tree = {"x": jnp.zeros((2,))}
    for s in (1, 2, 3, 4):
        ckpt.save_checkpoint(str(tmp_path), tree, step=s, keep=2)
    assert ckpt.latest_step(str(tmp_path)) == 4
    import os
    kept = sorted(n for n in os.listdir(tmp_path) if n.startswith("step_"))
    assert len(kept) == 2


def test_restore_without_target_nested_dict(tmp_path):
    tree = {"a": {"b": jnp.ones((2, 2)), "c": jnp.zeros((3,))}, "d": jnp.asarray(5)}
    ckpt.save_checkpoint(str(tmp_path), tree, step=0)
    out, _ = ckpt.restore_checkpoint(str(tmp_path))
    np.testing.assert_array_equal(np.asarray(out["a"]["b"]), np.ones((2, 2)))
    np.testing.assert_array_equal(np.asarray(out["d"]), 5)


def test_raw_half_storage_round_trips(tmp_path):
    """fp32_portable=False keeps bf16 bits exactly (stored as a uint16 view)."""
    tree = {"w": (jnp.arange(7, dtype=jnp.bfloat16) / 3.0)}
    ckpt.save_checkpoint(str(tmp_path), tree, step=0, fp32_portable=False)
    back, _ = ckpt.restore_checkpoint(str(tmp_path), target=tree)
    assert back["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(back["w"]).view(np.uint16), np.asarray(tree["w"]).view(np.uint16))


def test_latest_step_survives_crash_artifacts(tmp_path):
    import os
    tree = {"x": jnp.zeros((2,))}
    ckpt.save_checkpoint(str(tmp_path), tree, step=2)
    # a save that died mid-write: .tmp dir with a manifest + truncated marker
    os.makedirs(tmp_path / "step_0000000003.tmp")
    (tmp_path / "step_0000000003.tmp" / "manifest.json").write_text("{}")
    (tmp_path / "latest").write_text("")
    (tmp_path / "step_junk").mkdir()
    assert ckpt.latest_step(str(tmp_path)) == 2
    restored, step = ckpt.restore_checkpoint(str(tmp_path), target=tree)
    assert step == 2


def test_keep_never_deletes_just_written_rollback(tmp_path):
    """Rollback-resume: saving a LOWER step than what's on disk with keep=1
    must keep the new save, pruning by recency not step number."""
    import os
    tree = {"x": jnp.zeros((2,))}
    ckpt.save_checkpoint(str(tmp_path), tree, step=5)
    path = ckpt.save_checkpoint(str(tmp_path), tree, step=3, keep=1)
    assert os.path.exists(path)
    assert ckpt.latest_step(str(tmp_path)) == 3
    assert not os.path.exists(ckpt.step_dir(str(tmp_path), 5))


def test_prefix_shardings_broadcast(tmp_path):
    """A PartitionSpec given at a subtree root applies to every leaf under it
    (pjit in_shardings broadcast rule)."""
    import json
    devs = jax.devices()
    mesh = Mesh(np.array(devs[:4]), ("data",))
    tree = {"params": {"w": jnp.zeros((8, 2)), "v": jnp.zeros((8,))}}
    ckpt.save_checkpoint(str(tmp_path), tree, step=0,
                         shardings={"params": P("data")})
    with open(str(tmp_path) + "/step_0000000000/manifest.json") as f:
        man = json.load(f)
    assert all(e["spec"] == ["data"] for e in man["leaves"].values())
    restored, _ = ckpt.restore_checkpoint(
        str(tmp_path), target=tree, mesh=mesh, shardings={"params": P("data")})
    assert restored["params"]["w"].sharding.spec == P("data")


def test_missing_leaf_errors(tmp_path):
    ckpt.save_checkpoint(str(tmp_path), {"x": jnp.zeros((2,))}, step=0)
    with pytest.raises(KeyError):
        ckpt.restore_checkpoint(str(tmp_path), target={"x": jnp.zeros((2,)),
                                                       "y": jnp.zeros((2,))})


def test_missing_leaf_error_lists_all_missing_keys(tmp_path):
    """A target/checkpoint mismatch names EVERY missing leaf plus what the
    checkpoint actually holds — not a bare KeyError on the first key."""
    ckpt.save_checkpoint(str(tmp_path), {"x": jnp.zeros((2,))}, step=0)
    target = {"x": jnp.zeros((2,)), "y": jnp.zeros((2,)), "z": jnp.zeros((3,))}
    with pytest.raises(KeyError) as ei:
        ckpt.restore_checkpoint(str(tmp_path), target=target)
    msg = ei.value.args[0]  # str(KeyError) repr-escapes the quoted keys
    assert "missing 2 leaves" in msg
    assert "['y']" in msg and "['z']" in msg
    assert "['x']" in msg  # ...and says what IS there


def test_malformed_step_names_ignored(tmp_path):
    """Scanning tolerates every crash/user artifact: tmp dirs, non-digit
    suffixes, int()-parseable-but-nonstandard names ("+3", "1_0"), and
    plain files named like steps."""
    import os
    tree = {"x": jnp.zeros((2,))}
    ckpt.save_checkpoint(str(tmp_path), tree, step=4)
    os.makedirs(tmp_path / "step_0000000009.tmp")
    (tmp_path / "step_0000000009.tmp" / "manifest.json").write_text("{}")
    for bad in ("step_+3", "step_1_0", "step_ 7", "step_junk", "step_",
                "step_³", "step_٣"):  # non-ASCII "digits"
        os.makedirs(tmp_path / bad)
        (tmp_path / bad / "manifest.json").write_text("{}")
    (tmp_path / "step_0000000012").write_text("a file, not a dir")
    (tmp_path / "latest").write_text("12")  # marker points at the junk file
    assert ckpt.latest_step(str(tmp_path)) == 4
    restored, step = ckpt.restore_checkpoint(str(tmp_path), target=tree)
    assert step == 4


def test_multi_checkpoint_corrupt_latest_falls_back(tmp_path):
    """Satellite acceptance: save steps N<M, corrupt M's arrays file —
    resilient restore falls back to N and reports the corruption."""
    from apex_tpu import resilience as res
    from apex_tpu.resilience import chaos

    ckpt.save_checkpoint(str(tmp_path), {"x": jnp.ones((4,)) * 1}, step=3)
    ckpt.save_checkpoint(str(tmp_path), {"x": jnp.ones((4,)) * 2}, step=8)
    chaos.corrupt_arrays(str(tmp_path), 8, mode="flip")
    # plain restore of the corrupt step with verify=True refuses
    with pytest.raises(ckpt.CheckpointCorruptionError):
        ckpt.restore_checkpoint(str(tmp_path), target={"x": jnp.zeros((4,))},
                                step=8, verify=True)
    with pytest.warns(res.CheckpointFallbackWarning):
        restored, step = res.restore_resilient(
            str(tmp_path), target={"x": jnp.zeros((4,))})
    assert step == 3
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.ones(4))


def test_packed_format_round_trip_exact(tmp_path):
    """format 2: one flat superblock file written via the native threaded
    pack (apex_C-parity host runtime) — bitwise equal restore, including
    bf16 leaves stored fp32-portable."""
    params = _toy_params(jax.random.PRNGKey(0))
    params["half"] = jnp.arange(7, dtype=jnp.bfloat16) / 3
    opt = FusedAdam(lr=1e-2)
    amp_state = amp.initialize("O2")
    state = ckpt.TrainState.create(params, opt.init(params),
                                   amp_state.scaler.init())

    ckpt.save_checkpoint(str(tmp_path / "p"), state, step=1, packed=True)
    import os
    d = ckpt.step_dir(str(tmp_path / "p"), 1)
    assert os.path.exists(os.path.join(d, "arrays.pack"))
    assert not os.path.exists(os.path.join(d, "arrays.npz"))

    restored, step = ckpt.restore_checkpoint(str(tmp_path / "p"), target=state)
    assert step == 1
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_packed_matches_npz_content(tmp_path):
    params = _toy_params(jax.random.PRNGKey(2))
    opt = FusedAdam(lr=1e-2)
    amp_state = amp.initialize("O2")
    state = ckpt.TrainState.create(params, opt.init(params),
                                   amp_state.scaler.init())
    ckpt.save_checkpoint(str(tmp_path / "a"), state, step=5, packed=True)
    ckpt.save_checkpoint(str(tmp_path / "b"), state, step=5, packed=False)
    ra, _ = ckpt.restore_checkpoint(str(tmp_path / "a"), target=state)
    rb, _ = ckpt.restore_checkpoint(str(tmp_path / "b"), target=state)
    for a, b in zip(jax.tree_util.tree_leaves(ra),
                    jax.tree_util.tree_leaves(rb)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_packed_raw_half_bits(tmp_path):
    params = {"h": jnp.array([1.5, -2.25, 3.0], jnp.bfloat16)}
    ckpt.save_checkpoint(str(tmp_path), params, step=0, packed=True,
                         fp32_portable=False)
    restored, _ = ckpt.restore_checkpoint(str(tmp_path), target=params)
    np.testing.assert_array_equal(np.asarray(restored["h"]),
                                  np.asarray(params["h"]))


def test_restore_without_target_handles_odd_keys(tmp_path):
    """Dict keys containing quotes/brackets/dots survive target=None
    restore via the manifest's structured path components (ADVICE r2:
    keystr re-parsing mangled them)."""
    tree = {"a'b": {"c[0].d": jnp.ones((2,))}, "plain": jnp.zeros((1,))}
    ckpt.save_checkpoint(str(tmp_path), tree, step=0)
    out, _ = ckpt.restore_checkpoint(str(tmp_path))
    np.testing.assert_array_equal(np.asarray(out["a'b"]["c[0].d"]),
                                  np.ones((2,)))
    np.testing.assert_array_equal(np.asarray(out["plain"]), np.zeros((1,)))


def test_bracket_quote_keys_round_trip(tmp_path):
    """Leaves whose dict keys contain quotes/brackets survive save +
    restore (with and without target).  (jax keystr double-quotes such
    keys so these do NOT actually collide; the save-side '#N' rename is
    a defensive guard for any pytree whose keystrs do collide, and spec
    association is keyed by structured path so it is rename-immune.)"""
    tree = {"x": {"y": jnp.ones((2,)) * 3}, "x']['y": jnp.ones((2,)) * 7}
    ckpt.save_checkpoint(str(tmp_path), tree, step=0)
    back, _ = ckpt.restore_checkpoint(str(tmp_path), target=tree)
    np.testing.assert_array_equal(np.asarray(back["x"]["y"]), 3 * np.ones(2))
    np.testing.assert_array_equal(np.asarray(back["x']['y"]), 7 * np.ones(2))
    out, _ = ckpt.restore_checkpoint(str(tmp_path))
    np.testing.assert_array_equal(np.asarray(out["x"]["y"]), 3 * np.ones(2))
    np.testing.assert_array_equal(np.asarray(out["x']['y"]), 7 * np.ones(2))
