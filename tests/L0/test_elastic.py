"""Elastic-mesh resilience tests (ISSUE 3 tentpole): sharded ZeRO
checkpoints, cross-topology restore, collective watchdog, device-loss
chaos — all on the emulated 8-device CPU mesh.

Markers: everything here is ``chaos_mesh`` (mesh-aware fault injection);
the flagship-model reshard/trajectory cases are additionally ``slow``
(multiple 8-device jit constructions) so tier-1 stays fast — see README
for both invocations.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import checkpoint as ckpt
from apex_tpu import resilience as res
from apex_tpu.resilience import chaos
from apex_tpu.transformer.testing import (
    flagship_elastic_build,
    gpt1p3b_config,
    run_resilient_training,
)

pytestmark = [pytest.mark.chaos, pytest.mark.chaos_mesh]

N_DEV = 8

# the gpt1p3b_toy_zero golden-trajectory cell's exact configuration
# (tests/L1/common/harness.py run_flagship_trajectory): d=128 head
# geometry at toy depth, ZeRO bf16_fit over the 8-device mesh
TOY_KW = dict(num_layers=2, hidden_size=256, num_attention_heads=2,
              vocab_size=512, max_position_embeddings=32)


def _toy_cfg():
    return gpt1p3b_config(**TOY_KW)


def _golden_batches(cfg, n, seed=0):
    """The EXACT batch stream of the golden cell (harness.py:196-200)."""
    out = []
    for i in range(n):
        k = jax.random.fold_in(jax.random.PRNGKey(seed + 300), i % 2)
        tokens = jax.random.randint(k, (8, cfg.max_position_embeddings),
                                    0, cfg.vocab_size)
        out.append((tokens, jnp.roll(tokens, -1, axis=-1)))
    return out


def _bf16_ulp_diff(a, b):
    """Max bit-distance between two bf16 arrays (0 = bitwise equal)."""
    ba = np.asarray(a, jnp.bfloat16.dtype).view(np.uint16).astype(np.int64)
    bb = np.asarray(b, jnp.bfloat16.dtype).view(np.uint16).astype(np.int64)
    return int(np.max(np.abs(ba - bb))) if ba.size else 0


def _assert_flat_parity(restored, source, *, bitwise: bool):
    """Restored flat-buffer leaf vs the source topology's: equal on the
    common prefix (bitwise, or ≤ 1 bf16 ulp), all-zero beyond it (the
    only size difference the reshard contract allows is schema tail
    padding)."""
    fa = np.asarray(restored, np.float32).reshape(-1)
    fb = np.asarray(source, np.float32).reshape(-1)
    n = min(fa.size, fb.size)
    assert np.all(fa[n:] == 0) and np.all(fb[n:] == 0)
    if bitwise:
        np.testing.assert_array_equal(fa[:n], fb[:n])
    else:
        assert _bf16_ulp_diff(fa[:n], fb[:n]) <= 1


# ---------------------------------------------------- sharded format


def _synthetic_state(n_shards=8, shard=32):
    """A flagship-shaped state without the model: replicated params,
    stacked per-rank opt partitions, broadcast step counter."""
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(16), jnp.float32)}
    opt = {
        "step": jnp.broadcast_to(jnp.asarray(5, jnp.int32), (n_shards,)),
        "exp_avg": jnp.asarray(rng.randn(n_shards, shard), jnp.float32),
        "exp_avg_sq": jnp.asarray(
            np.abs(rng.randn(n_shards, shard)), jnp.float32),
    }
    return (params, opt), (P(), P("data"))


def test_sharded_save_layout_and_manifest(chaos_ckpt_dir):
    """The sharded manifest contract (docs/resilience.md "Distributed
    resilience"): per-rank shard files, per-shard CRC32 digests, a
    topology record, replicated leaves stored once."""
    import json

    state, shardings = _synthetic_state()
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")
    d = ckpt.step_dir(str(chaos_ckpt_dir), 1)
    names = sorted(os.listdir(d))
    assert "arrays.npz" in names  # the replicated params
    assert [ckpt.shard_file(r) in names for r in range(8)] == [True] * 8
    with open(os.path.join(d, "manifest.json")) as f:
        man = json.load(f)
    assert man["format"] == 3
    assert man["topology"] == {"shard_axis": "data", "n_shards": 8}
    opt_entries = {k: e for k, e in man["leaves"].items()
                   if e.get("shard_axis")}
    assert len(opt_entries) == 3
    for e in opt_entries.values():
        assert len(e["crc32_shards"]) == 8
    step_e = next(e for k, e in opt_entries.items() if "step" in k)
    assert step_e["replicated_shards"] is True
    assert ckpt.verify_checkpoint(str(chaos_ckpt_dir), 1) == 1


@pytest.mark.parametrize("m", [8, 4, 1])
def test_sharded_roundtrip_reshard_synthetic(chaos_ckpt_dir, m):
    """8→M reshard of the stacked flat-buffer layout: fp32 bitwise on
    the common prefix, broadcast step counter re-broadcast, growth
    zero-filled."""
    state, shardings = _synthetic_state(8, 32)  # logical 256
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=2,
                         shardings=shardings, shard_axis="data")
    shard = 256 // m
    target = ({"w": jnp.zeros(16, jnp.float32)},
              {"step": jnp.zeros((m,), jnp.int32),
               "exp_avg": jnp.zeros((m, shard), jnp.float32),
               "exp_avg_sq": jnp.zeros((m, shard), jnp.float32)})
    (p, o), step = res.restore_resilient(str(chaos_ckpt_dir), target)
    assert step == 2
    np.testing.assert_array_equal(np.asarray(p["w"]),
                                  np.asarray(state[0]["w"]))
    assert np.all(np.asarray(o["step"]) == 5) and o["step"].shape == (m,)
    for leaf in ("exp_avg", "exp_avg_sq"):
        _assert_flat_parity(o[leaf], state[1][leaf], bitwise=True)


def test_fresh_init_zero_state_reshards_by_concat(chaos_ckpt_dir):
    """A fresh ZeRO init's moments are all-zero, so every rank's
    partition is bitwise identical — that must NOT classify them as
    replicated-per-rank (only 1-D per-rank scalar stacks are): an 8→4
    reshard of step-0 state re-partitions by concat and succeeds."""
    import json

    state = ({"w": jnp.ones(8, jnp.float32)},
             {"step": jnp.zeros((8,), jnp.int32),
              "exp_avg": jnp.zeros((8, 16), jnp.float32),
              "exp_avg_sq": jnp.zeros((8, 16), jnp.float32)})
    shardings = (P(), P("data"))
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=0,
                         shardings=shardings, shard_axis="data")
    with open(os.path.join(ckpt.step_dir(str(chaos_ckpt_dir), 0),
                           "manifest.json")) as f:
        man = json.load(f)
    flags = {k: e["replicated_shards"] for k, e in man["leaves"].items()
             if e.get("shard_axis")}
    assert [v for k, v in sorted(flags.items()) if "step" in k] == [True]
    assert [v for k, v in sorted(flags.items()) if "exp" in k] == [False,
                                                                   False]
    target = ({"w": jnp.zeros(8, jnp.float32)},
              {"step": jnp.zeros((4,), jnp.int32),
               "exp_avg": jnp.zeros((4, 32), jnp.float32),
               "exp_avg_sq": jnp.zeros((4, 32), jnp.float32)})
    (_, o), _ = ckpt.restore_checkpoint(str(chaos_ckpt_dir), target)
    assert np.all(np.asarray(o["exp_avg"]) == 0)


def test_reshard_refuses_to_drop_real_state(chaos_ckpt_dir):
    """Shrinking beyond schema padding (non-zero tail) must raise, not
    silently truncate optimizer state."""
    state, shardings = _synthetic_state(8, 32)
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")
    target = ({"w": jnp.zeros(16, jnp.float32)},
              {"step": jnp.zeros((4,), jnp.int32),
               "exp_avg": jnp.zeros((4, 32), jnp.float32),  # 128 < 256
               "exp_avg_sq": jnp.zeros((4, 32), jnp.float32)})
    with pytest.raises(ValueError, match="not all zero"):
        ckpt.restore_checkpoint(str(chaos_ckpt_dir), target)


def test_reshard_zero_state_in_memory():
    """The host-side reshard helper (contrib.optimizers) agrees with the
    checkpoint path: concat → re-split against the target schema."""
    from apex_tpu.contrib.optimizers import (
        DistributedFusedAdam, ShardedOptState, reshard_zero_state)

    params = {"w": jnp.asarray(np.random.RandomState(1).randn(300),
                               jnp.float32)}
    opt = DistributedFusedAdam()
    sch8 = opt.make_schema(params, 8)
    sch4 = opt.make_schema(params, 4)
    rng = np.random.RandomState(2)
    stacked = ShardedOptState(
        step=jnp.broadcast_to(jnp.asarray(3, jnp.int32), (8,)),
        exp_avg=jnp.asarray(rng.randn(8, sch8.total // 8), jnp.float32),
        exp_avg_sq=jnp.asarray(rng.randn(8, sch8.total // 8), jnp.float32))
    # zero the schema tail so an 8→4 shrink is legal (live state never
    # has non-zero padding; random fill does)
    def _zero_tail(a, raw):
        a = np.array(a).reshape(-1)  # writable copy
        a[raw:] = 0
        return jnp.asarray(a.reshape(8, -1))
    raw = sum(sch8.sizes)
    stacked = stacked._replace(exp_avg=_zero_tail(stacked.exp_avg, raw),
                               exp_avg_sq=_zero_tail(stacked.exp_avg_sq,
                                                     raw))
    out = reshard_zero_state(stacked, n_shards=4, schema=sch4)
    assert out.exp_avg.shape == (4, sch4.total // 4)
    assert np.all(np.asarray(out.step) == 3) and out.step.shape == (4,)
    for a, b in ((out.exp_avg, stacked.exp_avg),
                 (out.exp_avg_sq, stacked.exp_avg_sq)):
        _assert_flat_parity(a, b, bitwise=True)


def test_largest_divisor_submesh():
    """Losing 2 of 8 devices must rebuild on 4 (6 does not divide the
    global batch of 8), the select_devices policy the verify demo and a
    real deployment use."""
    devs = list(range(8))
    assert res.largest_divisor_submesh(devs, 8) == devs
    assert res.largest_divisor_submesh(devs[:6], 8) == devs[:4]
    assert res.largest_divisor_submesh(devs[:3], 8) == devs[:2]
    assert res.largest_divisor_submesh(devs[:5], 7) == devs[:1]


# --------------------------------------------------------- watchdog


def test_watchdog_timeout_escalates_to_grace_handler(chaos_ckpt_dir):
    """A slow-collective step overruns the armed deadline: the watchdog
    logs the straggler diagnostic and escalates to the GracePeriodHandler
    save-and-exit path — the loop writes a final checkpoint and returns
    preempted with the watchdog's reason."""
    state = {"w": jnp.ones((4,))}
    # generous margins: under full-suite load a NORMAL step can take
    # hundreds of ms, and a deadline racing that fires at the wrong
    # step (observed flake at timeout=0.25/delay=0.6)
    slow = chaos.slow_collective(lambda s, b: ({"w": s["w"] + 1.0}, None),
                                 at_step=3, delay=2.5)
    h = res.GracePeriodHandler()
    with res.Watchdog(timeout=1.0, handler=h, poll_interval=0.02) as wd:
        result = run_resilient_training(
            slow, state, [None] * 6, ckpt_dir=str(chaos_ckpt_dir),
            save_every=2, handler=h, watchdog=wd)
        assert result.preempted
        assert result.stop_reason == "watchdog_timeout(step=2)"
        # the loop finished the straggling step, then saved and exited
        assert result.steps_run == 3
        assert result.last_saved_step == 3
        assert wd.expired and wd.fired_steps == [2]
        report = wd.last_report
        assert set(report["device_heartbeat_age_s"]) == {
            getattr(d, "id", d) for d in jax.devices()}
        pct = report["step_duration_percentiles"]
        assert set(pct) >= {"p50", "p90", "p99", "max"}
        assert pct["max"] < 2.5  # history holds the FAST steps only
    assert ckpt.latest_step(str(chaos_ckpt_dir)) == 3


def test_watchdog_without_handler_raises_at_next_arm():
    import time

    wd = res.Watchdog(timeout=0.08, poll_interval=0.01)
    try:
        with wd.step(0):
            time.sleep(0.25)
        with pytest.raises(res.WatchdogTimeout, match="step 0 overran"):
            with wd.step(1):
                pass
    finally:
        wd.close()


def test_watchdog_adaptive_timeout_unarmed_before_history():
    """The documented adaptive deadline (`lambda d: 10 * max(d[-20:])`)
    must not crash on the empty duration history of the first step — it
    stays unarmed until a step has completed."""
    with res.Watchdog(timeout=lambda d: 10 * max(d[-20:]),
                      poll_interval=0.01) as wd:
        with wd.step(0):  # no history yet: must arm as infinite, not raise
            pass
        assert wd._current_timeout() < float("inf")  # history exists now
        with wd.step(1):
            pass
    assert not wd.expired


def test_elastic_restore_below_start_step_raises(chaos_ckpt_dir):
    """A fallback restore landing BEFORE this run's start_step must
    raise: the caller does not hold those batches, and a negative
    batches slice would silently train on the wrong data."""
    state, shardings = _synthetic_state()
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")

    def build(devs):
        def step_fn(s, batch):
            raise chaos.DeviceLossError(devs[-1:])
        return step_fn, _synthetic_state()[0], shardings

    with pytest.raises(RuntimeError, match="before this run's start_step"):
        res.run_elastic_training(build, jax.devices(), [None] * 2,
                                 ckpt_dir=str(chaos_ckpt_dir),
                                 start_step=5, max_restarts=2)


def test_watchdog_quiet_run_never_fires():
    h = res.GracePeriodHandler()
    with res.Watchdog(timeout=5.0, handler=h) as wd:
        for i in range(4):
            with wd.step(i):
                pass
    assert not wd.expired and not h.should_stop
    assert wd.step_percentiles()["n"] == 4


# ------------------------------------------- chaos: kill mid-async-save


def test_kill_mid_async_save_newest_intact_shard_set_wins(chaos_ckpt_dir):
    """THE sharded-chaos acceptance case: step 1 lands intact; the step-2
    ASYNC sharded save dies mid-shard-set (injected write_shard fault —
    the atomic commit never happens); step 3 lands but one of its shard
    files is then corrupted on disk.  restore_resilient must skip step 3
    (one bad shard condemns the whole set), never see a partial step 2,
    and land on step 1 — the newest INTACT shard set."""
    state, shardings = _synthetic_state()
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")
    with chaos.FaultyStore(fail_events=("write_shard",),
                           fail_times=None) as store:
        ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=2,
                             shardings=shardings, shard_axis="data",
                             blocking=False)
        with pytest.raises(res.AsyncSaveError):
            res.wait_for_save()
    assert store.failures_injected >= 1
    # the killed save left no committed step_2 (tmp cleaned, not renamed)
    assert not os.path.isdir(ckpt.step_dir(str(chaos_ckpt_dir), 2))
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=3,
                         shardings=shardings, shard_axis="data")
    chaos.corrupt_shard(str(chaos_ckpt_dir), 3, rank=5)
    target, _ = _synthetic_state()
    with pytest.warns(res.CheckpointFallbackWarning) as record:
        restored, step = res.restore_resilient(str(chaos_ckpt_dir), target)
    assert step == 1
    assert any("step 3" in str(w.message) for w in record)
    np.testing.assert_array_equal(np.asarray(restored[1]["exp_avg"]),
                                  np.asarray(state[1]["exp_avg"]))


def test_corrupt_shard_names_failure_under_direct_verify(chaos_ckpt_dir):
    state, shardings = _synthetic_state()
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")
    chaos.corrupt_shard(str(chaos_ckpt_dir), 1, rank=2)
    with pytest.raises(ckpt.CheckpointCorruptionError):
        ckpt.verify_checkpoint(str(chaos_ckpt_dir), 1)


# --------------------------------------- flagship reshard + device loss


def _flagship_state_flat(state):
    """(params, opt_state) → comparable pieces."""
    params, opt = state
    return params, opt


@pytest.mark.slow  # 4 flagship jit constructions on the 8-device mesh
@pytest.mark.parametrize("plan,bitwise", [("fp32", True),
                                          ("bf16_fit", False)])
def test_flagship_sharded_reshard_parity(tmp_path, plan, bitwise):
    """ISSUE 3 acceptance: 8→4→8 reshard of GPT-1.3B-toy ZeRO state
    matches the unsharded restore bitwise (fp32) / ≤ 1 bf16 ulp
    (bf16_fit); the direct 8→1 debug restore holds the same parity
    against the source topology."""
    cfg = _toy_cfg()
    build = flagship_elastic_build(cfg, plan=plan, lr=1e-3)
    batches = _golden_batches(cfg, 2)

    step_fn, state8, shardings = build(jax.devices()[:8])
    for b in batches:
        state8, _ = step_fn(state8, b)
    d_sharded = str(tmp_path / "sharded")
    d_plain = str(tmp_path / "plain")
    ckpt.save_checkpoint(d_sharded, state8, step=2, shardings=shardings,
                         shard_axis="data")
    ckpt.save_checkpoint(d_plain, state8, step=2, shardings=shardings)

    # 8 -> 4
    _, state4_t, _ = build(jax.devices()[:4])
    state4, s = res.restore_zero_checkpoint(d_sharded, state4_t)
    assert s == 2
    for leaf_r, leaf_s in zip(jax.tree_util.tree_leaves(state4[1]),
                              jax.tree_util.tree_leaves(state8[1])):
        if leaf_r.ndim >= 2:  # flat-buffer stacks
            _assert_flat_parity(leaf_r, leaf_s, bitwise=bitwise)

    # 4 -> 8, against the unsharded restore of the same state
    d_mid = str(tmp_path / "mid")
    ckpt.save_checkpoint(d_mid, state4, step=2,
                         shardings=shardings, shard_axis="data")
    _, state8_t, _ = build(jax.devices()[:8])
    state8_rt, _ = res.restore_zero_checkpoint(d_mid, state8_t)
    state8_direct, _ = ckpt.restore_checkpoint(d_plain, target=state8_t,
                                               verify=True)
    for a, b in zip(jax.tree_util.tree_leaves(state8_rt),
                    jax.tree_util.tree_leaves(state8_direct)):
        if bitwise:
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))
        else:
            assert _bf16_ulp_diff(np.asarray(a, np.float32),
                                  np.asarray(b, np.float32)) <= 1

    # 8 -> 1: the single-chip debug restore
    _, state1_t, _ = build(jax.devices()[:1])
    state1, _ = res.restore_zero_checkpoint(d_sharded, state1_t)
    for leaf_r, leaf_s in zip(jax.tree_util.tree_leaves(state1[1]),
                              jax.tree_util.tree_leaves(state8[1])):
        if leaf_r.ndim >= 2:
            _assert_flat_parity(leaf_r, leaf_s, bitwise=bitwise)


@pytest.mark.slow  # two flagship jit constructions + 7 train steps
def test_device_loss_resumes_on_submesh_with_golden_trajectory(tmp_path):
    """ISSUE 3 acceptance: a deterministic device-loss chaos run (4 of 8
    devices lost at step 3) rebuilds the ZeRO step on the surviving
    4-device submesh, resumes from the newest intact sharded checkpoint
    (step 2), and reproduces the ``gpt1p3b_toy_zero`` golden loss
    trajectory from the restored step."""
    from tests.L1.common.harness import load_baseline

    golden = load_baseline("gpt1p3b_toy_zero")
    assert golden is not None and len(golden) == 6

    cfg = _toy_cfg()
    losses = []
    build = flagship_elastic_build(cfg, plan="bf16_fit", lr=1e-3,
                                   on_loss=losses.append)
    dl = chaos.DeviceLoss(at_step=3, device_ids=jax.devices()[4:8])
    result = res.run_elastic_training(
        build, jax.devices()[:8], _golden_batches(cfg, 6),
        ckpt_dir=str(tmp_path / "ckpt"), save_every=1, on_step=dl.poll,
        max_restarts=2)
    assert result.restarts == 1
    assert len(result.devices) == 4
    assert result.lost_devices == [4, 5, 6, 7]
    assert result.step == 6

    # 7 losses: steps 1-3 on 8 devices, then the replayed step 3 and
    # steps 4-6 on the 4-device submesh after the step-2 restore
    assert len(losses) == 7
    # the 8-device prefix IS the golden run
    np.testing.assert_array_equal(losses[:3], golden[:3])
    # resumed-on-submesh steps reproduce the golden trajectory from the
    # restored step: bf16 compute quantizes away the reduction-order
    # difference of the shrunken data axis — ≤ 1 bf16 ulp, 0 in practice
    for got, want in zip(losses[3:], golden[2:]):
        assert _bf16_ulp_diff(np.float32(got), np.float32(want)) <= 1, (
            losses, golden)


# ----------------------------------------- multi-axis (3-D) resilience


def _synthetic_state_3d(lead=(4, 1, 2), shard=32, seed=0):
    """A 3-D-flagship-shaped state without the model: replicated params,
    opt partitions stacked ``[dp, pp, tp, shard]`` over the linearized
    world, broadcast step counter stacked per coordinate."""
    rng = np.random.RandomState(seed)
    params = {"w": jnp.asarray(rng.randn(16), jnp.float32)}
    opt = {
        "step": jnp.broadcast_to(jnp.asarray(5, jnp.int32), lead),
        "exp_avg": jnp.asarray(rng.randn(*lead, shard), jnp.float32),
        "exp_avg_sq": jnp.asarray(
            np.abs(rng.randn(*lead, shard)), jnp.float32),
    }
    shardings = (P(), P("data", "pipeline", "tensor"))
    axes = {"data": lead[0], "pipeline": lead[1], "tensor": lead[2]}
    return (params, opt), shardings, axes


def _target_3d(lead, shard):
    return ({"w": jnp.zeros(16, jnp.float32)},
            {"step": jnp.zeros(lead, jnp.int32),
             "exp_avg": jnp.zeros((*lead, shard), jnp.float32),
             "exp_avg_sq": jnp.zeros((*lead, shard), jnp.float32)})


def test_format4_manifest_and_shard_files(chaos_ckpt_dir):
    """The format-4 contract (docs/resilience.md "3D topologies"):
    shard files keyed by (d, p, t) mesh coordinates, per-coordinate
    CRC32 digests, a mesh_axes topology record, replicated leaves
    stored once."""
    import json

    state, shardings, axes = _synthetic_state_3d((4, 1, 2))
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axes=axes)
    d = ckpt.step_dir(str(chaos_ckpt_dir), 1)
    names = sorted(os.listdir(d))
    assert "arrays.npz" in names  # the replicated params
    want = [ckpt.shard_file_coords((dd, 0, t))
            for dd in range(4) for t in range(2)]
    assert all(w in names for w in want)
    with open(os.path.join(d, "manifest.json")) as f:
        man = json.load(f)
    assert man["format"] == 4
    assert man["topology"]["mesh_axes"] == {
        "data": 4, "pipeline": 1, "tensor": 2}
    opt_entries = {k: e for k, e in man["leaves"].items()
                   if e.get("shard_axes")}
    assert len(opt_entries) == 3
    for e in opt_entries.values():
        assert e["shard_axes"] == ["data", "pipeline", "tensor"]
        assert len(e["crc32_shards"]) == 8  # one digest per coordinate
    step_e = next(e for k, e in opt_entries.items() if "step" in k)
    assert step_e["replicated_shards"] is True
    assert ckpt.verify_checkpoint(str(chaos_ckpt_dir), 1) == 1


def test_garbled_mesh_axes_manifest_is_corruption(chaos_ckpt_dir):
    """A valid-JSON manifest whose topology lost mesh_axes (bit rot /
    partial overwrite) must surface as CheckpointCorruptionError under
    verify — not a raw KeyError — so restore_resilient's fallback walk
    can condemn the step and move to an older intact checkpoint."""
    import json

    state, shardings, axes = _synthetic_state_3d((4, 1, 2))
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axes=axes)
    mpath = os.path.join(ckpt.step_dir(str(chaos_ckpt_dir), 1),
                         "manifest.json")
    with open(mpath) as f:
        man = json.load(f)
    man["topology"]["mesh_axes_corrupt"] = man["topology"].pop("mesh_axes")
    with open(mpath, "w") as f:
        json.dump(man, f)
    with pytest.raises(ckpt.CheckpointCorruptionError):
        ckpt.restore_checkpoint(str(chaos_ckpt_dir), state, verify=True)


def _schema_total(raw: int, world: int) -> int:
    """total_multiple_of = 128·world, as the real flat schema pads."""
    m = 128 * world
    return (raw + m - 1) // m * m


@pytest.mark.parametrize("src,dst", [
    ((4, 1, 2), (2, 2, 2)),
    ((2, 2, 2), (8, 1, 1)),
    ((8, 1, 1), (1, 1, 1)),
    ((4, 1, 2), (1, 1, 1)),
    ((1, 1, 1), (4, 2, 1)),
    ((2, 2, 2), (4, 2, 1)),
])
def test_format4_reshard_sweep_bitwise(chaos_ckpt_dir, src, dst):
    """Property-style (dp, pp, tp) reshape sweep: restored optimizer
    state is fp32-BITWISE equal to the source's logical flat buffer for
    any N→M reshape of the mesh, the broadcast counter re-broadcasts,
    and schema tail padding grows/trims exactly — modelled on the real
    flat schema (raw content + zeros to 128·world)."""
    raw = 1500
    rng = np.random.RandomState(7)
    buf = rng.randn(raw).astype(np.float32)
    world_s, world_d = int(np.prod(src)), int(np.prod(dst))
    total_s = _schema_total(raw, world_s)
    total_d = _schema_total(raw, world_d)

    def _stacked(lead, total):
        world = int(np.prod(lead))
        flat = np.zeros((total,), np.float32)
        flat[:raw] = buf
        return jnp.asarray(flat.reshape(*lead, total // world))

    state = ({"w": jnp.asarray(buf[:16])},
             {"step": jnp.broadcast_to(jnp.asarray(5, jnp.int32), src),
              "exp_avg": _stacked(src, total_s),
              "exp_avg_sq": _stacked(src, total_s)})
    shardings = (P(), P("data", "pipeline", "tensor"))
    axes = {"data": src[0], "pipeline": src[1], "tensor": src[2]}
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axes=axes)
    target = _target_3d(dst, total_d // world_d)
    (p, o), step = res.restore_resilient(str(chaos_ckpt_dir), target)
    assert step == 1
    assert np.all(np.asarray(o["step"]) == 5)
    assert o["step"].shape == tuple(dst)
    for leaf in ("exp_avg", "exp_avg_sq"):
        got = np.asarray(o[leaf]).reshape(-1)
        np.testing.assert_array_equal(got[:raw], buf)  # fp32 bitwise
        assert np.all(got[raw:] == 0)


def test_format4_roundtrip_8_to_222_to_8(chaos_ckpt_dir):
    """The ISSUE 6 round-trip: (8,1,1) → (2,2,2) → (8,1,1) restores the
    optimizer state fp32-bitwise."""
    state, shardings, axes = _synthetic_state_3d((8, 1, 1), 32)
    d1 = str(chaos_ckpt_dir / "a")
    d2 = str(chaos_ckpt_dir / "b")
    ckpt.save_checkpoint(d1, state, step=1, shardings=shardings,
                         shard_axes=axes)
    mid, _ = res.restore_resilient(d1, _target_3d((2, 2, 2), 32))
    ckpt.save_checkpoint(d2, mid, step=1, shardings=shardings,
                         shard_axes={"data": 2, "pipeline": 2,
                                     "tensor": 2})
    (p, o), _ = res.restore_resilient(d2, _target_3d((8, 1, 1), 32))
    for leaf in ("exp_avg", "exp_avg_sq"):
        np.testing.assert_array_equal(np.asarray(o[leaf]),
                                      np.asarray(state[1][leaf]))
    assert np.all(np.asarray(o["step"]) == 5)


def test_format4_pp_stage_remap_of_layer_slices(chaos_ckpt_dir):
    """A pp-stacked layer-slice leaf ([pp, L/pp, h], spec leading with
    "pipeline") re-maps its layer slices exactly across a pp change —
    the C-order flatten contract makes stage boundaries land on layer
    boundaries."""
    rng = np.random.RandomState(3)
    layers = jnp.asarray(rng.randn(8, 16), jnp.float32)  # L=8 logical
    state = {"stages": layers.reshape(2, 4, 16)}         # pp=2
    shardings = {"stages": P("pipeline")}
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings,
                         shard_axes={"data": 1, "pipeline": 2,
                                     "tensor": 1})
    out, _ = ckpt.restore_checkpoint(
        str(chaos_ckpt_dir), {"stages": jnp.zeros((4, 2, 16))})
    np.testing.assert_array_equal(
        np.asarray(out["stages"]).reshape(8, 16), np.asarray(layers))
    # and down to the pp=1 debug restore
    out1, _ = ckpt.restore_checkpoint(
        str(chaos_ckpt_dir), {"stages": jnp.zeros((1, 8, 16))})
    np.testing.assert_array_equal(
        np.asarray(out1["stages"]).reshape(8, 16), np.asarray(layers))


def test_format3_restores_byte_identical_through_new_path(chaos_ckpt_dir):
    """Format-3 ("data"-axis) checkpoints keep restoring BYTE-identically
    through the format-4-capable path (ISSUE 6 acceptance), including
    into a 3-D-shaped target (the migration direction)."""
    state, shardings = _synthetic_state(8, 32)
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")
    # byte-identical same-topology restore
    (p, o), _ = res.restore_resilient(str(chaos_ckpt_dir),
                                      _synthetic_state(8, 32)[0])
    for k in ("step", "exp_avg", "exp_avg_sq"):
        np.testing.assert_array_equal(np.asarray(o[k]),
                                      np.asarray(state[1][k]))
    # format-3 → 3-D target: the dp stack linearizes into the
    # (dp', pp', tp') world exactly (migration note, docs/resilience.md)
    target = _target_3d((2, 1, 2), 64)
    (_, o3), _ = res.restore_resilient(str(chaos_ckpt_dir), target)
    for k in ("exp_avg", "exp_avg_sq"):
        np.testing.assert_array_equal(
            np.asarray(o3[k]).reshape(-1),
            np.asarray(state[1][k]).reshape(-1))
    assert np.all(np.asarray(o3["step"]) == 5)


def test_best_surviving_submesh_policy():
    """Largest-divisor per axis, shrinking dp before tp before pp; dp
    additionally divides the global batch."""
    devs = list(range(8))
    # lose 2 of (4, 2, 1): dp shrinks 4→2, tp/pp untouched
    assert res.best_surviving_submesh(devs[:6], (4, 2, 1)) == (
        devs[:4], (2, 2, 1))
    # batch divisibility caps dp
    assert res.best_surviving_submesh(devs[:6], (4, 2, 1),
                                      batch_size=6) == (devs[:4],
                                                        (2, 2, 1))
    assert res.best_surviving_submesh(devs[:6], (4, 2, 1),
                                      batch_size=9) == (devs[:2],
                                                        (1, 2, 1))
    # tp shrinks only after dp is exhausted
    assert res.best_surviving_submesh(devs[:1], (4, 2, 1)) == (
        devs[:1], (1, 1, 1))
    assert res.best_surviving_submesh(devs[:3], (2, 4, 1)) == (
        devs[:2], (1, 2, 1))
    # pp survives while tp gives way: (1, 4, 2) on 7 survivors
    assert res.best_surviving_submesh(devs[:7], (1, 4, 2)) == (
        devs[:4], (1, 2, 2))


def test_watchdog_per_axis_attribution():
    """A stalled tp group shows up as the suspect tensor index: every
    device but the (t=1) column heartbeats; the report's axis_groups
    names tensor group 1 (and no data suspect, since every data row
    contains a stale device symmetrically... the stale column makes
    every data group contain exactly one stale device, so data ages tie
    and only the tensor axis diverges)."""
    import time as _time

    mesh_axes = {"data": 4, "tensor": 2}
    coords = {i: (i // 2, i % 2) for i in range(8)}
    wd = res.Watchdog(timeout=60.0, devices=list(range(8)),
                      mesh_axes=mesh_axes, device_coords=coords,
                      poll_interval=0.01)
    try:
        with wd.step(0):
            pass  # stamps everyone together
        _time.sleep(0.05)
        for d in range(8):
            if coords[d][1] != 1:  # tensor column 1 goes silent
                wd.beat(d)
        report = wd.report()
        ax = report["axis_groups"]
        assert ax["mesh_axes"] == mesh_axes
        assert ax["suspect"].get("tensor") == 1
        assert "data" not in ax["suspect"]  # ties implicate nothing
        g1 = ax["groups"]["tensor"]["1"]
        g0 = ax["groups"]["tensor"]["0"]
        assert g1["max_age_s"] > g0["max_age_s"]
        # a lost device dominates the attribution
        wd.mark_lost([7])
        ax2 = wd.axis_report()
        assert 7 in ax2["groups"]["tensor"]["1"]["lost"]
        assert ax2["suspect"]["tensor"] == 1
    finally:
        wd.close()


def test_watchdog_mesh_derives_axes():
    """Passing a jax Mesh derives mesh_axes + device coordinates."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 1, 2),
                ("data", "pipeline", "tensor"))
    with res.Watchdog(timeout=60.0, mesh=mesh) as wd:
        assert wd.mesh_axes == {"data": 4, "pipeline": 1, "tensor": 2}
        assert len(wd.device_coords) == 8
        with wd.step(0):
            pass
        assert wd.report()["axis_groups"]["mesh_axes"]["tensor"] == 2


def test_watchdog_never_beaten_group_ranks_stalest():
    """A live device with NO heartbeat yet is infinitely stale, not
    infinitely fresh: a group wedged before its first completed step
    must become the suspect, never the freshly-beaten healthy group
    (and its max_age_s stays None — no observation — so the report
    stays JSON-safe)."""
    import json as _json

    mesh_axes = {"data": 2, "tensor": 1}
    coords = {0: (0, 0), 1: (1, 0)}
    with res.Watchdog(timeout=60.0, devices=[0, 1], mesh_axes=mesh_axes,
                      device_coords=coords) as wd:
        wd.beat(0)  # data group 0 healthy; group 1 never heartbeat
        ax = wd.axis_report()
        assert ax["suspect"].get("data") == 1
        assert ax["groups"]["data"]["1"]["max_age_s"] is None
        _json.dumps(ax)


def test_kill_mid_async_save_3d_newest_intact_shard_set_wins(
        chaos_ckpt_dir):
    """The 3-D chaos acceptance case (ISSUE 6 satellite): step 1 lands
    intact; the step-2 ASYNC multi-axis save dies mid-shard-set; step 3
    lands but a TENSOR-leg coordinate's shard file is corrupted.
    restore_resilient must skip step 3 (one bad coordinate condemns the
    whole set), never see a partial step 2, and land on step 1."""
    state, shardings, axes = _synthetic_state_3d((2, 1, 2), 32)
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axes=axes)
    with chaos.FaultyStore(fail_events=("write_shard",),
                           fail_times=None) as store:
        ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=2,
                             shardings=shardings, shard_axes=axes,
                             blocking=False)
        with pytest.raises(res.AsyncSaveError):
            res.wait_for_save()
    assert store.failures_injected >= 1
    assert not os.path.isdir(ckpt.step_dir(str(chaos_ckpt_dir), 2))
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=3,
                         shardings=shardings, shard_axes=axes)
    chaos.corrupt_shard(str(chaos_ckpt_dir), 3, (1, 0, 1))  # tp leg
    target = _synthetic_state_3d((2, 1, 2), 32)[0]
    with pytest.warns(res.CheckpointFallbackWarning) as record:
        restored, step = res.restore_resilient(str(chaos_ckpt_dir),
                                               target)
    assert step == 1
    assert any("step 3" in str(w.message) for w in record)
    np.testing.assert_array_equal(np.asarray(restored[1]["exp_avg"]),
                                  np.asarray(state[1]["exp_avg"]))


def test_reshard_tree_in_memory_multi_axis():
    """reshard_tree / reshard_zero_state(lead_shape=...) — the in-memory
    twins of the format-4 reshard — agree with the on-disk contract."""
    from apex_tpu.contrib.optimizers import (
        DistributedFusedAdam, ShardedOptState, reshard_zero_state)
    from apex_tpu.multi_tensor.flat import reshard_tree

    params = {"w": jnp.asarray(np.random.RandomState(1).randn(300),
                               jnp.float32)}
    opt = DistributedFusedAdam()
    sch8 = opt.make_schema(params, 8)
    sch4 = opt.make_schema(params, 4)
    rng = np.random.RandomState(2)
    raw = sum(sch8.sizes)

    def _zeroed(shape):
        a = rng.randn(int(np.prod(shape))).astype(np.float32)
        a[raw:] = 0
        return jnp.asarray(a.reshape(shape))

    stacked = ShardedOptState(
        step=jnp.broadcast_to(jnp.asarray(3, jnp.int32), (4, 1, 2)),
        exp_avg=_zeroed((4, 1, 2, sch8.total // 8)),
        exp_avg_sq=_zeroed((4, 1, 2, sch8.total // 8)))
    out = reshard_zero_state(stacked, lead_shape=(2, 2, 1), schema=sch4)
    assert out.exp_avg.shape == (2, 2, 1, sch4.total // 4)
    assert np.all(np.asarray(out.step) == 3)
    assert out.step.shape == (2, 2, 1)
    for a, b in ((out.exp_avg, stacked.exp_avg),
                 (out.exp_avg_sq, stacked.exp_avg_sq)):
        _assert_flat_parity(a, b, bitwise=True)
    # reshard_tree: same result through the spec-driven tree API
    spec = ShardedOptState(step=P("data", "pipeline", "tensor"),
                           exp_avg=P("data", "pipeline", "tensor"),
                           exp_avg_sq=P("data", "pipeline", "tensor"))
    out2 = reshard_tree(
        stacked, spec, spec,
        target=ShardedOptState(
            step=jnp.zeros((2, 2, 1), jnp.int32),
            exp_avg=jnp.zeros((2, 2, 1, sch4.total // 4)),
            exp_avg_sq=jnp.zeros((2, 2, 1, sch4.total // 4))),
        axes_from={"data": 4, "pipeline": 1, "tensor": 2},
        axes_to={"data": 2, "pipeline": 2, "tensor": 1})
    for a, b in zip(jax.tree_util.tree_leaves(out2),
                    jax.tree_util.tree_leaves(out)):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


@pytest.mark.slow  # three flagship jit constructions + 13 train steps
def test_3d_device_loss_resumes_on_best_submesh_with_golden(tmp_path):
    """ISSUE 6 acceptance: an 8-device run sharded (dp=4, tp=2) loses a
    device at step 3 → elastic rebuild on the best surviving submesh
    (dp shrinks to 2, tp=2 survives) → restore from the multi-axis
    format-4 shard set → the resumed loss trajectory matches the
    pre-loss golden run (same topology, uninterrupted) at ≤ 1 bf16
    ulp."""
    cfg = _toy_cfg()
    batches = _golden_batches(cfg, 6)

    # the pre-loss golden: uninterrupted (4, 2, 1) run
    golden = []
    build_g = flagship_elastic_build(cfg, plan="bf16_fit", lr=1e-3,
                                     on_loss=golden.append)
    step_fn, state, _ = build_g(jax.devices()[:8], mesh_shape=(4, 2, 1))
    for b in batches:
        state, _ = step_fn(state, b)
    assert len(golden) == 6

    losses = []
    build = flagship_elastic_build(cfg, plan="bf16_fit", lr=1e-3,
                                   on_loss=losses.append)
    dl = chaos.DeviceLoss(at_step=3, device_ids=jax.devices()[4:6])
    result = res.run_elastic_training(
        build, jax.devices()[:8], batches,
        ckpt_dir=str(tmp_path / "ckpt"), save_every=1, on_step=dl.poll,
        max_restarts=2, mesh_shape=(4, 2, 1), batch_size=8)
    assert result.restarts == 1
    assert result.mesh_shape == (2, 2, 1)  # dp shrank, tp survived
    assert len(result.devices) == 4
    assert result.lost_devices == [4, 5]
    assert result.step == 6

    # the final checkpoint on disk is a format-4 multi-axis shard set
    import json

    with open(os.path.join(ckpt.step_dir(str(tmp_path / "ckpt"), 6),
                           "manifest.json")) as f:
        man = json.load(f)
    assert man["format"] == 4
    assert man["topology"]["mesh_axes"] == {"data": 2, "pipeline": 1,
                                            "tensor": 2}

    # 7 losses: steps 1-3 on (4,2,1), then the replayed step 3 and
    # steps 4-6 on the (2,2,1) submesh after the step-2 restore
    assert len(losses) == 7
    np.testing.assert_array_equal(losses[:3], golden[:3])
    for got, want in zip(losses[3:], golden[2:]):
        assert _bf16_ulp_diff(np.float32(got), np.float32(want)) <= 1, (
            losses, golden)
