"""Elastic-mesh resilience tests (ISSUE 3 tentpole): sharded ZeRO
checkpoints, cross-topology restore, collective watchdog, device-loss
chaos — all on the emulated 8-device CPU mesh.

Markers: everything here is ``chaos_mesh`` (mesh-aware fault injection);
the flagship-model reshard/trajectory cases are additionally ``slow``
(multiple 8-device jit constructions) so tier-1 stays fast — see README
for both invocations.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import checkpoint as ckpt
from apex_tpu import resilience as res
from apex_tpu.resilience import chaos
from apex_tpu.transformer.testing import (
    flagship_elastic_build,
    gpt1p3b_config,
    run_resilient_training,
)

pytestmark = [pytest.mark.chaos, pytest.mark.chaos_mesh]

N_DEV = 8

# the gpt1p3b_toy_zero golden-trajectory cell's exact configuration
# (tests/L1/common/harness.py run_flagship_trajectory): d=128 head
# geometry at toy depth, ZeRO bf16_fit over the 8-device mesh
TOY_KW = dict(num_layers=2, hidden_size=256, num_attention_heads=2,
              vocab_size=512, max_position_embeddings=32)


def _toy_cfg():
    return gpt1p3b_config(**TOY_KW)


def _golden_batches(cfg, n, seed=0):
    """The EXACT batch stream of the golden cell (harness.py:196-200)."""
    out = []
    for i in range(n):
        k = jax.random.fold_in(jax.random.PRNGKey(seed + 300), i % 2)
        tokens = jax.random.randint(k, (8, cfg.max_position_embeddings),
                                    0, cfg.vocab_size)
        out.append((tokens, jnp.roll(tokens, -1, axis=-1)))
    return out


def _bf16_ulp_diff(a, b):
    """Max bit-distance between two bf16 arrays (0 = bitwise equal)."""
    ba = np.asarray(a, jnp.bfloat16.dtype).view(np.uint16).astype(np.int64)
    bb = np.asarray(b, jnp.bfloat16.dtype).view(np.uint16).astype(np.int64)
    return int(np.max(np.abs(ba - bb))) if ba.size else 0


def _assert_flat_parity(restored, source, *, bitwise: bool):
    """Restored flat-buffer leaf vs the source topology's: equal on the
    common prefix (bitwise, or ≤ 1 bf16 ulp), all-zero beyond it (the
    only size difference the reshard contract allows is schema tail
    padding)."""
    fa = np.asarray(restored, np.float32).reshape(-1)
    fb = np.asarray(source, np.float32).reshape(-1)
    n = min(fa.size, fb.size)
    assert np.all(fa[n:] == 0) and np.all(fb[n:] == 0)
    if bitwise:
        np.testing.assert_array_equal(fa[:n], fb[:n])
    else:
        assert _bf16_ulp_diff(fa[:n], fb[:n]) <= 1


# ---------------------------------------------------- sharded format


def _synthetic_state(n_shards=8, shard=32):
    """A flagship-shaped state without the model: replicated params,
    stacked per-rank opt partitions, broadcast step counter."""
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(16), jnp.float32)}
    opt = {
        "step": jnp.broadcast_to(jnp.asarray(5, jnp.int32), (n_shards,)),
        "exp_avg": jnp.asarray(rng.randn(n_shards, shard), jnp.float32),
        "exp_avg_sq": jnp.asarray(
            np.abs(rng.randn(n_shards, shard)), jnp.float32),
    }
    return (params, opt), (P(), P("data"))


def test_sharded_save_layout_and_manifest(chaos_ckpt_dir):
    """The sharded manifest contract (docs/resilience.md "Distributed
    resilience"): per-rank shard files, per-shard CRC32 digests, a
    topology record, replicated leaves stored once."""
    import json

    state, shardings = _synthetic_state()
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")
    d = ckpt.step_dir(str(chaos_ckpt_dir), 1)
    names = sorted(os.listdir(d))
    assert "arrays.npz" in names  # the replicated params
    assert [ckpt.shard_file(r) in names for r in range(8)] == [True] * 8
    with open(os.path.join(d, "manifest.json")) as f:
        man = json.load(f)
    assert man["format"] == 3
    assert man["topology"] == {"shard_axis": "data", "n_shards": 8}
    opt_entries = {k: e for k, e in man["leaves"].items()
                   if e.get("shard_axis")}
    assert len(opt_entries) == 3
    for e in opt_entries.values():
        assert len(e["crc32_shards"]) == 8
    step_e = next(e for k, e in opt_entries.items() if "step" in k)
    assert step_e["replicated_shards"] is True
    assert ckpt.verify_checkpoint(str(chaos_ckpt_dir), 1) == 1


@pytest.mark.parametrize("m", [8, 4, 1])
def test_sharded_roundtrip_reshard_synthetic(chaos_ckpt_dir, m):
    """8→M reshard of the stacked flat-buffer layout: fp32 bitwise on
    the common prefix, broadcast step counter re-broadcast, growth
    zero-filled."""
    state, shardings = _synthetic_state(8, 32)  # logical 256
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=2,
                         shardings=shardings, shard_axis="data")
    shard = 256 // m
    target = ({"w": jnp.zeros(16, jnp.float32)},
              {"step": jnp.zeros((m,), jnp.int32),
               "exp_avg": jnp.zeros((m, shard), jnp.float32),
               "exp_avg_sq": jnp.zeros((m, shard), jnp.float32)})
    (p, o), step = res.restore_resilient(str(chaos_ckpt_dir), target)
    assert step == 2
    np.testing.assert_array_equal(np.asarray(p["w"]),
                                  np.asarray(state[0]["w"]))
    assert np.all(np.asarray(o["step"]) == 5) and o["step"].shape == (m,)
    for leaf in ("exp_avg", "exp_avg_sq"):
        _assert_flat_parity(o[leaf], state[1][leaf], bitwise=True)


def test_fresh_init_zero_state_reshards_by_concat(chaos_ckpt_dir):
    """A fresh ZeRO init's moments are all-zero, so every rank's
    partition is bitwise identical — that must NOT classify them as
    replicated-per-rank (only 1-D per-rank scalar stacks are): an 8→4
    reshard of step-0 state re-partitions by concat and succeeds."""
    import json

    state = ({"w": jnp.ones(8, jnp.float32)},
             {"step": jnp.zeros((8,), jnp.int32),
              "exp_avg": jnp.zeros((8, 16), jnp.float32),
              "exp_avg_sq": jnp.zeros((8, 16), jnp.float32)})
    shardings = (P(), P("data"))
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=0,
                         shardings=shardings, shard_axis="data")
    with open(os.path.join(ckpt.step_dir(str(chaos_ckpt_dir), 0),
                           "manifest.json")) as f:
        man = json.load(f)
    flags = {k: e["replicated_shards"] for k, e in man["leaves"].items()
             if e.get("shard_axis")}
    assert [v for k, v in sorted(flags.items()) if "step" in k] == [True]
    assert [v for k, v in sorted(flags.items()) if "exp" in k] == [False,
                                                                   False]
    target = ({"w": jnp.zeros(8, jnp.float32)},
              {"step": jnp.zeros((4,), jnp.int32),
               "exp_avg": jnp.zeros((4, 32), jnp.float32),
               "exp_avg_sq": jnp.zeros((4, 32), jnp.float32)})
    (_, o), _ = ckpt.restore_checkpoint(str(chaos_ckpt_dir), target)
    assert np.all(np.asarray(o["exp_avg"]) == 0)


def test_reshard_refuses_to_drop_real_state(chaos_ckpt_dir):
    """Shrinking beyond schema padding (non-zero tail) must raise, not
    silently truncate optimizer state."""
    state, shardings = _synthetic_state(8, 32)
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")
    target = ({"w": jnp.zeros(16, jnp.float32)},
              {"step": jnp.zeros((4,), jnp.int32),
               "exp_avg": jnp.zeros((4, 32), jnp.float32),  # 128 < 256
               "exp_avg_sq": jnp.zeros((4, 32), jnp.float32)})
    with pytest.raises(ValueError, match="not all zero"):
        ckpt.restore_checkpoint(str(chaos_ckpt_dir), target)


def test_reshard_zero_state_in_memory():
    """The host-side reshard helper (contrib.optimizers) agrees with the
    checkpoint path: concat → re-split against the target schema."""
    from apex_tpu.contrib.optimizers import (
        DistributedFusedAdam, ShardedOptState, reshard_zero_state)

    params = {"w": jnp.asarray(np.random.RandomState(1).randn(300),
                               jnp.float32)}
    opt = DistributedFusedAdam()
    sch8 = opt.make_schema(params, 8)
    sch4 = opt.make_schema(params, 4)
    rng = np.random.RandomState(2)
    stacked = ShardedOptState(
        step=jnp.broadcast_to(jnp.asarray(3, jnp.int32), (8,)),
        exp_avg=jnp.asarray(rng.randn(8, sch8.total // 8), jnp.float32),
        exp_avg_sq=jnp.asarray(rng.randn(8, sch8.total // 8), jnp.float32))
    # zero the schema tail so an 8→4 shrink is legal (live state never
    # has non-zero padding; random fill does)
    def _zero_tail(a, raw):
        a = np.array(a).reshape(-1)  # writable copy
        a[raw:] = 0
        return jnp.asarray(a.reshape(8, -1))
    raw = sum(sch8.sizes)
    stacked = stacked._replace(exp_avg=_zero_tail(stacked.exp_avg, raw),
                               exp_avg_sq=_zero_tail(stacked.exp_avg_sq,
                                                     raw))
    out = reshard_zero_state(stacked, n_shards=4, schema=sch4)
    assert out.exp_avg.shape == (4, sch4.total // 4)
    assert np.all(np.asarray(out.step) == 3) and out.step.shape == (4,)
    for a, b in ((out.exp_avg, stacked.exp_avg),
                 (out.exp_avg_sq, stacked.exp_avg_sq)):
        _assert_flat_parity(a, b, bitwise=True)


def test_largest_divisor_submesh():
    """Losing 2 of 8 devices must rebuild on 4 (6 does not divide the
    global batch of 8), the select_devices policy the verify demo and a
    real deployment use."""
    devs = list(range(8))
    assert res.largest_divisor_submesh(devs, 8) == devs
    assert res.largest_divisor_submesh(devs[:6], 8) == devs[:4]
    assert res.largest_divisor_submesh(devs[:3], 8) == devs[:2]
    assert res.largest_divisor_submesh(devs[:5], 7) == devs[:1]


# --------------------------------------------------------- watchdog


def test_watchdog_timeout_escalates_to_grace_handler(chaos_ckpt_dir):
    """A slow-collective step overruns the armed deadline: the watchdog
    logs the straggler diagnostic and escalates to the GracePeriodHandler
    save-and-exit path — the loop writes a final checkpoint and returns
    preempted with the watchdog's reason."""
    state = {"w": jnp.ones((4,))}
    slow = chaos.slow_collective(lambda s, b: ({"w": s["w"] + 1.0}, None),
                                 at_step=3, delay=0.6)
    h = res.GracePeriodHandler()
    with res.Watchdog(timeout=0.25, handler=h, poll_interval=0.02) as wd:
        result = run_resilient_training(
            slow, state, [None] * 6, ckpt_dir=str(chaos_ckpt_dir),
            save_every=2, handler=h, watchdog=wd)
        assert result.preempted
        assert result.stop_reason == "watchdog_timeout(step=2)"
        # the loop finished the straggling step, then saved and exited
        assert result.steps_run == 3
        assert result.last_saved_step == 3
        assert wd.expired and wd.fired_steps == [2]
        report = wd.last_report
        assert set(report["device_heartbeat_age_s"]) == {
            getattr(d, "id", d) for d in jax.devices()}
        pct = report["step_duration_percentiles"]
        assert set(pct) >= {"p50", "p90", "p99", "max"}
        assert pct["max"] < 0.6  # history holds the FAST steps only
    assert ckpt.latest_step(str(chaos_ckpt_dir)) == 3


def test_watchdog_without_handler_raises_at_next_arm():
    import time

    wd = res.Watchdog(timeout=0.08, poll_interval=0.01)
    try:
        with wd.step(0):
            time.sleep(0.25)
        with pytest.raises(res.WatchdogTimeout, match="step 0 overran"):
            with wd.step(1):
                pass
    finally:
        wd.close()


def test_watchdog_adaptive_timeout_unarmed_before_history():
    """The documented adaptive deadline (`lambda d: 10 * max(d[-20:])`)
    must not crash on the empty duration history of the first step — it
    stays unarmed until a step has completed."""
    with res.Watchdog(timeout=lambda d: 10 * max(d[-20:]),
                      poll_interval=0.01) as wd:
        with wd.step(0):  # no history yet: must arm as infinite, not raise
            pass
        assert wd._current_timeout() < float("inf")  # history exists now
        with wd.step(1):
            pass
    assert not wd.expired


def test_elastic_restore_below_start_step_raises(chaos_ckpt_dir):
    """A fallback restore landing BEFORE this run's start_step must
    raise: the caller does not hold those batches, and a negative
    batches slice would silently train on the wrong data."""
    state, shardings = _synthetic_state()
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")

    def build(devs):
        def step_fn(s, batch):
            raise chaos.DeviceLossError(devs[-1:])
        return step_fn, _synthetic_state()[0], shardings

    with pytest.raises(RuntimeError, match="before this run's start_step"):
        res.run_elastic_training(build, jax.devices(), [None] * 2,
                                 ckpt_dir=str(chaos_ckpt_dir),
                                 start_step=5, max_restarts=2)


def test_watchdog_quiet_run_never_fires():
    h = res.GracePeriodHandler()
    with res.Watchdog(timeout=5.0, handler=h) as wd:
        for i in range(4):
            with wd.step(i):
                pass
    assert not wd.expired and not h.should_stop
    assert wd.step_percentiles()["n"] == 4


# ------------------------------------------- chaos: kill mid-async-save


def test_kill_mid_async_save_newest_intact_shard_set_wins(chaos_ckpt_dir):
    """THE sharded-chaos acceptance case: step 1 lands intact; the step-2
    ASYNC sharded save dies mid-shard-set (injected write_shard fault —
    the atomic commit never happens); step 3 lands but one of its shard
    files is then corrupted on disk.  restore_resilient must skip step 3
    (one bad shard condemns the whole set), never see a partial step 2,
    and land on step 1 — the newest INTACT shard set."""
    state, shardings = _synthetic_state()
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")
    with chaos.FaultyStore(fail_events=("write_shard",),
                           fail_times=None) as store:
        ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=2,
                             shardings=shardings, shard_axis="data",
                             blocking=False)
        with pytest.raises(res.AsyncSaveError):
            res.wait_for_save()
    assert store.failures_injected >= 1
    # the killed save left no committed step_2 (tmp cleaned, not renamed)
    assert not os.path.isdir(ckpt.step_dir(str(chaos_ckpt_dir), 2))
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=3,
                         shardings=shardings, shard_axis="data")
    chaos.corrupt_shard(str(chaos_ckpt_dir), 3, rank=5)
    target, _ = _synthetic_state()
    with pytest.warns(res.CheckpointFallbackWarning) as record:
        restored, step = res.restore_resilient(str(chaos_ckpt_dir), target)
    assert step == 1
    assert any("step 3" in str(w.message) for w in record)
    np.testing.assert_array_equal(np.asarray(restored[1]["exp_avg"]),
                                  np.asarray(state[1]["exp_avg"]))


def test_corrupt_shard_names_failure_under_direct_verify(chaos_ckpt_dir):
    state, shardings = _synthetic_state()
    ckpt.save_checkpoint(str(chaos_ckpt_dir), state, step=1,
                         shardings=shardings, shard_axis="data")
    chaos.corrupt_shard(str(chaos_ckpt_dir), 1, rank=2)
    with pytest.raises(ckpt.CheckpointCorruptionError):
        ckpt.verify_checkpoint(str(chaos_ckpt_dir), 1)


# --------------------------------------- flagship reshard + device loss


def _flagship_state_flat(state):
    """(params, opt_state) → comparable pieces."""
    params, opt = state
    return params, opt


@pytest.mark.slow  # 4 flagship jit constructions on the 8-device mesh
@pytest.mark.parametrize("plan,bitwise", [("fp32", True),
                                          ("bf16_fit", False)])
def test_flagship_sharded_reshard_parity(tmp_path, plan, bitwise):
    """ISSUE 3 acceptance: 8→4→8 reshard of GPT-1.3B-toy ZeRO state
    matches the unsharded restore bitwise (fp32) / ≤ 1 bf16 ulp
    (bf16_fit); the direct 8→1 debug restore holds the same parity
    against the source topology."""
    cfg = _toy_cfg()
    build = flagship_elastic_build(cfg, plan=plan, lr=1e-3)
    batches = _golden_batches(cfg, 2)

    step_fn, state8, shardings = build(jax.devices()[:8])
    for b in batches:
        state8, _ = step_fn(state8, b)
    d_sharded = str(tmp_path / "sharded")
    d_plain = str(tmp_path / "plain")
    ckpt.save_checkpoint(d_sharded, state8, step=2, shardings=shardings,
                         shard_axis="data")
    ckpt.save_checkpoint(d_plain, state8, step=2, shardings=shardings)

    # 8 -> 4
    _, state4_t, _ = build(jax.devices()[:4])
    state4, s = res.restore_zero_checkpoint(d_sharded, state4_t)
    assert s == 2
    for leaf_r, leaf_s in zip(jax.tree_util.tree_leaves(state4[1]),
                              jax.tree_util.tree_leaves(state8[1])):
        if leaf_r.ndim >= 2:  # flat-buffer stacks
            _assert_flat_parity(leaf_r, leaf_s, bitwise=bitwise)

    # 4 -> 8, against the unsharded restore of the same state
    d_mid = str(tmp_path / "mid")
    ckpt.save_checkpoint(d_mid, state4, step=2,
                         shardings=shardings, shard_axis="data")
    _, state8_t, _ = build(jax.devices()[:8])
    state8_rt, _ = res.restore_zero_checkpoint(d_mid, state8_t)
    state8_direct, _ = ckpt.restore_checkpoint(d_plain, target=state8_t,
                                               verify=True)
    for a, b in zip(jax.tree_util.tree_leaves(state8_rt),
                    jax.tree_util.tree_leaves(state8_direct)):
        if bitwise:
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))
        else:
            assert _bf16_ulp_diff(np.asarray(a, np.float32),
                                  np.asarray(b, np.float32)) <= 1

    # 8 -> 1: the single-chip debug restore
    _, state1_t, _ = build(jax.devices()[:1])
    state1, _ = res.restore_zero_checkpoint(d_sharded, state1_t)
    for leaf_r, leaf_s in zip(jax.tree_util.tree_leaves(state1[1]),
                              jax.tree_util.tree_leaves(state8[1])):
        if leaf_r.ndim >= 2:
            _assert_flat_parity(leaf_r, leaf_s, bitwise=bitwise)


@pytest.mark.slow  # two flagship jit constructions + 7 train steps
def test_device_loss_resumes_on_submesh_with_golden_trajectory(tmp_path):
    """ISSUE 3 acceptance: a deterministic device-loss chaos run (4 of 8
    devices lost at step 3) rebuilds the ZeRO step on the surviving
    4-device submesh, resumes from the newest intact sharded checkpoint
    (step 2), and reproduces the ``gpt1p3b_toy_zero`` golden loss
    trajectory from the restored step."""
    from tests.L1.common.harness import load_baseline

    golden = load_baseline("gpt1p3b_toy_zero")
    assert golden is not None and len(golden) == 6

    cfg = _toy_cfg()
    losses = []
    build = flagship_elastic_build(cfg, plan="bf16_fit", lr=1e-3,
                                   on_loss=losses.append)
    dl = chaos.DeviceLoss(at_step=3, device_ids=jax.devices()[4:8])
    result = res.run_elastic_training(
        build, jax.devices()[:8], _golden_batches(cfg, 6),
        ckpt_dir=str(tmp_path / "ckpt"), save_every=1, on_step=dl.poll,
        max_restarts=2)
    assert result.restarts == 1
    assert len(result.devices) == 4
    assert result.lost_devices == [4, 5, 6, 7]
    assert result.step == 6

    # 7 losses: steps 1-3 on 8 devices, then the replayed step 3 and
    # steps 4-6 on the 4-device submesh after the step-2 restore
    assert len(losses) == 7
    # the 8-device prefix IS the golden run
    np.testing.assert_array_equal(losses[:3], golden[:3])
    # resumed-on-submesh steps reproduce the golden trajectory from the
    # restored step: bf16 compute quantizes away the reduction-order
    # difference of the shrunken data axis — ≤ 1 bf16 ulp, 0 in practice
    for got, want in zip(losses[3:], golden[2:]):
        assert _bf16_ulp_diff(np.float32(got), np.float32(want)) <= 1, (
            losses, golden)
