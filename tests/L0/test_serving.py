"""Serving-engine tier (ISSUE 8): flash-decode parity over paged KV,
page-pool accounting, continuous-batching scheduler policy, and the
engine's bitwise batched-vs-sequential contract.

The decode kernel runs in interpret mode on CPU (forced via
``routing_override(decode="decode")``), so the parity sweep A/Bs the
Pallas kernel against the gather-based XLA baseline on IDENTICAL page
state — the acceptance bar is ≤ 1 bf16 ulp of the output scale
(measured ~1e-7 fp32; the two sides reduce in different orders, so
fp32-bitwise is not expected — docs/serving.md "Parity bar").
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.ops import flash_decode, flash_decode_route, routing_override
from apex_tpu.serving import (FINISHED, WAITING, ContinuousBatchingScheduler,
                              PagedKVCache, PagePoolExhausted, Request,
                              ServingEngine, ServingModelConfig, SimClock,
                              init_params, poisson_trace)

pytestmark = pytest.mark.serving


# ---------------------------------------------------------------------------
# Decode routing (ISSUE 8 satellite: the route must be forceable both
# ways so identical pages can A/B kernel vs generic)
# ---------------------------------------------------------------------------


class TestDecodeRouting:
    def _shapes(self, page_size=64, q_len=1):
        q = jax.ShapeDtypeStruct((2, 4, q_len, 16), jnp.float32)
        kp = jax.ShapeDtypeStruct((8, page_size, 4, 16), jnp.float32)
        return q, kp

    def test_auto_route_needs_tpu(self):
        q, kp = self._shapes()
        assert jax.default_backend() != "tpu"
        assert flash_decode_route(q, kp) == "xla"

    def test_forced_decode_skips_backend_gate(self):
        q, kp = self._shapes()
        with routing_override(decode="decode"):
            assert flash_decode_route(q, kp) == "decode"
        assert flash_decode_route(q, kp) == "xla"  # restored

    def test_forced_decode_still_respects_shape_gate(self):
        # a 6-row page is not a whole number of 8-row sublane tiles:
        # even a forced "decode" falls back
        q, kp = self._shapes(page_size=6)
        with routing_override(decode="decode"):
            assert flash_decode_route(q, kp) == "xla"

    def test_forced_xla(self):
        q, kp = self._shapes()
        with routing_override(decode="xla"):
            assert flash_decode_route(q, kp) == "xla"

    def test_head_mismatch_routes_generic(self):
        q = jax.ShapeDtypeStruct((2, 8, 1, 16), jnp.float32)
        kp = jax.ShapeDtypeStruct((8, 64, 4, 16), jnp.float32)
        with routing_override(decode="decode"):
            assert flash_decode_route(q, kp) == "xla"

    def test_auto_route_requires_lane_aligned_head_dim(self, monkeypatch):
        # auto routing on TPU additionally requires d % 128 == 0 (the
        # K/V block's lane extent); a forced "decode" skips the lane
        # check (interpret mode has no lane constraint)
        from apex_tpu.ops import attention as att

        monkeypatch.setattr(att.jax, "default_backend", lambda: "tpu")
        q128 = jax.ShapeDtypeStruct((2, 4, 1, 128), jnp.float32)
        kp128 = jax.ShapeDtypeStruct((8, 64, 4, 128), jnp.float32)
        q16, kp16 = self._shapes()
        assert flash_decode_route(q128, kp128) == "decode"
        assert flash_decode_route(q16, kp16) == "xla"
        with routing_override(decode="decode"):
            assert flash_decode_route(q16, kp16) == "decode"

    def test_grain_is_dtype_dependent(self):
        # the sublane grain follows the POOL dtype (8 rows at fp32, 16
        # at bf16 — the `_pallas_ok` Mosaic rule): an 8-row bf16 page
        # must fall back even when the route is forced
        q16 = jax.ShapeDtypeStruct((2, 4, 1, 16), jnp.bfloat16)
        kp8 = jax.ShapeDtypeStruct((8, 8, 4, 16), jnp.bfloat16)
        kp16 = jax.ShapeDtypeStruct((8, 16, 4, 16), jnp.bfloat16)
        with routing_override(decode="decode"):
            assert flash_decode_route(q16, kp8) == "xla"
            assert flash_decode_route(q16, kp16) == "decode"


# ---------------------------------------------------------------------------
# Flash-decode parity sweep (acceptance): kernel vs XLA baseline on
# identical paged KV state
# ---------------------------------------------------------------------------


def _paged_state(rng, lengths, page_size, p_max, h, d, q_len,
                 dtype=np.float32):
    """Build a pool + page tables for ragged ``lengths``.

    Every pool slot is pre-filled with a large sentinel, then only the
    VALID (page, offset) slots of each request are overwritten with
    real values — if the kernel (or the baseline) ever reads a dead
    page or a past-``kv_len`` tail slot, the sentinel blows the diff up
    instead of hiding in the noise."""
    b = len(lengths)
    n_pages = 1 + b * p_max
    k_pages = np.full((n_pages, page_size, h, d), 1e3, dtype)
    v_pages = np.full((n_pages, page_size, h, d), 1e3, dtype)
    table = np.zeros((b, p_max), np.int32)
    # non-contiguous, shuffled page ids: the page-list indirection is
    # the thing under test
    free = list(rng.permutation(np.arange(1, n_pages)))
    for i, n in enumerate(lengths):
        used = -(-n // page_size)
        pages = [free.pop() for _ in range(used)]
        table[i, :used] = pages
        for t in range(n):
            pg, off = pages[t // page_size], t % page_size
            k_pages[pg, off, :, :] = rng.randn(h, d).astype(dtype)
            v_pages[pg, off, :, :] = rng.randn(h, d).astype(dtype)
    q = rng.randn(b, h, q_len, d).astype(dtype)
    return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(table), jnp.asarray(np.asarray(lengths, np.int32)))


def _bf16_ulp_bound(ref):
    """One bf16 ulp at the output's magnitude scale — the documented
    parity bar (docs/serving.md)."""
    return max(float(np.max(np.abs(ref))), 1.0) * 2.0 ** -8


class TestFlashDecodeParity:
    @pytest.mark.slow  # interpret-mode Pallas sweep (PR 6 wall-clock tier)
    @pytest.mark.parametrize("q_len", [1, 4])
    @pytest.mark.parametrize("page_size", [64, 128])
    def test_kernel_matches_xla_on_ragged_pages(self, q_len, page_size):
        rng = np.random.RandomState(q_len * 1000 + page_size)
        p_max, h, d = 3, 2, 16
        # ragged per-request lengths: minimal (= q_len), one-short-of,
        # exactly-at, and JUST-PAST a page boundary, plus a multi-page
        # crossing — the off-by-one surface of the page math
        lengths = [q_len, page_size - 1, page_size, page_size + 1,
                   2 * page_size + 1, 3 * page_size]
        args = _paged_state(rng, lengths, page_size, p_max, h, d, q_len)
        with routing_override(decode="xla"):
            ref = flash_decode(*args)
        with routing_override(decode="decode"):
            out = flash_decode(*args)
        ref, out = np.asarray(ref), np.asarray(out)
        assert np.all(np.abs(ref) < 100), "baseline read a sentinel slot"
        diff = np.max(np.abs(out - ref))
        assert diff <= _bf16_ulp_bound(ref), (
            f"decode kernel diverges from XLA baseline by {diff}")
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_kernel_matches_xla_small(self):
        # the fast-tier sentinel of the slow sweep: one page size, both
        # q_lens, same adversarial sentinel construction
        for q_len in (1, 2):
            rng = np.random.RandomState(q_len)
            args = _paged_state(rng, [q_len, 9, 17], 8, 3, 2, 8, q_len)
            with routing_override(decode="xla"):
                ref = flash_decode(*args)
            with routing_override(decode="decode"):
                out = flash_decode(*args)
            ref, out = np.asarray(ref), np.asarray(out)
            assert np.max(np.abs(out - ref)) <= _bf16_ulp_bound(ref)

    def test_bf16_pool_parity(self):
        # page_size 16: the bf16 sublane grain (8 would fail the gate)
        rng = np.random.RandomState(7)
        args = _paged_state(rng, [5, 17], 16, 2, 2, 8, 1,
                            dtype=np.float32)
        args = tuple(a.astype(jnp.bfloat16) if a.dtype == jnp.float32
                     else a for a in args)
        with routing_override(decode="xla"):
            ref = np.asarray(flash_decode(*args), np.float32)
        with routing_override(decode="decode"):
            out = np.asarray(flash_decode(*args), np.float32)
        # bf16 storage: both sides accumulate fp32 but round the output
        # to bf16 — agreement bar is one bf16 ulp of the scale
        assert np.max(np.abs(out - ref)) <= _bf16_ulp_bound(ref)

    def test_causal_tail_within_q_len(self):
        # q_len > 1: row i of the query tail must NOT see columns past
        # kv_len - q_len + i.  Perturb the last cached token and check
        # only the last query row moves.
        rng = np.random.RandomState(3)
        q_len, ps = 3, 8
        args = _paged_state(rng, [10], ps, 2, 1, 8, q_len)
        q, kp, vp, pt, kl = args
        with routing_override(decode="xla"):
            base = np.asarray(flash_decode(q, kp, vp, pt, kl))
        # token index 9 (the last, seen only by query row 2) lives at
        # page pt[0,1], offset 1
        pg = int(pt[0, 1])
        vp2 = vp.at[pg, 1].add(1.0)
        for route in ("xla", "decode"):
            with routing_override(decode=route):
                pert = np.asarray(flash_decode(q, kp, vp2, pt, kl))
            assert np.allclose(pert[0, :, :2], base[0, :, :2],
                               atol=1e-6), route
            assert not np.allclose(pert[0, :, 2], base[0, :, 2]), route


# ---------------------------------------------------------------------------
# Page pool accounting
# ---------------------------------------------------------------------------


def _cache(num_pages=9, page_size=8, **kw):
    kw.setdefault("num_layers", 1)
    kw.setdefault("num_heads", 2)
    kw.setdefault("head_dim", 4)
    kw.setdefault("max_pages_per_request", 4)
    return PagedKVCache(num_pages=num_pages, page_size=page_size, **kw)


class TestPagedKVCache:
    def test_lowest_first_deterministic(self):
        c = _cache()
        assert c.allocate(3, owner=1) == [1, 2, 3]
        assert c.allocate(2, owner=2) == [4, 5]
        c.free([2, 4])
        # freed pages rejoin sorted: the next taker gets the LOWEST ids
        assert c.allocate(2, owner=3) == [2, 4]

    def test_exhaustion_raises_pool_untouched(self):
        c = _cache(num_pages=5)  # 4 allocatable
        c.allocate(3, owner=1)
        with pytest.raises(PagePoolExhausted):
            c.allocate(2, owner=2)
        assert c.pages_free == 1  # the failed allocate took nothing
        assert c.allocate(1, owner=2) == [4]

    def test_double_free_and_scratch_free_raise(self):
        c = _cache()
        pages = c.allocate(2, owner=1)
        c.free(pages)
        with pytest.raises(ValueError):
            c.free([pages[0]])
        with pytest.raises(ValueError):
            c.free([0])

    def test_page_table_pads_with_scratch_and_bounds_width(self):
        c = _cache()
        t = np.asarray(c.page_table([[3, 1], [2]], rows=4))
        assert t.shape == (4, 4)
        assert t[0].tolist() == [3, 1, 0, 0]
        assert t[1].tolist() == [2, 0, 0, 0]
        assert t[2].tolist() == [0, 0, 0, 0]
        with pytest.raises(ValueError):
            c.page_table([[1, 2, 3, 4, 5]])

    def test_write_tokens_lands_in_pages(self):
        c = _cache(num_pages=4, page_size=4, max_pages_per_request=3)
        pages = c.allocate(2, owner=1)  # 6 tokens -> 2 pages of 4
        T = 6
        k_new = np.arange(1 * T * 2 * 4, dtype=np.float32).reshape(
            1, T, 2, 4)
        idx = np.arange(T)
        pg = np.asarray(pages, np.int32)[idx // 4]
        off = idx % 4
        c.write_tokens(jnp.asarray(k_new), jnp.asarray(k_new), pg, off)
        got = np.asarray(c.k)[0, pg, off]
        np.testing.assert_array_equal(got, k_new[0])

    def test_defrag_compacts_and_rewrites_lists(self):
        c = _cache(num_pages=9, page_size=4)
        a = c.allocate(2, owner=1)
        b = c.allocate(2, owner=2)
        cc = c.allocate(2, owner=3)
        # stamp each page with its owner id so content is trackable
        k = np.array(c.k)  # writable copy
        for p in a + b + cc:
            k[:, p] = p
        c.k = jnp.asarray(k)
        c.v = jnp.asarray(k)
        c.free(b)
        lists = [a, cc]
        old_live = set(a) | set(cc)
        before = [[int(np.asarray(c.k)[0, p, 0, 0, 0]) for p in lst]
                  for lst in lists]
        mapping = c.defrag(lists)
        # live pages now occupy the dense prefix 1..4, lists rewritten
        assert sorted(p for lst in lists for p in lst) == [1, 2, 3, 4]
        after = [[int(np.asarray(c.k)[0, p, 0, 0, 0]) for p in lst]
                 for lst in lists]
        assert before == after  # content moved with the ids
        assert set(mapping) == old_live  # only live pages map
        assert c.pages_free == 4
        assert c.allocate(1, owner=9) == [5]

    def test_defrag_rejects_overlapping_lists(self):
        c = _cache()
        a = c.allocate(2, owner=1)
        with pytest.raises(ValueError):
            c.defrag([a, [a[0]]])


# ---------------------------------------------------------------------------
# Continuous-batching scheduler (host-side policy, no model)
# ---------------------------------------------------------------------------


def _sched(num_pages=9, page_size=8, max_batch=4, prefill_budget=64,
           max_position=64, max_pages_per_request=8):
    cache = PagedKVCache(num_layers=1, num_pages=num_pages,
                         page_size=page_size, num_heads=1, head_dim=4,
                         max_pages_per_request=max_pages_per_request)
    return ContinuousBatchingScheduler(
        cache, max_batch=max_batch, prefill_budget=prefill_budget,
        max_position=max_position), cache


def _simulate(sched, trace, max_steps=500):
    """Drive the scheduler with a fake model (decode = append token 0):
    returns the (admit/evict/retire) event log — the determinism
    witness."""
    log = []
    pending = sorted(trace, key=lambda r: (r.arrival_t, r.rid))
    i, t = 0, 0
    for t in range(max_steps):
        while i < len(pending) and pending[i].arrival_t <= t:
            sched.submit(pending[i])
            i += 1
        for req in sched.admit():
            req.kv_len = len(req.context)
            req.generated.append(0)  # prefill samples one token
            log.append(("admit", req.rid, len(req.pages)))
        for req in sched.retire_finished(float(t)):
            log.append(("retire", req.rid, len(req.generated)))
        if sched.running:
            for req in sched.ensure_decode_capacity():
                log.append(("evict", req.rid))
            for req in sched.running:
                req.kv_len = req.seq_len
                req.generated.append(0)
        for req in sched.retire_finished(float(t)):
            log.append(("retire", req.rid, len(req.generated)))
        if sched.idle and i == len(pending):
            break
    assert sched.idle, "scheduler did not drain"
    return log


class TestScheduler:
    def test_submit_rejects_never_servable(self):
        sched, _ = _sched(max_position=32)
        with pytest.raises(ValueError, match="max_position"):
            sched.submit(Request(rid=0, prompt=[1] * 30,
                                 max_new_tokens=10))
        sched2, _ = _sched(prefill_budget=16, max_position=64)
        with pytest.raises(ValueError, match="prefill budget"):
            sched2.submit(Request(rid=0, prompt=[1] * 10,
                                  max_new_tokens=10))
        sched3, _ = _sched(max_pages_per_request=2)
        with pytest.raises(ValueError, match="max_pages_per_request"):
            sched3.submit(Request(rid=0, prompt=[1] * 20,
                                  max_new_tokens=10))

    def test_seeded_trace_replays_identically(self):
        def run():
            sched, _ = _sched(num_pages=7, max_pages_per_request=4)
            trace = poisson_trace(42, 12, rate=2.0, prompt_len=(3, 12),
                                  max_new=(2, 8), vocab_size=16)
            return _simulate(sched, trace)

        a, b = run(), run()
        assert a == b
        assert any(e[0] == "evict" for e in a), (
            "trace was meant to exercise preemption")

    def test_exhaustion_evicts_not_oom(self):
        # pool of 4 pages, page_size 8: two requests of 20+12 tokens
        # cannot both finish resident — growth must preempt the newest
        sched, cache = _sched(num_pages=5, max_pages_per_request=4)
        r0 = Request(rid=0, prompt=[1] * 14, max_new_tokens=18)
        r1 = Request(rid=1, prompt=[1] * 14, max_new_tokens=4)
        sched.submit(r0)
        sched.submit(r1)
        for req in sched.admit():
            req.kv_len = len(req.context)
            req.generated.append(0)
        assert {r.rid for r in sched.running} == {0, 1}
        evicted = []
        for _ in range(60):
            if not sched.running and not sched.waiting:
                break
            evicted += sched.ensure_decode_capacity()
            for req in sched.running:
                req.kv_len = req.seq_len
                req.generated.append(0)
            sched.retire_finished(0.0)
            for req in sched.admit():
                req.kv_len = len(req.context)
                req.generated.append(0)
        assert evicted, "pool pressure should have preempted"
        assert all(r.state == FINISHED
                   for r in (r0, r1)), (r0.state, r1.state)
        assert cache.pages_used == 0

    def test_evicted_request_requeues_front_with_pages_freed(self):
        sched, cache = _sched(num_pages=5, max_pages_per_request=4)
        r0 = Request(rid=0, prompt=[1] * 8, max_new_tokens=2)
        sched.submit(r0)
        sched.admit()
        used = cache.pages_used
        assert used > 0
        victim = sched.preempt_one()
        assert victim is r0
        assert r0.state == WAITING and r0.pages == [] and r0.kv_len == 0
        assert r0.preemptions == 1
        assert cache.pages_used == 0
        assert sched.waiting[0] is r0

    def test_sizing_bug_caught_at_construction(self):
        # a request that could never fit the pool is impossible by
        # construction: submit() bounds every request by
        # max_pages_per_request, and the cache refuses an
        # max_pages_per_request wider than its allocatable pool — so
        # admit()'s PagePoolExhausted raise is pure defence in depth
        with pytest.raises(ValueError, match="allocatable"):
            PagedKVCache(num_layers=1, num_pages=3, page_size=8,
                         num_heads=1, head_dim=4,
                         max_pages_per_request=4)

    def test_retired_pages_immediately_reusable(self):
        sched, cache = _sched(num_pages=5, max_pages_per_request=4)
        r0 = Request(rid=0, prompt=[1] * 16, max_new_tokens=1)
        sched.submit(r0)
        sched.admit()
        first_pages = list(r0.pages)
        r0.generated.append(0)
        sched.retire_finished(0.0)
        assert cache.pages_used == 0
        r1 = Request(rid=1, prompt=[1] * 16, max_new_tokens=1)
        sched.submit(r1)
        sched.admit()
        # lowest-first allocation hands the SAME page ids back
        assert r1.pages == first_pages


# ---------------------------------------------------------------------------
# The engine: bitwise batching contract, preemption, telemetry
# ---------------------------------------------------------------------------


CFG = ServingModelConfig(vocab_size=64, hidden_size=32, num_heads=4,
                         num_layers=2, max_position=96)


@pytest.fixture(scope="module")
def serving_params():
    return init_params(CFG, seed=0)


def _prompts(n=4):
    return [[int(x) for x in
             np.random.RandomState(100 + i).randint(0, CFG.vocab_size,
                                                    5 + 3 * i)]
            for i in range(n)]


def _run_engine(params, prompts, *, max_batch=4, num_pages=64,
                max_new=12, mppr=None, telemetry=None, eos=None):
    eng = ServingEngine(CFG, params, num_pages=num_pages, page_size=8,
                        max_batch=max_batch, max_pages_per_request=mppr,
                        prefill_budget=CFG.max_position,
                        telemetry=telemetry, clock=SimClock())
    reqs = [eng.submit(p, max_new, eos_id=eos) for p in prompts]
    eng.run()
    return [list(r.generated) for r in reqs], eng


class TestServingEngine:
    def test_batched_matches_sequential_bitwise(self, serving_params):
        # THE acceptance criterion: continuous batching must not
        # perturb any request's greedy stream — token-for-token
        prompts = _prompts(4)
        batched, engB = _run_engine(serving_params, prompts, max_batch=4)
        sequential = [
            _run_engine(serving_params, [p], max_batch=1)[0][0]
            for p in prompts]
        assert batched == sequential
        assert all(len(g) == 12 for g in batched)
        assert engB.cache.pages_used == 0  # retirement drained the pool

    def test_isolation_one_vs_crowd(self, serving_params):
        # one request's pages must never leak into another's attention:
        # the same prompt decodes identically alone and in a crowd
        prompts = _prompts(4)
        alone = _run_engine(serving_params, [prompts[2]], max_batch=1)[0][0]
        crowd, _ = _run_engine(serving_params, prompts, max_batch=4)
        assert crowd[2] == alone

    def test_eos_retires_early(self, serving_params):
        prompts = _prompts(2)
        free, _ = _run_engine(serving_params, prompts, max_new=12)
        # pick the token the model actually emits mid-stream and rerun
        # with it as EOS: greedy determinism makes this a fixed point
        eos = free[0][4]
        stopped, eng = _run_engine(serving_params, prompts, max_new=12,
                                   eos=eos)
        req0 = next(r for r in eng.sched.finished if r.rid == 0)
        assert stopped[0] == free[0][:free[0].index(eos) + 1]
        assert req0.finish_reason == "eos"
        assert len(stopped[0]) < 12

    def test_preemption_is_output_invisible(self, serving_params):
        prompts = _prompts(4)
        roomy, _ = _run_engine(serving_params, prompts, num_pages=64)
        tight, eng = _run_engine(serving_params, prompts, num_pages=9,
                                 mppr=4)
        assert sum(r.preemptions for r in eng.sched.finished) >= 1, (
            "tight pool was meant to force preemption")
        assert tight == roomy
        assert eng.cache.pages_used == 0

    def test_telemetry_stream_validates_and_summarizes(
            self, serving_params, tmp_path):
        from apex_tpu import telemetry as tel
        from apex_tpu.telemetry.__main__ import main as tel_cli

        path = str(tmp_path / "serving.jsonl")
        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="serve-l0",
                               sinks=[tel.JsonlSink(path), mem])
        _run_engine(serving_params, _prompts(3), num_pages=7, mppr=4,
                    max_new=6, telemetry=bus)
        bus.close()
        for ev in mem.events:
            tel.validate_event(ev)
        types = {e["type"] for e in mem.events}
        assert {"request_admit", "request_retire",
                "decode_step"} <= types
        # a preempted request's re-admission is visible in the stream
        readmits = [e for e in mem.events if e["type"] == "request_admit"
                    and e["preemptions"] > 0]
        evictions = [r for e in mem.events if e["type"] == "decode_step"
                     for r in e.get("evicted", [])]
        assert bool(readmits) == bool(evictions)
        # the existing CLI validates the stream (acceptance criterion)
        assert tel_cli(["validate", path]) == 0
        s = tel.summarize_file(path)
        assert s["serving_requests"] == 3
        assert s["serving_tpot_p50"] is not None
        assert s["serving_ttft_p50"] is not None
        assert 0 < s["serving_pool_peak"] <= 1
        out = tel.format_summary(s)
        assert "serving" in out and "tpot" in out

    def test_decode_route_ab_identical_tokens(self, serving_params):
        # the satellite A/B: the SAME engine workload with the decode
        # kernel forced (interpret mode on CPU) vs the generic paged
        # XLA baseline must emit identical greedy tokens
        prompts = _prompts(2)
        # mppr=2 keeps the interpret-mode page grid narrow
        xla_out, _ = _run_engine(serving_params, prompts, max_batch=2,
                                 max_new=4, mppr=2)
        with routing_override(decode="decode"):
            kern_out, _ = _run_engine(serving_params, prompts,
                                      max_batch=2, max_new=4, mppr=2)
        assert kern_out == xla_out

    @pytest.mark.slow  # long Poisson trace end-to-end (PR 6 wall-clock)
    def test_poisson_trace_serve_deterministic(self, serving_params):
        def run():
            eng = ServingEngine(CFG, serving_params, num_pages=17,
                                page_size=8, max_batch=3,
                                max_pages_per_request=5,
                                prefill_budget=CFG.max_position,
                                clock=SimClock(0.5))
            trace = poisson_trace(9, 10, rate=1.0, prompt_len=(4, 12),
                                  max_new=(2, 8), vocab_size=CFG.vocab_size)
            fin = eng.serve(trace)
            assert len(fin) == 10
            return {r.rid: list(r.generated) for r in fin}

        a, b = run(), run()
        assert a == b

    def test_serve_rejects_reused_trace(self, serving_params):
        # serve() rebases arrival times in place: a re-served trace
        # would double-rebase (and replay half-mutated request state),
        # so non-fresh requests are rejected up front
        eng = ServingEngine(CFG, serving_params, num_pages=16,
                            page_size=8, max_batch=2,
                            clock=SimClock(0.1))
        trace = poisson_trace(4, 3, rate=5.0, prompt_len=(4, 8),
                              max_new=(2, 3), vocab_size=CFG.vocab_size)
        assert len(eng.serve(trace)) == 3
        eng2 = ServingEngine(CFG, serving_params, num_pages=16,
                             page_size=8, max_batch=2,
                             clock=SimClock(0.1))
        with pytest.raises(ValueError, match="single-use"):
            eng2.serve(trace)

    def test_warmup_compiles_without_perturbing_serving(
            self, serving_params):
        # warmup must leave the pool in a servable state (its zero K/V
        # lands only in scratch page 0) and not change any output
        prompts = _prompts(2)
        cold, _ = _run_engine(serving_params, prompts, max_batch=2,
                              max_new=5)
        eng = ServingEngine(CFG, serving_params, num_pages=64,
                            page_size=8, max_batch=2,
                            prefill_budget=CFG.max_position,
                            clock=SimClock())
        assert eng.warmup() > 0
        reqs = [eng.submit(p, 5) for p in prompts]
        eng.run()
        assert [list(r.generated) for r in reqs] == cold

    def test_rejects_unservable_up_front(self, serving_params):
        eng = ServingEngine(CFG, serving_params, num_pages=16,
                            page_size=8, clock=SimClock())
        with pytest.raises(ValueError):
            eng.submit([1] * 90, 20)  # 110 > max_position
        with pytest.raises(ValueError):
            eng.submit([1], 0)
        with pytest.raises(ValueError):
            eng.submit([], 4)
