"""Tensor-parallel tier tests on the 8-device emulated CPU mesh.

Mirrors reference tests (SURVEY.md §4): run_initialize_test.py,
run_mappings_test.py, run_layers_test.py (incl. master-weight equivalence),
run_cross_entropy_test.py, run_data_test.py, run_random_test.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state, tensor_parallel

TP = 4


@pytest.fixture()
def tp_mesh():
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(TP, 1)
    yield mesh
    parallel_state.destroy_model_parallel()


def tp_shard_map(f, mesh, in_specs, out_specs):
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False)


class TestInitialize:
    def test_sizes(self, tp_mesh):
        # reference run_initialize_test.py: sizes consistent with world
        assert parallel_state.model_parallel_is_initialized()
        assert parallel_state.get_tensor_model_parallel_world_size() == TP
        assert parallel_state.get_pipeline_model_parallel_world_size() == 1
        assert parallel_state.get_data_parallel_world_size() == 8 // TP
        assert tp_mesh.shape["tensor"] == TP

    def test_invalid_sizes(self):
        parallel_state.destroy_model_parallel()
        with pytest.raises(RuntimeError):
            parallel_state.initialize_model_parallel(3, 1)
        with pytest.raises(RuntimeError):
            parallel_state.initialize_model_parallel()  # not initialised
            parallel_state.destroy_model_parallel()
            parallel_state._state()


class TestMappings:
    def test_copy_backward_sums_rank_contributions(self, tp_mesh):
        # reference copy_to: identity forward, all-reduce backward (:77-91).
        # Here the all-reduce is *derived*: a replicated input used in
        # rank-varying ways must receive the sum of per-rank cotangents.
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 6))

        def fwd(xs):
            return tensor_parallel.copy_to_tensor_model_parallel_region(xs)

        out = tp_shard_map(fwd, tp_mesh, P(), P())(x)
        np.testing.assert_array_equal(out, x)

        def loss(xs):
            def inner(xv):
                y = tensor_parallel.copy_to_tensor_model_parallel_region(xv)
                rank = jax.lax.axis_index("tensor")
                # rank-varying use, then reduce (the row-parallel pattern)
                partial = jnp.sum(y) * (rank + 1.0)
                return jax.lax.psum(partial, "tensor")
            return tp_shard_map(inner, tp_mesh, P(), P())(xs)

        # serial: loss = (1+2+3+4)·Σx → dL/dx = 10 everywhere
        np.testing.assert_allclose(loss(x), 10.0 * float(jnp.sum(x)), rtol=1e-5)
        g = jax.grad(loss)(x)
        np.testing.assert_allclose(g, jnp.full_like(x, 10.0), rtol=1e-5)

    def test_scatter_gather_roundtrip(self, tp_mesh):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8 * TP))

        def roundtrip(xs):
            s = tensor_parallel.scatter_to_tensor_model_parallel_region(xs)
            assert s.shape == (2, 4, 8 * TP // TP)
            return tensor_parallel.gather_from_tensor_model_parallel_region(s)

        out = tp_shard_map(roundtrip, tp_mesh, P(), P(None, None, None))(x)
        np.testing.assert_array_equal(out, x)

    def test_reduce(self, tp_mesh):
        x = jnp.ones((4, 4))

        def f(xs):
            return tensor_parallel.reduce_from_tensor_model_parallel_region(xs)

        out = tp_shard_map(f, tp_mesh, P(), P())(x)
        np.testing.assert_allclose(out, x * TP)


class TestLayers:
    def test_column_parallel_matches_serial(self, tp_mesh):
        # reference run_layers_test.py: sharded layer output == full linear
        layer = tensor_parallel.ColumnParallelLinear(16, 32, gather_output=True)
        master = layer.init_master(jax.random.PRNGKey(0))
        shards = [layer.shard_master(master, r) for r in range(TP)]
        # stack shards on a leading axis mapped to the tensor axis
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)
        x = jax.random.normal(jax.random.PRNGKey(1), (6, 16))

        def f(p, xs):
            p = jax.tree_util.tree_map(lambda v: v[0], p)  # local shard
            return layer.apply(p, xs)

        out = tp_shard_map(
            f, tp_mesh, (P("tensor"), P()), P())(stacked, x)
        ref = x @ master["weight"].T + master["bias"]
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_row_parallel_matches_serial(self, tp_mesh):
        layer = tensor_parallel.RowParallelLinear(32, 16, input_is_parallel=False)
        master = layer.init_master(jax.random.PRNGKey(0))
        shards = [layer.shard_master(master, r) for r in range(TP)]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)
        x = jax.random.normal(jax.random.PRNGKey(1), (6, 32))

        def f(p, xs):
            p = jax.tree_util.tree_map(lambda v: v[0], p)
            return layer.apply(p, xs)

        out = tp_shard_map(f, tp_mesh, (P("tensor"), P()), P())(stacked, x)
        ref = x @ master["weight"].T + master["bias"]
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.slow  # 8-device TP grad parity (ISSUE 2 CI satellite)
    def test_column_row_pair_grads_match_serial(self, tp_mesh):
        # the canonical Megatron MLP pattern: column (no gather) -> row
        col = tensor_parallel.ColumnParallelLinear(8, 16, gather_output=False)
        row = tensor_parallel.RowParallelLinear(16, 8, input_is_parallel=True)
        cm, rm = col.init_master(jax.random.PRNGKey(0)), row.init_master(
            jax.random.PRNGKey(1))
        cs = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[col.shard_master(cm, r) for r in range(TP)])
        rs = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[row.shard_master(rm, r) for r in range(TP)])
        x = jax.random.normal(jax.random.PRNGKey(2), (4, 8))

        def tp_loss(cp, rp, xs):
            def inner(cp, rp, xv):
                cp = jax.tree_util.tree_map(lambda v: v[0], cp)
                rp = jax.tree_util.tree_map(lambda v: v[0], rp)
                h = col.apply(cp, xv)
                h = jax.nn.gelu(h, approximate=True)
                y = row.apply(rp, h)
                return jnp.sum(y ** 2)
            return tp_shard_map(inner, tp_mesh, (P("tensor"), P("tensor"), P()),
                                P())(cp, rp, xs)

        def serial_loss(cm, rm, xs):
            h = xs @ cm["weight"].T + cm["bias"]
            h = jax.nn.gelu(h, approximate=True)
            y = h @ rm["weight"].T + rm["bias"]
            return jnp.sum(y ** 2)

        np.testing.assert_allclose(tp_loss(cs, rs, x), serial_loss(cm, rm, x),
                                   rtol=1e-5)
        gx_tp = jax.grad(tp_loss, argnums=2)(cs, rs, x)
        gx_serial = jax.grad(serial_loss, argnums=2)(cm, rm, x)
        np.testing.assert_allclose(gx_tp, gx_serial, rtol=1e-4, atol=1e-5)
        # weight grads: column shard r grad == rows of serial grad
        gc_tp = jax.grad(tp_loss, argnums=0)(cs, rs, x)
        gc_serial = jax.grad(serial_loss, argnums=0)(cm, rm, x)
        chunk = 16 // TP
        for r in range(TP):
            np.testing.assert_allclose(
                gc_tp["weight"][r], gc_serial["weight"][r * chunk:(r + 1) * chunk],
                rtol=1e-4, atol=1e-5)

    def test_vocab_parallel_embedding(self, tp_mesh):
        emb = tensor_parallel.VocabParallelEmbedding(32, 12)
        master = emb.init_master(jax.random.PRNGKey(0))
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[emb.shard_master(master, r) for r in range(TP)])
        ids = jax.random.randint(jax.random.PRNGKey(1), (5, 7), 0, 32)

        def f(p, i):
            p = jax.tree_util.tree_map(lambda v: v[0], p)
            return emb.apply(p, i)

        out = tp_shard_map(f, tp_mesh, (P("tensor"), P()), P())(stacked, ids)
        np.testing.assert_allclose(out, master["weight"][ids], rtol=1e-6)


class TestVocabParallelCrossEntropy:
    def test_matches_serial_ce(self, tp_mesh):
        # reference run_cross_entropy_test.py: sharded CE == torch CE
        vocab = 8 * TP
        logits = jax.random.normal(jax.random.PRNGKey(0), (6, vocab)) * 3
        target = jax.random.randint(jax.random.PRNGKey(1), (6,), 0, vocab)

        def f(z, t):
            local = tensor_parallel.scatter_to_tensor_model_parallel_region(z)
            return tensor_parallel.vocab_parallel_cross_entropy(local, t)

        out = tp_shard_map(f, tp_mesh, (P(), P()), P())(logits, target)
        ref = -jax.nn.log_softmax(logits)[jnp.arange(6), target]
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.slow  # 8-device vocab-parallel CE grads (ISSUE 2 CI satellite)
    def test_grad_matches_serial(self, tp_mesh):
        vocab = 4 * TP
        logits = jax.random.normal(jax.random.PRNGKey(0), (5, vocab))
        target = jax.random.randint(jax.random.PRNGKey(1), (5,), 0, vocab)

        def tp_loss(z):
            def inner(zv, t):
                local = tensor_parallel.scatter_to_tensor_model_parallel_region(zv)
                return jnp.mean(
                    tensor_parallel.vocab_parallel_cross_entropy(local, t))
            return tp_shard_map(inner, tp_mesh, (P(), P()), P())(z, target)

        def ref_loss(z):
            return jnp.mean(-jax.nn.log_softmax(z)[jnp.arange(5), target])

        np.testing.assert_allclose(
            jax.grad(tp_loss)(logits), jax.grad(ref_loss)(logits),
            rtol=1e-4, atol=1e-6)


class TestDataAndRandom:
    def test_broadcast_data(self, tp_mesh):
        data = {"tokens": jnp.arange(12, dtype=jnp.int32).reshape(3, 4)}

        def f(d):
            rank = jax.lax.axis_index("tensor")
            # simulate divergent replicas: only rank 0 has the true payload
            d = {"tokens": jnp.where(rank == 0, d["tokens"], -1)}
            return tensor_parallel.broadcast_data(["tokens"], d, jnp.int32)

        out = tp_shard_map(f, tp_mesh, P(), P())(data)
        np.testing.assert_array_equal(out["tokens"], data["tokens"])

    def test_broadcast_data_dtype_check(self, tp_mesh):
        def f(d):
            return tensor_parallel.broadcast_data(["x"], d, jnp.int32)

        with pytest.raises(ValueError):
            tp_shard_map(f, tp_mesh, P(), P())({"x": jnp.ones((2,), jnp.float32)})

    def test_rng_tracker_distinct_streams(self):
        tracker = tensor_parallel.RngStatesTracker()
        tracker.add("a", 1)
        tracker.add("b", 2)
        with pytest.raises(Exception):
            tracker.add("a", 3)
        with pytest.raises(Exception):
            tracker.add("c", 1)  # duplicate seed
        ka, kb = tracker.fork("a"), tracker.fork("b")
        assert not np.array_equal(np.asarray(ka), np.asarray(kb))
        assert not np.array_equal(
            np.asarray(tracker.fork("a", 0)), np.asarray(tracker.fork("a", 1)))

    def test_model_parallel_seed_per_rank(self, tp_mesh):
        def f(_):
            tensor_parallel.model_parallel_cuda_manual_seed(1234)
            tracker = tensor_parallel.get_rng_tracker()
            key = tracker.fork("model-parallel-rng")
            return jax.random.normal(key, (4,))

        out = tp_shard_map(
            f, tp_mesh, P(), P(("data", "pipeline", "tensor")))(jnp.zeros((8,)))
        per_rank = np.asarray(out).reshape(2, TP, 4)[0]
        # each tp rank draws different dropout noise
        for r in range(1, TP):
            assert not np.allclose(per_rank[0], per_rank[r])

    def test_split_gather_1d(self, tp_mesh):
        x = jnp.arange(TP * 6.0).reshape(2, TP * 3)

        def f(xs):
            c = tensor_parallel.split_tensor_into_1d_equal_chunks(xs)
            return tensor_parallel.gather_split_1d_tensor(c)

        out = tp_shard_map(f, tp_mesh, P(), P())(x)
        np.testing.assert_array_equal(out, x.reshape(-1))

    def test_checkpoint_matches_direct(self):
        def fn(x):
            return jnp.sin(x) * jnp.cos(x)

        x = jnp.linspace(0, 1, 16)
        np.testing.assert_allclose(
            tensor_parallel.checkpoint(fn, x), fn(x), rtol=1e-6)
        g1 = jax.grad(lambda x: jnp.sum(tensor_parallel.checkpoint(fn, x)))(x)
        g2 = jax.grad(lambda x: jnp.sum(fn(x)))(x)
        np.testing.assert_allclose(g1, g2, rtol=1e-6)


class TestUtils:
    def test_divide(self):
        assert tensor_parallel.divide(12, 4) == 3
        with pytest.raises(ValueError):
            tensor_parallel.divide(13, 4)

    def test_split_last_dim(self):
        x = jnp.arange(24.0).reshape(2, 12)
        parts = tensor_parallel.split_tensor_along_last_dim(x, 4)
        assert len(parts) == 4 and parts[0].shape == (2, 3)
        np.testing.assert_array_equal(jnp.concatenate(parts, -1), x)

    def test_vocab_ranges(self):
        f, l = tensor_parallel.VocabUtility.vocab_range_from_global_vocab_size(
            64, 2, 4)
        assert (f, l) == (32, 48)
