"""Multi-tensor engine tests.

Mirrors reference tests/L0/run_amp/test_multi_tensor_{scale,axpby,l2norm}.py:
ops vs plain math, including inf/nan propagation across a long tensor list.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import multi_tensor as mt


def rand_tree(rng, n_tensors=12, dtype=np.float32):
    return {
        f"t{i}": jnp.asarray(rng.standard_normal((rng.integers(1, 50),)).astype(dtype))
        for i in range(n_tensors)
    }


class TestFlatten:
    def test_roundtrip(self):
        rng = np.random.default_rng(0)
        tree = rand_tree(rng)
        flat, schema = mt.flatten(tree)
        assert flat.ndim == 1 and flat.size == schema.total
        back = mt.unflatten(flat, schema)
        for k in tree:
            np.testing.assert_array_equal(back[k], tree[k])

    def test_alignment(self):
        tree = {"a": jnp.ones((3,)), "b": jnp.ones((130,))}
        flat, schema = mt.flatten(tree, align=128)
        assert schema.offsets == (0, 128)
        assert schema.total == 128 + 256

    def test_total_multiple(self):
        tree = {"a": jnp.ones((3,))}
        flat, schema = mt.flatten(tree, total_multiple_of=1024)
        assert schema.total == 1024

    def test_mixed_dtypes_cast(self):
        tree = {"a": jnp.ones((4,), jnp.bfloat16), "b": jnp.ones((4,), jnp.float32)}
        flat, schema = mt.flatten(tree, dtype=jnp.float32)
        assert flat.dtype == jnp.float32
        back = mt.unflatten(flat, schema)
        assert back["a"].dtype == jnp.bfloat16
        assert back["b"].dtype == jnp.float32

    def test_segment_ids(self):
        tree = {"a": jnp.ones((3,)), "b": jnp.ones((2,))}
        _, schema = mt.flatten(tree, align=4)
        ids = schema.segment_ids()
        np.testing.assert_array_equal(ids[:3], [0, 0, 0])
        np.testing.assert_array_equal(ids[4:6], [1, 1])
        assert ids[3] == 2  # padding marker


class TestOps:
    def test_scale(self):
        rng = np.random.default_rng(1)
        tree = rand_tree(rng)
        out, finite = mt.multi_tensor_scale(tree, 0.5)
        assert bool(finite)
        np.testing.assert_allclose(out["t0"], np.asarray(tree["t0"]) * 0.5, rtol=1e-6)

    def test_scale_detects_nan_in_any_tensor(self):
        rng = np.random.default_rng(2)
        tree = rand_tree(rng, n_tensors=40)
        tree["t17"] = tree["t17"].at[0].set(jnp.nan)
        _, finite = mt.multi_tensor_scale(tree, 1.0)
        assert not bool(finite)

    def test_scale_detects_inf_via_overflow(self):
        tree = {"a": jnp.asarray([3e38], jnp.float32)}
        _, finite = mt.multi_tensor_scale(tree, 10.0)  # overflows to inf
        assert not bool(finite)

    def test_axpby(self):
        x = {"a": jnp.asarray([1.0, 2.0])}
        y = {"a": jnp.asarray([10.0, 20.0])}
        out, finite = mt.multi_tensor_axpby(x, y, 2.0, 0.5)
        np.testing.assert_allclose(out["a"], [7.0, 14.0])
        assert bool(finite)

    def test_l2norm_global_and_per_tensor(self):
        rng = np.random.default_rng(3)
        tree = rand_tree(rng, n_tensors=8)
        total, per = mt.multi_tensor_l2norm(tree, per_tensor=True)
        ref_per = [np.linalg.norm(np.asarray(v)) for v in tree.values()]
        ref_total = np.sqrt(sum(r**2 for r in ref_per))
        np.testing.assert_allclose(total, ref_total, rtol=1e-5)
        np.testing.assert_allclose(per, ref_per, rtol=1e-5)

    def test_segment_l2norms_match_per_tensor(self):
        rng = np.random.default_rng(4)
        tree = rand_tree(rng, n_tensors=6)
        flat, schema = mt.flatten(tree)
        seg = mt.segment_l2norms(flat, schema)
        _, per = mt.multi_tensor_l2norm(tree, per_tensor=True)
        np.testing.assert_allclose(seg, per, rtol=1e-5)

    def test_clip_grad_norm(self):
        tree = {"a": jnp.asarray([3.0, 4.0])}  # norm 5
        clipped, norm = mt.clip_grad_norm(tree, 1.0)
        np.testing.assert_allclose(norm, 5.0, rtol=1e-5)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(clipped["a"])), 1.0, rtol=1e-4
        )
        # under the max: untouched
        clipped, _ = mt.clip_grad_norm(tree, 10.0)
        np.testing.assert_allclose(clipped["a"], [3.0, 4.0], rtol=1e-5)
