"""r17 serving execution modes: tp-sharded decode, prefix-sharing /
copy-on-write pages, and the quantized KV pool (docs/serving.md
"Tensor-parallel serving" / "Prefix sharing" / "Quantized KV pool").

The parity ladder this file pins:

- FULL-PRECISION routes (tp=1 and tp>1) are BITWISE: batched ==
  sequential == tp=1, token for token — the head shards recombine
  through one deterministic psum per residual, so tensor parallelism
  must not move a single logit past the argmax.
- The QUANTIZED route's bar is DETERMINISM, not fp equality: int8
  batched == int8 sequential == int8 re-run, bitwise — but the int8
  streams may legitimately diverge from the fp pool's (the bitwise
  claim vs full precision is explicitly NOT made; docs/serving.md
  "Parity bar").
- Prefix sharing changes WHERE K/V bytes live, never what any reader
  computes: shared-prefix admissions produce the exact streams of an
  unshared control engine.

Resilience rides the same ladder: kill-mid-decode recovery and the
snapshot/restore round trip re-prove stream equality on the tp=2 +
int8 engine (re-quantization is deterministic, so rebuild lands on
the same codes), and the zero-compiles-after-warmup guard extends
over every new executable, the COW page copy included.
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu.analysis import hot_path_guard
from apex_tpu.resilience import chaos
from apex_tpu.serving import (PagedKVCache, PrefixIndex, ServingEngine,
                              ServingModelConfig, SimClock, SpecConfig,
                              init_params)

pytestmark = pytest.mark.serving

CFG = ServingModelConfig(vocab_size=64, hidden_size=32, num_heads=4,
                         num_layers=2, max_position=96)

#: shapes chosen to cross page boundaries at page_size=8 and to give
#: the n-gram proposer something to accept on the spec engines
PROMPTS = [[1, 2, 3, 4, 5], [6, 7] * 4, list(range(1, 13)),
           [9, 8, 7, 6, 5, 4, 3]]


@pytest.fixture(scope="module")
def serving_params():
    return init_params(CFG, seed=0)


def _engine(params, **kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_budget", CFG.max_position)
    kw.setdefault("clock", SimClock())
    return ServingEngine(CFG, params, **kw)


def _streams(params, prompts, **kw):
    """Batched: one engine, all prompts in flight together."""
    eng = _engine(params, **kw)
    reqs = [eng.submit(p, max_new_tokens=3 + i)
            for i, p in enumerate(prompts)]
    eng.run()
    return [list(r.generated) for r in reqs]


def _streams_sequential(params, prompts, **kw):
    """Sequential: a fresh engine per prompt, batch width 1."""
    out = []
    for i, p in enumerate(prompts):
        eng = _engine(params, **kw)
        r = eng.submit(p, max_new_tokens=3 + i)
        eng.run()
        out.append(list(r.generated))
    return out


@pytest.fixture(scope="module")
def fp_control(serving_params):
    """The tp=1 full-precision batched streams every full-precision
    mode must reproduce bitwise."""
    return _streams(serving_params, PROMPTS)


# ---------------------------------------------------------------------------
# tp-sharded decode: full-precision bitwise parity
# ---------------------------------------------------------------------------


class TestTensorParallel:
    def test_tp2_matches_tp1_bitwise(self, serving_params, fp_control):
        """THE tp acceptance pin: sharding attention heads over the
        tensor axis reproduces the tp=1 streams token for token."""
        assert _streams(serving_params, PROMPTS, tp=2) == fp_control

    def test_tp2_batched_matches_sequential_bitwise(self, serving_params,
                                                    fp_control):
        # batched==sequential re-proven on the tp route (the PR 8
        # criterion survives head sharding)
        assert _streams_sequential(serving_params, PROMPTS, tp=2) \
            == fp_control

    def test_tp4_full_head_split_still_bitwise(self, serving_params,
                                               fp_control):
        # one head per shard: the degenerate split exercises the
        # boundary collective hardest
        assert _streams(serving_params, PROMPTS, tp=4) == fp_control

    def test_tp_requires_divisible_heads(self, serving_params):
        with pytest.raises(ValueError, match="not divisible"):
            _engine(serving_params, tp=3)

    def test_tp2_spec_and_chunked_still_bitwise(self, serving_params):
        """The grown executable set (verify, chunked prefill) under tp
        matches its own tp=1 control — speculation only ever commits
        tokens the target model verifies, so tp must not change them."""
        spec = SpecConfig(k=2, chunk_size=8)
        ctrl = _streams(serving_params, PROMPTS, spec=spec)
        assert _streams(serving_params, PROMPTS, spec=spec, tp=2) == ctrl


# ---------------------------------------------------------------------------
# quantized KV pool: narrow codes + scales, determinism parity bar
# ---------------------------------------------------------------------------


class TestQuantizedPool:
    def test_pool_stores_int8_codes_and_fp32_scales(self, serving_params):
        eng = _engine(serving_params, kv_quant="int8")
        assert eng.cache.k.dtype == jnp.int8
        assert eng.cache.v.dtype == jnp.int8
        # one fp32 scale per (layer, page, slot, head): head_dim bytes
        # of bf16 become head_dim int8 codes + 4 scale bytes
        assert eng.cache.k_scale.dtype == jnp.float32
        assert eng.cache.k_scale.shape == eng.cache.k.shape[:-1]

    def test_quant_batched_matches_sequential_bitwise(self, serving_params):
        """The quantized parity bar (docs/serving.md): the int8 route
        is DETERMINISTIC — batched == sequential == re-run, bitwise
        against ITSELF.  Equality with the full-precision streams is
        deliberately NOT asserted: per-page re-scaling moves logits."""
        got = _streams(serving_params, PROMPTS, kv_quant="int8")
        assert _streams_sequential(serving_params, PROMPTS,
                                   kv_quant="int8") == got
        assert _streams(serving_params, PROMPTS, kv_quant="int8") == got
        # the streams are real generations, same lengths as requested
        assert [len(s) for s in got] == [3 + i for i in range(len(got))]

    def test_quant_tp2_matches_quant_tp1_bitwise(self, serving_params):
        # quantize-on-write happens per shard-local head slice with
        # per-(slot, head) scales, so head sharding must not change
        # the codes either: int8×tp2 == int8×tp1 bitwise
        ctrl = _streams(serving_params, PROMPTS, kv_quant="int8")
        assert _streams(serving_params, PROMPTS, kv_quant="int8",
                        tp=2) == ctrl

    def test_quant_roundtrip_error_is_bounded_and_measured(self):
        """The documented half of the parity bar (docs/serving.md
        "Parity bar (quantized)"): per-element int8 round-trip error is
        bounded by scale/2 = absmax/(2·127) — ~0.4% of each token-
        head's own absmax.  Measured here, on adversarial inputs
        (mixed magnitudes per head), so the doc's number is pinned."""
        from apex_tpu.serving.kv_cache import quantize_tokens

        rng = np.random.RandomState(11)
        x = jnp.asarray(rng.randn(64, 4, 32) *
                        np.logspace(-3, 3, 64)[:, None, None],
                        jnp.float32)
        codes, scale = quantize_tokens(x, jnp.int8, 127.0)
        back = codes.astype(jnp.float32) * scale[..., None]
        absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        err = jnp.max(jnp.abs(back - x) / absmax)
        assert float(err) <= 1.0 / (2 * 127.0) + 1e-7
        # zero rows stay exactly zero (absmax 0 -> scale 1)
        zc, zs = quantize_tokens(jnp.zeros((2, 1, 8)), jnp.int8, 127.0)
        assert jnp.all(zc == 0) and jnp.all(zs == 1.0)

    def test_unknown_quant_mode_rejected(self, serving_params):
        with pytest.raises(ValueError, match="unknown quantize"):
            _engine(serving_params, kv_quant="int3")


# ---------------------------------------------------------------------------
# prefix sharing: refcounted pages, COW, eviction safety
# ---------------------------------------------------------------------------


def _unit_cache(**kw):
    kw.setdefault("num_layers", 1)
    kw.setdefault("num_pages", 8)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_heads", 2)
    kw.setdefault("head_dim", 4)
    kw.setdefault("max_pages_per_request", 4)
    return PagedKVCache(**kw)


def _fill_page(cache, page, seed):
    """Write one full page of distinct K/V content."""
    T = cache.page_size
    rng = np.random.RandomState(seed)
    k = jnp.asarray(rng.randn(cache.num_layers, T, cache.num_heads,
                              cache.head_dim), cache.dtype)
    v = jnp.asarray(rng.randn(*k.shape), cache.dtype)
    cache.write_tokens(k, v, np.full((T,), page, np.int32),
                       np.arange(T, dtype=np.int32))


class TestPrefixPages:
    @pytest.mark.parametrize("quant", [None, "int8"])
    def test_cow_gives_private_copy_and_preserves_content(self, quant):
        cache = _unit_cache(quantize=quant)
        [p] = cache.allocate(1, owner=1)
        _fill_page(cache, p, seed=3)
        cache.share([p])
        assert cache.is_shared(p)
        new = cache.cow(p, owner=2)
        # the copy is a different page, both now private again
        assert new != p
        assert cache.refcount(p) == 1 and cache.refcount(new) == 1
        assert jnp.array_equal(cache.k[:, new], cache.k[:, p])
        assert jnp.array_equal(cache.v[:, new], cache.v[:, p])
        if quant:
            # scale planes move with the codes
            assert jnp.array_equal(cache.k_scale[:, new],
                                   cache.k_scale[:, p])
        # writing into the private copy leaves the original untouched
        before = cache.k[:, p]
        _fill_page(cache, new, seed=4)
        assert jnp.array_equal(cache.k[:, p], before)
        assert not jnp.array_equal(cache.k[:, new], cache.k[:, p])

    def test_cow_on_unshared_page_raises(self):
        cache = _unit_cache()
        [p] = cache.allocate(1, owner=1)
        with pytest.raises(ValueError, match="unshared"):
            cache.cow(p, owner=2)

    def test_free_tail_refuses_shared_pages(self):
        cache = _unit_cache()
        pages = cache.allocate(2, owner=1)
        cache.share([pages[1]])
        with pytest.raises(ValueError, match="shared"):
            cache.free_tail(pages, keep=1)
        # the refusal left the page list and refcounts untouched
        assert len(pages) == 2 and cache.refcount(pages[1]) == 2

    def test_defrag_refuses_while_any_page_shared(self):
        cache = _unit_cache()
        pages = cache.allocate(2, owner=1)
        cache.share(pages)
        with pytest.raises(ValueError, match="defrag forbidden"):
            cache.defrag([pages])

    def test_share_of_free_page_refused(self):
        cache = _unit_cache()
        with pytest.raises(ValueError, match="unallocated"):
            cache.share([3])

    def test_shared_page_never_freed_while_second_reader_live(self):
        """THE r17 eviction pin (PrefixIndex docstring): evicting an
        index entry drops only the INDEX's reference — a page a live
        request still reads survives eviction, retirement of the
        original owner, everything, until its last reader frees it."""
        cache = _unit_cache()
        pages = cache.allocate(2, owner=1)
        idx = PrefixIndex(cache, max_entries=1)
        assert idx.register(list(range(1, 9)), pages)   # index: +1 each
        cache.share(pages)                              # second reader
        cache.free(pages)                               # owner retires
        assert all(cache.refcount(p) == 2 for p in pages)
        # capacity pressure evicts the entry; the live reader pins the
        # pages — ZERO return to the free list
        assert idx.evict_one() == 0
        assert cache.pages_used == 2
        assert all(cache.refcount(p) == 1 for p in pages)
        # only the last reader's free returns them
        cache.free(pages)
        assert cache.pages_used == 0

    def test_eviction_is_oldest_first_and_frees_unpinned_pages(self):
        cache = _unit_cache(num_pages=16)
        idx = PrefixIndex(cache, max_entries=2)
        a = cache.allocate(1, owner=1)
        idx.register(list(range(1, 5)), a)
        cache.free(a)               # owner gone: index holds the last ref
        b = cache.allocate(1, owner=2)
        idx.register(list(range(11, 15)), b)
        cache.free(b)
        used = cache.pages_used
        # third registration overflows capacity: the OLDEST entry (a)
        # evicts, and with no other reader its page really frees
        c = cache.allocate(1, owner=3)
        idx.register(list(range(21, 25)), c)
        cache.free(c)
        assert idx.entries[0] == tuple(range(11, 15))
        assert cache.pages_used == used  # -1 (a freed) +1 (c pinned)

    def test_register_rejects_wrong_page_footprint(self):
        cache = _unit_cache()
        pages = cache.allocate(2, owner=1)
        with pytest.raises(ValueError, match="register"):
            PrefixIndex(cache).register(list(range(1, 5)), pages)

    def test_prefix_sharing_requires_chunked_prefill(self, serving_params):
        # the shared prefix skips prefill for covered tokens; only the
        # chunked path can prefill an arbitrary-length suffix
        with pytest.raises(ValueError, match="chunk"):
            _engine(serving_params, prefix_sharing=True)


class TestPrefixSharingEngine:
    SPEC = SpecConfig(k=0, chunk_size=8)
    #: 12 tokens = one full page + 4: the retired first request
    #: registers its aligned 16-token context, so the repeat's lookup
    #: covers 11 tokens — ending MID-PAGE, which forces a COW before
    #: the suffix chunk writes
    PROMPT = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]

    def _serve_twice(self, params, **kw):
        from apex_tpu import telemetry as tel

        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="pfx", sinks=[mem])
        eng = _engine(params, spec=self.SPEC, prefix_sharing=True,
                      telemetry=bus, **kw)
        r1 = eng.submit(list(self.PROMPT), max_new_tokens=6)
        eng.run()
        r2 = eng.submit(list(self.PROMPT), max_new_tokens=6)
        eng.run()
        return eng, r1, r2, mem

    def test_repeat_prompt_hits_and_streams_stay_bitwise(
            self, serving_params):
        """Prefix sharing is a placement optimization: the repeat
        admission skips prefill for the shared tokens, COWs the
        mid-page boundary, and still produces the unshared control's
        exact streams."""
        ctrl = _engine(serving_params, spec=self.SPEC)
        c1 = ctrl.submit(list(self.PROMPT), max_new_tokens=6)
        ctrl.run()
        c2 = ctrl.submit(list(self.PROMPT), max_new_tokens=6)
        ctrl.run()

        eng, r1, r2, _ = self._serve_twice(serving_params)
        assert r1.prefix_hit is False and r2.prefix_hit is True
        assert list(r1.generated) == list(c1.generated)
        assert list(r2.generated) == list(c2.generated)
        # both retired: every page back except the index's warm prefix
        assert eng.cache.pages_used == len(eng.prefix_index.entries) \
            and len(eng.prefix_index) > 0

    def test_prefix_telemetry_fields(self, serving_params):
        """Satellite 1 wiring: every admit under sharing carries the
        prefix_hit BOOL (misses too — the denominator), and decode
        steps report the pool_shared_pages INT count."""
        from apex_tpu import telemetry as tel

        _, _, _, mem = self._serve_twice(serving_params)
        admits = [e for e in mem.events if e["type"] == "request_admit"]
        assert [e["prefix_hit"] for e in admits] == [False, True]
        assert all(type(e["prefix_hit"]) is bool for e in admits)
        shared = [e["pool_shared_pages"] for e in mem.events
                  if e["type"] == "decode_step"]
        assert all(type(s) is int for s in shared)
        assert max(shared) >= 1     # the repeat really decoded shared
        for e in mem.events:
            tel.validate_event(e)

    def test_sharing_composes_with_tp_and_quant(self, serving_params):
        # the full r17 stack at once; quantized, so the bar is the
        # engine's OWN unshared int8 control, not the fp streams
        ctrl = _engine(serving_params, spec=self.SPEC, tp=2,
                       kv_quant="int8")
        c1 = ctrl.submit(list(self.PROMPT), max_new_tokens=6)
        ctrl.run()
        c2 = ctrl.submit(list(self.PROMPT), max_new_tokens=6)
        ctrl.run()
        _, r1, r2, _ = self._serve_twice(serving_params, tp=2,
                                         kv_quant="int8")
        assert r2.prefix_hit is True
        assert list(r1.generated) == list(c1.generated)
        assert list(r2.generated) == list(c2.generated)


# ---------------------------------------------------------------------------
# resilience on the grown modes: recovery, snapshot/restore, guard
# ---------------------------------------------------------------------------


def _trace_streams(eng, prompts):
    reqs = [eng.submit(p, max_new_tokens=3 + i)
            for i, p in enumerate(prompts)]
    eng.run()
    return [list(r.generated) for r in reqs]


class TestModesResilience:
    MODES = dict(tp=2, kv_quant="int8")

    def test_kill_mid_decode_recovers_bitwise_on_tp_quant(
            self, serving_params):
        """Kill-mid-decode on the tp=2 + int8 engine: rebuild +
        deterministic re-prefill RE-QUANTIZES the same codes, so the
        recovered streams equal the uninterrupted control's bitwise
        (at the quantized route's own parity bar — control is int8)."""
        ctrl = _trace_streams(_engine(serving_params, **self.MODES),
                              PROMPTS)
        with chaos.ServingDeviceLoss(at_step=3, device_ids=[0]) as dl:
            eng = _engine(serving_params, **self.MODES)
            got = _trace_streams(eng, PROMPTS)
        assert dl.fired and eng.recoveries == 1
        assert got == ctrl

    def test_snapshot_restore_round_trip_tp_quant(self, serving_params):
        """snapshot → JSON → restore into a fresh tp=2 + int8 engine
        whose code AND scale pools are sentinel-poisoned → continue:
        the control's streams.  Proves restore re-derives every
        quantized byte from tokens alone."""
        ctrl = _trace_streams(_engine(serving_params, **self.MODES),
                              PROMPTS)
        src = _engine(serving_params, **self.MODES)
        reqs = [src.submit(p, max_new_tokens=3 + i)
                for i, p in enumerate(PROMPTS)]
        for _ in range(4):
            src.step()
        snap = json.loads(json.dumps(src.snapshot()))
        dst = _engine(serving_params, **self.MODES)
        dst.cache.k = jnp.full_like(dst.cache.k, 101)
        dst.cache.v = jnp.full_like(dst.cache.v, 102)
        dst.cache.k_scale = jnp.full_like(dst.cache.k_scale, 1e3)
        dst.cache.v_scale = jnp.full_like(dst.cache.v_scale, 1e3)
        restored = dst.restore(snap)
        dst.run()
        assert restored     # the cut really caught live requests
        # same submission order → same rids on the control engine
        ctrl_by_rid = dict(enumerate(ctrl))
        for r in restored:
            assert list(r.generated) == ctrl_by_rid[r.rid], r.rid

    def test_zero_compiles_after_warmup_all_modes(self, serving_params):
        """The compiled-shapes contract over the FULL r17 executable
        set: tp=2 shard_map steps, quantize-on-write scatter, verify +
        chunked prefill, and the COW page copy — a shared-prefix
        admission after warmup compiles NOTHING."""
        from apex_tpu.analysis import HotPathViolation  # noqa: F401

        eng = _engine(serving_params, tp=2, kv_quant="int8",
                      prefix_sharing=True,
                      spec=SpecConfig(k=2, chunk_size=8))
        eng.warmup()
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
        with hot_path_guard("r17 serving lifetime",
                            transfers=None) as g:
            r1 = eng.submit(list(prompt), max_new_tokens=6)
            eng.run()
            r2 = eng.submit(list(prompt), max_new_tokens=6)
            eng.run()
        assert r2.prefix_hit is True        # the COW path really ran
        assert len(r1.generated) == len(r2.generated) == 6
        assert g.recompiles == 0 and g.syncs == []


# ---------------------------------------------------------------------------
# the heavy parity grid (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("page_size", [4, 8])
@pytest.mark.parametrize("quant", [None, "int8"])
@pytest.mark.parametrize("tp", [1, 2, 4])
def test_parity_grid_batched_matches_sequential(serving_params, tp, quant,
                                                page_size):
    """tp × quant × page-size sweep: every cell holds batched ==
    sequential bitwise, and every full-precision cell additionally
    reproduces the tp=1 fp streams (page size is pool layout only)."""
    kw = dict(tp=tp, kv_quant=quant, page_size=page_size)
    got = _streams(serving_params, PROMPTS, **kw)
    assert _streams_sequential(serving_params, PROMPTS, **kw) == got
    if quant is None:
        assert got == _streams(serving_params, PROMPTS,
                               page_size=page_size)
