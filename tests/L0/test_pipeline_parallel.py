"""Pipeline-parallel tier tests on the 8-device emulated CPU mesh.

Mirrors reference tests (SURVEY.md §4): run_pipeline_parallel_test.py (all
three schedules on a toy model, loss parity vs single-stage),
run_dynamic_batchsize_test.py (microbatch calculators), plus mask/position
utils.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    get_ltor_masks_and_position_ids,
    split_into_microbatches,
)

PP = 4
N_MICRO = 8
MB = 2
HIDDEN = 8


@pytest.fixture()
def pp_mesh():
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(1, PP)
    yield mesh
    parallel_state.destroy_model_parallel()


def _toy_stage_params(key, n_stages):
    """Each stage: one dense layer [HIDDEN, HIDDEN] (same shape per stage
    — SPMD requirement, like the reference's toy MyModel)."""
    keys = jax.random.split(key, n_stages)
    return {
        "w": jnp.stack([jax.random.normal(k, (HIDDEN, HIDDEN)) * 0.3
                        for k in keys]),
        "b": jnp.zeros((n_stages, HIDDEN)),
    }


def _serial_forward(params, x):
    h = x
    for s in range(PP):
        h = jnp.tanh(h @ params["w"][s] + params["b"][s])
    return h


def _serial_loss(params, microbatches):
    losses = []
    for m in range(N_MICRO):
        x = microbatches["x"][m]
        y = microbatches["y"][m]
        out = _serial_forward(params, x)
        losses.append(jnp.mean((out - y) ** 2))
    return jnp.mean(jnp.stack(losses))


def _make_data():
    x = jax.random.normal(jax.random.PRNGKey(1), (N_MICRO, MB, HIDDEN))
    y = jax.random.normal(jax.random.PRNGKey(2), (N_MICRO, MB, HIDDEN))
    return {"x": x, "y": y}


class TestNoPipelining:
    def test_grad_accumulation_matches_full_batch(self, pp_mesh):
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (HIDDEN, HIDDEN)) * 0.3}
        data = _make_data()

        def fwd(p, mb):
            out = jnp.tanh(mb["x"] @ p["w"])
            return jnp.mean((out - mb["y"]) ** 2)

        loss, grads = forward_backward_no_pipelining(
            fwd, params=params, microbatches=data, n_microbatches=N_MICRO)

        def full(p):
            return jnp.mean(jnp.stack(
                [fwd(p, jax.tree_util.tree_map(lambda a: a[m], data))
                 for m in range(N_MICRO)]))

        ref_loss, ref_grads = jax.value_and_grad(full)(params)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
        np.testing.assert_allclose(grads["w"], ref_grads["w"], rtol=1e-4,
                                   atol=1e-6)

    def test_forward_only(self, pp_mesh):
        params = {"w": jnp.eye(HIDDEN)}
        data = _make_data()

        def fwd(p, mb):
            return jnp.sum(mb["x"] @ p["w"])

        (loss,) = forward_backward_no_pipelining(
            fwd, params=params, microbatches=data, n_microbatches=N_MICRO,
            forward_only=True)
        ref = jnp.mean(jnp.stack(
            [jnp.sum(data["x"][m]) for m in range(N_MICRO)]))
        np.testing.assert_allclose(loss, ref, rtol=1e-5)

    def test_schedule_compatible_signature(self, pp_mesh):
        # the unified (stage_fn, loss_fn, ...) convention at pp=1
        params = {"w": jax.random.normal(jax.random.PRNGKey(0),
                                         (HIDDEN, HIDDEN)) * 0.3}
        data = _make_data()

        def stage_fn(p, h, mb):
            return jnp.tanh(mb["x"] @ p["w"])

        def loss_fn(p, y, mb):
            return jnp.mean((y - mb["y"]) ** 2)

        loss, grads = forward_backward_no_pipelining(
            stage_fn, loss_fn, params, data, n_microbatches=N_MICRO,
            tensor_shape=(MB, HIDDEN))

        def full(p):
            return jnp.mean(jnp.stack(
                [loss_fn(p, stage_fn(p, None, jax.tree_util.tree_map(
                    lambda a: a[m], data)), jax.tree_util.tree_map(
                    lambda a: a[m], data)) for m in range(N_MICRO)]))

        ref_loss, ref_grads = jax.value_and_grad(full)(params)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
        np.testing.assert_allclose(grads["w"], ref_grads["w"], rtol=1e-4,
                                   atol=1e-6)


class TestPipelining1F1B:
    def _run_pipelined(self, pp_mesh, params, data, forward_only=False):
        # canonical Megatron layout: each stage owns its own params —
        # the stacked [PP, ...] tree is sharded over the pipeline axis and
        # every device sees only its local [1, ...] slice.
        def stage_fn(p, h, mb):
            s = parallel_state.get_pipeline_model_parallel_rank()
            inp = jnp.where(s == 0, mb["x"], h)
            return jnp.tanh(inp @ p["w"][0] + p["b"][0])

        def loss_fn(p, y, mb):
            return jnp.mean((y - mb["y"]) ** 2)

        def run(p, d):
            return forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, p, d,
                n_microbatches=N_MICRO, tensor_shape=(MB, HIDDEN),
                forward_only=forward_only)

        return shard_map(run, mesh=pp_mesh,
                         in_specs=(P("pipeline"), P()),
                         out_specs=P() if forward_only else (P(), P("pipeline")),
                         check_rep=False)(params, data)

    def test_loss_parity_with_serial(self, pp_mesh):
        # the reference's canonical assertion: pipeline loss == no-pipeline
        # loss (run_megatron_gpt_pipeline.py / run_pipeline_parallel_test.py)
        params = _toy_stage_params(jax.random.PRNGKey(0), PP)
        data = _make_data()
        (loss,) = self._run_pipelined(pp_mesh, params, data, forward_only=True)
        np.testing.assert_allclose(loss, _serial_loss(params, data), rtol=1e-5)

    def test_grad_parity_with_serial(self, pp_mesh):
        params = _toy_stage_params(jax.random.PRNGKey(0), PP)
        data = _make_data()
        loss, grads = self._run_pipelined(pp_mesh, params, data)
        ref_loss, ref_grads = jax.value_and_grad(_serial_loss)(params, data)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
        # each stage's grads live on its own device; serial grads are the
        # full stack.  The pipelined grads for stage s's slice must match.
        np.testing.assert_allclose(grads["w"], ref_grads["w"], rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(grads["b"], ref_grads["b"], rtol=1e-4,
                                   atol=1e-5)

    @pytest.mark.slow  # 8-device 1F1B training loop (ISSUE 2 CI satellite)
    def test_training_decreases_loss(self, pp_mesh):
        params = _toy_stage_params(jax.random.PRNGKey(0), PP)
        data = _make_data()
        losses = []
        for _ in range(10):
            loss, grads = self._run_pipelined(pp_mesh, params, data)
            params = jax.tree_util.tree_map(
                lambda p, g: p - 0.5 * g, params, grads)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestInterleaved:
    def test_loss_and_grad_parity(self, pp_mesh):
        # 2 model chunks per stage -> 8 virtual stages
        vpp = 2
        total_virtual = PP * vpp
        keys = jax.random.split(jax.random.PRNGKey(0), total_virtual)
        full_w = jnp.stack(
            [jax.random.normal(k, (HIDDEN, HIDDEN)) * 0.2 for k in keys])
        data = _make_data()

        # chunked params: device d holds virtual stages d + PP*k, stacked on
        # a leading vpp axis *per device*; build the stacked global layout
        # [PP, vpp, ...] and shard over pipeline.
        chunked = {"w": jnp.stack(
            [jnp.stack([full_w[d + PP * k] for k in range(vpp)])
             for d in range(PP)])}

        def chunk_fn(p, h, mb, k):
            s = parallel_state.get_pipeline_model_parallel_rank()
            v_first = (s == 0) & (k == 0)
            inp = jnp.where(v_first, mb["x"], h)
            return jnp.tanh(inp @ p["w"])

        def loss_fn(p, y, mb):
            return jnp.mean((y - mb["y"]) ** 2)

        def run(p, d):
            p_local = jax.tree_util.tree_map(lambda a: a[0], p)  # [vpp, ...]
            return forward_backward_pipelining_with_interleaving(
                chunk_fn, loss_fn, p_local, d,
                n_microbatches=N_MICRO, num_model_chunks=vpp,
                tensor_shape=(MB, HIDDEN))

        loss, grads = shard_map(
            run, mesh=pp_mesh, in_specs=(P("pipeline"), P()),
            out_specs=(P(), P("pipeline")), check_rep=False)(chunked, data)

        def serial(full_w, d):
            losses = []
            for m in range(N_MICRO):
                h = d["x"][m]
                for v in range(total_virtual):
                    h = jnp.tanh(h @ full_w[v])
                losses.append(jnp.mean((h - d["y"][m]) ** 2))
            return jnp.mean(jnp.stack(losses))

        ref_loss, ref_gw = jax.value_and_grad(serial)(full_w, data)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
        # out_specs P("pipeline") concatenates each device's [vpp, H, H]
        # grads into [PP*vpp, H, H]: device d's chunk k is row d*vpp + k,
        # holding virtual stage d + PP*k.
        for d in range(PP):
            for k in range(vpp):
                np.testing.assert_allclose(
                    grads["w"][d * vpp + k], ref_gw[d + PP * k],
                    rtol=1e-4, atol=1e-5)


class TestScheduleSelector:
    def test_selector(self):
        assert get_forward_backward_func(None, 1) is forward_backward_no_pipelining
        assert (get_forward_backward_func(None, 4)
                is forward_backward_pipelining_without_interleaving)
        assert (get_forward_backward_func(2, 4)
                is forward_backward_pipelining_with_interleaving)


class TestMicrobatchCalculators:
    def test_constant(self):
        c = ConstantNumMicroBatches(64, 2, 4)
        assert c.get() == 8
        with pytest.raises(ValueError):
            ConstantNumMicroBatches(65, 2, 4)

    def test_rampup(self):
        # reference run_dynamic_batchsize_test.py semantics
        c = RampupBatchsizeNumMicroBatches(
            start_batch_size=8, batch_size_increment=8, ramup_samples=80,
            global_batch_size=32, micro_batch_size=2, data_parallel_size=2)
        assert c.get_current_global_batch_size() == 8
        c.update(0, True)
        assert c.get() == 2
        c.update(40, True)
        assert c.get_current_global_batch_size() == 16
        c.update(100, True)
        assert c.get_current_global_batch_size() == 32
        assert c.get() == 8


class TestLtorMasks:
    def test_basic_causal(self):
        data = jnp.array([[5, 3, 9, 3]])
        am, lm, pid = get_ltor_masks_and_position_ids(data, eod_token=9)
        assert am.shape == (1, 1, 4, 4)
        # row i can attend to j <= i  (True = masked out)
        assert not bool(am[0, 0, 2, 0]) and bool(am[0, 0, 0, 2])
        np.testing.assert_array_equal(pid[0], [0, 1, 2, 3])
        np.testing.assert_allclose(lm[0], [1, 1, 1, 1])

    def test_eod_handling(self):
        data = jnp.array([[5, 9, 7, 8]])
        am, lm, pid = get_ltor_masks_and_position_ids(
            data, eod_token=9, reset_position_ids=True,
            reset_attention_mask=True, eod_mask_loss=True)
        np.testing.assert_allclose(lm[0], [1, 0, 1, 1])
        # position ids reset after eod
        np.testing.assert_array_equal(pid[0], [0, 1, 0, 1])
        # token 2 (doc 2) cannot attend to token 0 (doc 1)
        assert bool(am[0, 0, 2, 0])
        assert not bool(am[0, 0, 3, 2])

    def test_split_into_microbatches(self):
        batch = {"x": jnp.arange(24.0).reshape(12, 2)}
        out = split_into_microbatches(batch, 4)
        assert out["x"].shape == (4, 3, 2)
        np.testing.assert_array_equal(out["x"][1, 0], batch["x"][3])


class TestOneFOneBMemory:
    """The point of 1F1B (VERDICT weak #3): live activation memory is O(p),
    not O(m). Peak compiled temp bytes must stay ~flat as n_microbatches
    grows 4x (reference bound: fwd_bwd_pipelining_without_interleaving.py
    keeps <= num_warmup in-flight microbatches)."""

    HID = 128
    MBB = 8

    def _compiled_temp_bytes(self, pp_mesh, n_micro):
        def stage_fn(p, h, mb):
            s = parallel_state.get_pipeline_model_parallel_rank()
            inp = jnp.where(s == 0, mb["x"], h)
            return jnp.tanh(inp @ p["w"][0] + p["b"][0])

        def loss_fn(p, y, mb):
            return jnp.mean((y - mb["y"]) ** 2)

        def run(p, d):
            return forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, p, d,
                n_microbatches=n_micro, tensor_shape=(self.MBB, self.HID))

        params = {
            "w": jnp.zeros((PP, self.HID, self.HID)),
            "b": jnp.zeros((PP, self.HID)),
        }
        data = {
            "x": jnp.zeros((n_micro, self.MBB, self.HID)),
            "y": jnp.zeros((n_micro, self.MBB, self.HID)),
        }
        fn = jax.jit(shard_map(run, mesh=pp_mesh,
                               in_specs=(P("pipeline"), P()),
                               out_specs=(P(), P("pipeline")),
                               check_rep=False))
        compiled = fn.lower(params, data).compile()
        stats = compiled.memory_analysis()
        assert stats is not None and stats.temp_size_in_bytes > 0
        return stats.temp_size_in_bytes

    def test_peak_memory_flat_in_n_microbatches(self, pp_mesh):
        small = self._compiled_temp_bytes(pp_mesh, 4)
        big = self._compiled_temp_bytes(pp_mesh, 16)
        # O(m) residuals would grow temp ~4x here; the ring-buffer design
        # must stay essentially flat (allow slack for compiler noise)
        assert big < small * 1.5, (small, big)

    def _interleaved_temp_bytes(self, pp_mesh, n_micro, vpp=2):
        def chunk_fn(p, h, mb, k):
            s = parallel_state.get_pipeline_model_parallel_rank()
            inp = jnp.where((s == 0) & (k == 0), mb["x"], h)
            return jnp.tanh(inp @ p["w"])

        def loss_fn(p, y, mb):
            return jnp.mean((y - mb["y"]) ** 2)

        def run(p, d):
            p_local = jax.tree_util.tree_map(lambda a: a[0], p)
            return forward_backward_pipelining_with_interleaving(
                chunk_fn, loss_fn, p_local, d,
                n_microbatches=n_micro, num_model_chunks=vpp,
                tensor_shape=(self.MBB, self.HID))

        params = {"w": jnp.zeros((PP, vpp, self.HID, self.HID))}
        data = {
            "x": jnp.zeros((n_micro, self.MBB, self.HID)),
            "y": jnp.zeros((n_micro, self.MBB, self.HID)),
        }
        fn = jax.jit(shard_map(run, mesh=pp_mesh,
                               in_specs=(P("pipeline"), P()),
                               out_specs=(P(), P("pipeline")),
                               check_rep=False))
        stats = fn.lower(params, data).compile().memory_analysis()
        assert stats is not None and stats.temp_size_in_bytes > 0
        return stats.temp_size_in_bytes

    def test_interleaved_peak_memory_flat_in_n_microbatches(self, pp_mesh):
        small = self._interleaved_temp_bytes(pp_mesh, 4)
        big = self._interleaved_temp_bytes(pp_mesh, 16)
        assert big < small * 1.5, (small, big)


class TestUtilsParity:
    def test_print_params_min_max_norm(self, capsys):
        from apex_tpu.transformer.pipeline_parallel.utils import (
            print_params_min_max_norm)
        msg = print_params_min_max_norm(
            {"a": jnp.array([1.0, -2.0]), "b": jnp.ones((2, 2))},
            iteration=7)
        assert "iteration 7" in msg and "min" in msg and "norm" in msg
        assert "a" in msg

    def test_autoresume_noop(self):
        from apex_tpu.transformer.pipeline_parallel.utils import (
            check_adlr_autoresume_termination, get_autoresume)
        assert get_autoresume() is None
        assert check_adlr_autoresume_termination(0, None) is False
