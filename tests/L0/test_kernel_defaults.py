"""Win-or-fall-back CI gate: the newest committed bench record must show
every default-on fused path non-losing (ops/kernel_defaults.py).

Record-selection rules (reworked in r5 after the r4 incident — VERDICT
r4 Weak #1/#2, Next #1):

* **Driver records** (``BENCH_rNN.json``, no suffix) are the authority:
  the newest parseable one with ``bench_schema >= 2`` supplies the gate
  values.  Builder-captured records (``BENCH_rNNb_builder.json``) may
  *supplement* — consulted only when no driver record qualifies — but
  never substitute for a qualifying driver record.
* An **unparseable newest driver record is a FAILURE, not a skip**: it
  means the official perf artifact carries no metrics, which is exactly
  the r4 incident (bench.py printed a final line too large for the
  driver's ~2000-char tail capture; ``parsed: null`` landed in-tree).
  ``BENCH_r04.json`` itself is allowlisted as the diagnosed, fixed
  instance (bench.py now routes top-ops to a sidecar and size-guards
  the summary line via ``_emit_record``).
"""
import glob
import json
import os
import re

import pytest

from apex_tpu.ops.kernel_defaults import DEFAULT_GATES

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# The one diagnosed incident: r4's summary line embedded full top-ops
# tables and defeated the driver's tail parser.  Named here so the gate
# stays green on the historical artifact while FAILING on any future
# driver record that comes back unparseable.
KNOWN_UNPARSEABLE = {"BENCH_r04.json"}

_DRIVER_NAME = re.compile(r"^BENCH_r(\d+)\.json$")


def _round_key(path):
    """Natural sort on the round number: BENCH_r10 must sort after
    BENCH_r9 (lexicographic sort would silently enforce a stale record
    from round 10 on).  Suffixed builder records (e.g. r03b_builder)
    sort after the same round's driver record via the string tail."""
    name = os.path.basename(path)
    m = re.match(r"BENCH_r(\d+)(.*)\.json$", name)
    if not m:
        return (-1, name)
    return (int(m.group(1)), m.group(2))


def _extras(path, merge_sidecar=False):
    """Parsed extras dict of a record, or None if the record carries no
    parsed metrics (unreadable file, ``parsed: null``, missing extras).

    ``merge_sidecar`` is set only for the record SELECTED as the gate
    authority: sections the bench spilled to the committed sidecar file
    (``spilled_to_sidecar``) are merged back, and a gated section that
    cannot be recovered is a hard failure — never for mere selection
    scans (the sidecar is rewritten each bench run, so it only speaks
    for the newest record; older records' spilled sections rotate out
    and must not be graded against a different run's values)."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except Exception:
        return None
    extras = (rec.get("parsed") or {}).get("extras")
    if not isinstance(extras, dict):
        return None
    spilled = extras.get("spilled_to_sidecar")
    if spilled and merge_sidecar:
        try:
            with open(os.path.join(os.path.dirname(path),
                                   "BENCH_TOPOPS.json")) as f:
                sidecar = json.load(f)
        except Exception:
            sidecar = {}
        missing = []
        for key in spilled:
            if key in sidecar:
                extras.setdefault(key, sidecar[key])
            else:
                missing.append(key)
        gated = {e for e, _, _, _ in DEFAULT_GATES}
        lost = sorted(set(missing) & gated)
        assert not lost, (
            f"{os.path.basename(path)}: gated section(s) {lost} were "
            "spilled to the sidecar but BENCH_TOPOPS.json does not "
            "carry them — the gate would be silently un-enforced "
            "(sidecar write failed or file not committed)")
    return extras


def _latest_record():
    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")),
                   key=_round_key)
    driver = [p for p in paths if _DRIVER_NAME.match(os.path.basename(p))]
    if driver:
        newest = driver[-1]
        name = os.path.basename(newest)
        if _extras(newest) is None and name not in KNOWN_UNPARSEABLE:
            raise AssertionError(
                f"{name}: the newest DRIVER perf record is unparseable "
                "(parsed: null / missing extras) — the official artifact "
                "carries no metrics.  bench.py's summary line must stay "
                "under the driver's tail-capture size (see _emit_record); "
                "builder-captured records cannot substitute.")
    for path in reversed(driver):
        extras = _extras(path)
        if extras is not None and extras.get("bench_schema", 0) >= 2:
            return os.path.basename(path), _extras(path,
                                                   merge_sidecar=True)
    for path in reversed(paths):  # supplement: builder-captured records
        if path in driver:
            continue
        extras = _extras(path)
        if extras is not None and extras.get("bench_schema", 0) >= 2:
            return os.path.basename(path), _extras(path,
                                                   merge_sidecar=True)
    return None, None


def test_every_default_wins_in_latest_record():
    name, extras = _latest_record()
    if extras is None:
        pytest.skip("no bench_schema>=2 record committed yet (enforcement "
                    "begins with the first device-timed record)")
    failures = []
    for entry, field, min_val, guards in DEFAULT_GATES:
        section = extras.get(entry)
        if not isinstance(section, dict) or field not in section:
            continue  # entry lost to a transient bench failure: no verdict
        val = section[field]
        if val < min_val:
            failures.append(
                f"{name}: {entry}.{field} = {val} < {min_val} — losing "
                f"default: {guards}")
    assert not failures, "\n".join(failures)


def test_gate_covers_every_speedup_field():
    """Every *speedup* field the bench emits must be claimed by a gate —
    a new fused path cannot ship default-on without enforcement."""
    name, extras = _latest_record()
    if extras is None:
        pytest.skip("no bench_schema>=2 record committed yet")
    gated = {(e, f) for e, f, _, _ in DEFAULT_GATES}
    ungated = []
    for entry, section in extras.items():
        if not isinstance(section, dict):
            continue
        for field in section:
            if "speedup" in field and (entry, field) not in gated:
                ungated.append(f"{entry}.{field}")
    assert not ungated, (
        f"{name}: speedup fields without a kernel_defaults gate: {ungated}")


def test_sweep_cells_not_losing():
    """Applicability-window sweeps (VERDICT r5 Weak #2, acted on in r7):
    every per-shape cell recorded in the sweep sections must stay above
    the parity floor — a losing cell means the fused formulation is
    worse than naive somewhere inside the window it claims, which the
    single-shape scalar gates cannot see.  Winners (>= SWEEP_WIN_MIN)
    are surfaced by kernel_defaults.sweep_verdict as the per-shape
    evidence behind keeping each default (the demote-or-gate decision
    protocol recorded in BASELINE.md)."""
    from apex_tpu.ops.kernel_defaults import (
        SWEEP_PARITY_MIN, SWEEP_SECTIONS, sweep_cells, sweep_verdict)

    name, extras = _latest_record()
    if extras is None:
        pytest.skip("no bench_schema>=2 record committed yet")
    # the per-shape tables ride the sidecar (bench.py writes them there
    # directly, not via the spill path) — the sidecar is rewritten each
    # bench run, so it speaks for the newest record, which is exactly
    # the one _latest_record selects for enforcement
    try:
        with open(os.path.join(REPO, "BENCH_TOPOPS.json")) as f:
            sidecar = json.load(f)
    except Exception:
        sidecar = {}
    failures, seen = [], 0
    for entry in SWEEP_SECTIONS:
        section = extras.get(entry, sidecar.get(entry))
        if not isinstance(section, dict):
            continue  # sweep not in this record: no verdict
        seen += 1
        verdict = sweep_verdict(section)
        for cell, ratio in sweep_cells(section):
            if ratio < SWEEP_PARITY_MIN:
                failures.append(
                    f"{name}: {entry}.{cell} ratio {ratio} < "
                    f"{SWEEP_PARITY_MIN} — the fused form LOSES at this "
                    f"shape; demote it for this cell (losers="
                    f"{verdict['losers']})")
    if not seen:
        pytest.skip(f"{name} carries no sweep sections yet (first "
                    "driver run after r6 records them)")
    assert not failures, "\n".join(failures)


def test_sweep_verdict_classifies():
    """The demote-or-gate helper: winners/parity/losers split at the
    documented thresholds, tolerating error cells and scalar tails."""
    from apex_tpu.ops.kernel_defaults import sweep_verdict

    section = {
        "sk512_causal": {"ratio": 1.31},
        "sk1024_causal": {"ratio": 1.0},
        "sk2048_padding": {"ratio": 0.7},
        "sk4096_causal": {"error": "boom"},
        "s384": {"fast_vs_generic": 1.2},
        "min_ratio": 0.7,
    }
    v = sweep_verdict(section)
    assert v["winners"] == ["sk512_causal", "s384"]
    assert v["parity"] == ["sk1024_causal"]
    assert v["losers"] == ["sk2048_padding"]


def test_sweep_gate_fails_on_losing_cell(tmp_path, monkeypatch):
    """A committed record with a below-parity sweep cell must trip the
    sweep gate."""
    import tests.L0.test_kernel_defaults as mod

    rec = {"parsed": {"extras": {
        "bench_schema": 3,
        "fused_softmax_sweep": {"sk2048_padding": {"ratio": 0.5}},
    }}}
    (tmp_path / "BENCH_r97.json").write_text(json.dumps(rec))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    with pytest.raises(AssertionError, match="sk2048_padding ratio 0.5"):
        mod.test_sweep_cells_not_losing()


def test_gate_fails_on_losing_default(tmp_path, monkeypatch):
    """The failure path: a record showing a losing default must trip the
    gate (the r3 scenario — 0.17x recorded for a default-on path)."""
    import tests.L0.test_kernel_defaults as mod

    rec = {"parsed": {"extras": {
        "bench_schema": 2,
        "layer_norm": {"fwd_speedup": 1.5, "bwd_speedup": 0.17},
    }}}
    p = tmp_path / "BENCH_r99.json"
    p.write_text(json.dumps(rec))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    with pytest.raises(AssertionError, match="bwd_speedup = 0.17"):
        mod.test_every_default_wins_in_latest_record()


def test_natural_sort_picks_double_digit_rounds(tmp_path, monkeypatch):
    import tests.L0.test_kernel_defaults as mod

    old = {"parsed": {"extras": {"bench_schema": 2,
                                 "xentropy": {"speedup": 0.1}}}}
    newer = {"parsed": {"extras": {"bench_schema": 2,
                                   "xentropy": {"speedup": 1.0}}}}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(old))
    (tmp_path / "BENCH_r10.json").write_text(json.dumps(newer))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    name, extras = mod._latest_record()
    assert name == "BENCH_r10.json"
    assert extras["xentropy"]["speedup"] == 1.0


def test_unparseable_newest_driver_record_fails(tmp_path, monkeypatch):
    """The r4 incident class: a fresh driver record with parsed:null must
    FAIL the gate, not silently fall back to self-captured numbers."""
    import tests.L0.test_kernel_defaults as mod

    good = {"parsed": {"extras": {"bench_schema": 2,
                                  "xentropy": {"speedup": 1.0}}}}
    (tmp_path / "BENCH_r06.json").write_text(json.dumps(good))
    (tmp_path / "BENCH_r07.json").write_text(json.dumps({"parsed": None}))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    with pytest.raises(AssertionError, match="unparseable"):
        mod._latest_record()


def test_known_bad_r04_falls_back_to_builder(tmp_path, monkeypatch):
    """BENCH_r04.json (the diagnosed incident) is allowlisted: selection
    falls through it to the newest parseable schema>=2 record."""
    import tests.L0.test_kernel_defaults as mod

    builder = {"parsed": {"extras": {"bench_schema": 2,
                                     "xentropy": {"speedup": 1.0}}}}
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({"parsed": None}))
    (tmp_path / "BENCH_r03b_builder.json").write_text(json.dumps(builder))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    name, extras = mod._latest_record()
    assert name == "BENCH_r03b_builder.json"
    assert extras["xentropy"]["speedup"] == 1.0


def test_driver_record_outranks_builder_record(tmp_path, monkeypatch):
    """A qualifying driver record is the authority even when a builder
    record from the same round sorts after it (closes the r4 loophole
    where the gate only ever graded self-captured numbers)."""
    import tests.L0.test_kernel_defaults as mod

    drv = {"parsed": {"extras": {"bench_schema": 2,
                                 "xentropy": {"speedup": 0.97}}}}
    bld = {"parsed": {"extras": {"bench_schema": 2,
                                 "xentropy": {"speedup": 2.0}}}}
    (tmp_path / "BENCH_r08.json").write_text(json.dumps(drv))
    (tmp_path / "BENCH_r08b_builder.json").write_text(json.dumps(bld))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    name, extras = mod._latest_record()
    assert name == "BENCH_r08.json"
    assert extras["xentropy"]["speedup"] == 0.97


def test_summary_line_always_fits_driver_capture():
    """bench._emit_record must keep the final stdout line under the
    driver's tail-capture size no matter how large extras grow, spilling
    bulk sections to the sidecar (named in spilled_to_sidecar)."""
    import bench

    huge = [{"name": "fusion.%d" % i, "ms": 1.0, "op": "x" * 120}
            for i in range(200)]
    record = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
              "extras": {"bench_schema": 3,
                         "gpt350m_top_ops": huge,
                         "layer_norm": {"fwd_speedup": 1.5},
                         "matmul_roof_tflops": 100.0}}
    line, spilled = bench._emit_record(record)
    assert len(line) <= bench.SUMMARY_LINE_LIMIT
    parsed = json.loads(line)
    assert "gpt350m_top_ops" in spilled
    assert "gpt350m_top_ops" in parsed["extras"]["spilled_to_sidecar"]
    # scalars and small gate sections survive in the line itself
    assert parsed["extras"]["layer_norm"]["fwd_speedup"] == 1.5
    assert parsed["extras"]["matmul_roof_tflops"] == 100.0


def test_spilled_sections_merge_back_from_sidecar(tmp_path, monkeypatch):
    """A record whose gated section was size-spilled to the sidecar must
    still be enforced — the gate merges it back (r5 incident: the grown
    summary line spilled layer_norm and would have un-gated it)."""
    import tests.L0.test_kernel_defaults as mod

    rec = {"parsed": {"extras": {
        "bench_schema": 3,
        "spilled_to_sidecar": ["layer_norm"],
    }}}
    (tmp_path / "BENCH_r42.json").write_text(json.dumps(rec))
    (tmp_path / "BENCH_TOPOPS.json").write_text(json.dumps({
        "layer_norm": {"fwd_speedup": 1.5, "bwd_speedup": 0.17}}))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    with pytest.raises(AssertionError, match="bwd_speedup = 0.17"):
        mod.test_every_default_wins_in_latest_record()


def test_summary_line_fits_even_on_relay_down_run():
    """A run where every microbench fails leaves only long *_error
    strings in extras — those must spill too (review finding: strings
    alone recreated the oversized-line incident)."""
    import bench

    extras = {"bench_schema": 3}
    for i in range(12):
        extras[f"bench_{i}_error"] = "RuntimeError(" + "x" * 200 + ")"
    record = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
              "extras": extras}
    line, spilled = bench._emit_record(record)
    assert len(line) <= bench.SUMMARY_LINE_LIMIT
    assert json.loads(line)["extras"]["bench_schema"] == 3
    assert spilled
