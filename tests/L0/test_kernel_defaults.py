"""Win-or-fall-back CI gate: the newest committed bench record must show
every default-on fused path non-losing (ops/kernel_defaults.py)."""
import glob
import json
import os

import pytest

from apex_tpu.ops.kernel_defaults import DEFAULT_GATES

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _round_key(path):
    """Natural sort on the round number: BENCH_r10 must sort after
    BENCH_r9 (lexicographic sort would silently enforce a stale record
    from round 10 on).  Suffixed builder records (e.g. r03b_builder)
    sort after the same round's driver record via the string tail."""
    import re

    name = os.path.basename(path)
    m = re.match(r"BENCH_r(\d+)(.*)\.json$", name)
    if not m:
        return (-1, name)
    return (int(m.group(1)), m.group(2))


def _latest_record():
    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")),
                   key=_round_key)
    for path in reversed(paths):
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        extras = rec.get("parsed", {}).get("extras", {})
        if extras.get("bench_schema", 0) >= 2:
            return os.path.basename(path), extras
    return None, None


def test_every_default_wins_in_latest_record():
    name, extras = _latest_record()
    if extras is None:
        pytest.skip("no bench_schema>=2 record committed yet (enforcement "
                    "begins with the first device-timed record)")
    failures = []
    for entry, field, min_val, guards in DEFAULT_GATES:
        section = extras.get(entry)
        if not isinstance(section, dict) or field not in section:
            continue  # entry lost to a transient bench failure: no verdict
        val = section[field]
        if val < min_val:
            failures.append(
                f"{name}: {entry}.{field} = {val} < {min_val} — losing "
                f"default: {guards}")
    assert not failures, "\n".join(failures)


def test_gate_covers_every_speedup_field():
    """Every *speedup* field the bench emits must be claimed by a gate —
    a new fused path cannot ship default-on without enforcement."""
    name, extras = _latest_record()
    if extras is None:
        pytest.skip("no bench_schema>=2 record committed yet")
    gated = {(e, f) for e, f, _, _ in DEFAULT_GATES}
    ungated = []
    for entry, section in extras.items():
        if not isinstance(section, dict):
            continue
        for field in section:
            if "speedup" in field and (entry, field) not in gated:
                ungated.append(f"{entry}.{field}")
    assert not ungated, (
        f"{name}: speedup fields without a kernel_defaults gate: {ungated}")


def test_gate_fails_on_losing_default(tmp_path, monkeypatch):
    """The failure path: a record showing a losing default must trip the
    gate (the r3 scenario — 0.17x recorded for a default-on path)."""
    import tests.L0.test_kernel_defaults as mod

    rec = {"parsed": {"extras": {
        "bench_schema": 2,
        "layer_norm": {"fwd_speedup": 1.5, "bwd_speedup": 0.17},
    }}}
    p = tmp_path / "BENCH_r99.json"
    p.write_text(json.dumps(rec))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    with pytest.raises(AssertionError, match="bwd_speedup = 0.17"):
        mod.test_every_default_wins_in_latest_record()


def test_natural_sort_picks_double_digit_rounds(tmp_path, monkeypatch):
    import tests.L0.test_kernel_defaults as mod

    old = {"parsed": {"extras": {"bench_schema": 2,
                                 "xentropy": {"speedup": 0.1}}}}
    newer = {"parsed": {"extras": {"bench_schema": 2,
                                   "xentropy": {"speedup": 1.0}}}}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(old))
    (tmp_path / "BENCH_r10.json").write_text(json.dumps(newer))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    name, extras = mod._latest_record()
    assert name == "BENCH_r10.json"
    assert extras["xentropy"]["speedup"] == 1.0
