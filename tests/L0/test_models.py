"""Model-zoo tests (CPU, tiny shapes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, optimizers
from apex_tpu.models import ResNet, ResNetConfig
from apex_tpu.ops import softmax_cross_entropy_loss


def _tiny_cfg(**kw):
    return ResNetConfig(block_sizes=(1, 1), width=8, num_classes=10, **kw)


class TestResNet:
    def test_forward_shapes_and_state(self):
        model = ResNet(_tiny_cfg())
        params, state = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits, new_state = model.apply(params, state, x, training=True)
        assert logits.shape == (2, 10)
        # BN running stats must move in training mode
        assert not np.allclose(new_state["bn1"]["mean"], state["bn1"]["mean"])
        # eval mode keeps state
        logits_eval, eval_state = model.apply(params, new_state, x,
                                              training=False)
        np.testing.assert_array_equal(eval_state["bn1"]["mean"],
                                      new_state["bn1"]["mean"])

    def test_amp_o2_training_decreases_loss(self):
        # the bench.py path in miniature: O2 + FusedLAMB + dynamic scale
        model = ResNet(_tiny_cfg())
        params, bn_state = model.init(jax.random.PRNGKey(0))
        amp_state = amp.initialize("O2")
        scaler = amp_state.scaler
        scale_state = scaler.init()
        opt = optimizers.FusedLAMB(lr=1e-2)
        opt_state = opt.init(params)

        def loss_fn(p, bn, x, y):
            logits, new_bn = model.apply(p, bn, x, training=True)
            return softmax_cross_entropy_loss(logits, y).mean(), new_bn

        grad_fn = amp.scaled_value_and_grad(loss_fn, scaler, has_aux=True)

        @jax.jit
        def train_step(params, bn, opt_state, scale_state, x, y):
            half = amp_state.cast_model(params)
            (loss, new_bn), grads, finite = grad_fn(scale_state, half, bn, x, y)
            new_params, new_opt = opt.step(grads, opt_state, params)
            params, opt_state = amp.skip_or_step(
                finite, (new_params, new_opt), (params, opt_state))
            scale_state = scaler.update(scale_state, finite)
            return params, new_bn, opt_state, scale_state, loss

        x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3),
                              jnp.bfloat16)
        y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10)
        losses = []
        for _ in range(8):
            params, bn_state, opt_state, scale_state, loss = train_step(
                params, bn_state, opt_state, scale_state, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        assert all(np.isfinite(losses))

    def test_half_params_stay_half_master_fp32(self):
        model = ResNet(_tiny_cfg())
        params, _ = model.init(jax.random.PRNGKey(0))
        amp_state = amp.initialize("O2")
        half = amp_state.cast_model(params)
        assert half["conv1"]["w"].dtype == jnp.bfloat16
        assert half["bn1"]["weight"].dtype == jnp.float32  # keep_batchnorm_fp32
        assert params["conv1"]["w"].dtype == jnp.float32


class TestStemSpaceToDepth:
    """The r7 ResNet stem conv attempt (VERDICT r5 Weak #3): the 4x4/s1
    space-to-depth form must be numerically identical to the 7x7/s2 SAME
    stem — the bench's speedup comparison is only meaningful if the two
    compute the same function."""

    def test_s2d_stem_matches_standard(self):
        import bench

        x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
        w7 = jax.random.normal(jax.random.PRNGKey(1), (7, 7, 3, 8)) * 0.1
        std = jax.lax.conv_general_dilated(
            x, w7, (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        s2d = bench.stem_conv_s2d(x, w7)
        assert s2d.shape == std.shape
        np.testing.assert_allclose(np.asarray(s2d), np.asarray(std),
                                   rtol=1e-5, atol=1e-5)

    def test_s2d_stem_grads_match(self):
        import bench

        x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 16, 3))
        w7 = jax.random.normal(jax.random.PRNGKey(1), (7, 7, 3, 4)) * 0.1

        def loss_std(x, w):
            return jnp.sum(jax.lax.conv_general_dilated(
                x, w, (2, 2), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) ** 2)

        def loss_s2d(x, w):
            return jnp.sum(bench.stem_conv_s2d(x, w) ** 2)

        gx1, gw1 = jax.grad(loss_std, argnums=(0, 1))(x, w7)
        gx2, gw2 = jax.grad(loss_s2d, argnums=(0, 1))(x, w7)
        np.testing.assert_allclose(np.asarray(gx2), np.asarray(gx1),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw2), np.asarray(gw1),
                                   rtol=1e-4, atol=1e-5)


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as ge
        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[-1] == 1024

    @pytest.mark.slow  # 8-device multichip dryrun (ISSUE 2 CI satellite)
    def test_dryrun_multichip(self):
        import __graft_entry__ as ge
        ge.dryrun_multichip(8)
