"""fused_linear_cross_entropy vs the unfused formulation (SURVEY.md §4:
kernel-vs-reference tier). Loss must be fp32-exact; grads bf16-class."""
import jax
import jax.numpy as jnp
import pytest

from apex_tpu.ops import fused_linear_cross_entropy


def _naive(h, w, labels, smoothing=0.0):
    z = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    m = jnp.max(z, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(z - m[:, None]), axis=-1))
    tz = jnp.take_along_axis(z, labels[:, None], axis=-1)[:, 0]
    if smoothing:
        return lse - (1 - smoothing) * tz - smoothing * jnp.mean(z, -1)
    return lse - tz


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_loss_matches_exactly(smoothing):
    N, H, V = 64, 32, 200
    h = jax.random.normal(jax.random.PRNGKey(0), (N, H), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (V, H), jnp.bfloat16) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(2), (N,), 0, V)
    fused = jax.jit(lambda: fused_linear_cross_entropy(
        h, w, labels, smoothing))()
    ref = jax.jit(lambda: _naive(h, w, labels, smoothing))()
    # identical fp32 math, but compiled as two separate programs whose
    # reduction order XLA may legally reorder — ulp-level tolerance
    assert float(jnp.max(jnp.abs(fused - ref))) < 1e-5


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_grads_match_bf16_class(smoothing):
    N, H, V = 64, 32, 200
    h = jax.random.normal(jax.random.PRNGKey(0), (N, H), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (V, H), jnp.bfloat16) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(2), (N,), 0, V)
    r = jax.random.normal(jax.random.PRNGKey(3), (N,), jnp.float32)

    def fl(h, w):
        return jnp.sum(fused_linear_cross_entropy(h, w, labels, smoothing)
                       * r)

    def nl(h, w):
        return jnp.sum(_naive(h, w, labels, smoothing) * r)

    gf = jax.jit(jax.grad(fl, argnums=(0, 1)))(h, w)
    gn = jax.jit(jax.grad(nl, argnums=(0, 1)))(h, w)
    for a, b in zip(gf, gn):
        a32, b32 = a.astype(jnp.float32), b.astype(jnp.float32)
        scale = float(jnp.max(jnp.abs(b32))) or 1.0
        assert float(jnp.max(jnp.abs(a32 - b32))) / scale < 2e-2


@pytest.mark.slow  # full-model fused-vs-unfused parity (ISSUE 6 wall-clock)
def test_gpt_head_uses_fused_path_and_matches():
    """GPT tp=1 losses via the fused head vs the logits+vocab-CE path."""
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.testing import GPTConfig, GPTModel
    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        vocab_parallel_cross_entropy)
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    cfg = GPTConfig(num_layers=2, hidden_size=64, num_attention_heads=2,
                    vocab_size=512, max_position_embeddings=128,
                    tp_size=1, bf16=True)
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        1, 1, devices=jax.devices()[:1])
    model = GPTModel(cfg)
    params = model.shard_master(model.init_master(jax.random.PRNGKey(0)), 0)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 512)
    labels = jnp.roll(toks, -1, axis=-1)

    def run(fn):
        return shard_map(fn, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
                         check_rep=False)(toks, labels)

    fused = jax.jit(lambda t, l: run(
        lambda t, l: model.apply(params, t, labels=l)))(toks, labels)
    unfused = jax.jit(lambda t, l: run(
        lambda t, l: vocab_parallel_cross_entropy(
            model.apply(params, t), l)))(toks, labels)
    assert float(jnp.max(jnp.abs(fused - unfused))) < 1e-5
    parallel_state.destroy_model_parallel()
