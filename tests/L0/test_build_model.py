"""Generic build_model tests (reference schedules/common.py:18-106) plus the
simple distributed example as a subprocess smoke test."""

import os
import subprocess
import sys

import pytest

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel.build_model import build_model


class _Chunk:
    def __init__(self, pre_process, post_process, tag):
        self.pre_process = pre_process
        self.post_process = post_process
        self.tag = tag


def _provider(pre_process=False, post_process=False, tag="x"):
    return _Chunk(pre_process, post_process, tag)


def setup_function(_):
    parallel_state.destroy_model_parallel()


def test_single_chunk_flags():
    parallel_state.initialize_model_parallel(1, 1)
    models = build_model(_provider, tag="m")
    assert len(models) == 1
    # pp=1: the only stage is both first and last
    assert models[0].pre_process and models[0].post_process
    assert models[0].tag == "m"
    assert models[0].data_parallel_axis == "data"
    parallel_state.destroy_model_parallel()


def test_no_ddp_wrap():
    parallel_state.initialize_model_parallel(1, 1)
    models = build_model(_provider, wrap_with_ddp=False)
    assert not hasattr(models[0], "data_parallel_axis")
    parallel_state.destroy_model_parallel()


def test_virtual_chunks():
    parallel_state.initialize_model_parallel(1, 4, 2)
    models = build_model(_provider, virtual_pipeline_model_parallel_size=2)
    assert len(models) == 2
    # first chunk may hold the embedding end, last chunk the head end
    assert models[0].pre_process and not models[0].post_process
    assert models[1].post_process and not models[1].pre_process
    # cursor restored after building (common.py:59)
    assert parallel_state.get_virtual_pipeline_model_parallel_rank() == 0
    parallel_state.destroy_model_parallel()


def test_simple_distributed_example_runs():
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    script = os.path.join(os.path.dirname(__file__), "..", "..", "examples",
                          "simple", "distributed",
                          "distributed_data_parallel.py")
    out = subprocess.run([sys.executable, script], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "final loss:" in out.stdout
    first = float(out.stdout.split("loss ")[1].split()[0])
    final = float(out.stdout.rsplit("final loss:", 1)[1])
    assert final < first
