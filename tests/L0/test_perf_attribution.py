"""In-run performance attribution (ISSUE 9): ProfileSampler through the
telemetry bus, the profile/memory event schema, the overhead budget, the
train-loop wiring, and the BENCH regress CLI gate.

The sampler tests run on a SYNTHETIC tracer (a capture backend that
writes a fixed Chrome-trace fixture), so the classifier -> bus -> schema
-> summarize path is deterministic on CPU; one live jax.profiler capture
rides the slow tier like PR 4's trace-backed case.
"""

import gzip
import json
import os

import jax
import jax.numpy as jnp
import pytest

from apex_tpu import telemetry as tele
from apex_tpu.telemetry.__main__ import main as tele_cli

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------- helpers


class SynthTracer:
    """Capture backend writing a fixed device-timeline fixture: a 100us
    all-reduce with 60us of concurrent fusion compute and a 10us dot at
    [70, 80) -> exposed collective = 30us = 0.03 ms."""

    EVENTS = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 100.0,
         "name": "all-reduce.1"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 0.0, "dur": 60.0,
         "name": "fusion.2"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 70.0, "dur": 10.0,
         "name": "dot.3"},
    ]

    def __init__(self, fail_on=()):
        self.starts = 0
        self.fail_on = set(fail_on)
        self._dir = None

    def start(self, logdir):
        self.starts += 1
        if "start" in self.fail_on:
            raise RuntimeError("injected start failure")
        self._dir = logdir

    def stop(self):
        if "stop" in self.fail_on:
            raise RuntimeError("injected stop failure")
        with gzip.open(os.path.join(self._dir, "d.trace.json.gz"),
                       "wt") as f:
            json.dump({"traceEvents": self.EVENTS}, f)


def _bus(tmp_path, run_id="prof"):
    mem = tele.MemorySink()
    path = str(tmp_path / f"{run_id}.jsonl")
    bus = tele.TelemetryBus(run_id, sinks=[tele.JsonlSink(path), mem])
    return bus, mem, path


EXPOSED_MS = 0.03  # the fixture's analytic answer


# ------------------------------------------------------- event schema


def test_profile_and_memory_events_validate_round_trip(tmp_path):
    """ISSUE 9 satellite: the new types are in the closed event set and
    their payloads round-trip through emit -> JSONL -> validator."""
    bus, mem, path = _bus(tmp_path)
    bus.emit("profile", step=3, window_steps=1,
             phase_ms={"matmul": 1.5, "collective": 0.4},
             exposed_collective_ms=0.2, collective_ms=0.4,
             total_device_ms=2.0, overhead_ms=12.0)
    bus.emit("memory", step=3, stats_available=True, n_devices=1,
             live_bytes=123, peak_bytes=456)
    bus.emit("memory", step=4, stats_available=False, n_devices=0)
    bus.close()
    assert tele.validate_jsonl(path) == 3
    assert [e["type"] for e in tele.load_jsonl(path)] == [
        "profile", "memory", "memory"]


def test_profile_schema_rejects_malformed():
    bus = tele.TelemetryBus("x", sinks=[])
    ev = bus.emit("profile", step=1, window_steps=1, phase_ms={},
                  exposed_collective_ms=0.0, collective_ms=0.0,
                  total_device_ms=0.0, overhead_ms=0.0)
    tele.validate_event(ev)
    bad = dict(ev)
    del bad["phase_ms"]
    with pytest.raises(tele.SchemaError, match="phase_ms"):
        tele.validate_event(bad)
    bad = dict(ev, exposed_collective_ms="lots")
    with pytest.raises(tele.SchemaError, match="exposed_collective_ms"):
        tele.validate_event(bad)


def test_memory_schema_bool_not_int_discipline():
    """stats_available must be a real bool — 1/0 sentinels are exactly
    what the validator's bool discipline exists to reject."""
    bus = tele.TelemetryBus("x", sinks=[])
    ev = bus.emit("memory", step=1, stats_available=True, n_devices=1)
    tele.validate_event(ev)
    with pytest.raises(tele.SchemaError, match="stats_available"):
        tele.validate_event(dict(ev, stats_available=1))
    # and n_devices is an int, not a smuggled bool
    with pytest.raises(tele.SchemaError, match="n_devices"):
        tele.validate_event(dict(ev, n_devices=True))


def test_device_memory_payload_shape():
    p = tele.device_memory_payload()
    assert isinstance(p["stats_available"], bool)
    assert isinstance(p["n_devices"], int)
    if not p["stats_available"]:
        assert "live_bytes" not in p and "peak_bytes" not in p
    else:  # pragma: no cover — backend-dependent
        assert p["peak_bytes"] >= 0


# ------------------------------------------------------- sampler core


def test_sampler_cadence_emits_at_every_with_window(tmp_path):
    bus, mem, path = _bus(tmp_path)
    tr = SynthTracer()
    s = tele.ProfileSampler(bus, every=5, window=2, tracer=tr,
                            max_overhead=1e9)  # budget off: cadence test
    for step in range(1, 13):
        s.on_step(step)
    bus.close()
    profs = [e for e in mem.events if e["type"] == "profile"]
    mems = [e for e in mem.events if e["type"] == "memory"]
    # windows start after steps 5 and 10, close 2 steps later
    assert [e["step"] for e in profs] == [7, 12]
    assert len(mems) == 2
    assert s.samples == 2 and tr.starts == 2
    for e in profs:
        assert e["window_steps"] == 2
        assert e["phase_ms"]["collective"] == pytest.approx(0.1)
        assert e["exposed_collective_ms"] == pytest.approx(EXPOSED_MS)
        assert e["overhead_ms"] > 0
    # the stream a sampler produces passes the validate CLI (acceptance)
    assert tele_cli(["validate", path]) == 0


def test_sampler_books_overhead_to_profile_bucket(tmp_path):
    bus, mem, _ = _bus(tmp_path)
    acct = bus.accountant(window=10)
    s = tele.ProfileSampler(bus, every=2, window=1, tracer=SynthTracer(),
                            accountant=acct, max_overhead=1e9)
    for step in range(1, 6):
        s.on_step(step)
    assert s.samples >= 1
    assert acct.buckets["profile"] == pytest.approx(s.overhead_s)
    end = acct.finish(step=5)
    assert end["buckets_s"]["profile"] > 0
    bus.close()


def test_sampler_budget_defers_and_bounds_overhead(tmp_path):
    """The ≤1% bound is enforced by construction: with a fake clock
    (100 ms steps, 30 ms captures) the sampler must defer captures
    whenever another one would push overhead past max_overhead of the
    wall — asserted deterministically, no real sleeps."""
    bus, mem, _ = _bus(tmp_path)
    clock = {"t": 0.0}
    tr = SynthTracer()
    real_start, real_stop = tr.start, tr.stop

    def start(d):
        clock["t"] += 0.015  # 15 ms to start a capture
        real_start(d)

    def stop():
        clock["t"] += 0.015  # 15 ms to stop + parse
        real_stop()

    tr.start, tr.stop = start, stop
    s = tele.ProfileSampler(bus, every=10, window=1, tracer=tr,
                            max_overhead=0.01)
    s._now = lambda: clock["t"]
    for step in range(1, 1001):
        clock["t"] += 0.1  # the step itself
        s.on_step(step)
    bus.close()
    assert s.samples >= 1, "budget must not starve the sampler forever"
    assert s.deferred > 0, "with 30ms captures every 10x100ms steps the" \
                           " budget must defer some slots"
    assert s.overhead_fraction() <= 0.01 + 1e-9, s.totals()
    # deferral happens instead of violation: every scheduled slot either
    # sampled or deferred
    assert s.samples + s.deferred == 1000 // 10


def test_sampler_failure_disables_after_max_and_never_raises(tmp_path):
    bus, mem, _ = _bus(tmp_path)
    s = tele.ProfileSampler(bus, every=1, window=1,
                            tracer=SynthTracer(fail_on={"stop"}),
                            max_overhead=1e9, max_failures=3)
    for step in range(1, 10):
        s.on_step(step)  # must not raise
    assert s.disabled and s.failures == 3
    assert "injected stop failure" in s.last_error
    assert not any(e["type"] == "profile" for e in mem.events)
    bus.close()


def test_sampler_capture_explicit_window(tmp_path):
    """The bench entry point: capture(run_window) returns the report
    and emits the profile/memory pair."""
    bus, mem, path = _bus(tmp_path)
    ran = {"n": 0}
    s = tele.ProfileSampler(bus, window=1, tracer=SynthTracer())
    rep = s.capture(lambda: ran.__setitem__("n", ran["n"] + 1), step=42)
    bus.close()
    assert ran["n"] == 1
    assert rep is not None
    assert rep.exposed_collective_ms == pytest.approx(EXPOSED_MS)
    profs = [e for e in mem.events if e["type"] == "profile"]
    assert len(profs) == 1 and profs[0]["step"] == 42
    assert tele_cli(["validate", path]) == 0


# -------------------------------------------------- loop + summarize


def test_loop_wires_sampler_and_summarize_renders_phases(tmp_path, capsys):
    """run_resilient_training(profile_sampler=...): profile/memory
    events ride the run's stream, overhead books to the profile
    bucket, the stream validates, and summarize renders the phase
    breakdown + exposed-collective next to the step percentiles."""
    from apex_tpu.transformer.testing import run_resilient_training

    bus, mem, path = _bus(tmp_path, "loop")
    sampler = tele.ProfileSampler(bus, every=3, window=1,
                                  tracer=SynthTracer(), max_overhead=1e9)

    @jax.jit
    def stepfn(s, b):
        return s + b

    result = run_resilient_training(
        lambda s, b: (stepfn(s, b), None), jnp.zeros(()),
        [jnp.ones(())] * 10, telemetry=bus, profile_sampler=sampler)
    bus.close()
    assert result.step == 10 and sampler.samples >= 2
    # the loop handed the sampler its accountant
    assert sampler._acct is bus._accountant
    assert tele.validate_jsonl(path) == len(mem.events)
    end = [e for e in mem.events if e["type"] == "run_end"][-1]
    assert end["buckets_s"].get("profile", 0) > 0

    s = tele.summarize_events(mem.events)
    assert s["profile_samples"] == sampler.samples
    assert s["phase_ms"]["collective"] == pytest.approx(0.1)
    assert s["exposed_collective_ms"] == pytest.approx(EXPOSED_MS)
    txt = tele.format_summary(s)
    assert "phases" in txt and "exposed coll" in txt

    # the CLI renders the same stream (and --json carries the fields)
    assert tele_cli(["summarize", path, "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["exposed_collective_ms"] == pytest.approx(EXPOSED_MS)


def test_diff_carries_phase_and_exposed_rows(tmp_path, capsys):
    bus_a, mem_a, path_a = _bus(tmp_path, "a")
    sa = tele.ProfileSampler(bus_a, every=1, window=1,
                             tracer=SynthTracer(), max_overhead=1e9)
    for i in range(1, 4):
        sa.on_step(i)
    bus_a.emit("step", step=4, step_ms=5.0)
    bus_a.close()
    bus_b, mem_b, path_b = _bus(tmp_path, "b")
    bus_b.emit("step", step=1, step_ms=6.0)
    bus_b.emit("profile", step=1, window_steps=1,
               phase_ms={"collective": 0.02, "matmul": 0.3},
               exposed_collective_ms=0.001, collective_ms=0.02,
               total_device_ms=0.4, overhead_ms=1.0)
    bus_b.close()
    assert tele_cli(["summarize", path_a, "--diff", path_b]) == 0
    out = capsys.readouterr().out
    assert "exposed (ms)" in out
    assert "ph:collective" in out and "ph:matmul" in out


# ------------------------------------------------------- regress gate


def test_regress_direction_rules():
    from apex_tpu.telemetry.regress import key_direction

    assert key_direction("gpt1p3b_tokens_per_sec") == "higher"
    assert key_direction("resnet50_mfu_vs_roof") == "higher"
    assert key_direction("gpt1p3b_goodput") == "higher"
    assert key_direction("bert_varlen_vs_padded_speedup") == "higher"
    assert key_direction("resnet50_step_ms_p95") == "lower"
    assert key_direction("serving_tpot_p50") == "lower"
    assert key_direction("gpt1p3b_exposed_collective_ms") == "lower"
    assert key_direction("gpt1p3b_hbm_peak_gb") == "lower"
    assert key_direction("resnet50_phase_collective_ms") == "lower"
    # serving overload keys (ISSUE 10): SLO attainment up, tail
    # latency down, shed rate REPORTED but never gated (its right
    # value depends on the offered load — a gate must not guess)
    assert key_direction("serving_deadline_hit_rate") == "higher"
    assert key_direction("serving_tpot_p99_overload") == "lower"
    assert key_direction("serving_shed_rate") is None
    # speculation (ISSUE 12): committed tokens per decode-step row up;
    # the SLO-reference echoes are config, not measurements
    assert key_direction("serving_accepted_tokens_per_step") == "higher"
    assert key_direction("serving_slo_ref_first_token") is None
    assert key_direction("serving_slo_ref_per_token") is None
    # config echoes and counters are NOT gated
    assert key_direction("gpt1p3b_batch") is None
    assert key_direction("bench_schema") is None


def test_regress_compare_and_exit_codes(tmp_path):
    a = tmp_path / "a.json"
    b_ok = tmp_path / "b_ok.json"
    b_bad = tmp_path / "b_bad.json"
    base = {"metric": "resnet50_amp_o2_fusedlamb_images_per_sec",
            "value": 2400.0,
            "extras": {"gpt1p3b_tokens_per_sec": 10000.0,
                       "gpt1p3b_step_ms_p95": 200.0,
                       "gpt1p3b_batch": 4}}
    a.write_text(json.dumps(base))
    ok = json.loads(a.read_text())
    ok["value"] = 2380.0                       # -0.8%: inside 5%
    ok["extras"]["gpt1p3b_tokens_per_sec"] = 10400.0
    ok["extras"]["gpt1p3b_step_ms_p95"] = 208.0
    ok["extras"]["gpt1p3b_batch"] = 8          # ungated: may move freely
    b_ok.write_text(json.dumps(ok))
    bad = json.loads(a.read_text())
    bad["extras"]["gpt1p3b_tokens_per_sec"] = 8000.0  # -20%
    b_bad.write_text(json.dumps(bad))

    assert tele_cli(["regress", str(a), str(b_ok),
                     "--max-regress", "5"]) == 0
    assert tele_cli(["regress", str(a), str(b_bad),
                     "--max-regress", "5"]) == 1
    # a tighter threshold turns the ok pair's +4% p95 into a failure
    assert tele_cli(["regress", str(a), str(b_ok),
                     "--max-regress", "1"]) == 1
    # --keys makes a named key mandatory: a vanished headline fails
    assert tele_cli(["regress", str(a), str(b_ok), "--max-regress", "50",
                     "--keys", "does_not_exist"]) == 1


def test_regress_lower_is_better_direction(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"gpt1p3b_exposed_collective_ms": 50.0}))
    b.write_text(json.dumps({"gpt1p3b_exposed_collective_ms": 80.0}))
    # +60% exposed communication = regression on a lower-is-better key
    assert tele_cli(["regress", str(a), str(b),
                     "--max-regress", "10"]) == 1
    # the other way around is an improvement
    assert tele_cli(["regress", str(b), str(a),
                     "--max-regress", "10"]) == 0


def test_regress_zero_baseline_is_not_a_blind_spot(tmp_path):
    """Review finding: a gated key moving OFF a 0.0 baseline is an
    unbounded move, not a 0% change — e.g. exposed collective going
    0 -> 50 ms must fail the gate at any threshold."""
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"gpt1p3b_exposed_collective_ms": 0.0,
                             "gpt1p3b_tokens_per_sec": 0.0}))
    b.write_text(json.dumps({"gpt1p3b_exposed_collective_ms": 50.0,
                             "gpt1p3b_tokens_per_sec": 100.0}))
    # exposed 0 -> 50 regresses (lower-better); tok/s 0 -> 100 improves
    assert tele_cli(["regress", str(a), str(b),
                     "--max-regress", "1000"]) == 1
    assert tele_cli(["regress", str(b), str(a),
                     "--max-regress", "50"]) == 1  # tok/s 100 -> 0: -100%
    # both-zero pairs are a clean 0% pass
    z = tmp_path / "z.json"
    z.write_text(json.dumps({"gpt1p3b_exposed_collective_ms": 0.0}))
    assert tele_cli(["regress", str(z), str(z), "--max-regress", "1"]) == 0


def test_capture_books_overhead_exactly_once_on_emit_failure(tmp_path):
    """Review finding: a failure AFTER the window ran must not book the
    capture wall twice (it would overstate sampler overhead and skew
    goodput)."""
    bus, mem, _ = _bus(tmp_path)
    acct = bus.accountant(window=10)
    clock = {"t": 0.0}
    s = tele.ProfileSampler(bus, window=1, tracer=SynthTracer(),
                            accountant=acct)
    s._now = lambda: clock["t"]

    def boom(step, report, overhead_s):
        raise RuntimeError("emit failed")

    s._emit = boom
    rep = s.capture(lambda: clock.__setitem__("t", clock["t"] + 2.0),
                    step=1)
    bus.close()
    assert rep is not None              # the report itself succeeded
    assert s.failures == 1              # ...but the emit failure counted
    assert s.overhead_s == pytest.approx(2.0)   # once, not twice
    assert acct.buckets["profile"] == pytest.approx(2.0)


def test_regress_self_test_on_committed_records(capsys):
    """ISSUE 9 satellite: the gate runs against two committed BENCH
    records (r5 and its same-round builder rerun — a genuinely clean
    pair) and compares a meaningful number of gated keys."""
    a = os.path.join(REPO, "BENCH_r05.json")
    b = os.path.join(REPO, "BENCH_r05b_builder.json")
    rc = tele_cli(["regress", a, b, "--max-regress", "25", "--json"])
    rec = json.loads(capsys.readouterr().out)
    assert rc == 0, rec["failures"]
    gated = [r for r in rec["rows"] if r["gated"]]
    assert len(gated) >= 20, "the committed records must gate the " \
                             "flagship throughput/latency keys"
    keys = {r["key"] for r in gated}
    assert "gpt350m_tokens_per_sec" in keys
    assert "resnet50_amp_o2_fusedlamb_images_per_sec" in keys


def test_regress_serving_keys_mandatory_on_committed_pair(capsys):
    """ISSUE 10 satellite: ``serving_deadline_hit_rate`` is MANDATORY
    (via --keys) over the committed serving BENCH pair — if a future
    change drops the overload segment's headline key, the gate fails
    instead of silently comparing nothing."""
    a = os.path.join(REPO, "BENCH_r10_serving.json")
    b = os.path.join(REPO, "BENCH_r10b_serving.json")
    rc = tele_cli(["regress", a, b, "--max-regress", "75", "--json",
                   "--keys", "serving_deadline_hit_rate,"
                             "serving_tpot_p99_overload,"
                             "serving_shed_rate"])
    rec = json.loads(capsys.readouterr().out)
    assert rc == 0, rec["failures"]
    by_key = {r["key"]: r for r in rec["rows"]}
    assert by_key["serving_deadline_hit_rate"]["direction"] == "higher"
    assert by_key["serving_tpot_p99_overload"]["direction"] == "lower"
    assert by_key["serving_shed_rate"]["gated"] is False
    # the committed records really carry non-degenerate overload data
    assert 0.0 < by_key["serving_deadline_hit_rate"]["a"] <= 1.0
    # ...and a vanished mandatory key is a failure, not a skip
    assert tele_cli(["regress", a, b, "--max-regress", "75",
                     "--keys", "serving_deadline_hit_rate,gone_key"]) == 1


def test_regress_speculation_keys_mandatory_on_committed_r12_pair(capsys):
    """ISSUE 12 satellite: the speculation headline keys are MANDATORY
    over the committed r12 pair (A = speculation off, B = draft–verify
    + chunked prefill on, judged against A's own SLO bar).  The gate
    proves the acceptance criterion on committed data: accepted tokens
    per step moved OFF the 1.0 baseline while TTFT did not regress."""
    a = os.path.join(REPO, "BENCH_r12_serving.json")
    b = os.path.join(REPO, "BENCH_r12b_serving.json")
    rc = tele_cli(["regress", a, b, "--max-regress", "25", "--json",
                   "--keys", "serving_accepted_tokens_per_step,"
                             "serving_ttft_p50,"
                             "serving_tpot_p99_overload,"
                             "serving_deadline_hit_rate,"
                             "serving_shed_rate"])
    rec = json.loads(capsys.readouterr().out)
    assert rc == 0, rec["failures"]
    by_key = {r["key"]: r for r in rec["rows"]}
    acc = by_key["serving_accepted_tokens_per_step"]
    assert acc["direction"] == "higher"
    assert acc["a"] == 1.0 and acc["b"] > 1.0     # the speculation claim
    ttft = by_key["serving_ttft_p50"]
    assert ttft["direction"] == "lower" and ttft["b"] <= ttft["a"]
    assert by_key["serving_shed_rate"]["gated"] is False
    # the cpu-toy honesty stamp (ISSUE 12 small fix): the committed
    # absolute numbers must be self-labelled as CLI fixtures, not the
    # serving perf trajectory
    for path in (a, b):
        with open(path) as f:
            rec = json.load(f)
        assert rec["serving_config"]["geometry"] == "cpu-toy", path
    # ...and a vanished mandatory key is a failure, not a skip
    assert tele_cli(["regress", a, b, "--max-regress", "25",
                     "--keys", "serving_accepted_tokens_per_step,"
                               "gone_key"]) == 1


def test_regress_bucketed_zero_keys_mandatory_on_committed_r15_pair(capsys):
    """ISSUE 15 satellite: the overlap-aware-ZeRO headline keys are
    MANDATORY over the committed r15 pair (A = the legacy serialized
    dp×tp step, B = the bucketed-overlap default; both cpu-toy
    self-stamped).  The gate proves the acceptance criteria on
    committed data: the flagship exposed-collective key exists and did
    not regress, the per-bucket collective wall is gated lower-is-
    better, and the loss-trajectory goldens are BITWISE equal across
    the A/B — bucketing restructured the collectives without moving
    the math."""
    a = os.path.join(REPO, "BENCH_r15_gpt.json")
    b = os.path.join(REPO, "BENCH_r15b_gpt.json")
    rc = tele_cli(["regress", a, b, "--max-regress", "25", "--json",
                   "--keys", "gpt1p3b_exposed_collective_ms,"
                             "gpt3d_bucket_collective_ms,"
                             "gpt3d_loss_first,"
                             "gpt3d_loss_final,"
                             "gpt3d_zero_allreduce_bytes"])
    rec = json.loads(capsys.readouterr().out)
    assert rc == 0, rec["failures"]
    by_key = {r["key"]: r for r in rec["rows"]}
    exp = by_key["gpt1p3b_exposed_collective_ms"]
    assert exp["direction"] == "lower" and exp["b"] <= exp["a"]
    assert by_key["gpt3d_bucket_collective_ms"]["direction"] == "lower"
    # the loss goldens are informational (no direction rule) but must
    # be BITWISE equal: the parity claim, in record form
    for k in ("gpt3d_loss_first", "gpt3d_loss_final"):
        row = by_key[k]
        assert row["gated"] is False
        assert row["a"] == row["b"], (k, row)
    # counters are reported-not-gated; assert the structural claim
    # directly on the committed records
    ka, kb = (json.load(open(p)) for p in (a, b))
    assert ka["gpt3d_bucket_count"] == 0 and kb["gpt3d_bucket_count"] > 1
    assert ka["gpt3d_zero_allreduce_count"] \
        > kb["gpt3d_zero_allreduce_count"]
    assert ka["gpt3d_zero_allreduce_bytes"] \
        > 10 * kb["gpt3d_zero_allreduce_bytes"]
    assert ka["gpt3d_zero_reduce_scatter_count"] == 1
    assert kb["gpt3d_zero_reduce_scatter_count"] \
        == kb["gpt3d_bucket_count"] == kb["gpt3d_zero_all_gather_count"]
    # cpu-toy honesty stamp (r12 discipline)
    for rec_ in (ka, kb):
        assert rec_["gpt3d_config"]["geometry"] == "cpu-toy"
    # ...and a vanished mandatory key is a failure, not a skip
    assert tele_cli(["regress", a, b, "--max-regress", "25",
                     "--keys", "gpt1p3b_exposed_collective_ms,"
                               "gone_key"]) == 1


def test_bucket_ms_direction_rule():
    """The *_bucket_*_ms family (ISSUE 15) is gated lower-is-better —
    by the explicit family rule, not only the generic _ms suffix."""
    from apex_tpu.telemetry.regress import key_direction

    assert key_direction("gpt3d_bucket_collective_ms") == "lower"
    assert key_direction("anything_bucket_rs_wall_ms") == "lower"
    # counters/echoes in the same family stay ungated
    assert key_direction("gpt3d_bucket_count") is None
    assert key_direction("gpt3d_bucket_bytes") is None


def test_regress_fleet_keys_mandatory_on_committed_r16_pair(capsys):
    """ISSUE 16 satellite: the fleet headline keys are MANDATORY over
    the committed r16 pair (A = 1 replica, B = 3 replicas; same offered
    load, virtual-time fleet clock, both cpu-toy self-stamped).  The
    gate proves the acceptance criteria on committed data: aggregate
    decode throughput scales with replicas, and the rolling restart's
    p99 TTFT holds near steady on the fleet while the single replica
    pays the stop-the-world cost."""
    a = os.path.join(REPO, "BENCH_r16_fleet.json")
    b = os.path.join(REPO, "BENCH_r16b_fleet.json")
    rc = tele_cli(["regress", a, b, "--max-regress", "25", "--json",
                   "--keys", "fleet_decode_tokens_per_sec,"
                             "fleet_ttft_p99_restart_ms,"
                             "fleet_ttft_p99_steady_ms,"
                             "fleet_dropped"])
    rec = json.loads(capsys.readouterr().out)
    assert rc == 0, rec["failures"]
    by_key = {r["key"]: r for r in rec["rows"]}
    tok = by_key["fleet_decode_tokens_per_sec"]
    assert tok["direction"] == "higher" and tok["b"] > tok["a"]
    p99 = by_key["fleet_ttft_p99_restart_ms"]
    assert p99["direction"] == "lower" and p99["b"] <= p99["a"]
    # a drop counter has no "better" direction — reported, never gated
    assert by_key["fleet_dropped"]["gated"] is False
    ka, kb = (json.load(open(p)) for p in (a, b))
    # zero silent drops and zero recompiles after warmup — on BOTH
    # committed records, the standing contracts in record form
    for rec_ in (ka, kb):
        assert rec_["fleet_dropped"] == 0
        assert rec_["fleet_recompiles_after_warmup"] == 0
        assert rec_["fleet_config"]["geometry"] == "cpu-toy"
    # rolling restart HOLDS SLO on the fleet: the restart-segment tail
    # stays within 25% of steady when peers serve through the downtime
    # windows...
    assert kb["fleet_ttft_p99_restart_ms"] \
        <= 1.25 * kb["fleet_ttft_p99_steady_ms"], (kb,)
    # ...while the fleet-of-one control pays the full stop-the-world
    # cost for the same operation (the contrast that makes the fleet
    # tier worth its complexity)
    assert ka["fleet_ttft_p99_restart_ms"] \
        > 1.25 * ka["fleet_ttft_p99_steady_ms"], (ka,)
    # the restart arc really ran: every replica fenced once, and on
    # the fleet the live requests moved to peers
    assert ka["fleet_fences"] == 1 and kb["fleet_fences"] == 3
    assert kb["fleet_migrations"] > 0
    # ...and a vanished mandatory key is a failure, not a skip
    assert tele_cli(["regress", a, b, "--max-regress", "25",
                     "--keys", "fleet_decode_tokens_per_sec,"
                               "gone_key"]) == 1


def test_fleet_key_direction_rules():
    """The fleet key families (ISSUE 16) are gated by the explicit
    family rules — TTFT tails lower-is-better, aggregate throughput
    higher — while the operational counters stay ungated (a migration
    or fence count has no universally better direction)."""
    from apex_tpu.telemetry.regress import key_direction

    assert key_direction("fleet_ttft_p99_restart_ms") == "lower"
    assert key_direction("fleet_ttft_p99_steady_ms") == "lower"
    assert key_direction("fleet_decode_tokens_per_sec") == "higher"
    assert key_direction("fleet_migrations") is None
    assert key_direction("fleet_fences") is None
    assert key_direction("fleet_dropped") is None
    assert key_direction("fleet_restart_wall_s") is None


def test_pool_peak_direction_rule():
    """r17: the pool-occupancy high-water mark is gated lower-is-better
    by the explicit *_pool_peak$ rule (no generic suffix covers a
    fraction) — the quantized-KV headline's direction, pinned by name
    from the regress.py comment."""
    from apex_tpu.telemetry.regress import key_direction

    assert key_direction("serving_pool_peak") == "lower"
    assert key_direction("fleet_pool_peak") == "lower"
    # neighbors in the same family stay ungated: a shared-page count or
    # a pool size has no universally better direction
    assert key_direction("serving_shared_pages_peak") is None
    assert key_direction("serving_pool_pages") is None


def test_prefix_hit_rate_direction_rule():
    """r17: prefix-sharing hit rate is gated higher-is-better — by the
    explicit family rule (documented-redundant with _hit_rate$), while
    shed rate stays deliberately direction-free."""
    from apex_tpu.telemetry.regress import key_direction

    assert key_direction("serving_prefix_hit_rate") == "higher"
    assert key_direction("serving_deadline_hit_rate") == "higher"
    assert key_direction("serving_shed_rate") is None


def test_regress_serving_keys_mandatory_on_committed_r17_pair(capsys):
    """r17 satellite: the serving-mode headline keys are MANDATORY over
    the committed r17 pair (A = tp=1 full-precision unshared, B = tp=2
    + int8 pool + prefix sharing; same offered load, virtual-flops
    timebase, both cpu-toy self-stamped).  The gate proves the
    acceptance criteria on committed data: decode throughput scales
    with tp, the byte-matched int8 pool cuts the occupancy peak by at
    least the claimed 40%, and the shared-prompt trace actually hits
    the prefix index."""
    a = os.path.join(REPO, "BENCH_r17_serving.json")
    b = os.path.join(REPO, "BENCH_r17b_serving.json")
    rc = tele_cli(["regress", a, b, "--max-regress", "25", "--json",
                   "--keys", "decode_tokens_per_sec,"
                             "serving_pool_peak,"
                             "serving_prefix_hit_rate"])
    rec = json.loads(capsys.readouterr().out)
    assert rc == 0, rec["failures"]
    by_key = {r["key"]: r for r in rec["rows"]}
    tok = by_key["decode_tokens_per_sec"]
    assert tok["direction"] == "higher" and tok["b"] > tok["a"]
    peak = by_key["serving_pool_peak"]
    assert peak["direction"] == "lower"
    assert peak["b"] <= 0.6 * peak["a"]        # the >= 40% claim
    hit = by_key["serving_prefix_hit_rate"]
    assert hit["direction"] == "higher"
    assert hit["a"] == 0.0 and hit["b"] > 0.0  # sharing off vs hitting
    ka, kb = (json.load(open(p)) for p in (a, b))
    for rec_ in (ka, kb):
        # geometry + timebase provenance on BOTH records: emulated CPU
        # devices share one socket, so the tp speedup is only honest
        # under the virtual-flops timebase the records self-declare
        assert rec_["serving_config"]["geometry"] == "cpu-toy"
        assert rec_["serving_config"]["timebase"] == "virtual-flops"
    assert ka["serving_config"]["tp"] == 1 and ka["serving_config"][
        "kv_quant"] is None
    assert kb["serving_config"]["tp"] == 2 and kb["serving_config"][
        "kv_quant"] == "int8"
    assert kb["serving_config"]["prefix_sharing"] is not None
    # the B side really shared pages, not just counted hits
    assert kb["serving_shared_pages_peak"] > 0
    # ...and a vanished mandatory key is a failure, not a skip
    assert tele_cli(["regress", a, b, "--max-regress", "25",
                     "--keys", "decode_tokens_per_sec,"
                               "gone_key"]) == 1


def test_regress_disagg_keys_mandatory_on_committed_r18_pair(capsys):
    """r18 satellite: the disagg headline keys are MANDATORY over the
    committed r18 pair (A = 4 colocated replicas, B = the same four
    split 2 prefill + 2 decode behind the transport seam; same offered
    load, single decode wave per segment so the comparison gates the
    SHIPPING overhead rather than halved decode slots, both cpu-toy
    self-stamped).  The gate proves the acceptance criteria on
    committed data: every request's KV pages shipped (no local-prefill
    fallback, ``fleet_ship_fallback_rate`` gated lower-is-better at
    0.0), aggregate decode throughput holds within the regress budget,
    and both arrangements drop nothing and never recompile after
    warmup — including through the rolling restart both records
    carry."""
    a = os.path.join(REPO, "BENCH_r18_fleet.json")
    b = os.path.join(REPO, "BENCH_r18b_fleet.json")
    rc = tele_cli(["regress", a, b, "--max-regress", "25", "--json",
                   "--keys", "fleet_decode_tokens_per_sec,"
                             "fleet_ship_fallback_rate,"
                             "fleet_kv_ships,"
                             "fleet_dropped"])
    rec = json.loads(capsys.readouterr().out)
    assert rc == 0, rec["failures"]
    by_key = {r["key"]: r for r in rec["rows"]}
    assert by_key["fleet_decode_tokens_per_sec"]["direction"] == "higher"
    fall = by_key["fleet_ship_fallback_rate"]
    assert fall["direction"] == "lower"
    assert fall["a"] == 0.0 and fall["b"] == 0.0
    # a shipment counter has no "better" direction — reported, not gated
    assert by_key["fleet_kv_ships"]["gated"] is False
    ka, kb = (json.load(open(p)) for p in (a, b))
    # the A side is the colocated control: nothing ships, the keys
    # still exist (the --keys list must hold on BOTH sides)
    assert ka["fleet_config"]["mode"] == "colocated"
    assert ka["fleet_kv_ships"] == 0
    # the B side shipped EVERY request exactly once — zero fallbacks
    # AND zero double-ships (idempotency in record form)
    assert kb["fleet_config"]["mode"] == "disagg"
    assert kb["fleet_config"]["prefill_replicas"] == 2
    assert kb["fleet_kv_ships"] == kb["fleet_requests"]
    assert kb["fleet_ship_fallback_rate"] == 0.0
    for rec_ in (ka, kb):
        assert rec_["fleet_dropped"] == 0
        assert rec_["fleet_recompiles_after_warmup"] == 0
        assert rec_["fleet_config"]["geometry"] == "cpu-toy"
    # ...and a vanished mandatory key is a failure, not a skip
    assert tele_cli(["regress", a, b, "--max-regress", "25",
                     "--keys", "fleet_ship_fallback_rate,"
                               "gone_key"]) == 1


def test_multichip_records_are_geometry_stamped(tmp_path):
    """ISSUE 15 satellite (the ROADMAP maintenance note's last gap):
    every committed MULTICHIP_r*.json self-declares its geometry, and
    the loader refuses an unstamped record."""
    import glob

    from apex_tpu.telemetry import load_multichip_record

    paths = sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
    assert len(paths) >= 9  # r01..r08 + r15
    for p in paths:
        rec = load_multichip_record(p)
        assert rec["geometry"], p
    # the r15 record is the consolidated-leg run, on the emulated mesh
    r15 = load_multichip_record(os.path.join(REPO, "MULTICHIP_r15.json"))
    assert r15["ok"] is True and r15["geometry"] == "cpu-toy"
    assert "legs=[gpt_3d, chaos_mesh, chaos_data, chaos_serving]" \
        in r15["tail"]
    # the r16 record adds the serving-fleet migration leg (ISSUE 16)
    r16 = load_multichip_record(os.path.join(REPO, "MULTICHIP_r16.json"))
    assert r16["ok"] is True and r16["geometry"] == "cpu-toy"
    assert "dryrun leg chaos_fleet OK" in r16["tail"]
    assert "streams=bitwise drops=0" in r16["tail"]
    # refusal controls: unstamped record, non-record file
    p = tmp_path / "unstamped.json"
    p.write_text(json.dumps({"n_devices": 8, "rc": 0, "ok": True,
                             "tail": ""}))
    with pytest.raises(ValueError, match="geometry provenance"):
        load_multichip_record(str(p))
    q = tmp_path / "notarecord.json"
    q.write_text(json.dumps({"hello": 1}))
    with pytest.raises(ValueError, match="not a MULTICHIP"):
        load_multichip_record(str(q))


def test_regress_refuses_unparsed_driver_capture(capsys):
    """The r4 record's parsed:null capture must exit 2 (usage error),
    never green — a gate comparing nothing is no gate."""
    a = os.path.join(REPO, "BENCH_r04.json")
    b = os.path.join(REPO, "BENCH_r05.json")
    assert tele_cli(["regress", a, b, "--max-regress", "10"]) == 2
    assert "parsed=None" in capsys.readouterr().err


# --------------------------------------------- live capture (slow tier)


@pytest.mark.slow
def test_live_capture_end_to_end_with_collectives(tmp_path):
    """One REAL jax.profiler capture (like PR 4's trace-backed case):
    a shard_map psum program over the emulated 8-device mesh under the
    sampler.  CPU traces may lack device lanes or collective rows, so
    the hard asserts are structural (report exists, stream validates);
    when collective rows DO appear, exposed <= total collective wall."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs the emulated multi-device mesh")
    mesh = Mesh(devs, ("data",))

    @jax.jit
    def stepfn(x):
        def f(x):
            y = jnp.tanh(x @ x.T)
            return jax.lax.psum(y, "data")

        return shard_map(f, mesh=mesh, in_specs=P("data"),
                         out_specs=P())(x)

    x = jnp.ones((len(devs) * 16, 64), jnp.float32)
    stepfn(x).block_until_ready()

    bus, mem, path = _bus(tmp_path, "live")
    s = tele.ProfileSampler(bus, window=1)
    rep = s.capture(
        lambda: float(jnp.sum(stepfn(x))), step=1)
    bus.close()
    if rep is None:
        pytest.skip(f"profiler capture unavailable: {s.last_error}")
    assert tele.validate_jsonl(path) == len(mem.events)
    profs = [e for e in mem.events if e["type"] == "profile"]
    assert len(profs) == 1
    assert rep.total_ms >= 0
    if rep.collective_ms > 0:
        assert 0 <= rep.exposed_collective_ms <= rep.collective_ms + 1e-6
