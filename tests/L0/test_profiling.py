"""Profiling subsystem: annotation scopes, timeline capture, cost reports
(reference pyprof + NVTX-range parity; SURVEY.md §5.1 TPU mapping)."""

import os

import jax
import jax.numpy as jnp
import pytest

from apex_tpu import profiling


class TestAnnotate:
    def test_annotate_outside_jit(self):
        with profiling.annotate("host_region"):
            x = jnp.ones((4,)) * 2
        assert float(x.sum()) == 8.0

    def test_annotate_inside_jit_names_ops(self):
        @jax.jit
        def f(x):
            with profiling.annotate("my_marker"):
                return x @ x

        x = jnp.ones((8, 8))
        assert float(f(x)[0, 0]) == 8.0
        # named_scope must show in the compiled HLO op metadata
        text = f.lower(x).compile().as_text()
        assert "my_marker" in text

    def test_annotated_decorator(self):
        @profiling.annotated("layer1")
        def f(x):
            return x + 1

        assert float(f(jnp.zeros(()))) == 1.0

    def test_annotated_default_name(self):
        @profiling.annotated()
        def some_fn(x):
            return x

        assert some_fn.__name__ == "some_fn"


class TestCostReport:
    def _fn(self, x, w):
        return jnp.tanh(x @ w) @ w

    def test_flops_and_bytes(self):
        x = jnp.ones((64, 64))
        rep = profiling.cost_report(self._fn, x, x)
        # 2 matmuls of 64^3 MACs = 2 * 2 * 64^3 flops (plus tanh noise)
        assert rep.flops >= 2 * 2 * 64 ** 3
        assert rep.bytes_accessed > 0
        assert rep.arithmetic_intensity > 0
        assert rep.argument_bytes == 2 * 64 * 64 * 4
        assert rep.output_bytes == 64 * 64 * 4

    def test_opcode_histogram_sees_dots(self):
        x = jnp.ones((32, 32))
        rep = profiling.cost_report(self._fn, x, x)
        assert rep.opcode_histogram, "histogram empty"
        ops = set(rep.opcode_histogram)
        assert ops & {"dot", "fusion", "dot-general", "custom-call"}, ops

    def test_accepts_prejitted(self):
        x = jnp.ones((16, 16))
        rep = profiling.cost_report(jax.jit(self._fn), x, x)
        assert rep.flops > 0

    def test_utilisation_bound(self):
        rep = profiling.CostReport(
            flops=1e12, bytes_accessed=1e6, argument_bytes=0,
            output_bytes=0, temp_bytes=0, opcode_histogram={})
        u = rep.utilisation(peak_flops=1e14, peak_bytes_per_s=1e11)
        assert u["bound"] == "compute"
        assert u["mxu_fraction_at_roofline"] == pytest.approx(1.0)

    def test_format_contains_sections(self):
        x = jnp.ones((16, 16))
        rep = profiling.cost_report(self._fn, x, x)
        s = profiling.format_cost_report(
            rep, peak_flops=1e14, peak_bytes_per_s=1e11)
        assert "flops" in s and "roofline" in s and "opcodes" in s


class TestTrace:
    @pytest.mark.slow  # profiler capture round-trip (ISSUE 2 CI satellite)
    def test_trace_writes_profile(self, tmp_path):
        logdir = str(tmp_path / "tb")
        with profiling.trace(logdir):
            x = jnp.ones((32, 32))
            float((x @ x).sum())
        found = []
        for root, _, files in os.walk(logdir):
            found += files
        assert found, "profiler produced no files"


class TestTraceReport:
    def _write_trace(self, path, events):
        import gzip, json
        with gzip.open(path, "wt") as f:
            json.dump({"traceEvents": events}, f)

    def test_parse_trace_dir_aggregates_device_events(self, tmp_path):
        d = tmp_path / "plugins" / "profile" / "run1"
        d.mkdir(parents=True)
        self._write_trace(str(d / "host.trace.json.gz"), [
            {"ph": "M", "name": "process_name", "pid": 1,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "name": "process_name", "pid": 9,
             "args": {"name": "python host"}},
            # a container span (step/module lane) wrapping the real ops:
            # must NOT double-count
            {"ph": "X", "pid": 1, "ts": 0.0, "name": "module_span",
             "dur": 2000.0},
            {"ph": "X", "pid": 1, "ts": 10.0, "name": "fusion.7",
             "dur": 300.0},
            {"ph": "X", "pid": 1, "ts": 400.0, "name": "fusion.7",
             "dur": 100.0},
            {"ph": "X", "pid": 1, "ts": 600.0, "name": "dot.3",
             "dur": 600.0},
            # bare-number step lanes are skipped by name
            {"ph": "X", "pid": 1, "ts": 0.0, "name": "7", "dur": 5000.0},
            # host event must be excluded when device pids exist
            {"ph": "X", "pid": 9, "ts": 0.0, "name": "hostwork",
             "dur": 9999.0},
        ])
        ops = profiling.parse_trace_dir(str(tmp_path))
        names = {o.name: o for o in ops}
        assert "hostwork" not in names
        assert "module_span" not in names   # container, not a leaf
        assert "7" not in names             # step lane
        assert names["dot.3"].total_ms == pytest.approx(0.6)
        assert names["fusion.7"].calls == 2
        assert names["fusion.7"].total_ms == pytest.approx(0.4)
        assert ops[0].name == "dot.3"  # sorted by time
        assert names["dot.3"].frac_of_device == pytest.approx(0.6)

    @pytest.mark.slow  # real trace capture round-trip (ISSUE 6 wall-clock)
    def test_top_ops_report_end_to_end(self, tmp_path):
        """Capture a real (CPU) trace and attribute per-op time; on
        platforms whose trace lacks device lanes the host timeline is
        used, so the table is non-empty either way — or, if this jax
        build writes no trace.json at all, the report is empty and we
        only require it not to crash."""
        w = jnp.ones((256, 256))
        f = jax.jit(lambda x: jnp.tanh(x @ w) @ w)
        x = jnp.ones((256, 256))
        float(f(x).sum())  # warm/compile outside the trace
        ops = profiling.top_ops_report(f, x, steps=2,
                                       logdir=str(tmp_path / "tb"))
        table = profiling.format_top_ops(ops)
        assert isinstance(table, str)
        for o in ops:
            assert o.total_ms >= 0 and o.calls >= 1


class TestClassifyOp:
    """ISSUE 9 tentpole: HLO-opcode -> phase classification."""

    @pytest.mark.parametrize("name,phase", [
        ("all-reduce.1", "collective"),
        ("all-gather-start.3", "collective"),
        ("all-gather-done.3", "collective"),
        ("reduce-scatter.7", "collective"),
        ("collective-permute.2", "collective"),
        ("all-to-all.4", "collective"),
        ("dot.3", "matmul"),
        ("dot-general.1", "matmul"),
        ("convolution.19", "matmul"),
        ("copy.5", "copy"),
        ("copy-start.1", "copy"),
        ("infeed.0", "infeed"),
        ("outfeed.2", "infeed"),
        ("custom-call.9", "custom"),
        ("fusion.12", "vector"),     # no HLO text: conservative
        ("add.77", "vector"),
        ("reduce.3", "vector"),
        # XLA compiler-pass rows (leaked by CPU traces without device
        # lanes) must NOT fake collective/matmul time — anchored match
        ("all-reduce-promotion", "vector"),
        ("reduce-scatter-decomposer", "vector"),
        ("all_to_all_decomposer", "vector"),
        ("dot_merger", "vector"),
        ("copy-insertion", "vector"),
    ])
    def test_prefix_rules(self, name, phase):
        from apex_tpu.profiling import classify_op

        assert classify_op(name) == phase

    def test_fusion_with_contraction_flops_classifies_matmul(self):
        """A fusion is ambiguous by name; joined with the program's HLO
        (hlo_fusion_flops) a contraction-bearing fusion becomes matmul
        while a flopless one stays vector."""
        from apex_tpu.profiling import classify_op
        from apex_tpu.profiling.trace_report import hlo_fusion_flops

        hlo = """
%fused_computation.1 (p0: f32[64,32], p1: f32[32,48]) -> f32[64,48] {
  %p0 = f32[64,32]{1,0} parameter(0)
  %p1 = f32[32,48]{1,0} parameter(1)
  ROOT %d = f32[64,48]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}
}
%fused_computation.2 (p0: f32[64]) -> f32[64] {
  %p0 = f32[64]{0} parameter(0)
  ROOT %a = f32[64]{0} add(%p0, %p0)
}
ENTRY %main (x: f32[64,32], y: f32[32,48]) -> f32[64,48] {
  %x = f32[64,32]{1,0} parameter(0)
  %y = f32[32,48]{1,0} parameter(1)
  %fusion.1 = f32[64,48]{1,0} fusion(%x, %y), kind=kOutput, calls=%fused_computation.1
  %fusion.2 = f32[64]{0} fusion(%x), kind=kLoop, calls=%fused_computation.2
}
"""
        fl = hlo_fusion_flops(hlo)
        assert classify_op("fusion.1", flops_map=fl) == "matmul"
        assert classify_op("fusion.2", flops_map=fl) == "vector"


class TestPhaseReport:
    """Synthetic Chrome-trace fixtures drive the classifier and the
    exposed-collective overlap math deterministically on CPU (ISSUE 9
    satellite: no live capture needed)."""

    def _write(self, path, events):
        import gzip, json
        with gzip.open(path, "wt") as f:
            json.dump({"traceEvents": events}, f)

    def _fixture(self, tmp_path, events):
        d = tmp_path / "plugins" / "profile" / "run1"
        d.mkdir(parents=True)
        self._write(str(d / "host.trace.json.gz"),
                    [{"ph": "M", "name": "process_name", "pid": 1,
                      "args": {"name": "/device:TPU:0"}}] + events)
        return str(tmp_path)

    def test_phases_and_exposed_overlap(self, tmp_path):
        # collective lane: [0, 1000); compute lanes cover [0, 600) and
        # [700, 800) -> exposed = 1000 - 600 - 100 = 300us = 0.3ms
        logdir = self._fixture(tmp_path, [
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 1000.0,
             "name": "all-reduce.1"},
            {"ph": "X", "pid": 1, "tid": 2, "ts": 0.0, "dur": 600.0,
             "name": "fusion.3"},
            {"ph": "X", "pid": 1, "tid": 2, "ts": 700.0, "dur": 100.0,
             "name": "dot.2"},
            {"ph": "X", "pid": 1, "tid": 3, "ts": 1200.0, "dur": 50.0,
             "name": "copy.9"},
        ])
        from apex_tpu.profiling import phase_report

        rep = phase_report(logdir)
        assert rep.phase_ms["collective"] == pytest.approx(1.0)
        assert rep.phase_ms["vector"] == pytest.approx(0.6)
        assert rep.phase_ms["matmul"] == pytest.approx(0.1)
        assert rep.phase_ms["copy"] == pytest.approx(0.05)
        assert rep.collective_ms == pytest.approx(1.0)
        assert rep.exposed_collective_ms == pytest.approx(0.3)
        assert rep.total_ms == pytest.approx(1.75)
        assert rep.span_ms == pytest.approx(1.25)  # [0, 1250)
        assert rep.n_ops == 4
        assert rep.top_ops[0].name == "all-reduce.1"

    def test_copy_does_not_hide_collectives(self, tmp_path):
        """Only compute (matmul/vector/custom) hides a collective: a
        concurrent copy/infeed leaves it exposed — D2D traffic is not
        the overlap ROADMAP item 3 is allowed to claim."""
        logdir = self._fixture(tmp_path, [
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 400.0,
             "name": "all-gather.1"},
            {"ph": "X", "pid": 1, "tid": 2, "ts": 0.0, "dur": 400.0,
             "name": "copy.1"},
        ])
        from apex_tpu.profiling import phase_report

        rep = phase_report(logdir)
        assert rep.exposed_collective_ms == pytest.approx(0.4)

    def test_overlapping_collectives_union_not_sum(self, tmp_path):
        """Two concurrent collectives on different lanes count their
        union toward exposure, never double."""
        logdir = self._fixture(tmp_path, [
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 300.0,
             "name": "all-reduce.1"},
            {"ph": "X", "pid": 1, "tid": 2, "ts": 100.0, "dur": 300.0,
             "name": "reduce-scatter.2"},
        ])
        from apex_tpu.profiling import phase_report

        rep = phase_report(logdir)
        assert rep.collective_ms == pytest.approx(0.4)   # [0, 400)
        assert rep.exposed_collective_ms == pytest.approx(0.4)
        # per-phase sum still counts both ops' durations
        assert rep.phase_ms["collective"] == pytest.approx(0.6)

    def test_fully_hidden_collective_reads_zero(self, tmp_path):
        logdir = self._fixture(tmp_path, [
            {"ph": "X", "pid": 1, "tid": 1, "ts": 100.0, "dur": 200.0,
             "name": "all-reduce.1"},
            {"ph": "X", "pid": 1, "tid": 2, "ts": 0.0, "dur": 500.0,
             "name": "fusion.1"},
        ])
        from apex_tpu.profiling import phase_report

        rep = phase_report(logdir)
        assert rep.exposed_collective_ms == 0.0
        assert rep.collective_ms == pytest.approx(0.2)

    def test_hlo_text_reclassifies_fusions(self, tmp_path):
        logdir = self._fixture(tmp_path, [
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 100.0,
             "name": "fusion.1"},
        ])
        from apex_tpu.profiling import phase_report

        hlo = """
%fused_computation.1 (p0: f32[8,8], p1: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %p1 = f32[8,8]{1,0} parameter(1)
  ROOT %d = f32[8,8]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}
}
ENTRY %main {
  %x = f32[8,8]{1,0} parameter(0)
  %fusion.1 = f32[8,8]{1,0} fusion(%x, %x), kind=kOutput, calls=%fused_computation.1
}
"""
        assert phase_report(logdir).phase_ms == {"vector": 0.1}
        rep = phase_report(logdir, hlo_text=hlo)
        assert rep.phase_ms == {"matmul": 0.1}

    def test_to_payload_is_json_ready(self, tmp_path):
        import json as _json

        logdir = self._fixture(tmp_path, [
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 10.0,
             "name": "all-reduce.1"},
        ])
        from apex_tpu.profiling import phase_report

        p = phase_report(logdir).to_payload()
        _json.dumps(p)
        assert p["exposed_collective_ms"] == pytest.approx(0.01)
        assert p["top_ops"][0]["name"] == "all-reduce.1"


class TestFlopOverrides:
    """ISSUE 9 satellite: per-op analytic flop overrides make the
    documented 5x-under-report on Pallas custom calls fixable."""

    def test_flash_attention_flops_values(self):
        from apex_tpu.profiling import flash_attention_flops

        # 2 matmuls x 2*s*s*d each per (b, h) row
        assert flash_attention_flops(128, 1024, 64) == pytest.approx(
            2 * 2 * 128 * 1024 * 1024 * 64)
        assert flash_attention_flops(128, 1024, 64, causal=True) \
            == pytest.approx(2 * 128 * 1024 * 1024 * 64)
        assert flash_attention_flops(1, 128, 64, backward=True) \
            == pytest.approx(2.5 * 4 * 128 * 128 * 64)

    def test_join_roofline_override_resolves_custom_call(self):
        from apex_tpu.profiling import OpTime, flash_attention_flops
        from apex_tpu.profiling.trace_report import join_roofline

        hlo = ('ENTRY %main {\n'
               '  %custom-call.3 = f32[128,1024,64]{2,1,0} '
               'custom-call(%q, %k, %v), '
               'custom_call_target="tpu_custom_call", '
               'metadata={op_name="jit(step)/flash_fwd" '
               'source_file="attention.py"}\n'
               '}\n')
        ops = [OpTime(name="custom-call.3", total_ms=2.0, calls=1,
                      frac_of_device=1.0)]
        fl = flash_attention_flops(128, 1024, 64)
        # without the override: the documented blind spot (flops 0)
        row0 = join_roofline(ops, hlo)[0]
        assert row0["est_gflops"] == 0.0
        row = join_roofline(ops, hlo, roof_tflops=180.0,
                            flop_overrides={"flash_fwd": fl})[0]
        assert row["flops_src"] == "override"
        assert row["est_gflops"] == pytest.approx(fl / 1e9, abs=0.01)
        assert row["achieved_tflops"] == pytest.approx(
            fl / 2e-3 / 1e12, abs=0.1)

    def test_override_never_clobbers_parsed_flops(self):
        """An op the HLO parser already attributed keeps its parsed
        flops even when an override pattern matches."""
        from apex_tpu.profiling import OpTime
        from apex_tpu.profiling.trace_report import join_roofline

        hlo = """
%fused_computation.1 (p0: f32[640,320], p1: f32[320,480]) -> f32[640,480] {
  %p0 = f32[640,320]{1,0} parameter(0)
  %p1 = f32[320,480]{1,0} parameter(1)
  ROOT %d = f32[640,480]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}
}
ENTRY %main (x: f32[640,320], y: f32[320,480]) -> f32[640,480] {
  %x = f32[640,320]{1,0} parameter(0)
  %y = f32[320,480]{1,0} parameter(1)
  %fusion.1 = f32[640,480]{1,0} fusion(%x, %y), kind=kOutput, calls=%fused_computation.1, metadata={op_name="jit(f)/dot_general"}
}
"""
        ops = [OpTime(name="fusion.1", total_ms=1.0, calls=1,
                      frac_of_device=1.0)]
        row = join_roofline(ops, hlo, flop_overrides={"fusion.1": 1e15})[0]
        assert row["est_gflops"] == pytest.approx(
            2 * 640 * 320 * 480 / 1e9, abs=0.01)
        assert "flops_src" not in row

    def test_cost_report_adds_override_flops(self):
        """cost_report(flop_overrides=...) patches XLA's cost-analysis
        blind spot: matched custom calls add analytic flops, recorded
        separately in override_flops."""
        from apex_tpu import profiling

        class FakeCompiled:
            def cost_analysis(self):
                return {"flops": 100.0, "bytes accessed": 10.0}

            def memory_analysis(self):
                return None

            def as_text(self):
                return ('ENTRY %main {\n'
                        '  %custom-call.1 = f32[8]{0} custom-call(%x), '
                        'custom_call_target="tpu_custom_call", '
                        'metadata={op_name="jit(f)/flash_fwd"}\n'
                        '  %custom-call.2 = f32[8]{0} custom-call(%y), '
                        'custom_call_target="tpu_custom_call", '
                        'metadata={op_name="jit(f)/flash_fwd"}\n'
                        '}\n')

        rep = profiling.cost_report_from_compiled(
            FakeCompiled(), flop_overrides={"flash_fwd": 1e9})
        assert rep.override_flops == pytest.approx(2e9)  # both calls
        assert rep.flops == pytest.approx(100.0 + 2e9)
        # no overrides: unchanged behavior
        rep0 = profiling.cost_report_from_compiled(FakeCompiled())
        assert rep0.flops == 100.0 and rep0.override_flops == 0.0


class TestRooflineJoin:
    """hlo_fusion_flops / join_roofline: the pyprof measured-time x
    derived-flops join (VERDICT r3 missing #2)."""

    def test_matmul_flops_exact_from_hlo(self):
        from apex_tpu.profiling.trace_report import hlo_fusion_flops

        # exact for 2-tensor contractions: [M,K]x[K,N] -> 2MNK
        hlo = """
%fused_computation.1 (p0: f32[64,32], p1: f32[32,48]) -> f32[64,48] {
  %p0 = f32[64,32]{1,0} parameter(0)
  %p1 = f32[32,48]{1,0} parameter(1)
  ROOT %d = f32[64,48]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}
}
ENTRY %main {
  %x = f32[64,32]{1,0} parameter(0)
  %y = f32[32,48]{1,0} parameter(1)
  %fusion.1 = f32[64,48]{1,0} fusion(%x, %y), kind=kOutput, calls=%fused_computation.1, metadata={op_name="jit(f)/dot_general" source_file="x.py"}
}
"""
        fl = hlo_fusion_flops(hlo)
        assert "fusion.1" in fl
        flops, nbytes, op_name = fl["fusion.1"]
        assert flops == pytest.approx(2 * 64 * 32 * 48)
        # boundary traffic: two fp32 params + fp32 result
        assert nbytes == pytest.approx((64 * 32 + 32 * 48 + 64 * 48) * 4)
        assert "dot_general" in op_name

    @pytest.mark.slow  # real-XLA compile + cost analysis (~22s); the
    # analytic join cells above pin the math in tier-1 (ISSUE 12 trim)
    def test_join_on_real_compiled_program(self):
        from apex_tpu.profiling.trace_report import (
            hlo_fusion_flops, join_roofline)
        from apex_tpu.profiling import top_ops_report

        w = jnp.ones((128, 128), jnp.float32)

        @jax.jit
        def f(x):
            return jnp.tanh(x @ w) @ w

        x = jnp.ones((128, 128))
        float(f(x).sum())
        hlo = f.lower(x).compile().as_text()
        fl = hlo_fusion_flops(hlo)
        # parser must not crash on a real program; rows join cleanly
        ops = top_ops_report(f, x, steps=2)
        rows = join_roofline(ops, hlo, roof_tflops=100.0)
        assert all("ms" in r and "est_gflops" in r for r in rows)


class TestNarrowedDegrades:
    """ISSUE 13 satellite: the broad `except Exception` degrades in
    `_opcode_histogram` / `cost_report_from_compiled` are narrowed to
    the documented backend-unsupported cases, LOGGED, and anything
    else surfaces (the `guards.global_grad_norm` incident class the
    PR 11 EX001 rule encodes)."""

    class _Stub:
        """Compiled-like stub whose as_text raises a chosen error."""

        def __init__(self, exc):
            self._exc = exc

        def as_text(self):
            raise self._exc

        def cost_analysis(self):
            return {"flops": 7.0, "bytes accessed": 3.0}

        def memory_analysis(self):
            return None

    def test_histogram_degrades_on_not_implemented_and_logs(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING, logger="apex_tpu.profiling"):
            out = profiling._opcode_histogram(
                self._Stub(NotImplementedError("no HLO text")))
        assert out == {}
        assert any("degrading to empty" in r.message
                   for r in caplog.records)

    def test_histogram_degrades_on_unimplemented_runtime_error(self):
        err = jax.errors.JaxRuntimeError("UNIMPLEMENTED: as_text")
        assert profiling._opcode_histogram(self._Stub(err)) == {}

    def test_histogram_propagates_unexpected_errors(self):
        # the regression: a real bug (here a seeded ValueError) used to
        # silently become an empty histogram
        with pytest.raises(ValueError, match="seeded"):
            profiling._opcode_histogram(self._Stub(ValueError("seeded")))
        with pytest.raises(jax.errors.JaxRuntimeError, match="INTERNAL"):
            profiling._opcode_histogram(
                self._Stub(jax.errors.JaxRuntimeError("INTERNAL: boom")))

    def test_cost_report_override_degrades_logged_not_silently(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING, logger="apex_tpu.profiling"):
            rep = profiling.cost_report_from_compiled(
                self._Stub(NotImplementedError("no HLO text")),
                flop_overrides={"flash": 1e9})
        # flops keep the cost-model value; the override contributes 0
        # and the degrade is VISIBLE in the log, never silent
        assert rep.flops == 7.0 and rep.override_flops == 0.0
        assert any("custom-call flop override" in r.message
                   for r in caplog.records)

    def test_cost_report_override_propagates_unexpected_errors(self):
        with pytest.raises(ValueError, match="seeded"):
            profiling.cost_report_from_compiled(
                self._Stub(ValueError("seeded")),
                flop_overrides={"flash": 1e9})
