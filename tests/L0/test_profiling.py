"""Profiling subsystem: annotation scopes, timeline capture, cost reports
(reference pyprof + NVTX-range parity; SURVEY.md §5.1 TPU mapping)."""

import os

import jax
import jax.numpy as jnp
import pytest

from apex_tpu import profiling


class TestAnnotate:
    def test_annotate_outside_jit(self):
        with profiling.annotate("host_region"):
            x = jnp.ones((4,)) * 2
        assert float(x.sum()) == 8.0

    def test_annotate_inside_jit_names_ops(self):
        @jax.jit
        def f(x):
            with profiling.annotate("my_marker"):
                return x @ x

        x = jnp.ones((8, 8))
        assert float(f(x)[0, 0]) == 8.0
        # named_scope must show in the compiled HLO op metadata
        text = f.lower(x).compile().as_text()
        assert "my_marker" in text

    def test_annotated_decorator(self):
        @profiling.annotated("layer1")
        def f(x):
            return x + 1

        assert float(f(jnp.zeros(()))) == 1.0

    def test_annotated_default_name(self):
        @profiling.annotated()
        def some_fn(x):
            return x

        assert some_fn.__name__ == "some_fn"


class TestCostReport:
    def _fn(self, x, w):
        return jnp.tanh(x @ w) @ w

    def test_flops_and_bytes(self):
        x = jnp.ones((64, 64))
        rep = profiling.cost_report(self._fn, x, x)
        # 2 matmuls of 64^3 MACs = 2 * 2 * 64^3 flops (plus tanh noise)
        assert rep.flops >= 2 * 2 * 64 ** 3
        assert rep.bytes_accessed > 0
        assert rep.arithmetic_intensity > 0
        assert rep.argument_bytes == 2 * 64 * 64 * 4
        assert rep.output_bytes == 64 * 64 * 4

    def test_opcode_histogram_sees_dots(self):
        x = jnp.ones((32, 32))
        rep = profiling.cost_report(self._fn, x, x)
        assert rep.opcode_histogram, "histogram empty"
        ops = set(rep.opcode_histogram)
        assert ops & {"dot", "fusion", "dot-general", "custom-call"}, ops

    def test_accepts_prejitted(self):
        x = jnp.ones((16, 16))
        rep = profiling.cost_report(jax.jit(self._fn), x, x)
        assert rep.flops > 0

    def test_utilisation_bound(self):
        rep = profiling.CostReport(
            flops=1e12, bytes_accessed=1e6, argument_bytes=0,
            output_bytes=0, temp_bytes=0, opcode_histogram={})
        u = rep.utilisation(peak_flops=1e14, peak_bytes_per_s=1e11)
        assert u["bound"] == "compute"
        assert u["mxu_fraction_at_roofline"] == pytest.approx(1.0)

    def test_format_contains_sections(self):
        x = jnp.ones((16, 16))
        rep = profiling.cost_report(self._fn, x, x)
        s = profiling.format_cost_report(
            rep, peak_flops=1e14, peak_bytes_per_s=1e11)
        assert "flops" in s and "roofline" in s and "opcodes" in s


class TestTrace:
    @pytest.mark.slow  # profiler capture round-trip (ISSUE 2 CI satellite)
    def test_trace_writes_profile(self, tmp_path):
        logdir = str(tmp_path / "tb")
        with profiling.trace(logdir):
            x = jnp.ones((32, 32))
            float((x @ x).sum())
        found = []
        for root, _, files in os.walk(logdir):
            found += files
        assert found, "profiler produced no files"


class TestTraceReport:
    def _write_trace(self, path, events):
        import gzip, json
        with gzip.open(path, "wt") as f:
            json.dump({"traceEvents": events}, f)

    def test_parse_trace_dir_aggregates_device_events(self, tmp_path):
        d = tmp_path / "plugins" / "profile" / "run1"
        d.mkdir(parents=True)
        self._write_trace(str(d / "host.trace.json.gz"), [
            {"ph": "M", "name": "process_name", "pid": 1,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "name": "process_name", "pid": 9,
             "args": {"name": "python host"}},
            # a container span (step/module lane) wrapping the real ops:
            # must NOT double-count
            {"ph": "X", "pid": 1, "ts": 0.0, "name": "module_span",
             "dur": 2000.0},
            {"ph": "X", "pid": 1, "ts": 10.0, "name": "fusion.7",
             "dur": 300.0},
            {"ph": "X", "pid": 1, "ts": 400.0, "name": "fusion.7",
             "dur": 100.0},
            {"ph": "X", "pid": 1, "ts": 600.0, "name": "dot.3",
             "dur": 600.0},
            # bare-number step lanes are skipped by name
            {"ph": "X", "pid": 1, "ts": 0.0, "name": "7", "dur": 5000.0},
            # host event must be excluded when device pids exist
            {"ph": "X", "pid": 9, "ts": 0.0, "name": "hostwork",
             "dur": 9999.0},
        ])
        ops = profiling.parse_trace_dir(str(tmp_path))
        names = {o.name: o for o in ops}
        assert "hostwork" not in names
        assert "module_span" not in names   # container, not a leaf
        assert "7" not in names             # step lane
        assert names["dot.3"].total_ms == pytest.approx(0.6)
        assert names["fusion.7"].calls == 2
        assert names["fusion.7"].total_ms == pytest.approx(0.4)
        assert ops[0].name == "dot.3"  # sorted by time
        assert names["dot.3"].frac_of_device == pytest.approx(0.6)

    @pytest.mark.slow  # real trace capture round-trip (ISSUE 6 wall-clock)
    def test_top_ops_report_end_to_end(self, tmp_path):
        """Capture a real (CPU) trace and attribute per-op time; on
        platforms whose trace lacks device lanes the host timeline is
        used, so the table is non-empty either way — or, if this jax
        build writes no trace.json at all, the report is empty and we
        only require it not to crash."""
        w = jnp.ones((256, 256))
        f = jax.jit(lambda x: jnp.tanh(x @ w) @ w)
        x = jnp.ones((256, 256))
        float(f(x).sum())  # warm/compile outside the trace
        ops = profiling.top_ops_report(f, x, steps=2,
                                       logdir=str(tmp_path / "tb"))
        table = profiling.format_top_ops(ops)
        assert isinstance(table, str)
        for o in ops:
            assert o.total_ms >= 0 and o.calls >= 1


class TestRooflineJoin:
    """hlo_fusion_flops / join_roofline: the pyprof measured-time x
    derived-flops join (VERDICT r3 missing #2)."""

    def test_matmul_flops_exact_from_hlo(self):
        from apex_tpu.profiling.trace_report import hlo_fusion_flops

        # exact for 2-tensor contractions: [M,K]x[K,N] -> 2MNK
        hlo = """
%fused_computation.1 (p0: f32[64,32], p1: f32[32,48]) -> f32[64,48] {
  %p0 = f32[64,32]{1,0} parameter(0)
  %p1 = f32[32,48]{1,0} parameter(1)
  ROOT %d = f32[64,48]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}
}
ENTRY %main {
  %x = f32[64,32]{1,0} parameter(0)
  %y = f32[32,48]{1,0} parameter(1)
  %fusion.1 = f32[64,48]{1,0} fusion(%x, %y), kind=kOutput, calls=%fused_computation.1, metadata={op_name="jit(f)/dot_general" source_file="x.py"}
}
"""
        fl = hlo_fusion_flops(hlo)
        assert "fusion.1" in fl
        flops, nbytes, op_name = fl["fusion.1"]
        assert flops == pytest.approx(2 * 64 * 32 * 48)
        # boundary traffic: two fp32 params + fp32 result
        assert nbytes == pytest.approx((64 * 32 + 32 * 48 + 64 * 48) * 4)
        assert "dot_general" in op_name

    def test_join_on_real_compiled_program(self):
        from apex_tpu.profiling.trace_report import (
            hlo_fusion_flops, join_roofline)
        from apex_tpu.profiling import top_ops_report

        w = jnp.ones((128, 128), jnp.float32)

        @jax.jit
        def f(x):
            return jnp.tanh(x @ w) @ w

        x = jnp.ones((128, 128))
        float(f(x).sum())
        hlo = f.lower(x).compile().as_text()
        fl = hlo_fusion_flops(hlo)
        # parser must not crash on a real program; rows join cleanly
        ops = top_ops_report(f, x, steps=2)
        rows = join_roofline(ops, hlo, roof_tflops=100.0)
        assert all("ms" in r and "est_gflops" in r for r in rows)
