"""Profiling subsystem: annotation scopes, timeline capture, cost reports
(reference pyprof + NVTX-range parity; SURVEY.md §5.1 TPU mapping)."""

import os

import jax
import jax.numpy as jnp
import pytest

from apex_tpu import profiling


class TestAnnotate:
    def test_annotate_outside_jit(self):
        with profiling.annotate("host_region"):
            x = jnp.ones((4,)) * 2
        assert float(x.sum()) == 8.0

    def test_annotate_inside_jit_names_ops(self):
        @jax.jit
        def f(x):
            with profiling.annotate("my_marker"):
                return x @ x

        x = jnp.ones((8, 8))
        assert float(f(x)[0, 0]) == 8.0
        # named_scope must show in the compiled HLO op metadata
        text = f.lower(x).compile().as_text()
        assert "my_marker" in text

    def test_annotated_decorator(self):
        @profiling.annotated("layer1")
        def f(x):
            return x + 1

        assert float(f(jnp.zeros(()))) == 1.0

    def test_annotated_default_name(self):
        @profiling.annotated()
        def some_fn(x):
            return x

        assert some_fn.__name__ == "some_fn"


class TestCostReport:
    def _fn(self, x, w):
        return jnp.tanh(x @ w) @ w

    def test_flops_and_bytes(self):
        x = jnp.ones((64, 64))
        rep = profiling.cost_report(self._fn, x, x)
        # 2 matmuls of 64^3 MACs = 2 * 2 * 64^3 flops (plus tanh noise)
        assert rep.flops >= 2 * 2 * 64 ** 3
        assert rep.bytes_accessed > 0
        assert rep.arithmetic_intensity > 0
        assert rep.argument_bytes == 2 * 64 * 64 * 4
        assert rep.output_bytes == 64 * 64 * 4

    def test_opcode_histogram_sees_dots(self):
        x = jnp.ones((32, 32))
        rep = profiling.cost_report(self._fn, x, x)
        assert rep.opcode_histogram, "histogram empty"
        ops = set(rep.opcode_histogram)
        assert ops & {"dot", "fusion", "dot-general", "custom-call"}, ops

    def test_accepts_prejitted(self):
        x = jnp.ones((16, 16))
        rep = profiling.cost_report(jax.jit(self._fn), x, x)
        assert rep.flops > 0

    def test_utilisation_bound(self):
        rep = profiling.CostReport(
            flops=1e12, bytes_accessed=1e6, argument_bytes=0,
            output_bytes=0, temp_bytes=0, opcode_histogram={})
        u = rep.utilisation(peak_flops=1e14, peak_bytes_per_s=1e11)
        assert u["bound"] == "compute"
        assert u["mxu_fraction_at_roofline"] == pytest.approx(1.0)

    def test_format_contains_sections(self):
        x = jnp.ones((16, 16))
        rep = profiling.cost_report(self._fn, x, x)
        s = profiling.format_cost_report(
            rep, peak_flops=1e14, peak_bytes_per_s=1e11)
        assert "flops" in s and "roofline" in s and "opcodes" in s


class TestTrace:
    def test_trace_writes_profile(self, tmp_path):
        logdir = str(tmp_path / "tb")
        with profiling.trace(logdir):
            x = jnp.ones((32, 32))
            float((x @ x).sum())
        found = []
        for root, _, files in os.walk(logdir):
            found += files
        assert found, "profiler produced no files"
