"""Bucketed-overlap ZeRO tests (ISSUE 15 tentpole).

Three claims, each pinned:

1. **Planner** — ``plan_buckets`` follows reference-DDP cap semantics
   over the canonical pack order and always produces a partition of
   the per-rank shard, for every cap including the one-bucket and
   one-param-per-bucket edges.
2. **Parity** — the bucketed flagship step's loss trajectory AND
   parameters are fp32-bitwise identical across the whole
   ``bucket_bytes`` sweep (the one-bucket edge IS the serialized
   collective tail on the new data path), and match the legacy
   serialized control (grad-through-the-boundary + monolithic
   scatter/gather) bitwise on losses — the partial-grad
   reduce-scatter sums the same summands the boundary all-reduces
   did.
3. **Layout** — bucket geometry never leaks into the optimizer-state
   layout: a state trained under one plan resumes bitwise under any
   other, and a format-4 checkpoint round-trips across topologies
   regardless of the plan on either side (the C-order reshard
   contract is plan-invariant).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import checkpoint as ckpt
from apex_tpu.contrib.optimizers import (
    DistributedFusedLAMB,
)
from apex_tpu.multi_tensor import (
    DEFAULT_BUCKET_BYTES,
    BucketPlan,
    make_schema,
    plan_buckets,
)
from apex_tpu.transformer.testing import (
    build_flagship_train_step,
    gpt1p3b_config,
)

N_DEV = 8

TOY_KW = dict(num_layers=2, hidden_size=256, num_attention_heads=2,
              vocab_size=256, max_position_embeddings=64)


def _batch(cfg, b=8, seed=1):
    k = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(k, (b, cfg.max_position_embeddings), 0,
                                cfg.vocab_size)
    return tokens, jnp.roll(tokens, -1, axis=-1)


def _run(fs, tokens, labels, steps=3):
    p, s = fs.params, fs.opt_state
    losses = []
    for _ in range(steps):
        p, s, loss = fs.step(p, s, tokens, labels)
        losses.append(float(loss))
    return p, s, losses


def _leaves32(tree):
    return [np.asarray(a, np.float32)
            for a in jax.tree_util.tree_leaves(tree)]


def _assert_trees_bitwise(a, b, what=""):
    for x, y in zip(_leaves32(a), _leaves32(b)):
        np.testing.assert_array_equal(x, y, err_msg=what)


# ------------------------------------------------------------- planner


def _toy_schema(world=8):
    tree = {"a": jnp.zeros((700,)), "b": jnp.zeros((64, 64)),
            "c": jnp.zeros((5,)), "d": jnp.zeros((3000,)),
            "e": jnp.zeros((129,))}
    return make_schema(tree, align=128, total_multiple_of=128 * world)


def test_plan_buckets_partitions_the_shard():
    schema = _toy_schema()
    for bb in (None, 1, 4096, 1 << 20, DEFAULT_BUCKET_BYTES):
        plan = plan_buckets(schema, 8, bucket_bytes=bb)
        plan.validate()  # spans partition [0, shard) in order
        assert plan.shard == schema.total // 8
        assert plan.world == 8
        assert all((hi - lo) % 1 == 0 and lo % 128 == 0
                   for lo, hi in plan.spans[:-1])
        # per-collective payload covers all ranks of the span
        assert sum(plan.collective_elements(b)
                   for b in range(plan.num_buckets)) == schema.total


def test_plan_buckets_ddp_cap_semantics():
    """Reference-DDP cap: leaves accumulate until the next leaf would
    exceed the cap; a bucket always takes at least one leaf (an
    oversized leaf becomes its own bucket)."""
    schema = _toy_schema(world=1)
    # cap of one leaf's bytes: every leaf closes a bucket -> canonical
    # boundaries at every leaf offset (world=1: spans ARE canonical)
    plan = plan_buckets(schema, 1, bucket_bytes=1)
    cut_points = {lo for lo, _ in plan.spans}
    assert cut_points == set(schema.offsets), (plan.spans, schema.offsets)
    assert plan.num_buckets == schema.num_tensors
    # a cap far above the buffer: one bucket (the serialized edge)
    plan1 = plan_buckets(schema, 1, bucket_bytes=schema.total * 4 + 1)
    assert plan1.num_buckets == 1
    assert plan1.spans == ((0, schema.total),)
    # None is the explicit serialized single-bucket plan
    plan_none = plan_buckets(schema, 1, bucket_bytes=None)
    assert plan_none.spans == plan1.spans
    assert plan_none.bucket_bytes is None


def test_plan_buckets_cap_is_monotone():
    """Shrinking the cap never produces fewer buckets."""
    schema = _toy_schema()
    prev = None
    for bb in (1 << 24, 1 << 16, 1 << 12, 1 << 8, 1):
        n = plan_buckets(schema, 8, bucket_bytes=bb).num_buckets
        if prev is not None:
            assert n >= prev, (bb, n, prev)
        prev = n
    assert prev >= 2  # the tiny cap really buckets at this geometry


def test_plan_buckets_validation():
    schema = _toy_schema()
    with pytest.raises(ValueError, match="world must be >= 1"):
        plan_buckets(schema, 0)
    with pytest.raises(ValueError, match="does not divide world"):
        plan_buckets(schema, 7)
    with pytest.raises(ValueError, match="bucket_bytes must be >= 1"):
        plan_buckets(schema, 8, bucket_bytes=0)
    with pytest.raises(ValueError, match="span_align"):
        plan_buckets(schema, 8, span_align=64)
    with pytest.raises(ValueError, match="spans must partition"):
        BucketPlan(spans=((0, 128), (256, 512)), shard=512, world=1,
                   bucket_bytes=None).validate()
    with pytest.raises(ValueError, match=r"cover \[0, 256\)"):
        BucketPlan(spans=((0, 256),), shard=512, world=1,
                   bucket_bytes=None).validate()


def test_plan_buckets_span_align_rounds_to_sublane_rows():
    """span_align=8*128 (the Pallas flat-Adam requirement) still
    partitions exactly; every interior cut is sublane-row aligned.
    The buffer must be packed to the same multiple (the FlatFusedAdam
    1024-element contract)."""
    tree = {"a": jnp.zeros((700,)), "b": jnp.zeros((64, 64)),
            "d": jnp.zeros((3000,))}
    schema = make_schema(tree, align=128, total_multiple_of=8 * 128)
    with pytest.raises(ValueError, match="not aligned"):
        plan_buckets(_toy_schema(world=1), 1, span_align=8 * 128)
    plan = plan_buckets(schema, 1, bucket_bytes=1, span_align=8 * 128)
    plan.validate()
    assert all(lo % (8 * 128) == 0 for lo, _ in plan.spans)


# ---------------------------------------------------- flagship parity


@pytest.fixture(scope="module")
def sweep_runs():
    """One 3-step trajectory per data path at the fp32 plan (grad noise
    removed, so any bucketing error shows as a bit flip): the legacy
    serialized control, the one-bucket edge, a mid cap, and the
    one-param-per-bucket edge.  Built once per module — five 8-device
    jit constructions are the dominant wall cost here."""
    cfg = gpt1p3b_config(bf16=False, **TOY_KW)
    tokens, labels = _batch(cfg)
    out = {}
    for name, bb in (("legacy", None), ("one_bucket", 1 << 30),
                     ("mid", 1 << 20), ("per_param", 1)):
        fs = build_flagship_train_step(
            cfg, plan="fp32", lr=1e-3, devices=jax.devices()[:N_DEV],
            donate=False, mesh_shape=(4, 2, 1), bucket_bytes=bb)
        p, s, losses = _run(fs, tokens, labels)
        out[name] = (p, s, losses, fs.bucket_plan)
    return out


def test_bucket_sweep_is_fp32_bitwise(sweep_runs):
    """THE parity acceptance (ISSUE 15): losses, params AND optimizer
    moments are fp32-bitwise identical across the bucket-size sweep —
    the one-bucket edge is the serialized collective tail, so
    'bucketed vs serialized' is exact, not approximate.  Elementwise
    Adam + identical per-element summation order in every
    reduce-scatter make this a strict invariant, not a tolerance."""
    ref_p, ref_s, ref_losses, ref_plan = sweep_runs["one_bucket"]
    assert ref_plan.num_buckets == 1
    for name in ("mid", "per_param"):
        p, s, losses, plan = sweep_runs[name]
        assert plan.num_buckets > 1, (name, plan)
        assert losses == ref_losses, (name, losses, ref_losses)
        _assert_trees_bitwise(p, ref_p, f"params {name} vs one_bucket")
        _assert_trees_bitwise(s, ref_s, f"opt state {name} vs one_bucket")
    # the edges really are edges
    assert sweep_runs["per_param"][3].num_buckets \
        > sweep_runs["mid"][3].num_buckets


def test_bucketed_matches_legacy_serialized_step(sweep_runs):
    """The new data path (partial grads summed IN the per-bucket
    reduce-scatters) reproduces the legacy control (per-leaf boundary
    all-reduces + monolithic scatter/gather) bitwise on the fp32 loss
    trajectory: same summands, same per-element reduction — only the
    collective *structure* changed.  Params carry reduction-order dust
    at the 1e-5 level (the boundary all-reduce and the reduce-scatter
    are different XLA reductions), bounded well under the 1e-3
    ISSUE 2 parity bar."""
    _, _, legacy_losses, _ = sweep_runs["legacy"]
    p, _, losses, _ = sweep_runs["one_bucket"]
    assert losses == legacy_losses, (losses, legacy_losses)
    legacy_p = sweep_runs["legacy"][0]
    maxdw = max(float(np.max(np.abs(a - b)))
                for a, b in zip(_leaves32(p), _leaves32(legacy_p)))
    assert maxdw <= 1e-4, maxdw


@pytest.mark.slow  # two extra 8-device bf16 constructions (~25 s)
def test_bucketed_matches_legacy_bf16_fit_bitwise():
    """At the real bf16_fit plan the 1e-5 reduction-order dust vanishes
    below bf16 resolution: params and losses match the legacy
    serialized step BITWISE (measured 0 ulp)."""
    cfg = gpt1p3b_config(**TOY_KW)
    tokens, labels = _batch(cfg)
    runs = {}
    for name, bb in (("legacy", None), ("bucketed", 1 << 20)):
        fs = build_flagship_train_step(
            cfg, plan="bf16_fit", lr=1e-3, devices=jax.devices()[:N_DEV],
            donate=False, mesh_shape=(4, 2, 1), bucket_bytes=bb)
        runs[name] = _run(fs, tokens, labels)
    assert runs["bucketed"][2] == runs["legacy"][2]
    _assert_trees_bitwise(runs["bucketed"][0], runs["legacy"][0],
                          "bf16_fit params bucketed vs legacy")


# ------------------------------------------------- layout / checkpoint


def test_bucket_plan_does_not_leak_into_state_layout():
    """Cross-plan resume, same topology: 2 steps under plan A, then the
    (params, opt_state) snapshot feeds a step built with plan B for 2
    more — bitwise equal to 4 straight steps under EITHER plan.  The
    optimizer-state stack is canonical for every plan (buckets are
    per-rank shard spans), so swapping plans mid-run is a no-op."""
    cfg = gpt1p3b_config(bf16=False, **TOY_KW)
    tokens, labels = _batch(cfg)

    def build(bb):
        return build_flagship_train_step(
            cfg, plan="fp32", lr=1e-3, devices=jax.devices()[:N_DEV],
            donate=False, mesh_shape=(4, 2, 1), bucket_bytes=bb)

    fs_a, fs_b = build(1 << 30), build(1 << 18)
    assert fs_b.bucket_plan.num_buckets > fs_a.bucket_plan.num_buckets

    control_p, control_s, control_losses = _run(fs_a, tokens, labels,
                                                steps=4)
    p, s = fs_a.params, fs_a.opt_state
    mixed_losses = []
    for step_fn in (fs_a.step, fs_a.step, fs_b.step, fs_b.step):
        p, s, loss = step_fn(p, s, tokens, labels)
        mixed_losses.append(float(loss))
    assert mixed_losses == control_losses
    _assert_trees_bitwise(p, control_p, "cross-plan params")
    _assert_trees_bitwise(s, control_s, "cross-plan opt state")


@pytest.mark.slow  # three 8-device constructions + a format-4 round trip
def test_format4_round_trip_is_bucket_plan_invariant(tmp_path):
    """THE reshard-contract satellite: a format-4 checkpoint written
    from a bucketed (4,2,1) run restores BITWISE into a (2,2,1)
    4-device target built with a different bucket plan — the on-disk
    C-order contract never sees bucket geometry — and the resumed
    trajectory matches the uninterrupted source run at <= 1 bf16
    ulp (the elastic-recovery bar)."""
    cfg = gpt1p3b_config(**TOY_KW)
    tokens, labels = _batch(cfg)

    fs_src = build_flagship_train_step(
        cfg, plan="bf16_fit", lr=1e-3, devices=jax.devices()[:N_DEV],
        donate=False, mesh_shape=(4, 2, 1), bucket_bytes=1 << 18)
    p, s = fs_src.params, fs_src.opt_state
    losses = []
    p2 = s2 = None
    for _ in range(4):
        p, s, loss = fs_src.step(p, s, tokens, labels)
        losses.append(float(loss))
        if len(losses) == 2:
            p2, s2 = p, s
            ckpt.save_checkpoint(
                str(tmp_path / "c"), (p, s), step=2,
                shardings=fs_src.shardings,
                shard_axes=fs_src.mesh_axes)

    fs_dst = build_flagship_train_step(
        cfg, plan="bf16_fit", lr=1e-3, devices=jax.devices()[:4],
        donate=False, mesh_shape=(2, 2, 1), bucket_bytes=1 << 30)
    (rp, rs), step = ckpt.restore_checkpoint(
        str(tmp_path / "c"), (fs_dst.params, fs_dst.opt_state),
        verify=True)
    assert step == 2
    # restored moments == source moments under the C-order contract:
    # concat over the (2,2,1) stack == concat over the (4,2,1) stack
    # (the world-8 schema may pad a longer all-zero tail than the
    # world-4 schema keeps — the only legal size difference)
    for got, want in ((rs.exp_avg, s2.exp_avg),
                      (rs.exp_avg_sq, s2.exp_avg_sq)):
        got = np.asarray(got, np.float32).reshape(-1)
        want = np.asarray(want, np.float32).reshape(-1)
        np.testing.assert_array_equal(got, want[:got.size])
        assert np.all(want[got.size:] == 0)
    _assert_trees_bitwise(rp, p2, "restored params")

    def ulp(a, b):
        ba = np.asarray(a, jnp.bfloat16.dtype).view(np.uint16)
        bb = np.asarray(b, jnp.bfloat16.dtype).view(np.uint16)
        return int(np.abs(ba.astype(np.int64) - bb.astype(np.int64)).max())

    for want in losses[2:]:
        rp, rs, loss = fs_dst.step(rp, rs, tokens, labels)
        assert ulp(np.float32(loss), np.float32(want)) <= 1, (
            float(loss), want)


# ------------------------------------------------ optimizer-level API


def test_flat_adam_bucketed_plan_is_bitwise():
    """FlatFusedAdam's bucketed walk (one kernel launch per span) is
    bitwise the single-launch step — the single-device twin of the
    flagship pipeline, registered with the contract checker."""
    from apex_tpu.optimizers.flat import FlatFusedAdam

    n = 8 * 1024
    opt = FlatFusedAdam(lr=1e-3, weight_decay=0.01)
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(n).astype(np.float32))
    g = jnp.asarray(rng.randn(n).astype(np.float32) * 0.1)
    state = opt.init(p)
    schema = make_schema({"w": jnp.zeros((n,))}, align=128)
    plan = plan_buckets(schema, 1, bucket_bytes=n, span_align=8 * 128)
    assert plan.num_buckets == 1  # one leaf -> DDP cap can't split it
    # a hand-built multi-span plan (the leaf-cap path can't split a
    # single giant leaf, which is exactly DDP semantics)
    plan4 = BucketPlan(spans=((0, 2048), (2048, 4096), (4096, n)),
                       shard=n, world=1, bucket_bytes=2048 * 4)
    p_ref, s_ref = opt.step(g, state, p)
    p_b, s_b = opt.step(g, state, p, plan=plan4)
    np.testing.assert_array_equal(np.asarray(p_ref), np.asarray(p_b))
    np.testing.assert_array_equal(np.asarray(s_ref.exp_avg),
                                  np.asarray(s_b.exp_avg))
    np.testing.assert_array_equal(np.asarray(s_ref.exp_avg_sq),
                                  np.asarray(s_b.exp_avg_sq))
    assert int(s_b.step) == 1


def test_flat_adam_bucketed_plan_validation():
    from apex_tpu.optimizers.flat import FlatFusedAdam

    n = 8 * 1024
    opt = FlatFusedAdam()
    p = jnp.zeros((n,), jnp.float32)
    state = opt.init(p)
    bad_world = BucketPlan(spans=((0, n // 2),), shard=n // 2, world=2,
                           bucket_bytes=None)
    with pytest.raises(ValueError, match="world=1 plan"):
        opt.step(p, state, p, plan=bad_world)
    misaligned = BucketPlan(spans=((0, 128), (128, n)), shard=n, world=1,
                            bucket_bytes=None)
    with pytest.raises(ValueError, match="sublane-row"):
        opt.step(p, state, p, plan=misaligned)


def test_lamb_refuses_bucketed_step():
    """LAMB's global grad-norm prepass cannot be honored per-bucket —
    the bucketed path must refuse loudly, not clip per-bucket."""
    opt = DistributedFusedLAMB()
    with pytest.raises(NotImplementedError, match="grad-norm prepass"):
        opt.step_buckets(None, None, None, None, None)


def test_e5m2_allgather_refuses_bucketed_step():
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    opt = DistributedFusedAdam(e5m2_allgather=True)
    with pytest.raises(NotImplementedError, match="e5m2"):
        opt.step_buckets(None, None, None, None, None)


def test_bucketed_step_records_its_plan():
    """FlagshipSetup carries the compiled plan (bench_gpt_3d echoes it
    into the record); the legacy control carries None."""
    cfg = gpt1p3b_config(bf16=False, **TOY_KW)
    fs = build_flagship_train_step(
        cfg, plan="fp32", lr=1e-3, devices=jax.devices()[:4],
        donate=False, mesh_shape=(2, 2, 1), bucket_bytes=1 << 20)
    assert fs.bucket_plan is not None
    assert fs.bucket_plan.world == 4
    fs_legacy = build_flagship_train_step(
        cfg, plan="fp32", lr=1e-3, devices=jax.devices()[:4],
        donate=False, mesh_shape=(2, 2, 1), bucket_bytes=None)
    assert fs_legacy.bucket_plan is None
    with pytest.raises(ValueError, match="single-axis"):
        build_flagship_train_step(cfg, plan="fp32", bucket_bytes=1)
