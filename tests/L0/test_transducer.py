"""Transducer joint + loss tests.

Mirrors reference tests contrib/test/transducer/test_transducer_{joint,loss}.py:
the wavefront DP + analytic fused backward are checked against a naive
per-cell implementation (the role transducer_ref.py plays in the reference),
both for values and for gradients (via AD through the naive version).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.transducer import (
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
)

B, T, U, V = 3, 6, 5, 8  # U = max y_len + 1
BLANK = 0


def _case(seed=0):
    key = jax.random.PRNGKey(seed)
    kx, kl = jax.random.split(key)
    x = jax.random.normal(kx, (B, T, U, V), jnp.float32)
    label = jax.random.randint(kl, (B, U - 1), 1, V)  # labels never blank
    f_len = jnp.asarray([T, T - 2, T - 1])
    y_len = jnp.asarray([U - 1, U - 3, U - 2])
    return x, label, f_len, y_len


def _naive_loss(x, label, f_len, y_len, blank):
    """Cell-by-cell alpha DP (the spec the reference encodes in
    transducer_ref.py), differentiable via plain AD. Python loops — tiny
    shapes only."""
    y = jax.nn.log_softmax(x, axis=-1)
    losses = []
    for b in range(x.shape[0]):
        fl, yl = int(f_len[b]), int(y_len[b])
        a = {(0, 0): jnp.asarray(0.0)}
        for t in range(1, fl):
            a[(t, 0)] = a[(t - 1, 0)] + y[b, t - 1, 0, blank]
        for u in range(1, yl + 1):
            a[(0, u)] = a[(0, u - 1)] + y[b, 0, u - 1, label[b, u - 1]]
        for t in range(1, fl):
            for u in range(1, yl + 1):
                a[(t, u)] = jnp.logaddexp(
                    a[(t - 1, u)] + y[b, t - 1, u, blank],
                    a[(t, u - 1)] + y[b, t, u - 1, label[b, u - 1]],
                )
        losses.append(-(a[(fl - 1, yl)] + y[b, fl - 1, yl, blank]))
    return jnp.stack(losses)


class TestTransducerLoss:
    def test_matches_naive(self):
        x, label, f_len, y_len = _case()
        got = transducer_loss(x, label, f_len, y_len, BLANK)
        want = _naive_loss(x, label, f_len, y_len, BLANK)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow  # wavefront-DP grad parity vs AD (ISSUE 2 CI satellite)
    def test_grad_matches_naive_ad(self):
        """The analytic fused-softmax backward (custom_vjp) equals plain AD
        through the naive DP — the check the reference does against
        transducer_ref's hand-written backward."""
        x, label, f_len, y_len = _case(1)
        w = jax.random.normal(jax.random.PRNGKey(5), (B,))  # per-seq weights

        g_fused = jax.grad(
            lambda x: jnp.sum(w * transducer_loss(x, label, f_len, y_len, BLANK))
        )(x)
        g_naive = jax.grad(
            lambda x: jnp.sum(w * _naive_loss(x, label, f_len, y_len, BLANK))
        )(x)
        np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_naive),
                                   rtol=1e-4, atol=1e-5)

    def test_jits_and_bf16(self):
        x, label, f_len, y_len = _case(2)
        f = jax.jit(lambda x: transducer_loss(x, label, f_len, y_len, BLANK))
        out = f(x.astype(jnp.bfloat16))
        assert jnp.all(jnp.isfinite(out))
        g = jax.jit(jax.grad(lambda x: jnp.sum(
            transducer_loss(x, label, f_len, y_len, BLANK))))(x.astype(jnp.bfloat16))
        assert g.dtype == jnp.bfloat16
        assert jnp.all(jnp.isfinite(g.astype(jnp.float32)))

    def test_debug_alpha_beta_consistency(self):
        """alpha[b,t,u] + beta[b,t,u] marginalises to the total path mass:
        at (0,0), beta[0,0] = -loss (reference debug_list contract,
        transducer.py:113-116,142-144)."""
        x, label, f_len, y_len = _case(3)
        dbg = []
        loss_mod = TransducerLoss()
        loss = loss_mod(x, label, f_len, y_len, BLANK, debug_list=dbg)
        alpha, beta = dbg
        np.testing.assert_allclose(np.asarray(-beta[:, 0, 0]), np.asarray(loss),
                                   rtol=1e-6)
        # total mass is the same viewed from either end
        term = alpha[jnp.arange(B), f_len - 1, y_len] + jax.nn.log_softmax(
            x, -1)[jnp.arange(B), f_len - 1, y_len, BLANK]
        np.testing.assert_allclose(np.asarray(term), np.asarray(beta[:, 0, 0]),
                                   rtol=1e-5)

    def test_packed_input_matches_dense(self):
        x, label, f_len, y_len = _case(4)
        g_len = y_len + 1
        batch_offset = jnp.cumsum(f_len * g_len)
        packed_n = int(batch_offset[-1])
        # pack x the way a packed joint would produce it
        valid = (jnp.arange(T)[None, :, None] < f_len[:, None, None]) & (
            jnp.arange(U)[None, None, :] < g_len[:, None, None])
        from apex_tpu.contrib.transducer.transducer import _pack
        x_packed = _pack(x, f_len, g_len, batch_offset, packed_n, valid)

        dense = transducer_loss(x, label, f_len, y_len, BLANK)
        packed = transducer_loss(
            x_packed, label, f_len, y_len, BLANK,
            packed_input=True, batch_offset=batch_offset, max_f_len=T)
        np.testing.assert_allclose(np.asarray(packed), np.asarray(dense),
                                   rtol=1e-5, atol=1e-5)


class TestTransducerJoint:
    def _fg(self, seed=0):
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        H = 16
        f = jax.random.normal(k1, (B, T, H))
        g = jax.random.normal(k2, (B, U, H))
        f_len = jnp.asarray([T, T - 2, T - 1])
        g_len = jnp.asarray([U, U - 2, U - 1])
        return f, g, f_len, g_len

    def test_matches_broadcast_add(self):
        f, g, f_len, g_len = self._fg()
        h = transducer_joint(f, g, f_len, g_len)
        want = f[:, :, None, :] + g[:, None, :, :]
        for b in range(B):
            np.testing.assert_allclose(
                np.asarray(h[b, : f_len[b], : g_len[b]]),
                np.asarray(want[b, : f_len[b], : g_len[b]]), rtol=1e-6)
        # don't-care region is zeroed (reference leaves it unwritten)
        assert float(jnp.abs(h[1, f_len[1]:]).max()) == 0.0

    def test_relu_and_grads(self):
        f, g, f_len, g_len = self._fg(1)
        def total(f, g):
            return jnp.sum(transducer_joint(f, g, f_len, g_len, relu=True))
        h = transducer_joint(f, g, f_len, g_len, relu=True)
        assert float(h.min()) >= 0.0
        df, dg = jax.grad(total, argnums=(0, 1))(f, g)
        assert df.shape == f.shape and dg.shape == g.shape
        # grads only flow from valid cells
        assert float(jnp.abs(df[1, f_len[1]:]).max()) == 0.0

    def test_pack_output_matches_dense(self):
        f, g, f_len, g_len = self._fg(2)
        batch_offset = jnp.cumsum(f_len * g_len)
        packed_n = int(batch_offset[-1])
        joint = TransducerJoint(pack_output=True)
        hp = joint(f, g, f_len, g_len, batch_offset=batch_offset,
                   packed_batch=packed_n)
        assert hp.shape == (packed_n, f.shape[-1])
        dense = transducer_joint(f, g, f_len, g_len)
        # batch 1 cells live at offset batch_offset[0]
        row = int(batch_offset[0])
        np.testing.assert_allclose(np.asarray(hp[row]), np.asarray(dense[1, 0, 0]),
                                   rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(hp[row + int(g_len[1])]), np.asarray(dense[1, 1, 0]), rtol=1e-6)

    def test_dropout(self):
        f, g, f_len, g_len = self._fg(3)
        joint = TransducerJoint(dropout=True, dropout_prob=0.5)
        h = joint(f, g, f_len, g_len, dropout_key=jax.random.PRNGKey(0))
        dense = transducer_joint(f, g, f_len, g_len)
        kept = h != 0
        # kept entries are scaled by 1/(1-p)
        np.testing.assert_allclose(
            np.asarray(h[kept]), np.asarray((dense * 2.0)[kept]), rtol=1e-5)
        frac = float(jnp.mean(kept[0, : f_len[0], : g_len[0]].astype(jnp.float32)))
        assert 0.35 < frac < 0.65
        # eval mode: no dropout
        h_eval = joint(f, g, f_len, g_len, training=False)
        np.testing.assert_allclose(np.asarray(h_eval), np.asarray(dense), rtol=1e-6)
