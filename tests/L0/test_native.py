"""Native host runtime: g++-built pack/unpack + threaded record loader
(reference apex_C flatten/unflatten + the DALI data-backend role)."""

import os

import numpy as np
import pytest

from apex_tpu import _native
from apex_tpu.data import NativeRecordLoader, native_available, write_records

needs_native = pytest.mark.skipif(
    not native_available(),
    reason=f"native toolchain unavailable: {_native.build_error()}")


class TestPackUnpack:
    def _arrays(self):
        return [
            np.arange(100, dtype=np.float32),
            np.random.default_rng(0).normal(size=(7, 9)).astype(np.float64),
            np.arange(13, dtype=np.int32),
            np.zeros((2, 2, 2), np.uint8),
        ]

    def _offsets(self, arrays, align=128):
        offs, off = [], 0
        for a in arrays:
            offs.append(off)
            off += (a.nbytes + align - 1) // align * align
        return offs, off

    @needs_native
    def test_roundtrip_native(self):
        arrays = self._arrays()
        offs, total = self._offsets(arrays)
        buf = _native.pack_host(arrays, offs, total)
        outs = [np.empty_like(a) for a in arrays]
        _native.unpack_host(buf, outs, offs)
        for a, b in zip(arrays, outs):
            np.testing.assert_array_equal(a, b)

    def test_roundtrip_numpy_fallback(self, monkeypatch):
        monkeypatch.setattr(_native, "get_lib", lambda: None)
        arrays = self._arrays()
        offs, total = self._offsets(arrays)
        buf = _native.pack_host(arrays, offs, total)
        outs = [np.empty_like(a) for a in arrays]
        _native.unpack_host(buf, outs, offs)
        for a, b in zip(arrays, outs):
            np.testing.assert_array_equal(a, b)

    @needs_native
    def test_native_matches_fallback(self, monkeypatch):
        arrays = self._arrays()
        offs, total = self._offsets(arrays)
        native = _native.pack_host(arrays, offs, total)
        monkeypatch.setattr(_native, "get_lib", lambda: None)
        fallback = _native.pack_host(arrays, offs, total)
        np.testing.assert_array_equal(native, fallback)


@needs_native
class TestNativeRecordLoader:
    RB = 24

    def _write(self, tmp_path, n_a=32, n_b=16):
        a = (np.arange(n_a * self.RB, dtype=np.int64) % 251).astype(
            np.uint8).reshape(n_a, self.RB)
        b = ((np.arange(n_b * self.RB, dtype=np.int64) + 7) % 251).astype(
            np.uint8).reshape(n_b, self.RB)
        pa, pb = str(tmp_path / "a.bin"), str(tmp_path / "b.bin")
        write_records(pa, a)
        write_records(pb, b)
        return [pa, pb], np.concatenate([a, b])

    def test_shuffled_epoch_covers_every_record_once(self, tmp_path):
        paths, recs = self._write(tmp_path)
        with NativeRecordLoader(paths, self.RB, 8, shuffle=True,
                                seed=1, num_threads=3) as ld:
            assert ld.num_records == len(recs)
            seen = []
            for _ in range(ld.batches_per_epoch):
                batch = ld.next_batch()
                assert batch.shape == (8, self.RB)
                seen += [bytes(r.tobytes()) for r in batch]
        expect = {bytes(r.tobytes()) for r in recs}
        assert len(seen) == len(recs)
        assert set(seen) == expect

    def test_epochs_reshuffle_deterministically(self, tmp_path):
        paths, recs = self._write(tmp_path)

        def epochs(n):
            with NativeRecordLoader(paths, self.RB, 8, shuffle=True,
                                    seed=9) as ld:
                return [bytes(ld.next_batch().tobytes())
                        for _ in range(n * ld.batches_per_epoch)]

        assert epochs(2) == epochs(2)  # same seed -> same stream
        one = epochs(2)
        half = len(one) // 2
        assert one[:half] != one[half:]  # epoch 2 differs from epoch 1

    def test_sequential_preserves_order(self, tmp_path):
        paths, recs = self._write(tmp_path)
        with NativeRecordLoader(paths, self.RB, 8, shuffle=False) as ld:
            got = np.concatenate(
                [ld.next_batch() for _ in range(ld.batches_per_epoch)])
        np.testing.assert_array_equal(got, recs[:len(got)])

    def test_decode_hook(self, tmp_path):
        paths, _ = self._write(tmp_path)
        ld = NativeRecordLoader(
            paths, self.RB, 4, shuffle=False,
            decode=lambda b: (b[:, :-4],
                              b[:, -4:].copy().view(np.int32).ravel()))
        x, y = ld.next_batch()
        assert x.shape == (4, self.RB - 4) and y.shape == (4,)
        ld.close()

    def test_too_small_dataset_raises(self, tmp_path):
        p = str(tmp_path / "tiny.bin")
        write_records(p, np.zeros((2, self.RB), np.uint8))
        with pytest.raises(RuntimeError):
            NativeRecordLoader([p], self.RB, 8)

    def test_truncated_file_surfaces_error_count(self, tmp_path):
        """IO failures must not be silent: a file whose tail is truncated
        mid-record yields zero-filled records AND a nonzero error_count
        (ADVICE r2: silent zero-fill was training-data corruption)."""
        n = 16
        recs = np.full((n, self.RB), 7, np.uint8)
        p = str(tmp_path / "t.bin")
        write_records(p, recs)
        with NativeRecordLoader([p], self.RB, 4, shuffle=False,
                                num_threads=1, queue_depth=1) as ld:
            assert ld.error_count == 0
            ld.next_batch()
            # truncate the file mid-way: later records now fail to read
            with open(p, "r+b") as f:
                f.truncate(self.RB * 6 + 3)
            bad = 0
            for _ in range(ld.batches_per_epoch - 1):
                b = ld.next_batch()
                bad += int((b == 0).all(axis=1).sum())
            assert ld.error_count > 0
            assert ld.error_count >= bad > 0
