"""Expert-parallel Switch MLP tests (apex_tpu/transformer/moe.py).

Properties: (1) with ample capacity the routed output equals the dense
per-token reference exactly; (2) expert-parallel execution over an
"expert" mesh axis matches single-device execution; (3) capacity
overflow drops tokens to zero (residual path) instead of corrupting
others; (4) gradients flow to gate and experts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.moe import MoEConfig, SwitchMLP

H, F, E = 16, 32, 4


def _cfg(capacity_factor=8.0):
    return MoEConfig(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                     capacity_factor=capacity_factor)


def _dense_ref(params, h):
    """Per-token dense evaluation of the routed computation."""
    logits = h.astype(jnp.float32) @ params["gate"]["weight"]
    probs = jax.nn.softmax(logits, -1)
    eid = jnp.argmax(probs, -1)
    gw = jnp.max(probs, -1)
    ex = params["experts"]
    outs = []
    for t in range(h.shape[0]):
        e = int(eid[t])
        inter = jax.nn.gelu(
            h[t].astype(jnp.float32) @ ex["w1"][e] + ex["b1"][e],
            approximate=True)
        outs.append((inter @ ex["w2"][e] + ex["b2"][e]) * gw[t])
    return jnp.stack(outs).astype(h.dtype)


class TestSwitchMLP:
    def test_matches_dense_reference(self):
        moe = SwitchMLP(_cfg())
        params = moe.init_master(jax.random.PRNGKey(0))
        h = jax.random.normal(jax.random.PRNGKey(1), (24, H))
        out, aux = moe.apply(params, h)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_dense_ref(params, h)),
                                   rtol=1e-5, atol=1e-5)
        assert float(aux) > 0  # balanced would be ~1.0

    @pytest.mark.slow  # 8-device expert-parallel parity (ISSUE 2 CI satellite)
    def test_expert_parallel_matches_single_device(self):
        WORLD = 4
        moe = SwitchMLP(_cfg())
        master = moe.init_master(jax.random.PRNGKey(0))
        h = jax.random.normal(jax.random.PRNGKey(1), (WORLD * 8, H))
        ref, _ = moe.apply(master, h)

        mesh = Mesh(np.array(jax.devices()[:WORLD]), ("expert",))
        shards = [moe.shard_master(master, r, WORLD) for r in range(WORLD)]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)

        def run(p, ht):
            p = jax.tree_util.tree_map(lambda a: a[0], p)
            out, aux = moe.apply(p, ht, axis_name="expert")
            return out, aux

        out, aux = shard_map(
            run, mesh=mesh,
            in_specs=(P("expert"), P("expert")),
            out_specs=(P("expert"), P()), check_rep=False)(stacked, h)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_capacity_overflow_drops_not_corrupts(self):
        # capacity 1: at most one token per expert survives; the rest are
        # exactly zero (residual carries them)
        moe = SwitchMLP(_cfg(capacity_factor=E / 24.0))  # C=1 for T=24
        params = moe.init_master(jax.random.PRNGKey(0))
        h = jax.random.normal(jax.random.PRNGKey(1), (24, H))
        assert moe.capacity(24) == 1
        out, _ = moe.apply(params, h)
        dense = _dense_ref(params, h)
        kept = ~np.all(np.asarray(out) == 0, axis=-1)
        assert kept.sum() <= E
        np.testing.assert_allclose(np.asarray(out)[kept],
                                   np.asarray(dense)[kept],
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_flow(self):
        moe = SwitchMLP(_cfg())
        params = moe.init_master(jax.random.PRNGKey(0))
        h = jax.random.normal(jax.random.PRNGKey(1), (16, H))

        def loss(p):
            out, aux = moe.apply(p, h)
            return jnp.sum(out ** 2) + 0.01 * aux

        g = jax.grad(loss)(params)
        for name in ("w1", "w2"):
            assert float(jnp.abs(g["experts"][name]).max()) > 0
        assert float(jnp.abs(g["gate"]["weight"]).max()) > 0

    @pytest.mark.slow  # 8-device aux-loss parity (ISSUE 2 CI satellite)
    def test_aux_loss_identical_across_expert_ranks(self):
        """The load-balancing aux loss must be the SAME on every expert
        rank (the gate is replicated; a rank-local aux term would desync
        the replicas' gate gradients)."""
        WORLD = 4
        moe = SwitchMLP(_cfg())
        master = moe.init_master(jax.random.PRNGKey(0))
        h = jax.random.normal(jax.random.PRNGKey(3), (WORLD * 8, H))
        mesh = Mesh(np.array(jax.devices()[:WORLD]), ("expert",))
        shards = [moe.shard_master(master, r, WORLD) for r in range(WORLD)]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)

        def run(p, ht):
            p = jax.tree_util.tree_map(lambda a: a[0], p)
            _, aux = moe.apply(p, ht, axis_name="expert")
            return aux[None]

        auxes = shard_map(run, mesh=mesh,
                          in_specs=(P("expert"), P("expert")),
                          out_specs=P("expert"), check_rep=False)(
            stacked, h)
        np.testing.assert_allclose(np.asarray(auxes),
                                   np.asarray(auxes)[0], rtol=1e-6)
        # and equals the single-device aux on the full batch
        _, ref_aux = moe.apply(master, h)
        np.testing.assert_allclose(float(auxes[0]), float(ref_aux),
                                   rtol=1e-5)
