"""Resilience subsystem tests: async checkpointing, preemption, divergence
guards, and integrity verification — all exercised deterministically on CPU
via the chaos fault-injection harness (apex_tpu.resilience.chaos).

The reference has nothing to match here (its fault story is per-rank
torch.save, SURVEY §5.4); these tests define the contract of the hardening
layer instead: a training run survives simulated preemption and resumes
bit-identically, a corrupted latest checkpoint falls back to the previous
intact one, and async saves overlap the step loop with fence-on-next-save
semantics.
"""

import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu import checkpoint as ckpt
from apex_tpu import resilience as res
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import chaos
from apex_tpu.transformer.testing import run_resilient_training

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------- helpers


def _toy_state():
    k = jax.random.PRNGKey(0)
    params = {"dense": {"w": jax.random.normal(k, (4, 4), jnp.float32),
                        "b": jnp.zeros((4,), jnp.float32)}}
    opt = FusedAdam(lr=1e-2)
    scaler = amp.initialize("O2").scaler
    state = ckpt.TrainState.create(params, opt.init(params), scaler.init())
    return state, opt, scaler


def _make_step_fn(opt, scaler):
    @jax.jit
    def train_step(state, xy):
        x, y = xy
        def loss(p):
            pred = x @ p["dense"]["w"] + p["dense"]["b"]
            return scaler.scale(jnp.mean((pred - y) ** 2), state.scaler_state)

        grads = jax.grad(loss)(state.params)
        grads, finite = scaler.unscale(grads, state.scaler_state)
        new_p, new_o = opt.step_if_finite(grads, state.opt_state,
                                          state.params, finite)
        return state.replace(
            step=state.step + 1, params=new_p, opt_state=new_o,
            scaler_state=scaler.update(state.scaler_state, finite)), finite

    return lambda s, b: train_step(s, b)


def _batches(n, key=jax.random.PRNGKey(3)):
    out = []
    for i in range(n):
        k = jax.random.fold_in(key, i)
        out.append((jax.random.normal(k, (8, 4), jnp.float32),
                    jax.random.normal(jax.random.fold_in(k, 1), (8, 4),
                                      jnp.float32)))
    return out


def _assert_trees_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ----------------------------------------------------- async checkpointing


def test_async_save_overlaps_training(chaos_ckpt_dir):
    """The acceptance case: with a slow injected writer in flight, the step
    loop keeps advancing; the fence then blocks until the write lands and
    the checkpoint restores intact."""
    state, opt, scaler = _toy_state()
    step_fn = _make_step_fn(opt, scaler)
    batches = _batches(4)
    # warm the jit cache so steps during the write are fast
    state2, _ = step_fn(state, batches[0])

    with chaos.slow_writer(0.5):
        ckpt.save_checkpoint(str(chaos_ckpt_dir), state2, step=1,
                             blocking=False)
        assert res.in_flight()
        steps_while_writing = 0
        s = state2
        for b in batches[1:]:
            s, _ = step_fn(s, b)
            if res.in_flight():
                steps_while_writing += 1
        # the loop made progress while the writer slept
        assert steps_while_writing > 0
        res.wait_for_save()  # the fence
    assert not res.in_flight()
    assert ckpt.latest_step(str(chaos_ckpt_dir)) == 1
    restored, _ = ckpt.restore_checkpoint(str(chaos_ckpt_dir), target=state2,
                                          verify=True)
    _assert_trees_equal(state2, restored)


def test_next_save_fences_on_in_flight_write(chaos_ckpt_dir):
    """A second save — async or blocking — must wait for the first write to
    complete (at most one write in flight)."""
    tree = {"w": jnp.arange(16, dtype=jnp.float32)}
    t0 = time.perf_counter()
    with chaos.slow_writer(0.4):
        ckpt.save_checkpoint(str(chaos_ckpt_dir), tree, step=1,
                             blocking=False)
        # this save fences on step 1's slow write AND is itself slow
        ckpt.save_checkpoint(str(chaos_ckpt_dir), tree, step=2)
    elapsed = time.perf_counter() - t0
    assert elapsed >= 0.8  # both writes serialized, neither skipped
    assert ckpt.latest_step(str(chaos_ckpt_dir)) == 2
    assert ckpt.verify_checkpoint(str(chaos_ckpt_dir), 1) == 1
    assert ckpt.verify_checkpoint(str(chaos_ckpt_dir), 2) == 2


def test_async_write_failure_surfaces_at_fence(chaos_ckpt_dir):
    """A background write that exhausts its retries parks the error; the
    next fence raises it (never silently dropped)."""
    tree = {"w": jnp.zeros((4,))}
    with chaos.FaultyStore(fail_events=("write_arrays",), fail_times=None):
        ckpt.save_checkpoint(
            str(chaos_ckpt_dir), tree, step=1, blocking=False,
            retry=ckpt.RetryPolicy(max_attempts=2, base_delay=0.01))
        with pytest.raises(res.AsyncSaveError) as ei:
            res.wait_for_save()
    assert "injected fault" in str(ei.value.__cause__)
    # the error was consumed: the writer is reusable afterwards
    ckpt.save_checkpoint(str(chaos_ckpt_dir), tree, step=2, blocking=False)
    res.wait_for_save()
    assert ckpt.latest_step(str(chaos_ckpt_dir)) == 2


def test_retry_recovers_from_transient_write_errors(chaos_ckpt_dir):
    """First two attempts hit injected storage errors; the third lands.
    No partial state survives (each attempt rewrites the tmp dir)."""
    tree = {"w": jnp.arange(8, dtype=jnp.float32)}
    with chaos.FaultyStore(fail_events=("write_arrays",),
                           fail_times=2) as store:
        ckpt.save_checkpoint(
            str(chaos_ckpt_dir), tree, step=3,
            retry=ckpt.RetryPolicy(max_attempts=3, base_delay=0.01))
    assert store.failures_injected == 2
    assert store.calls["write_arrays"] == 3
    assert ckpt.verify_checkpoint(str(chaos_ckpt_dir), 3) == 3
    leftovers = [n for n in os.listdir(chaos_ckpt_dir) if n.endswith(".tmp")]
    assert leftovers == []


def test_retry_exhaustion_raises_and_leaves_no_partial(chaos_ckpt_dir):
    tree = {"w": jnp.zeros((2,))}
    with chaos.FaultyStore(fail_events=("commit",), fail_times=None):
        with pytest.raises(OSError):
            ckpt.save_checkpoint(
                str(chaos_ckpt_dir), tree, step=1,
                retry=ckpt.RetryPolicy(max_attempts=2, base_delay=0.01))
    assert ckpt.latest_step(str(chaos_ckpt_dir)) is None


# ------------------------------------------------------ integrity / verify


def test_crc32_digests_recorded_per_leaf(chaos_ckpt_dir):
    import json
    import zlib

    tree = {"w": jnp.arange(6, dtype=jnp.float32),
            "h": jnp.ones((3,), jnp.bfloat16)}
    ckpt.save_checkpoint(str(chaos_ckpt_dir), tree, step=0)
    with open(os.path.join(ckpt.step_dir(str(chaos_ckpt_dir), 0),
                           "manifest.json")) as f:
        man = json.load(f)
    assert all("crc32" in e for e in man["leaves"].values())
    # the digest is over the bytes as STORED (bf16 leaf stored fp32)
    want = zlib.crc32(
        np.asarray(tree["h"], dtype=np.float32).tobytes()) & 0xFFFFFFFF
    assert man["leaves"]["['h']"]["crc32"] == want


def test_verify_detects_flipped_byte_npz(chaos_ckpt_dir):
    ckpt.save_checkpoint(str(chaos_ckpt_dir),
                         {"w": jnp.arange(64, dtype=jnp.float32)}, step=1)
    assert ckpt.verify_checkpoint(str(chaos_ckpt_dir)) == 1
    chaos.corrupt_arrays(str(chaos_ckpt_dir), 1, mode="flip")
    with pytest.raises(ckpt.CheckpointCorruptionError):
        ckpt.verify_checkpoint(str(chaos_ckpt_dir))


def test_verify_detects_exact_leaf_in_packed(chaos_ckpt_dir):
    """Packed superblock has no zip CRC safety net — our per-leaf digest is
    the only integrity check, and it names the damaged leaf."""
    tree = {"a": jnp.arange(32, dtype=jnp.float32),
            "b": jnp.ones((32,), jnp.float32)}
    ckpt.save_checkpoint(str(chaos_ckpt_dir), tree, step=2, packed=True)
    chaos.flip_packed_leaf_byte(str(chaos_ckpt_dir), 2, "['b']")
    with pytest.raises(ckpt.CheckpointCorruptionError) as ei:
        ckpt.verify_checkpoint(str(chaos_ckpt_dir), 2)
    assert "['b']" in str(ei.value) and "['a']" not in str(ei.value)


def test_verify_detects_truncation(chaos_ckpt_dir):
    ckpt.save_checkpoint(str(chaos_ckpt_dir),
                         {"w": jnp.arange(256, dtype=jnp.float32)}, step=1,
                         packed=True)
    chaos.corrupt_arrays(str(chaos_ckpt_dir), 1, mode="truncate")
    with pytest.raises(ckpt.CheckpointCorruptionError):
        ckpt.verify_checkpoint(str(chaos_ckpt_dir), 1)


def test_restore_falls_back_to_newest_intact(chaos_ckpt_dir):
    """Acceptance case: steps N<M on disk, M's arrays corrupted — restore
    lands on N and reports the corruption via CheckpointFallbackWarning."""
    tree = {"w": jnp.arange(16, dtype=jnp.float32)}
    ckpt.save_checkpoint(str(chaos_ckpt_dir),
                         jax.tree_util.tree_map(lambda x: x * 2, tree),
                         step=5)
    ckpt.save_checkpoint(str(chaos_ckpt_dir),
                         jax.tree_util.tree_map(lambda x: x * 3, tree),
                         step=9)
    chaos.corrupt_arrays(str(chaos_ckpt_dir), 9, mode="flip")
    with pytest.warns(res.CheckpointFallbackWarning) as record:
        restored, step = res.restore_resilient(str(chaos_ckpt_dir),
                                               target=tree)
    assert any("step 9" in str(w.message) for w in record)
    assert step == 5
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(16, dtype=np.float32) * 2)


def test_restore_resilient_all_corrupt_raises(chaos_ckpt_dir):
    tree = {"w": jnp.arange(8, dtype=jnp.float32)}
    for s in (1, 2):
        ckpt.save_checkpoint(str(chaos_ckpt_dir), tree, step=s)
        chaos.corrupt_arrays(str(chaos_ckpt_dir), s, mode="flip")
    with pytest.warns(res.CheckpointFallbackWarning):
        with pytest.raises(ckpt.CheckpointCorruptionError,
                           match="no intact checkpoint"):
            res.restore_resilient(str(chaos_ckpt_dir), target=tree)


def test_restore_resilient_structure_mismatch_is_not_corruption(
        chaos_ckpt_dir):
    """A target/checkpoint structure mismatch must raise immediately (every
    older checkpoint would fail identically), not walk the history."""
    tree = {"w": jnp.zeros((2,))}
    ckpt.save_checkpoint(str(chaos_ckpt_dir), tree, step=1)
    ckpt.save_checkpoint(str(chaos_ckpt_dir), tree, step=2)
    with pytest.raises(KeyError, match="missing 1 leaves"):
        res.restore_resilient(str(chaos_ckpt_dir),
                              target={"w": jnp.zeros((2,)),
                                      "extra": jnp.zeros((2,))})


def test_restore_resilient_honors_rollback_recency(chaos_ckpt_dir):
    """A rollback-resume writes a LOWER step more recently than a higher
    one still on disk; the resilient walk must start from the marker/most
    recent write, not resurrect the rolled-back higher step."""
    tree10 = {"w": jnp.ones((4,)) * 10}
    tree8 = {"w": jnp.ones((4,)) * 8}
    ckpt.save_checkpoint(str(chaos_ckpt_dir), tree10, step=10)
    ckpt.save_checkpoint(str(chaos_ckpt_dir), tree8, step=8)  # rollback
    assert ckpt.latest_step(str(chaos_ckpt_dir)) == 8
    restored, step = res.restore_resilient(str(chaos_ckpt_dir),
                                           target={"w": jnp.zeros((4,))})
    assert step == 8
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones(4) * 8)


def test_injected_read_fault_triggers_fallback(chaos_ckpt_dir):
    """A read-side storage fault on the newest checkpoint counts as
    corruption under verification and falls back like damaged bytes do."""
    tree = {"w": jnp.ones((4,))}
    ckpt.save_checkpoint(str(chaos_ckpt_dir), tree, step=1)
    ckpt.save_checkpoint(str(chaos_ckpt_dir), tree, step=2)
    with chaos.FaultyStore(fail_events=("read_arrays",), fail_times=1):
        with pytest.warns(res.CheckpointFallbackWarning):
            _, step = res.restore_resilient(str(chaos_ckpt_dir), target=tree)
    assert step == 1


def test_legacy_two_leaf_scaler_state_round_trips(chaos_ckpt_dir):
    """A checkpoint written before LossScaleState.skipped existed (2-leaf
    scaler state) restores into a skipped=None target, and update() keeps
    the legacy treedef stable instead of growing a third leaf mid-train."""
    from apex_tpu.amp.scaler import LossScaleState, LossScaler

    legacy = LossScaleState(loss_scale=jnp.asarray(128.0, jnp.float32),
                            unskipped=jnp.asarray(5, jnp.int32))
    assert len(jax.tree_util.tree_leaves(legacy)) == 2
    ckpt.save_checkpoint(str(chaos_ckpt_dir), {"scaler": legacy}, step=1)
    back, _ = ckpt.restore_checkpoint(str(chaos_ckpt_dir),
                                      target={"scaler": legacy}, verify=True)
    assert float(back["scaler"].loss_scale) == 128.0

    s = LossScaler.dynamic_scaler()
    stepped = s.update(back["scaler"], jnp.asarray(False))
    assert stepped.skipped is None  # treedef unchanged
    assert (jax.tree_util.tree_structure(stepped)
            == jax.tree_util.tree_structure(legacy))


# ------------------------------------------------------------- preemption


def test_grace_period_handler_catches_sigterm():
    with res.GracePeriodHandler() as h:
        assert not h.should_stop
        os.kill(os.getpid(), signal.SIGTERM)
        # signal delivery is synchronous for a self-kill on the main thread
        assert h.wait(timeout=5.0)
        assert h.should_stop
        assert h.reason == "SIGTERM"
    # handlers restored on exit
    assert signal.getsignal(signal.SIGTERM) is not h._on_signal


def test_grace_period_handler_restores_previous_handler():
    prev = signal.getsignal(signal.SIGINT)
    with res.GracePeriodHandler(signals=(signal.SIGINT,)):
        assert signal.getsignal(signal.SIGINT) is not prev
    assert signal.getsignal(signal.SIGINT) is prev


def test_request_stop_and_reset():
    h = res.GracePeriodHandler()
    h.request_stop()
    assert h.should_stop and h.reason == "requested"
    h.reset()
    assert not h.should_stop and h.reason is None


def test_preempted_training_resumes_bit_identical(chaos_ckpt_dir):
    """THE end-to-end chaos acceptance test: a run receives a simulated
    preemption (real SIGTERM) mid-run, writes a final checkpoint, exits
    cleanly; a restarted run restores and finishes with params bit-identical
    to an uninterrupted run."""
    state, opt, scaler = _toy_state()
    step_fn = _make_step_fn(opt, scaler)
    batches = _batches(6)

    straight = run_resilient_training(step_fn, state, batches)
    assert straight.steps_run == 6 and not straight.preempted

    with res.GracePeriodHandler() as h:
        preempt = chaos.SimulatedPreemption(3, handler=h)
        first = run_resilient_training(
            step_fn, state, batches, ckpt_dir=str(chaos_ckpt_dir),
            save_every=2, handler=h, on_step=preempt.poll)
    assert first.preempted and first.stop_reason == "SIGTERM"
    assert first.steps_run == 3
    # the final (grace-period) checkpoint is the one at the stop step
    assert first.last_saved_step == 3
    assert ckpt.latest_step(str(chaos_ckpt_dir)) == 3

    # "restart": fresh restore, consume the remaining batches
    restored, start = res.restore_resilient(str(chaos_ckpt_dir),
                                            target=state)
    assert start == 3
    second = run_resilient_training(step_fn, restored, batches[start:],
                                    start_step=start)
    assert second.step == 6
    _assert_trees_equal(straight.state, second.state)


def test_preemption_with_corrupt_final_falls_back_one_save(chaos_ckpt_dir):
    """Preempt, then corrupt the final checkpoint: the restart falls back
    to the periodic save and replays from there — still bit-identical."""
    state, opt, scaler = _toy_state()
    step_fn = _make_step_fn(opt, scaler)
    batches = _batches(6)
    straight = run_resilient_training(step_fn, state, batches)

    with res.GracePeriodHandler() as h:
        preempt = chaos.SimulatedPreemption(3, handler=h)
        run_resilient_training(
            step_fn, state, batches, ckpt_dir=str(chaos_ckpt_dir),
            save_every=2, handler=h, on_step=preempt.poll)
    chaos.corrupt_arrays(str(chaos_ckpt_dir), 3, mode="flip")
    with pytest.warns(res.CheckpointFallbackWarning):
        restored, start = res.restore_resilient(str(chaos_ckpt_dir),
                                                target=state)
    assert start == 2  # the periodic async save
    second = run_resilient_training(step_fn, restored, batches[start:],
                                    start_step=start)
    _assert_trees_equal(straight.state, second.state)


# ------------------------------------------------------- divergence guards


def test_step_guard_skips_then_raises_with_diagnostic():
    guard = res.StepGuard(max_consecutive_skips=3)
    bad_grads = {"dense": {"w": jnp.array([1.0, jnp.nan, jnp.inf, 2.0])}}
    assert not bool(guard.check(bad_grads))
    assert guard.update(False, bad_grads) is True
    assert guard.update(False, bad_grads) is True
    with pytest.raises(res.DivergenceError) as ei:
        guard.update(False, bad_grads)
    msg = str(ei.value)
    assert "3 consecutive" in msg
    assert "['dense']['w']" in msg  # names the first non-finite leaf
    assert "1 nan" in msg and "1 inf" in msg


def test_step_guard_resets_on_finite_step():
    guard = res.StepGuard(max_consecutive_skips=2)
    guard.update(False)
    guard.update(True)
    guard.update(False)  # would raise if the counter had not reset
    assert guard.consecutive == 1
    assert guard.total_skipped == 2
    assert guard.total_steps == 3


def test_step_guard_nonamp_loop_skips_bad_step():
    """Non-amp fp32 run: the guard's own all-finite check drives
    step_if_finite — params untouched on the poisoned step, updated on the
    clean one (the unification the amp scaler already had)."""
    params = {"w": jnp.ones((4,), jnp.float32)}
    opt = FusedAdam(lr=0.1)
    opt_state = opt.init(params)
    guard = res.StepGuard(max_consecutive_skips=5)

    @jax.jit
    def step(params, opt_state, grads):
        finite = guard.check(grads)
        new_p, new_o = opt.step_if_finite(grads, opt_state, params, finite)
        return new_p, new_o, finite

    bad = {"w": jnp.full((4,), jnp.nan)}
    p1, o1, f1 = step(params, opt_state, bad)
    assert guard.update(f1, bad) is True
    np.testing.assert_array_equal(np.asarray(p1["w"]), np.ones(4))

    good = {"w": jnp.ones((4,), jnp.float32)}
    p2, o2, f2 = step(p1, o1, good)
    assert guard.update(f2) is False
    assert not np.array_equal(np.asarray(p2["w"]), np.ones(4))
    assert guard.total_skipped == 1


def test_step_guard_sync_from_scaler():
    _, _, scaler = _toy_state()
    s = scaler.init()
    s = scaler.update(s, jnp.asarray(False))
    s = scaler.update(s, jnp.asarray(False))
    guard = res.StepGuard()
    guard.sync_from_scaler(s)
    assert guard.total_skipped == 2


def test_first_nonfinite_leaf_clean_tree():
    assert res.first_nonfinite_leaf({"a": jnp.ones((3,))}) is None


def test_loop_exception_not_masked_by_failed_async_save(chaos_ckpt_dir):
    """A parked async-save failure must not replace the primary exception
    (the DivergenceError diagnostic) raised from the loop body."""
    state, opt, scaler = _toy_state()
    # step 1: skip counted, async save submitted (fails, error parked);
    # step 2: guard raises — the fence must not swap in AsyncSaveError
    guard = res.StepGuard(max_consecutive_skips=2)

    def poisoned_step(s, b):
        return s, jnp.asarray(False)  # every step "non-finite"

    with chaos.FaultyStore(fail_events=("write_arrays",), fail_times=None):
        with pytest.raises(res.DivergenceError):
            run_resilient_training(
                poisoned_step, state, _batches(4),
                ckpt_dir=str(chaos_ckpt_dir), save_every=1, guard=guard,
                )


# ------------------------------------------------------------ housekeeping


def test_fault_hook_cleared_after_context():
    from apex_tpu.checkpoint import checkpoint as ckpt_mod

    with chaos.FaultyStore(fail_events=("write_arrays",), fail_times=1):
        pass
    assert ckpt_mod._fault_hook is None
