"""Serving resilience tier (ISSUE 10): request deadlines + overload
shedding, anti-livelock aging, engine crash recovery with
deterministic KV rebuild, per-page CRC validation, and the serving
chaos injectors.

THE acceptance pin lives here: a ``DeviceLossError`` raised MID-DECODE
(live requests holding pool pages) triggers rebuild + restore +
continue, and every request's token stream is bitwise identical to an
uninterrupted control — the PR 8 deterministic re-prefill contract is
what makes the KV pool checkpoint-free.
"""

import json

import pytest

import jax.numpy as jnp

from apex_tpu.resilience import chaos
from apex_tpu.resilience.chaos import DeviceLossError
from apex_tpu.resilience.elastic import Watchdog, WatchdogTimeout
from apex_tpu.serving import (FINISHED, ContinuousBatchingScheduler,
                              PagedKVCache, QueueFullError, Request,
                              ServingEngine, ServingModelConfig, SimClock,
                              init_params, poisson_trace)

pytestmark = pytest.mark.serving

CFG = ServingModelConfig(vocab_size=64, hidden_size=32, num_heads=4,
                         num_layers=2, max_position=96)


@pytest.fixture(scope="module")
def serving_params():
    return init_params(CFG, seed=0)


def _engine(params, **kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_budget", CFG.max_position)
    kw.setdefault("clock", SimClock())
    return ServingEngine(CFG, params, **kw)


def _trace(**kw):
    kw.setdefault("rate", 2.0)
    kw.setdefault("prompt_len", (4, 10))
    kw.setdefault("max_new", (3, 8))
    kw.setdefault("vocab_size", CFG.vocab_size)
    return poisson_trace(3, 6, **kw)


@pytest.fixture(scope="module")
def control_tokens(serving_params):
    """Uninterrupted control streams for the shared trace shape."""
    tr = _trace()
    _engine(serving_params).serve(tr)
    return {r.rid: list(r.generated) for r in tr}


# ---------------------------------------------------------------------------
# Deadlines: queue shedding, in-flight timeout, SLO-aware early shed
# ---------------------------------------------------------------------------


def _sched(num_pages=9, page_size=8, max_batch=4, prefill_budget=64,
           max_position=64, max_pages_per_request=8, **kw):
    cache = PagedKVCache(num_layers=1, num_pages=num_pages,
                         page_size=page_size, num_heads=1, head_dim=4,
                         max_pages_per_request=max_pages_per_request)
    return ContinuousBatchingScheduler(
        cache, max_batch=max_batch, prefill_budget=prefill_budget,
        max_position=max_position, **kw), cache


class TestDeadlines:
    def test_expired_queued_request_is_shed(self):
        sched, cache = _sched()
        r = Request(rid=0, prompt=[1] * 4, max_new_tokens=4,
                    arrival_t=0.0, deadline_s=2.0)
        sched.submit(r)
        shed, touts = sched.expire_deadlines(1.0)
        assert not shed and not touts          # still meetable
        shed, touts = sched.expire_deadlines(2.0)
        assert shed == [r] and not touts
        assert r.state == FINISHED and r.finish_reason == "shed"
        assert not sched.waiting and r in sched.finished

    def test_slo_shed_before_expiry(self):
        # the SLO-aware part: with a min-service floor the scheduler
        # refuses work that COULD only miss, before the deadline dies
        sched, _ = _sched()
        r = Request(rid=0, prompt=[1] * 4, max_new_tokens=4,
                    arrival_t=0.0, deadline_s=5.0)
        sched.submit(r)
        shed, _ = sched.expire_deadlines(1.0, min_service_s=3.0)
        assert not shed                        # 1 + 3 < 5: still viable
        shed, _ = sched.expire_deadlines(2.0, min_service_s=3.0)
        assert shed == [r]                     # 2 + 3 >= 5: hopeless

    def test_running_timeout_frees_pages_immediately(self):
        sched, cache = _sched()
        r = Request(rid=0, prompt=[1] * 12, max_new_tokens=8,
                    arrival_t=0.0, deadline_s=3.0)
        sched.submit(r)
        sched.admit()
        assert r.state == "running" and cache.pages_used > 0
        shed, touts = sched.expire_deadlines(3.0)
        assert touts == [r] and not shed
        assert r.finish_reason == "timeout" and r.pages == []
        assert cache.pages_used == 0
        # the freed pages are reusable by the very next admission
        r2 = Request(rid=1, prompt=[1] * 12, max_new_tokens=2)
        sched.submit(r2)
        assert sched.admit() == [r2]

    def test_deadline_free_requests_never_expire(self):
        sched, _ = _sched()
        sched.submit(Request(rid=0, prompt=[1] * 4, max_new_tokens=4))
        assert sched.expire_deadlines(1e9) == ([], [])

    def test_done_but_unretired_request_is_not_timed_out(self):
        # review regression: a request whose LAST token was generated
        # before its deadline died is complete, merely not yet swept by
        # retire_finished (the engine expires before retiring) — it
        # must retire normally, never be misreported as a timeout
        sched, _ = _sched()
        r = Request(rid=0, prompt=[1] * 4, max_new_tokens=2,
                    arrival_t=0.0, deadline_s=1.0)
        sched.submit(r)
        sched.admit()
        r.generated.extend([5, 6])             # done, awaiting sweep
        shed, touts = sched.expire_deadlines(10.0)   # deadline long dead
        assert not shed and not touts
        assert sched.retire_finished(10.0) == [r]
        assert r.finish_reason == "length"


class TestBoundedQueue:
    def test_scheduler_raises_queue_full(self):
        sched, _ = _sched(max_queue=2)
        sched.submit(Request(rid=0, prompt=[1] * 4, max_new_tokens=2))
        sched.submit(Request(rid=1, prompt=[1] * 4, max_new_tokens=2))
        with pytest.raises(QueueFullError):
            sched.submit(Request(rid=2, prompt=[1] * 4, max_new_tokens=2))

    def test_preemption_requeue_bypasses_the_bound(self):
        # an evicted request must ALWAYS be able to come back, even
        # when the queue is at its bound — only NEW submissions are
        # refused
        sched, _ = _sched(max_queue=1)
        r0 = Request(rid=0, prompt=[1] * 8, max_new_tokens=2)
        sched.submit(r0)
        sched.admit()
        sched.submit(Request(rid=1, prompt=[1] * 4, max_new_tokens=2))
        victim = sched.preempt_one()
        assert victim is r0 and sched.waiting[0] is r0
        assert len(sched.waiting) == 2         # over the bound, by design

    def test_engine_rejects_explicitly_with_event(self, serving_params):
        from apex_tpu import telemetry as tel

        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="reject", sinks=[mem])
        eng = _engine(serving_params, max_queue=2, telemetry=bus)
        reqs = [eng.submit([1, 2, 3], 2) for _ in range(4)]
        rejected = [r for r in reqs if r.finish_reason == "rejected"]
        assert len(rejected) == 2 and eng.rejected == rejected
        assert all(r.state == FINISHED for r in rejected)
        ev = [e for e in mem.events if e["type"] == "request_reject"]
        assert len(ev) == 2
        for e in ev:
            tel.validate_event(e)
            assert e["reason"] == "queue_full" and e["queue_depth"] == 2
        # the accepted half still serves to completion
        eng.run()
        assert all(len(r.generated) == 2 for r in reqs[:2])


# ---------------------------------------------------------------------------
# Anti-livelock aging
# ---------------------------------------------------------------------------


class TestAging:
    def _two_running(self, cap):
        sched, cache = _sched(num_pages=14, page_size=4,
                              max_pages_per_request=12, preempt_cap=cap)
        a = Request(rid=0, prompt=[1] * 4, max_new_tokens=30)
        b = Request(rid=1, prompt=[1] * 4, max_new_tokens=30)
        sched.submit(a)
        sched.submit(b)
        for req in sched.admit():
            req.kv_len = len(req.context)
            req.generated.append(0)
        return sched, cache, a, b

    def test_cap_redirects_eviction_after_repeat_hits(self):
        """THE livelock regression pin: evict-newest may hit the same
        request at most ``preempt_cap`` times; after that the aging
        rule makes it senior and the victim is the newest request
        still under the cap."""
        sched, cache, a, b = self._two_running(cap=2)
        for round_ in range(3):
            # pool-dry pressure: a dummy owner holds every free page,
            # then the newest running request crosses a page boundary
            dummy = cache.allocate(cache.pages_free, owner=-1)
            victim_pool = list(sched.running)
            grow = victim_pool[-1]
            grow.generated.extend([0] * 4)     # cross a page boundary
            evicted = sched.ensure_decode_capacity()
            assert len(evicted) >= 1
            cache.free([p for p in dummy if cache.owner_of(p) == -1])
            for req in sched.admit():
                req.kv_len = len(req.context)
            if round_ < 2:
                assert evicted[0] is b, (round_, evicted)
            else:
                # b is capped (2 preemptions): a — the OLDER request —
                # takes the hit instead
                assert any(r is a for r in evicted), (
                    round_, [r.rid for r in evicted], b.preemptions)
        assert b.preemptions == 2

    def test_uncapped_keeps_hitting_the_newest(self):
        sched, cache, a, b = self._two_running(cap=None)
        for _ in range(3):
            dummy = cache.allocate(cache.pages_free, owner=-1)
            sched.running[-1].generated.extend([0] * 4)
            evicted = sched.ensure_decode_capacity()
            assert evicted and evicted[0] is b
            cache.free([p for p in dummy if cache.owner_of(p) == -1])
            for req in sched.admit():
                req.kv_len = len(req.context)
        assert b.preemptions == 3 and a.preemptions == 0

    def test_long_request_completes_under_sustained_pressure(self):
        """Property: a long request keeps completing while short
        requests arrive EVERY step — sustained pressure must never
        starve it past the cap."""
        sched, cache = _sched(num_pages=9, page_size=4,
                              max_pages_per_request=8, max_batch=3,
                              preempt_cap=2)
        long_req = Request(rid=0, prompt=[1] * 4, max_new_tokens=20)
        sched.submit(long_req)
        next_rid = 1
        for t in range(200):
            if next_rid <= 40:
                sched.submit(Request(rid=next_rid, prompt=[1] * 8,
                                     max_new_tokens=2))
                next_rid += 1
            for req in sched.admit():
                req.kv_len = len(req.context)
                req.generated.append(0)
            sched.retire_finished(float(t))
            if sched.running:
                sched.ensure_decode_capacity()
                for req in sched.running:
                    req.kv_len = req.seq_len
                    req.generated.append(0)
            sched.retire_finished(float(t))
            if sched.idle and next_rid > 40:
                break
        assert long_req.state == FINISHED, (
            long_req.state, long_req.preemptions)
        assert long_req.preemptions <= 2
        assert len(long_req.generated) == 20
        assert cache.pages_used == 0


# ---------------------------------------------------------------------------
# Reserve-at-admit (ISSUE 10 satellite: the admit-then-exhaust window)
# ---------------------------------------------------------------------------


class TestReserveAtAdmit:
    def test_admit_then_exhaust_leaves_reservation_intact(self):
        # the regression: pages are reserved AT ADMIT, so exhausting
        # the pool between admission and prefill cannot steal the
        # admitted request's pages
        sched, cache = _sched(num_pages=9, page_size=8)
        r = Request(rid=0, prompt=[1] * 20, max_new_tokens=4)
        sched.submit(r)
        assert sched.admit() == [r]
        reserved = list(r.pages)
        assert len(reserved) == cache.pages_needed(20)
        cache.allocate(cache.pages_free, owner=99)   # the exhaust window
        assert cache.pages_free == 0
        # the reservation survives: same pages, same owner
        assert r.pages == reserved
        assert all(cache.owner_of(p) == r.rid for p in reserved)

    def test_prefill_asserts_reservation(self, serving_params):
        # defence in depth: a prefill that somehow finds its
        # reservation gone is a scheduler BUG and must raise loudly,
        # not scatter K/V into unowned pages
        eng = _engine(serving_params)
        req = eng.submit([1, 2, 3, 4], 2)
        admitted = eng.sched.admit()
        assert admitted == [req]
        stolen = req.pages
        req.pages = []
        with pytest.raises(RuntimeError, match="reserved"):
            eng._prefill_request(req)
        req.pages = stolen  # restore for clean teardown


# ---------------------------------------------------------------------------
# Crash recovery: THE acceptance pin + snapshot/restore round trip
# ---------------------------------------------------------------------------


class TestCrashRecovery:
    def test_device_loss_mid_decode_recovers_bitwise(
            self, serving_params, control_tokens):
        """Acceptance criterion: device loss mid-decode → rebuild +
        restore → per-request token streams bitwise identical to the
        uninterrupted control."""
        from apex_tpu import telemetry as tel

        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="loss", sinks=[mem])
        tr = _trace()
        with chaos.ServingDeviceLoss(at_step=3, device_ids=[0],
                                     telemetry=bus) as dl:
            eng = _engine(serving_params, telemetry=bus)
            eng.serve(tr)
        assert dl.fired and eng.recoveries == 1
        got = {r.rid: list(r.generated) for r in tr}
        assert got == control_tokens           # bitwise, token-for-token
        types = [e["type"] for e in mem.events]
        assert "serving_recovery" in types and "device_loss" in types
        rec = next(e for e in mem.events if e["type"] == "serving_recovery")
        assert rec["pool_rebuilt"] is True and rec["cause"] == "device_loss"
        assert rec["running_restored"] >= 1    # mid-decode: batch was live
        for e in mem.events:
            tel.validate_event(e)

    def test_corrupt_page_caught_and_recovered_bitwise(
            self, serving_params, control_tokens):
        from apex_tpu import telemetry as tel

        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="crc", sinks=[mem])
        eng = _engine(serving_params, telemetry=bus, validate_pages=True)
        tr = _trace()
        with chaos.CorruptLivePage(eng.cache, at_step=2,
                                   telemetry=bus) as cp:
            eng.serve(tr)
        assert cp.corrupted_page is not None and eng.recoveries == 1
        got = {r.rid: list(r.generated) for r in tr}
        assert got == control_tokens
        rec = next(e for e in mem.events if e["type"] == "serving_recovery")
        assert rec["cause"] == "page_corruption"

    def test_corruption_without_crc_validation_goes_unnoticed(
            self, serving_params):
        # the control for the CRC feature: the same byte flip with
        # validation OFF raises nothing (the damage silently perturbs
        # attention) — which is exactly why the knob exists
        eng = _engine(serving_params)
        tr = _trace()
        with chaos.CorruptLivePage(eng.cache, at_step=2):
            eng.serve(tr)
        assert eng.recoveries == 0

    def test_recovery_budget_exhausted_reraises(self, serving_params):
        with chaos.ServingDeviceLoss(at_step=2):
            eng = _engine(serving_params, max_recoveries=0)
            with pytest.raises(DeviceLossError):
                eng.serve(_trace())

    def test_recovery_disabled_reraises(self, serving_params):
        with chaos.ServingDeviceLoss(at_step=2):
            eng = _engine(serving_params, recover_on_fault=False)
            with pytest.raises(DeviceLossError):
                eng.serve(_trace())

    def test_snapshot_restore_round_trip_with_poisoned_pool(
            self, serving_params, control_tokens):
        """snapshot → JSON → restore into a fresh engine whose pool is
        sentinel-poisoned → continue: bitwise the control's streams.
        The poison proves restore depends on NOTHING in the old pool —
        KV pages are deliberately not part of the snapshot."""
        src = _engine(serving_params)
        tr = _trace()
        for r in tr:
            src.submit_request(r)
        for _ in range(4):
            src.step()
        snap = json.loads(json.dumps(src.snapshot()))  # serializability pin
        dst = _engine(serving_params)
        dst.cache.k = jnp.full_like(dst.cache.k, 1e3)
        dst.cache.v = jnp.full_like(dst.cache.v, 1e3)
        restored = dst.restore(snap)
        dst.run()
        assert restored                         # something was live
        for r in restored:
            assert list(r.generated) == control_tokens[r.rid], r.rid

    @pytest.mark.slow  # every cut boundary incl. done-but-unretired window
    def test_snapshot_restore_at_every_boundary(self, serving_params,
                                                control_tokens):
        for cut in range(1, 12):
            src = _engine(serving_params)
            tr = _trace()
            for r in tr:
                src.submit_request(r)
            for _ in range(cut):
                if src.sched.idle:
                    break
                src.step()
            snap = json.loads(json.dumps(src.snapshot()))
            dst = _engine(serving_params)
            dst.cache.k = jnp.full_like(dst.cache.k, 1e3)
            dst.cache.v = jnp.full_like(dst.cache.v, 1e3)
            restored = dst.restore(snap)
            dst.run()
            for r in restored:
                assert list(r.generated) == control_tokens[r.rid], (
                    cut, r.rid)

    def test_restore_into_busy_engine_refuses(self, serving_params):
        src = _engine(serving_params)
        src.submit([1, 2], 2)
        snap = src.snapshot()
        busy = _engine(serving_params)
        busy.submit([3, 4], 2)
        with pytest.raises(RuntimeError, match="busy"):
            busy.restore(snap)
        fresh = _engine(serving_params)
        with pytest.raises(ValueError, match="format"):
            fresh.restore({"format": 99})


# ---------------------------------------------------------------------------
# Timeout storm: no page leak, bounded queue, stream validates
# ---------------------------------------------------------------------------


class TestTimeoutStorm:
    def test_storm_leaves_every_page_reallocatable(self, serving_params):
        from apex_tpu import telemetry as tel

        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="storm", sinks=[mem])
        eng = ServingEngine(CFG, serving_params, num_pages=16, page_size=8,
                            max_batch=2, prefill_budget=CFG.max_position,
                            clock=SimClock(0.25), telemetry=bus,
                            max_queue=6)
        tr = poisson_trace(13, 24, rate=50.0, prompt_len=(4, 12),
                           max_new=(3, 10), vocab_size=CFG.vocab_size,
                           deadline_s=(1.0, 5.0))
        eng.serve(tr)
        reasons = {}
        for r in tr:
            reasons[r.finish_reason] = reasons.get(r.finish_reason, 0) + 1
        # the storm must exercise every drop path AND still serve
        assert reasons.get("rejected", 0) > 0, reasons
        assert reasons.get("timeout", 0) > 0, reasons
        assert (reasons.get("length", 0) + reasons.get("eos", 0)) > 0, reasons
        # no leak: pool fully drained and the WHOLE pool allocatable
        # in one take
        assert eng.cache.pages_used == 0
        pages = eng.cache.allocate(eng.cache.num_pages - 1, owner=-1)
        assert len(pages) == eng.cache.num_pages - 1
        for e in mem.events:
            tel.validate_event(e)
        types = {e["type"] for e in mem.events}
        assert {"request_reject", "request_timeout"} <= types

    def test_summarize_and_diff_render_overload_health(
            self, serving_params, tmp_path):
        from apex_tpu import telemetry as tel
        from apex_tpu.telemetry.__main__ import main as tel_cli

        path = str(tmp_path / "storm.jsonl")
        bus = tel.TelemetryBus(run_id="storm-sum",
                               sinks=[tel.JsonlSink(path)])
        eng = ServingEngine(CFG, serving_params, num_pages=16, page_size=8,
                            max_batch=2, prefill_budget=CFG.max_position,
                            clock=SimClock(0.25), telemetry=bus,
                            max_queue=6)
        eng.serve(poisson_trace(13, 24, rate=50.0, prompt_len=(4, 12),
                                max_new=(3, 10),
                                vocab_size=CFG.vocab_size,
                                deadline_s=(1.0, 5.0)))
        bus.close()
        assert tel_cli(["validate", path]) == 0   # acceptance contract
        s = tel.summarize_file(path)
        assert s["serving_sheds"] > 0
        assert s["serving_timeouts"] > 0
        assert s["serving_rejects"] > 0
        assert 0.0 <= s["serving_deadline_hit_rate"] < 1.0
        out = tel.format_summary(s)
        assert "shed" in out and "timeout" in out and "deadline hit" in out
        # the --diff table carries a deadline-hit-rate row
        diff = tel.format_diff(s, s)
        assert "deadline hit" in diff


# ---------------------------------------------------------------------------
# Decode-loop watchdog
# ---------------------------------------------------------------------------


class TestDecodeWatchdog:
    def test_wedged_decode_escalates_instead_of_hanging(
            self, serving_params):
        # margins follow the PR 6 de-flaked watchdog case
        # (timeout=1.0 / delay=2.5, executable warmed before arming)
        reports = []
        wd = Watchdog(timeout=1.0, on_hang=reports.append,
                      poll_interval=0.02, devices=[0])
        eng = _engine(serving_params, watchdog=wd)
        eng.warmup()    # compile outside the armed region
        with chaos.SlowDecode(at_step=2, delay=2.5):
            with wd:
                reqs = [eng.submit([1, 2, 3], 4), eng.submit([4, 5], 4)]
                eng.run()
        assert wd.expired and reports, "watchdog never fired"
        assert reports[0]["timeout"] == 1.0
        # the wedge cleared (injected sleep ended): serving completed
        assert all(len(r.generated) == 4 for r in reqs)

    def test_unhandled_overrun_raises_at_next_step(self, serving_params):
        # no handler / on_hang: the overrun must surface as
        # WatchdogTimeout on the next arm — a hang is never silent
        wd = Watchdog(timeout=0.8, poll_interval=0.02, devices=[0])
        eng = _engine(serving_params, watchdog=wd)
        eng.warmup()
        with chaos.SlowDecode(at_step=1, delay=2.0):
            with wd:
                eng.submit([1, 2, 3], 6)
                with pytest.raises(WatchdogTimeout):
                    eng.run()


# ---------------------------------------------------------------------------
# Schema: the new event types keep the closed-set discipline
# ---------------------------------------------------------------------------


class TestServingEventSchema:
    def _stamp(self, type_, **payload):
        ev = {"type": type_, "run_id": "r", "step": 0, "t": 0.0,
              "ts": 0.0, "mesh": {}}
        ev.update(payload)
        return ev

    def test_new_events_validate(self):
        from apex_tpu.telemetry import validate_event

        validate_event(self._stamp("request_reject", rid=1,
                                   reason="queue_full", queue_depth=3))
        validate_event(self._stamp("request_timeout", rid=1,
                                   where="queued", overshoot_ms=1.5))
        validate_event(self._stamp("serving_recovery", cause="device_loss",
                                   pool_rebuilt=True, running_restored=2,
                                   waiting_restored=1))

    def test_pool_rebuilt_must_be_a_real_bool(self):
        from apex_tpu.telemetry import validate_event
        from apex_tpu.telemetry.schema import SchemaError

        with pytest.raises(SchemaError, match="pool_rebuilt"):
            validate_event(self._stamp(
                "serving_recovery", cause="device_loss", pool_rebuilt=1,
                running_restored=2, waiting_restored=1))

    def test_missing_required_fields_rejected(self):
        from apex_tpu.telemetry import validate_event
        from apex_tpu.telemetry.schema import SchemaError

        with pytest.raises(SchemaError, match="where"):
            validate_event(self._stamp("request_timeout", rid=1,
                                       overshoot_ms=0.0))
        with pytest.raises(SchemaError, match="queue_depth"):
            validate_event(self._stamp("request_reject", rid=1,
                                       reason="queue_full"))

    def test_deadline_hit_rides_retire_as_bool(self, serving_params):
        from apex_tpu import telemetry as tel

        mem = tel.MemorySink()
        bus = tel.TelemetryBus(run_id="dh", sinks=[mem])
        eng = _engine(serving_params, telemetry=bus)
        eng.submit([1, 2, 3], 3, deadline_s=1e6)   # generous: must hit
        eng.submit([4, 5, 6], 3)                   # no deadline: absent
        eng.run()
        retires = {e["rid"]: e for e in mem.events
                   if e["type"] == "request_retire"}
        assert retires[0]["deadline_hit"] is True
        assert "deadline_hit" not in retires[1]
        for e in mem.events:
            tel.validate_event(e)
