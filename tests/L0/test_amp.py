"""amp unit tests.

Mirrors tests/L0/run_amp in the reference: casting behavior per opt level
(test_basic_casts.py), promotion rules (test_promotion.py), loss-scale
dynamics, and checkpoint round-trip (test_checkpointing.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp


def make_params():
    return {
        "dense": {"kernel": jnp.ones((4, 4), jnp.float32), "bias": jnp.zeros((4,), jnp.float32)},
        "batch_norm": {"scale": jnp.ones((4,), jnp.float32), "bias": jnp.zeros((4,), jnp.float32)},
    }


class TestOptLevels:
    def test_o0_identity(self):
        a = amp.initialize("O0")
        p = a.cast_model(make_params())
        assert p["dense"]["kernel"].dtype == jnp.float32
        assert a.scaler.dynamic is False and a.scaler.init_scale == 1.0

    def test_o2_casts_but_keeps_bn_fp32(self):
        a = amp.initialize("O2")
        p = a.cast_model(make_params())
        assert p["dense"]["kernel"].dtype == jnp.bfloat16
        assert p["batch_norm"]["scale"].dtype == jnp.float32
        m = a.master_params(make_params())
        assert m["dense"]["kernel"].dtype == jnp.float32
        assert a.scaler.dynamic is True

    def test_o3_pure_half(self):
        a = amp.initialize("O3")
        p = a.cast_model(make_params())
        assert p["batch_norm"]["scale"].dtype == jnp.bfloat16
        assert a.scaler.dynamic is False

    def test_o1_no_model_cast(self):
        a = amp.initialize("O1")
        p = a.cast_model(make_params())
        assert p["dense"]["kernel"].dtype == jnp.float32

    def test_overrides(self):
        a = amp.initialize("O2", keep_batchnorm_fp32=False, loss_scale=128.0)
        p = a.cast_model(make_params())
        assert p["batch_norm"]["scale"].dtype == jnp.bfloat16
        assert a.scaler.dynamic is False and a.scaler.init_scale == 128.0

    def test_bad_level(self):
        with pytest.raises(ValueError):
            amp.initialize("O4")

    def test_fp16_half_dtype(self):
        a = amp.initialize("O2", half_dtype=jnp.float16)
        p = a.cast_model(make_params())
        assert p["dense"]["kernel"].dtype == jnp.float16


class TestLossScaler:
    def test_dynamic_defaults_match_reference(self):
        s = amp.LossScaler.dynamic_scaler()
        # reference scaler.py:38-54
        assert s.init_scale == 2.0 ** 16
        assert s.scale_factor == 2.0
        assert s.scale_window == 2000
        assert s.max_scale == 2.0 ** 24

    def test_overflow_halves(self):
        s = amp.LossScaler.dynamic_scaler()
        st = s.init()
        st = s.update(st, jnp.asarray(False))
        assert float(st.loss_scale) == 2.0 ** 15
        assert int(st.unskipped) == 0

    def test_growth_after_window(self):
        s = amp.LossScaler.dynamic_scaler(scale_window=3, init_scale=4.0)
        st = s.init()
        for _ in range(3):
            st = s.update(st, True)
        assert float(st.loss_scale) == 8.0
        assert int(st.unskipped) == 0

    def test_cap_at_max(self):
        s = amp.LossScaler.dynamic_scaler(scale_window=1, init_scale=2.0 ** 24)
        st = s.update(s.init(), True)
        assert float(st.loss_scale) == 2.0 ** 24

    def test_floor_at_min(self):
        s = amp.LossScaler.dynamic_scaler(init_scale=1.0, min_scale=1.0)
        st = s.update(s.init(), False)
        assert float(st.loss_scale) == 1.0

    def test_static_never_moves(self):
        s = amp.LossScaler.static(128.0)
        st = s.update(s.init(), False)
        assert float(st.loss_scale) == 128.0

    def test_unscale_detects_inf_and_nan(self):
        s = amp.LossScaler.dynamic_scaler(init_scale=2.0)
        st = s.init()
        grads = {"a": jnp.asarray([1.0, jnp.inf]), "b": jnp.ones((2,))}
        g, finite = s.unscale(grads, st)
        assert not bool(finite)
        grads = {"a": jnp.asarray([1.0, 2.0]), "b": jnp.ones((2,))}
        g, finite = s.unscale(grads, st)
        assert bool(finite)
        np.testing.assert_allclose(g["a"], [0.5, 1.0])

    def test_state_dict_roundtrip(self):
        s = amp.LossScaler.dynamic_scaler()
        st = s.update(s.init(), True)
        d = amp.state_dict(st)
        st2 = amp.load_state_dict(d)
        assert float(st2.loss_scale) == float(st.loss_scale)
        assert int(st2.unskipped) == 1
        assert int(st2.skipped) == 0

    def test_min_scale_clamps_repeated_overflow(self):
        # custom (non-default) floor, starting well above it: repeated
        # overflows divide down then stick at min_scale exactly
        s = amp.LossScaler.dynamic_scaler(init_scale=32.0, min_scale=8.0)
        st = s.init()
        for expect in (16.0, 8.0, 8.0, 8.0):
            st = s.update(st, False)
            assert float(st.loss_scale) == expect

    def test_max_scale_clamps_growth(self):
        # growth would overshoot a custom cap: 6 -> would be 12, capped at 10
        s = amp.LossScaler.dynamic_scaler(
            init_scale=6.0, scale_window=1, max_scale=10.0)
        st = s.update(s.init(), True)
        assert float(st.loss_scale) == 10.0
        st = s.update(st, True)
        assert float(st.loss_scale) == 10.0  # stays clamped

    def test_skipped_counter_monotonic(self):
        """`skipped` counts every overflow-skipped step and never resets —
        the queryable version of the reference's "Gradient overflow.
        Skipping step" print (used by resilience.StepGuard)."""
        s = amp.LossScaler.dynamic_scaler(init_scale=16.0)
        st = s.init()
        assert int(st.skipped) == 0
        seq = [False, True, False, False, True]
        for finite in seq:
            st = s.update(st, finite)
        assert int(st.skipped) == 3
        # clean steps never decrease it
        st = s.update(st, True)
        assert int(st.skipped) == 3

    def test_skipped_counts_under_static_scaler_too(self):
        s = amp.LossScaler.static(128.0)
        st = s.update(s.init(), False)
        assert float(st.loss_scale) == 128.0  # scale pinned
        assert int(st.skipped) == 1  # but the skip is still recorded

    def test_load_state_dict_without_skipped_key(self):
        st = amp.load_state_dict({"loss_scale": 4.0, "unskipped": 7})
        assert int(st.skipped) == 0


class TestScaledValueAndGrad:
    def test_grads_match_unscaled(self):
        s = amp.LossScaler.dynamic_scaler(init_scale=2.0 ** 10)
        st = s.init()

        def loss_fn(p, x):
            return jnp.sum((x @ p) ** 2)

        p = jnp.ones((3, 3))
        x = jnp.arange(6.0).reshape(2, 3)
        vg = amp.scaled_value_and_grad(loss_fn, s)
        loss, grads, finite = vg(st, p, x)
        ref_loss, ref_grads = jax.value_and_grad(loss_fn)(p, x)
        assert bool(finite)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-6)
        np.testing.assert_allclose(grads, ref_grads, rtol=1e-5)
        assert grads.dtype == jnp.float32

    def test_has_aux(self):
        s = amp.LossScaler.static(4.0)
        st = s.init()

        def loss_fn(p):
            return jnp.sum(p**2), {"metric": jnp.sum(p)}

        vg = amp.scaled_value_and_grad(loss_fn, s, has_aux=True)
        (loss, aux), grads, finite = vg(st, jnp.ones((2,)))
        assert float(loss) == 2.0
        assert float(aux["metric"]) == 2.0
        np.testing.assert_allclose(grads, [2.0, 2.0])

    def test_overflow_flag_under_jit(self):
        s = amp.LossScaler.dynamic_scaler(init_scale=2.0)
        st = s.init()

        def loss_fn(p):
            return jnp.sum(p * jnp.asarray([1.0, jnp.nan]))

        vg = jax.jit(amp.scaled_value_and_grad(loss_fn, s))
        _, grads, finite = vg(st, jnp.ones((2,)))
        assert not bool(finite)

    def test_skip_or_step(self):
        new = {"w": jnp.ones((2,))}
        old = {"w": jnp.zeros((2,))}
        kept = amp.handle.skip_or_step(jnp.asarray(False), new, old)
        np.testing.assert_allclose(kept["w"], [0.0, 0.0])
        stepped = amp.handle.skip_or_step(jnp.asarray(True), new, old)
        np.testing.assert_allclose(stepped["w"], [1.0, 1.0])


class TestCastWrappers:
    def test_half_function(self):
        f = amp.half_function(lambda x: x)
        assert f(jnp.ones((2,), jnp.float32)).dtype == jnp.bfloat16

    def test_float_function(self):
        f = amp.float_function(lambda x: x)
        assert f(jnp.ones((2,), jnp.bfloat16)).dtype == jnp.float32

    def test_promote_function(self):
        f = amp.promote_function(lambda x, y: (x, y))
        a, b = f(jnp.ones((2,), jnp.bfloat16), jnp.ones((2,), jnp.float32))
        assert a.dtype == jnp.float32 and b.dtype == jnp.float32

    def test_policy_lookup(self):
        from apex_tpu.amp import lists

        assert lists.autocast_policy("matmul") == "half"
        assert lists.autocast_policy("softmax") == "float"
        assert lists.autocast_policy("add") == "promote"
        assert lists.autocast_policy("relu") is None
        with pytest.raises(NotImplementedError):
            lists.autocast_policy("binary_cross_entropy")


class TestOptimWrapper:
    """Legacy amp.opt surface (reference apex/amp/opt.py:9-104):
    per-loss scalers selected by loss_id, functional state."""

    def test_two_losses_scale_independently(self):
        from apex_tpu import amp, optimizers

        params = {"w": jnp.ones((4,))}
        wrapper = amp.OptimWrapper(optimizers.FusedSGD(lr=0.1), num_loss=2)
        state = wrapper.init(params)

        def loss_a(p, x):
            return jnp.sum(p["w"] * x)

        def loss_bad(p, x):
            return jnp.sum(p["w"] * x) * jnp.inf  # always overflows

        x = jnp.ones((4,))
        (l0), g0, fin0 = wrapper.scaled_grad(loss_a, state, params, x,
                                             loss_id=0)
        params2, state = wrapper.step(state, params, g0, fin0, loss_id=0)
        assert bool(fin0)
        assert float(jnp.abs(params2["w"] - params["w"]).max()) > 0

        (l1), g1, fin1 = wrapper.scaled_grad(loss_bad, state, params2, x,
                                             loss_id=1)
        params3, state = wrapper.step(state, params2, g1, fin1, loss_id=1)
        assert not bool(fin1)
        np.testing.assert_array_equal(np.asarray(params3["w"]),
                                      np.asarray(params2["w"]))  # skipped
        sd = wrapper.state_dict(state)
        # loss 0's scaler untouched by loss 1's overflow; loss 1 halved
        assert sd["scalers"][0]["loss_scale"] == 2.0 ** 16
        assert sd["scalers"][1]["loss_scale"] == 2.0 ** 15
