"""GPT-1.3B flagship machinery tests (ISSUE 2 tentpole).

The full 1.3B shape only runs on hardware (bench.py gpt1p3b_*); here the
same construction — d=128 head geometry, ZeRO-sharded FusedAdam over the
mesh "data" axis, fit-plan dtypes — runs at toy width/depth on the
emulated 8-device mesh, with the acceptance parity check:
ZeRO-sharded step vs unsharded FusedAdam, max|dw| ≤ 1e-3.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import optimizers
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    FIT_PLANS,
    GPTModel,
    build_flagship_train_step,
    flagship_state_bytes,
    gpt1p3b_config,
    gpt_param_count,
)

N_DEV = 8

# toy depth/width, flagship head geometry: hidden/heads = 128 keeps the
# d=128 kernel routing (the thing the flagship exists to measure) while
# the model stays CPU-small
TOY_KW = dict(num_layers=2, hidden_size=256, num_attention_heads=2,
              vocab_size=256, max_position_embeddings=64)


def _batch(cfg, b=8, seed=1):
    k = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(k, (b, cfg.max_position_embeddings), 0,
                                cfg.vocab_size)
    return tokens, jnp.roll(tokens, -1, axis=-1)


@pytest.fixture(scope="module")
def flagship_bf16_fit():
    """ONE bf16_fit flagship construction shared by every test in this
    module that steps the default toy config (ISSUE 6 wall-clock
    satellite: the 8-device jit construction is the dominant cost —
    build it once per module, not once per test).  donate=False so each
    test can step from the pristine (params, opt_state) snapshot."""
    cfg = gpt1p3b_config(**TOY_KW)
    return cfg, build_flagship_train_step(
        cfg, plan="bf16_fit", lr=1e-3, devices=jax.devices()[:N_DEV],
        donate=False)


def _unsharded_reference(cfg, plan, tokens, labels, steps, lr):
    """Plain (unsharded) FusedAdam trajectory of the identical model —
    the parity baseline the reference's test_dist_adam.py compares
    against.  Params in the same storage dtype as the ZeRO run so the
    comparison isolates the sharding machinery, not the fit plan."""
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        1, 1, devices=jax.devices()[:1])
    model = GPTModel(cfg)
    params = model.shard_master(
        model.init_master(jax.random.PRNGKey(0)), 0)
    params = jax.tree_util.tree_map(
        lambda a: a.astype(plan.param_dtype), params)
    opt = optimizers.FusedAdam(lr=lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s, t, l):
        def lossf(p):
            return shard_map(
                lambda p, t, l: jnp.mean(model.apply(p, t, labels=l)),
                mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
                check_rep=False)(p, t, l)

        loss, grads = jax.value_and_grad(lossf)(p)
        p, s = opt.step(grads, s, p)
        return p, s, loss

    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, labels)
    return params, float(loss)


@pytest.mark.parametrize("plan_name,compute_bf16,tol", [
    # fp32 plan at fp32 compute — the ISSUE 2 acceptance cell
    # (max|dw| ≤ 1e-3), measured ~1e-6: with grad noise removed, the
    # diff isolates the sharding machinery (psum_scatter reduction
    # order, flat-schema slicing, all_gather reassembly).  bf16 compute
    # would make the comparison vacuous: Adam's step-1 update is
    # ~sign(g)·lr, so bf16-level grad noise between the batch-split and
    # full-batch graphs flips signs of near-zero grads and saturates
    # max|dw| at 2·lr for ANY correct implementation.
    ("fp32", False, 1e-3),
    # the single-chip fit plan at the real bf16 compute: params are
    # STORED bf16 in both runs, so the floor is one bf16 ulp at the
    # largest param scale (layernorm weights ≈ 1.0 → ulp 2⁻⁸); two
    # ulps bound the two steps — slow tier (~16s; the fp32 cell keeps
    # the sharding-machinery parity in tier-1, ISSUE 12 wall trim)
    pytest.param("bf16_fit", True, 2 ** -7, marks=pytest.mark.slow),
])
def test_zero_step_parity_vs_unsharded(plan_name, compute_bf16, tol,
                                       flagship_bf16_fit):
    cfg = gpt1p3b_config(bf16=compute_bf16, **TOY_KW)
    plan = FIT_PLANS[plan_name]
    tokens, labels = _batch(cfg)

    if plan_name == "bf16_fit" and compute_bf16:
        # the default toy construction — reuse the module's shared build
        _, fs = flagship_bf16_fit
    else:
        fs = build_flagship_train_step(
            cfg, plan=plan_name, lr=1e-3, devices=jax.devices()[:N_DEV],
            donate=False)
    p, s = fs.params, fs.opt_state
    for _ in range(2):
        p, s, loss = fs.step(p, s, tokens, labels)
    assert np.isfinite(float(loss))

    ref_p, ref_loss = _unsharded_reference(cfg, plan, tokens, labels,
                                           steps=2, lr=1e-3)
    # compare on host: the two trees live on different device sets
    maxdw = max(
        float(np.max(np.abs(np.asarray(a, np.float32)
                            - np.asarray(b, np.float32))))
        for a, b in zip(jax.tree_util.tree_leaves(p),
                        jax.tree_util.tree_leaves(ref_p)))
    assert maxdw <= tol, (plan_name, maxdw)


def test_flagship_loss_decreases(flagship_bf16_fit):
    cfg, fs = flagship_bf16_fit
    tokens, labels = _batch(cfg)
    p, s = fs.params, fs.opt_state
    losses = []
    for _ in range(6):
        p, s, loss = fs.step(p, s, tokens, labels)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_param_count_matches_tree():
    cfg = gpt1p3b_config(**TOY_KW)
    model = GPTModel(cfg)
    params = model.shard_master(
        model.init_master(jax.random.PRNGKey(0)), 0)
    n = sum(int(a.size) for a in jax.tree_util.tree_leaves(params))
    assert n == gpt_param_count(cfg)


def test_fit_plan_table_matches_module_docs():
    """The fitting table the BASELINE.md gpt1p3b section records: at the
    full 1.3B shape only bf16_fit's optimizer-phase peak fits a
    15.75-GiB chip at world=1; bf16_fp32m fits once sharded."""
    cfg = gpt1p3b_config()
    n = gpt_param_count(cfg)
    assert 1.25e9 < n < 1.40e9, n  # "1.3B-class"
    budget = 15.75 * 2 ** 30  # ≈16.9e9 bytes
    peaks = {name: flagship_state_bytes(cfg, plan)["step_peak"]
             for name, plan in FIT_PLANS.items()}
    assert peaks["fp32"] > peaks["bf16_fp32m"] > peaks["bf16_fit"]
    assert peaks["fp32"] > budget
    assert peaks["bf16_fp32m"] > budget  # the near-miss the docs name
    assert peaks["bf16_fit"] < budget
    # sharding shrinks the moment terms: fp32 moments fit at world ≥ 2
    sharded = flagship_state_bytes(cfg, FIT_PLANS["bf16_fp32m"],
                                   n_shards=8)
    assert sharded["step_peak"] < budget


def test_flagship_shape_engages_packed_attention(monkeypatch):
    """Tentpole (d): at the flagship geometry (s=2048, d=128, bf16,
    block 256) the packed-QKV gate must pass — the shape class the
    flagship exists for cannot silently fall to the generic kernels."""
    from apex_tpu.ops import attention as attn_mod

    monkeypatch.setattr(attn_mod.jax, "default_backend", lambda: "tpu")
    cfg = gpt1p3b_config()
    hn = cfg.kv_channels
    assert hn == 128
    assert attn_mod._qkv_packed_ok(
        4, cfg.max_position_embeddings, cfg.num_attention_heads, hn,
        cfg.flash_block_q, True, 0.0, jnp.bfloat16)
    # and the generic-kernel backward (the attn_res recompute path for
    # masked variants) stays compilable at this shape via the grid
    # one-pass kernel
    q = jax.ShapeDtypeStruct((4 * 16, 2048, 128), jnp.bfloat16)
    assert attn_mod._pallas_bwd_ok(q, q, None, 512, 512)
