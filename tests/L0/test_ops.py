"""L0 fused-op tests vs unfused jnp references.

Mirrors the reference's kernel-vs-reference tier (SURVEY.md §4):
tests/L0/run_fused_layer_norm/test_fused_layer_norm.py,
run_transformer/test_fused_softmax.py, run_mlp/test_mlp.py,
contrib xentropy tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import ops


def _ln_ref(x, w, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    y = (x32 - mean) / jnp.sqrt(var + eps)
    if w is not None:
        y = y * w
    if b is not None:
        y = y + b
    return y.astype(x.dtype)


class TestFusedLayerNorm:
    def test_fwd_matches_reference(self):
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (12, 256), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (256,)) * 0.1 + 1.0
        b = jax.random.normal(jax.random.PRNGKey(2), (256,)) * 0.1
        np.testing.assert_allclose(
            ops.layer_norm(x, w, b), _ln_ref(x, w, b), rtol=1e-5, atol=1e-5
        )

    def test_fwd_no_affine(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
        np.testing.assert_allclose(
            ops.layer_norm(x), _ln_ref(x, None, None), rtol=1e-5, atol=1e-5
        )

    def test_grad_matches_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 128), jnp.float32)
        w = jnp.ones((128,)) * 1.3
        b = jnp.zeros((128,)) + 0.1

        def loss_fused(x, w, b):
            return jnp.sum(jnp.sin(ops.layer_norm(x, w, b)))

        def loss_ref(x, w, b):
            return jnp.sum(jnp.sin(_ln_ref(x, w, b)))

        g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
        for a, r in zip(g1, g2):
            np.testing.assert_allclose(a, r, rtol=1e-4, atol=1e-4)

    def test_pallas_interpret_matches_xla(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (16, 128), jnp.float32)
        w = jnp.full((128,), 1.1)
        b = jnp.full((128,), -0.2)
        np.testing.assert_allclose(
            ops.layer_norm(x, w, b, use_pallas=True),
            ops.layer_norm(x, w, b, use_pallas=False),
            rtol=1e-6, atol=1e-6,
        )

    def test_pallas_bwd_interpret_matches_xla(self):
        # rows=200 with cols=4096 gives block_rows=128 -> a ragged last
        # block, exercising the stage-1 partial-sum row masking of the
        # r5 Pallas backward (dx + two-stage dgamma/dbeta)
        x = jax.random.normal(jax.random.PRNGKey(5), (200, 4096),
                              jnp.float32)
        r = jax.random.normal(jax.random.PRNGKey(6), (200, 4096),
                              jnp.float32)
        w = jnp.full((4096,), 1.1)
        b = jnp.full((4096,), -0.2)

        def loss(up):
            def f(x, w, b):
                return jnp.sum(ops.layer_norm(x, w, b, use_pallas=up) * r)
            return f

        g1 = jax.grad(loss(True), argnums=(0, 1, 2))(x, w, b)
        g2 = jax.grad(loss(False), argnums=(0, 1, 2))(x, w, b)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-3)

    def test_bf16_output_dtype_follows_input(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 128)).astype(jnp.bfloat16)
        w = jnp.ones((128,), jnp.float32)
        b = jnp.zeros((128,), jnp.float32)
        y = ops.layer_norm(x, w, b)
        assert y.dtype == jnp.bfloat16

    def test_module_wrapper(self):
        m = ops.FusedLayerNorm(64)
        params = m.init()
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 5, 64))
        y = m.apply(params, x)
        assert y.shape == x.shape
        np.testing.assert_allclose(
            y, _ln_ref(x, params["weight"], params["bias"]), rtol=1e-5, atol=1e-5
        )

    def test_rms_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 128))
        w = jnp.full((128,), 2.0)
        ref = x / jnp.sqrt((x**2).mean(-1, keepdims=True) + 1e-5) * 2.0
        np.testing.assert_allclose(ops.rms_norm(x, w), ref, rtol=1e-5, atol=1e-5)


class TestFusedSoftmax:
    def test_masked_matches_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 16))
        mask = jax.random.bernoulli(jax.random.PRNGKey(1), 0.3, (2, 1, 8, 16))
        scale = 0.5
        ref = jax.nn.softmax(jnp.where(mask, -10000.0, x * scale), axis=-1)
        np.testing.assert_allclose(
            ops.scaled_masked_softmax(x, mask, scale), ref, rtol=1e-5, atol=1e-6
        )

    def test_causal_matches_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 2, 16, 16))
        tri = jnp.tril(jnp.ones((16, 16), bool))
        ref = jax.nn.softmax(jnp.where(tri, x * 2.0, -10000.0), axis=-1)
        np.testing.assert_allclose(
            ops.scaled_upper_triang_masked_softmax(x, 2.0), ref, rtol=1e-5, atol=1e-6
        )

    def test_grad_matches_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 2, 8, 8))

        def f_fused(x):
            return jnp.sum(ops.scaled_upper_triang_masked_softmax(x, 1.7) ** 2)

        def f_ref(x):
            tri = jnp.tril(jnp.ones((8, 8), bool))
            return jnp.sum(jax.nn.softmax(jnp.where(tri, x * 1.7, -10000.0), -1) ** 2)

        np.testing.assert_allclose(
            jax.grad(f_fused)(x), jax.grad(f_ref)(x), rtol=1e-4, atol=1e-5
        )

    def test_wrapper_fused_vs_unfused(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 8)).astype(jnp.bfloat16)
        mask = jax.random.bernoulli(jax.random.PRNGKey(1), 0.2, (2, 1, 8, 8))
        fused = ops.FusedScaleMaskSoftmax(
            input_in_bf16=True, scaled_masked_softmax_fusion=True)
        unfused = ops.FusedScaleMaskSoftmax(
            input_in_bf16=True, scaled_masked_softmax_fusion=False)
        np.testing.assert_allclose(
            np.asarray(fused(x, mask), np.float32),
            np.asarray(unfused(x, mask), np.float32), rtol=1e-2, atol=1e-2)

    def test_wrapper_scale_requires_fp32(self):
        with pytest.raises(ValueError):
            ops.FusedScaleMaskSoftmax(softmax_in_fp32=False, scale=2.0)


class TestXentropy:
    def test_matches_reference_no_smoothing(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (16, 100))
        labels = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 100)
        ref = -jax.nn.log_softmax(logits)[jnp.arange(16), labels]
        np.testing.assert_allclose(
            ops.softmax_cross_entropy_loss(logits, labels), ref, rtol=1e-5, atol=1e-5
        )

    def test_matches_reference_smoothing(self):
        s = 0.1
        logits = jax.random.normal(jax.random.PRNGKey(0), (8, 50))
        labels = jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 50)
        logp = jax.nn.log_softmax(logits)
        onehot = jax.nn.one_hot(labels, 50)
        target = (1 - s) * onehot + s / 50
        ref = -(target * logp).sum(-1)
        np.testing.assert_allclose(
            ops.softmax_cross_entropy_loss(logits, labels, s), ref,
            rtol=1e-5, atol=1e-5)

    def test_grad_matches_reference(self):
        s = 0.2
        logits = jax.random.normal(jax.random.PRNGKey(0), (8, 30))
        labels = jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 30)

        def f_fused(z):
            return ops.softmax_cross_entropy_loss(z, labels, s).mean()

        def f_ref(z):
            logp = jax.nn.log_softmax(z)
            target = (1 - s) * jax.nn.one_hot(labels, 30) + s / 30
            return -(target * logp).sum(-1).mean()

        np.testing.assert_allclose(
            jax.grad(f_fused)(logits), jax.grad(f_ref)(logits), rtol=1e-4, atol=1e-6
        )

    def test_half_to_float(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 10)).astype(jnp.bfloat16)
        labels = jnp.array([1, 2, 3, 4])
        assert ops.softmax_cross_entropy_loss(
            logits, labels, 0.0, True).dtype == jnp.float32
        assert ops.softmax_cross_entropy_loss(
            logits, labels, 0.0, False).dtype == jnp.bfloat16


class TestDenseAndMLP:
    def test_fused_dense(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 32))
        layer = ops.FusedDense(32, 16)
        p = layer.init(jax.random.PRNGKey(1))
        np.testing.assert_allclose(
            layer.apply(p, x), x @ p["weight"].T + p["bias"], rtol=1e-5, atol=1e-5
        )

    def test_fused_dense_gelu_dense(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
        layer = ops.FusedDenseGeluDense(16, 64, 16)
        p = layer.init(jax.random.PRNGKey(1))
        h = x @ p["dense1"]["weight"].T + p["dense1"]["bias"]
        ref = jax.nn.gelu(h, approximate=True) @ p["dense2"]["weight"].T + p["dense2"]["bias"]
        np.testing.assert_allclose(layer.apply(p, x), ref, rtol=1e-5, atol=1e-5)

    def test_mlp_matches_linear_stack(self):
        # reference tests/L0/run_mlp/test_mlp.py: MLP vs nn.Linear sequence
        sizes = [40, 30, 20, 10]
        m = ops.MLP(sizes, activation="relu")
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 40))
        h = x
        for i, layer in enumerate(p):
            h = h @ layer["weight"].T + layer["bias"]
            if i != len(p) - 1:
                h = jax.nn.relu(h)
        np.testing.assert_allclose(m.apply(p, x), h, rtol=1e-5, atol=1e-5)

    def test_mlp_grads(self):
        m = ops.MLP([16, 16, 4], bias=True, activation="sigmoid")
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))

        def loss(p):
            return jnp.sum(m.apply(p, x) ** 2)

        g = jax.grad(loss)(p)
        leaves = jax.tree_util.tree_leaves(g)
        assert all(bool(jnp.all(jnp.isfinite(l))) for l in leaves)
        assert max(float(jnp.abs(l).max()) for l in leaves) > 0
