"""One realistically-shaped model-parallel train step on the emulated mesh.

The toy-scale GPT tests (hidden 32, seq 8) verify wiring but cannot catch
sharding-divisibility, padding, or remat-boundary bugs that only appear at
real tiling grains (VERDICT r1 weak #6).  This runs a single 3D
TP2×PP2×DP2 training step at transformer-realistic dimensions — hidden
1024 (head dim 64, 8 heads per TP shard), seq 512, vocab 8192 — slow on
CPU (~1 min) but shape-honest.
"""

import jax
import jax.numpy as jnp
import pytest
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, optimizers
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving,
)
from apex_tpu.transformer.testing import (
    GPTConfig,
    GPTModel,
    make_gpt_stage_fns,
)

# whole-module slow tier (ISSUE 2 CI satellite): the realistically-
# shaped 8-device 3D step is the single largest mesh test (~40 s)
pytestmark = pytest.mark.slow

TP, PP, DP = 2, 2, 2
SEQ, VOCAB, HIDDEN, HEADS = 512, 8192, 1024, 16
N_MICRO, MBS = 2, 1


def test_3d_train_step_realistic_dims():
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        TP, PP, devices=jax.devices()[:8])

    cfg = GPTConfig(num_layers=2, hidden_size=HIDDEN,
                    num_attention_heads=HEADS, vocab_size=VOCAB,
                    max_position_embeddings=SEQ, tp_size=TP)
    cfg1 = GPTConfig(num_layers=2, hidden_size=HIDDEN,
                     num_attention_heads=HEADS, vocab_size=VOCAB,
                     max_position_embeddings=SEQ, tp_size=1)
    stage_fn, loss_fn = make_gpt_stage_fns(cfg, PP)
    per_layer = cfg.num_layers // PP
    master = GPTModel(cfg1).init_master(jax.random.PRNGKey(0))

    def stage_params(s, r):
        m = {**master, "transformer": {"layers": jax.tree_util.tree_map(
            lambda a: a[s * per_layer:(s + 1) * per_layer],
            master["transformer"]["layers"])}}
        return GPTModel(cfg, num_layers=per_layer).shard_master(m, r)

    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[jax.tree_util.tree_map(
            lambda *ys: jnp.stack(ys),
            *[stage_params(s, r) for r in range(TP)]) for s in range(PP)])

    opt = optimizers.FusedAdam(lr=1e-4)
    opt_state = opt.init(stacked)

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (DP, N_MICRO, MBS, SEQ), 0, VOCAB)
    labels = jnp.roll(tokens, -1, axis=-1)

    @jax.jit
    def train_step(p, opt_state, tokens, labels):
        def run(p, t, l):
            p_local = jax.tree_util.tree_map(lambda a: a[0, 0], p)
            mb = {"tokens": t[0], "labels": l[0]}
            loss, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, p_local, mb,
                n_microbatches=N_MICRO,
                tensor_shape=(MBS, SEQ, cfg.hidden_size))
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, "data"), grads)
            loss = jax.lax.pmean(loss, "data")
            return loss, jax.tree_util.tree_map(
                lambda g: g[None, None], grads)

        loss, grads = shard_map(
            run, mesh=mesh,
            in_specs=(P("pipeline", "tensor"), P("data"), P("data")),
            out_specs=(P(), P("pipeline", "tensor")),
            check_rep=False)(p, tokens, labels)
        new_p, new_opt = opt.step(grads, opt_state, p)
        return new_p, new_opt, loss

    p, opt_state, loss = train_step(stacked, opt_state, tokens, labels)
    loss = float(loss)
    parallel_state.destroy_model_parallel()
    # random-init CE over vocab 8192 ≈ ln(8192) ≈ 9.01; a broken sharding
    # (e.g. head-dim padding corruption) shifts this far away
    assert np.isfinite(loss), loss
    assert 7.0 < loss < 11.0, loss
    # grads flowed through every stage/shard
    some_grad = jax.tree_util.tree_leaves(p)[0]
    assert np.all(np.isfinite(np.asarray(some_grad, np.float32)))
