"""ZeRO sharded-optimizer tests on the 8-device mesh + fp16_utils tier.

Mirrors reference tests: tests/L0/run_optimizers/test_dist_adam.py (sharded
vs unsharded parity), contrib DistributedFusedLAMB paths, fp16util tests
(tests/L0/run_fp16util/).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import fp16_utils, optimizers
from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:N_DEV]), ("data",))


def _params(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w1": jax.random.normal(k1, (33, 7)),  # deliberately unaligned sizes
        "w2": jax.random.normal(k2, (129,)),
        "b": jax.random.normal(k3, (5, 3)),
    }


class TestDistributedFusedAdam:
    def test_matches_unsharded_fused_adam(self, mesh):
        # reference test_dist_adam.py: sharded optimizer == unsharded Adam
        params = _params(jax.random.PRNGKey(0))
        grads = _params(jax.random.PRNGKey(1))

        dopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01)
        schema = dopt.make_schema(params, N_DEV)

        def step_fn(p, g):
            state = dopt.init(p, schema, N_DEV)
            # per-device grads: same grads on every device, grad_average
            # divides the psum back to the original values
            new_p, _ = dopt.step(g, state, p, schema)
            return new_p

        out = shard_map(step_fn, mesh=mesh, in_specs=(P(), P()),
                        out_specs=P(), check_rep=False)(params, grads)

        ref_opt = optimizers.FusedAdam(lr=1e-2, weight_decay=0.01,
                                       adam_w_mode=True)
        ref_state = ref_opt.init(params)
        ref_p, _ = ref_opt.step(grads, ref_state, params)
        for k in params:
            np.testing.assert_allclose(out[k], ref_p[k], rtol=1e-5, atol=1e-6)

    def test_matches_unsharded_classic_adam_l2_decay(self, mesh):
        """adam_w_mode=False: L2 decay folds into the grad BEFORE the moment
        updates (reference AdamFunctor ADAM_MODE_1, multi_tensor_adam.cu)."""
        params = _params(jax.random.PRNGKey(2))
        grads = _params(jax.random.PRNGKey(3))

        dopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.05,
                                    adam_w_mode=False)
        schema = dopt.make_schema(params, N_DEV)

        def step_fn(p, g):
            state = dopt.init(p, schema, N_DEV)
            new_p, _ = dopt.step(g, state, p, schema)
            return new_p

        out = shard_map(step_fn, mesh=mesh, in_specs=(P(), P()),
                        out_specs=P(), check_rep=False)(params, grads)

        ref_opt = optimizers.FusedAdam(lr=1e-2, weight_decay=0.05,
                                       adam_w_mode=False)
        ref_p, _ = ref_opt.step(grads, ref_opt.init(params), params)
        for k in params:
            np.testing.assert_allclose(out[k], ref_p[k], rtol=1e-5, atol=1e-6)

    def test_multi_step_convergence(self, mesh):
        params = _params(jax.random.PRNGKey(0))
        target = _params(jax.random.PRNGKey(7))
        dopt = DistributedFusedAdam(lr=5e-2)
        schema = dopt.make_schema(params, N_DEV)

        @jax.jit
        def train_step(p, state):
            def inner(p, state):
                # strip the leading per-device axis: each rank keeps ITS OWN
                # exp_avg/exp_avg_sq shard across steps (P("data") on both
                # specs), not a replicated copy of rank 0's
                state = jax.tree_util.tree_map(lambda a: a[0], state)
                grads = jax.tree_util.tree_map(lambda a, t: a - t, p, target)
                new_p, new_s = dopt.step(grads, state, p, schema)
                return new_p, jax.tree_util.tree_map(lambda a: a[None], new_s)
            return shard_map(inner, mesh=mesh, in_specs=(P(), P("data")),
                             out_specs=(P(), P("data")),
                             check_rep=False)(p, state)

        state0 = dopt.init(params, schema, N_DEV)
        state = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a, (N_DEV, *a.shape)), state0)

        def dist(p):
            return sum(float(jnp.sum((p[k] - target[k]) ** 2)) for k in p)

        # all 50 steps inside ONE dispatch: repeated host dispatches of the
        # 8-device CPU executable abort intermittently in the runtime's
        # collective thread pool (observed ~2/5 full-suite runs)
        @jax.jit
        def train_50(p, state):
            def body(carry, _):
                p, state = carry
                return train_step(p, state), None

            (p, state), _ = jax.lax.scan(body, (p, state), None, length=50)
            return p, state

        d0 = dist(params)
        p, state = train_50(params, state)
        assert dist(p) < d0 * 0.2

    @pytest.mark.slow  # heaviest dtype-plan parity case (ISSUE 6 wall-clock)
    def test_dtype_plan_close_to_fp32(self, mesh):
        """The r6 memory-fit knobs (bf16 scatter/gather transport + bf16
        momentum storage — the gpt1p3b bf16_fit plan): update math stays
        fp32 inside the fused chain, so one step agrees with the
        all-fp32 optimizer to bf16-rounding tolerance."""
        params = _params(jax.random.PRNGKey(0))
        grads = _params(jax.random.PRNGKey(1))
        dopt = DistributedFusedAdam(
            lr=1e-2, scatter_dtype=jnp.bfloat16,
            gather_dtype=jnp.bfloat16, exp_avg_dtype=jnp.bfloat16)
        schema = dopt.make_schema(params, N_DEV)

        def inner(p, g):
            state = dopt.init(p, schema, N_DEV)
            assert state.exp_avg.dtype == jnp.bfloat16
            new_p, new_s = dopt.step(g, state, p, schema)
            assert new_s.exp_avg.dtype == jnp.bfloat16
            assert new_s.exp_avg_sq.dtype == jnp.float32
            return new_p

        out = shard_map(inner, mesh=mesh, in_specs=(P(), P()),
                        out_specs=P(), check_rep=False)(params, grads)
        ref = DistributedFusedAdam(lr=1e-2)

        def ref_inner(p, g):
            state = ref.init(p, schema, N_DEV)
            new_p, _ = ref.step(g, state, p, schema)
            return new_p

        out_r = shard_map(ref_inner, mesh=mesh, in_specs=(P(), P()),
                          out_specs=P(), check_rep=False)(params, grads)
        for k in params:
            # gathering fp32 params through bf16 transport quantizes the
            # values themselves: the bound is ~2 bf16 ulps relative
            # (one from the gather, one from the update diff).  In the
            # real fit plan params are STORED bf16, so this rounding is
            # the storage format, not an extra loss.
            np.testing.assert_allclose(out[k], out_r[k], rtol=2e-2,
                                       atol=1e-3)

    @pytest.mark.slow  # 8-device e5m2 transport parity (ISSUE 2 CI satellite)
    def test_e5m2_allgather_close(self, mesh):
        params = _params(jax.random.PRNGKey(0))
        grads = _params(jax.random.PRNGKey(1))
        dopt = DistributedFusedAdam(lr=1e-2, e5m2_allgather=True)
        ref = DistributedFusedAdam(lr=1e-2, e5m2_allgather=False)
        schema = dopt.make_schema(params, N_DEV)

        def run(opt):
            def inner(p, g):
                state = opt.init(p, schema, N_DEV)
                new_p, _ = opt.step(g, state, p, schema)
                return new_p
            return shard_map(inner, mesh=mesh, in_specs=(P(), P()),
                             out_specs=P(), check_rep=False)(params, grads)

        out_c, out_r = run(dopt), run(ref)
        for k in params:
            # e5m2 has ~2 mantissa bits: deltas agree to ~25% relative,
            # and the fp32 base is exactly preserved
            np.testing.assert_allclose(out_c[k], out_r[k], rtol=0.3,
                                       atol=1e-3)


class TestDistributedFusedLAMB:
    def test_step_moves_toward_target_with_clipping(self, mesh):
        params = _params(jax.random.PRNGKey(0))
        dopt = DistributedFusedLAMB(lr=1e-2, max_grad_norm=1.0)
        schema = dopt.make_schema(params, N_DEV)
        big_grads = jax.tree_util.tree_map(lambda a: a * 100.0, params)

        def inner(p, g):
            state = dopt.init(p, schema, N_DEV)
            new_p, _ = dopt.step(g, state, p, schema)
            return new_p

        out = shard_map(inner, mesh=mesh, in_specs=(P(), P()),
                        out_specs=P(), check_rep=False)(params, big_grads)
        # grad clipping must keep the update bounded despite x100 grads
        for k in params:
            delta = float(jnp.max(jnp.abs(out[k] - params[k])))
            assert delta < 0.1, (k, delta)
            assert delta > 0

    def test_replicated_output_across_ranks(self, mesh):
        params = _params(jax.random.PRNGKey(0))
        grads = _params(jax.random.PRNGKey(1))
        dopt = DistributedFusedLAMB(lr=1e-3)
        schema = dopt.make_schema(params, N_DEV)

        def inner(p, g):
            p = jax.tree_util.tree_map(lambda a: a[0], p)
            g = jax.tree_util.tree_map(lambda a: a[0], g)
            state = dopt.init(p, schema, N_DEV)
            new_p, _ = dopt.step(g, state, p, schema)
            return jax.tree_util.tree_map(lambda a: a[None], new_p)

        # stack outputs per device and check bitwise equality
        out = shard_map(inner, mesh=mesh, in_specs=(P("data"), P("data")),
                        out_specs=P("data"), check_rep=False)(
            jax.tree_util.tree_map(lambda a: jnp.broadcast_to(
                a, (N_DEV, *a.shape)), params),
            jax.tree_util.tree_map(lambda a: jnp.broadcast_to(
                a, (N_DEV, *a.shape)), grads))
        for k in params:
            base = np.asarray(out[k]).reshape(N_DEV, -1)
            for r in range(1, N_DEV):
                np.testing.assert_array_equal(base[0], base[r])


class TestFP16Utils:
    def test_network_to_half_keeps_bn_fp32(self):
        tree = {"conv": {"w": jnp.ones((4, 4))},
                "bn1": {"weight": jnp.ones((4,))}}
        half = fp16_utils.network_to_half(tree)
        assert half["conv"]["w"].dtype == jnp.bfloat16
        assert half["bn1"]["weight"].dtype == jnp.float32

    def test_master_model_sync(self):
        model = {"w": jnp.ones((3,), jnp.bfloat16)}
        master = {"w": jnp.full((3,), 1.5, jnp.float32)}
        synced = fp16_utils.master_params_to_model_params(model, master)
        assert synced["w"].dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(synced["w"], np.float32), 1.5)

    def test_fp16_optimizer_end_to_end(self):
        opt = fp16_utils.FP16_Optimizer(optimizers.FusedSGD(lr=0.5),
                                        dynamic_loss_scale=True)
        params = {"w": jnp.array([2.0, -3.0])}
        opt.load_params(params)

        def loss_fn(p, x):
            return jnp.sum((p["w"] * x) ** 2)

        x = jnp.array([1.0, 1.0])
        l0 = float(loss_fn(opt.master_params, x))
        for _ in range(5):
            half = opt.model_params()
            grads, finite = opt.backward(loss_fn, opt.master_params, x)
            opt.step(grads, finite)
        assert float(loss_fn(opt.master_params, x)) < l0

    def test_fp16_optimizer_skips_on_overflow(self):
        opt = fp16_utils.FP16_Optimizer(optimizers.FusedSGD(lr=0.5))
        params = {"w": jnp.array([1.0])}
        opt.load_params(params)
        before = opt.master_params["w"]
        scale_before = float(opt.loss_scale)

        def inf_loss(p, x):
            return jnp.sum(p["w"] * jnp.inf)

        grads, finite = opt.backward(inf_loss, opt.master_params,
                                     jnp.ones(1))
        assert not bool(finite)
        opt.step(grads, finite)
        np.testing.assert_array_equal(opt.master_params["w"], before)
        assert float(opt.loss_scale) == scale_before / 2.0

    def test_state_dict_roundtrip(self):
        opt = fp16_utils.FP16_Optimizer(optimizers.FusedSGD(lr=0.1))
        opt.load_params({"w": jnp.ones((2,))})
        sd = opt.state_dict()
        opt2 = fp16_utils.FP16_Optimizer(optimizers.FusedSGD(lr=0.1))
        opt2.load_state_dict(sd)
        np.testing.assert_array_equal(opt2.master_params["w"],
                                      opt.master_params["w"])

    def test_clip_master_grads(self):
        opt = fp16_utils.FP16_Optimizer(optimizers.FusedSGD(lr=0.1))
        grads = {"w": jnp.array([30.0, 40.0])}  # norm 50
        clipped, norm = opt.clip_master_grads(grads, max_norm=5.0)
        np.testing.assert_allclose(norm, 50.0, rtol=1e-6)
        np.testing.assert_allclose(
            jnp.linalg.norm(clipped["w"]), 5.0, rtol=1e-5)
