"""Test harness: force an 8-device CPU mesh before any test imports jax.

The reference tests multi-GPU behavior only with real GPUs under a launcher
(SURVEY.md §4); JAX lets the whole "distributed" tier run on emulated host
devices, so every test here — including 8-way data/tensor/pipeline-parallel
tests — runs on CPU in CI.

Note: this environment pre-imports jax at interpreter startup (sitecustomize)
with ``JAX_PLATFORMS`` pointing at the real TPU, so setting the env var here
is too late for the platform choice — use ``jax.config.update`` instead.
``XLA_FLAGS`` is still honored because the CPU backend only parses it at
first backend initialisation, which happens inside the tests.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import shutil  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def chaos_ckpt_dir(tmp_path):
    """Checkpoint dir for fault-injection tests, with crash-proof teardown.

    Chaos tests deliberately leave the checkpoint layer mid-operation
    (simulated preemption, injected write failures).  This fixture
    guarantees that no matter how the test ends: (1) any installed storage
    fault hook is cleared, (2) the background writer is drained with parked
    errors swallowed (one test's injected failure must not surface at the
    next test's fence), and (3) the directory — including ``.tmp`` crash
    artifacts — is removed."""
    d = tmp_path / "ckpt"
    try:
        yield d
    finally:
        from apex_tpu.checkpoint import checkpoint as _ckpt_mod
        from apex_tpu.resilience import async_checkpoint as _async

        _ckpt_mod.set_fault_hook(None)
        _async.drain(ignore_errors=True)
        shutil.rmtree(d, ignore_errors=True)
