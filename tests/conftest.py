"""Test harness: force an 8-device CPU mesh before jax initialises.

The reference tests multi-GPU behavior only with real GPUs under a launcher
(SURVEY.md §4); JAX lets the whole "distributed" tier run on emulated host
devices, so every test here — including 8-way data/tensor/pipeline-parallel
tests — runs on CPU in CI.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
