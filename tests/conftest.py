"""Test harness: force an 8-device CPU mesh before any test imports jax.

The reference tests multi-GPU behavior only with real GPUs under a launcher
(SURVEY.md §4); JAX lets the whole "distributed" tier run on emulated host
devices, so every test here — including 8-way data/tensor/pipeline-parallel
tests — runs on CPU in CI.

Note: this environment pre-imports jax at interpreter startup (sitecustomize)
with ``JAX_PLATFORMS`` pointing at the real TPU, so setting the env var here
is too late for the platform choice — use ``jax.config.update`` instead.
``XLA_FLAGS`` is still honored because the CPU backend only parses it at
first backend initialisation, which happens inside the tests.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
