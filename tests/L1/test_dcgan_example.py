"""Smoke test of the DCGAN amp example — the multi-model / multi-loss amp
consumer (reference examples/dcgan/main_amp.py, num_losses=3 semantics)."""

import importlib.util
import os
import sys


def _load_main():
    path = os.path.join(os.path.dirname(__file__), "..", "..",
                        "examples", "dcgan", "main_amp.py")
    spec = importlib.util.spec_from_file_location("dcgan_main_amp", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_dcgan_three_scaled_losses_train(capsys, monkeypatch):
    mod = _load_main()
    monkeypatch.setattr(sys, "argv",
                        ["main_amp.py", "--steps", "6", "--batch", "8",
                         "--opt-level", "O1"])
    mod.main()
    out = capsys.readouterr().out
    assert "loss_D" in out and "loss_G" in out and "done" in out
    # three independent dynamic scales reported (loss_id parity)
    assert out.count("65536.0") >= 3
