"""L1 trajectory harness (reference tests/L1/common/).

The reference L1 tier trains ResNet over the cross-product of
opt-level × keep_batchnorm_fp32 × loss_scale × fused-optimizer
(tests/L1/common/run_test.sh:29-60), dumps per-iteration loss, and asserts
**bitwise-equal** trajectories between equivalent runs
(tests/L1/common/compare.py:40-64). This harness provides the same
instrument for the TPU build: ``run_trajectory(RunConfig)`` returns the
per-step loss list for a tiny ResNet or GPT trained on deterministic
synthetic data, single-device or data-parallel over the emulated mesh.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp, optimizers
from apex_tpu.models import ResNet, ResNetConfig
from apex_tpu.ops import softmax_cross_entropy_loss
from apex_tpu.transformer.testing.standalone_gpt import GPTConfig, GPTModel


@dataclasses.dataclass(frozen=True)
class RunConfig:
    model: str = "resnet"  # "resnet" | "gpt"
    opt_level: str = "O2"
    loss_scale: Union[str, float] = "dynamic"
    keep_batchnorm_fp32: Optional[bool] = None
    optimizer: str = "adam"  # "adam" | "lamb" | "sgd"
    n_devices: int = 1  # data-parallel width (1 = single device)
    steps: int = 12
    seed: int = 0
    lr: float = 1e-2


_GLOBAL_BATCH = 8
_IMG, _CLASSES = 16, 10
_SEQ = 16


def _make_optimizer(cfg: RunConfig):
    if cfg.optimizer == "adam":
        return optimizers.FusedAdam(lr=cfg.lr, weight_decay=1e-4)
    if cfg.optimizer == "lamb":
        return optimizers.FusedLAMB(lr=cfg.lr, weight_decay=1e-4)
    if cfg.optimizer == "sgd":
        return optimizers.FusedSGD(lr=cfg.lr, momentum=0.9)
    raise ValueError(cfg.optimizer)


def _resnet_batch(step: int, seed: int):
    # two fixed batches cycled: convergence is visible on synthetic data
    # (fresh random labels every step have no learnable signal) while the
    # trajectory still exercises more than one input
    k = jax.random.fold_in(jax.random.PRNGKey(seed + 100), step % 2)
    x = jax.random.normal(k, (_GLOBAL_BATCH, _IMG, _IMG, 3), jnp.float32)
    y = jax.random.randint(jax.random.fold_in(k, 1), (_GLOBAL_BATCH,), 0, _CLASSES)
    return x, y


def _gpt_batch(step: int, seed: int, vocab: int):
    k = jax.random.fold_in(jax.random.PRNGKey(seed + 200), step % 2)
    tokens = jax.random.randint(k, (_GLOBAL_BATCH, _SEQ + 1), 0, vocab)
    return tokens[:, :-1], tokens[:, 1:]


def run_trajectory(cfg: RunConfig) -> List[float]:
    """Train ``cfg.steps`` steps, return the per-step (pre-update) losses —
    the per-iteration dump of reference tests/L1/common/main_amp.py."""
    amp_state = amp.initialize(
        cfg.opt_level,
        loss_scale=cfg.loss_scale,
        keep_batchnorm_fp32=cfg.keep_batchnorm_fp32,
    )
    opt = _make_optimizer(cfg)
    dp = cfg.n_devices > 1
    axis = "data" if dp else None

    if cfg.model == "resnet":
        model = ResNet(ResNetConfig(block_sizes=(1, 1), width=8,
                                    num_classes=_CLASSES, bn_axis_name=axis))
        params, model_state = model.init(jax.random.PRNGKey(cfg.seed))

        def loss_fn(p, st, x, y):
            logits, new_st = model.apply(p, st, x, training=True)
            return softmax_cross_entropy_loss(
                logits.astype(jnp.float32), y).mean(), new_st

        batch_fn = lambda i: _resnet_batch(i, cfg.seed)
    elif cfg.model == "gpt":
        if dp:
            raise NotImplementedError("L1 GPT runs single-device semantics")
        gcfg = GPTConfig(num_layers=2, hidden_size=32, num_attention_heads=2,
                         vocab_size=64, max_position_embeddings=_SEQ,
                         bf16=cfg.opt_level in ("O2", "O3"))
        model = GPTModel(gcfg)
        master = model.init_master(jax.random.PRNGKey(cfg.seed))
        params = model.shard_master(master, 0)
        model_state = {}

        def loss_fn(p, st, tokens, labels):
            loss = model.apply(p, tokens, labels=labels)
            return loss.mean(), st

        batch_fn = lambda i: _gpt_batch(i, cfg.seed, gcfg.vocab_size)
    else:
        raise ValueError(cfg.model)

    scaler = amp_state.scaler
    grad_fn = amp.scaled_value_and_grad(loss_fn, scaler, has_aux=True)

    def step_body(params, st, opt_state, scale_state, x, y):
        half = amp_state.cast_model(params)
        xc = amp_state.cast_inputs(x) if cfg.model == "resnet" else x
        (loss, new_st), grads, finite = grad_fn(scale_state, half, st, xc, y)
        if axis is not None:
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, axis), grads)
            finite = jax.lax.pmin(finite.astype(jnp.int32), axis) > 0
            # reported loss is the global-batch mean (reference
            # average_losses_across_data_parallel_group)
            loss = jax.lax.pmean(loss, axis)
        new_params, new_opt = opt.step(grads, opt_state, params)
        params, opt_state = amp.skip_or_step(
            finite, (new_params, new_opt), (params, opt_state))
        scale_state = scaler.update(scale_state, finite)
        return params, new_st, opt_state, scale_state, loss

    opt_state = opt.init(params)
    scale_state = scaler.init()

    if dp:
        mesh = Mesh(np.asarray(jax.devices()[: cfg.n_devices]), ("data",))
        sharded = shard_map(
            step_body, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P(), P(), P()),
            check_rep=False)
        step = jax.jit(sharded)
    elif cfg.model == "gpt":
        # the TP layers resolve a "tensor" axis even at tp=1: run the step
        # replicated inside the parallel_state world mesh (the pattern of
        # tests/L0/test_megatron_models.py)
        from apex_tpu.transformer import parallel_state

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(1, 1)
        n_args = 6

        def replicated(*args):
            return shard_map(
                step_body, mesh=mesh, in_specs=(P(),) * n_args,
                out_specs=(P(),) * 5, check_rep=False)(*args)

        step = jax.jit(replicated)
    else:
        step = jax.jit(step_body)

    losses = []
    st = model_state
    for i in range(cfg.steps):
        x, y = batch_fn(i)
        params, st, opt_state, scale_state, loss = step(
            params, st, opt_state, scale_state, x, y)
        losses.append(float(loss))
    return losses


def run_flagship_trajectory(steps: int = 8, seed: int = 0) -> List[float]:
    """Per-step losses of the 1.3B-config flagship construction at toy
    width/depth (d=128 head geometry, ZeRO bf16_fit plan over the
    8-device emulated mesh) — the golden-trajectory cell covering the
    gpt1p3b bench path (ISSUE 2 satellite)."""
    import jax

    from apex_tpu.transformer.testing import (
        build_flagship_train_step, gpt1p3b_config)

    cfg = gpt1p3b_config(num_layers=2, hidden_size=256,
                         num_attention_heads=2, vocab_size=512,
                         max_position_embeddings=32)
    fs = build_flagship_train_step(cfg, plan="bf16_fit", lr=1e-3,
                                   devices=jax.devices()[:8],
                                   seed=seed, donate=False)
    p, s = fs.params, fs.opt_state
    losses = []
    for i in range(steps):
        k = jax.random.fold_in(jax.random.PRNGKey(seed + 300), i % 2)
        tokens = jax.random.randint(k, (8, cfg.max_position_embeddings),
                                    0, cfg.vocab_size)
        labels = jnp.roll(tokens, -1, axis=-1)
        p, s, loss = fs.step(p, s, tokens, labels)
        losses.append(float(loss))
    return losses


def write_toy_token_shards(work_dir: str, *, seq: int = 32,
                           vocab: int = 512, n_per_shard: int = 32,
                           n_shards: int = 2):
    """The deterministic checksummed token dataset of the data-pipeline
    golden cell (ISSUE 7): ``n_shards`` files of ``n_per_shard`` uint32
    token records (seq+1 ids each), seeded so every regeneration is
    byte-identical.  Returns ``(paths, record_bytes, decode)`` with
    ``decode`` mapping a payload matrix to (tokens, labels) jnp arrays."""
    from apex_tpu.data import write_checksummed_records

    rng = np.random.RandomState(41)
    paths, rb = [], None
    for s in range(n_shards):
        toks = rng.randint(0, vocab,
                           size=(n_per_shard, seq + 1)).astype(np.uint32)
        p = os.path.join(work_dir, f"tokens_{s}.bin")
        rb = write_checksummed_records(
            p, toks.view(np.uint8).reshape(n_per_shard, -1))
        paths.append(p)

    def decode(mat):
        ids = np.ascontiguousarray(mat).view(np.uint32).reshape(
            mat.shape[0], seq + 1).astype(np.int32)
        ids = ids % vocab
        return jnp.asarray(ids[:, :-1]), jnp.asarray(ids[:, 1:])

    return paths, rb, decode


def run_flagship_data_trajectory(work_dir: str,
                                 steps: int = 6) -> List[float]:
    """Per-step losses of the toy ZeRO flagship fed by the
    fault-tolerant record pipeline (ShardedRecordIterator over the
    :func:`write_toy_token_shards` dataset) — the golden cell the
    exactly-once kill/resume tests replay against (ISSUE 7)."""
    from apex_tpu.data import ShardedRecordIterator
    from apex_tpu.transformer.testing import (
        build_flagship_train_step, gpt1p3b_config)

    cfg = gpt1p3b_config(num_layers=2, hidden_size=256,
                         num_attention_heads=2, vocab_size=512,
                         max_position_embeddings=32)
    paths, rb, decode = write_toy_token_shards(work_dir)
    it = ShardedRecordIterator(paths, rb, 8, checksummed=True,
                               shuffle_window=16, seed=5,
                               num_batches=steps, decode=decode)
    fs = build_flagship_train_step(cfg, plan="bf16_fit", lr=1e-3,
                                   devices=jax.devices()[:8],
                                   seed=0, donate=False)
    p, s = fs.params, fs.opt_state
    losses = []
    for tokens, labels in it:
        p, s, loss = fs.step(p, s, tokens, labels)
        losses.append(float(loss))
    return losses


def run_bert_trajectory(steps: int = 6, seed: int = 0) -> List[float]:
    """Per-step losses of a toy BERT MLM run over PACKED varlen inputs
    (segment ids + per-segment positions) through the flash path — the
    golden-trajectory cell covering the r7 varlen fast path and the
    bert_large bench construction end-to-end (ISSUE 5 satellite)."""
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.testing import BertConfig, BertModel

    seq = 16
    cfg = BertConfig(num_layers=2, hidden_size=32, num_attention_heads=2,
                     vocab_size=64, max_position_embeddings=seq,
                     tp_size=1, use_flash_attention=True,
                     add_binary_head=False, num_tokentypes=0)
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(1, 1)
    model = BertModel(cfg)
    params = model.shard_master(model.init_master(
        jax.random.PRNGKey(seed)), 0)
    opt = optimizers.FusedAdam(lr=1e-2, weight_decay=1e-4)
    opt_state = opt.init(params)

    # two fixed packed batches cycled (the harness convention): rows of
    # two segments + a pad tail in its own bucket
    lens = [(6, 7), (5, 9)]

    def batch(i):
        k = jax.random.fold_in(jax.random.PRNGKey(seed + 400), i % 2)
        tokens = jax.random.randint(k, (_GLOBAL_BATCH, seq), 0,
                                    cfg.vocab_size)
        labels = jax.random.randint(jax.random.fold_in(k, 1),
                                    (_GLOBAL_BATCH, seq), 0,
                                    cfg.vocab_size)
        a, b = lens[i % 2]
        seg = jnp.asarray([0] * a + [1] * b + [2] * (seq - a - b),
                          jnp.int32)
        pos = jnp.asarray(list(range(a)) + list(range(b))
                          + [0] * (seq - a - b), jnp.int32)
        msk = jnp.asarray([1] * (a + b) + [0] * (seq - a - b), jnp.int32)
        tile = lambda x: jnp.broadcast_to(x[None], (_GLOBAL_BATCH, seq))
        return tokens, labels, tile(seg), tile(pos), tile(msk)

    def step_body(params, opt_state, tokens, labels, seg, pos, msk):
        def lossf(p):
            losses, _ = model.apply(p, tokens, lm_labels=labels,
                                    segment_ids=seg, position_ids=pos)
            m = msk.astype(jnp.float32)
            return jnp.sum(losses * m) / jnp.sum(m)

        loss, grads = jax.value_and_grad(lossf)(params)
        params, opt_state = opt.step(grads, opt_state, params)
        return params, opt_state, loss

    def replicated(*args):
        return shard_map(step_body, mesh=mesh, in_specs=(P(),) * 7,
                         out_specs=(P(),) * 3, check_rep=False)(*args)

    step = jax.jit(replicated)
    losses = []
    for i in range(steps):
        params, opt_state, loss = step(params, opt_state, *batch(i))
        losses.append(float(loss))
    parallel_state.destroy_model_parallel()
    return losses


# --- golden (stored) baselines ----------------------------------------------
#
# The reference's L1 compares runs against DUMPED baseline files
# (tests/L1/common/compare.py:40-64) so a numerics change between
# commits is caught; the same instrument here stores fp32-hex loss
# trajectories under tests/L1/baselines/ (VERDICT r5 missing #1).

BASELINE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "baselines")


def baseline_path(name: str) -> str:
    return os.path.join(BASELINE_DIR, f"{name}.json")


def load_baseline(name: str) -> Optional[List[float]]:
    """Stored trajectory, decoded from fp32 hex (exact), or None."""
    try:
        with open(baseline_path(name)) as f:
            rec = json.load(f)
    except FileNotFoundError:
        return None
    return [float.fromhex(h) for h in rec["losses_hex"]]


def save_baseline(name: str, traj: List[float], meta: str = "") -> None:
    os.makedirs(BASELINE_DIR, exist_ok=True)
    with open(baseline_path(name), "w") as f:
        json.dump({
            "meta": meta,
            # hex is the comparison format (bit-exact round-trip);
            # the decimal column is for human diff-reading only
            "losses_hex": [float(x).hex() for x in traj],
            "losses": [round(float(x), 6) for x in traj],
        }, f, indent=1)
        f.write("\n")


def compare_trajectories(a: List[float], b: List[float], *,
                         bitwise: bool = True, rtol: float = 1e-5):
    """Reference compare.py:40-64: bitwise where precision-identical,
    tight tolerance otherwise."""
    assert len(a) == len(b)
    if bitwise:
        mism = [(i, x, y) for i, (x, y) in enumerate(zip(a, b)) if x != y]
        assert not mism, f"trajectories diverge bitwise at {mism[:3]}"
    else:
        np.testing.assert_allclose(a, b, rtol=rtol)
