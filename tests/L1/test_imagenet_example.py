"""End-to-end smoke tests of the ImageNet training CLI (the examples tier —
reference examples/imagenet/main_amp.py driven by tests/L1/common/run_test.sh).
Runs the real main() with tiny shapes: train, checkpoint, resume, evaluate,
data-parallel."""

import importlib.util
import os
import sys

import pytest


def _load_main():
    path = os.path.join(os.path.dirname(__file__), "..", "..",
                        "examples", "imagenet", "main_amp.py")
    spec = importlib.util.spec_from_file_location("imagenet_main_amp", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


TINY = ["--arch", "resnet_tiny", "--image-size", "16", "--num-classes", "10",
        "-b", "8", "--steps-per-epoch", "6", "--eval-steps", "2",
        "--print-freq", "3", "--lr", "0.01"]


def test_train_eval_o2(capsys):
    mod = _load_main()
    state = mod.main(TINY + ["--epochs", "1", "--opt-level", "O2",
                             "--optimizer", "lamb"])
    out = capsys.readouterr().out
    assert "Prec@1" in out and "img/s" in out
    assert int(state.step) == 6


def test_checkpoint_resume(tmp_path, capsys):
    mod = _load_main()
    d = str(tmp_path / "ckpts")
    mod.main(TINY + ["--epochs", "1", "--save-dir", d])
    assert os.path.isdir(d)
    state = mod.main(TINY + ["--epochs", "2", "--save-dir", d, "--resume", d])
    out = capsys.readouterr().out
    assert "resumed" in out
    # resumed run trains only epoch 1 (6 more steps on top of the 6 saved)
    assert int(state.step) == 12


def test_evaluate_only(capsys):
    mod = _load_main()
    mod.main(TINY + ["--epochs", "1", "--evaluate"])
    out = capsys.readouterr().out
    assert "Prec@1" in out and "Epoch" not in out


@pytest.mark.slow  # 8-device SyncBN example run (~17 s) (ISSUE 2 CI satellite)
def test_data_parallel_sync_bn(capsys):
    mod = _load_main()
    state = mod.main(TINY + ["--epochs", "1", "--n-devices", "8", "--sync_bn",
                             "--opt-level", "O2"])
    assert int(state.step) == 6
    out = capsys.readouterr().out
    assert "Prec@1" in out


def test_bad_batch_split():
    mod = _load_main()
    with pytest.raises(ValueError):
        mod.main(TINY + ["--epochs", "1", "--n-devices", "3"])


def test_native_record_backend(tmp_path, capsys):
    """Train from packed record files through the C++ prefetching loader
    (the reference's DALI data-backend path)."""
    import numpy as np

    from apex_tpu.data import native_available, write_records
    if not native_available():
        pytest.skip("native toolchain unavailable")

    img, classes, n = 16, 10, 48
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (n, img * img * 3), dtype=np.int64)
    labels = rng.integers(0, classes, (n,), dtype=np.int64)
    recs = np.concatenate(
        [imgs.astype(np.uint8),
         labels.astype(np.int32).view(np.uint8).reshape(n, 4)], axis=1)
    write_records(str(tmp_path / "train0.rec"), recs)

    mod = _load_main()
    state = mod.main(TINY + ["--epochs", "1", "--data", str(tmp_path)])
    assert int(state.step) == 6
    out = capsys.readouterr().out
    assert "Prec@1" in out
