"""Golden loss-trajectory regression against COMMITTED baselines.

VERDICT r5 "What's missing" #1 / ISSUE 2 satellite: the same-process
bitwise checks in test_cross_product.py catch nondeterminism but not
drift introduced by a code change *between commits* — the reference's
L1 catches exactly that by diffing against dumped baseline files
(/root/reference/tests/L1/common/compare.py:40-64).  Here every
cross-product cell (plus the 1.3B-flagship toy cell) is compared
fp32-bit-exactly against ``tests/L1/baselines/<cell>.json``.

Regeneration protocol (one line) — after an INTENDED numerics change::

    REGEN_BASELINES=1 python -m pytest tests/L1/test_golden_trajectories.py -q

then commit the baseline diff; the changed cells name exactly what
moved.  Baselines are recorded on the tier-1 platform (CPU,
JAX_PLATFORMS=cpu, emulated 8-device mesh); bit-exactness is a
per-platform+jax-version contract, which is the CI environment's.
"""

import os

import pytest

from tests.L1.common.harness import (
    RunConfig,
    load_baseline,
    run_bert_trajectory,
    run_flagship_trajectory,
    run_trajectory,
    save_baseline,
)

REGEN = os.environ.get("REGEN_BASELINES", "0") == "1"

# the L1 cross-product cells (test_cross_product.py), abbreviated to the
# determinism-tested opt levels plus both optimizers; steps kept short —
# drift shows up in step 1, not step 12
CELLS = {
    "resnet_o0_adam": RunConfig(model="resnet", opt_level="O0",
                                loss_scale=1.0, steps=6),
    "resnet_o2_adam": RunConfig(model="resnet", opt_level="O2", steps=6),
    "resnet_o2_lamb": RunConfig(model="resnet", opt_level="O2",
                                optimizer="lamb", steps=6),
    "resnet_o3_adam": RunConfig(model="resnet", opt_level="O3",
                                loss_scale=1.0, steps=6),
    "gpt_o0_adam": RunConfig(model="gpt", opt_level="O0", steps=6,
                             lr=5e-3),
    "gpt_o2_adam": RunConfig(model="gpt", opt_level="O2", steps=6,
                             lr=5e-3),
}


def _check(name, traj):
    if REGEN:
        save_baseline(name, traj, meta=f"cell {name}; see module "
                      "docstring for the regeneration protocol")
        pytest.skip(f"baseline {name} regenerated — commit the diff")
    stored = load_baseline(name)
    assert stored is not None, (
        f"no committed baseline for {name}: run REGEN_BASELINES=1 "
        "python -m pytest tests/L1/test_golden_trajectories.py and "
        "commit tests/L1/baselines/")
    mism = [(i, a, b) for i, (a, b) in enumerate(zip(traj, stored))
            if a != b]
    assert len(traj) == len(stored) and not mism, (
        f"{name}: trajectory drifted from the committed baseline at "
        f"{mism[:3]} — if the numerics change is intended, regenerate "
        "(module docstring) and commit the baseline diff")


@pytest.mark.parametrize("name", sorted(CELLS))
def test_golden_trajectory(name):
    _check(name, run_trajectory(CELLS[name]))


def test_golden_trajectory_bert_toy_varlen():
    """Toy BERT MLM over packed varlen inputs (segment ids + restarting
    positions, flash path) — covers the r7 varlen fast path and the
    bert_large bench construction (ISSUE 5 satellite)."""
    _check("bert_toy_varlen", run_bert_trajectory(steps=6))


def test_golden_trajectory_gpt1p3b_toy():
    """The flagship construction (d=128 head geometry, ZeRO bf16_fit
    over the emulated mesh) at toy depth — covers the gpt1p3b bench
    path end-to-end (ISSUE 2 satellite)."""
    _check("gpt1p3b_toy_zero", run_flagship_trajectory(steps=6))


def test_golden_trajectory_gpt1p3b_toy_data(tmp_path):
    """The toy flagship fed by the fault-tolerant record pipeline
    (deterministic checksummed shards → ShardedRecordIterator) — the
    golden the ISSUE 7 exactly-once kill/resume tests replay against:
    any drift here means the data stream, not just the step, changed."""
    from tests.L1.common.harness import run_flagship_data_trajectory

    _check("gpt1p3b_toy_data", run_flagship_data_trajectory(str(tmp_path)))
