"""L1: GPT trains WITH dropout (attention in-kernel + hidden) — loss
decreases, step is jittable, eval mode is deterministic.  The
convergence-tier companion of the L0 mask-property tests."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import optimizers
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, GPTModel


@pytest.mark.slow  # dropout training convergence (~28 s) (ISSUE 2 CI satellite)
def test_gpt_trains_with_dropout():
    cfg = GPTConfig(num_layers=2, hidden_size=64, num_attention_heads=4,
                    vocab_size=128, max_position_embeddings=32,
                    attention_dropout=0.1, hidden_dropout=0.1,
                    use_flash_attention=True, remat=True)
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(1, 1)
    model = GPTModel(cfg)
    params = model.shard_master(model.init_master(jax.random.PRNGKey(0)), 0)
    opt = optimizers.FusedAdam(lr=3e-3)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
    labels = jnp.roll(tokens, -1, axis=-1)

    @jax.jit
    def step(p, o, key):
        def lossf(p):
            return shard_map(
                lambda p, t, l: jnp.mean(model.apply(
                    p, t, labels=l, dropout_key=key)),
                mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
                check_rep=False)(p, tokens, labels)

        loss, g = jax.value_and_grad(lossf)(p)
        p, o = opt.step(g, o, p)
        return p, o, loss

    key = jax.random.PRNGKey(2)
    first = None
    for i in range(30):
        params, opt_state, loss = step(params, opt_state,
                                       jax.random.fold_in(key, i))
        if first is None:
            first = float(loss)
    assert np.isfinite(float(loss))
    assert float(loss) < first * 0.8, (first, float(loss))

    # eval (no dropout key): bitwise deterministic
    @jax.jit
    def evaluate(p):
        return shard_map(
            lambda p, t, l: jnp.mean(model.apply(p, t, labels=l)),
            mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            check_rep=False)(p, tokens, labels)

    assert float(evaluate(params)) == float(evaluate(params))
    parallel_state.destroy_model_parallel()
