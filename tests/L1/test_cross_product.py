"""L1 cross-product convergence/parity tier.

Mirrors reference tests/L1: ResNet (and toy GPT) trained across
opt-level × loss-scale × fused-optimizer (run_test.sh:29-60), trajectories
compared bitwise between equivalent variants (compare.py:40-64) and checked
for convergence everywhere.
"""

import pytest

from tests.L1.common.harness import RunConfig, compare_trajectories, run_trajectory

OPT_LEVELS = ["O0", "O1", "O2", "O3"]
OPTIMIZERS = ["adam", "lamb"]


@pytest.mark.parametrize("opt_level", OPT_LEVELS)
@pytest.mark.parametrize("optimizer", OPTIMIZERS)
def test_resnet_cross_product_converges(opt_level, optimizer):
    """Every cell of the cross-product trains: finite losses, net decrease
    (the run_test.sh sweep, pass/fail = trained-at-all + parity below)."""
    traj = run_trajectory(RunConfig(
        model="resnet", opt_level=opt_level, optimizer=optimizer,
        loss_scale="dynamic" if opt_level in ("O1", "O2") else 1.0))
    assert all(l == l and l < 1e4 for l in traj), traj  # finite
    # two batches cycle; compare parity-aligned steps
    assert traj[-2] < traj[0] and traj[-1] < traj[1], traj


@pytest.mark.parametrize("opt_level", [
    "O0",
    # O2 cell: same bitwise-determinism machinery at amp dtypes —
    # heaviest duplicate of the O0 cell (ISSUE 6 wall-clock tier)
    pytest.param("O2", marks=pytest.mark.slow),
])
def test_resnet_determinism_bitwise(opt_level):
    """Same config twice → bitwise-identical loss trajectory — the
    compare.py discipline that catches nondeterminism (the reference needs
    this to compare ext vs no-ext builds)."""
    cfg = RunConfig(model="resnet", opt_level=opt_level)
    compare_trajectories(run_trajectory(cfg), run_trajectory(cfg), bitwise=True)


def test_resnet_dynamic_vs_static_scale_bitwise():
    """Dynamic scaling at init 2^16 with no overflows == static 2^16,
    bitwise (the scale value is the only thing the state machine changes,
    and short clean runs never hit the growth window)."""
    dyn = run_trajectory(RunConfig(model="resnet", opt_level="O2",
                                   loss_scale="dynamic"))
    static = run_trajectory(RunConfig(model="resnet", opt_level="O2",
                                      loss_scale=2.0 ** 16))
    compare_trajectories(dyn, static, bitwise=True)


@pytest.mark.slow  # keep-bn-fp32 convergence cells (~18 s) (ISSUE 2 CI satellite)
def test_resnet_keep_batchnorm_fp32_variants_converge():
    """keep_batchnorm_fp32 axis of the reference cross-product."""
    for keep in (True, False):
        traj = run_trajectory(RunConfig(model="resnet", opt_level="O2",
                                        keep_batchnorm_fp32=keep, steps=8))
        assert traj[-2] < traj[0]


def test_resnet_master_weights_drift_o2_vs_o0():
    """O2 (bf16 compute, fp32 master) must track O0 (fp32) loosely — the
    loss-parity sanity the reference checks across opt levels."""
    o0 = run_trajectory(RunConfig(model="resnet", opt_level="O0",
                                  loss_scale=1.0))
    o2 = run_trajectory(RunConfig(model="resnet", opt_level="O2"))
    # same trend, bf16-level tolerance
    assert abs(o0[-1] - o2[-1]) < 0.15 * max(abs(o0[0]), 1.0)


@pytest.mark.parametrize("opt_level", [
    "O0",
    # O2 cell: the amp-variant convergence duplicate (ISSUE 6
    # wall-clock tier; the slow tier still runs it)
    pytest.param("O2", marks=pytest.mark.slow),
])
def test_gpt_converges_and_deterministic(opt_level):
    cfg = RunConfig(model="gpt", opt_level=opt_level, steps=10, lr=5e-3)
    a = run_trajectory(cfg)
    assert a[-2] < a[0], a
    compare_trajectories(a, run_trajectory(cfg), bitwise=True)


@pytest.mark.slow  # GPT scale-state bitwise cell (~19 s) (ISSUE 2 CI satellite)
def test_gpt_dynamic_vs_static_scale_bitwise():
    dyn = run_trajectory(RunConfig(model="gpt", opt_level="O2", steps=8,
                                   loss_scale="dynamic", lr=5e-3))
    static = run_trajectory(RunConfig(model="gpt", opt_level="O2", steps=8,
                                      loss_scale=2.0 ** 16, lr=5e-3))
    compare_trajectories(dyn, static, bitwise=True)
