"""L1 distributed parity: 8-way data-parallel trajectory vs single device
on the same global batch (reference tests/L1/cross_product_distributed/ —
2-process DDP runs compared against single-GPU baselines)."""

import pytest

from tests.L1.common.harness import RunConfig, compare_trajectories, run_trajectory


@pytest.mark.parametrize("opt_level,rtol", [
    # both 8-device parity cells now ride the slow tier (~12s each;
    # ISSUE 12 wall trim) — tier-1 keeps the dp8 machinery covered via
    # the flagship ZeRO parity cell and the L0 tensor-parallel tier
    pytest.param("O0", 2e-3, marks=pytest.mark.slow),
    # the O2 cell repeats the same 8-device parity at the slower mixed-
    # precision build — held for the slow tier (ISSUE 2 CI satellite)
    pytest.param("O2", 3e-2, marks=pytest.mark.slow),
])
def test_dp8_matches_single_device(opt_level, rtol):
    """Same global batch split 8 ways (SyncBN pools the stats, grads pmean):
    trajectory must match the 1-device run to fp reassociation tolerance
    (bf16 compute under O2 drifts faster than fp32, hence the wider rtol —
    step 0 is bitwise-identical in both modes)."""
    single = run_trajectory(RunConfig(model="resnet", opt_level=opt_level,
                                      loss_scale=1.0, steps=8))
    dp = run_trajectory(RunConfig(model="resnet", opt_level=opt_level,
                                  loss_scale=1.0, steps=8, n_devices=8))
    assert single[0] == dp[0]
    compare_trajectories(single, dp, bitwise=False, rtol=rtol)


@pytest.mark.slow  # 8-device DP bitwise determinism (~35 s) (ISSUE 2 CI satellite)
def test_dp8_deterministic_bitwise():
    cfg = RunConfig(model="resnet", opt_level="O2", steps=8, n_devices=8)
    compare_trajectories(run_trajectory(cfg), run_trajectory(cfg), bitwise=True)
