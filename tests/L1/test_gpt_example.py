"""examples/gpt/pretrain_gpt.py end-to-end on the emulated mesh: tp x dp
training, checkpoint at the end, resume continues from the saved step
(SURVEY.md L6 tier; reference run_megatron_gpt_pipeline.py role)."""
import os
import sys

import pytest

EX = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "examples", "gpt")


@pytest.fixture()
def pretrain():
    sys.path.insert(0, EX)
    import pretrain_gpt
    yield pretrain_gpt
    sys.path.remove(EX)


def _args(tmp, extra=()):
    return [
        "--tensor-model-parallel-size", "2",
        "--num-layers", "2", "--hidden-size", "64",
        "--num-attention-heads", "2", "--seq-length", "64",
        "--max-position-embeddings", "64",
        "--micro-batch-size", "2", "--train-iters", "6",
        "--lr", "1e-3", "--log-interval", "3", "--vocab-size", "512",
        "--bf16", "--save", tmp, *extra,
    ]


@pytest.mark.slow  # end-to-end GPT CLI train+resume (~40 s) (ISSUE 2 CI satellite)
def test_train_checkpoint_resume(pretrain, tmp_path):
    tmp = str(tmp_path / "ckpt")
    loss = pretrain.main(_args(tmp))
    assert loss == pytest.approx(loss)  # finite
    # a checkpoint at the final step exists and resume continues from it
    import apex_tpu.checkpoint as ckpt

    assert ckpt.latest_step(tmp) == 6
    loss2 = pretrain.main(_args(tmp, ("--load", tmp,
                                      "--train-iters", "8")))
    assert ckpt.latest_step(tmp) == 8
    assert loss2 == pytest.approx(loss2)
