"""BENCH-record regression gate: ``python -m apex_tpu.telemetry regress``.

The repo's perf trajectory is a sequence of committed ``BENCH_r*.json``
records (the driver's capture of ``bench.py``'s summary line).  Until
now comparing two of them was a human task; this module makes it an
exit-code CI gate:

    python -m apex_tpu.telemetry regress BENCH_r04.json BENCH_r05.json \\
        --max-regress 10

loads both records, pairs every numeric key present in both, decides
per key whether higher or lower is better (suffix/substring rules over
the repo's established key vocabulary — ``*_per_sec`` up, ``*_ms``
down, ...), and exits 1 if any *gated* key moved in the losing
direction by more than ``--max-regress`` percent.  Keys matching no
direction rule (batch sizes, config echoes, counters) are reported but
never gated — a gate that guesses directions would manufacture
failures.

Accepted file shapes: the driver's wrapped capture
(``{"parsed": {"metric", "value", "extras": {...}}}``), bench.py's raw
summary line (``{"metric", "value", "extras": {...}}``), or a flat
``{key: number}`` dict — so the gate also works on ad-hoc key files.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["load_bench_keys", "load_multichip_record", "key_direction",
           "compare_bench", "format_regress", "GATED_LOWER",
           "GATED_HIGHER"]

#: Lower-is-better key patterns (regex, searched): latency, wait,
#: skip/stall counts, memory peaks, exposed communication.  ``_p99``
#: (ISSUE 10) covers tail-latency keys that don't end in the
#: percentile (``serving_tpot_p99_overload``).
GATED_LOWER = (
    r"_ms$", r"_ms_p\d+$", r"_ms_per_step$", r"tpot", r"ttft",
    r"_wait_ms", r"_hbm_peak_gb$", r"peak_hbm_gb$", r"_hbm_gb$",
    r"exposed_collective_ms$", r"_phase_collective_ms$", r"_p99",
    # ISSUE 15: the bucketed-ZeRO wall family (e.g.
    # gpt3d_bucket_collective_ms).  Deliberately redundant with the
    # _ms$ suffix rule — this entry is DOCUMENTATION that the family
    # is load-bearing (the committed r15 pair gates on it; the
    # direction is pinned by test_bucket_ms_direction_rule), not extra
    # coverage: a key renamed off the _ms suffix un-gates either way.
    r"_bucket_\w*_ms$",
    # ISSUE 16: the fleet tail-latency family (fleet_ttft_p99_steady_ms
    # / fleet_ttft_p99_restart_ms).  Deliberately redundant with the
    # ttft/_ms$/_p99 rules above, same as the bucket family: this entry
    # DOCUMENTS that the committed r16 pair gates on the family (the
    # direction is pinned by test_fleet_key_direction_rules), it adds
    # no new coverage.
    r"fleet_ttft_\w*_ms$",
    # r17: pool occupancy high-water mark (serving_pool_peak, a
    # FRACTION of the page pool, not a byte count — the quantized-KV
    # headline: the committed r17 pair gates pool peak DOWN ≥ 40% on
    # the int8 pool).  Genuinely new coverage: no suffix rule above
    # matches it.  Direction pinned by test_pool_peak_direction_rule.
    r"_pool_peak$",
    # r18: disaggregation fallback rate (fleet_ship_fallback_rate /
    # serving_ship_fallback_rate) — the share of KV shipments that
    # exhausted their retry budget and degraded to local prefill.
    # Genuinely new coverage: no suffix rule above matches it (note
    # `_hit_rate$` is HIGHER — a fallback is a miss, not a hit).
    # Direction pinned by test_ship_fallback_rate_direction_rule; the
    # companion retry rate stays deliberately UNGATED (reported only):
    # the right retry count depends on the injected fault rate, so
    # the gate must not guess a direction for it.
    r"_ship_fallback_rate$",
    # r19: the TTFT decomposition family (fleet_ttft_queue_ms /
    # fleet_ttft_prefill_ms / fleet_ttft_ship_ms /
    # fleet_ttft_decode_wait_ms, and their serving_* summarize twins)
    # — span-derived attribution of WHERE the first-token wait went.
    # Deliberately redundant with the ttft/_ms$ rules above, same
    # contract as the bucket/fleet entries: this entry DOCUMENTS that
    # the committed r19 pair gates the family (direction pinned by
    # test_ttft_decomposition_direction_rules), it adds no new
    # coverage.
    r"ttft_\w*(queue|prefill|ship|decode_wait)_ms$",
)

#: Higher-is-better key patterns: throughput, efficiency, rooflines,
#: SLO attainment (``*_hit_rate``, ISSUE 10).  Note ``*_shed_rate`` is
#: DELIBERATELY unmatched: the right shed rate depends on the offered
#: load, so the gate reports it but must not guess a direction.
GATED_HIGHER = (
    r"_per_sec$", r"_tflops$", r"_mfu", r"goodput$", r"_speedup",
    r"_gb_s$", r"frac_of_roof$", r"frac_of_dot_floor$", r"_min_ratio$",
    r"_hit_rate$", r"_accepted_tokens_per_step$",
    # ISSUE 16: fleet aggregate throughput (documented-redundant with
    # _per_sec$, same contract as the fleet_ttft entry above)
    r"fleet_\w*_tokens_per_sec$",
    # r17: prefix-sharing hit rate (serving_prefix_hit_rate).
    # Deliberately redundant with _hit_rate$ above, same contract as
    # the fleet entries: this entry DOCUMENTS that the committed r17
    # pair gates the key UP (non-zero on the shared-prompt trace; the
    # direction is pinned by test_prefix_hit_rate_direction_rule), it
    # adds no new coverage.
    r"_prefix_hit_rate$",
)


def key_direction(key: str) -> Optional[str]:
    """``"higher"`` / ``"lower"`` when the key matches a gated pattern,
    None for informational keys the gate must not guess about."""
    for pat in GATED_LOWER:
        if re.search(pat, key):
            return "lower"
    for pat in GATED_HIGHER:
        if re.search(pat, key):
            return "higher"
    return None


def _flatten(prefix: str, obj: Any, out: Dict[str, float]) -> None:
    if isinstance(obj, bool):
        return  # booleans are claims, not magnitudes
    if isinstance(obj, (int, float)):
        out[prefix] = float(obj)
    elif isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)


def load_bench_keys(path: str) -> Dict[str, float]:
    """Flat {key: number} view of one BENCH record file (see module
    docstring for the accepted shapes).  Nested dict entries flatten
    with dotted keys (``flash_attention_s4096.fwd_tflops``), so kernel
    sub-records gate too."""
    with open(path) as f:
        rec = json.load(f)
    if isinstance(rec, dict) and "parsed" in rec:
        rec = rec["parsed"]
        if not isinstance(rec, dict):
            # the r4 incident: a driver capture whose summary line did
            # not parse.  Gating against it would compare nothing and
            # exit green — refuse instead.
            raise ValueError(
                f"{path}: driver capture has parsed={rec!r} (truncated "
                "summary line) — no keys to gate against")
    out: Dict[str, float] = {}
    if isinstance(rec, dict) and "extras" in rec:
        _flatten("", rec.get("extras") or {}, out)
        # the headline rides under its metric name so the suffix rules
        # apply to it like any other key
        if isinstance(rec.get("value"), (int, float)) and rec.get("metric"):
            out[str(rec["metric"])] = float(rec["value"])
    elif isinstance(rec, dict):
        _flatten("", rec, out)
    else:
        raise ValueError(f"{path}: not a BENCH record (dict expected)")
    return out


def load_multichip_record(path: str) -> Dict[str, Any]:
    """Load one committed ``MULTICHIP_r*.json`` dryrun record.

    ISSUE 15 satellite (closing the ROADMAP maintenance note's last
    gap): like the serving BENCH records and ``hlo_contracts.json``,
    a multichip record must SELF-DECLARE its geometry provenance — a
    top-level ``"geometry"`` stamp (``"cpu-toy"`` for the emulated
    8-device CPU mesh the driver re-execs onto) — so nobody reads an
    emulated dryrun's numbers as pod-scale truth.  An unstamped
    record refuses to load; a record whose legs failed (``ok`` false)
    loads fine — failure is honest data, missing provenance is not.
    """
    with open(path) as f:
        rec = json.load(f)
    if not isinstance(rec, dict) or "n_devices" not in rec:
        raise ValueError(f"{path}: not a MULTICHIP dryrun record")
    geom = rec.get("geometry")
    if not isinstance(geom, str) or not geom:
        raise ValueError(
            f"{path} carries no geometry provenance stamp — dryrun "
            "numbers without a geometry read as pod-scale truth "
            "(re-record, or stamp the header: \"geometry\": \"cpu-toy\")")
    return rec


def compare_bench(a: Dict[str, float], b: Dict[str, float],
                  max_regress_pct: float,
                  keys: Optional[Sequence[str]] = None
                  ) -> Tuple[List[dict], List[dict]]:
    """Pair the two key sets; returns ``(rows, failures)``.

    Each row: key, a, b, delta_pct (B vs A in the key's *good*
    direction: positive = improved), direction (or None), gated, ok.
    ``failures`` are the gated rows whose regression exceeds
    ``max_regress_pct``.  ``keys`` restricts the comparison (exact
    names); a requested key missing from either file is itself a
    failure — a gate that silently skips a vanished headline key is no
    gate."""
    rows: List[dict] = []
    failures: List[dict] = []
    names = sorted(set(a) & set(b)) if keys is None else list(keys)
    for k in names:
        va, vb = a.get(k), b.get(k)
        if va is None or vb is None:
            row = {"key": k, "a": va, "b": vb, "direction": None,
                   "gated": True, "ok": False,
                   "error": "missing from " + ("A" if va is None else "B")}
            rows.append(row)
            failures.append(row)
            continue
        direction = key_direction(k)
        if direction is None:
            change = None
        elif va:
            change = ((vb - va) if direction == "higher" else (va - vb)) \
                / abs(va) * 100.0
        elif vb == va:
            change = 0.0
        else:
            # moved off a 0.0 baseline: percent is undefined, but the
            # gate must not go blind — e.g. exposed_collective_ms
            # 0.0 -> 50.0 is an unbounded regression, not a 0% change
            worse = (vb < va) if direction == "higher" else (vb > va)
            change = float("-inf") if worse else float("inf")
        gated = direction is not None
        ok = (not gated) or change is None or change >= -max_regress_pct
        row = {"key": k, "a": va, "b": vb, "delta_pct": change,
               "direction": direction, "gated": gated, "ok": ok}
        rows.append(row)
        if not ok:
            failures.append(row)
    return rows, failures


def format_regress(rows: List[dict], failures: List[dict],
                   max_regress_pct: float, *,
                   verbose: bool = False) -> str:
    """Human-readable gate report: failures first, then (``verbose``)
    every gated row; informational keys only with ``verbose``."""
    lines = []

    def fmt(row):
        d = {"higher": "↑", "lower": "↓", None: " "}[row["direction"]]
        if row.get("error"):
            return f"  {row['key']:<44} {row['error']}"
        ch = row.get("delta_pct")
        chs = f"{ch:+7.1f}%" if ch is not None else "    n/a"
        return (f"  {row['key']:<44} {d} {row['a']:>12g} -> "
                f"{row['b']:>12g}  {chs}")

    if failures:
        lines.append(f"REGRESSIONS (> {max_regress_pct:g}% in the losing "
                     f"direction):")
        lines += [fmt(r) for r in failures]
    else:
        lines.append(f"ok: no gated key regressed more than "
                     f"{max_regress_pct:g}%")
    gated = [r for r in rows if r["gated"] and not r.get("error")]
    if gated:
        worst = min((r["delta_pct"] for r in gated
                     if r["delta_pct"] is not None), default=None)
        lines.append(f"gated keys compared: {len(gated)}"
                     + (f"  (worst move {worst:+.1f}%)"
                        if worst is not None else ""))
    if verbose:
        for r in rows:
            if r not in failures:
                lines.append(fmt(r))
    return "\n".join(lines)
