"""Fleet-wide distributed request tracing (r19).

The serving fleet's telemetry is N independent per-replica event
streams; this module gives them a causal join.  Every boundary in a
request's life — queueing, admission, chunked prefill, KV export /
per-attempt shipment / import over the transport seam, decode, the
first-token stream emission, and migration hops — is emitted as a
``span`` event through the ordinary closed schema/bus, and
:func:`build_traces` reconstructs per-request span trees from ANY set
of recorded streams, in any file order.

Identity rules (the part that survives a lossy wire):

* **trace_id is the fleet rid** — rids are fleet-global (ISSUE 16),
  so spans recorded on different replicas' buses join by payload
  alone.
* **span ids derive from application-level identity** — admission
  life (``preemptions:admit_t``), transfer attempt number, hop
  endpoints — never from transport ``msg_id``s (sender retries mint
  fresh ones).  Re-emission of the same id under at-most-once
  redelivery is harmless: :func:`build_traces` MERGES identical ids
  (earliest start, latest end, first non-null attribute).
* **parents are only ever spans guaranteed emitted**: ``admit`` is
  parented to its own life's ``queue_wait`` (emitted together),
  ``kv_import`` to the successful ``kv_ship`` attempt whose span id
  rode the wire envelope's trace context verbatim, ``migrate_hop``
  and ``queue_wait`` are root-level.  Zero dangling parents by
  construction, under any ChaosTransport fault pattern.

Span times are on the fleet's SHARED engine clock (``time.monotonic``
or a ``SimClock``), not the per-bus stamp ``t`` — that is what lets
prefill-side and decode-side spans share one time base.

TTFT decomposition (:func:`ttft_decomposition`) telescopes the
critical path into ``ttft_queue_ms`` / ``ttft_prefill_ms`` /
``ttft_ship_ms`` / ``ttft_decode_wait_ms``; the components sum to the
engine's measured (shipping-aware) ``ttft_ms`` within
:data:`TTFT_SUM_TOLERANCE_MS` — the residual is only float rounding,
and the trace CLI enforces the bound (exit 1 on violation).

The fleet **flight recorder** rides the bus's existing
:class:`~apex_tpu.telemetry.recorder.FlightRecorder` ring:
:func:`maybe_dump_flight_record` dumps a replica's recent
spans+events as a schema-valid ``postmortem_*.jsonl`` trace bundle on
``replica_fence``, ``migrate_refused``, and recovery exhaustion.
See ``docs/tracing.md``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterable, List, Optional, Sequence

from apex_tpu.telemetry.schema import EVENT_FIELDS, load_jsonl

#: The closed span-kind vocabulary — derived from the single-sourced
#: schema table (an unknown kind cannot be emitted OR reconstructed).
SPAN_KINDS = tuple(EVENT_FIELDS["span"]["kind"].choices)

#: Documented bound on |sum(components) - measured ttft_ms|: each of
#: the four components and the measured total is rounded to 3 decimals
#: independently, so the worst-case drift is 5 half-ulps = 0.0025 ms.
TTFT_SUM_TOLERANCE_MS = 0.01

_EPS = 1e-9


def admission_life(preemptions: int, admit_t: float) -> str:
    """The admission-life discriminator spans of one (re)admission
    share: ``preemptions`` alone is not unique (a fallback re-admission
    keeps the count), but no two lives of one rid admit at the same
    shared-clock instant, so ``preemptions:admit_t`` is."""
    return f"{int(preemptions)}:{float(admit_t):.6f}"


@dataclasses.dataclass
class Span:
    """One reconstructed span (a closed ``[t_start, t_end]`` causal
    interval of a request's fleet-wide life)."""

    rid: int
    span_id: str
    kind: str
    t_start: float
    t_end: float
    parent_id: Optional[str] = None
    replica: Optional[str] = None
    attempt: Optional[int] = None
    outcome: Optional[str] = None
    reason: Optional[str] = None

    @property
    def wall_ms(self) -> float:
        return (self.t_end - self.t_start) * 1e3

    @classmethod
    def from_event(cls, ev: Dict[str, Any]) -> "Span":
        return cls(rid=int(ev["rid"]), span_id=str(ev["span_id"]),
                   kind=str(ev["kind"]), t_start=float(ev["t_start"]),
                   t_end=float(ev["t_end"]),
                   parent_id=ev.get("parent_id"),
                   replica=ev.get("replica"),
                   attempt=ev.get("attempt"),
                   outcome=ev.get("outcome"), reason=ev.get("reason"))

    def merge(self, other: "Span") -> None:
        """Idempotent-redelivery merge: same id re-emitted (duplicated
        wire message, overlapping stream files, a flight-recorder dump
        replaying its ring) widens the interval and fills gaps —
        never forks the tree."""
        self.t_start = min(self.t_start, other.t_start)
        self.t_end = max(self.t_end, other.t_end)
        for f in ("parent_id", "replica", "attempt", "outcome",
                  "reason"):
            if getattr(self, f) is None:
                setattr(self, f, getattr(other, f))


class Trace:
    """The span tree of one request (trace_id == fleet rid)."""

    def __init__(self, rid: int):
        self.rid = rid
        self.spans: Dict[str, Span] = {}
        self.duplicates = 0   # merged re-emissions (diagnostic only)

    def add(self, span: Span) -> None:
        have = self.spans.get(span.span_id)
        if have is None:
            self.spans[span.span_id] = span
        else:
            have.merge(span)
            self.duplicates += 1

    def by_kind(self, kind: str) -> List[Span]:
        out = [s for s in self.spans.values() if s.kind == kind]
        out.sort(key=lambda s: (s.t_start, s.t_end, s.span_id))
        return out

    def roots(self) -> List[Span]:
        out = [s for s in self.spans.values() if s.parent_id is None]
        out.sort(key=lambda s: (s.t_start, s.t_end, s.span_id))
        return out

    def children(self, span_id: str) -> List[Span]:
        out = [s for s in self.spans.values()
               if s.parent_id == span_id]
        out.sort(key=lambda s: (s.t_start, s.t_end, s.span_id))
        return out

    def orphans(self) -> List[Span]:
        """Spans whose parent_id references no reconstructed span —
        the completeness invariant the chaos_disagg leg pins at zero."""
        out = [s for s in self.spans.values()
               if s.parent_id is not None
               and s.parent_id not in self.spans]
        out.sort(key=lambda s: s.span_id)
        return out

    def ancestors(self, span: Span) -> List[Span]:
        """Parent chain of ``span``, nearest first; stops at a root or
        a dangling reference (cycle-guarded)."""
        out: List[Span] = []
        seen = {span.span_id}
        cur = span
        while cur.parent_id is not None and cur.parent_id in self.spans:
            if cur.parent_id in seen:
                break
            cur = self.spans[cur.parent_id]
            seen.add(cur.span_id)
            out.append(cur)
        return out


def build_traces(events: Iterable[Dict[str, Any]]
                 ) -> Dict[int, Trace]:
    """Reconstruct per-request span trees from any iterable of
    recorded events (concatenate as many per-replica streams as you
    have, in ANY order — reconstruction keys on payload identity, not
    stream position)."""
    traces: Dict[int, Trace] = {}
    for ev in events:
        if ev.get("type") != "span":
            continue
        span = Span.from_event(ev)
        traces.setdefault(span.rid, Trace(span.rid)).add(span)
    return traces


def load_trace_streams(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """Concatenate recorded jsonl streams (torn tails tolerated — a
    crashed replica's stream still joins the trace)."""
    events: List[Dict[str, Any]] = []
    for p in paths:
        events.extend(load_jsonl(p, tolerate_torn_tail=True))
    return events


def validate_trace(trace: Trace) -> List[str]:
    """Structural completeness problems (empty list = complete):
    orphan spans (dangling parent references) and kind values outside
    the closed vocabulary.  An *unfinished* trace (no ``stream_emit``
    yet) is not a problem — incompleteness in time is normal,
    incompleteness in STRUCTURE is never."""
    problems: List[str] = []
    for s in trace.orphans():
        problems.append(
            f"rid {trace.rid}: orphan span {s.span_id} ({s.kind}) — "
            f"dangling parent {s.parent_id}")
    for s in trace.spans.values():
        if s.kind not in SPAN_KINDS:
            problems.append(
                f"rid {trace.rid}: span {s.span_id} has unknown kind "
                f"{s.kind!r}")
        if s.t_end < s.t_start - _EPS:
            problems.append(
                f"rid {trace.rid}: span {s.span_id} ends before it "
                f"starts ({s.t_start} -> {s.t_end})")
    return problems


def _stream_span(trace: Trace) -> Optional[Span]:
    streams = trace.by_kind("stream_emit")
    return streams[-1] if streams else None


def _ship_segment(trace: Trace) -> float:
    """Wall seconds of the successful ship segment on the critical
    path: ``kv_export.start -> kv_import.end`` (0.0 when the request
    never shipped — the colocated control's built-in sanity zero)."""
    imports = trace.by_kind("kv_import")
    if not imports:
        return 0.0
    imp = imports[-1]
    exp: Optional[Span] = None
    # follow the causal links when they resolved (kv_import -> the
    # winning kv_ship attempt -> kv_export) ...
    for anc in trace.ancestors(imp):
        if anc.kind == "kv_export":
            exp = anc
            break
    if exp is None:
        # ... else fall back to the latest export that precedes it
        cand = [s for s in trace.by_kind("kv_export")
                if s.t_start <= imp.t_end + _EPS]
        exp = cand[-1] if cand else None
    if exp is None:
        return 0.0
    return max(0.0, imp.t_end - exp.t_start)


def critical_path(trace: Trace) -> List[Span]:
    """The causal chain that produced the request's first streamed
    token: the ``stream_emit`` span's ancestor chain, spliced with the
    successful ship chain (export -> winning attempt -> import) when
    the request was disaggregated.  Ordered by start time."""
    stream = _stream_span(trace)
    if stream is None:
        return []
    chain = {stream.span_id: stream}
    for anc in trace.ancestors(stream):
        chain[anc.span_id] = anc
    imports = trace.by_kind("kv_import")
    if imports:
        imp = imports[-1]
        chain[imp.span_id] = imp
        for anc in trace.ancestors(imp):
            chain[anc.span_id] = anc
    return sorted(chain.values(),
                  key=lambda s: (s.t_start, s.t_end, s.span_id))


def ttft_decomposition(trace: Trace) -> Optional[Dict[str, float]]:
    """Decompose the request's measured TTFT along its critical path.

    Returns ``None`` until the trace holds a first-token emission
    (``stream_emit``).  The four components telescope over the
    boundaries arrival -> admit -> prefill-done -> (+ship) -> stream:

    * ``ttft_queue_ms``   — arrival to admission,
    * ``ttft_prefill_ms`` — admission to the first sampled token,
    * ``ttft_ship_ms``    — the kv_export.start -> kv_import.end wall
      (0.0 colocated / fallback),
    * ``ttft_decode_wait_ms`` — the residual: export-pump wait plus
      adoption-to-stream — so the sum is EXACT by construction and
      only per-key rounding (≤ :data:`TTFT_SUM_TOLERANCE_MS`)
      separates it from the engine's emitted ``ttft_ms``.
    """
    stream = _stream_span(trace)
    if stream is None:
        return None
    decode_wait = trace.spans.get(stream.parent_id or "")
    if decode_wait is None or decode_wait.kind != "decode_wait":
        waits = trace.by_kind("decode_wait")
        decode_wait = waits[-1] if waits else None
    if decode_wait is None:
        return None
    admit = trace.spans.get(decode_wait.parent_id or "")
    if admit is None or admit.kind != "admit" \
            or admit.t_start > decode_wait.t_start + _EPS:
        # a preempted request's final life admits AFTER its first
        # token; the prefill that produced the token belongs to the
        # latest life that STARTED before it
        cand = [s for s in trace.by_kind("admit")
                if s.t_start <= decode_wait.t_start + _EPS]
        admit = cand[-1] if cand else admit
    if admit is None:
        return None
    queue = trace.spans.get(admit.parent_id or "")
    if queue is None or queue.kind != "queue_wait":
        return None
    total_ms = (stream.t_end - queue.t_start) * 1e3
    queue_ms = (admit.t_start - queue.t_start) * 1e3
    prefill_ms = (decode_wait.t_start - admit.t_start) * 1e3
    ship_ms = _ship_segment(trace) * 1e3
    wait_ms = total_ms - queue_ms - prefill_ms - ship_ms
    return {
        "rid": trace.rid,
        "ttft_ms": round(total_ms, 3),
        "ttft_queue_ms": round(queue_ms, 3),
        "ttft_prefill_ms": round(prefill_ms, 3),
        "ttft_ship_ms": round(ship_ms, 3),
        "ttft_decode_wait_ms": round(wait_ms, 3),
    }


# -- the fleet flight recorder ------------------------------------------


def maybe_dump_flight_record(bus, reason: str, *,
                             step: Optional[int] = None
                             ) -> Optional[str]:
    """Dump a replica bus's flight-recorder ring (recent spans AND
    events) as a schema-valid ``postmortem_*.jsonl`` trace bundle.

    The fleet calls this on ``replica_fence``, ``migrate_refused``,
    and recovery exhaustion.  Only buses with a file-backed
    (:class:`~apex_tpu.telemetry.bus.JsonlSink`) stream dump — a
    memory-only bus has nowhere sensible to put a bundle, and a chaos
    *test* must not litter the working directory.  Returns the bundle
    path, or None when no dump was taken."""
    if bus is None:
        return None
    from apex_tpu.telemetry.bus import JsonlSink

    if not any(isinstance(s, JsonlSink)
               for s in getattr(bus, "sinks", ())):
        return None
    return bus.flush_postmortem(reason, step=step)


# -- the trace CLI ------------------------------------------------------


def _format_span(s: Span) -> str:
    bits = [f"{s.kind} [{s.t_start:.6f} -> {s.t_end:.6f}] "
            f"{s.wall_ms:.3f}ms"]
    if s.replica:
        bits.append(f"@{s.replica}")
    if s.attempt is not None:
        bits.append(f"attempt={s.attempt}")
    if s.outcome:
        bits.append(f"outcome={s.outcome}")
    if s.reason:
        bits.append(f"reason={s.reason}")
    return " ".join(bits)


def format_trace(trace: Trace) -> str:
    """Render one request's span tree plus its critical path and TTFT
    decomposition."""
    lines = [f"rid {trace.rid}: {len(trace.spans)} spans"
             + (f" ({trace.duplicates} merged re-emissions)"
                if trace.duplicates else "")]

    def walk(span: Span, depth: int) -> None:
        lines.append("  " * (depth + 1) + _format_span(span))
        for child in trace.children(span.span_id):
            walk(child, depth + 1)

    for root in trace.roots():
        walk(root, 0)
    for s in trace.orphans():
        lines.append(f"  ORPHAN {_format_span(s)} "
                     f"(dangling parent {s.parent_id})")
    cp = critical_path(trace)
    if cp:
        lines.append("  critical path: "
                     + " -> ".join(s.kind for s in cp))
    d = ttft_decomposition(trace)
    if d is not None:
        lines.append(
            "  ttft {ttft_ms}ms = queue {ttft_queue_ms} + prefill "
            "{ttft_prefill_ms} + ship {ttft_ship_ms} + decode-wait "
            "{ttft_decode_wait_ms}".format(**d))
    return "\n".join(lines)


def run_trace_cli(paths: Sequence[str], *, rid: Optional[int] = None,
                  as_json: bool = False, echo=print) -> int:
    """``python -m apex_tpu.telemetry trace`` body.  Exit codes follow
    the regress convention: 0 = complete trees and every decomposition
    sums to its measured TTFT; 1 = structural problems (orphans,
    dangling parents, kind drift) or a sum outside
    :data:`TTFT_SUM_TOLERANCE_MS`; 2 = an unreadable stream."""
    try:
        events = load_trace_streams(paths)
    except Exception as e:
        echo(f"error: {e}")
        return 2
    traces = build_traces(events)
    if rid is not None:
        traces = {r: t for r, t in traces.items() if r == rid}
        if not traces:
            echo(f"error: no spans for rid {rid} in "
                 f"{len(events)} events")
            return 2
    # the engine's measured (shipping-aware) TTFT, for the sum pin
    measured: Dict[int, float] = {}
    for ev in events:
        if ev.get("type") == "request_retire" and "ttft_ms" in ev:
            measured[int(ev["rid"])] = float(ev["ttft_ms"])
    problems: List[str] = []
    rows: List[Dict[str, Any]] = []
    for r in sorted(traces):
        trace = traces[r]
        problems.extend(validate_trace(trace))
        d = ttft_decomposition(trace)
        if d is not None and r in measured:
            parts = (d["ttft_queue_ms"] + d["ttft_prefill_ms"]
                     + d["ttft_ship_ms"] + d["ttft_decode_wait_ms"])
            if abs(parts - measured[r]) > TTFT_SUM_TOLERANCE_MS:
                problems.append(
                    f"rid {r}: decomposition sums to {parts:.3f}ms "
                    f"but measured ttft_ms is {measured[r]:.3f} "
                    f"(tolerance {TTFT_SUM_TOLERANCE_MS}ms)")
        rows.append({
            "rid": r, "spans": len(trace.spans),
            "duplicates_merged": trace.duplicates,
            "orphans": len(trace.orphans()),
            "critical_path": [s.kind for s in critical_path(trace)],
            "ttft_decomposition": d,
            "measured_ttft_ms": measured.get(r),
        })
    if as_json:
        echo(json.dumps({"traces": rows, "problems": problems},
                        indent=1, sort_keys=True))
    else:
        for r in sorted(traces):
            echo(format_trace(traces[r]))
        echo(f"{len(traces)} traces from {len(paths)} streams "
             f"({len(events)} events)")
        for p in problems:
            echo(f"PROBLEM: {p}")
    return 1 if problems else 0
