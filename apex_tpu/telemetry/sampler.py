"""In-run performance attribution: the profiling<->telemetry bridge.

The offline layer (:mod:`apex_tpu.profiling`) can say where a step's
milliseconds went — but only in a manual TensorBoard session; the
online layer (the PR 4 bus) records *that* p95 moved but not *why*.
:class:`ProfileSampler` joins them (ISSUE 9): every N steps it captures
a short profiler window around the live train step, runs the
phase/collective/overlap classifier
(:func:`apex_tpu.profiling.trace_report.phase_report`), and emits the
result as typed ``profile`` and ``memory`` events through the bus — so
a long-running job's stream answers "what fraction of the step is
exposed collective wall, and what is HBM doing" without stopping the
run.

Two disciplines inherited from the PR 4 accounting:

1. **Overhead is booked, not hidden.**  Every host second the sampler
   spends (trace start/stop, parse, classify) goes to its own
   ``profile`` accountant bucket, so goodput stays honest.
2. **Overhead is bounded.**  The sampler tracks its own cost and
   *defers* a scheduled capture whenever taking it would push total
   sampler overhead past ``max_overhead`` (default 1%) of the run's
   wall so far — the ≤1% bound is enforced by construction, not hoped
   for (asserted in tests/L0/test_perf_attribution.py).

The sampler must never kill the run it observes: every capture is
wrapped; a failure increments ``failures``, remembers ``last_error``,
and after ``max_failures`` consecutive failures the sampler disables
itself (a broken profiler backend degrades to "no profile events", not
a crashed job).
"""

from __future__ import annotations

import logging
import shutil
import tempfile
import time
from typing import Any, Callable, Dict, Optional

log = logging.getLogger("apex_tpu.telemetry")

__all__ = ["ProfileSampler", "JaxProfilerTracer", "device_memory_payload"]


class JaxProfilerTracer:
    """Default capture backend: ``jax.profiler`` with the host/python
    tracers OFF (the trace writer caps at ~1M events total and a
    host-spammed window evicts the device timeline — the r5 incident
    :mod:`apex_tpu.profiling.trace_report` documents)."""

    def start(self, logdir: str) -> None:
        import jax

        try:
            opts = jax.profiler.ProfileOptions()
            opts.host_tracer_level = 0
            opts.python_tracer_level = 0
            jax.profiler.start_trace(logdir, profiler_options=opts)
        except (AttributeError, TypeError):  # older jax: no options
            jax.profiler.start_trace(logdir)

    def stop(self) -> None:
        import jax

        jax.profiler.stop_trace()


def device_memory_payload() -> Dict[str, Any]:
    """Live/peak HBM sampled from ``device.memory_stats()`` across local
    devices.  Backends without stats (CPU) report
    ``stats_available=False`` with the byte fields ABSENT — optionality
    is explicit in the schema, never smuggled via sentinel zeros."""
    payload: Dict[str, Any] = {"stats_available": False, "n_devices": 0}
    try:
        import jax

        devs = jax.local_devices()
        payload["n_devices"] = len(devs)
        live = peak = limit = 0
        seen = False
        for d in devs:
            try:
                st = d.memory_stats()
            except Exception:
                st = None
            if not st:
                continue
            seen = True
            live += int(st.get("bytes_in_use", 0))
            peak += int(st.get("peak_bytes_in_use", 0))
            limit += int(st.get("bytes_limit", 0))
        if seen:
            payload["stats_available"] = True
            payload["live_bytes"] = live
            payload["peak_bytes"] = peak
            if limit:
                payload["limit_bytes"] = limit
    except Exception:  # pragma: no cover — jax not importable
        pass
    return payload


class ProfileSampler:
    """Periodic in-run phase/collective/HBM attribution sampler.

    ``bus`` — the run's :class:`~apex_tpu.telemetry.TelemetryBus`.
    ``every`` — capture cadence in steps (a window starts at each
    multiple, budget permitting).  ``window`` — how many steps one
    capture spans.  ``hlo_text`` — optional compiled-HLO text of the
    profiled step (``jitted.lower(...).compile().as_text()``); with it
    fusions classify matmul-vs-vector, without it they count as vector.
    ``accountant`` — where overhead books (default: the bus's shared
    accountant if one exists).  ``max_overhead`` — the budget fraction
    (see module docstring).  ``tracer`` — capture backend with
    ``start(logdir)``/``stop()`` (tests inject a synthetic one; default
    :class:`JaxProfilerTracer`).

    Train loops call :meth:`on_step` once per completed step
    (``run_resilient_training(profile_sampler=...)`` does).  Benches
    that have no step hook use :meth:`capture` around an explicit
    window.  The latest parsed report stays on ``last_report``.
    """

    def __init__(self, bus, *, every: int = 50, window: int = 1,
                 top_k: int = 5, max_overhead: float = 0.01,
                 hlo_text: Optional[str] = None,
                 accountant: Any = None,
                 tracer: Any = None,
                 max_failures: int = 3):
        self.bus = bus
        self.every = max(1, int(every))
        self.window = max(1, int(window))
        self.top_k = top_k
        self.max_overhead = float(max_overhead)
        self.hlo_text = hlo_text
        self.tracer = tracer if tracer is not None else JaxProfilerTracer()
        self.max_failures = max_failures
        self._acct = accountant
        self._now: Callable[[], float] = time.monotonic
        self._t0: Optional[float] = None  # first on_step/capture
        self._active_dir: Optional[str] = None
        self._remaining = 0
        self._capture_cost = 0.0  # host cost of the in-flight capture
        self.overhead_s = 0.0
        self.samples = 0
        self.deferred = 0
        self.failures = 0
        self._consecutive_failures = 0
        self.disabled = False
        self.last_error: Optional[str] = None
        self.last_report = None

    # -- budget ----------------------------------------------------------

    def wall(self) -> float:
        if self._t0 is None:
            return 0.0
        return max(self._now() - self._t0, 1e-9)

    def overhead_fraction(self) -> float:
        """Sampler host-overhead as a fraction of the run wall observed
        so far (0 before the first step)."""
        if self._t0 is None:
            return 0.0
        return self.overhead_s / self.wall()

    def attach_accountant(self, accountant) -> None:
        """Give the sampler a :class:`StepAccountant` to book its
        overhead against, unless the constructor already supplied one —
        the train loops call this with the bus's shared ledger."""
        if self._acct is None:
            self._acct = accountant

    def _budget_allows(self) -> bool:
        """Would another capture (projected at the mean cost of the
        captures so far) keep total overhead within ``max_overhead`` of
        wall?  The first capture has no cost estimate and is always
        allowed — the bound holds asymptotically, which is the regime a
        *long-running* job's sampler lives in."""
        if self.samples == 0:
            return True
        projected = self.overhead_s * (self.samples + 1) / self.samples
        return projected <= self.max_overhead * self.wall()

    # -- bookkeeping -----------------------------------------------------

    def _book(self, seconds: float) -> None:
        self.overhead_s += seconds
        acct = self._acct
        if acct is None:
            acct = getattr(self.bus, "_accountant", None)
        if acct is not None:
            try:
                acct.pause(seconds, "profile")
            except Exception:  # pragma: no cover — old accountant
                pass

    def _fail(self, err: Exception) -> None:
        self.failures += 1
        self._consecutive_failures += 1
        self.last_error = repr(err)[:200]
        if self._consecutive_failures >= self.max_failures:
            self.disabled = True
            log.warning("ProfileSampler disabled after %d consecutive "
                        "failures: %s", self._consecutive_failures,
                        self.last_error)

    # -- capture machinery -----------------------------------------------

    def _start(self) -> None:
        d = tempfile.mkdtemp(prefix="apex_tpu_sampler_")
        try:
            self.tracer.start(d)
        except Exception:
            shutil.rmtree(d, ignore_errors=True)
            raise
        self._active_dir = d
        self._remaining = self.window

    def _finish(self):
        """Stop the active capture and classify it (no emission)."""
        from apex_tpu.profiling.trace_report import phase_report

        d, self._active_dir = self._active_dir, None
        try:
            self.tracer.stop()
            report = phase_report(d, hlo_text=self.hlo_text,
                                  top=self.top_k)
        finally:
            shutil.rmtree(d, ignore_errors=True)
        self.last_report = report
        self.samples += 1
        return report

    def _emit(self, step: Optional[int], report,
              overhead_s: float) -> None:
        payload = report.to_payload()
        payload["window_steps"] = self.window
        payload["overhead_ms"] = round(overhead_s * 1e3, 3)
        self.bus.emit("profile", step=step, **payload)
        self.bus.emit("memory", step=step, **device_memory_payload())

    # -- public entry points ---------------------------------------------

    def on_step(self, step: int) -> None:
        """Call once per *completed* step.  Starts a capture at each
        ``every`` multiple (budget permitting) and closes it after
        ``window`` further steps.  Never raises."""
        if self.disabled:
            return
        if self._t0 is None:
            self._t0 = self._now()
        try:
            if self._active_dir is not None:
                self._remaining -= 1
                if self._remaining <= 0:
                    t0 = self._now()
                    report = self._finish()
                    dt = self._now() - t0
                    self._capture_cost += dt
                    self._book(dt)
                    self._emit(step, report, self._capture_cost)
                    self._consecutive_failures = 0
                return
            if step % self.every == 0:
                if not self._budget_allows():
                    self.deferred += 1
                    return
                t0 = self._now()
                self._start()
                dt = self._now() - t0
                self._capture_cost = dt  # start cost; finish adds parse
                self._book(dt)
        except Exception as e:
            # observability must never kill the run it observes
            self._abort_quietly()
            self._fail(e)

    def capture(self, run_window: Callable[[], Any], *,
                step: Optional[int] = None):
        """Explicit one-shot capture: trace ``run_window()`` (which
        should run the already-warmed step(s) and sync), classify, emit
        the ``profile``/``memory`` pair, book the overhead.  The whole
        wall — window included — books as ``profile`` overhead: these
        steps ran purely to be profiled (the bench entry point; a train
        loop uses :meth:`on_step`, where only start/stop/parse book).
        Returns the :class:`~apex_tpu.profiling.trace_report.
        PhaseReport`, or None on failure (never raises)."""
        if self.disabled:
            return None
        if self._t0 is None:
            self._t0 = self._now()
        t0 = self._now()
        report = None
        try:
            self._start()
            run_window()
            report = self._finish()
        except Exception as e:
            self._abort_quietly()
            self._fail(e)
        finally:
            dt = self._now() - t0
            self._book(dt)  # booked exactly once, success or failure
        if report is not None:
            try:
                self._emit(step, report, dt)
                self._consecutive_failures = 0
            except Exception as e:  # emit failure: no re-booking
                self._fail(e)
        return report

    def _abort_quietly(self) -> None:
        """Tear down a half-open capture without raising."""
        d, self._active_dir = self._active_dir, None
        self._remaining = 0
        if d is not None:
            try:
                self.tracer.stop()
            except Exception:
                pass
            shutil.rmtree(d, ignore_errors=True)

    def totals(self) -> Dict[str, Any]:
        """Sampler self-accounting for logs/records."""
        return {
            "samples": self.samples,
            "deferred": self.deferred,
            "failures": self.failures,
            "overhead_s": round(self.overhead_s, 4),
            "overhead_fraction": round(self.overhead_fraction(), 5),
            "disabled": self.disabled,
        }
