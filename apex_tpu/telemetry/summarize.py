"""Offline aggregation of a telemetry JSONL stream.

``python -m apex_tpu.telemetry summarize run.jsonl`` renders the
operator's one-screen view of a run — step-time percentiles, goodput
with its loss buckets, per-event-type counts — and ``--diff b.jsonl``
turns two runs into an A/B table (the diffable-stream payoff: "did the
new remat policy move p95, and did goodput pay for it?").
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from apex_tpu.telemetry.schema import load_jsonl


def percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence."""
    if not sorted_vals:
        return float("nan")
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def summarize_events(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate a stream into the summary record.

    Goodput comes from the last ``run_end`` event when the run exited
    through its accounting (the accountant's ledger is authoritative —
    it spans elastic restarts); a crashed stream without one falls back
    to productive-step seconds over the stream's time extent.
    """
    counts: Dict[str, int] = {}
    step_ms: List[float] = []
    skipped_steps = 0
    run_end: Optional[Dict[str, Any]] = None
    t_lo = t_hi = None
    tpot_ms: List[float] = []
    ttft_ms: List[float] = []
    pool_occ: List[float] = []
    commit_tokens = commit_rows = 0
    spec_drafted = spec_accepted = 0
    prefix_hits = prefix_total = 0
    shared_pages_peak = None
    deadline_hits = deadline_total = 0
    queue_sheds = run_timeouts = 0
    phase_ms: Dict[str, List[float]] = {}
    exposed_ms: List[float] = []
    profile_overhead_ms = 0.0
    hbm_peak = None
    for ev in events:
        counts[ev.get("type", "?")] = counts.get(ev.get("type", "?"), 0) + 1
        t = ev.get("t")
        if isinstance(t, (int, float)):
            t_lo = t if t_lo is None else min(t_lo, t)
            t_hi = t if t_hi is None else max(t_hi, t)
        if ev.get("type") == "step":
            step_ms.append(float(ev.get("step_ms", 0.0)))
            if ev.get("skipped"):
                skipped_steps += 1
        elif ev.get("type") == "run_end":
            run_end = ev
        elif ev.get("type") == "request_retire":
            if ev.get("tpot_ms") is not None:
                tpot_ms.append(float(ev["tpot_ms"]))
            if ev.get("ttft_ms") is not None:
                ttft_ms.append(float(ev["ttft_ms"]))
            if ev.get("deadline_hit") is not None:
                deadline_total += 1
                deadline_hits += 1 if ev["deadline_hit"] else 0
        elif ev.get("type") == "request_admit":
            # prefix sharing (r17): the field is present on every admit
            # while sharing is on — misses included, which is what makes
            # hits/total a real hit RATE rather than a hit count
            if ev.get("prefix_hit") is not None:
                prefix_total += 1
                prefix_hits += 1 if ev["prefix_hit"] else 0
        elif ev.get("type") == "request_timeout":
            # a timed-out request HAD a deadline by definition and
            # missed it — it counts in the hit-rate denominator even
            # though it never produced a retire record
            deadline_total += 1
            if ev.get("where") == "queued":
                queue_sheds += 1
            else:
                run_timeouts += 1
        elif ev.get("type") == "decode_step":
            if ev.get("pool_pages"):
                pool_occ.append(ev.get("pool_used", 0)
                                / ev["pool_pages"])
            # accepted tokens per step (ISSUE 12): committed tokens
            # over occupied batch rows — exactly 1.0 for a plain
            # decode stream, > 1.0 whenever speculation lands
            commit_tokens += int(ev.get("new_tokens", 0))
            commit_rows += int(ev.get("batch", 0))
            spec_drafted += int(ev.get("spec_drafted", 0))
            spec_accepted += int(ev.get("spec_accepted", 0))
            if ev.get("pool_shared_pages") is not None:
                sp = int(ev["pool_shared_pages"])
                shared_pages_peak = (sp if shared_pages_peak is None
                                     else max(shared_pages_peak, sp))
        elif ev.get("type") == "profile":
            for k, v in (ev.get("phase_ms") or {}).items():
                phase_ms.setdefault(k, []).append(float(v))
            if ev.get("exposed_collective_ms") is not None:
                exposed_ms.append(float(ev["exposed_collective_ms"]))
            profile_overhead_ms += float(ev.get("overhead_ms", 0.0))
        elif ev.get("type") == "memory":
            if ev.get("peak_bytes") is not None:
                pk = float(ev["peak_bytes"])
                hbm_peak = pk if hbm_peak is None else max(hbm_peak, pk)

    s = sorted(step_ms)
    run_ids = list(dict.fromkeys(
        e.get("run_id") for e in events if e.get("run_id")))
    out: Dict[str, Any] = {
        "run_id": run_ids[0] if run_ids else None,
        "n_events": len(events),
        "counts": dict(sorted(counts.items())),
        "steps": len(step_ms),
        # a skipped step may surface twice (a `skip` event from the
        # guard AND the skipped flag on its `step` event): take the max,
        # never the sum
        "skipped_steps": max(skipped_steps, counts.get("skip", 0)),
        "step_ms_p50": round(percentile(s, 0.50), 3) if s else None,
        "step_ms_p95": round(percentile(s, 0.95), 3) if s else None,
        "step_ms_p99": round(percentile(s, 0.99), 3) if s else None,
        # data-plane health (ISSUE 7): stall/quarantine counts are
        # first-class summary fields, not just rows in the counts dict —
        # an input-bound or data-damaged run must be visible on the one-
        # screen view
        "data_stalls": counts.get("data_stall", 0),
        "records_quarantined": counts.get("data_quarantine", 0),
    }
    if counts.get("request_retire") or counts.get("decode_step") \
            or counts.get("request_timeout") or counts.get("request_reject"):
        # serving summary (ISSUE 8): the one-screen view of a serving
        # stream is latency percentiles + pool pressure, not step time
        st, sf = sorted(tpot_ms), sorted(ttft_ms)
        out["serving_requests"] = counts.get("request_retire", 0)
        out["serving_decode_steps"] = counts.get("decode_step", 0)
        out["serving_tpot_p50"] = (round(percentile(st, 0.50), 3)
                                   if st else None)
        out["serving_tpot_p95"] = (round(percentile(st, 0.95), 3)
                                   if st else None)
        out["serving_ttft_p50"] = (round(percentile(sf, 0.50), 3)
                                   if sf else None)
        out["serving_pool_peak"] = (round(max(pool_occ), 4)
                                    if pool_occ else None)
        out["serving_accepted_tokens_per_step"] = (
            round(commit_tokens / commit_rows, 4) if commit_rows
            else None)
        if spec_drafted:
            # proposer quality (pre-truncation): how much of what it
            # guessed did the model's own argmax endorse
            out["serving_spec_drafted"] = spec_drafted
            out["serving_spec_accept_rate"] = round(
                spec_accepted / spec_drafted, 4)
        # overload/deadline health (ISSUE 10): sheds = explicit load
        # refusal (bounded-queue rejects + queued deadline sheds);
        # timeouts = in-flight deadline deaths; deadline hit rate =
        # hits over every deadline-carrying request seen (completions
        # AND deadline deaths — rejects are excluded because a reject
        # event does not say whether a deadline existed)
        out["serving_rejects"] = counts.get("request_reject", 0)
        out["serving_sheds"] = out["serving_rejects"] + queue_sheds
        out["serving_timeouts"] = run_timeouts
        out["serving_deadline_hit_rate"] = (
            round(deadline_hits / deadline_total, 4)
            if deadline_total else None)
        # prefix sharing (r17): hit rate over every sharing-on admit,
        # and the pool's peak count of pages held by >1 reader
        out["serving_prefix_hit_rate"] = (
            round(prefix_hits / prefix_total, 4)
            if prefix_total else None)
        out["serving_shared_pages_peak"] = shared_pages_peak
        # disaggregated prefill/decode (r18): shipment health over
        # every transfer OUTCOME (success or fallback — retries are a
        # cost, not an outcome, so they scale neither rate); None when
        # the stream carried no ship traffic at all
        ships = counts.get("kv_ship", 0)
        fallbacks = counts.get("kv_ship_fallback", 0)
        out["serving_ship_success_rate"] = (
            round(ships / (ships + fallbacks), 4)
            if ships + fallbacks else None)
        out["serving_ship_fallback_rate"] = (
            round(fallbacks / (ships + fallbacks), 4)
            if ships + fallbacks else None)
        # distributed tracing (r19): p50 TTFT decomposition over every
        # request whose span tree is complete enough to decompose —
        # where the waiting actually happened, not just how long it was
        if counts.get("span"):
            from apex_tpu.telemetry.tracing import (build_traces,
                                                    ttft_decomposition)
            decomps = [d for d in (ttft_decomposition(t)
                                   for t in build_traces(events).values())
                       if d is not None]
            if decomps:
                out["serving_traced_requests"] = len(decomps)
                for comp in ("ttft_queue_ms", "ttft_prefill_ms",
                             "ttft_ship_ms", "ttft_decode_wait_ms"):
                    vals = sorted(d[comp] for d in decomps)
                    out[f"serving_{comp}"] = round(
                        percentile(vals, 0.50), 3)
    if counts.get("profile"):
        # phase attribution (ISSUE 9): mean per-phase device ms over the
        # run's sampled windows — the answer to "where do a step's
        # milliseconds go" on the same one-screen view that says the
        # p95 moved
        out["profile_samples"] = counts["profile"]
        out["phase_ms"] = {
            k: round(sum(v) / len(v), 3)
            for k, v in sorted(phase_ms.items())}
        out["exposed_collective_ms"] = (
            round(sum(exposed_ms) / len(exposed_ms), 3)
            if exposed_ms else None)
        out["profile_overhead_ms"] = round(profile_overhead_ms, 3)
    if hbm_peak is not None:
        out["hbm_peak_gb"] = round(hbm_peak / 1e9, 3)
    if len(run_ids) > 1:
        # JsonlSink appends: a restarted job continues its stream file
        # under a new run_id.  Aggregating across runs is legitimate,
        # but the record must say it happened.
        out["run_ids"] = run_ids
    if run_end is not None:
        out["goodput"] = run_end.get("goodput")
        out["buckets_s"] = run_end.get("buckets_s", {})
        out["wall_s"] = run_end.get("wall_s")
        out["steps_per_sec"] = run_end.get("steps_per_sec")
        out["stop_reason"] = run_end.get("reason")
    elif s and t_hi is not None and t_hi > t_lo:
        productive_s = sum(
            float(e.get("step_ms", 0.0)) for e in events
            if e.get("type") == "step" and not e.get("skipped")) / 1e3
        out["goodput"] = round(min(1.0, productive_s / (t_hi - t_lo)), 4)
        out["wall_s"] = round(t_hi - t_lo, 3)
        out["goodput_estimated"] = True  # no run_end: crashed stream
    return out


def summarize_file(path: str) -> Dict[str, Any]:
    # tolerant load: a crashed stream may end in a torn line, and the
    # crashed stream is the one that most needs summarizing
    return summarize_events(load_jsonl(path, tolerate_torn_tail=True))


def _pct(v) -> str:
    return "n/a" if v is None else f"{100.0 * v:.1f}%"


def _ms(v) -> str:
    return "n/a" if v is None else f"{v:.1f}ms"


def format_summary(s: Dict[str, Any]) -> str:
    runs = s.get("run_ids")
    lines = [
        f"run {' + '.join(runs) if runs else s.get('run_id')}  "
        f"events {s.get('n_events')}  "
        f"steps {s.get('steps')} ({s.get('skipped_steps', 0)} skipped)",
        f"step time   p50 {_ms(s.get('step_ms_p50'))}  "
        f"p95 {_ms(s.get('step_ms_p95'))}  "
        f"p99 {_ms(s.get('step_ms_p99'))}",
        f"goodput     {_pct(s.get('goodput'))}"
        + (" (estimated: no run_end)" if s.get("goodput_estimated") else ""),
    ]
    buckets = s.get("buckets_s")
    if buckets:
        lines.append("time split  " + "  ".join(
            f"{k} {v:.2f}s" for k, v in sorted(buckets.items())))
    if s.get("serving_requests") is not None:
        parts = [f"serving     requests {s['serving_requests']}"]
        if s.get("serving_tpot_p50") is not None:
            parts.append(f"tpot p50 {_ms(s['serving_tpot_p50'])} "
                         f"p95 {_ms(s.get('serving_tpot_p95'))}")
        if s.get("serving_ttft_p50") is not None:
            parts.append(f"ttft p50 {_ms(s['serving_ttft_p50'])}")
        if s.get("serving_pool_peak") is not None:
            parts.append(f"pool peak {_pct(s['serving_pool_peak'])}")
        if s.get("serving_accepted_tokens_per_step") is not None:
            parts.append(
                f"acc {s['serving_accepted_tokens_per_step']:.2f} tok/step")
        if s.get("serving_spec_accept_rate") is not None:
            parts.append(
                f"spec accept {_pct(s['serving_spec_accept_rate'])}")
        if s.get("serving_sheds") or s.get("serving_timeouts"):
            parts.append(f"shed {s.get('serving_sheds', 0)} "
                         f"timeout {s.get('serving_timeouts', 0)}")
        if s.get("serving_deadline_hit_rate") is not None:
            parts.append(
                f"deadline hit {_pct(s['serving_deadline_hit_rate'])}")
        if s.get("serving_prefix_hit_rate") is not None:
            parts.append(
                f"prefix hit {_pct(s['serving_prefix_hit_rate'])}")
        if s.get("serving_shared_pages_peak"):
            parts.append(
                f"shared pages peak {s['serving_shared_pages_peak']}")
        if s.get("serving_ship_success_rate") is not None:
            parts.append(
                f"ship ok {_pct(s['serving_ship_success_rate'])} "
                f"fallback {_pct(s.get('serving_ship_fallback_rate'))}")
        lines.append("  ".join(parts))
        if s.get("serving_traced_requests"):
            lines.append(
                f"ttft split  queue {_ms(s.get('serving_ttft_queue_ms'))}"
                f"  prefill {_ms(s.get('serving_ttft_prefill_ms'))}"
                f"  ship {_ms(s.get('serving_ttft_ship_ms'))}"
                f"  decode-wait {_ms(s.get('serving_ttft_decode_wait_ms'))}"
                f"  (p50 over {s['serving_traced_requests']} traces)")
    if s.get("profile_samples"):
        parts = ["phases      " + "  ".join(
            f"{k} {v:.2f}ms" for k, v in (s.get("phase_ms") or {}).items())]
        if s.get("exposed_collective_ms") is not None:
            parts.append(f"exposed coll {_ms(s['exposed_collective_ms'])}")
        parts.append(f"({s['profile_samples']} samples)")
        lines.append("  ".join(parts))
    if s.get("hbm_peak_gb") is not None:
        lines.append(f"hbm peak    {s['hbm_peak_gb']:.2f} GB")
    if s.get("data_stalls") or s.get("records_quarantined"):
        parts = [f"data        stalls {s.get('data_stalls', 0)}"]
        if s.get("records_quarantined"):
            parts.append(f"quarantined {s['records_quarantined']}")
        if buckets and buckets.get("data_wait"):
            parts.append(f"wait {buckets['data_wait']:.2f}s")
        lines.append("  ".join(parts))
    if s.get("stop_reason"):
        lines.append(f"stop        {s['stop_reason']}"
                     + (f"  ({s.get('steps_per_sec')} steps/s)"
                        if s.get("steps_per_sec") is not None else ""))
    counts = s.get("counts", {})
    if counts:
        lines.append("events      " + "  ".join(
            f"{k}={v}" for k, v in counts.items()))
    return "\n".join(lines)


#: Scalar rows the A/B diff table compares.
_DIFF_ROWS = (
    ("steps", "steps", "{:d}"),
    ("skipped_steps", "skipped", "{:d}"),
    ("step_ms_p50", "p50 (ms)", "{:.2f}"),
    ("step_ms_p95", "p95 (ms)", "{:.2f}"),
    ("step_ms_p99", "p99 (ms)", "{:.2f}"),
    ("goodput", "goodput", "{:.3f}"),
    ("steps_per_sec", "steps/s", "{:.3f}"),
    ("data_stalls", "data stalls", "{:d}"),
    ("serving_tpot_p50", "tpot p50 (ms)", "{:.2f}"),
    # speculation health (ISSUE 12): committed tokens per decode-step
    # row — the accepted-tokens-per-step headline
    ("serving_accepted_tokens_per_step", "acc tok/step", "{:.3f}"),
    # overload health (ISSUE 10): did the change move the SLO story?
    ("serving_deadline_hit_rate", "deadline hit", "{:.3f}"),
    # memory-lean serving (r17): did prefix sharing land, and did the
    # quantized pool move the occupancy high-water mark?
    ("serving_prefix_hit_rate", "prefix hit", "{:.3f}"),
    ("serving_pool_peak", "pool peak", "{:.3f}"),
    # disaggregation health (r18): did the change push shipments past
    # their retry budget into local-prefill fallbacks?
    ("serving_ship_fallback_rate", "ship fallback", "{:.3f}"),
    # TTFT decomposition (r19): WHERE did the first-token wait move —
    # intake queue, prefill compute, the KV ship wall, or decode entry?
    ("serving_ttft_queue_ms", "ttft queue", "{:.2f}"),
    ("serving_ttft_prefill_ms", "ttft prefill", "{:.2f}"),
    ("serving_ttft_ship_ms", "ttft ship", "{:.2f}"),
    ("serving_ttft_decode_wait_ms", "ttft dec-wait", "{:.2f}"),
    # phase-attribution rows (ISSUE 9): did the change move exposed
    # communication or the memory high-water mark?
    ("exposed_collective_ms", "exposed (ms)", "{:.2f}"),
    ("hbm_peak_gb", "hbm peak (GB)", "{:.2f}"),
)


#: Per-phase diff rows are dynamic (phases present in either summary).
def _phase_diff_rows(a: Dict[str, Any], b: Dict[str, Any]):
    pa, pb = a.get("phase_ms") or {}, b.get("phase_ms") or {}
    for k in sorted(set(pa) | set(pb)):
        yield (k, pa.get(k), pb.get(k))


def format_diff(a: Dict[str, Any], b: Dict[str, Any]) -> str:
    """A/B comparison table of two summaries (A = first file, the
    baseline; delta = B - A, with a ratio where it makes sense)."""
    name_a = str(a.get("run_id"))[:24]
    name_b = str(b.get("run_id"))[:24]
    lines = [f"{'':<12} {'A: ' + name_a:>28} {'B: ' + name_b:>28} "
             f"{'delta':>12}"]
    for key, label, fmt in _DIFF_ROWS:
        va, vb = a.get(key), b.get(key)
        fa = fmt.format(va) if va is not None else "n/a"
        fb = fmt.format(vb) if vb is not None else "n/a"
        if va is not None and vb is not None:
            d = vb - va
            delta = f"{d:+.3f}" if isinstance(d, float) else f"{d:+d}"
            if va not in (0, None) and key not in ("steps", "skipped_steps",
                                                   "data_stalls"):
                delta += f" ({vb / va:.2f}x)"
        else:
            delta = "n/a"
        lines.append(f"{label:<12} {fa:>28} {fb:>28} {delta:>12}")
    for phase, va, vb in _phase_diff_rows(a, b):
        fa = f"{va:.2f}" if va is not None else "n/a"
        fb = f"{vb:.2f}" if vb is not None else "n/a"
        if va is not None and vb is not None:
            delta = f"{vb - va:+.3f}"
            if va:
                delta += f" ({vb / va:.2f}x)"
        else:
            delta = "n/a"
        lines.append(f"{'ph:' + phase:<12} {fa:>28} {fb:>28} {delta:>12}")
    return "\n".join(lines)
