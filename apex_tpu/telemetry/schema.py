"""JSONL event-schema validator (CI/tooling tier).

Telemetry is only useful if every producer agrees on the record shape —
a stream a tool can't parse is a ``print`` with extra steps.  This is a
small hand-rolled validator (no jsonschema dependency; the container
rule is "stub or gate missing deps") enforcing:

- the universal stamp every event carries (``type`` in
  :data:`~apex_tpu.telemetry.bus.EVENT_TYPES`, ``run_id`` str,
  ``step`` int-or-None, ``t``/``ts`` numbers, ``mesh`` dict);
- per-type required payload fields with their types
  (:data:`PAYLOAD_REQUIRED`);
- JSON-serializability (an event that can't round-trip through
  ``json`` would poison the sink file).

Tests run every emitted event through :func:`validate_event`;
:func:`validate_jsonl` checks a whole file (e.g. a postmortem).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List

from apex_tpu.telemetry.bus import EVENT_TYPES

NUMBER = (int, float)

#: Universal stamp: field -> allowed types (None allowed where noted).
STAMP_REQUIRED: Dict[str, tuple] = {
    "type": (str,),
    "run_id": (str,),
    "step": (int, type(None)),
    "t": NUMBER,
    "ts": NUMBER,
    "mesh": (dict,),
}

#: Per-type required payload fields -> allowed types.
PAYLOAD_REQUIRED: Dict[str, Dict[str, tuple]] = {
    "run_start": {},
    "run_end": {"goodput": NUMBER, "steps": (int,), "wall_s": NUMBER,
                "reason": (str,)},
    "step": {"step_ms": NUMBER},
    "ckpt_save": {"blocking": (bool,)},
    "ckpt_restore": {},
    "skip": {"consecutive": (int,), "total_skipped": (int,)},
    "watchdog": {"report": (dict,)},
    "device_loss": {"device_ids": (list,)},
    "recompile": {},
    "fault_injected": {"kind": (str,)},
    "timers": {"timers_ms": (dict,)},
    "postmortem": {"reason": (str,), "ring_events": (int,)},
    "data_stall": {"wait_ms": NUMBER, "cause": (str,)},
    "data_quarantine": {"record_id": (int,), "reason": (str,),
                        "total": (int,)},
    # serving events (ISSUE 8): latency fields (ttft_ms/tpot_ms on
    # retire, step_ms/evicted on decode_step) are optional — a
    # one-token request has no TPOT, and optionality must be explicit
    # in the schema, not smuggled via sentinel values
    "request_admit": {"rid": (int,), "context_tokens": (int,),
                      "pages": (int,), "preemptions": (int,)},
    "request_retire": {"rid": (int,), "reason": (str,),
                       "new_tokens": (int,), "preemptions": (int,)},
    "decode_step": {"batch": (int,), "new_tokens": (int,),
                    "pool_used": (int,), "pool_pages": (int,)},
    # serving resilience (ISSUE 10): overload rejects, deadline deaths
    # (where = "queued" shed / "running" timeout), and crash recovery.
    # pool_rebuilt is a REAL bool (bool-not-int discipline); the
    # optional deadline_hit on request_retire is likewise a bool,
    # present only when the request carried a deadline
    "request_reject": {"rid": (int,), "reason": (str,),
                       "queue_depth": (int,)},
    "request_timeout": {"rid": (int,), "where": (str,),
                        "overshoot_ms": NUMBER},
    "serving_recovery": {"cause": (str,), "pool_rebuilt": (bool,),
                         "running_restored": (int,),
                         "waiting_restored": (int,)},
    # in-run attribution (ISSUE 9): the ProfileSampler's window result.
    # phase_ms maps phase -> device ms; exposed_collective_ms is the
    # overlap-analysis headline; overhead_ms is the sampler's own host
    # cost for this window (also booked to the `profile` goodput bucket)
    "profile": {"window_steps": (int,), "phase_ms": (dict,),
                "exposed_collective_ms": NUMBER,
                "collective_ms": NUMBER, "total_device_ms": NUMBER,
                "overhead_ms": NUMBER},
    # HBM sample: stats_available is a REAL bool (bool-not-int
    # discipline); live/peak/limit bytes are present only when the
    # backend exposes memory_stats — optionality explicit, no sentinels
    "memory": {"stats_available": (bool,), "n_devices": (int,)},
}


class SchemaError(ValueError):
    """An event violates the telemetry schema."""


def _type_names(types: tuple) -> str:
    return "/".join(t.__name__ for t in types)


def validate_event(event: Any) -> Dict[str, Any]:
    """Validate one event dict; returns it (for chaining) or raises
    :class:`SchemaError` naming the offending field."""
    if not isinstance(event, dict):
        raise SchemaError(f"event must be a dict, got {type(event).__name__}")
    for field, types in STAMP_REQUIRED.items():
        if field not in event:
            raise SchemaError(f"missing stamp field {field!r}: {event}")
        if not isinstance(event[field], types):
            raise SchemaError(
                f"stamp field {field!r} must be {_type_names(types)}, got "
                f"{type(event[field]).__name__} ({event[field]!r})")
    etype = event["type"]
    if etype not in EVENT_TYPES:
        raise SchemaError(
            f"unknown event type {etype!r}; known: {sorted(EVENT_TYPES)}")
    for field, types in PAYLOAD_REQUIRED[etype].items():
        if field not in event:
            raise SchemaError(
                f"{etype} event missing required field {field!r}: {event}")
        # bool is an int subclass; an int-typed field must not accept it
        v = event[field]
        if isinstance(v, bool) and bool not in types:
            raise SchemaError(
                f"{etype}.{field} must be {_type_names(types)}, got bool")
        if not isinstance(v, types):
            raise SchemaError(
                f"{etype}.{field} must be {_type_names(types)}, got "
                f"{type(v).__name__} ({v!r})")
    try:
        json.dumps(event)
    except (TypeError, ValueError) as e:
        raise SchemaError(f"event not JSON-serializable: {e}") from e
    return event


def validate_events(events: Iterable[Dict[str, Any]]) -> int:
    """Validate an iterable of events; returns the count."""
    n = 0
    for ev in events:
        validate_event(ev)
        n += 1
    return n


def load_jsonl(path: str,
               tolerate_torn_tail: bool = False) -> List[Dict[str, Any]]:
    """Parse a telemetry/postmortem JSONL file (blank lines skipped).

    ``tolerate_torn_tail`` — a SIGKILL/OOM-kill or ENOSPC can leave one
    partial final line despite the sink's per-event flush; the
    *summarize* path drops that torn last line instead of refusing the
    stream (the crashed stream is exactly the one an operator most
    needs summarized).  ``validate`` stays strict."""
    out = []
    with open(path) as f:
        lines = f.readlines()
    last_payload = max((i for i, ln in enumerate(lines, 1) if ln.strip()),
                       default=0)
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError as e:
            if tolerate_torn_tail and i == last_payload:
                break
            raise SchemaError(f"{path}:{i}: not valid JSON: {e}") from e
    return out


def validate_jsonl(path: str) -> int:
    """Validate every event in a JSONL file; returns the count."""
    return validate_events(load_jsonl(path))
