"""Telemetry event schema: the single-sourced field-spec tables.

Telemetry is only useful if every producer agrees on the record shape —
a stream a tool can't parse is a ``print`` with extra steps.  This
module owns that contract (ISSUE 11 satellite):

- :data:`EVENT_FIELDS` — per event type, every known payload field with
  its allowed types and whether it is required.  This is THE table:
  :func:`validate_event` (the runtime/CI validator) and the
  ``apex_tpu.analysis`` TL001 lint rule both consume it, so the schema
  can never drift from the linter;
- :data:`EVENT_TYPES` — **derived** from :data:`EVENT_FIELDS`
  (``frozenset(EVENT_FIELDS)``), re-exported by
  :mod:`apex_tpu.telemetry.bus` whose ``emit`` rejects anything else.
  An event type therefore cannot exist without a field spec — the
  drift the PR 4 → PR 10 era policed by reviewer memory is now
  impossible by construction (pinned in ``tests/L0/test_analysis.py``);
- the universal stamp every event carries (:data:`STAMP_REQUIRED`);
- bool-not-int discipline: ``bool`` is an ``int`` subclass in Python,
  so an int-typed field must explicitly reject bools and vice versa —
  a ``1`` where the schema says ``True`` breaks every downstream
  ``is True`` check and the ``--diff`` ratio math.

This module is deliberately **stdlib-only and import-light**: the
linter loads it without touching jax or any checked module, which is
what keeps the lint gate an AST-speed CI step.

Tests run every emitted event through :func:`validate_event`;
:func:`validate_jsonl` checks a whole file (e.g. a postmortem).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, NamedTuple

NUMBER = (int, float)
OPT_NUMBER = (int, float, type(None))


class FieldSpec(NamedTuple):
    """One payload field's contract: allowed types + requiredness.

    ``required=False`` fields are OPTIONAL — absent entirely when the
    producer has nothing to say (a one-token request has no TPOT, a
    CPU backend has no HBM stats).  Optionality must be explicit in
    the schema, never smuggled via sentinel values.

    ``choices`` (ISSUE 16) closes a string field over an enum: a
    reason/hint field whose consumers branch on its value must not
    grow ad-hoc spellings — ``validate_event`` rejects values outside
    the set, the same single-source discipline as bool-not-int."""

    types: tuple
    required: bool = True
    choices: tuple = ()


def opt(*types, choices=()) -> FieldSpec:
    """An optional field spec (shorthand for the table below)."""
    return FieldSpec(tuple(types), required=False, choices=tuple(choices))


def req(*types, choices=()) -> FieldSpec:
    """A required field spec (shorthand for the table below)."""
    return FieldSpec(tuple(types), required=True, choices=tuple(choices))


#: The closed event vocabulary WITH its per-field contracts.  Every
#: field a producer names literally at an emit site must appear here —
#: the TL001 lint rule enforces that; ``validate_event`` type-checks
#: required fields always and optional fields whenever present.
EVENT_FIELDS: Dict[str, Dict[str, FieldSpec]] = {
    # loop (re)entered: config snapshot, start step.  workload/config/
    # fast come from the bench and example entrypoints (bench.py,
    # pretrain_gpt.py) — the table covers EVERY producer in the repo,
    # not just the apex_tpu package, or TL001 flags them
    "run_start": {
        "save_every": opt(int),
        "async_saves": opt(bool),
        "sharded": opt(bool),
        "watchdog": opt(bool),
        "guarded": opt(bool),
        "workload": opt(str),
        "config": opt(dict),
        "fast": opt(bool),
    },
    # loop exited: goodput buckets, stop reason
    "run_end": {
        "goodput": req(*NUMBER),
        "steps": req(int),
        "wall_s": req(*NUMBER),
        "reason": req(str),
        "skips": opt(int),
        "steps_per_sec": opt(*NUMBER),
        "buckets_s": opt(dict),
        "scalars": opt(dict),
    },
    # one train step: wall split + windowed scalars
    "step": {
        "step_ms": req(*NUMBER),
        "compile_ms": opt(*NUMBER),
        "data_wait_ms": opt(*NUMBER),
        "skipped": opt(bool),
        "scalars": opt(dict),
        "timing": opt(str),
    },
    # checkpoint write issued (blocking or async)
    "ckpt_save": {
        "blocking": req(bool),
        "wall_ms": opt(*NUMBER),
    },
    # restore completed (incl. elastic re-partition)
    "ckpt_restore": {
        "wall_ms": opt(*NUMBER),
        "n_shards": opt(int),
        "reason": opt(str),
    },
    # divergence guard skipped a non-finite step
    "skip": {
        "consecutive": req(int),
        "total_skipped": req(int),
        "total_steps": opt(int),
        "grad_norm": opt(*OPT_NUMBER),
        "loss_scale": opt(*OPT_NUMBER),
    },
    # collective watchdog fired: straggler report
    "watchdog": {
        "report": req(dict),
    },
    # mesh device(s) disappeared; elastic rebuild
    "device_loss": {
        "device_ids": req(list),
        "survivors": opt(int),
        "restarts": opt(int),
        "recoverable": opt(bool),
        "mesh_axes": opt(dict),
    },
    # XLA backend compile observed mid-run
    "recompile": {
        "duration_ms": opt(*NUMBER),
        "source": opt(str),
    },
    # chaos tier injected a fault (test streams)
    "fault_injected": {
        "kind": req(str),
        "event": opt(str),
        "path": opt(str),
        "device_ids": opt(list),
        "at_poll": opt(int),
        "at_step": opt(int),
        "at_decode_step": opt(int),
        "axis": opt(str, type(None)),
        "delay_s": opt(*NUMBER),
        "page": opt(int),
        "use_signal": opt(bool),
        # fleet chaos (ISSUE 16): the replica the injector targeted
        "replica": opt(str),
    },
    # pipeline-parallel Timers.log snapshot
    "timers": {
        "timers_ms": req(dict),
        "normalizer": opt(*NUMBER),
    },
    # flight-recorder flush header
    "postmortem": {
        "reason": req(str),
        "ring_events": req(int),
        "path": opt(str),
        "watchdog": opt(dict),
    },
    # input pipeline made the step wait (dry prefetch queue, slow
    # shard read, shard re-assignment)
    "data_stall": {
        "wait_ms": req(*NUMBER),
        "cause": req(str),
        "depth": opt(int),
    },
    # a damaged record was skipped and counted
    "data_quarantine": {
        "record_id": req(int),
        "reason": req(str),
        "total": req(int),
        "rate": opt(*NUMBER),
    },
    # serving (ISSUE 8): latency fields (ttft_ms/tpot_ms on retire,
    # step_ms/evicted on decode_step) are optional — a one-token
    # request has no TPOT
    "request_admit": {
        "rid": req(int),
        "context_tokens": req(int),
        "pages": req(int),
        "preemptions": req(int),
        # a REAL bool, present only when the request admitted into
        # chunked prefill (ISSUE 12) — absent means whole-row
        "chunked": opt(bool),
        # a REAL bool (r17): present on EVERY admit while prefix
        # sharing is on — True when the page-aligned prompt prefix
        # matched the PrefixIndex (shared pages pinned, prefill
        # resumed past the match), False on a miss.  Emitting misses
        # too is what gives summarize its hit-rate denominator;
        # absent entirely means sharing was off
        "prefix_hit": opt(bool),
    },
    "request_retire": {
        "rid": req(int),
        "reason": req(str),
        "new_tokens": req(int),
        "preemptions": req(int),
        "ttft_ms": opt(*NUMBER),
        "tpot_ms": opt(*NUMBER),
        # a REAL bool, present only when the request carried a deadline
        "deadline_hit": opt(bool),
        # r19 shipping-aware SLO accounting: the kv_ship wall this
        # request paid between prefill-side export and decode-side
        # adoption (== its kv_export.start -> kv_import.end span
        # segment).  Present only on shipped requests — ttft_ms on
        # those is STREAM TTFT (first token available to the decode
        # replica), so the ship wall lands in TTFT, not TPOT
        "ship_ms": opt(*NUMBER),
    },
    "decode_step": {
        "batch": req(int),
        "new_tokens": req(int),
        "pool_used": req(int),
        "pool_pages": req(int),
        "evicted": opt(list),
        "step_ms": opt(*NUMBER),
        # speculative verify boundaries (ISSUE 12): present only when
        # the step ran the draft–verify executable.  spec_verify is a
        # REAL bool; spec_drafted/spec_accepted count draft tokens
        # launched/model-endorsed this step (new_tokens carries the
        # committed total, so accepted-tokens-per-step falls out of
        # new_tokens / batch on ANY stream, speculative or not)
        "spec_verify": opt(bool),
        "spec_drafted": opt(int),
        "spec_accepted": opt(int),
        # prefix sharing (r17): pages currently referenced by more
        # than one holder (an int COUNT, never a bool — pairs with
        # pool_used for the memory-saved story); present only while
        # prefix sharing is on
        "pool_shared_pages": opt(int),
    },
    # serving resilience (ISSUE 10): overload rejects, deadline deaths
    # (where = "queued" shed / "running" timeout), crash recovery.
    # pool_rebuilt is a REAL bool (bool-not-int discipline).
    # reason is CLOSED (ISSUE 16): "queue_full" is backpressure (retry
    # elsewhere / later), "unservable" is permanent refusal by this
    # engine's geometry (retrying the same replica is futile) — the
    # fleet router branches on exactly this distinction
    "request_reject": {
        "rid": req(int),
        "reason": req(str, choices=("queue_full", "unservable")),
        "queue_depth": req(int),
    },
    "request_timeout": {
        "rid": req(int),
        "where": req(str),
        "overshoot_ms": req(*NUMBER),
    },
    "serving_recovery": {
        "cause": req(str),
        "pool_rebuilt": req(bool),
        "running_restored": req(int),
        "waiting_restored": req(int),
    },
    # a wedged engine is observable (ISSUE 16 satellite): run()/serve()
    # exhausted their step budget with live requests still queued
    "serving_stall": {
        "waiting": req(int),
        "running": req(int),
        "budget": req(int),
    },
    # serving fleet (ISSUE 16): a replica leaving rotation (its engine
    # burned through max_recoveries, its health check timed out, or a
    # rolling restart is draining it), each live request's migration
    # hop, and the autoscaling SIGNAL (never an action) derived from
    # SLO attainment / shed rate / pool occupancy
    "replica_fence": {
        "replica": req(str),
        "cause": req(str),
        "live_requests": req(int),
        "recoveries": opt(int),
        "fault_retries": opt(int),
    },
    "request_migrate": {
        "rid": req(int),
        "from_replica": req(str),
        "to_replica": req(str),
        "tokens_done": req(int),
        # a REAL bool: the request was mid-flight (holding pages) on
        # the source when fenced, vs still queued
        "was_running": req(bool),
    },
    "fleet_scale_hint": {
        "hint": req(str, choices=("scale_up", "hold", "scale_down")),
        "shed_rate": req(*NUMBER),
        "occupancy": req(*NUMBER),
        "replicas": req(int),
        "healthy": req(int),
        # absent when no request carried a deadline in the window —
        # optional means absent, never a sentinel
        "deadline_hit_rate": opt(*NUMBER),
    },
    # disaggregated prefill/decode (r18): one kv_ship per completed
    # KV page shipment (prefill replica -> decode replica; attempts
    # counts transfer-level retries that preceded success),
    # kv_ship_retry per bounded retry (reason is CLOSED: transport
    # loss/lateness, in-flight corruption caught at the envelope, a
    # page refused by the receiver's CRC check, pages missing at
    # commit, or a capacity refusal by the decode engine), and
    # kv_ship_fallback when the retry budget is spent and the request
    # degrades to LOCAL prefill on the decode replica — slower, never
    # dropped
    "kv_ship": {
        "rid": req(int),
        "from_replica": req(str),
        "to_replica": req(str),
        "pages": req(int),
        "payload_bytes": req(int),
        "attempts": req(int),
    },
    "kv_ship_retry": {
        "rid": req(int),
        "from_replica": req(str),
        "to_replica": req(str),
        "attempt": req(int),
        "reason": req(str, choices=("timeout", "corrupt",
                                    "crc_mismatch", "missing_pages",
                                    "no_capacity")),
        # absent on immediate per-page re-sends (no backoff round)
        "backoff_rounds": opt(int),
    },
    "kv_ship_fallback": {
        "rid": req(int),
        "from_replica": req(str),
        "to_replica": req(str),
        "attempts": req(int),
        "reason": req(str, choices=("timeout", "corrupt",
                                    "crc_mismatch", "missing_pages",
                                    "no_capacity")),
    },
    # distributed request tracing (r19): one `span` event per closed
    # causal interval in a request's fleet-wide life.  trace_id IS the
    # fleet rid; span_id/parent_id are DERIVED from application-level
    # identity (rid, admission life, transfer attempt, hop endpoints)
    # — never from transport msg ids, whose sender retries mint fresh
    # ones — so re-emission under at-most-once redelivery is
    # idempotent (reconstruction merges identical ids).  t_start/t_end
    # are on the fleet's SHARED engine clock (monotonic / SimClock),
    # NOT the per-bus stamp `t`, so spans recorded on different
    # replicas' streams join on one time base.  kind is CLOSED;
    # kv_ship spans carry one span PER ATTEMPT with the outcome typed
    # (ok / retry / fallback / retarget) and the retry reason
    "span": {
        "rid": req(int),
        "span_id": req(str),
        # absent = root-level span of its trace (never a dangling ref)
        "parent_id": opt(str),
        "kind": req(str, choices=("queue_wait", "admit",
                                  "prefill_chunk", "kv_export",
                                  "kv_ship", "kv_import",
                                  "decode_wait", "decode_steps",
                                  "migrate_hop", "stream_emit")),
        "t_start": req(*NUMBER),
        "t_end": req(*NUMBER),
        # emitting side, when fleet-scoped (absent on bare engines)
        "replica": opt(str),
        # kv_ship / kv_import: 1-based transfer attempt
        "attempt": opt(int),
        # kv_ship per-attempt outcome — typed annotations, CLOSED
        "outcome": opt(str, choices=("ok", "retry", "fallback",
                                     "retarget")),
        # retry/fallback cause (the kv_ship_retry reason vocabulary)
        "reason": opt(str, choices=("timeout", "corrupt",
                                    "crc_mismatch", "missing_pages",
                                    "no_capacity")),
    },
    # a migration plan refused whole (r18 satellite): the FULL
    # unplaceable rid list plus required-vs-available page counts —
    # the numbers an operator sizes capacity from
    "migrate_refused": {
        "replica": req(str),
        "unplaceable": req(list),
        "requests": req(int),
        "pages_required": req(int),
        "pages_available": req(int),
    },
    # in-run attribution (ISSUE 9): the ProfileSampler's window result.
    # exposed_collective_ms is the overlap-analysis headline;
    # overhead_ms is the sampler's own host cost for this window (also
    # booked to the `profile` goodput bucket)
    "profile": {
        "window_steps": req(int),
        "phase_ms": req(dict),
        "exposed_collective_ms": req(*NUMBER),
        "collective_ms": req(*NUMBER),
        "total_device_ms": req(*NUMBER),
        "overhead_ms": req(*NUMBER),
        "span_ms": opt(*NUMBER),
        "n_ops": opt(int),
        "top_ops": opt(list),
    },
    # HBM sample: stats_available is a REAL bool; byte fields are
    # present only when the backend exposes memory_stats
    "memory": {
        "stats_available": req(bool),
        "n_devices": req(int),
        "live_bytes": opt(int),
        "peak_bytes": opt(int),
        "limit_bytes": opt(int),
    },
}

#: The typed event vocabulary — DERIVED from the field table, so an
#: event type without a field spec cannot exist.  ``bus.EVENT_TYPES``
#: re-exports this object.
EVENT_TYPES = frozenset(EVENT_FIELDS)

#: Legacy view: per-type REQUIRED payload fields -> allowed types
#: (kept for callers written against the pre-ISSUE-11 shape).
PAYLOAD_REQUIRED: Dict[str, Dict[str, tuple]] = {
    etype: {f: spec.types for f, spec in fields.items() if spec.required}
    for etype, fields in EVENT_FIELDS.items()
}

#: Universal stamp: field -> allowed types (None allowed where noted).
STAMP_REQUIRED: Dict[str, tuple] = {
    "type": (str,),
    "run_id": (str,),
    "step": (int, type(None)),
    "t": NUMBER,
    "ts": NUMBER,
    "mesh": (dict,),
}


class SchemaError(ValueError):
    """An event violates the telemetry schema."""


def _type_names(types: tuple) -> str:
    return "/".join(t.__name__ for t in types)


def _check_field(etype: str, field: str, v: Any, types: tuple,
                 choices: tuple = ()) -> None:
    # bool is an int subclass; an int-typed field must not accept it
    if isinstance(v, bool) and bool not in types:
        raise SchemaError(
            f"{etype}.{field} must be {_type_names(types)}, got bool")
    if not isinstance(v, types):
        raise SchemaError(
            f"{etype}.{field} must be {_type_names(types)}, got "
            f"{type(v).__name__} ({v!r})")
    if choices and v not in choices:
        raise SchemaError(
            f"{etype}.{field} must be one of {sorted(choices)}, got {v!r}")


def validate_event(event: Any) -> Dict[str, Any]:
    """Validate one event dict; returns it (for chaining) or raises
    :class:`SchemaError` naming the offending field.

    Required fields must be present with a spec-conforming type;
    optional fields are type-checked whenever present.  Fields not in
    the spec are tolerated at runtime (producers may attach ad-hoc
    context via ``**payload``) — but fields named *literally* at an
    emit site are held to the table by the TL001 lint rule."""
    if not isinstance(event, dict):
        raise SchemaError(f"event must be a dict, got {type(event).__name__}")
    for field, types in STAMP_REQUIRED.items():
        if field not in event:
            raise SchemaError(f"missing stamp field {field!r}: {event}")
        if not isinstance(event[field], types):
            raise SchemaError(
                f"stamp field {field!r} must be {_type_names(types)}, got "
                f"{type(event[field]).__name__} ({event[field]!r})")
    etype = event["type"]
    if etype not in EVENT_FIELDS:
        raise SchemaError(
            f"unknown event type {etype!r}; known: {sorted(EVENT_TYPES)}")
    for field, spec in EVENT_FIELDS[etype].items():
        if field not in event:
            if spec.required:
                raise SchemaError(
                    f"{etype} event missing required field {field!r}: "
                    f"{event}")
            continue
        _check_field(etype, field, event[field], spec.types, spec.choices)
    try:
        json.dumps(event)
    except (TypeError, ValueError) as e:
        raise SchemaError(f"event not JSON-serializable: {e}") from e
    return event


def validate_events(events: Iterable[Dict[str, Any]]) -> int:
    """Validate an iterable of events; returns the count."""
    n = 0
    for ev in events:
        validate_event(ev)
        n += 1
    return n


def load_jsonl(path: str,
               tolerate_torn_tail: bool = False) -> List[Dict[str, Any]]:
    """Parse a telemetry/postmortem JSONL file (blank lines skipped).

    ``tolerate_torn_tail`` — a SIGKILL/OOM-kill or ENOSPC can leave one
    partial final line despite the sink's per-event flush; the
    *summarize* path drops that torn last line instead of refusing the
    stream (the crashed stream is exactly the one an operator most
    needs summarized).  ``validate`` stays strict."""
    out = []
    with open(path) as f:
        lines = f.readlines()
    last_payload = max((i for i, ln in enumerate(lines, 1) if ln.strip()),
                       default=0)
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError as e:
            if tolerate_torn_tail and i == last_payload:
                break
            raise SchemaError(f"{path}:{i}: not valid JSON: {e}") from e
    return out


def validate_jsonl(path: str) -> int:
    """Validate every event in a JSONL file; returns the count."""
    return validate_events(load_jsonl(path))
