"""Training telemetry: metrics bus, goodput accounting, flight recorder.

The *online* observability layer (ISSUE 4) — the offline half (trace
capture, per-op attribution) is :mod:`apex_tpu.profiling`:

- **bus** — :class:`TelemetryBus` with a closed set of typed events
  (:data:`EVENT_TYPES`) and pluggable sinks (:class:`JsonlSink`,
  :class:`MemorySink`, :class:`StdoutSink`); every event stamped with
  run id, step, monotonic time, and mesh topology;
- **accounting** — :class:`StepAccountant` splits wall time into
  data-wait / step / checkpoint-fence (+ restore / rebuild / compile)
  buckets, batches scalar fetches into one ``device_get`` per logging
  window, and computes **goodput** (productive-step fraction);
- **flight recorder** — :class:`FlightRecorder` ring of the last N
  events, flushed to ``postmortem_*.jsonl`` on SIGTERM, watchdog
  escalation, or device loss (``bus.flush_postmortem``);
- **schema** — :func:`validate_event` / :func:`validate_jsonl`, the
  CI-side contract every producer is tested against;
- **sampler** — :class:`ProfileSampler` (ISSUE 9): periodic in-run
  capture + phase/collective/HBM attribution through the bus
  (``profile``/``memory`` events), overhead booked to its own goodput
  bucket and budget-bounded ≤1%;
- **tracing** — :mod:`apex_tpu.telemetry.tracing` (ISSUE 19):
  request-scoped causal spans over the fleet (``span`` events; trace
  id = fleet rid), reconstruction of per-request span trees from any
  set of per-replica streams, critical-path extraction, TTFT
  decomposition, and the fleet flight recorder
  (:func:`~apex_tpu.telemetry.tracing.maybe_dump_flight_record`);
- **CLI** — ``python -m apex_tpu.telemetry summarize run.jsonl``
  (p50/p95/p99 step time, goodput %, phase breakdown, event counts,
  ``--diff`` A/B; ``regress A.json B.json --max-regress PCT`` — the
  BENCH-record CI gate; ``trace STREAM.jsonl...`` — span-tree
  reconstruction + TTFT decomposition).

See ``docs/telemetry.md`` for the event schema and wiring examples.
"""

from apex_tpu.telemetry.accounting import (  # noqa: F401
    PAUSE_KINDS,
    StepAccountant,
)
from apex_tpu.telemetry.bus import (  # noqa: F401
    EVENT_TYPES,
    JsonlSink,
    MemorySink,
    StdoutSink,
    TelemetryBus,
    TelemetryError,
    default_mesh_topology,
    install_recompile_listener,
)
from apex_tpu.telemetry.recorder import FlightRecorder  # noqa: F401
from apex_tpu.telemetry.regress import (  # noqa: F401
    load_multichip_record,
)
from apex_tpu.telemetry.sampler import (  # noqa: F401
    JaxProfilerTracer,
    ProfileSampler,
    device_memory_payload,
)
from apex_tpu.telemetry.schema import (  # noqa: F401
    SchemaError,
    load_jsonl,
    validate_event,
    validate_events,
    validate_jsonl,
)
from apex_tpu.telemetry.summarize import (  # noqa: F401
    format_diff,
    format_summary,
    summarize_events,
    summarize_file,
)
from apex_tpu.telemetry.tracing import (  # noqa: F401
    SPAN_KINDS,
    TTFT_SUM_TOLERANCE_MS,
    Span,
    Trace,
    admission_life,
    build_traces,
    critical_path,
    load_trace_streams,
    maybe_dump_flight_record,
    run_trace_cli,
    ttft_decomposition,
    validate_trace,
)

__all__ = [
    "EVENT_TYPES",
    "FlightRecorder",
    "SPAN_KINDS",
    "Span",
    "TTFT_SUM_TOLERANCE_MS",
    "Trace",
    "admission_life",
    "build_traces",
    "critical_path",
    "load_trace_streams",
    "maybe_dump_flight_record",
    "run_trace_cli",
    "ttft_decomposition",
    "validate_trace",
    "JsonlSink",
    "MemorySink",
    "PAUSE_KINDS",
    "SchemaError",
    "StdoutSink",
    "StepAccountant",
    "TelemetryBus",
    "TelemetryError",
    "default_mesh_topology",
    "format_diff",
    "format_summary",
    "install_recompile_listener",
    "JaxProfilerTracer",
    "ProfileSampler",
    "device_memory_payload",
    "load_jsonl",
    "summarize_events",
    "summarize_file",
    "validate_event",
    "validate_events",
    "validate_jsonl",
]
