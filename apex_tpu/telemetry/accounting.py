"""Per-step accounting: wall-time split, batched scalars, goodput.

Two disciplines, both about *not* paying for observability:

1. **No extra device syncs.**  Scalar metrics (loss, grad-norm, loss
   scale, skip counters) are device arrays; fetching one per step would
   serialize the pipelined dispatch the train loop works hard to keep.
   :meth:`StepAccountant.step_done` therefore only *holds the latest
   device references*; at every ``window``-th step it batches them into
   ONE ``jax.device_get`` — the same single sync the loop's
   ``log_every`` print already paid — and attaches the values to that
   step's event.

2. **Time is bucketed, not just summed.**  Each step's wall is split
   into data-wait / step / checkpoint-fence stall, and pauses the loop
   knows about (restore, elastic rebuild, compile) are booked to their
   own buckets.  **Goodput** is the productive fraction: time spent in
   non-skipped train steps over total wall — skips, restores, and
   elastic rebuilds all drag it below 1 even when "the run finished
   fine", which is exactly the number a fleet operator wants.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

#: Non-step time buckets ``pause`` accepts.  ``profile`` is the
#: ProfileSampler's own capture/parse overhead (ISSUE 9) — booked so
#: goodput stays honest about what observability itself costs.
PAUSE_KINDS = ("ckpt_fence", "restore", "rebuild", "compile", "data_wait",
               "profile", "other")


def _to_scalar(v: Any):
    """Best-effort native-typing of a fetched device scalar."""
    try:
        import numpy as np

        a = np.asarray(v)
        if a.size != 1:
            return a.tolist()
        if a.dtype.kind in "fc":
            return float(a.reshape(()))
        if a.dtype.kind in "iub":
            x = a.reshape(())
            return bool(x) if a.dtype.kind == "b" else int(x)
    except Exception:
        pass
    return v


class StepAccountant:
    """Goodput ledger + windowed scalar fetcher over a TelemetryBus.

    One per run (get it via ``bus.accountant()`` so elastic restarts
    share the ledger).  The loop calls :meth:`step_done` once per step,
    :meth:`pause` for known non-step time, :meth:`finish` on exit.
    """

    def __init__(self, bus, window: int = 10):
        self.bus = bus
        self.window = max(1, int(window))
        self.t_start = time.monotonic()
        self.buckets: Dict[str, float] = {"step": 0.0, "skipped": 0.0}
        for k in PAUSE_KINDS:
            self.buckets.setdefault(k, 0.0)
        self.steps = 0
        self.skips = 0
        self._pending: Dict[str, Any] = {}

    # -- per-step --------------------------------------------------------

    def step_done(self, step: int, *, step_s: float,
                  data_wait_s: float = 0.0, skipped: bool = False,
                  compile_s: float = 0.0,
                  scalars: Optional[Dict[str, Any]] = None,
                  **extra: Any) -> Dict[str, Any]:
        """Book one completed step and emit its ``step`` event.

        ``scalars`` — device references (or host values) to surface;
        held until the window boundary, then fetched in one batch.
        ``compile_s`` — XLA compile wall observed *inside* this step's
        measurement (the recompile listener's accumulator): booked to
        the ``compile`` bucket instead of productive step time, so a
        first-step (or mid-run reshape) compile cannot inflate goodput.
        ``step_ms`` on the event stays the full measured wall — that IS
        the step time the operator saw — with ``compile_ms`` alongside.
        ``extra`` — host-side payload merged into the event as-is
        (e.g. ``timing="amortized"`` for bench loops that only sync per
        trial)."""
        self.steps += 1
        compile_s = min(float(compile_s), float(step_s))
        self.buckets["compile"] += compile_s
        productive_s = float(step_s) - compile_s
        self.buckets["skipped" if skipped else "step"] += productive_s
        self.buckets["data_wait"] += float(data_wait_s)
        if scalars:
            self._pending.update(
                {k: v for k, v in scalars.items() if v is not None})
        payload: Dict[str, Any] = {"step_ms": round(step_s * 1e3, 3)}
        if compile_s > 0:
            payload["compile_ms"] = round(compile_s * 1e3, 3)
        if data_wait_s > 0:
            payload["data_wait_ms"] = round(data_wait_s * 1e3, 3)
        if skipped:
            payload["skipped"] = True
            self.skips += 1
        payload.update(extra)
        if self.steps % self.window == 0:
            fetched = self.fetch_scalars()
            if fetched:
                payload["scalars"] = fetched
        return self.bus.emit("step", step=step, **payload)

    def fetch_scalars(self) -> Dict[str, Any]:
        """Batch-fetch every pending device scalar in ONE device_get."""
        if not self._pending:
            return {}
        refs, self._pending = self._pending, {}
        try:
            import jax

            vals = jax.device_get(refs)
        except Exception:
            vals = refs
        return {k: _to_scalar(v) for k, v in vals.items()}

    def pause(self, seconds: float, kind: str) -> None:
        """Book non-step time the loop can attribute (see
        :data:`PAUSE_KINDS`)."""
        if kind not in PAUSE_KINDS:
            raise ValueError(
                f"unknown pause kind {kind!r}; known: {PAUSE_KINDS}")
        self.buckets[kind] += float(seconds)

    # -- aggregates ------------------------------------------------------

    def wall(self) -> float:
        return time.monotonic() - self.t_start

    def goodput(self) -> float:
        """Productive-step fraction of total wall so far (skips,
        restores, rebuilds, fences, and idle all count against it).
        Clamped to 1.0: the buckets are host-measured slices of the
        same wall, so only clock rounding could push the ratio over."""
        return min(1.0, self.buckets["step"] / max(self.wall(), 1e-9))

    def totals(self) -> Dict[str, Any]:
        wall = self.wall()
        out = {"wall_s": round(wall, 3), "steps": self.steps,
               "skips": self.skips,
               "goodput": round(self.goodput(), 4),
               "steps_per_sec": round(self.steps / max(wall, 1e-9), 3)}
        out["buckets_s"] = {k: round(v, 3)
                            for k, v in self.buckets.items() if v > 0}
        return out

    def finish(self, step: Optional[int] = None,
               reason: str = "completed") -> Dict[str, Any]:
        """Emit the ``run_end`` event carrying the ledger (and any
        scalars still pending from a partial window)."""
        payload = dict(self.totals(), reason=reason)
        fetched = self.fetch_scalars()
        if fetched:
            payload["scalars"] = fetched
        return self.bus.emit("run_end", step=step, **payload)
