"""Crash flight recorder: a bounded ring of the most recent events.

When the PR-3 watchdog escalates, a SIGTERM lands, or a chaos
``DeviceLossError`` fires, the run used to die with whatever happened
to be on stdout.  The recorder keeps the last N bus events in memory —
every type, so a postmortem shows the interleaving of steps, skips,
checkpoint saves, and watchdog heartbeats that led up to the crash —
and :meth:`TelemetryBus.flush_postmortem` dumps them to a
``postmortem_*.jsonl`` on the way down.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List


class FlightRecorder:
    """Bounded in-memory ring of telemetry events.

    ``capacity`` — events retained (default 256: at one step event per
    step plus occasional ckpt/skip events, roughly the last couple of
    hundred steps of context — enough to see a divergence spiral or a
    stall, small enough to never matter for memory)."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)

    def record(self, event: Dict[str, Any]) -> None:
        self._ring.append(event)

    def snapshot(self) -> List[Dict[str, Any]]:
        """The retained events, oldest first (a copy — safe to flush
        while the loop keeps emitting)."""
        return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        self._ring.clear()
