"""Telemetry bus: typed events, pluggable sinks, postmortem flushing.

The *online* half of the observability story (the offline half —
trace capture, per-op attribution, cost reports — lives in
:mod:`apex_tpu.profiling`).  A long-running training process emits one
structured event stream instead of scattered ``print`` lines:

    bus = TelemetryBus(run_id="gpt1p3b-0", sinks=[JsonlSink(path)])
    bus.emit("step", step=12, step_ms=208.4)
    ...
    bus.flush_postmortem(reason="SIGTERM")  # ring -> postmortem_*.jsonl
    bus.close()

Every event is stamped with the run id, the global step (when known),
monotonic time since bus creation (``t``), wall-clock time (``ts``),
and the mesh topology — so a reader can always answer *which run,
which step, which mesh, when* without joining against out-of-band
logs.

Emission is cheap by construction (a dict build plus per-sink append;
no device syncs — scalar fetching is the
:class:`~apex_tpu.telemetry.accounting.StepAccountant`'s job, batched
one ``device_get`` per logging window) and thread-safe (the
:class:`~apex_tpu.resilience.elastic.Watchdog` monitor thread emits
``watchdog`` events from outside the train loop).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Iterable, Optional

from apex_tpu.telemetry.schema import EVENT_TYPES  # noqa: F401

log = logging.getLogger("apex_tpu.telemetry")

# The typed event vocabulary (``EVENT_TYPES``) is DERIVED from the
# single-sourced field-spec table in :mod:`apex_tpu.telemetry.schema`
# (ISSUE 11): ``emit`` rejects anything outside it, and — because the
# set is ``frozenset(EVENT_FIELDS)`` — an event type cannot be added
# without its field spec, so the schema, the runtime validator, and
# the ``apex_tpu.analysis`` TL001 lint rule can never drift apart.
# Each type's meaning is documented next to its field spec there.


class TelemetryError(ValueError):
    """Raised on emit of an unknown event type (typo-guard: a stream
    with free-form types cannot be validated or diffed)."""


class JsonlSink:
    """Append events to a JSONL file, one line per event, flushed per
    write — the file must be parseable right up to a crash (it feeds
    the postmortem story, not just offline analysis)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "a")

    def write(self, event: Dict[str, Any]) -> None:
        self._f.write(json.dumps(event) + "\n")
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass


class MemorySink:
    """Keep events in a list — the test tier's sink."""

    def __init__(self):
        self.events: list = []

    def write(self, event: Dict[str, Any]) -> None:
        self.events.append(event)

    def close(self) -> None:
        pass


class StdoutSink:
    """Print events as JSON lines (operator tailing a run live)."""

    def write(self, event: Dict[str, Any]) -> None:
        print(json.dumps(event), flush=True)

    def close(self) -> None:
        pass


def default_mesh_topology() -> Dict[str, Any]:
    """Mesh stamp from the current jax runtime: device count + platform
    (enough to tell an 8-way emulated CPU mesh from a single TPU chip,
    or a pre-loss mesh from its post-rebuild survivor submesh)."""
    try:
        import jax

        devs = jax.devices()
        return {"n_devices": len(devs),
                "platform": devs[0].platform if devs else "none"}
    except Exception:  # pragma: no cover — jax not importable/initialised
        return {"n_devices": 0, "platform": "unknown"}


class TelemetryBus:
    """Low-overhead structured event stream for long-running training.

    ``sinks`` — any objects with ``write(event_dict)`` / ``close()``
    (:class:`JsonlSink`, :class:`MemorySink`, :class:`StdoutSink`).
    ``recorder`` — a :class:`~apex_tpu.telemetry.recorder.FlightRecorder`
    holding the last-N events for crash postmortems; one is created by
    default so every bus can flush a postmortem.  ``mesh`` — the
    topology stamp applied to every event; update it via
    :meth:`set_mesh` when an elastic rebuild shrinks the mesh.
    ``postmortem_dir`` — where :meth:`flush_postmortem` writes; defaults
    to the first JsonlSink's directory, else the cwd.
    """

    def __init__(self, run_id: Optional[str] = None, *,
                 sinks: Iterable = (), recorder: Any = None,
                 mesh: Optional[Dict[str, Any]] = None,
                 postmortem_dir: Optional[str] = None):
        if recorder is None:
            from apex_tpu.telemetry.recorder import FlightRecorder

            recorder = FlightRecorder()
        self.run_id = run_id or f"run-{os.getpid()}-{int(time.time())}"
        self.sinks = list(sinks)
        self.recorder = recorder
        self.mesh = dict(mesh) if mesh is not None else (
            default_mesh_topology())
        self.counts: Dict[str, int] = {}
        self.t0 = time.monotonic()
        self._postmortem_dir = postmortem_dir
        self._postmortems = 0
        self._accountant = None
        self._watchdog = None
        self._lock = threading.Lock()

    # -- emission --------------------------------------------------------

    def _stamp(self, type: str, step: Optional[int],
               payload: Dict[str, Any]) -> Dict[str, Any]:
        ev = {
            "type": type,
            "run_id": self.run_id,
            "step": int(step) if step is not None else None,
            "t": round(time.monotonic() - self.t0, 6),
            "ts": round(time.time(), 3),
            "mesh": self.mesh,
        }
        ev.update(payload)
        return ev

    def emit(self, type: str, *, step: Optional[int] = None,
             **payload: Any) -> Dict[str, Any]:
        """Stamp and fan out one event; returns the stamped dict."""
        if type not in EVENT_TYPES:
            raise TelemetryError(
                f"unknown event type {type!r}; known: "
                f"{sorted(EVENT_TYPES)}")
        ev = self._stamp(type, step, payload)
        with self._lock:
            self.counts[type] = self.counts.get(type, 0) + 1
            if self.recorder is not None:
                self.recorder.record(ev)
            for s in list(self.sinks):
                try:
                    s.write(ev)
                except Exception:
                    # observability must never kill the run it observes
                    # (ENOSPC on the stream file, a broken pipe): log,
                    # drop the sink, keep training.  The in-memory
                    # recorder still holds the ring for a postmortem.
                    log.exception("telemetry sink %s failed; dropping it",
                                  s.__class__.__name__)
                    self.sinks.remove(s)
        return ev

    def set_mesh(self, mesh: Dict[str, Any]) -> None:
        """Update the topology stamp (elastic rebuild on a submesh).
        Applies to events emitted from now on."""
        with self._lock:
            self.mesh = dict(mesh)

    # -- shared accounting / watchdog attachment -------------------------

    def accountant(self, window: int = 10):
        """The bus's shared :class:`StepAccountant` (created on first
        call).  Shared so elastic restarts keep one goodput ledger
        across inner-loop invocations instead of resetting it."""
        if self._accountant is None:
            from apex_tpu.telemetry.accounting import StepAccountant

            self._accountant = StepAccountant(self, window=window)
        return self._accountant

    def attach_watchdog(self, watchdog) -> None:
        """Remember the run's watchdog so postmortems include its
        per-device heartbeat ages, and give the watchdog this bus to
        emit ``watchdog`` events on escalation."""
        self._watchdog = watchdog
        if getattr(watchdog, "telemetry", None) is None:
            watchdog.telemetry = self

    # -- postmortem ------------------------------------------------------

    @property
    def postmortem_dir(self) -> str:
        if self._postmortem_dir:
            return self._postmortem_dir
        for s in self.sinks:
            if isinstance(s, JsonlSink):
                return os.path.dirname(s.path)
        return os.getcwd()

    def flush_postmortem(self, reason: str, *, step: Optional[int] = None,
                         watchdog: Any = None,
                         extra: Optional[Dict[str, Any]] = None
                         ) -> Optional[str]:
        """Write the flight-recorder ring to ``postmortem_*.jsonl``.

        The file is a header event (``type="postmortem"``: reason, ring
        size, watchdog heartbeat-age report when available) followed by
        the recorded last-N events, oldest first.  The header (with the
        file path, without the ring) is also emitted to the live sinks
        so the main stream records that — and where — a postmortem was
        taken.  Returns the path, or None when no recorder is attached.
        """
        if self.recorder is None:
            return None
        wd = watchdog if watchdog is not None else self._watchdog
        payload: Dict[str, Any] = {"reason": reason}
        if wd is not None:
            try:
                payload["watchdog"] = wd.report()
            except Exception:
                pass
        if extra:
            payload.update(extra)
        with self._lock:
            ring = self.recorder.snapshot()
            self._postmortems += 1
            n = self._postmortems
        payload["ring_events"] = len(ring)
        path = os.path.join(
            self.postmortem_dir,
            f"postmortem_{self.run_id}_{n:02d}.jsonl")
        header = self._stamp("postmortem", step, payload)
        os.makedirs(self.postmortem_dir, exist_ok=True)
        with open(path, "w") as f:
            f.write(json.dumps(dict(header, path=path)) + "\n")
            for ev in ring:
                f.write(json.dumps(ev) + "\n")
        # announce on the live stream too (ring stays in the file only)
        self.emit("postmortem", step=step, reason=reason, path=path,
                  ring_events=len(ring))
        return path

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        for s in self.sinks:
            s.close()

    def __enter__(self) -> "TelemetryBus":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def install_recompile_listener(bus: Optional[TelemetryBus] = None,
                               on_duration=None):
    """Emit a ``recompile`` event whenever the jax runtime reports an
    XLA backend compile — mid-run recompiles are the classic silent
    step-time cliff (a shape change recompiling a 1.3B step costs
    minutes).  ``on_duration(seconds)`` additionally feeds each compile
    to the caller (the train loops accumulate it and book it to the
    accountant's ``compile`` bucket, so compile wall measured inside a
    step never counts as productive goodput).  ``bus`` may be ``None``
    for callback-only use — :func:`apex_tpu.analysis.hot_path_guard`
    counts compiles inside a guarded region without owning a stream.
    Returns an ``uninstall()`` callable; best-effort: on a jax without
    the monitoring hooks it installs nothing and returns a no-op."""
    try:
        from jax._src import monitoring as _mon
    except Exception:  # pragma: no cover — jax internals moved
        return lambda: None

    def _listener(event: str, duration: float, **_kw) -> None:
        if event.endswith("backend_compile_duration"):
            try:
                if bus is not None:
                    bus.emit("recompile",
                             duration_ms=round(duration * 1e3, 3),
                             source=event)
                if on_duration is not None:
                    on_duration(float(duration))
            except Exception:  # pragma: no cover — never break compile
                pass

    try:
        _mon.register_event_duration_secs_listener(_listener)
    except Exception:  # pragma: no cover
        return lambda: None

    def uninstall() -> None:
        try:
            _mon._unregister_event_duration_listener_by_callback(_listener)
        except Exception:  # pragma: no cover
            pass

    return uninstall
