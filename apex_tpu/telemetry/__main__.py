"""CLI: ``python -m apex_tpu.telemetry summarize run.jsonl [--diff b.jsonl]``.

Subcommands:

- ``summarize RUN.jsonl`` — step-time p50/p95/p99, goodput %, time
  buckets, phase breakdown (when the run sampled profiles), per-event-
  type counts.  ``--diff OTHER.jsonl`` renders an A/B table instead
  (RUN is the A/baseline column).  ``--json`` emits the raw summary
  record(s) for tooling.
- ``validate FILE.jsonl`` — schema-check every event (exit 1 on the
  first violation); works on run streams and postmortem files alike.
- ``regress A.json B.json --max-regress PCT`` — BENCH-record CI gate
  (ISSUE 9): compares two committed ``BENCH_r*.json`` key files with
  per-key direction rules and exits 1 when any gated key regressed
  more than PCT percent (``--keys`` restricts and makes the named keys
  mandatory; ``--verbose`` prints every compared row).
- ``trace STREAM.jsonl [STREAM2.jsonl ...]`` — reconstruct per-request
  span trees from any set of per-replica streams (ISSUE 19): renders
  each request's causal tree, marks the critical path, and prints the
  TTFT decomposition.  ``--rid N`` restricts to one request (exit 2
  when it has no spans); ``--json`` emits the trees + decompositions
  as a record.  Exit 1 when any tree is structurally broken (orphan
  spans, dangling parents) or a decomposition fails to sum to the
  measured TTFT within tolerance.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.telemetry",
        description="Telemetry stream tools (see docs/telemetry.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_sum = sub.add_parser("summarize",
                           help="aggregate one run (or A/B-diff two)")
    p_sum.add_argument("jsonl", help="telemetry JSONL stream")
    p_sum.add_argument("--diff", metavar="OTHER",
                       help="second stream: render an A/B table "
                            "(JSONL = A/baseline, OTHER = B)")
    p_sum.add_argument("--json", action="store_true",
                       help="emit the summary record(s) as JSON")

    p_val = sub.add_parser("validate",
                           help="schema-check every event in a file")
    p_val.add_argument("jsonl")

    p_reg = sub.add_parser(
        "regress", help="BENCH-record regression gate (exit 1 on a "
                        "gated-key regression beyond --max-regress)")
    p_reg.add_argument("a", help="baseline BENCH_r*.json (A)")
    p_reg.add_argument("b", help="candidate BENCH_r*.json (B)")
    p_reg.add_argument("--max-regress", type=float, default=5.0,
                       metavar="PCT",
                       help="tolerated regression percent on any gated "
                            "key (default 5)")
    p_reg.add_argument("--keys", default=None,
                       help="comma-separated exact keys to gate "
                            "(missing key = failure); default: every "
                            "gated key present in both files")
    p_reg.add_argument("--json", action="store_true",
                       help="emit the comparison rows as JSON")
    p_reg.add_argument("--verbose", action="store_true",
                       help="print every compared row, not just "
                            "failures")

    p_tr = sub.add_parser(
        "trace", help="reconstruct per-request span trees from one or "
                      "more per-replica streams (exit 1 on broken "
                      "trees or TTFT decomposition mismatch)")
    p_tr.add_argument("jsonl", nargs="+",
                      help="telemetry JSONL stream(s) — any subset of "
                           "the fleet's per-replica files")
    p_tr.add_argument("--rid", type=int, default=None,
                      help="restrict to one request id (exit 2 when "
                           "it has no spans)")
    p_tr.add_argument("--json", action="store_true",
                      help="emit trees + decompositions as JSON")

    args = parser.parse_args(argv)

    if args.cmd == "trace":
        from apex_tpu.telemetry.tracing import run_trace_cli

        return run_trace_cli(args.jsonl, rid=args.rid,
                             as_json=args.json)

    if args.cmd == "regress":
        from apex_tpu.telemetry.regress import (
            compare_bench, format_regress, load_bench_keys)

        try:
            ka, kb = load_bench_keys(args.a), load_bench_keys(args.b)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"ERROR: {e}", file=sys.stderr)
            return 2
        keys = ([k.strip() for k in args.keys.split(",") if k.strip()]
                if args.keys else None)
        rows, failures = compare_bench(ka, kb, args.max_regress, keys=keys)
        if args.json:
            print(json.dumps({"max_regress_pct": args.max_regress,
                              "rows": rows,
                              "failures": [r["key"] for r in failures]},
                             indent=1))
        else:
            print(format_regress(rows, failures, args.max_regress,
                                 verbose=args.verbose))
        return 1 if failures else 0

    if args.cmd == "validate":
        from apex_tpu.telemetry.schema import SchemaError, validate_jsonl

        try:
            n = validate_jsonl(args.jsonl)
        except SchemaError as e:
            print(f"INVALID: {e}", file=sys.stderr)
            return 1
        print(f"ok: {n} events valid")
        return 0

    from apex_tpu.telemetry.summarize import (
        format_diff, format_summary, summarize_file)

    summary = summarize_file(args.jsonl)
    if args.diff:
        other = summarize_file(args.diff)
        if args.json:
            print(json.dumps({"a": summary, "b": other}, indent=1))
        else:
            print(format_diff(summary, other))
        return 0
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
