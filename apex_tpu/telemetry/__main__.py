"""CLI: ``python -m apex_tpu.telemetry summarize run.jsonl [--diff b.jsonl]``.

Subcommands:

- ``summarize RUN.jsonl`` — step-time p50/p95/p99, goodput %, time
  buckets, per-event-type counts.  ``--diff OTHER.jsonl`` renders an
  A/B table instead (RUN is the A/baseline column).  ``--json`` emits
  the raw summary record(s) for tooling.
- ``validate FILE.jsonl`` — schema-check every event (exit 1 on the
  first violation); works on run streams and postmortem files alike.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.telemetry",
        description="Telemetry stream tools (see docs/telemetry.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_sum = sub.add_parser("summarize",
                           help="aggregate one run (or A/B-diff two)")
    p_sum.add_argument("jsonl", help="telemetry JSONL stream")
    p_sum.add_argument("--diff", metavar="OTHER",
                       help="second stream: render an A/B table "
                            "(JSONL = A/baseline, OTHER = B)")
    p_sum.add_argument("--json", action="store_true",
                       help="emit the summary record(s) as JSON")

    p_val = sub.add_parser("validate",
                           help="schema-check every event in a file")
    p_val.add_argument("jsonl")

    args = parser.parse_args(argv)

    if args.cmd == "validate":
        from apex_tpu.telemetry.schema import SchemaError, validate_jsonl

        try:
            n = validate_jsonl(args.jsonl)
        except SchemaError as e:
            print(f"INVALID: {e}", file=sys.stderr)
            return 1
        print(f"ok: {n} events valid")
        return 0

    from apex_tpu.telemetry.summarize import (
        format_diff, format_summary, summarize_file)

    summary = summarize_file(args.jsonl)
    if args.diff:
        other = summarize_file(args.diff)
        if args.json:
            print(json.dumps({"a": summary, "b": other}, indent=1))
        else:
            print(format_diff(summary, other))
        return 0
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
