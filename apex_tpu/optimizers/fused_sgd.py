"""FusedSGD — momentum SGD as one fused update.

Parity with reference ``FusedSGD`` (apex/optimizers/fused_sgd.py:6-227;
kernel csrc/multi_tensor_sgd_kernel.cu): momentum with dampening, Nesterov,
and ``wd_after_momentum``. The reference's depth-4 launch sets that fuse the
fp32→fp16 master-param copy into the update (fused_sgd.py:120-195) are
unnecessary here: :meth:`step` updates fp32 masters and the amp policy's
``cast_model`` produces the compute copy in the same jitted step, which XLA
fuses end-to-end.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from apex_tpu.optimizers.base import Optimizer, _f32, tree_map, tree_multimap_split


class SGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buffer: object


class FusedSGD(Optimizer):
    def __init__(
        self,
        lr: float,
        momentum: float = 0.0,
        dampening: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
        wd_after_momentum: bool = False,
    ):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires a momentum and zero dampening")
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum

    def init(self, params) -> SGDState:
        buf = tree_map(lambda x: jnp.zeros(x.shape, jnp.float32), params)
        return SGDState(step=jnp.zeros((), jnp.int32), momentum_buffer=buf)

    def update(self, grads, state: SGDState, params):
        first = state.step == 0
        wd = self.weight_decay

        def _leaf(g, p, buf):
            g = _f32(g)
            p32 = _f32(p)
            if wd and not self.wd_after_momentum:
                g = g + wd * p32
            if self.momentum:
                # first step: buf = g (torch semantics, mirrored by the kernel)
                new_buf = jnp.where(
                    first, g, self.momentum * buf + (1.0 - self.dampening) * g
                )
                d = g + self.momentum * new_buf if self.nesterov else new_buf
            else:
                new_buf = buf
                d = g
            if wd and self.wd_after_momentum:
                d = d + wd * p32
            return -self.lr * d, new_buf

        updates, buf = tree_multimap_split(_leaf, 2, grads, params, state.momentum_buffer)
        return updates, SGDState(step=state.step + 1, momentum_buffer=buf)
