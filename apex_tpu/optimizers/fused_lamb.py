"""FusedLAMB — layerwise adaptive large-batch optimizer.

Parity with reference ``FusedLAMB`` (apex/optimizers/fused_lamb.py:96-215;
kernel csrc/multi_tensor_lamb.cu): two phases —

1. global grad l2 norm over ALL params (reference launches
   ``multi_tensor_l2norm`` per dtype group then blends, fused_lamb.py:121-136;
   here one fused reduction), optionally clipped by ``max_grad_norm``:
   every grad is divided by ``max(1, global_norm/max_grad_norm)``;
2. per-tensor Adam moments + trust ratio
   ``ratio = ||p|| / ||m_hat/(sqrt(v_hat)+eps) + wd*p||`` applied to lr.
   ``use_nvlamb`` applies the ratio even for params with zero weight decay
   (reference kernel's NVLAMB switch).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from apex_tpu.multi_tensor.ops import multi_tensor_l2norm
from apex_tpu.optimizers.base import Optimizer, _f32, tree_map, tree_multimap_split


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: object
    exp_avg_sq: object


class FusedLAMB(Optimizer):
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
    ):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        if not adam_w_mode:
            raise RuntimeError("FusedLAMB only supports adam_w_mode (reference kernel mode 0 unused).")
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def init(self, params) -> LambState:
        z = lambda t: tree_map(lambda x: jnp.zeros(x.shape, jnp.float32), t)
        return LambState(step=jnp.zeros((), jnp.int32), exp_avg=z(params), exp_avg_sq=z(params))

    def update(self, grads, state: LambState, params):
        step = state.step + 1
        b1, b2 = self.beta1, self.beta2
        beta3 = 1.0 - b1 if self.grad_averaging else 1.0
        if self.bias_correction:
            c1 = 1.0 - b1 ** step.astype(jnp.float32)
            c2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            c1 = c2 = jnp.asarray(1.0, jnp.float32)

        # phase 1: global grad norm (+ optional clip)
        global_norm = multi_tensor_l2norm(grads)
        if self.max_grad_norm:
            clip = jnp.maximum(1.0, global_norm / self.max_grad_norm)
        else:
            clip = jnp.asarray(1.0, jnp.float32)

        wd = self.weight_decay

        def _leaf(g, p, m, v):
            g = _f32(g) / clip
            p32 = _f32(p)
            m = b1 * m + beta3 * g
            v = b2 * v + (1.0 - b2) * g * g
            upd = (m / c1) / (jnp.sqrt(v / c2) + self.eps)
            if wd:
                upd = upd + wd * p32
            w_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(upd * upd))
            apply_ratio = (wd != 0.0) or self.use_nvlamb
            if apply_ratio:
                ratio = jnp.where(
                    (w_norm > 0.0) & (u_norm > 0.0), w_norm / u_norm, 1.0
                )
            else:
                ratio = 1.0
            return -self.lr * ratio * upd, m, v

        updates, m, v = tree_multimap_split(
            _leaf, 3, grads, params, state.exp_avg, state.exp_avg_sq
        )
        return updates, LambState(step=step, exp_avg=m, exp_avg_sq=v)
