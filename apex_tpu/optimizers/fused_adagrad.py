"""FusedAdagrad.

Parity with reference ``FusedAdagrad`` (apex/optimizers/fused_adagrad.py:5-121;
kernel csrc/multi_tensor_adagrad.cu): ``adagrad_w_mode`` selects decoupled
weight decay vs L2-into-grad.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from apex_tpu.optimizers.base import Optimizer, _f32, tree_map, tree_multimap_split


class AdagradState(NamedTuple):
    sum: object


class FusedAdagrad(Optimizer):
    def __init__(
        self,
        lr: float = 1e-2,
        eps: float = 1e-10,
        weight_decay: float = 0.0,
        adagrad_w_mode: bool = False,
    ):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode

    def init(self, params) -> AdagradState:
        return AdagradState(sum=tree_map(lambda x: jnp.zeros(x.shape, jnp.float32), params))

    def update(self, grads, state: AdagradState, params):
        wd = self.weight_decay

        def _leaf(g, p, h):
            g = _f32(g)
            p32 = _f32(p)
            if wd and not self.adagrad_w_mode:
                g = g + wd * p32
            h = h + g * g
            upd = -self.lr * g / (jnp.sqrt(h) + self.eps)
            if wd and self.adagrad_w_mode:
                upd = upd - self.lr * wd * p32
            return upd, h

        updates, h = tree_multimap_split(_leaf, 2, grads, params, state.sum)
        return updates, AdagradState(sum=h)
