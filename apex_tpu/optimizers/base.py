"""Common optimizer machinery.

The reference optimizers are drop-in ``torch.optim.Optimizer`` replacements
that gather param/grad/state lists per dtype and fire one
``multi_tensor_applier`` per group (apex/optimizers/fused_adam.py:147-170).
Here the whole update is one fused XLA computation over the param pytree —
the superblock/Pallas path (:mod:`apex_tpu.optimizers.flat`) exists for the
cases where packing wins (many small tensors, ZeRO shards).

API: optax-style ``init(params) -> state`` / ``update(grads, state, params)
-> (updates, state)`` plus a ``step`` convenience that applies updates and a
``skip-step on overflow`` composition point for amp.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class Optimizer:
    """Base for apex-style fused optimizers (functional)."""

    def init(self, params) -> Any:
        raise NotImplementedError

    def update(self, grads, state, params):
        raise NotImplementedError

    def step(self, grads, state, params):
        """Apply one optimizer step: returns ``(new_params, new_state)``."""
        with jax.named_scope(f"apex_{type(self).__name__}_step"):
            updates, state = self.update(grads, state, params)
            return apply_updates(params, updates), state

    def step_if_finite(self, grads, state, params, finite):
        """amp-integrated step: branchless skip on overflow (the reference
        patches optimizer.step to a warning no-op, handle.py:127-154; the
        dynamic scale state machine handles the rest)."""
        from apex_tpu.utils.tree import tree_select

        new_params, new_state = self.step(grads, state, params)
        return tree_select(finite, new_params, params), tree_select(finite, new_state, state)

    def as_gradient_transformation(self):
        """Expose as an optax ``GradientTransformation`` for ecosystem
        composition."""
        import optax

        return optax.GradientTransformation(
            init=self.init,
            update=lambda g, s, p=None: self.update(g, s, p),
        )


def apply_updates(params, updates):
    return jax.tree_util.tree_map(
        lambda p, u: (p.astype(jnp.float32) + u).astype(p.dtype), params, updates
    )


def _f32(x):
    return x.astype(jnp.float32)


def tree_map(fn, *trees):
    return jax.tree_util.tree_map(fn, *trees)


def tree_multimap_split(fn, n_out: int, *trees):
    """tree_map a function returning an ``n_out``-tuple; returns ``n_out``
    trees (one per output). Safe regardless of leaf types."""
    flat_trees = [jax.tree_util.tree_flatten(t) for t in trees]
    treedef = flat_trees[0][1]
    outs = [fn(*leaves) for leaves in zip(*(f[0] for f in flat_trees))]
    return tuple(
        jax.tree_util.tree_unflatten(treedef, [o[i] for o in outs]) for i in range(n_out)
    )
