"""FusedNovoGrad — per-layer second-moment Adam variant.

Parity with reference ``FusedNovoGrad`` (apex/optimizers/fused_novograd.py:4-214;
kernel csrc/multi_tensor_novograd.cu): the second moment is a per-TENSOR
scalar — ``norm_type=2`` uses the grad l2 norm (the only type the reference
kernel implements), ``init_zero`` selects v_0 = 0 vs v_0 = ||g_1||²,
``reg_inside_moment`` moves weight decay inside the first moment.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from apex_tpu.optimizers.base import Optimizer, _f32, tree_map, tree_multimap_split


class NovoGradState(NamedTuple):
    step: jnp.ndarray
    exp_avg: object
    exp_avg_sq: object  # per-leaf scalar


class FusedNovoGrad(Optimizer):
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.95, 0.98),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        grad_averaging: bool = True,
        reg_inside_moment: bool = False,
        norm_type: int = 2,
        init_zero: bool = False,
    ):
        if norm_type != 2:
            raise RuntimeError("FusedNovoGrad only supports l2 norm_type=2 (as does the reference kernel).")
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.grad_averaging = grad_averaging
        self.reg_inside_moment = reg_inside_moment
        self.init_zero = init_zero

    def init(self, params) -> NovoGradState:
        m = tree_map(lambda x: jnp.zeros(x.shape, jnp.float32), params)
        v = tree_map(lambda x: jnp.zeros((), jnp.float32), params)
        return NovoGradState(step=jnp.zeros((), jnp.int32), exp_avg=m, exp_avg_sq=v)

    def update(self, grads, state: NovoGradState, params):
        step = state.step + 1
        first = state.step == 0
        b1, b2 = self.beta1, self.beta2
        beta3 = 1.0 - b1 if self.grad_averaging else 1.0
        if self.bias_correction:
            c1 = 1.0 - b1 ** step.astype(jnp.float32)
            c2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            c1 = c2 = jnp.asarray(1.0, jnp.float32)
        wd = self.weight_decay

        def _leaf(g, p, m, v):
            g = _f32(g)
            p32 = _f32(p)
            g_norm_sq = jnp.sum(g * g)
            if self.init_zero:
                new_v = b2 * v + (1.0 - b2) * g_norm_sq
            else:
                new_v = jnp.where(first, g_norm_sq, b2 * v + (1.0 - b2) * g_norm_sq)
            denom = jnp.sqrt(new_v / c2) + self.eps
            gn = g / denom
            if wd and self.reg_inside_moment:
                gn = gn + wd * p32
            m = b1 * m + beta3 * gn
            upd = m / c1
            if wd and not self.reg_inside_moment:
                upd = upd + wd * p32
            return -self.lr * upd, m, new_v

        updates, m, v = tree_multimap_split(
            _leaf, 3, grads, params, state.exp_avg, state.exp_avg_sq
        )
        return updates, NovoGradState(step=step, exp_avg=m, exp_avg_sq=v)
