"""apex_tpu.optimizers — fully-fused optimizers.

Re-design of ``apex.optimizers`` (reference apex/optimizers/__init__.py:1-5):
FusedSGD / FusedAdam / FusedLAMB / FusedNovoGrad / FusedAdagrad with the same
algorithms and knobs, plus LARC (reference apex/parallel/LARC.py). Instead of
per-dtype tensor-list launches through ``multi_tensor_applier``
(fused_adam.py:147-170), each update is one fused XLA computation over the
param pytree; :mod:`apex_tpu.optimizers.flat` provides the packed-superblock
Pallas path for many-small-tensor models.
"""

from apex_tpu.optimizers.base import Optimizer, apply_updates  # noqa: F401
from apex_tpu.optimizers.flat import FlatAdamState, FlatFusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_adagrad import AdagradState, FusedAdagrad  # noqa: F401
from apex_tpu.optimizers.fused_adam import AdamState, FusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_lamb import FusedLAMB, LambState  # noqa: F401
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad, NovoGradState  # noqa: F401
from apex_tpu.optimizers.fused_sgd import FusedSGD, SGDState  # noqa: F401
from apex_tpu.optimizers.larc import LARC  # noqa: F401
