"""FusedAdam — Adam/AdamW as one fused update.

Algorithm parity with the reference ``FusedAdam``
(apex/optimizers/fused_adam.py:4-173; kernel csrc/multi_tensor_adam.cu:23-171
``AdamFunctor``): ``adam_w_mode`` selects decoupled weight decay (AdamW) vs
L2-into-grad, ``bias_correction`` applies the 1/(1-beta^t) corrections.
The reference fuses all tensors into ~1 kernel launch; XLA fuses the whole
tree_map into one computation — same effect, no launcher.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from apex_tpu.optimizers.base import Optimizer, _f32, tree_map, tree_multimap_split


class AdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: object  # m
    exp_avg_sq: object  # v


class FusedAdam(Optimizer):
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
    ):
        if amsgrad:
            # parity: reference raises too (fused_adam.py:79-80)
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay

    def init(self, params) -> AdamState:
        f32 = lambda t: tree_map(lambda x: jnp.zeros(x.shape, jnp.float32), t)
        return AdamState(step=jnp.zeros((), jnp.int32), exp_avg=f32(params), exp_avg_sq=f32(params))

    def update(self, grads, state: AdamState, params):
        step = state.step + 1
        b1, b2 = self.beta1, self.beta2
        if self.bias_correction:
            c1 = 1.0 - b1 ** step.astype(jnp.float32)
            c2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            c1 = c2 = jnp.asarray(1.0, jnp.float32)

        def _leaf(g, p, m, v):
            g = _f32(g)
            p32 = _f32(p)
            if not self.adam_w_mode and self.weight_decay:
                g = g + self.weight_decay * p32  # L2 mode (AdamFunctor ADAM_MODE_1)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * g * g
            denom = jnp.sqrt(v / c2) + self.eps
            upd = -self.lr * (m / c1) / denom
            if self.adam_w_mode and self.weight_decay:
                upd = upd - self.lr * self.weight_decay * p32  # decoupled (ADAM_MODE_0)
            return upd, m, v

        updates, m, v = tree_multimap_split(
            _leaf, 3, grads, params, state.exp_avg, state.exp_avg_sq
        )
        return updates, AdamState(step=step, exp_avg=m, exp_avg_sq=v)
