"""Superblock (flat) optimizer path with a Pallas multi-tensor Adam kernel.

This is the literal TPU analog of the reference's multi-tensor launcher
(csrc/multi_tensor_apply.cuh:41-133 driving csrc/multi_tensor_adam.cu): the
whole parameter set lives in ONE 1-D fp32 HBM buffer (packed by
:mod:`apex_tpu.multi_tensor.flat`), and one Pallas kernel walks it in
(block_rows × 128) VMEM tiles, updating params and both moments in place
(``input_output_aliases`` = the donated-buffer equivalent of the reference's
in-place pointer writes).

Use :class:`FlatFusedAdam` when the model has many small parameters (the
case multi_tensor_apply exists for); for typical large-tensor models the
pytree path in :class:`apex_tpu.optimizers.FusedAdam` compiles to equally
fused XLA and avoids the pack/unpack.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._pallas import LANE, use_interpret


class FlatAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: jnp.ndarray
    exp_avg_sq: jnp.ndarray


def _adam_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref, vo_ref,
                 *, beta1, beta2, eps, weight_decay, adam_w_mode):
    """One VMEM tile of the fused Adam update (AdamFunctor parity,
    csrc/multi_tensor_adam.cu:23-97)."""
    lr = scal_ref[0]
    c1 = scal_ref[1]
    c2 = scal_ref[2]
    g = g_ref[:]
    p = p_ref[:]
    if weight_decay and not adam_w_mode:
        g = g + weight_decay * p
    m = beta1 * m_ref[:] + (1.0 - beta1) * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    denom = jnp.sqrt(v / c2) + eps
    upd = (m / c1) / denom
    if weight_decay and adam_w_mode:
        upd = upd + weight_decay * p
    po_ref[:] = p - lr * upd
    mo_ref[:] = m
    vo_ref[:] = v


class FlatFusedAdam:
    """FusedAdam over a packed superblock (see module docstring).

    The flat buffer length must be a multiple of 8*128 = 1024 (pack with
    ``flatten(tree, total_multiple_of=1024)``).
    """

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, adam_w_mode=True, weight_decay=0.0,
                 block_rows: int = 512):
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.block_rows = block_rows

    def init(self, flat_params: jnp.ndarray) -> FlatAdamState:
        z = jnp.zeros_like(flat_params, jnp.float32)
        return FlatAdamState(step=jnp.zeros((), jnp.int32), exp_avg=z, exp_avg_sq=z)

    def jit_step(self, *, donate: bool = True, plan=None):
        """Jitted :meth:`step` with ``state`` and ``flat_params``
        donated — the entry-level twin of the kernel's
        ``input_output_aliases={1: 0, 3: 1, 4: 2}`` (at flagship scale
        the old params + both moments ARE the fit margin).  The
        ISSUE 13 contract checker registers this executable and
        verifies the aliasing actually survived compilation;
        ``donate=False`` is its negative control.  ``plan`` (a
        :class:`~apex_tpu.multi_tensor.buckets.BucketPlan`, world=1)
        selects the bucketed walk — one kernel launch per bucket, the
        single-device twin of the flagship's per-bucket pipeline,
        registered separately by the checker
        (``zero_flat_adam_update_bucketed``)."""
        step = self.step if plan is None else functools.partial(
            self.step, plan=plan)
        return jax.jit(step, donate_argnums=(1, 2) if donate else ())

    def step(self, flat_grads, state: FlatAdamState, flat_params, *,
             plan=None):
        """One fused Adam step over the superblock.

        ``plan=None`` walks the whole buffer in one ``pallas_call``
        (one grid).  A :class:`~apex_tpu.multi_tensor.buckets.
        BucketPlan` with ``world=1`` walks it bucket by bucket — one
        kernel launch per span, each updating its slice in place
        (``input_output_aliases``) — the launch structure the
        bucketed ZeRO step pipelines collectives between.  Results
        are bitwise identical for every plan: the update is
        elementwise and every span sees the same scalars."""
        assert flat_params.ndim == 1 and flat_params.size % (8 * LANE) == 0, (
            "superblock must be 1-D with length a multiple of 1024; pack with "
            "apex_tpu.multi_tensor.flatten(tree, total_multiple_of=1024)"
        )
        step = state.step + 1
        if self.bias_correction:
            c1 = 1.0 - self.beta1 ** step.astype(jnp.float32)
            c2 = 1.0 - self.beta2 ** step.astype(jnp.float32)
        else:
            c1 = c2 = jnp.asarray(1.0, jnp.float32)
        scal = jnp.stack([jnp.asarray(self.lr, jnp.float32), c1, c2])

        n = flat_params.size
        if plan is None:
            spans = ((0, n),)
        else:
            if plan.world != 1 or plan.shard != n:
                raise ValueError(
                    f"FlatFusedAdam wants a world=1 plan over the whole "
                    f"buffer (shard={n}); got world={plan.world}, "
                    f"shard={plan.shard}")
            # hand-built plans are the documented use case (the
            # registry's FLAT_ADAM_SPANS) — overlapping/gapped spans
            # would silently corrupt the concat reassembly
            plan.validate()
            if any(lo % (8 * LANE) for lo, _ in plan.spans):
                raise ValueError(
                    "FlatFusedAdam bucket spans must start on 8*128 "
                    "sublane-row boundaries; plan with "
                    "plan_buckets(..., span_align=8*128)")
            spans = plan.spans

        p_parts, m_parts, v_parts = [], [], []
        for lo, hi in spans:
            p, m, v = self._span_update(
                scal,
                jax.lax.dynamic_slice_in_dim(flat_params, lo, hi - lo),
                jax.lax.dynamic_slice_in_dim(flat_grads, lo, hi - lo),
                jax.lax.dynamic_slice_in_dim(state.exp_avg, lo, hi - lo),
                jax.lax.dynamic_slice_in_dim(state.exp_avg_sq, lo,
                                             hi - lo))
            p_parts.append(p)
            m_parts.append(m)
            v_parts.append(v)
        if len(spans) == 1:
            p, m, v = p_parts[0], m_parts[0], v_parts[0]
        else:
            p = jnp.concatenate(p_parts)
            m = jnp.concatenate(m_parts)
            v = jnp.concatenate(v_parts)
        return p, FlatAdamState(step=step, exp_avg=m, exp_avg_sq=v)

    def _span_update(self, scal, p_span, g_span, m_span, v_span):
        """One kernel launch over a contiguous lane-aligned span."""
        n = p_span.size
        rows = n // LANE
        block_rows = min(self.block_rows, rows)
        # shrink to a divisor of rows (rows is a multiple of 8)
        while rows % block_rows:
            block_rows //= 2
        grid = rows // block_rows

        kern = functools.partial(
            _adam_kernel,
            beta1=self.beta1, beta2=self.beta2, eps=self.eps,
            weight_decay=self.weight_decay, adam_w_mode=self.adam_w_mode,
        )
        shape2d = (rows, LANE)
        tile = (block_rows, LANE)
        vspec = pl.BlockSpec(tile, lambda i: (i, 0))
        out = pl.pallas_call(
            kern,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                vspec, vspec, vspec, vspec,
            ],
            out_specs=[vspec, vspec, vspec],
            out_shape=[jax.ShapeDtypeStruct(shape2d, jnp.float32)] * 3,
            input_output_aliases={1: 0, 3: 1, 4: 2},
            interpret=use_interpret(),
        )(
            scal,
            p_span.reshape(shape2d).astype(jnp.float32),
            g_span.reshape(shape2d).astype(jnp.float32),
            m_span.reshape(shape2d),
            v_span.reshape(shape2d),
        )
        p, m, v = (x.reshape(-1) for x in out)
        return p, m, v
