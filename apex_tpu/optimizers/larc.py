"""LARC — layerwise adaptive rate control as a gradient transform.

Parity with reference ``LARC`` (apex/parallel/LARC.py:5-107), which wraps an
optimizer and mutates grads in-place before its step:

    adaptive_lr = trust_coefficient * ||p|| / (||g|| + wd*||p|| + eps)
    clip mode:  g = g * min(adaptive_lr / lr, 1);  g += wd * p
    scale mode: g = g * adaptive_lr;               g += wd * p

Here it is a pure grad transform composed in front of any
:class:`apex_tpu.optimizers.base.Optimizer` (weight decay is folded into the
grad exactly as the reference does, so the inner optimizer should be given
weight_decay=0 — mirroring how LARC zeroes the wrapped group's wd,
LARC.py:91-104).
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.optimizers.base import Optimizer, _f32, tree_map


class LARC(Optimizer):
    def __init__(
        self,
        optimizer: Optimizer,
        trust_coefficient: float = 0.02,
        clip: bool = True,
        eps: float = 1e-8,
        weight_decay: float = 0.0,
    ):
        self.inner = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps
        self.weight_decay = weight_decay

    def init(self, params):
        return self.inner.init(params)

    def transform_grads(self, grads, params):
        lr = getattr(self.inner, "lr", 1.0)
        wd = self.weight_decay

        def _leaf(g, p):
            g = _f32(g)
            p32 = _f32(p)
            p_norm = jnp.sqrt(jnp.sum(p32 * p32))
            g_norm = jnp.sqrt(jnp.sum(g * g))
            adaptive_lr = self.trust_coefficient * p_norm / (g_norm + p_norm * wd + self.eps)
            if self.clip:
                adaptive_lr = jnp.minimum(adaptive_lr / lr, 1.0)
            transformed = (g + wd * p32) * adaptive_lr
            # reference skips params with zero param/grad norm entirely —
            # grad left untouched, no wd fold-in (LARC.py:92-102)
            return jnp.where((p_norm > 0.0) & (g_norm > 0.0), transformed, g)

        return tree_map(_leaf, grads, params)

    def update(self, grads, state, params):
        return self.inner.update(self.transform_grads(grads, params), state, params)
