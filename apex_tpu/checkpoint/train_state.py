"""Canonical train-state pytree for checkpoint/resume.

The reference has no single train-state object — checkpoints are ad-hoc
``torch.save`` dicts assembled in the examples
(examples/imagenet/main_amp.py:178-193: model state_dict, optimizer
state_dict, ``amp.state_dict()``, epoch, best_prec1). Here the same pieces
are one registered pytree so the whole thing jits, shards, and checkpoints
as a unit:

- ``params``  — fp32 master params (reference O2 master weights,
  _process_optimizer.py:28-90; precision-portable like ``O2StateDictHook``
  _initialize.py:133-142)
- ``opt_state`` — fused-optimizer state (m/v/momentum trees)
- ``scaler_state`` — dynamic loss-scale state (reference
  ``amp.state_dict()``: loss_scale + unskipped, frontend.py:361-370)
- ``model_state`` — non-trained model state: BN running mean/var
  (reference BN buffers travel in the model state_dict)
- ``step`` — global step counter
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Everything needed to resume training exactly."""

    step: jnp.ndarray  # i32 scalar
    params: Any
    opt_state: Any
    scaler_state: Any = None
    model_state: Any = None

    @classmethod
    def create(cls, params, opt_state, scaler_state=None, model_state=None, step=0):
        return cls(
            step=jnp.asarray(step, jnp.int32),
            params=params,
            opt_state=opt_state,
            scaler_state=scaler_state,
            model_state=model_state,
        )

    def replace(self, **kw) -> "TrainState":
        return dataclasses.replace(self, **kw)
