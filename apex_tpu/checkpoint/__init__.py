"""Checkpoint/resume of the full train state (SURVEY.md §5.4).

Usage::

    from apex_tpu import checkpoint as ckpt

    state = ckpt.TrainState.create(params, opt_state, scaler_state)
    ckpt.save_checkpoint(dir, state, step=int(state.step), shardings=specs)
    state, step = ckpt.restore_checkpoint(dir, target=state, mesh=mesh)
"""

from apex_tpu.checkpoint.checkpoint import (
    CheckpointCorruptionError,
    RetryPolicy,
    latest_step,
    load_data_state,
    restore_checkpoint,
    save_checkpoint,
    shard_file,
    shard_file_coords,
    step_dir,
    verify_checkpoint,
)
from apex_tpu.checkpoint.train_state import TrainState

__all__ = [
    "TrainState",
    "save_checkpoint",
    "restore_checkpoint",
    "verify_checkpoint",
    "latest_step",
    "load_data_state",
    "shard_file",
    "shard_file_coords",
    "step_dir",
    "CheckpointCorruptionError",
    "RetryPolicy",
]
